"""Traffic-shaping controller parity tests: RateLimiter, WarmUp,
WarmUpRateLimiter — against the sequential oracle re-derivation of
RateLimiterController.java / WarmUpController.java semantics."""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.testing.oracle import OracleRateLimiter, OracleWarmUp, OracleNode


def rate_rule(resource, count, maxq):
    return st.FlowRule(
        resource,
        count=count,
        control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
        max_queueing_time_ms=maxq,
    )


class TestRateLimiter:
    def test_paces_requests(self, manual_clock, engine):
        """count=10 -> 100ms spacing; queueing up to 500ms."""
        st.flow_rule_manager.load_rules([rate_rule("paced", 10, 500)])
        oracle = OracleRateLimiter(10, 500)

        # Burst of 10 at t=0 (sync mode: each entry sleeps its wait on
        # the manual clock, exactly like the reference's in-check sleep).
        results = []
        for _ in range(10):
            t = manual_clock.now_ms()
            e = st.try_entry("paced")
            want_ok, want_wait = oracle.can_pass(t)
            results.append((e is not None, want_ok))
            if want_ok and want_wait:
                # oracle mirrors the sleep the API already performed
                pass
            if e is not None:
                e.exit()
        got = [g for g, _ in results]
        want = [w for _, w in results]
        assert got == want
        assert all(got[:6])  # first ~6 fit in the 500ms queue

    def test_block_beyond_queue(self, manual_clock, engine):
        """Deferred batch: all at t=0; only 1 immediate + maxq/cost queued pass."""
        st.flow_rule_manager.load_rules([rate_rule("q", 10, 300)])  # cost=100
        ops = [engine.submit_entry("q", ts=0) for _ in range(8)]
        engine.flush()
        oracle = OracleRateLimiter(10, 300)
        want = [oracle.can_pass(0) for _ in range(8)]
        got = [(op.verdict.admitted, op.verdict.wait_ms) for op in ops]
        assert got == [(ok, w) for ok, w in want]
        # 1 immediate + 3 queued (100/200/300ms), rest blocked
        assert [g[0] for g in got] == [True, True, True, True, False, False, False, False]
        assert [g[1] for g in got][:4] == [0, 100, 200, 300]

    def test_spaced_stream_matches_oracle(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([rate_rule("s", 5, 1000)])  # cost=200
        oracle = OracleRateLimiter(5, 1000)
        rng = np.random.default_rng(1)
        t = 0
        for _ in range(60):
            t += int(rng.choice([10, 50, 150, 400]))
            manual_clock.set_ms(t)
            e = st.try_entry("s")
            want_ok, want_wait = oracle.can_pass(t)
            assert (e is not None) == want_ok, f"t={t}"
            if e is not None:
                # The API slept want_wait on the manual clock; re-sync
                # our notion of t for the next iteration.
                t = manual_clock.now_ms()
                e.exit()

    def test_count_zero_blocks(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([rate_rule("z", 0, 500)])
        assert st.try_entry("z") is None


class TestWarmUp:
    def _rule(self, resource, count=20, warmup=10):
        return st.FlowRule(
            resource,
            count=count,
            control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
            warm_up_period_sec=warmup,
        )

    def test_cold_start_limits_qps(self, manual_clock, engine):
        """count=20, warmup=10s, cf=3: cold warningQps ≈ 6.67 — a burst
        in the first second admits only 6."""
        st.flow_rule_manager.load_rules([self._rule("wu")])
        manual_clock.set_ms(100)
        ops = [engine.submit_entry("wu", ts=100) for _ in range(20)]
        engine.flush()
        admitted = sum(op.verdict.admitted for op in ops)
        assert admitted == 6

    def test_matches_oracle_over_warmup(self, manual_clock, engine):
        """Stream spread over several seconds matches the oracle's
        decisions while tokens cool down."""
        st.flow_rule_manager.load_rules([self._rule("wo", count=10, warmup=4)])
        oracle = OracleWarmUp(10, 4, 3)
        onode = OracleNode()
        t = 0
        mismatches = []
        for step in range(200):
            t += 37  # prime-ish stride crossing second boundaries
            manual_clock.set_ms(t)
            e = st.try_entry("wo")
            want = oracle.can_pass(onode, t)
            if want:
                onode.add_pass(t, 1)
                onode.cur_thread_num += 1
            else:
                onode.add_block(t, 1)
            if (e is not None) != want:
                mismatches.append((step, t, e is not None, want))
            if e is not None:
                e.exit()
                onode.add_rt_and_success(t, 0, 1)
                onode.cur_thread_num -= 1
        assert not mismatches, mismatches[:5]

    def test_warm_state_allows_full_count(self, manual_clock, engine):
        """After the warm-up period of sustained traffic, the full count
        is admitted (tokens below warning line)."""
        st.flow_rule_manager.load_rules([self._rule("wf", count=10, warmup=2)])
        oracle = OracleWarmUp(10, 2, 3)
        onode = OracleNode()
        # Drive sustained near-limit traffic for several seconds.
        last_sec_admits = 0
        for sec in range(8):
            admits = 0
            for i in range(12):
                t = sec * 1000 + i * 80
                manual_clock.set_ms(t)
                e = st.try_entry("wf")
                want = oracle.can_pass(onode, t)
                if want:
                    onode.add_pass(t, 1)
                else:
                    onode.add_block(t, 1)
                assert (e is not None) == want, f"t={t}"
                if e is not None:
                    admits += 1
                    e.exit()
                    onode.add_rt_and_success(t, 0, 1)
            last_sec_admits = admits
        assert last_sec_admits >= 9  # warmed up to ~full count


class TestWarmUpRateLimiter:
    def test_cold_pacing_spacing(self, manual_clock, engine):
        """Cold state paces at the warming QPS (≈6.67 -> ~150ms cost),
        not the stable rate (100ms)."""
        st.flow_rule_manager.load_rules(
            [
                st.FlowRule(
                    "wrl",
                    count=20,
                    control_behavior=C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER,
                    warm_up_period_sec=10,
                    max_queueing_time_ms=2000,
                )
            ]
        )
        manual_clock.set_ms(50)
        ops = [engine.submit_entry("wrl", ts=50) for _ in range(4)]
        engine.flush()
        waits = [op.verdict.wait_ms for op in ops]
        assert all(op.verdict.admitted for op in ops)
        assert waits[0] == 0
        # Cold warningQps = 1/((200-100)*0.001 + 0.05) = 6.666…;
        # cost = round(1000/6.666…) = 150ms spacing.
        assert waits[1:] == [150, 300, 450]
