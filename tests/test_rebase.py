"""Epoch-rebase tests.

Device timestamps are int32 ms since the clock epoch; after ~22 days
the engine re-anchors the epoch and shifts every stored absolute-ms
tensor (Engine._apply_rebase). The offset is aligned to
SystemClock.REBASE_GRANULARITY_MS (60 s) so every window grid —
second-window 500 ms buckets, minute-window 1000 ms buckets, breaker
windows — keeps both its bucket indices and its alignment.

These tests drive the shift directly under the fake clock and assert
each dyn-state family keeps behaving as if time were continuous — the
ADVICE-r1 bug was that breaker and hot-param state were NOT shifted,
so an OPEN breaker stayed blocked ~22 days and param token buckets
wedged after a rebase.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C
from sentinel_tpu.rules.param_table import PARAM_NEVER
from sentinel_tpu.utils.clock import SystemClock

OFF = SystemClock.REBASE_GRANULARITY_MS  # 60_000
BASE = 2 * OFF  # run the pre-rebase phase at t≈120s


def exc_ratio_rule(resource, ratio=0.5, tw=2, min_req=5):
    return st.DegradeRule(
        resource,
        grade=C.DEGRADE_GRADE_EXCEPTION_RATIO,
        count=ratio,
        time_window=tw,
        min_request_amount=min_req,
    )


def _trip_open(clock, resource):
    """5 consecutive errors starting at BASE → breaker OPEN with
    next_retry ≈ BASE + tw*1000."""
    for i in range(5):
        clock.set_ms(BASE + i)
        e = st.try_entry(resource)
        assert e is not None
        e.set_error(RuntimeError("boom"))
        e.exit()
    clock.set_ms(BASE + 100)
    assert st.try_entry(resource) is None  # OPEN


class TestRebaseShiftsDegradeState:
    def test_open_breaker_probes_after_rebase(self, manual_clock, engine):
        """OPEN breaker with retry ≈ BASE+2004; shift epoch by 60s → the
        probe must open at (shifted) BASE-60000+2004, not 22 days on."""
        st.degrade_rule_manager.load_rules([exc_ratio_rule("svc", 0.4, tw=2)])
        _trip_open(manual_clock, "svc")

        engine._apply_rebase(OFF)
        shifted_retry = BASE - OFF + 2004
        manual_clock.set_ms(shifted_retry - 500)
        assert st.try_entry("svc") is None  # still OPEN before retry

        manual_clock.set_ms(shifted_retry + 100)
        e = st.try_entry("svc")
        assert e is not None, "OPEN breaker never probed after rebase"
        e.exit()  # success → CLOSED

    def test_closed_breaker_window_keeps_accumulating(self, manual_clock, engine):
        """CLOSED breaker: exits after a rebase must still land in the
        same breaker window (the r1 bug made every exit look stale)."""
        st.degrade_rule_manager.load_rules([exc_ratio_rule("c", 0.4, tw=2, min_req=5)])
        # Two errors pre-rebase (below min_request_amount), same second.
        for i in range(2):
            manual_clock.set_ms(BASE + i * 10)
            e = st.try_entry("c")
            e.set_error(RuntimeError("x"))
            e.exit()
        engine.flush()
        engine._apply_rebase(OFF)
        # Three more errors post-rebase, same (shifted) second window.
        for i in range(3):
            manual_clock.set_ms(BASE - OFF + 30 + i * 10)
            e = st.try_entry("c")
            e.set_error(RuntimeError("x"))
            e.exit()
        manual_clock.set_ms(BASE - OFF + 90)
        assert st.try_entry("c") is None, (
            "errors across the rebase did not accumulate — breaker never opened"
        )

    def test_odd_stat_interval_survives_rebase(self, manual_clock, engine):
        """A breaker whose statIntervalMs (7s) does not divide the 60s
        rebase granularity: its ws is floor-realigned to its own grid so
        exits keep accumulating instead of being dropped or wedged."""
        st.degrade_rule_manager.load_rules(
            [
                st.DegradeRule(
                    "odd",
                    grade=C.DEGRADE_GRADE_EXCEPTION_RATIO,
                    count=0.4,
                    time_window=2,
                    min_request_amount=5,
                    stat_interval_ms=7000,
                )
            ]
        )
        # Two errors pre-rebase inside the window [119000, 126000).
        for i in range(2):
            manual_clock.set_ms(BASE + i * 10)  # BASE=120000
            e = st.try_entry("odd")
            e.set_error(RuntimeError("x"))
            e.exit()
        engine.flush()
        engine._apply_rebase(OFF)
        ws = int(np.asarray(engine.degrade_dyn.ws)[0])
        assert ws % 7000 == 0, f"breaker ws {ws} off its 7000ms grid after rebase"
        # Three more errors in the same shifted window → breaker opens.
        for i in range(3):
            manual_clock.set_ms(BASE - OFF + 30 + i * 10)
            e = st.try_entry("odd")
            e.set_error(RuntimeError("x"))
            e.exit()
        manual_clock.set_ms(BASE - OFF + 90)
        assert st.try_entry("odd") is None, (
            "odd-interval breaker lost its counts across the rebase"
        )

    def test_sentinel_floor_preserved(self, manual_clock, engine):
        st.degrade_rule_manager.load_rules([exc_ratio_rule("s")])
        engine.flush()
        engine._apply_rebase(OFF)
        assert int(np.asarray(engine.degrade_dyn.ws)[0]) == -(10**9)

    def test_unaligned_offset_rejected(self, manual_clock, engine):
        with pytest.raises(AssertionError):
            engine._apply_rebase(7)


class TestRebaseShiftsParamState:
    def test_token_bucket_refills_after_rebase(self, manual_clock, engine):
        """Param token bucket: last_add shifted with the epoch keeps the
        per-second refill schedule; unshifted it blocks all refills."""
        rule = st.ParamFlowRule("p", param_idx=0, count=2, duration_in_sec=1)
        st.param_flow_rule_manager.load_rules([rule])
        manual_clock.set_ms(BASE)
        assert st.try_entry("p", args=("k",)) is not None
        assert st.try_entry("p", args=("k",)) is not None
        assert st.try_entry("p", args=("k",)) is None  # bucket drained

        engine._apply_rebase(OFF)
        manual_clock.set_ms(BASE - OFF + 200)
        assert st.try_entry("p", args=("k",)) is None  # still drained
        # 1s (shifted) after the first acquire: bucket refilled.
        manual_clock.set_ms(BASE - OFF + 1100)
        assert st.try_entry("p", args=("k",)) is not None, (
            "token bucket never refilled after rebase"
        )

    def test_param_never_sentinel_preserved(self, manual_clock, engine):
        rule = st.ParamFlowRule("q", param_idx=0, count=2)
        st.param_flow_rule_manager.load_rules([rule])
        engine.flush()
        engine._apply_rebase(OFF)
        assert int(np.asarray(engine.param_dyn.last_add)[-1]) == PARAM_NEVER


class TestRebaseShiftsPacer:
    def test_rate_limiter_pacing_continuous(self, manual_clock, engine):
        """RateLimiter latest_passed_time (already shifted in r1) still
        paces correctly across a rebase — regression guard."""
        st.flow_rule_manager.load_rules(
            [
                st.FlowRule(
                    "rl",
                    count=10.0,  # 100ms spacing
                    control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                    max_queueing_time_ms=500,
                )
            ]
        )
        manual_clock.set_ms(BASE)
        assert st.try_entry("rl") is not None  # passes, latest=BASE
        engine._apply_rebase(OFF)
        # Next permitted slot was BASE+100 → shifted BASE-OFF+100; a
        # request at +40 queues within the 500ms budget.
        manual_clock.set_ms(BASE - OFF + 40)
        assert st.try_entry("rl") is not None
        # Burst past the queueing budget must block.
        for _ in range(10):
            st.try_entry("rl")
        manual_clock.set_ms(BASE - OFF + 41)
        assert st.try_entry("rl") is None
