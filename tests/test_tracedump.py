"""tools/tracedump.py: Chrome trace-event export of the flight recorder.

Tier-1-safe validation (ISSUE 3 CI satellite): the emitted JSON is
well-formed trace-event format, same-tid slices never overlap, and a
depth-2 run shows flush N's in-flight (dispatch→settle) window
overlapping flush N+1's encode — the pipelining proof Perfetto
renders."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import tracedump  # noqa: E402


@pytest.fixture(scope="module")
def depth2_trace(tmp_path_factory):
    """One depth-2 demo run shared by the structural checks."""
    eng = tracedump.run_demo(depth=2, flushes=16, rows=64)
    path = tmp_path_factory.mktemp("trace") / "trace.json"
    trace = tracedump.dump(eng, str(path))
    # Round-trip through disk: the file itself must parse.
    with open(path, "r", encoding="utf-8") as f:
        return json.load(f), trace


class TestTraceFormat:
    def test_well_formed_trace_events(self, depth2_trace):
        loaded, emitted = depth2_trace
        assert loaded == emitted
        events = loaded["traceEvents"]
        assert events, "demo run must emit events"
        for e in events:
            assert e["ph"] in ("X", "M", "s", "f")
            assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
            if e["ph"] == "X" and e.get("cat") == "admission":
                # Request track: one slice per sampled admission.
                assert e["ts"] >= 0.0 and e["dur"] > 0.0
                assert "trace_id" in e["args"] and "flush_seq" in e["args"]
            elif e["ph"] == "X":
                assert e["name"] in ("encode", "dispatch", "inflight")
                assert e["ts"] >= 0.0 and e["dur"] >= 0.0
                assert "flush_id" in e["args"]

    def test_same_tid_slices_do_not_overlap(self, depth2_trace):
        events = [e for e in depth2_trace[0]["traceEvents"] if e["ph"] == "X"]
        by_tid = {}
        for e in events:
            by_tid.setdefault(e["tid"], []).append((e["ts"], e["ts"] + e["dur"]))
        for tid, spans in by_tid.items():
            spans.sort()
            for (s0, e0), (s1, _e1) in zip(spans, spans[1:]):
                # 1 µs grace for float rounding at shared boundaries.
                assert s1 >= e0 - 1e-3, (tid, (s0, e0), s1)

    def test_request_flow_events_link_to_deciding_flush(self, depth2_trace):
        """Acceptance: the dump contains request→flush flow arrows in
        the shape Perfetto accepts — matched s/f pairs (same cat, name,
        id), the start on a request track inside its admission slice,
        the finish on the host track inside the DECIDING flush's
        dispatch slice, and s.ts <= f.ts."""
        events = depth2_trace[0]["traceEvents"]
        starts = {e["id"]: e for e in events if e["ph"] == "s"}
        finishes = {e["id"]: e for e in events if e["ph"] == "f"}
        assert starts, "demo must emit flow arrows"
        assert set(starts) == set(finishes)
        slices = [e for e in events if e["ph"] == "X"]

        def enclosing(tid, ts):
            return [
                e for e in slices
                if e["tid"] == tid and e["ts"] - 1e-3 <= ts <= e["ts"] + e["dur"] + 1e-3
            ]

        for fid, s in starts.items():
            f = finishes[fid]
            assert s["cat"] == f["cat"] == "admission"
            assert s["name"] == f["name"] == "decide"
            assert f["bp"] == "e"
            assert s["ts"] <= f["ts"]
            req = [e for e in enclosing(s["tid"], s["ts"])
                   if e.get("cat") == "admission"]
            assert req, ("flow start must sit inside a request slice", s)
            disp = [e for e in enclosing(f["tid"], f["ts"])
                    if e.get("name") == "dispatch"]
            assert disp, ("flow finish must sit inside a dispatch slice", f)
            # And it is the DECIDING flush's dispatch slice.
            assert any(
                d["args"]["flush_id"] == req[0]["args"]["flush_seq"]
                for d in disp
            )

    def test_blocked_and_admitted_records_present(self, depth2_trace):
        """The demo's tight flow rule blocks part of every window: the
        request track must carry both verdicts, blocked ones named by
        the shared reason mapping."""
        reqs = [
            e for e in depth2_trace[0]["traceEvents"]
            if e["ph"] == "X" and e.get("cat") == "admission"
        ]
        blocked = [e for e in reqs if not e["args"]["admitted"]]
        admitted = [e for e in reqs if e["args"]["admitted"]]
        assert blocked and admitted
        assert all(e["args"]["reason_name"] == "FlowException" for e in blocked)

    def test_depth2_inflight_overlaps_next_encode(self, depth2_trace):
        """The pipelining proof: for most flushes N, the in-flight
        window of N (device exec + fetch) overlaps the encode slice of
        flush N+1 on the host track."""
        events = depth2_trace[0]["traceEvents"]
        encode = {
            e["args"]["flush_id"]: e for e in events if e.get("name") == "encode"
        }
        inflight = [e for e in events if e.get("name") == "inflight"]
        deferred = [e for e in inflight if e["args"]["deferred"]]
        assert deferred, "depth-2 run must have deferred in-flight spans"
        overlaps = 0
        candidates = 0
        for f in deferred:
            nxt = encode.get(f["args"]["flush_id"] + 1)
            if nxt is None:
                continue
            candidates += 1
            if (
                nxt["ts"] < f["ts"] + f["dur"]
                and nxt["ts"] + nxt["dur"] > f["ts"]
            ):
                overlaps += 1
        assert candidates > 0
        # At steady state every pair overlaps; allow pipeline ramp-up
        # and the final drain to miss.
        assert overlaps >= candidates // 2, (overlaps, candidates)

    def test_depth2_uses_parallel_inflight_tracks(self, depth2_trace):
        """A depth-2 pipeline needs >= 2 in-flight tracks: two
        dispatched-but-unfetched flushes coexist, so their windows
        cannot share a tid."""
        events = depth2_trace[0]["traceEvents"]
        tids = {
            e["tid"]
            for e in events
            if e.get("name") == "inflight" and e["args"]["deferred"]
        }
        assert len(tids) >= 2


class TestDumpApi:
    def test_dump_empty_recorder(self, tmp_path):
        from sentinel_tpu.metrics.telemetry import spans_to_trace

        trace = spans_to_trace([])
        assert trace["traceEvents"] == []

    def test_sync_engine_trace(self, manual_clock, engine, tmp_path):
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("td", count=1e9)])
        for _ in range(3):
            engine.submit_entry("td")
            engine.flush()
        trace = tracedump.dump(engine, str(tmp_path / "t.json"))
        names = {e.get("name") for e in trace["traceEvents"]}
        assert {"encode", "dispatch"} <= names
