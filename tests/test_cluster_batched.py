"""Batched cluster token plane (PR 16) — differential pins.

The acceptance surface: verdicts produced through the batched frame
(`FLOW_REQUEST_BATCH` / `PARAM_FLOW_REQUEST_BATCH`, the engine's bulk
seam, the client micro-window) are BIT-IDENTICAL to the per-call
oracle in the same request order; server death falls back to the local
stance; THREAD-grade cluster gauges read exactly 0 after quiesce;
the lease path admits the same totals as the no-lease path; and with
every new config key at its default the wire behavior is exactly
PR-15's (zero batch frames).
"""

from __future__ import annotations

import threading

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster import (
    ClusterStateManager,
    DefaultTokenService,
    EmbeddedClusterTokenServerProvider,
    TokenClientProvider,
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.client import ClusterTokenClient, client_stats
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule, ParamFlowRule
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import SentinelConfig, config


def cluster_rule(resource, count, flow_id, fallback=True):
    return FlowRule(
        resource,
        count=count,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            fallback_to_local_when_fail=fallback,
        ),
    )


def concurrent_rule(resource, count, flow_id):
    return FlowRule(
        resource,
        count=count,
        grade=C.FLOW_GRADE_THREAD,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            fallback_to_local_when_fail=False,
        ),
    )


def cluster_param_rule(resource, count, flow_id, param_idx=0):
    return ParamFlowRule(
        resource,
        count=count,
        param_idx=param_idx,
        cluster_mode=True,
        cluster_config=ClusterFlowConfig(
            flow_id=flow_id,
            threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            fallback_to_local_when_fail=True,
        ),
    )


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


@pytest.fixture(autouse=True)
def _stats_reset():
    client_stats.reset()
    yield
    client_stats.reset()


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=30000.0
    )
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()


def _embedded_env(clock, rules):
    """Fresh embedded token service + server registration — each call
    resets the cluster windows, so a batched run and its per-call
    oracle start from the identical world."""
    svc = DefaultTokenService(clock=clock)
    EmbeddedClusterTokenServerProvider.clear()
    EmbeddedClusterTokenServerProvider.register(
        SentinelTokenServer(port=0, service=svc)
    )
    ClusterStateManager.set_to_server()
    cluster_flow_rule_manager.load_rules("default", rules)
    return svc


# ---------------------------------------------------------------------------
# engine bulk seam vs per-call oracle
# ---------------------------------------------------------------------------
class TestEngineDifferential:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_flow_batched_bit_identical_to_per_call(
        self, cluster_env, manual_clock, depth
    ):
        """submit_many resolves cluster ops with ONE batched RPC; the
        verdict sequence must equal per-op submit_entry against a fresh
        identical token world — including interleaved non-cluster ops
        (order through the deferred tail is load-bearing)."""
        crule = cluster_rule("cr", 6, flow_id=901)
        local = FlowRule("plain", count=4)
        reqs = []
        for i in range(16):
            reqs.append({"resource": "cr" if i % 2 == 0 else "plain",
                         "ts": 1000})

        def run(batched: bool):
            _embedded_env(manual_clock, [crule])
            eng = Engine(clock=manual_clock)
            eng.pipeline_depth = depth
            eng.set_flow_rules([crule, local])
            if batched:
                ops = eng.submit_many([dict(r) for r in reqs])
            else:
                ops = [eng.submit_entry(**r) for r in reqs]
            eng.flush()
            eng.drain()
            out = [bool(op.verdict.admitted) for op in ops]
            eng.close()
            return out

        batched = run(True)
        oracle = run(False)
        assert batched == oracle
        # Sanity: the cluster budget actually bound the run.
        assert sum(batched[0::2]) == 6

    @pytest.mark.parametrize("depth", [0, 2])
    def test_param_batched_bit_identical_to_per_call(
        self, cluster_env, manual_clock, depth
    ):
        """Cluster hot-param verdicts through the bulk seam's one
        PARAM_FLOW batch equal the per-op oracle, per value."""
        prule = cluster_param_rule("pp", 2, flow_id=902)
        values = ["a", "b", "a", "c", "a", "b", "b", "c", "a", "c"]

        def run(batched: bool):
            _embedded_env(manual_clock, [prule])
            eng = Engine(clock=manual_clock)
            eng.pipeline_depth = depth
            eng.set_param_rules({"pp": [prule]})
            reqs = [{"resource": "pp", "ts": 1000, "args": (v,)}
                    for v in values]
            if batched:
                ops = eng.submit_many(reqs)
            else:
                ops = [eng.submit_entry(**r) for r in reqs]
            eng.flush()
            eng.drain()
            out = [bool(op.verdict.admitted) for op in ops]
            eng.close()
            return out

        batched = run(True)
        oracle = run(False)
        assert batched == oracle
        # Per-value budget of 2 actually enforced globally.
        for v in "abc":
            assert sum(
                adm for adm, val in zip(batched, values) if val == v
            ) == 2

    def test_fallback_to_local_on_server_death(self, cluster_env, manual_clock):
        """A dead token server turns every batched row into FAIL; with
        fallback_to_local the LOCAL rule decides — and the client
        counts the fallbacks honestly."""
        rule = cluster_rule("fb", 1, flow_id=903, fallback=True)
        cluster_flow_rule_manager.load_rules("default", [rule])
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=manual_clock)
        )
        server.start()
        client = ClusterTokenClient(
            "127.0.0.1", server.port, request_timeout_sec=0.5
        ).start()
        TokenClientProvider.register(client)
        ClusterStateManager.set_to_client()
        server.stop()  # die before any token is asked

        eng = Engine(clock=manual_clock)
        eng.set_flow_rules([rule])
        ops = eng.submit_many(
            [{"resource": "fb", "ts": 1000} for _ in range(3)]
        )
        eng.flush()
        eng.drain()
        # Local count=1 applies: exactly one admit.
        assert [bool(op.verdict.admitted) for op in ops].count(True) == 1
        assert client_stats.snapshot()["fallbacks"] >= 3
        eng.close()
        client.stop()

    def test_thread_gauges_zero_after_quiesce(self, cluster_env, manual_clock):
        """THREAD-grade cluster rules keep the held-token per-op path
        through submit_many; after every entry exits, the server-side
        concurrency gauge and held-token cache read exactly 0."""
        rule = concurrent_rule("cc", 8, flow_id=904)
        svc = _embedded_env(manual_clock, [rule])
        eng = Engine(clock=manual_clock)
        eng.set_flow_rules([rule])
        ops = eng.submit_many([{"resource": "cc"} for _ in range(5)])
        eng.flush()
        assert all(op.verdict.admitted for op in ops)
        assert svc.concurrent.now_calls(904) == 5
        for op in ops:
            eng.submit_exit(op.rows, rt=3, resource="cc",
                            cluster_tokens=op.cluster_tokens)
        eng.flush()
        assert svc.concurrent.now_calls(904) == 0
        assert svc.concurrent.held_tokens() == 0
        eng.close()


# ---------------------------------------------------------------------------
# wire-level differential: batch frame, micro-window, leases, default-off
# ---------------------------------------------------------------------------
class TestWireDifferential:
    def test_batch_frame_bit_identical_to_per_call(self, cluster_env):
        """One FLOW_REQUEST_BATCH of N rows returns the same status
        sequence as N per-call frames against a fresh identical
        server."""
        rows = [(905, 1, False)] * 9

        def statuses(use_batch: bool):
            cluster_flow_rule_manager.load_rules(
                "default", [cluster_rule("r", 5, flow_id=905)]
            )
            server = SentinelTokenServer(
                port=0, service=DefaultTokenService(clock=ManualClock(0))
            )
            server.start()
            try:
                client = ClusterTokenClient("127.0.0.1", server.port).start()
                if use_batch:
                    out = [r.status for r in client.request_tokens_batch(rows)]
                else:
                    out = [client.request_token(f, a, p).status
                           for f, a, p in rows]
                client.stop()
                return out
            finally:
                server.stop()

        assert statuses(True) == statuses(False)

    def test_default_off_sends_zero_batch_frames(self, cluster_env):
        """Every new key at its default (window.ms=0, leases off):
        request_token takes the PR-15 per-call wire path — zero batch
        frames, zero leases — and the verdicts match the oracle."""
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_rule("r", 4, flow_id=906)]
        )
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        )
        server.start()
        try:
            client = ClusterTokenClient("127.0.0.1", server.port).start()
            oks = [client.request_token(906).ok for _ in range(7)]
            assert oks == [True] * 4 + [False] * 3
            snap = client_stats.snapshot()
            assert snap["batch_frames"] == 0
            assert snap["leases_granted"] == 0
            assert snap["lease_admits"] == 0
            client.stop()
        finally:
            server.stop()

    def test_micro_window_coalesces_and_preserves_totals(self, cluster_env):
        """Concurrent request_token callers under the client window
        coalesce into shared frames; the admitted TOTAL is exactly the
        per-call budget (the intra-batch cumsum makes batched charging
        equal serial charging)."""
        config.set(SentinelConfig.CLUSTER_CLIENT_WINDOW_MS, "25")
        cluster_flow_rule_manager.load_rules(
            "default", [cluster_rule("r", 10, flow_id=907)]
        )
        server = SentinelTokenServer(
            port=0, service=DefaultTokenService(clock=ManualClock(0))
        )
        server.start()
        try:
            client = ClusterTokenClient("127.0.0.1", server.port).start()
            n = 16
            barrier = threading.Barrier(n)
            oks = []
            lock = threading.Lock()

            def worker():
                barrier.wait()
                r = client.request_token(907)
                with lock:
                    oks.append(r.ok)

            threads = [threading.Thread(target=worker) for _ in range(n)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert sum(oks) == 10
            snap = client_stats.snapshot()
            # Coalescing happened: fewer frames than ops (the count is
            # scheduler-dependent; the bench gates the ratio).
            assert 1 <= snap["batch_frames"] < n
            client.stop()
        finally:
            server.stop()

    def test_lease_path_parity_with_no_lease(self, cluster_env):
        """Leases never change WHAT is admitted in total, only how many
        RPCs it costs: a hot flow driven to exhaustion admits exactly
        the budget with leases on (some served with zero RPCs) and with
        leases off."""
        def drive(lease_on: bool) -> int:
            cluster_flow_rule_manager.clear()
            cluster_server_config_manager.load_global_flow_config(
                exceed_count=1.0, max_allowed_qps=30000.0
            )
            cluster_flow_rule_manager.load_rules(
                "default", [cluster_rule("r", 40, flow_id=908)]
            )
            config.set(
                SentinelConfig.CLUSTER_LEASE_ENABLED,
                "true" if lease_on else "false",
            )
            config.set(SentinelConfig.CLUSTER_LEASE_TTL_MS, "5000")
            server = SentinelTokenServer(
                port=0, service=DefaultTokenService(clock=ManualClock(0))
            )
            server.start()
            try:
                client = ClusterTokenClient("127.0.0.1", server.port).start()
                admitted = 0
                for _ in range(8):  # 8 batches of 8 = 64 asks > 40 budget
                    for r in client.request_tokens_batch([(908, 1, False)] * 8):
                        admitted += 1 if r.ok else 0
                client.stop()
                return admitted
            finally:
                server.stop()

        with_lease = drive(True)
        lease_admits = client_stats.snapshot()["lease_admits"]
        client_stats.reset()
        without_lease = drive(False)
        assert with_lease == without_lease == 40
        # The lease path actually served part of the hot flow RPC-free.
        assert lease_admits > 0
        assert client_stats.snapshot()["lease_admits"] == 0
