"""WarmUp f64 boundary pin (round-3 weak #7).

Java computes the warm-up warning QPS in float64
(WarmUpController.java:64-130: ``warningQps = Math.nextUp(1.0 /
(aboveToken * slope + 1.0 / count))``); the device kernel uses float32
(rules/shaping.py::_transition). This suite pins the kernel against
hand-computed Java-f64 verdicts at the EXACT boundary tick for extreme
rule counts:

* count <= 1e6: the f32 kernel's pass/block at every f32-representable
  integer passQps around the boundary equals Java-f64 — no divergence.
* count = 1e8: divergence exists but is confined to a tick of a few
  accumulated f32 rounding errors above the f64 boundary — pinned at
  relative width 2e-7 of the warning QPS (at 1e8 that is <= 20 QPS out
  of ~67M). Inside that tick the f32 kernel can admit where Java-f64
  blocks; outside it they agree exactly. The test pins that bound and
  the direction.

The kernel function under test is the real one (`_transition`), not a
re-derivation of its arithmetic.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from sentinel_tpu.models import constants as C
from sentinel_tpu.rules.shaping import _transition


def _java_model(count: float, warmup_sec: int, cf: int = 3):
    """WarmUpController.construct in Java-f64 (Python floats are f64),
    digit-for-digit: int cast of the product then INTEGER division."""
    warning = int(warmup_sec * count) // (cf - 1)
    max_tok = warning + int(2 * warmup_sec * count / (1.0 + cf))
    slope = (cf - 1.0) / count / (max_tok - warning)
    return warning, max_tok, slope


def _java_verdict(passq: float, acq: float, stored: float, warning: int,
                  slope: float, count: float) -> bool:
    above = stored - warning
    if above <= 0:
        return passq + acq <= count
    warning_qps = math.nextafter(1.0 / (above * slope + 1.0 / count), math.inf)
    return passq + acq <= warning_qps


def _kernel_verdict(passq: float, stored: float, warning: int, max_tok: int,
                    slope: float, count: float) -> bool:
    """One WARM_UP item through the real kernel transition, with sync
    disabled (lastfill == current second) so ``stored`` is checked
    as-is."""
    ts = 5000
    one = jnp.ones((1,), dtype=jnp.int32)

    def f(latest, stored_a, lastfill, passq_a):
        x = (
            jnp.ones((1,), dtype=bool),          # valid
            jnp.full((1,), ts, dtype=jnp.int32),  # ts
            jnp.ones((1,), dtype=jnp.float32),    # acq_f
            one,                                  # acq
            passq_a,                              # passq
            jnp.zeros((1,), dtype=jnp.float32),   # prevq
            jnp.full((1,), C.CONTROL_BEHAVIOR_WARM_UP, dtype=jnp.int32),
            jnp.full((1,), count, dtype=jnp.float32),
            jnp.zeros((1,), dtype=jnp.int32),     # mq
            jnp.zeros((1,), dtype=jnp.int32),     # c1
            jnp.full((1,), warning, dtype=jnp.float32),
            jnp.full((1,), max_tok, dtype=jnp.float32),
            jnp.full((1,), slope, dtype=jnp.float32),
            jnp.full((1,), 10**9, dtype=jnp.float32),  # refill thr (unused)
        )
        return _transition(latest, stored_a, lastfill, x)[0]

    ok = jax.jit(f)(
        jnp.zeros((1,), dtype=jnp.int32),
        jnp.full((1,), stored, dtype=jnp.float32),
        jnp.full((1,), ts - ts % 1000, dtype=jnp.int32),
        jnp.full((1,), passq, dtype=jnp.float32),
    )
    return bool(np.asarray(ok)[0])


def _boundary_probes(wq64: float):
    """f32-representable integer passQps values straddling the f64
    boundary (an integer not exactly representable in f32 cannot be a
    real windowed pass count input at these magnitudes — window sums
    enter the kernel through a f32 floor)."""
    bp = math.floor(wq64)
    step = max(1, int(np.spacing(np.float32(bp))))
    out = []
    for p in range(bp - 3 * step, bp + 3 * step + 1):
        if float(np.float32(p)) == float(p):
            out.append(p)
    return out


@pytest.mark.parametrize("count", [1e4, 1e6])
@pytest.mark.parametrize("frac", [0.25, 0.6, 1.0])
def test_boundary_tick_matches_java_f64_exactly(count, frac):
    warning, max_tok, slope = _java_model(count, 10)
    stored = warning + (max_tok - warning) * frac
    above = stored - warning
    wq64 = math.nextafter(1.0 / (above * slope + 1.0 / count), math.inf)
    for p in _boundary_probes(wq64):
        want = _java_verdict(p, 1.0, stored, warning, slope, count)
        got = _kernel_verdict(p, stored, warning, max_tok, slope, count)
        assert got == want, (
            f"count={count} frac={frac} passQps={p}: kernel={got} java={want} "
            f"(wq64={wq64})"
        )


@pytest.mark.parametrize("frac", [0.25, 0.6, 1.0])
def test_boundary_divergence_at_1e8_bounded_by_one_ulp(frac):
    """At count=1e8 the f32 warning QPS sits a few accumulated f32
    rounding steps above the f64 value (each of above*slope, +1/count,
    the divide and nextafter rounds once), so inside that tick the
    kernel admits where Java blocks. Pin: (a) divergence only ever in
    that direction, (b) only within relative 2e-7 of the boundary,
    (c) exact agreement outside."""
    count = 1e8
    warning, max_tok, slope = _java_model(count, 10)
    stored = warning + (max_tok - warning) * frac
    above = stored - warning
    wq64 = math.nextafter(1.0 / (above * slope + 1.0 / count), math.inf)
    tick = 2e-7 * wq64
    diverged = 0
    for p in _boundary_probes(wq64):
        want = _java_verdict(p, 1.0, stored, warning, slope, count)
        got = _kernel_verdict(p, stored, warning, max_tok, slope, count)
        if got != want:
            diverged += 1
            assert got and not want, "kernel must never BLOCK where Java passes"
            assert abs((p + 1.0) - wq64) <= tick, (
                f"divergence outside the pinned tick: passQps={p} wq64={wq64} "
                f"tick={tick}"
            )
    # The known cases (frac 0.25 and 1.0) do diverge inside the tick —
    # if the kernel ever goes f64 this xfail-style guard flips to full
    # exactness and the assert above keeps holding vacuously.
    assert diverged <= 2
