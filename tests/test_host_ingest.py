"""Host-ingest fast path — differential guarantees.

The persistent param-value intern cache, the encode-buffer arena, and
the columnar gateway batch are pure host-side optimizations: none of
them may ever change an admission verdict. These tests pin that —
against the sequential oracle (``testing/oracle.py``), against the
exact (fast-path-off) resolution path, and against buffer aliasing
across consecutive flushes (including the deferred-fetch
``flush_async`` path).
"""

import numpy as np
import pytest


def _param_setup(engine, resource, count, manual_clock=None):
    import sentinel_tpu as st
    from sentinel_tpu.models.rules import ParamFlowRule

    engine.set_flow_rules([st.FlowRule(resource, count=1e9)])
    engine.set_param_rules(
        {resource: [ParamFlowRule(resource, param_idx=0, count=count)]}
    )


def _oracle_admit(values, t, count):
    """Expected per-request admissions: one OracleParamBucket per
    distinct value, requests checked in submission order."""
    from sentinel_tpu.testing.oracle import OracleParamBucket

    buckets = {}
    out = []
    for v in values:
        b = buckets.get(v)
        if b is None:
            b = buckets[v] = OracleParamBucket(count, 0, 1000)
        out.append(b.check(t))
    return out


class TestInternCacheInvalidation:
    def test_reload_drops_stale_prows_and_matches_cold_engine(
        self, manual_clock, engine
    ):
        """Mid-traffic param-rule reload: the rebuilt index must drop
        every cached value→prow mapping, and post-reload verdicts must
        equal a cold engine's (differential vs the sequential oracle —
        the reference rebuilds ParameterMetric on reload, so budgets
        restart)."""
        count = 3
        _param_setup(engine, "rr", count)
        manual_clock.set_ms(1000)
        values = [f"hh-{i % 4}" for i in range(24)]
        g1 = engine.submit_bulk(
            "rr", 24, ts=np.full(24, 1000, dtype=np.int32),
            args_column=[(v,) for v in values],
        )
        engine.flush()
        assert g1.admitted.tolist() == _oracle_admit(values, 1000, count)
        old_index = engine.param_index
        assert any(old_index._resolved)  # cache warmed by the traffic

        # Reload (identical rules): a fresh ParamIndex — the intern
        # cache must die with the old one, budgets restart cold.
        _param_setup(engine, "rr", count)
        assert engine.param_index is not old_index
        assert all(not d for d in engine.param_index._resolved)
        assert all(not d for d in engine.param_index._values)

        manual_clock.set_ms(1100)
        g2 = engine.submit_bulk(
            "rr", 24, ts=np.full(24, 1100, dtype=np.int32),
            args_column=[(v,) for v in values],
        )
        engine.flush()
        # Cold oracle: the same per-value budget is available again.
        assert g2.admitted.tolist() == _oracle_admit(values, 1100, count)

    def test_lru_eviction_drops_resolved_entry(self, manual_clock, engine):
        """An LRU eviction recycles a row for a different value — the
        resolved-value cache must not keep serving the old mapping.
        At the cap, resolution reverts to the exact touch-per-value
        path, so a heavy hitter that keeps appearing is never evicted
        by a churn of cold one-off values."""
        from sentinel_tpu.models.rules import ParamFlowRule
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("ev", count=1e9)])
        engine.set_param_rules(
            {"ev": [ParamFlowRule("ev", param_idx=0, count=5)]}
        )
        pindex = engine.param_index
        # Shrink the cap so eviction is reachable.
        pindex._caps[0] = 4
        manual_clock.set_ms(1000)
        cols = [("hot",)] + [(f"v{i}",) for i in range(3)]
        engine.submit_bulk("ev", 4, ts=np.full(4, 1000, dtype=np.int32),
                           args_column=cols)
        engine.flush()
        assert set(pindex._resolved[0]) == {"hot", "v0", "v1", "v2"}
        # At the cap: cold churn alongside the hot value, several
        # flushes — the exact path's per-flush LRU touch must keep
        # "hot" resident while the one-off values evict each other.
        for i in range(3, 9):
            engine.submit_bulk(
                "ev", 2, ts=np.full(2, 1000, dtype=np.int32),
                args_column=[("hot",), (f"v{i}",)],
            )
            engine.flush()
            assert "hot" in pindex._values[0]
        # Evicted keys are gone from BOTH maps (no stale prow service).
        assert "v0" not in pindex._values[0]
        assert "v0" not in pindex._resolved[0]

    def test_cap_crossing_column_matches_exact_path(self, manual_clock, engine):
        """A column whose misses cross the intern cap mid-flush must
        not evict a key already resolved from the cache in that same
        flush (its cached prow would alias a reset, reassigned row) —
        the fast path restarts the column on the exact path instead.
        Differential vs a fastpath-off engine with the same cap."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.runtime.engine import Engine
        from sentinel_tpu.utils.config import config

        flow = [st.FlowRule("cx", count=1e9)]
        param = {"cx": [ParamFlowRule("cx", param_idx=0, count=3)]}
        engine.set_flow_rules(flow)
        engine.set_param_rules(param)
        prev = config.get(config.HOST_FASTPATH)
        config.set(config.HOST_FASTPATH, "false")
        try:
            ref = Engine(clock=manual_clock)
            ref.set_flow_rules(flow)
            ref.set_param_rules(param)
        finally:
            config.set(config.HOST_FASTPATH, prev if prev is not None else "true")
        engine.param_index._caps[0] = 4
        ref.param_index._caps[0] = 4
        streams = [
            (1000, ["hot", "v1", "v2"]),      # warm: 3 of 4 rows used
            (1050, ["hot"]),                  # pure cache hit — recency
                                              # must still advance like
                                              # the exact path's touch
            (1100, ["n1", "n2"]),             # crosses the cap WITHOUT
                                              # hot in the column
            (1200, ["hot"] * 5),              # hot budget must be continuous
            (1300, ["hot", "n3", "n4"]),      # crossing column WITH hot
            (1400, ["hot"] * 5),
        ]
        for t, vals in streams:
            manual_clock.set_ms(t)
            n = len(vals)
            ts = np.full(n, t, dtype=np.int32)
            col = [(v,) for v in vals]
            gf = engine.submit_bulk("cx", n, ts=ts, args_column=col)
            gs = ref.submit_bulk("cx", n, ts=ts, args_column=col)
            engine.flush()
            ref.flush()
            assert gf.admitted.tolist() == gs.admitted.tolist(), (t, vals)
        assert "hot" in engine.param_index._values[0]


class TestArenaAliasing:
    def _assert_no_pool_alias(self, engine, *arrays):
        arena = engine._arena
        if arena is None:
            return
        for sets in arena._pool.values():
            for bufs in sets:
                for buf in bufs:
                    for a in arrays:
                        assert not np.shares_memory(a, buf)

    def test_consecutive_flush_results_do_not_share_memory(
        self, manual_clock, engine
    ):
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("ar", count=4)])
        manual_clock.set_ms(1000)
        g1 = engine.submit_bulk("ar", 8, ts=np.full(8, 1000, dtype=np.int32))
        engine.flush()
        a1, r1, w1 = g1.admitted, g1.reason, g1.wait_ms
        snap = (a1.tolist(), r1.tolist(), w1.tolist())
        # Same shape key → the arena reuses the staging buffers.
        g2 = engine.submit_bulk("ar", 8, ts=np.full(8, 1000, dtype=np.int32))
        engine.flush()
        for x, y in ((g1.admitted, g2.admitted), (g1.reason, g2.reason),
                     (g1.wait_ms, g2.wait_ms)):
            assert not np.shares_memory(x, y)
        self._assert_no_pool_alias(engine, g1.admitted, g2.admitted,
                                   g1.reason, g2.reason, g1.wait_ms, g2.wait_ms)
        # g1's verdicts survive g2's flush bit-for-bit.
        assert (g1.admitted.tolist(), g1.reason.tolist(),
                g1.wait_ms.tolist()) == snap
        assert g1.admitted_count == 4
        assert g2.admitted_count == 0  # window budget spent by g1

    def test_flush_async_deferred_fetch_does_not_alias(
        self, manual_clock, engine
    ):
        """Two arena-sharing flush_async dispatches: the deferred
        fetches must fill verdict arrays that share no memory with each
        other or with the live staging buffers."""
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("aa", count=6)])
        manual_clock.set_ms(1000)
        g1 = engine.submit_bulk("aa", 8, ts=np.full(8, 1000, dtype=np.int32))
        engine.flush_async()
        g2 = engine.submit_bulk("aa", 8, ts=np.full(8, 1000, dtype=np.int32))
        engine.flush_async()
        engine.drain()
        assert not np.shares_memory(g1.admitted, g2.admitted)
        assert not np.shares_memory(g1.reason, g2.reason)
        self._assert_no_pool_alias(engine, g1.admitted, g2.admitted)
        assert g1.admitted_count == 6
        assert g2.admitted_count == 0

    def test_mixed_singles_and_param_shapes_reuse_safely(
        self, manual_clock, engine
    ):
        """Param staging buffers are arena-pooled too: back-to-back
        hot-param flushes at one shape must keep earlier verdicts
        intact."""
        _param_setup(engine, "pm", 2)
        manual_clock.set_ms(1000)
        col = [("a",), ("a",), ("a",), ("b",)]
        g1 = engine.submit_bulk("pm", 4, ts=np.full(4, 1000, dtype=np.int32),
                                args_column=col)
        engine.flush()
        snap = g1.admitted.tolist()
        g2 = engine.submit_bulk("pm", 4, ts=np.full(4, 1000, dtype=np.int32),
                                args_column=col)
        engine.flush()
        assert g1.admitted.tolist() == snap == [True, True, False, True]
        # "a" spent its budget in g1; "b" has one token left.
        assert g2.admitted.tolist() == [False, False, False, True]


class TestFastPathDifferentialSmoke:
    def test_with_and_without_fast_path_identical_verdicts(
        self, manual_clock, engine
    ):
        """The config toggle differential: random heavy-hitter gateway
        batches through the fast path (intern cache + arena, default)
        and through the exact path (sentinel.tpu.host.fastpath=false)
        must produce bit-identical verdict arrays — including across a
        param-rule reload."""
        import sentinel_tpu as st
        from sentinel_tpu.adapters.gateway import (
            GatewayFlowRule,
            GatewayParamFlowItem,
            GatewayRequestBatch,
            PARAM_PARSE_STRATEGY_CLIENT_IP,
            gateway_rule_manager,
            gateway_submit_bulk,
        )
        from sentinel_tpu.rules.param_manager import param_flow_rule_manager
        from sentinel_tpu.runtime.engine import Engine
        from sentinel_tpu.utils.config import config

        route = "smoke_route"
        gateway_rule_manager.load_rules([
            GatewayFlowRule(
                route, count=3,
                param_item=GatewayParamFlowItem(
                    parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP),
            ),
        ])
        engine.set_flow_rules([st.FlowRule(route, count=1e9)])
        prev = config.get(config.HOST_FASTPATH)
        config.set(config.HOST_FASTPATH, "false")
        try:
            slow = Engine(clock=manual_clock)
            assert slow._arena is None
            slow.set_flow_rules([st.FlowRule(route, count=1e9)])
            slow.set_param_rules(dict(param_flow_rule_manager.by_resource))
            assert not slow.param_index._use_value_cache
        finally:
            config.set(config.HOST_FASTPATH, prev if prev is not None else "true")
        assert engine.param_index._use_value_cache
        assert engine._arena is not None

        rng = np.random.default_rng(7)
        t = 1000
        for round_no in range(4):
            manual_clock.set_ms(t)
            n = int(rng.integers(16, 64))
            # Heavy-hitter mix: a few hot IPs plus a random long tail.
            hot = [f"10.0.0.{h}" for h in range(3)]
            ips = [
                hot[int(rng.integers(0, 3))]
                if rng.random() < 0.8
                else f"10.9.{int(rng.integers(0, 256))}.{int(rng.integers(0, 256))}"
                for _ in range(n)
            ]
            batch = GatewayRequestBatch(n=n, client_ip=ips)
            ts = np.full(n, t, dtype=np.int32)
            gf = gateway_submit_bulk(route, batch, engine=engine, ts=ts)
            gs = gateway_submit_bulk(route, batch, engine=slow, ts=ts)
            engine.flush()
            slow.flush()
            assert gf.admitted.tolist() == gs.admitted.tolist(), (
                f"fast/exact divergence in round {round_no}"
            )
            assert gf.reason.tolist() == gs.reason.tolist()
            if round_no == 1:
                # Reload mid-traffic: both engines must invalidate
                # their intern caches identically.
                engine.set_param_rules(dict(param_flow_rule_manager.by_resource))
                slow.set_param_rules(dict(param_flow_rule_manager.by_resource))
            t += int(rng.integers(50, 400))


class TestArgsColumns:
    def test_validation(self):
        from sentinel_tpu.rules.param_table import ArgsColumns

        with pytest.raises(ValueError, match="length"):
            ArgsColumns(3, {0: ["a", "b"]})
        assert len(ArgsColumns(2, {0: ["a", "b"]})) == 2

    def test_engine_parity_with_tuple_column(self, manual_clock, engine):
        """submit_bulk(args_column=ArgsColumns) decides exactly like
        the same values as per-entry tuples."""
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.rules.param_table import ArgsColumns
        from sentinel_tpu.runtime.engine import Engine

        flow = [st.FlowRule("ac", count=1e9)]
        param = {"ac": [ParamFlowRule("ac", param_idx=0, count=2)]}
        engine.set_flow_rules(flow)
        engine.set_param_rules(param)
        ref = Engine(clock=manual_clock)
        ref.set_flow_rules(flow)
        ref.set_param_rules(param)
        manual_clock.set_ms(1000)
        values = [f"k{i % 3}" for i in range(12)] + [None]
        n = len(values)
        ts = np.full(n, 1000, dtype=np.int32)
        g_flat = engine.submit_bulk(
            "ac", n, ts=ts, args_column=ArgsColumns(n, {0: values})
        )
        engine.flush()
        g_tup = ref.submit_bulk(
            "ac", n, ts=ts, args_column=[(v,) for v in values]
        )
        ref.flush()
        assert g_flat.admitted.tolist() == g_tup.admitted.tolist()
        assert g_flat.admitted.tolist()[-1]  # None value → rule passes

    def test_missing_idx_means_no_value(self, manual_clock, engine):
        import sentinel_tpu as st
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.rules.param_table import ArgsColumns

        engine.set_flow_rules([st.FlowRule("mi", count=1e9)])
        engine.set_param_rules(
            {"mi": [ParamFlowRule("mi", param_idx=1, count=1)]}
        )
        manual_clock.set_ms(1000)
        g = engine.submit_bulk(
            "mi", 4, ts=np.full(4, 1000, dtype=np.int32),
            args_column=ArgsColumns(4, {0: ["a", "a", "a", "a"]}),
        )
        engine.flush()
        assert g.admitted.all()  # no value for param_idx 1 → passes
