"""Redis push datasource against an in-process RESP server: initial GET,
SUBSCRIBE-driven live rule reload through converter → manager → engine
table swap, and reconnect-with-catchup — the fake-server strategy the
reference uses for its datasource adapters (no containers, SURVEY §4).
"""

import json
import socketserver
import threading
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.datasource.redis_source import RedisDataSource, RespConnection


class FakeRedis(socketserver.ThreadingTCPServer):
    """Just enough RESP: GET / SET / AUTH / SELECT / SUBSCRIBE / PUBLISH."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self):
        super().__init__(("127.0.0.1", 0), _FakeRedisHandler)
        self.data = {}
        self.subscribers = {}  # channel -> list of wfile-ish sockets
        self.sub_lock = threading.Lock()
        self._thread = threading.Thread(target=self.serve_forever, daemon=True)
        self._thread.start()

    @property
    def port(self):
        return self.server_address[1]

    def publish(self, channel, payload):
        raw = payload.encode()
        msg = (
            b"*3\r\n$7\r\nmessage\r\n"
            + b"$%d\r\n%s\r\n" % (len(channel), channel.encode())
            + b"$%d\r\n%s\r\n" % (len(raw), raw)
        )
        with self.sub_lock:
            socks = list(self.subscribers.get(channel, ()))
        for s in socks:
            try:
                s.sendall(msg)
            except OSError:
                pass

    def kill_subscribers(self, channel):
        with self.sub_lock:
            socks = self.subscribers.pop(channel, [])
        for s in socks:
            try:
                s.shutdown(2)
                s.close()
            except OSError:
                pass

    def stop(self):
        self.shutdown()
        self.server_close()


class _FakeRedisHandler(socketserver.BaseRequestHandler):
    def _read_command(self, buf):
        # Parse one RESP array-of-bulk-strings command from the socket.
        def read_line():
            nonlocal buf
            while b"\r\n" not in buf:
                chunk = self.request.recv(4096)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            line, buf = buf.split(b"\r\n", 1)
            return line

        def read_exact(n):
            nonlocal buf
            while len(buf) < n + 2:
                chunk = self.request.recv(4096)
                if not chunk:
                    raise ConnectionError
                buf += chunk
            data, buf = buf[:n], buf[n + 2:]
            return data

        line = read_line()
        assert line[:1] == b"*", line
        n = int(line[1:])
        parts = []
        for _ in range(n):
            hdr = read_line()
            assert hdr[:1] == b"$"
            parts.append(read_exact(int(hdr[1:])).decode())
        return parts, buf

    def handle(self):
        buf = b""
        server: FakeRedis = self.server  # type: ignore[assignment]
        try:
            while True:
                cmd, buf = self._read_command(buf)
                op = cmd[0].upper()
                if op in ("AUTH", "SELECT"):
                    self.request.sendall(b"+OK\r\n")
                elif op == "SET":
                    server.data[cmd[1]] = cmd[2]
                    self.request.sendall(b"+OK\r\n")
                elif op == "GET":
                    v = server.data.get(cmd[1])
                    if v is None:
                        self.request.sendall(b"$-1\r\n")
                    else:
                        raw = v.encode()
                        self.request.sendall(b"$%d\r\n%s\r\n" % (len(raw), raw))
                elif op == "SUBSCRIBE":
                    ch = cmd[1]
                    with server.sub_lock:
                        server.subscribers.setdefault(ch, []).append(self.request)
                    ack = (
                        b"*3\r\n$9\r\nsubscribe\r\n"
                        + b"$%d\r\n%s\r\n" % (len(ch), ch.encode())
                        + b":1\r\n"
                    )
                    self.request.sendall(ack)
                else:
                    self.request.sendall(b"-ERR unknown command\r\n")
        except (ConnectionError, OSError):
            pass


def _rules_json(count):
    return json.dumps([{"resource": "res", "count": count, "grade": 1}])


@pytest.fixture()
def fake_redis():
    server = FakeRedis()
    yield server
    server.stop()


def _wait(predicate, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


class TestRespConnection:
    def test_basic_commands(self, fake_redis):
        conn = RespConnection("127.0.0.1", fake_redis.port)
        assert conn.command("SET", "k", "v") == "OK"
        assert conn.command("GET", "k") == "v"
        assert conn.command("GET", "missing") is None
        conn.close()


class TestRedisDataSource:
    def test_initial_load_and_push_reload(self, fake_redis, manual_clock, engine):
        """GET seeds the rules; a PUBLISH live-swaps the engine table:
        push → converter → manager → engine (round-2 missing #4)."""
        fake_redis.data["sentinel.rules"] = _rules_json(1)
        src = RedisDataSource(
            json_converter(st.FlowRule), port=fake_redis.port,
            rule_key="sentinel.rules", channel="rules.ch",
        ).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            manual_clock.set_ms(100)
            assert st.try_entry("res") is not None
            assert st.try_entry("res") is None  # count=1 enforced

            fake_redis.publish("rules.ch", _rules_json(5))
            assert _wait(
                lambda: any(
                    r.count == 5 for r in (st.flow_rule_manager.get_rules() or [])
                )
            ), "published rules never reached the manager"
            manual_clock.set_ms(2000)  # fresh window
            admitted = sum(1 for _ in range(8) if st.try_entry("res") is not None)
            assert admitted == 5  # new count live in the engine table
        finally:
            src.close()

    def test_reconnect_rereads_key(self, fake_redis):
        """A dropped subscriber reconnects and re-reads the key so
        publishes during the outage are not lost."""
        fake_redis.data["k"] = _rules_json(1)
        src = RedisDataSource(
            json_converter(st.FlowRule), port=fake_redis.port,
            rule_key="k", channel="ch", reconnect_interval_sec=0.1,
        ).start()
        try:
            assert _wait(lambda: "ch" in fake_redis.subscribers)
            # Outage: kill the subscriber; meanwhile the key changes.
            fake_redis.kill_subscribers("ch")
            fake_redis.data["k"] = _rules_json(9)
            assert _wait(
                lambda: src.get_property().value
                and src.get_property().value[0].count == 9
            ), "reconnect did not re-read the key"
        finally:
            src.close()

    def test_bad_payload_keeps_old_rules(self, fake_redis):
        fake_redis.data["k"] = _rules_json(2)
        src = RedisDataSource(
            json_converter(st.FlowRule), port=fake_redis.port,
            rule_key="k", channel="ch",
        ).start()
        try:
            assert _wait(lambda: "ch" in fake_redis.subscribers)
            fake_redis.publish("ch", "{not json")
            time.sleep(0.3)
            assert src.get_property().value[0].count == 2  # unchanged
        finally:
            src.close()


class TestRespRobustness:
    def test_deep_nesting_raises_resp_error(self):
        """A stream of nested '*1' headers (~4 bytes/level) must hit the
        depth cap as a RespError, not recurse into RecursionError."""
        import socket

        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]

        def feed():
            s, _ = srv.accept()
            s.sendall(b"*1\r\n" * 600)
            s.close()

        t = threading.Thread(target=feed, daemon=True)
        t.start()
        conn = RespConnection("127.0.0.1", port)
        try:
            from sentinel_tpu.datasource.redis_source import RespError

            with pytest.raises(RespError, match="nested deeper"):
                conn.read_reply()
        finally:
            conn.close()
            srv.close()

    def test_oversize_length_reconnects_and_recovers(self, fake_redis):
        """A corrupted stream claiming an absurd bulk length must hit
        the size cap (no unbounded allocation), drop the connection,
        reconnect, and keep applying later publishes."""
        fake_redis.data["k"] = _rules_json(5)
        src = RedisDataSource(
            json_converter(st.FlowRule), "127.0.0.1", fake_redis.port,
            rule_key="k", channel="ch", reconnect_interval_sec=0.05,
        ).start()
        try:
            assert _wait(lambda: fake_redis.subscribers.get("ch"))
            assert _wait(
                lambda: src.get_property().value
                and src.get_property().value[0].count == 5
            )
            # Corrupt the live subscription with an oversize bulk
            # length FIRST (exercises the cap), then garbage bytes.
            with fake_redis.sub_lock:
                socks = [s for v in fake_redis.subscribers.values() for s in v]
            assert socks
            for s in socks:
                try:
                    s.sendall(b"$999999999999\r\n\xff garbage\r\n")
                except OSError:
                    pass
            # After reconnect (which re-reads the key), a new value
            # still lands via publish.
            fake_redis.data["k"] = _rules_json(9)

            def recovered():
                fake_redis.publish("ch", _rules_json(9))
                v = src.get_property().value
                return bool(v) and v[0].count == 9

            assert _wait(recovered), "datasource did not recover after corruption"
        finally:
            src.close()
