"""Subprocess worker for the batched differential streams.

The batched differential is the suite's heaviest compile generator,
and long single-process runs on this toolchain eventually segfault
inside XLA:CPU's LLVM compile (see conftest.py) — reliably while
compiling for these streams when they run late in the suite, while
every stream passes in a fresh process. So the pytest entry points
(test_differential_batched.py) spawn this worker: one fresh process
per engine mode, with the XLA state horizon all to itself.

Usage: python -m tests.diffbatch_worker single|mesh|dense
Exit 0 = every seed's stream matched the oracle exactly.
"""

from __future__ import annotations

import sys


def main() -> None:
    mode = sys.argv[1] if len(sys.argv) > 1 else "single"

    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_cpu_parallel_codegen_split_count=1"
    ).strip()
    from sentinel_tpu.utils.backend import force_cpu

    force_cpu(8)

    import numpy as np

    from sentinel_tpu.core import api
    from sentinel_tpu.utils.clock import ManualClock, set_default_clock
    from tests.test_differential import _load_rules
    from tests.test_differential_batched import _mk_models, _run_batched_stream

    if mode == "single":
        cases = [(100 + s, ["qps", "thread", "rl", "warmup", "wurl", "pbucket",
                            "pthrottle"], 60, False, f"seed={s}") for s in range(5)]
    elif mode == "mesh":
        # Warm-up kinds excluded: mesh warm-up passQps not seeing
        # same-flush co-row charges is a documented one-sided deviation.
        cases = [(200 + s, ["qps", "thread", "rl", "pbucket", "pthrottle"],
                  30, True, f"mesh seed={s}") for s in range(2)]
    elif mode == "dense":
        # ONLY the serializing kinds: big flushes over two resources
        # concentrate 10-45 same-key pacer/bucket items per flush, so
        # the recurrence randomly crosses every execution schedule —
        # unrolled rounds (<=4), fori_loop (8/16), and the lax.scan
        # fallback (>16 items per key) — all against the same oracle.
        cases = [(300 + s, ["rl", "pthrottle"], 50, False,
                  f"dense seed={s}") for s in range(2)]
    else:
        raise SystemExit(f"unknown mode {mode!r}")

    for seed, kinds, steps, mesh, ctx in cases:
        clock = ManualClock(0)
        prev = set_default_clock(clock)
        try:
            api.reset(clock=clock)
            engine = api.get_engine()
            if mesh:
                engine.enable_mesh(8)
            rng = np.random.default_rng(seed)
            kinds = list(kinds)
            rng.shuffle(kinds)
            models = _mk_models(kinds, rng)
            _load_rules(models)
            clock.set_ms(1000)
            _run_batched_stream(engine, models, rng, steps=steps, ctx=ctx)
            print(f"[diffbatch_worker] {ctx}: OK", flush=True)
        finally:
            set_default_clock(prev)
            api.reset()
            # Drop compiled executables between streams: each stream's
            # compiles pin JIT code pages whose mmap count accumulates
            # toward vm.max_map_count (65530 default) — the actual
            # mechanism behind the "LLVM compilation error: Cannot
            # allocate memory" → SIGSEGV this worker exists to dodge
            # (observed: ~30k maps after two streams; the crash lands
            # around stream 5). Same mitigation as conftest's periodic
            # clear, which the worker process otherwise lacks.
            import gc

            import jax

            jax.clear_caches()
            gc.collect()


if __name__ == "__main__":
    main()
