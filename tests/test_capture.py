"""Black-box flight recorder tests (runtime/capture.py).

Covers the write side of the capture journal: the capture-off
differential (arming capture must not change a single verdict bit),
segment roundtrip through the reader, bounded rollover, every freeze
trigger (manual, breaker, shed streak, DEGRADED, engine death), and
the ``capture`` transport command. The replay side (tools/replay.py)
is pinned separately by the golden-corpus differential.
"""

import json
import os

import pytest

from sentinel_tpu.models.rules import DegradeRule, FlowRule
from sentinel_tpu.runtime import capture as cap_mod
from sentinel_tpu.runtime.engine import Engine
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config


RULES = [
    FlowRule("cap-qps", count=3),
    FlowRule("cap-open", count=1e9),
]


def _drive(eng, clk, windows=6):
    """Deterministic mixed traffic; returns the flat verdict tuple list
    in submission order."""
    out = []
    for w in range(windows):
        ops = [
            eng.submit_entry("cap-qps", origin=f"svc-{i % 2}", args=("k", i))
            for i in range(5)
        ]
        ops.append(eng.submit_entry("cap-open", acquire=2))
        g = eng.submit_bulk("cap-open", 4, context_name="bulk-ctx")
        eng.flush()
        for op in ops:
            v = op.verdict
            out.append((v.admitted, v.reason, v.wait_ms))
            if v.admitted:
                eng.submit_exit(op.rows, rt=5)
        if g is not None:
            for j in range(4):
                out.append((
                    bool(g.admitted[j]), int(g.reason[j]), int(g.wait_ms[j]),
                ))
        clk.advance(250)
    eng.drain()
    return out


@pytest.fixture()
def cap_dir(tmp_path, manual_clock):
    """Capture armed into a per-test directory; restores config."""
    d = str(tmp_path / "cap")
    config.set(config.CAPTURE_ENABLED, "true")
    config.set(config.CAPTURE_DIR, d)
    try:
        yield d
    finally:
        config.set(config.CAPTURE_ENABLED, "false")
        config.set(config.CAPTURE_DIR, "")


class TestCaptureDifferential:
    def test_disabled_is_one_attribute(self, manual_clock, engine):
        # Default-off footprint: the hot path reads .capture once.
        assert engine.capture is None

    def test_capture_on_is_bit_identical(self, tmp_path, manual_clock):
        """The tentpole acceptance bit: arming capture must not perturb
        admission — same traffic, same clock, identical verdicts."""
        clk_off = ManualClock(start_ms=0)
        eng_off = Engine(clock=clk_off)
        eng_off.set_flow_rules(RULES)
        baseline = _drive(eng_off, clk_off)
        eng_off.close()
        assert any(not adm for adm, _r, _w in baseline)  # some blocked
        assert any(adm for adm, _r, _w in baseline)

        config.set(config.CAPTURE_ENABLED, "true")
        config.set(config.CAPTURE_DIR, str(tmp_path / "cap"))
        try:
            clk_on = ManualClock(start_ms=0)
            eng_on = Engine(clock=clk_on)
            assert eng_on.capture is not None
            eng_on.set_flow_rules(RULES)
            captured = _drive(eng_on, clk_on)
            eng_on.close()
        finally:
            config.set(config.CAPTURE_ENABLED, "false")
            config.set(config.CAPTURE_DIR, "")
        assert captured == baseline


class TestCaptureRoundtrip:
    def test_segments_decode_back_to_the_traffic(self, cap_dir, manual_clock):
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        live = _drive(eng, clk)
        snap = eng.capture.snapshot()
        eng.close()

        paths = cap_mod.capture_paths(cap_dir)
        assert paths
        decoded = cap_mod.decode_capture(paths)
        hdr = decoded["header"]
        assert hdr["boot_id"] == snap["boot_id"]
        assert hdr["config"][config.CAPTURE_ENABLED] == "true"
        # Segment 0 opened before set_flow_rules, so its header rule
        # snapshot is empty and the reload rides the timeline stream —
        # the record replay applies before the first chunk.
        assert hdr["rules"]["flow"] == []
        reloads = [
            d for k, d in decoded["stream"]
            if k == "rules" and d["kind"] == "flow"
        ]
        assert {r["resource"] for r in reloads[0]["rules"]} == {
            "cap-qps", "cap-open",
        }

        all_chunks = [ck for kind, ck in decoded["stream"] if kind == "chunk"]
        # 6 traffic windows + the close-time exits-only flush.
        chunks = [ck for ck in all_chunks if ck.rows]
        assert len(chunks) == 6
        replayed = []
        for ck in chunks:
            assert ck.verdicts is not None
            adm, rea, wait, flags = ck.verdicts
            assert not any(int(f) & cap_mod.F_VERDICT_MISSING for f in flags)
            # Entry rows decode back to submission shape.
            assert [e["resource"] for e in ck.entries] == \
                ["cap-qps"] * 5 + ["cap-open"]
            assert ck.entries[0]["args"] == ("k", 0)
            assert ck.entries[5]["acquire"] == 2
            assert len(ck.bulk) == 1 and len(ck.bulk[0]) == 4
            assert ck.bulk[0][0]["context"] == "bulk-ctx"
            for i in range(ck.rows):
                replayed.append((bool(adm[i]), int(rea[i]), int(wait[i])))
        assert replayed == live
        # Admitted ops' exits were captured too (windows 1.. see the
        # previous window's releases).
        assert any(ck.exits for ck in all_chunks)
        counters = snap["counters"]
        assert counters["chunks"] == 6
        assert counters["frames"] > 6 and counters["bytes"] > 0

    def test_telemetry_counters_flow(self, cap_dir, manual_clock):
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        _drive(eng, clk, windows=2)
        tele = eng.telemetry.counters_snapshot()
        eng.close()
        assert tele["capture_chunks"] == 2
        assert tele["capture_records"] > 0
        assert tele["capture_bytes"] > 0


class TestRolloverAndFreeze:
    def test_rollover_is_bounded(self, cap_dir, manual_clock):
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        cap = eng.capture
        cap.segment_bytes = 2048  # force a roll every couple of chunks
        cap.segments_max = 3
        _drive(eng, clk, windows=30)
        snap = cap.snapshot()
        eng.close()
        assert snap["counters"]["rollovers"] > 3
        assert len(snap["live"]) <= 3
        on_disk = [f for f in os.listdir(cap_dir) if f.startswith("seg-")]
        assert len(on_disk) <= 3
        # The bounded survivors still decode and carry verdicts.
        decoded = cap_mod.decode_capture(cap_mod.capture_paths(cap_dir))
        chunks = [ck for k, ck in decoded["stream"] if k == "chunk"]
        assert chunks and any(ck.verdicts is not None for ck in chunks)

    def test_manual_freeze_pins_segments(self, cap_dir, manual_clock):
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        _drive(eng, clk, windows=2)
        frozen = eng.capture.freeze("manual")
        assert frozen and all("frozen-manual-" in p for p in frozen)
        # Recording continues into a fresh segment after the freeze.
        _drive(eng, clk, windows=1)
        snap = eng.capture.snapshot()
        eng.close()
        assert snap["counters"]["freezes"] == 1
        assert snap["frozen"] and snap["live"]
        # Frozen segments decode standalone, with the freeze marker.
        hdr, recs = cap_mod.read_segment(frozen[0])
        assert recs[-1].rkind == cap_mod.RK_FREEZE
        assert recs[-1].json()["reason"] == "manual"

    def test_breaker_shed_and_degraded_triggers(self, cap_dir, manual_clock):
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        cap = eng.capture
        _drive(eng, clk, windows=1)
        cap.note_breaker_open(["cap-qps"])
        _drive(eng, clk, windows=1)
        cap.note_health({"event": "transition", "to": "DEGRADED"})
        _drive(eng, clk, windows=1)
        cap.shed_streak = 4
        cap.note_shed(3)   # below streak: no freeze
        assert cap.counters["freezes"] == 2
        cap.note_shed(1)   # crosses: freeze fires
        snap = cap.snapshot()
        eng.close()
        assert snap["counters"]["freezes"] == 3
        reasons = {f.split("-")[1] for f in snap["frozen"]}
        assert reasons == {"breaker", "degraded", "shed"}
        # The health events rode the rule-timeline stream.
        decoded = cap_mod.decode_capture(
            cap_mod.capture_paths(cap_dir, frozen=True)
        )
        health = [d for k, d in decoded["stream"] if k == "health"]
        assert {"breaker_open"} <= {h.get("event") for h in health}
        assert any(h.get("to") == "DEGRADED" for h in health)

    def test_frozen_set_is_trimmed(self, cap_dir, manual_clock):
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        cap = eng.capture
        cap.frozen_max = 2
        for i in range(4):
            _drive(eng, clk, windows=1)
            cap.freeze(f"f{i}")
        frozen = [f for f in os.listdir(cap_dir) if f.startswith("frozen-")]
        eng.close()
        assert len(frozen) <= 2


class TestDeathPreservation:
    def test_next_boot_preserves_dead_segments(self, cap_dir, manual_clock):
        """kill -9 leaves live seg-*.cap files behind; the next boot
        must rename them frozen-death-* BEFORE writing a byte, and a
        torn tail (death mid-record) must decode cleanly."""
        clk = ManualClock(start_ms=0)
        eng = Engine(clock=clk)
        eng.set_flow_rules(RULES)
        _drive(eng, clk, windows=3)
        dead_seg = eng.capture._live[-1][1]
        eng.capture.close()   # simulate death: no freeze, files left
        eng.close()
        # Tear the tail mid-record, as a dying write would.
        with open(dead_seg, "ab") as f:
            f.write(cap_mod._REC.pack(cap_mod.RK_FLUSH, 0, 0, 999, -1, 0, 0))
            f.write(b"{tr")  # payload cut short
        hdr, recs = cap_mod.read_segment(dead_seg)
        assert recs and recs[-1].rkind != 999

        eng2 = Engine(clock=ManualClock(start_ms=0))
        boot2 = eng2.capture._boot_id
        assert not [
            f for f in os.listdir(cap_dir)
            if f.startswith("seg-") and cap_mod.read_segment(
                os.path.join(cap_dir, f)
            )[0]["boot_id"] != boot2
        ]
        death = [
            f for f in os.listdir(cap_dir) if f.startswith("frozen-death-")
        ]
        assert death
        # The preserved postmortem still decodes to chunks + verdicts.
        decoded = cap_mod.decode_capture(
            [os.path.join(cap_dir, f) for f in sorted(death)]
        )
        chunks = [ck for k, ck in decoded["stream"] if k == "chunk"]
        assert len(chunks) == 3
        assert all(ck.verdicts is not None for ck in chunks)
        eng2.close()


class TestCaptureCommand:
    def test_command_disabled_and_armed(self, tmp_path, manual_clock):
        from sentinel_tpu.core import api
        from sentinel_tpu.transport import handlers
        from sentinel_tpu.transport.command_center import CommandRequest

        resp = handlers.capture_handler(
            CommandRequest(path="capture", params={}, body="")
        )
        assert json.loads(resp.result)["enabled"] is False

        config.set(config.CAPTURE_ENABLED, "true")
        config.set(config.CAPTURE_DIR, str(tmp_path / "cmdcap"))
        try:
            api.reset(clock=manual_clock)
            eng = api.get_engine()
            eng.set_flow_rules(RULES)
            _drive(eng, manual_clock, windows=2)
            resp = handlers.capture_handler(
                CommandRequest(path="capture", params={}, body="")
            )
            d = json.loads(resp.result)
            assert d["enabled"] is True
            assert d["counters"]["chunks"] == 2 and d["live"]
            # freeze=<reason> is the on-demand postmortem.
            resp = handlers.capture_handler(
                CommandRequest(
                    path="capture", params={"freeze": "oncall page!"}, body=""
                )
            )
            d = json.loads(resp.result)
            assert d["frozen_now"]
            assert all(f.startswith("frozen-oncallpage-") for f in d["frozen_now"])
            from sentinel_tpu.transport.prometheus import render_metrics

            text = render_metrics(eng)
            assert "_capture_enabled 1" in text
            assert "_capture_freezes_total" in text
        finally:
            config.set(config.CAPTURE_ENABLED, "false")
            config.set(config.CAPTURE_DIR, "")
            api.reset(clock=manual_clock)
