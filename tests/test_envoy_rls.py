"""Envoy RLS gRPC front-end: ShouldRateLimit over a real gRPC channel
with the v2 request shape (domain + descriptors + hits_addend), backed
by the shared token service (≙ SentinelEnvoyRlsServiceImpl +
SentinelEnvoyRlsServiceImplTest's pass/block scenarios).
"""

import pytest

from sentinel_tpu.cluster import cluster_flow_rule_manager
from sentinel_tpu.cluster.envoy_rls import (
    CODE_OK,
    CODE_OVER_LIMIT,
    EnvoyRlsRule,
    RlsDescriptor,
    SentinelRlsGrpcServer,
    decode_rate_limit_response,
    encode_rate_limit_request,
    envoy_rls_rule_manager,
    generate_flow_id,
    generate_key,
    to_flow_rules,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.utils.clock import ManualClock


@pytest.fixture()
def rls_rules():
    cluster_flow_rule_manager.clear()
    envoy_rls_rule_manager.load_rules(
        [
            EnvoyRlsRule(
                domain="mesh",
                descriptors=(
                    RlsDescriptor(resources=(("destination", "svcA"),), count=3),
                    RlsDescriptor(
                        resources=(("destination", "svcB"), ("method", "POST")),
                        count=1,
                    ),
                ),
            )
        ]
    )
    yield
    envoy_rls_rule_manager.clear()
    cluster_flow_rule_manager.clear()


class TestRuleConversion:
    def test_converter_shape(self):
        rule = EnvoyRlsRule(
            "d", (RlsDescriptor(resources=(("k", "v"),), count=7),)
        )
        (fr,) = to_flow_rules(rule)
        assert fr.resource == "d|k|v"
        assert fr.count == 7 and fr.cluster_mode
        cc = fr.cluster_config
        assert cc.flow_id == generate_flow_id("d|k|v")
        assert cc.sample_count == 1 and not cc.fallback_to_local_when_fail

    def test_flow_id_stable_and_positive(self):
        key = generate_key("d", [("a", "b"), ("c", "d")])
        assert key == "d|a|b|c|d"
        assert generate_flow_id(key) == generate_flow_id(key) > 0


class TestShouldRateLimitGrpc:
    def _call(self, channel, domain, descriptors, hits=0):
        import grpc  # noqa: F401

        method = channel.unary_unary(
            "/envoy.service.ratelimit.v2.RateLimitService/ShouldRateLimit",
            request_serializer=None,
            response_deserializer=None,
        )
        raw = method(encode_rate_limit_request(domain, descriptors, hits))
        return decode_rate_limit_response(raw)

    def test_pass_then_over_limit(self, rls_rules):
        import grpc

        svc = DefaultTokenService(clock=ManualClock(0))
        server = SentinelRlsGrpcServer(port=0, token_service=svc).start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{server.port}") as channel:
                desc = [[("destination", "svcA")]]
                for i in range(3):
                    overall, statuses = self._call(channel, "mesh", desc)
                    assert overall == CODE_OK, f"request {i} should pass"
                    assert statuses[0][0] == CODE_OK
                    assert statuses[0][1] == 3  # current_limit requests/s
                overall, statuses = self._call(channel, "mesh", desc)
                assert overall == CODE_OVER_LIMIT
                assert statuses[0][0] == CODE_OVER_LIMIT
        finally:
            server.stop()

    def test_unknown_descriptor_passes(self, rls_rules):
        import grpc

        server = SentinelRlsGrpcServer(
            port=0, token_service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{server.port}") as channel:
                overall, statuses = self._call(
                    channel, "mesh", [[("destination", "unknown-svc")]]
                )
                assert overall == CODE_OK
                assert statuses == [(CODE_OK, None, 0)]
        finally:
            server.stop()

    def test_multi_descriptor_any_block_is_over_limit(self, rls_rules):
        import grpc

        svc = DefaultTokenService(clock=ManualClock(0))
        server = SentinelRlsGrpcServer(port=0, token_service=svc).start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{server.port}") as channel:
                descs = [
                    [("destination", "svcA")],
                    [("destination", "svcB"), ("method", "POST")],
                ]
                overall, statuses = self._call(channel, "mesh", descs)
                assert overall == CODE_OK
                # svcB's count=1 is spent; next call blocks on it only.
                overall, statuses = self._call(channel, "mesh", descs)
                assert overall == CODE_OVER_LIMIT
                assert statuses[0][0] == CODE_OK  # svcA still has room
                assert statuses[1][0] == CODE_OVER_LIMIT
        finally:
            server.stop()

    def test_hits_addend_spends_batch(self, rls_rules):
        import grpc

        svc = DefaultTokenService(clock=ManualClock(0))
        server = SentinelRlsGrpcServer(port=0, token_service=svc).start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{server.port}") as channel:
                desc = [[("destination", "svcA")]]
                overall, _ = self._call(channel, "mesh", desc, hits=3)
                assert overall == CODE_OK
                overall, _ = self._call(channel, "mesh", desc, hits=1)
                assert overall == CODE_OVER_LIMIT
        finally:
            server.stop()


class TestRlsMalformedRequests:
    def test_decoder_rejects_truncation(self):
        import pytest as _pytest

        from sentinel_tpu.cluster.envoy_rls import decode_rate_limit_request

        bad = [
            b"\x80",  # truncated varint
            b"\x80" * 12,  # over-long varint
            b"\x0a\x64abc",  # length-delimited promising 100 bytes, 3 given
            b"\x0d\x01",  # truncated fixed32
            b"\x0b",  # unsupported wire type (3)
            b"\x08\x01",  # field 1 (domain) sent as varint, not bytes
            b"\x10\x01",  # field 2 (descriptor) sent as varint
            b"\x1d1234",  # field 3 (hits) sent as fixed32
        ]
        for raw in bad:
            with _pytest.raises(ValueError):
                decode_rate_limit_request(raw)

    def test_service_answers_invalid_argument_and_survives(self):
        import grpc
        import pytest as _pytest

        from sentinel_tpu.cluster.envoy_rls import (
            EnvoyRlsService,
            decode_rate_limit_response,
            encode_rate_limit_request,
        )

        svc = EnvoyRlsService()

        class Ctx:
            def abort(self, code, details):
                assert code == grpc.StatusCode.INVALID_ARGUMENT
                raise grpc.RpcError(details)

        with _pytest.raises(grpc.RpcError):
            svc.should_rate_limit(b"\x80\x80\x80", Ctx())
        # A well-formed request still serves afterwards.
        raw = encode_rate_limit_request("d", [[("k", "v")]], 1)
        overall, statuses = decode_rate_limit_response(svc.should_rate_limit(raw))
        assert overall in (1, 2) and len(statuses) == 1


class TestShouldRateLimitBulk:
    """The batched endpoint: one RateLimitRequest's descriptors admit
    as ONE columnar gateway_submit_bulk ride (per-descriptor verdicts,
    engine-metered)."""

    def test_mixed_pass_block_batch(self, rls_rules, manual_clock, engine):
        from sentinel_tpu.cluster.envoy_rls import EnvoyRlsService

        manual_clock.set_ms(1000)
        svc = EnvoyRlsService()
        descs = (
            [[("destination", "svcA")]] * 5  # count=3: 3 pass, 2 block
            + [[("destination", "svcB"), ("method", "POST")]]  # count=1
            + [[("destination", "nobody")]]  # no rule -> passes
        )
        raw = svc.should_rate_limit_bulk(
            encode_rate_limit_request("mesh", descs)
        )
        overall, statuses = decode_rate_limit_response(raw)
        assert overall == CODE_OVER_LIMIT
        codes = [s[0] for s in statuses]
        assert codes[:5] == [CODE_OK] * 3 + [CODE_OVER_LIMIT] * 2
        assert codes[5] == CODE_OK and codes[6] == CODE_OK
        # rpu column: the matched descriptor's configured count; None
        # (absent) for the no-rule descriptor.
        assert statuses[0][1] == 3 and statuses[5][1] == 1
        assert statuses[6][1] is None
        # Instantaneous decisions: the group's gauges drain immediately.
        engine.flush()
        engine.drain()
        assert (
            engine.cluster_node_stats("rls:mesh")["cur_thread_num"] == 0
        )

    def test_bulk_independent_of_token_service_book(
        self, rls_rules, manual_clock, engine
    ):
        """The bulk endpoint meters on the engine: a fresh second's
        budget admits again (per-second gateway param rules)."""
        from sentinel_tpu.cluster.envoy_rls import EnvoyRlsService

        manual_clock.set_ms(1000)
        svc = EnvoyRlsService()
        desc = [[("destination", "svcA")]] * 3
        raw = svc.should_rate_limit_bulk(encode_rate_limit_request("mesh", desc))
        overall, _ = decode_rate_limit_response(raw)
        assert overall == CODE_OK
        manual_clock.set_ms(3000)  # next window: budget refreshed
        raw = svc.should_rate_limit_bulk(encode_rate_limit_request("mesh", desc))
        overall, _ = decode_rate_limit_response(raw)
        assert overall == CODE_OK
        engine.flush()
        engine.drain()

    def test_empty_and_malformed(self, rls_rules, manual_clock, engine):
        from sentinel_tpu.cluster.envoy_rls import EnvoyRlsService

        svc = EnvoyRlsService()
        overall, statuses = decode_rate_limit_response(
            svc.should_rate_limit_bulk(encode_rate_limit_request("mesh", []))
        )
        assert overall == CODE_OK and statuses == []
        import pytest as _pytest

        with _pytest.raises(ValueError):
            svc.should_rate_limit_bulk(b"\x80\x80")

    def test_grpc_method_registered(self, rls_rules, manual_clock, engine):
        import grpc

        manual_clock.set_ms(1000)
        server = SentinelRlsGrpcServer(
            port=0, token_service=DefaultTokenService(clock=ManualClock(0))
        ).start()
        try:
            with grpc.insecure_channel(f"127.0.0.1:{server.port}") as ch:
                method = ch.unary_unary(
                    "/envoy.service.ratelimit.v2.RateLimitService/"
                    "ShouldRateLimitBulk",
                    request_serializer=None,
                    response_deserializer=None,
                )
                raw = method(
                    encode_rate_limit_request(
                        "mesh", [[("destination", "svcA")]] * 4
                    )
                )
                overall, statuses = decode_rate_limit_response(raw)
                assert overall == CODE_OVER_LIMIT
                assert [s[0] for s in statuses] == [CODE_OK] * 3 + [
                    CODE_OVER_LIMIT
                ]
        finally:
            server.stop()

    def test_unknown_domain_never_touches_the_engine(
        self, rls_rules, manual_clock, engine
    ):
        """Arbitrary wire-supplied domains must not allocate engine
        resources — every descriptor passes statelessly."""
        from sentinel_tpu.cluster.envoy_rls import EnvoyRlsService

        manual_clock.set_ms(1000)
        svc = EnvoyRlsService()
        for i in range(8):
            raw = svc.should_rate_limit_bulk(
                encode_rate_limit_request(f"attacker-{i}", [[("k", "v")]])
            )
            overall, statuses = decode_rate_limit_response(raw)
            assert overall == CODE_OK and statuses == [(CODE_OK, None, 0)]
        resources = {r for r, _ in engine.nodes.resources()}
        assert not any(r.startswith("rls:attacker") for r in resources)
