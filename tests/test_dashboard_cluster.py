"""Dashboard auth (session login) + cluster management plane.

Reference parity targets: sentinel-dashboard auth/
SimpleWebAuthServiceImpl.java:30 (login/session via the auth filter)
and service/cluster/ClusterAssignServiceImpl.java:36 (assign one
machine as token server, the rest as its clients; surface the server's
per-flowId state).
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

import sentinel_tpu as st
from sentinel_tpu.cluster.flow_rules import (
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.cluster.state import (
    ClusterClientConfigManager,
    ClusterStateManager,
    EmbeddedClusterTokenServerProvider,
    TokenClientProvider,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.dashboard import DashboardServer
from sentinel_tpu.models.rules import ClusterFlowConfig
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.utils.clock import ManualClock


def _req(dport, path, cookie=None, **params):
    qs = urllib.parse.urlencode(params)
    url = f"http://127.0.0.1:{dport}/{path}" + (f"?{qs}" if qs else "")
    req = urllib.request.Request(url)
    if cookie:
        req.add_header("Cookie", cookie)
    try:
        with urllib.request.urlopen(req, timeout=5) as resp:
            return resp.status, resp.read().decode(), resp.headers
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), e.headers


@pytest.fixture()
def cluster_env():
    cluster_flow_rule_manager.clear()
    yield
    cluster_flow_rule_manager.clear()
    ClusterStateManager.stop()
    TokenClientProvider.clear()
    EmbeddedClusterTokenServerProvider.clear()
    ClusterClientConfigManager.apply("", 0)


class TestDashboardAuth:
    def test_login_required_and_session_flow(self):
        dash = DashboardServer(
            port=0, fetch_interval_sec=999,
            auth_username="sentinel", auth_password="s3cret",
        ).start()
        try:
            # Protected API: 401 without a session.
            code, body, _ = _req(dash.port, "apps")
            assert code == 401
            # Exempt paths stay open: console shell, version, registry.
            assert _req(dash.port, "")[0] == 200
            assert _req(dash.port, "version")[0] == 200
            assert _req(
                dash.port, "registry/machine", app="a", ip="1.2.3.4", port="80"
            )[0] == 200
            # Bad credentials rejected.
            code, _, _ = _req(
                dash.port, "auth/login", username="sentinel", password="wrong"
            )
            assert code == 401
            # Good credentials: cookie, then the API opens up.
            code, _, headers = _req(
                dash.port, "auth/login", username="sentinel", password="s3cret"
            )
            assert code == 200
            cookie = headers.get("Set-Cookie", "").split(";")[0]
            assert cookie.startswith("sentinel_dashboard_session=")
            code, body, _ = _req(dash.port, "apps", cookie=cookie)
            assert code == 200
            assert "a" in json.loads(body)
            code, _, _ = _req(dash.port, "auth/check", cookie=cookie)
            # Logout invalidates the session.
            _req(dash.port, "auth/logout", cookie=cookie)
            assert _req(dash.port, "apps", cookie=cookie)[0] == 401
        finally:
            dash.stop()

    def test_auth_disabled_without_credentials(self):
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            assert _req(dash.port, "apps")[0] == 200
            code, body, _ = _req(dash.port, "auth/check")
            assert json.loads(body) == {"enabled": False, "loggedIn": True}
        finally:
            dash.stop()


class TestClusterManagement:
    def test_state_assign_and_server_stats(self, cluster_env, manual_clock, engine):
        """Drive the whole plane over HTTP: register a machine, assign
        it as token server, read back per-flowId qps/concurrency."""
        # The machine: a command center backed by this process's engine,
        # with an embedded (not yet started) token server available.
        clock = ManualClock(0)
        EmbeddedClusterTokenServerProvider.register(
            SentinelTokenServer(port=0, service=DefaultTokenService(clock=clock))
        )
        cluster_server_config_manager.load_global_flow_config(
            exceed_count=1.0, max_allowed_qps=30000.0
        )
        cluster_flow_rule_manager.load_rules(
            "default",
            [st.FlowRule(
                "cres", count=5, cluster_mode=True,
                cluster_config=ClusterFlowConfig(flow_id=7001),
            )],
        )
        cc = CommandCenter(port=0).start()
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            _req(dash.port, "registry/machine", app="capp", ip="127.0.0.1",
                 port=str(cc.port))
            # Before assign: mode off.
            code, body, _ = _req(dash.port, "cluster/state", app="capp")
            assert code == 200
            state = json.loads(body)
            assert state[0]["mode"] == -1

            code, body, _ = _req(
                dash.port, "cluster/assign", app="capp",
                server=f"127.0.0.1:{cc.port}",
            )
            assert code == 200 and json.loads(body)["code"] == 0

            # Token traffic so the server has per-flow state.
            svc = EmbeddedClusterTokenServerProvider.get_server().service
            for _ in range(3):
                assert svc.request_token(7001).ok

            code, body, _ = _req(dash.port, "cluster/state", app="capp")
            state = json.loads(body)
            assert state[0]["mode"] == 1
            stats = state[0]["server"]["stats"]
            flows = {f["flowId"]: f for f in stats["flows"]}
            assert flows[7001]["currentQps"] == pytest.approx(3.0)
            assert flows[7001]["threshold"] == 5.0
            assert state[0]["server"]["config"]["namespaces"] == ["default"]
        finally:
            cc.stop()
            dash.stop()

    def test_assign_unknown_machine_404(self, cluster_env):
        dash = DashboardServer(port=0, fetch_interval_sec=999).start()
        try:
            code, body, _ = _req(
                dash.port, "cluster/assign", app="x", server="9.9.9.9:1"
            )
            assert code == 404
        finally:
            dash.stop()

    def test_rule_store_publishes_through_config_center(
        self, manual_clock, engine
    ):
        """DynamicRuleProvider/Publisher mode end-to-end: the console
        pushes rules into etcd; a machine following the same key via
        EtcdDataSource picks them up through the watch and enforces
        them — no direct machine push involved (reference:
        dashboard/rule/DynamicRuleProvider.java:26)."""
        from tests.test_etcd_source import FakeEtcd, _wait
        from sentinel_tpu.dashboard import EtcdRuleStore
        from sentinel_tpu.datasource.base import json_converter
        from sentinel_tpu.datasource.etcd_source import EtcdDataSource

        fake = FakeEtcd()
        t = threading.Thread(target=fake.serve_forever, daemon=True)
        t.start()
        store = EtcdRuleStore(endpoint=f"http://127.0.0.1:{fake.port}")
        dash = DashboardServer(
            port=0, fetch_interval_sec=999, rule_store=store
        ).start()
        machine_src = EtcdDataSource(
            json_converter(st.FlowRule),
            store.key_for("sapp", "flow"),
            endpoint=f"http://127.0.0.1:{fake.port}",
            reconnect_interval_sec=0.05,
        ).start()
        try:
            st.flow_rule_manager.register_property(machine_src.get_property())
            data = json.dumps([{"resource": "sres", "count": 3}])
            code, body, _ = _req(
                dash.port, "rules", app="sapp", type="flow", data=data
            )
            assert code == 200 and json.loads(body)["code"] == 0
            # The console reads back from the store.
            code, body, _ = _req(dash.port, "rules", app="sapp", type="flow")
            assert json.loads(body)[0]["count"] == 3
            # The machine's watch delivered, and the engine enforces.
            assert _wait(
                lambda: any(
                    r.count == 3 for r in (st.flow_rule_manager.get_rules() or [])
                )
            ), "published rules never reached the machine"
            manual_clock.set_ms(500)
            admitted = sum(1 for _ in range(6) if st.try_entry("sres") is not None)
            assert admitted == 3
        finally:
            machine_src.close()
            dash.stop()
            fake.shutdown()
            fake.server_close()

    def test_client_modify_config_command(self, cluster_env, manual_clock, engine):
        """cluster/client/modifyConfig updates the client config and
        cluster/client/config reads it back (the dashboard assign
        flow's client leg)."""
        cc = CommandCenter(port=0).start()
        try:
            def get(path, **params):
                qs = urllib.parse.urlencode(params)
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{cc.port}/{path}?{qs}", timeout=5
                    ) as r:
                        return r.read().decode()
                except urllib.error.HTTPError as e:
                    return e.read().decode()

            assert get(
                "cluster/client/modifyConfig",
                serverHost="10.0.0.9", serverPort="18730",
            ) == "success"
            cfg = json.loads(get("cluster/client/config"))
            assert cfg["serverHost"] == "10.0.0.9"
            assert cfg["serverPort"] == 18730
            # Bad input fails loudly, config unchanged.
            out = get("cluster/client/modifyConfig", serverHost="", serverPort="x")
            assert "success" not in out
        finally:
            cc.stop()
