"""PR-7 fast-tier coverage: host shaping mirror + host system gate.

The acceptance differentials: (1) host pacer verdicts AND wait-ms
bit-match the device shaping oracle for acquire==1 at pipeline depths
{0, 2}, including arrivals spanning token re-fill seconds; (2) with a
system rule loaded the speculative tier keeps serving — spec_declined
stays 0 for non-prio ops (it used to be 100%) and the host gate's
verdicts match the device system check dimension for dimension.
"""

import numpy as np
import pytest

import sentinel_tpu as st
from sentinel_tpu.core import errors as E
from sentinel_tpu.models import constants as C
from sentinel_tpu.rules.system_manager import SystemConfig
from sentinel_tpu.utils.clock import ManualClock
from sentinel_tpu.utils.config import config
from sentinel_tpu.utils.system_status import sampler


@pytest.fixture(autouse=True)
def _config_sandbox():
    with config._lock:
        saved = dict(config._runtime)
    yield
    with config._lock:
        config._runtime.clear()
        config._runtime.update(saved)


def _mk_engine(clock, spec=True, depth=0, flush_batch=10000):
    from sentinel_tpu.runtime.engine import Engine

    config.set(config.SPECULATIVE_ENABLED, "true" if spec else "false")
    config.set(config.SPECULATIVE_FLUSH_BATCH, str(flush_batch))
    config.set(config.SPECULATIVE_OVERADMIT_MAX, "0")
    eng = Engine(clock=clock)
    eng.pipeline_depth = depth
    return eng


class TestPacerParity:
    @pytest.mark.parametrize("depth", [0, 2])
    def test_rate_limiter_exact_parity_acquire1(self, depth):
        """Randomized multi-second arrivals against a RateLimiter rule:
        every host verdict AND pacing wait bit-matches the depth-0
        device oracle (the shared cost1_ms formula + identical
        latestPassedTime recurrence), with zero reconciliation drift."""
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True, depth=depth)
        oracle = _mk_engine(clock, spec=False, depth=0)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule(
                "rl", count=10.0,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=400,
            )])
        rng = np.random.default_rng(7)
        offs = np.sort(rng.integers(0, 3000, 60))
        got, want = [], []
        for i, off in enumerate(offs):
            clock.set_ms(1000 + int(off))
            _, v = spec_e.entry_sync("rl")
            assert v.speculative, "shaped ops must be host-served now"
            got.append((v.admitted, v.wait_ms))
            _, ov = oracle.entry_sync("rl")
            want.append((ov.admitted, ov.wait_ms))
            if i % 7 == 6:
                spec_e.flush()
        spec_e.flush()
        spec_e.drain()
        assert got == want
        c = spec_e.speculative.counters
        assert c["spec_declined"] == 0
        assert c["over_admits"] == 0 and c["under_admits"] == 0
        assert c["spec_shaped"] == len(offs)

    def test_rate_limiter_bulk_closed_form_parity(self):
        """A single-ts uniform-acquire bulk group on a shaped resource
        is host-served via the closed-form rank math and matches the
        device oracle exactly (verdicts and waits)."""
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule(
                "blk", count=20.0,
                control_behavior=C.CONTROL_BEHAVIOR_RATE_LIMITER,
                max_queueing_time_ms=300,
            )])
        clock.set_ms(1000)
        now = clock.now_ms()
        g = spec_e.submit_bulk("blk", 16, ts=now)
        assert g.speculative and g.admitted is not None
        og = oracle.submit_bulk("blk", 16, ts=now)
        oracle.flush()
        assert list(g.admitted) == list(og.admitted)
        assert list(g.wait_ms) == list(og.wait_ms)
        spec_e.flush()
        spec_e.drain()
        c = spec_e.speculative.counters
        assert c["over_admits"] == 0 and c["under_admits"] == 0
        # Mixed-ts bulk groups stay device-decided (outside the
        # closed-form preconditions).
        ts_col = np.full(8, now, dtype=np.int64)
        ts_col[4:] += 200
        g2 = spec_e.submit_bulk("blk", 8, ts=ts_col)
        assert not g2.speculative
        spec_e.flush()
        spec_e.drain()

    def test_warm_up_ramp_parity_across_refill_seconds(self):
        """WarmUp ramp on the host mirror: burst arrivals in the first
        half of each second (so the rolling device window aligns with
        the mirror's per-second pass counters) across 4 token re-fill
        seconds — verdicts match the oracle exactly, and the ramp
        actually gates (some blocked, some admitted)."""
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule(
                "wu", count=10.0,
                control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                warm_up_period_sec=10,
            )])
        got, want = [], []
        for sec in range(4):
            for k in range(12):
                clock.set_ms(1000 + sec * 1000 + k * 30)
                _, v = spec_e.entry_sync("wu")
                assert v.speculative
                spec_e.flush()
                spec_e.drain()  # settle per op: pass windows align
                _, ov = oracle.entry_sync("wu")
                got.append(v.admitted)
                want.append(ov.admitted)
        assert got == want
        assert any(want) and not all(want), "the ramp must actually gate"
        c = spec_e.speculative.counters
        assert c["over_admits"] == 0 and c["under_admits"] == 0

    def test_warm_up_batched_settle_drift_bounded(self):
        """Batched settles de-align the pass windows (the device
        charges in-batch candidates conservatively); the mirror's drift
        stays small and the reconcile re-anchors the ramp columns."""
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule(
                "wub", count=10.0,
                control_behavior=C.CONTROL_BEHAVIOR_WARM_UP,
                warm_up_period_sec=10,
            )])
        spec_admits = oracle_admits = 0
        for sec in range(4):
            for k in range(12):
                clock.set_ms(1000 + sec * 1000 + k * 30)
                _, v = spec_e.entry_sync("wub")
                spec_admits += int(v.admitted)
                _, ov = oracle.entry_sync("wub")
                oracle_admits += int(ov.admitted)
            spec_e.flush()  # one settle per second's burst
            spec_e.drain()
        assert abs(spec_admits - oracle_admits) <= 4, (
            spec_admits, oracle_admits,
        )


class TestHostSystemGate:
    def test_system_qps_narrows_not_zeroes(self):
        """The acceptance criterion: a configured system rule narrows
        the tier's verdicts instead of zeroing it — spec_declined stays
        0 and the QPS dimension matches the device oracle."""
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule("svc", count=100.0)])
            eng.set_system_config(SystemConfig(qps=5.0))
        clock.set_ms(1000)
        got, want = [], []
        last = None
        for _ in range(8):
            _, v = spec_e.entry_sync("svc", entry_type=C.EntryType.IN)
            assert v.speculative, "system rule must not zero the tier"
            got.append(v.admitted)
            last = v
            _, ov = oracle.entry_sync("svc", entry_type=C.EntryType.IN)
            want.append(ov.admitted)
        assert got == want == [True] * 5 + [False] * 3
        assert last.reason == E.BLOCK_SYSTEM and last.limit_type == "qps"
        c = spec_e.speculative.counters
        assert c["spec_declined"] == 0
        assert c["spec_system_blocks"] == 3
        # Outbound traffic bypasses the gate, like the device check.
        _, v = spec_e.entry_sync("svc")
        assert v.admitted and v.speculative
        spec_e.flush()
        spec_e.drain()
        assert spec_e.speculative.counters["over_admits"] == 0

    def test_system_thread_gate_with_exits(self):
        """max_thread on the host gate: strict > on the PRE-increment
        global gauge (entries 1-3 pass with max_thread=2, the 4th
        blocks), exits release it synchronously."""
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("t", count=100.0)])
        eng.set_system_config(SystemConfig(max_thread=2))
        clock.set_ms(1000)
        ops = []
        for _ in range(3):
            op, v = eng.entry_sync("t", entry_type=C.EntryType.IN)
            assert v.admitted and v.speculative
            ops.append(op)
        _, v4 = eng.entry_sync("t", entry_type=C.EntryType.IN)
        assert not v4.admitted
        assert v4.reason == E.BLOCK_SYSTEM and v4.limit_type == "thread"
        for op in ops:
            eng.submit_exit(op.rows, rt=1, resource="t", speculative=True)
        _, v5 = eng.entry_sync("t", entry_type=C.EntryType.IN)
        assert v5.admitted, "exits must release the host gauge"
        eng.flush()
        eng.drain()

    def test_system_cpu_gate_reads_the_sampler(self):
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("c", count=100.0)])
        eng.set_system_config(SystemConfig(highest_cpu_usage=0.5))
        sampler.force(load=-1.0, cpu=0.9)
        try:
            clock.set_ms(1000)
            _, v = eng.entry_sync("c", entry_type=C.EntryType.IN)
            assert not v.admitted and v.speculative
            assert v.reason == E.BLOCK_SYSTEM and v.limit_type == "cpu"
            sampler.force(load=-1.0, cpu=0.1)
            _, v2 = eng.entry_sync("c", entry_type=C.EntryType.IN)
            assert v2.admitted
        finally:
            sampler.force(load=-1.0, cpu=-1.0)
        eng.flush()
        eng.drain()

    def test_degraded_admission_honors_system_gate(self):
        """The host gate guards DEGRADED admission too: with the
        device lost, a system QPS rule keeps narrowing the fallback's
        verdicts (PR 5 ignored system rules entirely while degraded)."""
        from sentinel_tpu.testing.faults import FaultInjector

        config.set(config.FAILOVER_ENABLED, "true")
        config.set(config.FAILOVER_RETRY_MS, "100000")
        clock = ManualClock(start_ms=0)
        eng = _mk_engine(clock, spec=True)
        eng.set_flow_rules([st.FlowRule("d", count=100.0)])
        eng.set_system_config(SystemConfig(qps=3.0))
        inj = FaultInjector().install(eng)
        clock.set_ms(1000)
        inj.fail_fetch(eng.flush_seq + 1)
        eng.submit_entry("d")
        eng.flush()
        assert eng.failover.state == "DEGRADED"
        clock.set_ms(2500)  # fresh second: bucket refilled
        verdicts = [
            eng.entry_sync("d", entry_type=C.EntryType.IN)[1]
            for _ in range(5)
        ]
        assert all(v.degraded for v in verdicts)
        admitted = [v.admitted for v in verdicts]
        assert sum(admitted) <= 4, admitted  # ~qps + refill slack
        blocked = [v for v in verdicts if not v.admitted]
        assert blocked and all(
            v.reason == E.BLOCK_SYSTEM for v in blocked
        )

    def test_bulk_system_gate_matches_oracle(self):
        clock = ManualClock(start_ms=0)
        spec_e = _mk_engine(clock, spec=True)
        oracle = _mk_engine(clock, spec=False)
        for eng in (spec_e, oracle):
            eng.set_flow_rules([st.FlowRule("b", count=100.0)])
            eng.set_system_config(SystemConfig(qps=5.0))
        clock.set_ms(1000)
        now = clock.now_ms()
        g = spec_e.submit_bulk("b", 8, ts=now, entry_type=C.EntryType.IN)
        assert g.speculative
        og = oracle.submit_bulk("b", 8, ts=now, entry_type=C.EntryType.IN)
        oracle.flush()
        assert list(g.admitted) == list(og.admitted)
        assert (g.reason[~g.admitted] == E.BLOCK_SYSTEM).all()
        spec_e.flush()
        spec_e.drain()
        assert spec_e.speculative.counters["over_admits"] == 0
