"""Engine throughput hardening: submission must proceed while a flush's
device round-trip is in flight, the pending buffer is bounded by
``max_batch`` (flush-on-size), and one flush processes arbitrarily many
queued ops in ``max_batch`` chunks with sequential semantics preserved
across chunk boundaries.

The reference never needs any of this — every request runs the slot
chain on its own thread — but the batched engine serializes decisions
through a device kernel, so the submission path must not sit behind the
kernel's latency (round-1 weak #7).
"""

import threading

import pytest


@pytest.fixture()
def qps_rule(manual_clock, engine):
    import sentinel_tpu as st

    st.flow_rule_manager.load_rules([st.FlowRule("res", count=1000)])
    return engine


class TestConcurrentSubmission:
    def test_submit_proceeds_during_device_roundtrip(self, qps_rule, monkeypatch):
        """While one thread's flush is blocked inside the kernel call,
        another thread's submit_entry must complete (it only takes the
        submission lock, never the flush lock)."""
        engine = qps_rule
        # Warm up: compile the kernel once so the block below is clean.
        engine.submit_entry("res")
        engine.flush()

        from sentinel_tpu.runtime import engine as eng_mod

        real = eng_mod.flush_step_jit
        in_kernel = threading.Event()
        release = threading.Event()

        def slow_kernel(*args, **kwargs):
            in_kernel.set()
            assert release.wait(30), "test deadlock: release never set"
            return real(*args, **kwargs)

        monkeypatch.setattr(eng_mod, "flush_step_jit", slow_kernel)

        op_a = engine.submit_entry("res")
        flusher = threading.Thread(target=engine.flush)
        flusher.start()
        try:
            assert in_kernel.wait(30), "flush never reached the kernel"
            # The flush is now parked inside the device call holding only
            # the flush lock. Submission must not block on it.
            done = threading.Event()

            def submit():
                engine.submit_entry("res")
                done.set()

            submitter = threading.Thread(target=submit)
            submitter.start()
            assert done.wait(10), (
                "submit_entry blocked behind an in-flight device round-trip"
            )
            assert not release.is_set()  # kernel genuinely still parked
        finally:
            release.set()
            flusher.join(30)
        assert op_a.verdict is not None and op_a.verdict.admitted
        # The op submitted mid-flight decides on the next flush.
        monkeypatch.setattr(eng_mod, "flush_step_jit", real)
        ops = engine.flush()
        assert len(ops) == 1 and ops[0].verdict.admitted

    def test_flush_fills_verdicts_for_ops_drained_by_other_thread(self, qps_rule):
        """A caller whose op was drained by a concurrent flush still
        finds its verdict filled once its own flush() returns."""
        engine = qps_rule
        ops = [engine.submit_entry("res") for _ in range(4)]
        threads = [threading.Thread(target=engine.flush) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(30)
        assert all(op.verdict is not None for op in ops)


class TestMaxBatch:
    def test_flush_on_size_bounds_pending_buffer(self, qps_rule):
        """Reaching max_batch triggers an automatic flush: the first
        max_batch ops have verdicts without any explicit flush()."""
        engine = qps_rule
        engine.max_batch = 8
        ops = engine.submit_many([{"resource": "res"} for _ in range(8)])
        assert all(op.verdict is not None for op in ops)
        assert len(engine._entries) == 0

    def test_chunked_flush_preserves_sequential_semantics(self, qps_rule):
        """One flush over 3 chunks: the admitted prefix must match the
        un-chunked sequential outcome (each chunk sees the previous
        chunks' pass counts in the windows)."""
        import sentinel_tpu as st

        engine = qps_rule
        st.flow_rule_manager.load_rules([st.FlowRule("res", count=10)])
        engine.max_batch = 1 << 20  # accumulate without flush-on-size
        now = engine.clock.now_ms()
        ops = engine.submit_many(
            [{"resource": "res", "ts": now} for _ in range(20)]
        )
        engine.max_batch = 8
        engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert sum(admitted) == 10
        assert admitted == [True] * 10 + [False] * 10
        stats = engine.cluster_node_stats("res")
        assert stats["pass_qps"] == pytest.approx(10.0)
        assert stats["total_block_minute"] == 10

    def test_exits_flush_on_size(self, qps_rule):
        engine = qps_rule
        op = engine.submit_entry("res")
        engine.flush()
        engine.max_batch = 4
        for _ in range(4):
            engine.submit_exit(op.rows, rt=5, resource="res")
        assert len(engine._exits) == 0  # auto-flushed


class TestRuleReloadConcurrency:
    def test_reload_during_flush_keeps_old_rules_for_pending(self, qps_rule, monkeypatch):
        """A rule reload arriving while a flush is in flight waits for
        the flush lock; pending ops decide under the rules they were
        submitted against."""
        import sentinel_tpu as st

        engine = qps_rule
        engine.submit_entry("res")
        engine.flush()

        from sentinel_tpu.runtime import engine as eng_mod

        real = eng_mod.flush_step_jit
        in_kernel = threading.Event()
        release = threading.Event()

        def slow_kernel(*args, **kwargs):
            in_kernel.set()
            assert release.wait(30)
            return real(*args, **kwargs)

        monkeypatch.setattr(eng_mod, "flush_step_jit", slow_kernel)
        op = engine.submit_entry("res")
        flusher = threading.Thread(target=engine.flush)
        flusher.start()
        try:
            assert in_kernel.wait(30)
            reloaded = threading.Event()

            def reload():
                st.flow_rule_manager.load_rules([st.FlowRule("res", count=0)])
                reloaded.set()

            reloader = threading.Thread(target=reload)
            reloader.start()
            # The reload must NOT complete while the flush is parked.
            assert not reloaded.wait(0.3)
        finally:
            release.set()
            flusher.join(30)
        reloader.join(30)
        assert reloaded.is_set()
        assert op.verdict is not None and op.verdict.admitted  # old count=1000
        monkeypatch.setattr(eng_mod, "flush_step_jit", real)
        nop = engine.submit_entry("res")
        engine.flush()
        assert not nop.verdict.admitted  # new count=0


class TestAutoFlush:
    def test_auto_flush_decides_pending_ops(self, manual_clock, engine):
        """Deferred submissions get verdicts without any explicit
        flush() once the background flusher runs."""
        import time

        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("af", count=100)])
        engine.start_auto_flush(interval_ms=5)
        try:
            op = engine.submit_entry("af")
            deadline = time.monotonic() + 5.0
            while op.verdict is None and time.monotonic() < deadline:
                time.sleep(0.01)
            assert op.verdict is not None and op.verdict.admitted
        finally:
            engine.stop_auto_flush()

    def test_auto_flush_idempotent_start_stop(self, manual_clock, engine):
        engine.start_auto_flush(interval_ms=5)
        engine.start_auto_flush(interval_ms=5)  # no second thread
        assert engine._auto_flush_thread is not None
        engine.stop_auto_flush()
        assert engine._auto_flush_thread is None
        engine.stop_auto_flush()  # no-op

    def test_auto_flush_restart_with_new_interval(self, manual_clock, engine):
        engine.start_auto_flush(interval_ms=5)
        t1 = engine._auto_flush_thread
        engine.start_auto_flush()  # no interval: no-op
        assert engine._auto_flush_thread is t1
        engine.start_auto_flush(interval_ms=50)  # explicit: restart
        assert engine._auto_flush_thread is not t1
        # The documented guarantee: an explicit interval is never
        # silently dropped — the running flusher's cadence matches it.
        assert engine._auto_flush_interval_s == pytest.approx(0.050)
        t2 = engine._auto_flush_thread
        engine.start_auto_flush(interval_ms=50)  # same cadence: no restart
        assert engine._auto_flush_thread is t2
        engine.stop_auto_flush()

    def test_auto_flush_concurrent_explicit_intervals(self, manual_clock, engine):
        """Racing explicit-interval starts: whichever flusher survives
        must run at one of the requested cadences, and a follow-up
        explicit call always converges to ITS cadence (the round-3
        advisor race: losing the restart race used to silently keep the
        other caller's interval)."""
        import threading

        ivs = [3, 7, 11, 13]
        threads = [
            threading.Thread(target=engine.start_auto_flush, kwargs={"interval_ms": iv})
            for iv in ivs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert engine._auto_flush_thread is not None
        assert engine._auto_flush_interval_s in [iv / 1000.0 for iv in ivs]
        engine.start_auto_flush(interval_ms=29)
        assert engine._auto_flush_interval_s == pytest.approx(0.029)
        engine.stop_auto_flush()

    def test_auto_flush_with_concurrent_submitters(self, manual_clock, engine):
        """The background flusher racing threaded bulk + singles
        submitters: no exceptions, every op decided, and the admitted
        total equals the submitted total (no lost or double-counted
        rows under the lock handoffs)."""
        import threading

        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("c", count=1e9)])
        engine.start_auto_flush(interval_ms=1)
        errs = []
        groups = []
        ops_all = []
        lock = threading.Lock()

        def worker(i):
            try:
                for _ in range(20):
                    if i % 2 == 0:
                        g = engine.submit_bulk("c", 50)
                        with lock:
                            groups.append(g)
                    else:
                        ops = engine.submit_many(
                            [{"resource": "c"} for _ in range(20)]
                        )
                        with lock:
                            ops_all.extend(ops)
            except Exception as e:  # pragma: no cover - failure path
                errs.append(e)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        engine.flush()
        engine.stop_auto_flush()
        assert not errs
        assert all(op.verdict is not None for op in ops_all)
        assert all(g.admitted is not None for g in groups)
        total = sum(g.n for g in groups) + len(ops_all)
        admitted = sum(g.admitted_count for g in groups) + sum(
            1 for op in ops_all if op.verdict.admitted
        )
        assert admitted == total  # count=1e9: nothing should block
        stats = engine.cluster_node_stats("c")
        assert stats["total_pass_minute"] == total


class TestLifecycle:
    def test_reset_stops_old_auto_flusher(self, engine):
        """api.reset() must terminate the discarded engine's flusher
        thread — an orphaned daemon would poll (and pin) the old engine
        for the process lifetime."""
        import threading
        import time

        from sentinel_tpu.core import api

        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("rs", count=1e9)])
        engine.start_auto_flush(interval_ms=5)
        old_thread = engine._auto_flush_thread
        assert old_thread is not None and old_thread.is_alive()
        engine.stop_auto_flush()  # freeze the queue for the race setup
        engine.start_auto_flush(interval_ms=3600_000)  # won't tick again
        queued = engine.submit_entry("rs")
        api.reset(clock=engine.clock)
        # reset() quiesces the OLD engine via close(): the op queued
        # behind the (stopped) flusher must still be DECIDED, not
        # stranded with verdict None forever.
        assert queued.verdict is not None and queued.verdict.admitted
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and old_thread.is_alive():
            time.sleep(0.02)
        assert not old_thread.is_alive(), "old auto-flusher survived reset"
        assert not any(
            t.name == "sentinel-auto-flush" and t.is_alive()
            for t in threading.enumerate()
        )

    def test_close_quiesces_and_decides(self, engine):
        """close(): flusher stopped, queued ops decided, idempotent,
        engine still usable afterwards."""
        import sentinel_tpu as st

        engine.set_flow_rules([st.FlowRule("lc", count=1e9)])
        engine.start_auto_flush(interval_ms=50)
        ops = [engine.submit_entry("lc") for _ in range(5)]
        engine.close()
        assert engine._auto_flush_thread is None
        assert all(op.verdict is not None and op.verdict.admitted for op in ops)
        engine.close()  # idempotent
        # Still usable.
        op = engine.submit_entry("lc")
        engine.flush()
        assert op.verdict.admitted
