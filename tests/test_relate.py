"""RELATE-strategy parity: a rule on resource A whose check node is
resource B (FlowRuleChecker.selectNodeByRequesterAndStrategy, reference:
slots/block/flow/FlowRuleChecker.java:96-165 — STRATEGY_RELATE reads the
ref resource's ClusterNode while accounting stays on A).

Pins the documented intra-batch conservatism (runtime/flush.py module
docstring): the batched rank math charges earlier same-batch entries'
acquires on the CHECK node, so same-flush RELATE entries under-admit
relative to the sequential reference — never over-admit. Flush-per-entry
sequences match the oracle exactly.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C


def _relate_rule(count):
    return st.FlowRule(
        "A", count=count, strategy=C.STRATEGY_RELATE, ref_resource="B"
    )


class TestRelateSequential:
    def test_checks_ref_resource_stats(self, manual_clock, engine):
        """Oracle semantics, one flush per entry: A admits while B's
        passQps stays under the rule count; A's own passes never charge
        the check node."""
        st.flow_rule_manager.load_rules([_relate_rule(5)])
        manual_clock.set_ms(100)
        for _ in range(3):
            assert st.try_entry("B") is not None  # B unthrottled, counted
        # Sequential A entries: each check sees B's passQps == 3
        # (3 + 1 <= 5), and A's accounting never bumps B — like the
        # reference, ALL sequential A entries are admitted.
        for _ in range(10):
            assert st.try_entry("A") is not None
        stats_b = engine.cluster_node_stats("B")
        assert stats_b["pass_qps"] == pytest.approx(3.0)  # untouched by A
        stats_a = engine.cluster_node_stats("A")
        assert stats_a["total_pass_minute"] == 10

    def test_blocks_when_ref_over_count(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([_relate_rule(2)])
        manual_clock.set_ms(100)
        for _ in range(2):
            st.try_entry("B")
        assert st.try_entry("A") is None  # 2 + 1 > 2
        # B's window expires -> A admits again.
        manual_clock.set_ms(1500)
        assert st.try_entry("A") is not None


class TestRelateBatchedConservatism:
    def test_same_batch_under_admits_never_over(self, manual_clock, engine):
        """One flush with 10 A entries: the kernel charges each A entry's
        acquire to B's row for later entries in the batch, admitting
        exactly count − pass(B) = 2 where the sequential reference admits
        all 10. Pinned: the deviation is one-sided (under, never over)
        and exactly the remaining headroom on the check node."""
        st.flow_rule_manager.load_rules([_relate_rule(5)])
        manual_clock.set_ms(100)
        for _ in range(3):
            st.try_entry("B")
        now = engine.clock.now_ms()
        ops = engine.submit_many([{"resource": "A", "ts": now} for _ in range(10)])
        engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert sum(admitted) == 2  # count(5) - pass_B(3)
        assert admitted == [True, True] + [False] * 8  # prefix, ts order
        # Never over: the admitted set cannot exceed the check node's
        # remaining headroom.
        assert sum(admitted) <= 5 - 3

    def test_direct_rules_in_same_batch_stay_exact(self, manual_clock, engine):
        """The conservatism is scoped to cross-resource topologies: a
        plain DIRECT rule in the same flush keeps exact prefix
        semantics."""
        st.flow_rule_manager.load_rules(
            [_relate_rule(5), st.FlowRule("D", count=4)]
        )
        manual_clock.set_ms(100)
        now = engine.clock.now_ms()
        ops = engine.submit_many([{"resource": "D", "ts": now} for _ in range(10)])
        engine.flush()
        assert sum(op.verdict.admitted for op in ops) == 4


class TestRelateResolutionCache:
    def test_relate_enforced_after_ref_appears(self, manual_clock, engine):
        """Traffic to A BEFORE B's node exists must not pin the rule to
        'omitted' — once B sees traffic, the cross-resource limit
        engages (selectReferenceNode is re-evaluated per entry in the
        reference; the resolution memo must not cache the transient
        miss)."""
        st.flow_rule_manager.load_rules([_relate_rule(0)])  # count=0: blocks
        manual_clock.set_ms(100)
        # B's node doesn't exist yet → the rule passes trivially.
        assert st.try_entry("A") is not None
        # B appears.
        assert st.try_entry("B") is not None
        # Now the RELATE rule binds (count=0 → block), even for the
        # same (resource, context, origin) key as the first entry.
        assert st.try_entry("A") is None
