"""RELATE-strategy parity: a rule on resource A whose check node is
resource B (FlowRuleChecker.selectNodeByRequesterAndStrategy, reference:
slots/block/flow/FlowRuleChecker.java:96-165 — STRATEGY_RELATE reads the
ref resource's ClusterNode while accounting stays on A).

Round-4 semantics (runtime/flush.py "Intra-batch sequencing"): the
rank-math charge is own-row-gated, so same-flush RELATE streams match
the sequential reference exactly when the ref resource is ruled; with
an unruled ref resource the checks read its pre-flush windows (the
legal guarded-entries-race-ahead interleaving — documented deviation).
Flush-per-entry sequences match the oracle exactly either way.
"""

import pytest

import sentinel_tpu as st
from sentinel_tpu.models import constants as C


def _relate_rule(count):
    return st.FlowRule(
        "A", count=count, strategy=C.STRATEGY_RELATE, ref_resource="B"
    )


class TestRelateSequential:
    def test_checks_ref_resource_stats(self, manual_clock, engine):
        """Oracle semantics, one flush per entry: A admits while B's
        passQps stays under the rule count; A's own passes never charge
        the check node."""
        st.flow_rule_manager.load_rules([_relate_rule(5)])
        manual_clock.set_ms(100)
        for _ in range(3):
            assert st.try_entry("B") is not None  # B unthrottled, counted
        # Sequential A entries: each check sees B's passQps == 3
        # (3 + 1 <= 5), and A's accounting never bumps B — like the
        # reference, ALL sequential A entries are admitted.
        for _ in range(10):
            assert st.try_entry("A") is not None
        stats_b = engine.cluster_node_stats("B")
        assert stats_b["pass_qps"] == pytest.approx(3.0)  # untouched by A
        stats_a = engine.cluster_node_stats("A")
        assert stats_a["total_pass_minute"] == 10

    def test_blocks_when_ref_over_count(self, manual_clock, engine):
        st.flow_rule_manager.load_rules([_relate_rule(2)])
        manual_clock.set_ms(100)
        for _ in range(2):
            st.try_entry("B")
        assert st.try_entry("A") is None  # 2 + 1 > 2
        # B's window expires -> A admits again.
        manual_clock.set_ms(1500)
        assert st.try_entry("A") is not None


class TestRelateBatchedConservatism:
    def test_same_batch_matches_sequential_exactly(self, manual_clock, engine):
        """One flush with 10 A entries: the reference never bumps B's
        count from A's entries (accounting stays on A), so ALL of them
        see pass_B(3) + 1 <= 5 and admit — and since round 4 the kernel
        matches: a slot charges its row's intra-batch stream only when
        the row is one the entry accounts on (flush.py own-row gate).
        Rounds 1-3 over-charged here, admitting only count − pass(B)."""
        st.flow_rule_manager.load_rules([_relate_rule(5)])
        manual_clock.set_ms(100)
        for _ in range(3):
            st.try_entry("B")
        now = engine.clock.now_ms()
        ops = engine.submit_many([{"resource": "A", "ts": now} for _ in range(10)])
        engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert admitted == [True] * 10  # sequential reference outcome

    def test_same_batch_ruled_b_traffic_still_charges(self, manual_clock, engine):
        """When B carries its own rule, direct B entries in the flush
        DO charge B's stream (own-row slots), so later-ordered A checks
        see them exactly as the sequential reference would — the
        own-row gate removes only the reverse direction (A charging B).
        """
        st.flow_rule_manager.load_rules(
            [_relate_rule(5), st.FlowRule("B", count=100)]
        )
        manual_clock.set_ms(100)
        for _ in range(3):
            st.try_entry("B")
        now = engine.clock.now_ms()
        # 2 more B entries then 10 A entries, one flush. Sequential:
        # B's land first (ts ties break by arrival), pass_B -> 5, every
        # A check sees 5 + 1 > 5 and blocks.
        reqs = [{"resource": "B", "ts": now}] * 2 + [{"resource": "A", "ts": now}] * 10
        ops = engine.submit_many([dict(r) for r in reqs])
        engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        assert admitted[:2] == [True, True]
        assert sum(admitted[2:]) == 0

    def test_same_batch_unruled_b_traffic_lands_next_flush(
        self, manual_clock, engine
    ):
        """When B has NO rule of its own, its entries carry no slots and
        cannot charge a stream: same-flush A checks read B's pre-flush
        windows — the legal interleaving where the guarded entries race
        ahead of the ref traffic (documented deviation; sub-flush
        interleaving is racy in the reference too). By the NEXT flush
        the B passes are in the windows and bind."""
        st.flow_rule_manager.load_rules([_relate_rule(5)])
        manual_clock.set_ms(100)
        for _ in range(3):
            st.try_entry("B")
        now = engine.clock.now_ms()
        reqs = [{"resource": "B", "ts": now}] * 2 + [{"resource": "A", "ts": now}] * 10
        ops = engine.submit_many([dict(r) for r in reqs])
        engine.flush()
        admitted = [op.verdict.admitted for op in ops]
        # A-first interleaving: checks see pass_B == 3 (pre-flush).
        assert admitted == [True] * 12
        # Next flush: pass_B == 5 is visible, A blocks.
        ops2 = engine.submit_many([{"resource": "A", "ts": now}] * 3)
        engine.flush()
        assert [o.verdict.admitted for o in ops2] == [False] * 3

    def test_direct_rules_in_same_batch_stay_exact(self, manual_clock, engine):
        """The conservatism is scoped to cross-resource topologies: a
        plain DIRECT rule in the same flush keeps exact prefix
        semantics."""
        st.flow_rule_manager.load_rules(
            [_relate_rule(5), st.FlowRule("D", count=4)]
        )
        manual_clock.set_ms(100)
        now = engine.clock.now_ms()
        ops = engine.submit_many([{"resource": "D", "ts": now} for _ in range(10)])
        engine.flush()
        assert sum(op.verdict.admitted for op in ops) == 4


class TestRelateResolutionCache:
    def test_relate_enforced_after_ref_appears(self, manual_clock, engine):
        """Traffic to A BEFORE B's node exists must not pin the rule to
        'omitted' — once B sees traffic, the cross-resource limit
        engages (selectReferenceNode is re-evaluated per entry in the
        reference; the resolution memo must not cache the transient
        miss)."""
        st.flow_rule_manager.load_rules([_relate_rule(0)])  # count=0: blocks
        manual_clock.set_ms(100)
        # B's node doesn't exist yet → the rule passes trivially.
        assert st.try_entry("A") is not None
        # B appears.
        assert st.try_entry("B") is not None
        # Now the RELATE rule binds (count=0 → block), even for the
        # same (resource, context, origin) key as the first entry.
        assert st.try_entry("A") is None
