"""Existing engine error paths the failure domain builds on — with
failover DISARMED, device errors must keep their original semantics:

* ``_dispatch_deferred``'s drain-after-failed-dispatch branch: a later
  chunk's dispatch failure still bounds the in-flight queue, and the
  swallowed drain error never masks the dispatch failure being raised;
* ``_drain_pending``'s per-record fallback: a failed coalesced fetch
  attributes the failure to exactly the faulted record while later
  records still materialize;
* dirty shutdown: a worker thread that outlives its close-join is
  reported (``closed_dirty``) instead of silently leaked.
"""

import threading
import time

import pytest

import sentinel_tpu as st
from sentinel_tpu.testing.faults import FaultInjector, InjectedFault


def _mk_engine(manual_clock, depth=0, max_batch=None):
    from sentinel_tpu.runtime.engine import Engine

    eng = Engine(clock=manual_clock)
    eng.pipeline_depth = depth
    if max_batch is not None:
        eng.max_batch = max_batch
    eng.set_flow_rules([st.FlowRule("r", count=1e9)])
    return eng


class TestDispatchFailureDrain:
    def test_drain_error_never_masks_the_dispatch_failure(self, manual_clock):
        """Oversized pipelined flush: chunk 1 dispatches, chunk 2's
        dispatch fails — the except-path drain (which itself hits a
        fetch error) is swallowed and the ORIGINAL dispatch error is
        what the caller sees; chunk 1's ops stay readable and report
        their own fetch error."""
        eng = _mk_engine(manual_clock, depth=1, max_batch=4)
        inj = FaultInjector().install(eng)
        manual_clock.set_ms(1000)

        # 8 singles split into 2 chunks of 4; chunk 1 dispatches fine
        # (in-flight), chunk 2's dispatch raises. Its except-path drain
        # then fails too (chunk 1's fetch is faulted) — and is
        # swallowed.
        dispatch_err = InjectedFault("chunk-2 dispatch")
        fetch_err = InjectedFault("chunk-1 fetch")
        inj.fail_fetch(eng.flush_seq + 1, fetch_err)
        inj.fail_dispatch(eng.flush_seq + 2, dispatch_err)
        ops = [eng.submit_entry("r") for _ in range(7)]
        with pytest.raises(InjectedFault) as ei:
            eng.flush()
        assert ei.value is dispatch_err, "drain error must not mask dispatch"
        # Chunk 1's record is still in flight (queue bounded, not
        # poisoned); reading a verdict surfaces ITS OWN fetch error.
        with pytest.raises(InjectedFault) as ei2:
            _ = ops[0].verdict
        assert ei2.value is fetch_err
        # The queue is bounded afterwards.
        assert len(eng._pending_fetches) <= 1

    def test_queue_stays_bounded_after_failed_dispatch(self, manual_clock):
        eng = _mk_engine(manual_clock, depth=1, max_batch=4)
        inj = FaultInjector().install(eng)
        manual_clock.set_ms(1000)
        [eng.submit_entry("r") for _ in range(4)]
        eng.flush()  # one in-flight record
        inj.fail_dispatch(eng.flush_seq + 2)
        [eng.submit_entry("r") for _ in range(7)]
        with pytest.raises(InjectedFault):
            eng.flush()
        assert len(eng._pending_fetches) <= 1
        eng.drain()  # chunk 1 of the failed flush settles cleanly


class TestDrainPerRecordFallback:
    def test_failure_attributes_to_exactly_the_faulted_record(
        self, manual_clock
    ):
        """Two async records; the coalesced fetch fails because record
        A's fetch is faulted. The per-record fallback re-fetches each:
        A raises its own error, B's verdicts still materialize, and the
        drain re-raises A's error after finishing."""
        eng = _mk_engine(manual_clock)
        eng.max_inflight = 4
        inj = FaultInjector().install(eng)
        manual_clock.set_ms(1000)

        fetch_err = InjectedFault("record-A fetch")
        inj.fail_fetch(eng.flush_seq + 1, fetch_err)
        ops_a = [eng.submit_entry("r") for _ in range(3)]
        eng.flush_async()
        ops_b = [eng.submit_entry("r") for _ in range(3)]
        eng.flush_async()
        assert len(eng._pending_fetches) == 2

        tele0 = eng.telemetry.counters_snapshot()["coalesced_fallbacks"]
        with pytest.raises(InjectedFault) as ei:
            eng.drain()
        assert ei.value is fetch_err
        # B materialized despite A's failure (one wedged fetch must not
        # strand the queue) …
        assert all(op.verdict is not None and op.verdict.admitted
                   for op in ops_b)
        # … the batch fetch fell back per-record …
        assert (
            eng.telemetry.counters_snapshot()["coalesced_fallbacks"]
            == tele0 + 1
        )
        # … and A's readers see A's error, repeatably.
        for op in ops_a:
            with pytest.raises(InjectedFault):
                _ = op.verdict


class TestDirtyShutdown:
    def test_stop_auto_flush_flags_a_stuck_flusher(self, manual_clock):
        eng = _mk_engine(manual_clock)
        inj = FaultInjector().install(eng)
        manual_clock.set_ms(1000)
        release = threading.Event()
        # Wedge the auto-flusher inside its flush's fetch.
        inj.hang_fetch(eng.flush_seq + 1, seconds=30.0, until=release)
        eng.submit_entry("r")
        eng.start_auto_flush(interval_ms=1)
        deadline = time.monotonic() + 5.0
        # Wait until the flusher is actually inside the hang.
        while not any(k == "hang" for k, _ in inj.fired):
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert not eng.closed_dirty
        eng.stop_auto_flush(join_timeout_s=0.2)
        assert eng.closed_dirty
        release.set()  # unwedge; the daemon thread exits on its own

    def test_join_clean_reports_a_stuck_thread(self):
        from sentinel_tpu.datasource.base import join_clean

        release = threading.Event()
        t = threading.Thread(target=release.wait, daemon=True)
        t.start()
        try:
            assert join_clean(None, 0.1, "x") is True
            assert join_clean(t, 0.05, "x") is False
        finally:
            release.set()
            t.join(timeout=1)
        assert join_clean(t, 0.1, "x") is True

    def test_longpoll_close_flags_stuck_watcher(self):
        """A long-poll source whose watcher ignores the stop signal for
        longer than the close join marks itself closed_dirty instead of
        pretending the shutdown was clean."""
        from sentinel_tpu.datasource.base import join_clean
        from sentinel_tpu.datasource.longpoll import LongPollPushDataSource

        release = threading.Event()

        class StuckSource(LongPollPushDataSource):
            _thread_name = "stuck-test-watcher"

            def __init__(self):
                super().__init__(lambda raw: [], 1024)

            def read_source(self):
                return None

            def _poll_once(self):
                release.wait(30.0)
                raise RuntimeError("done")

            def _on_poll_error(self, e):
                pass

            def close(self):  # shorter join than the stock 5 s
                self._stop.set()
                self.closed_dirty = self.closed_dirty or not join_clean(
                    self._thread, 0.1, type(self).__name__
                )

        src = StuckSource()
        src._thread = threading.Thread(
            target=src._watch_loop, daemon=True
        )
        src._thread.start()
        time.sleep(0.05)
        src.close()
        try:
            assert src.closed_dirty
        finally:
            release.set()
            src._thread.join(timeout=1)
