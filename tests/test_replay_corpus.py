"""Golden-capture replay differential (tests/data/capture_corpus/).

The committed corpus (see tests/data/gen_capture_corpus.py) is a
capture of mixed single/bulk traffic across four rule kinds with a
mid-stream reload, a rollover, a breaker freeze and a manual freeze.
This tier-1 pin replays those exact bytes through a fresh engine at
pipeline depths {0, 2} and requires ZERO verdict diffs — the
format-stability contract: any change to the frame codec, the capture
record layout, the rule-timeline semantics or the engine's admission
math that silently changes a captured verdict fails here, not in a
production postmortem.
"""

import os
import sys

import numpy as np
import pytest

from sentinel_tpu.runtime import capture as cap_mod

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

CORPUS = os.path.join(os.path.dirname(__file__), "data", "capture_corpus")


def _decoded():
    paths = cap_mod.capture_paths(CORPUS, frozen=True)
    assert paths, "corpus missing — run tests/data/gen_capture_corpus.py"
    return cap_mod.decode_capture(paths)


class TestGoldenCorpus:
    def test_corpus_shape(self):
        d = _decoded()
        chunks = [ck for k, ck in d["stream"] if k == "chunk"]
        assert sum(ck.rows for ck in chunks) >= 300
        # The adversarial ingredients are all present: a mid-stream
        # reload, a breaker health event, freezes, and blocked rows.
        kinds = {k for k, _ in d["stream"]}
        assert {"chunk", "rules", "health", "freeze"} <= kinds
        blocked = admitted = 0
        for ck in chunks:
            if ck.verdicts is None:
                continue
            adm = ck.verdicts[0]
            admitted += int(np.sum(adm == 1))
            blocked += int(np.sum(adm == 0))
        assert admitted > 50 and blocked > 20

    @pytest.mark.parametrize("depth", [0, 2])
    def test_replay_bit_exact(self, depth, manual_clock):
        import replay as replay_tool

        report = replay_tool.verify(_decoded(), depth=depth)
        assert report["diffs"] == 0, report["samples"]
        assert report["compared"] == report["rows"] > 300
        assert report["no_captured_verdict"] == 0
        assert report["not_replayed"] == 0

    def test_explain_names_deciding_rule(self, manual_clock):
        """Acceptance bit: --explain on a blocked admission names the
        deciding rule and its threshold vs the observed stat."""
        import replay as replay_tool
        from sentinel_tpu.core import errors as E

        d = _decoded()
        target = None
        for _k, ck in d["stream"]:
            if _k != "chunk" or ck.verdicts is None:
                continue
            adm, rea, _w, _f = ck.verdicts
            for i in range(ck.rows):
                if adm[i] == 0 and rea[i] == E.BLOCK_FLOW:
                    target = ck.cap_seq + i
                    break
            if target is not None:
                break
        assert target is not None
        out = replay_tool.explain(d, target)
        assert out["captured"]["reason_name"] == "FlowException"
        assert out["replayed"]["reason_name"] == "FlowException"
        rule = out["replayed"]["deciding_rule"]
        assert rule is not None
        assert rule["resource"] == out["row"]["resource"]
        assert out["replayed"]["threshold"] == rule["count"] > 0
        # The reconstructed observed stat sits at/over the threshold —
        # that's WHY the row blocked.
        assert out["observed_window_qps"] >= out["replayed"]["threshold"]
