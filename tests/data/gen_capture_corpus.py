"""Regenerate the golden capture corpus (tests/data/capture_corpus/).

Run from the repo root::

    JAX_PLATFORMS=cpu python tests/data/gen_capture_corpus.py

The corpus is a small but adversarial capture: mixed single/bulk
traffic with args, admitted and blocked rows across four rule kinds
(flow QPS, flow THREAD, degrade, param), exits releasing gauges, a
mid-stream rule reload, a segment rollover and a manual freeze — all
on a ManualClock so the bytes are deterministic up to the boot id and
wall-ms stamps (which replay never diffs on). The tier-1 pin
(tests/test_replay_corpus.py) replays the COMMITTED files at pipeline
depths {0, 2} and requires zero verdict diffs; regenerate only when
the capture format itself changes, and re-run that test after.
"""

import os
import shutil
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

CORPUS_DIR = os.path.join(os.path.dirname(__file__), "capture_corpus")


def main() -> None:
    from sentinel_tpu.models import constants as C
    from sentinel_tpu.models.rules import DegradeRule, FlowRule, ParamFlowRule
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.utils.clock import ManualClock, set_default_clock
    from sentinel_tpu.utils.config import config

    shutil.rmtree(CORPUS_DIR, ignore_errors=True)
    config.set(config.CAPTURE_ENABLED, "true")
    config.set(config.CAPTURE_DIR, CORPUS_DIR)
    clk = ManualClock(start_ms=0)
    set_default_clock(clk)
    eng = Engine(clock=clk)
    eng.capture.segment_bytes = 64 * 1024  # force a mid-corpus rollover

    eng.set_flow_rules([
        FlowRule("api/pay", count=3),
        FlowRule("api/search", count=2, grade=C.FLOW_GRADE_THREAD),
        FlowRule("api/open", count=1e9),
    ])
    eng.set_degrade_rules([
        DegradeRule("api/slow", grade=C.DEGRADE_GRADE_RT, count=5,
                    time_window=2, min_request_amount=3,
                    slow_ratio_threshold=0.5),
    ])
    eng.set_param_rules({
        "api/param": [ParamFlowRule(resource="api/param", param_idx=0,
                                    count=2.0)],
    })

    held = []
    for w in range(14):
        if w == 7:
            # Mid-stream reload: the QPS budget tightens — replay must
            # apply this from the timeline, not the segment header.
            eng.set_flow_rules([
                FlowRule("api/pay", count=1),
                FlowRule("api/search", count=2, grade=C.FLOW_GRADE_THREAD),
                FlowRule("api/open", count=1e9),
            ])
        ops = []
        for i in range(5):
            ops.append(eng.submit_entry(
                "api/pay", origin=f"caller-{i % 2}", args=("pay", i),
            ))
        for i in range(4):
            ops.append(eng.submit_entry("api/search", acquire=1))
        for i in range(6):
            ops.append(eng.submit_entry(
                "api/param", args=(f"user-{i % 3}",),
            ))
        # Slow calls feed the degrade (RT breaker) window.
        slow = [eng.submit_entry("api/slow") for _ in range(4)]
        g = eng.submit_bulk("api/open", 8, context_name="batch",
                            origin="bulk-src")
        eng.flush()
        eng.drain()
        for op in slow:
            if op.verdict.admitted:
                eng.submit_exit(op.rows, rt=40 if w % 2 else 1,
                                resource="api/slow")
        for op in ops:
            v = op.verdict
            if v.admitted and op.resource == "api/search":
                held.append(op)
        # Release half the held THREAD admissions (the other half keeps
        # the gauge charged so later windows block on THREAD).
        while len(held) > 2:
            op = held.pop(0)
            eng.submit_exit(op.rows, rt=3, resource="api/search")
        clk.advance(300)
    eng.capture.freeze("corpus")
    # A couple of post-freeze windows so live segments exist too.
    for w in range(2):
        for i in range(3):
            eng.submit_entry("api/pay", args=("tail", i))
        eng.flush()
        eng.drain()
        clk.advance(300)
    eng.close()
    set_default_clock(None)
    config.set(config.CAPTURE_ENABLED, "false")
    config.set(config.CAPTURE_DIR, "")
    names = sorted(os.listdir(CORPUS_DIR))
    print(f"wrote {len(names)} segments to {CORPUS_DIR}:")
    for fn in names:
        print(" ", fn, os.path.getsize(os.path.join(CORPUS_DIR, fn)), "bytes")


if __name__ == "__main__":
    main()
