"""Occupy/priority parity: the batched occupy branch vs the sequential
reference semantics (StatisticNode.tryOccupyNext, node/StatisticNode.
java:302-346; DefaultController prioritized branch, controller/
DefaultController.java:49-75; OccupiableBucketLeapArray maturation,
slots/statistic/metric/occupy/OccupiableBucketLeapArray.java:29-75).

Three layers:

* white-box kernel grid — arbitrary window contents (incl. states only
  reachable through maturation) drive both ``flow_admission`` and the
  oracle's ``try_occupy_next``; pins the *cumulative* window-pass
  subtraction (``currentPass -= windowPass`` per loop step) that a
  per-step recompute would get wrong;
* engine sequence replay — the public API against the oracle engine,
  including borrow caps, waiting()/occupiedPassQps visibility, minute
  accounting and cross-flush maturation;
* mesh — borrow budget conserved across the 8-device mesh.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sentinel_tpu.metrics.events import MetricEvent
from sentinel_tpu.models import constants as C
from sentinel_tpu.testing.oracle import OracleBucket, OracleDefaultController, OracleNode


def _seed_node(wp_prev, wp_cur, borrow, ws_cur=10000):
    """An OracleNode whose 1 s window holds ``wp_prev`` in the expiring
    bucket, ``wp_cur`` in the current one, and ``borrow`` waiting tokens
    in the next future window."""
    node = OracleNode()
    cur_idx = (ws_cur // 500) % 2
    b_cur = OracleBucket(ws_cur, 4900)
    b_cur.counts[MetricEvent.PASS] = wp_cur
    node.second.buckets[cur_idx] = b_cur
    b_prev = OracleBucket(ws_cur - 500, 4900)
    b_prev.counts[MetricEvent.PASS] = wp_prev
    node.second.buckets[1 - cur_idx] = b_prev
    if borrow:
        bb = OracleBucket(ws_cur + 500, 4900)
        bb.counts[MetricEvent.PASS] = borrow
        node.second.borrow.buckets[1 - cur_idx] = bb
    return node


def _kernel_occupy(cases, count, acquire, now, occupy_timeout_ms):
    """Run flow_admission once with one (row, entry) per case; every
    entry prioritized against a single QPS rule of ``count``."""
    from sentinel_tpu.metrics.nodes import SECOND_CFG, make_stats
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.rules.flow_table import FlowIndex
    from sentinel_tpu.runtime.flush import FlushBatch, flow_admission

    n = len(cases)
    rows = int(2 ** np.ceil(np.log2(max(n, 2))))
    stats = make_stats(rows)
    ws_cur = now - now % 500
    cur_idx = (ws_cur // 500) % 2
    sec_ws = np.full((rows, 2), SECOND_CFG.empty_ws, dtype=np.int32)
    sec_counts = np.zeros((rows, 2, len(MetricEvent)), dtype=np.int32)
    fut_ws = np.full((rows, 2), SECOND_CFG.empty_ws, dtype=np.int32)
    fut_pass = np.zeros((rows, 2), dtype=np.int32)
    for r, (wp_prev, wp_cur, borrow) in enumerate(cases):
        sec_ws[r, cur_idx] = ws_cur
        sec_counts[r, cur_idx, MetricEvent.PASS] = wp_cur
        sec_ws[r, 1 - cur_idx] = ws_cur - 500
        sec_counts[r, 1 - cur_idx, MetricEvent.PASS] = wp_prev
        if borrow:
            fut_ws[r, 1 - cur_idx] = ws_cur + 500
            fut_pass[r, 1 - cur_idx] = borrow
    stats = stats._replace(
        second=stats.second._replace(
            window_start=jnp.asarray(sec_ws), counts=jnp.asarray(sec_counts)
        ),
        future_ws=jnp.asarray(fut_ws),
        future_pass=jnp.asarray(fut_pass),
    )
    index = FlowIndex([FlowRule(resource="r", count=float(count))])
    npad = rows
    e_valid = np.zeros(npad, dtype=bool)
    e_valid[:n] = True
    e_rows = np.full((npad, 4), -1, dtype=np.int32)
    e_gid = np.full((npad, 1), -1, dtype=np.int32)
    e_crow = np.full((npad, 1), -1, dtype=np.int32)
    for i in range(n):
        e_rows[i, 0] = i
        e_gid[i, 0] = 0
        e_crow[i, 0] = i
    m = 8
    batch = FlushBatch(
        now=jnp.int32(now),
        e_valid=jnp.asarray(e_valid),
        e_ts=jnp.full(npad, now, dtype=jnp.int32),
        e_acquire=jnp.full(npad, acquire, dtype=jnp.int32),
        e_rows=jnp.asarray(e_rows),
        e_rule_gid=jnp.asarray(e_gid),
        e_check_row=jnp.asarray(e_crow),
        e_prio=jnp.asarray(e_valid),
        e_auth_ok=jnp.ones(npad, dtype=bool),
        e_cluster_ok=jnp.ones(npad, dtype=bool),
        e_dgid=jnp.full((npad, 1), -1, dtype=jnp.int32),
        x_valid=jnp.zeros(m, dtype=bool),
        x_ts=jnp.zeros(m, dtype=jnp.int32),
        x_count=jnp.zeros(m, dtype=jnp.int32),
        x_rows=jnp.full((m, 4), -1, dtype=jnp.int32),
        x_rt=jnp.zeros(m, dtype=jnp.int32),
        x_err=jnp.zeros(m, dtype=jnp.int32),
        x_thr=jnp.zeros(m, dtype=jnp.int32),
        x_dgid=jnp.full((m, 1), -1, dtype=jnp.int32),
    )
    from sentinel_tpu.runtime.flush import commit_borrow_slab

    slot_ok, flow_pass, _, occupied, occupy_wait, occ_slot, occ_target = (
        flow_admission(stats, index.device, batch, occupy_timeout_ms=occupy_timeout_ms)
    )
    stats2 = commit_borrow_slab(
        stats,
        occ_slot & (flow_pass & occupied)[:, None],
        occ_target,
        batch.e_acquire,
        batch.e_check_row,
    )
    return (
        np.asarray(flow_pass)[:n],
        np.asarray(occupied)[:n],
        np.asarray(occupy_wait)[:n],
        stats2,
    )


class TestTryOccupyNextKernelParity:
    """Grid over window contents × thresholds: the kernel's unrolled
    occupy search must make the reference's decision (grant/deny + exact
    waitInMs), including states where only the cumulative
    ``currentPass -= windowPass`` admits (both live windows over
    threshold — reachable through borrow maturation)."""

    @pytest.mark.parametrize("count,acquire,now_mod,timeout", [
        (2, 1, 100, 500),
        (2, 1, 100, 1000),
        (2, 1, 0, 1000),
        (4, 1, 250, 1000),
        (4, 2, 100, 1000),
        (2, 2, 499, 800),
    ])
    def test_grid(self, count, acquire, now_mod, timeout):
        now = 10000 + now_mod
        cases = [
            (wp_prev, wp_cur, borrow)
            for wp_prev in range(6)
            for wp_cur in range(6)
            for borrow in (0, 1, 2, 5)
        ]
        flow_pass, occupied, occupy_wait, _ = _kernel_occupy(
            cases, count, acquire, now, timeout
        )
        for i, (wp_prev, wp_cur, borrow) in enumerate(cases):
            node = _seed_node(wp_prev, wp_cur, borrow)
            ctl = OracleDefaultController(float(count), 1, occupy_timeout_ms=timeout)
            ok, wait, occ = ctl.can_pass_prio(node, now, acquire)
            label = f"case wp_prev={wp_prev} wp_cur={wp_cur} borrow={borrow}"
            assert bool(flow_pass[i]) == ok, label
            assert bool(occupied[i]) == occ, label
            if occ:
                assert int(occupy_wait[i]) == wait, label

    def test_cumulative_subtraction_case(self):
        """Both live windows at the threshold: step 0 fails, step 1
        admits ONLY because step 0's expiring pass was subtracted
        (StatisticNode.java:328-330). A non-cumulative check denies."""
        # wp_prev=2, wp_cur=2, count=2: pass=4. i=0: 4+1-2=3>2 deny;
        # i=1 cumulative: (4-2)+1-2=1<=2 grant (waitInMs = 900).
        flow_pass, occupied, occupy_wait, _ = _kernel_occupy(
            [(2, 2, 0)], count=2, acquire=1, now=10100, occupy_timeout_ms=1000
        )
        assert bool(occupied[0]) and bool(flow_pass[0])
        assert int(occupy_wait[0]) == 900
        node = _seed_node(2, 2, 0)
        assert node.try_occupy_next(10100, 1, 2.0, 1000) == 900

    def test_borrow_cap_denies(self):
        """currentBorrow >= maxCount → timeout (java:305-307)."""
        flow_pass, occupied, _, _ = _kernel_occupy(
            [(0, 3, 2)], count=2, acquire=1, now=10100, occupy_timeout_ms=1000
        )
        assert not bool(occupied[0]) and not bool(flow_pass[0])

    def test_slab_commit_lands_on_target_window(self):
        """A granted borrow writes acquire into the slab bucket of the
        first satisfiable future window (addWaitingRequest target =
        currentTime + waitInMs, aligned)."""
        _, occupied, occupy_wait, stats2 = _kernel_occupy(
            [(0, 3, 0)], count=2, acquire=1, now=10100, occupy_timeout_ms=1000
        )
        assert bool(occupied[0])
        # i=0: 3+1-0=4>2; i=1: (3-0)+1-3=1<=2 → wait 900, target 11000.
        assert int(occupy_wait[0]) == 900
        fut_ws = np.asarray(stats2.future_ws)[0]
        fut_pass = np.asarray(stats2.future_pass)[0]
        b = int(np.argmax(fut_ws))
        assert int(fut_ws[b]) == 11000
        assert int(fut_pass[b]) == 1


class TestOccupyEngineSequence:
    """Sequence replay through the public API vs the oracle engine —
    grants, caps, waiting/occupiedPass visibility, maturation, and
    borrow state honored across flush boundaries (every entry here is
    its own flush)."""

    @pytest.fixture(autouse=True)
    def _occupy_timeout(self):
        from sentinel_tpu.utils.config import config

        config.set(config.OCCUPY_TIMEOUT_MS, "1000")
        yield
        config.set(config.OCCUPY_TIMEOUT_MS, "500")

    def _load_qps_rule(self, count):
        import sentinel_tpu as st

        st.flow_rule_manager.load_rules([st.FlowRule("res", count=count)])

    def test_sequence_parity(self, manual_clock, engine):
        from sentinel_tpu.core import api
        from sentinel_tpu.core.errors import FlowBlockError as FlowError
        from sentinel_tpu.testing.oracle import OracleFlowEngine

        self._load_qps_rule(2.0)
        oracle = OracleFlowEngine()
        oracle.rules.setdefault("res", []).append(
            OracleDefaultController(2.0, 1, occupy_timeout_ms=1000)
        )

        # (ts, prio, acquire, expect_ok, expect_wait) — plain passes,
        # borrow grants (incl. ones only the *cumulative* window search
        # admits), borrow-cap denies, and two maturation cycles. The
        # acquire=5 steps at the start of a matured window are "touch"
        # traffic: the reference materialises borrowed tokens into the
        # bucket only when a write rolls it (OccupiableBucketLeapArray.
        # resetWindowTo), while the kernel folds them at read time —
        # deterministic and conservative (see
        # test_maturation_is_conservative_without_traffic); with any
        # write in the matured window the two agree exactly.
        seq = [
            (1510, False, 1, True, 0), (1520, False, 1, True, 0),
            (2100, True, 1, True, 400), (2110, True, 1, True, 390),
            (2120, True, 1, False, 0),               # borrow cap
            (2505, False, 5, False, 0),              # touch (blocks both)
            (2550, False, 1, False, 0),
            (2620, True, 1, True, 880),              # cumulative search
            (2630, True, 1, True, 870),
            (2640, True, 1, False, 0),               # cap again
            (3505, False, 5, False, 0),              # touch cycle 2
            (3600, False, 1, False, 0),
            (3610, True, 1, True, 890),
        ]
        for ts, prio, acq, expect_ok, expect_wait in seq:
            manual_clock.set_ms(ts)
            want_ok, want_wait = oracle.entry_prio("res", ts, acq, prio=prio)
            assert (want_ok, want_wait) == (expect_ok, expect_wait), (
                f"oracle vs hand-computed at t={ts}"
            )
            try:
                api.entry("res", count=acq, prio=prio)
                got_ok, got_wait = True, 0
                if prio:
                    # Occupied passes sleep waitInMs before returning
                    # (DefaultController sleeps, java:66); the manual
                    # clock records the sleep as an advance.
                    got_wait = manual_clock.now_ms() - ts
                # Leave the entry un-exited: the reference sequence
                # holds threads; exits would add success/RT noise.
            except FlowError:
                got_ok, got_wait = False, 0
            assert got_ok == want_ok, f"t={ts} prio={prio}"
            if want_ok:
                assert got_wait == want_wait, f"t={ts} prio={prio}"

    def test_maturation_is_conservative_without_traffic(self, manual_clock, engine):
        """Documented deviation: if NO write touches a matured borrowed
        window, the reference's passQps misses the borrowed tokens until
        a write rolls the bucket (materialise-on-reset) and would admit
        an extra entry; the kernel folds them at read time and blocks.
        The batched verdict never admits more than the reference."""
        from sentinel_tpu.core import api
        from sentinel_tpu.core.errors import FlowBlockError as FlowError

        self._load_qps_rule(2.0)
        for ts in (1510, 1520):
            manual_clock.set_ms(ts)
            api.entry("res")
        for ts in (2100, 2110):
            manual_clock.set_ms(ts)
            api.entry("res", prio=True)  # 2 tokens borrowed for [2500, 3000)
        manual_clock.set_ms(2610)  # borrowed window current, untouched
        with pytest.raises(FlowError):
            api.entry("res")  # reference would pass here (cur reads 0)

    def test_waiting_and_minute_accounting(self, manual_clock, engine):
        """After two borrows: waiting()=2, occupiedPassQps=2/60, minute
        pass counts the occupied entries immediately, second-window
        pass does NOT until the borrowed window matures."""
        from sentinel_tpu.core import api

        self._load_qps_rule(2.0)
        for ts in (1510, 1520):
            manual_clock.set_ms(ts)
            api.entry("res")
        for ts in (2100, 2110):
            manual_clock.set_ms(ts)
            api.entry("res", prio=True)
        manual_clock.set_ms(2130)
        stats = engine.cluster_node_stats("res")
        assert stats["waiting"] == 2
        assert stats["occupied_pass_qps"] == pytest.approx(2 / 60.0)
        # minute: 2 plain + 2 occupied (addOccupiedPass adds PASS too).
        assert stats["total_pass_minute"] == 4
        # second window: only the 2 plain passes are current yet.
        assert stats["pass_qps"] == pytest.approx(2.0)
        # StatisticSlot's PriorityWaitException branch still acquires
        # the thread slot for occupied entries.
        assert stats["cur_thread_num"] == 4

        # ...and once the borrowed window becomes current the borrowed
        # tokens mature into pass_qps (window [2500, 3000)).
        manual_clock.set_ms(2600)
        stats = engine.cluster_node_stats("res")
        assert stats["waiting"] == 0
        assert stats["pass_qps"] == pytest.approx(2.0)  # plain expired, borrows current

    def test_non_prio_blocks_where_prio_borrows(self, manual_clock, engine):
        from sentinel_tpu.core import api
        from sentinel_tpu.core.errors import FlowBlockError as FlowError

        self._load_qps_rule(1.0)
        manual_clock.set_ms(1000)
        api.entry("res")
        manual_clock.set_ms(1100)
        with pytest.raises(FlowError):
            api.entry("res")
        manual_clock.set_ms(1200)
        e = api.entry("res", prio=True)  # borrows instead
        assert e is not None
        assert manual_clock.now_ms() > 1200  # slept the occupy wait

    def test_borrow_not_committed_when_other_slot_vetoes(self, manual_clock, engine):
        """A prioritized entry whose QPS slot borrows but whose THREAD
        slot vetoes is blocked — and the borrow must NOT leak into the
        slab (waiting() stays 0, no phantom pass later). The batched
        chain checks every rule; the reference would order-dependently
        pass if the QPS rule sorted first (PriorityWaitException aborts
        before the THREAD check), so blocking is the conservative
        resolution."""
        import sentinel_tpu as st
        from sentinel_tpu.core import api
        from sentinel_tpu.core.errors import FlowBlockError as FlowError

        st.flow_rule_manager.load_rules([
            st.FlowRule("res", count=1.0),
            st.FlowRule("res", grade=C.FLOW_GRADE_THREAD, count=1),
        ])
        manual_clock.set_ms(1000)
        e1 = api.entry("res")  # holds the only thread slot
        manual_clock.set_ms(1100)
        with pytest.raises(FlowError):
            api.entry("res", prio=True)
        stats = engine.cluster_node_stats("res")
        assert stats["waiting"] == 0  # vetoed borrow did not leak
        e1.exit()

    def test_occupy_timeout_denies_prio(self, manual_clock, engine):
        """With the default 500 ms timeout the same borrow is denied
        (waitInMs ≥ timeout ends the search, java:320-322)."""
        from sentinel_tpu.core import api
        from sentinel_tpu.core.errors import FlowBlockError as FlowError
        from sentinel_tpu.utils.config import config

        config.set(config.OCCUPY_TIMEOUT_MS, "500")
        self._load_qps_rule(1.0)
        manual_clock.set_ms(1000)
        api.entry("res")
        manual_clock.set_ms(1100)
        # wait to next window = 500+400=900 or 400 for window 1; window 1
        # still holds the pass → both steps fail → blocked.
        with pytest.raises(FlowError):
            api.entry("res", prio=True)


class TestOccupyMesh:
    """Borrow budget on the 8-device mesh: prioritized entries across
    chips borrow at most maxCount − waiting in total, and the merged
    future slab holds exactly the granted tokens."""

    @pytest.mark.mesh
    def test_borrow_conserved_across_mesh(self):
        from sentinel_tpu.metrics.nodes import SECOND_CFG, make_stats
        from sentinel_tpu.models.rules import FlowRule
        from sentinel_tpu.rules.degrade_table import DegradeIndex
        from sentinel_tpu.rules.flow_table import FlowIndex
        from sentinel_tpu.rules.param_table import make_param_state
        from sentinel_tpu.runtime.flush import FlushBatch, SystemDevice
        from sentinel_tpu.parallel import make_mesh, make_sharded_flush

        n_devices, per_chip = 8, 16
        n = n_devices * per_chip
        rows = 16
        stats = make_stats(rows)
        # Row 0's current window [1000, 1500) is full: 20 passes.
        sec_ws = np.full((rows, 2), SECOND_CFG.empty_ws, dtype=np.int32)
        sec_counts = np.zeros((rows, 2, len(MetricEvent)), dtype=np.int32)
        sec_ws[0, 0] = 1000
        sec_counts[0, 0, MetricEvent.PASS] = 20
        stats = stats._replace(
            second=stats.second._replace(
                window_start=jnp.asarray(sec_ws), counts=jnp.asarray(sec_counts)
            )
        )
        index = FlowIndex([FlowRule(resource="r0", count=20.0)])
        dindex = DegradeIndex([])
        inf = float("inf")
        sysdev = SystemDevice(
            qps=jnp.float32(inf), max_thread=jnp.float32(inf),
            max_rt=jnp.float32(inf), load_threshold=jnp.float32(-1.0),
            cpu_threshold=jnp.float32(-1.0), cur_load=jnp.float32(-1.0),
            cur_cpu=jnp.float32(-1.0),
        )
        e_rows = np.full((n, 4), -1, dtype=np.int32)
        e_rows[:, 0] = 0
        m = n_devices
        batch = FlushBatch(
            now=jnp.int32(1100),
            e_valid=jnp.ones(n, dtype=bool),
            e_ts=jnp.full(n, 1100, dtype=jnp.int32),
            e_acquire=jnp.ones(n, dtype=jnp.int32),
            e_rows=jnp.asarray(e_rows),
            e_rule_gid=jnp.zeros((n, 1), dtype=jnp.int32),
            e_check_row=jnp.zeros((n, 1), dtype=jnp.int32),
            e_prio=jnp.ones(n, dtype=bool),
            e_auth_ok=jnp.ones(n, dtype=bool),
            e_cluster_ok=jnp.ones(n, dtype=bool),
            e_dgid=jnp.full((n, 1), -1, dtype=jnp.int32),
            x_valid=jnp.zeros(m, dtype=bool),
            x_ts=jnp.zeros(m, dtype=jnp.int32),
            x_count=jnp.zeros(m, dtype=jnp.int32),
            x_rows=jnp.full((m, 4), -1, dtype=jnp.int32),
            x_rt=jnp.zeros(m, dtype=jnp.int32),
            x_err=jnp.zeros(m, dtype=jnp.int32),
            x_thr=jnp.zeros(m, dtype=jnp.int32),
            x_dgid=jnp.full((m, 1), -1, dtype=jnp.int32),
        )
        mesh = make_mesh(n_devices)
        jitted = make_sharded_flush(mesh, occupy_timeout_ms=1000)
        stats2, fdyn, ddyn, pdyn, result = jitted(
            stats, index.device, index.make_dyn_state(), dindex.device,
            dindex.make_dyn_state(), make_param_state(8), sysdev, batch,
        )
        admitted = np.asarray(result.admitted)
        occupied = np.asarray(result.occupied)
        # Plain capacity is exhausted (window full) → every admission is
        # a borrow; the global borrow budget is maxCount=20.
        assert int(occupied.sum()) == 20
        assert int(admitted.sum()) == 20
        assert np.array_equal(admitted, occupied)
        # Merged slab: exactly 20 tokens waiting on window [2000, 2500).
        fut_ws = np.asarray(stats2.future_ws)[0]
        fut_pass = np.asarray(stats2.future_pass)[0]
        b = int(np.argmax(fut_ws))
        assert int(fut_ws[b]) == 2000
        assert int(fut_pass[b]) == 20
        # Accounting: no second-window PASS for occupied entries; blocks
        # for the demoted 108.
        from sentinel_tpu.metrics import metric_array as ma
        from sentinel_tpu.metrics.nodes import SECOND_CFG as SC

        sums = np.asarray(ma.window_sums(SC, stats2.second, jnp.int32(1100)))[0]
        assert int(sums[MetricEvent.PASS]) == 20  # the pre-seeded passes only
        assert int(sums[MetricEvent.BLOCK]) == n - 20
