"""ZookeeperDataSource against a fake in-process ZooKeeper speaking
real jute wire bytes (same approach as the Redis RESP / etcd gateway
tests): session handshake, getData/exists/setData/create, data + creation
watches, pings, outage catch-up, and corrupted-frame recovery.
"""

import json
import socket
import struct
import threading
import time

import pytest

from sentinel_tpu.datasource.base import json_converter
from sentinel_tpu.datasource.zookeeper_source import (
    ERR_NODEEXISTS,
    ERR_NONODE,
    ERR_OK,
    EVT_NODE_CREATED,
    EVT_NODE_DATA_CHANGED,
    OP_AUTH,
    OP_CLOSE,
    OP_CREATE,
    OP_EXISTS,
    OP_GETDATA,
    OP_PING,
    OP_SETDATA,
    XID_PING,
    XID_WATCH,
    ZookeeperDataSource,
    _Reader,
    _pack_buf,
    _pack_str,
)
from sentinel_tpu.models.rules import FlowRule


class FakeZk:
    """Minimal ZooKeeper: one thread per client, an in-memory znode
    tree, per-path data/exists watches (one-shot, like the real thing),
    and fault injection (garbage frames, connection kills)."""

    def __init__(self):
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self.nodes = {}  # path -> bytes
        self.watches = {}  # path -> list[(conn, send_lock)]
        self.lock = threading.Lock()
        self.stop = threading.Event()
        self.clients = []
        self.pings = 0
        self.auths = []
        self.inject_garbage_next_frame = False
        self._accept_thread = threading.Thread(target=self._accept, daemon=True)
        self._accept_thread.start()

    def close(self):
        self.stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
        self.kill_clients()

    def kill_clients(self):
        with self.lock:
            clients, self.clients = list(self.clients), []
            self.watches.clear()
        for c in clients:
            try:
                c.close()
            except OSError:
                pass

    def set_data(self, path, data: bytes):
        """Server-side change: update the tree and fire data watches."""
        with self.lock:
            created = path not in self.nodes
            self.nodes[path] = data
            watchers = self.watches.pop(path, [])
        ev = EVT_NODE_CREATED if created else EVT_NODE_DATA_CHANGED
        for conn, send_lock in watchers:
            self._send_watch_event(conn, send_lock, ev, path)

    # -- wire helpers --
    @staticmethod
    def _send_frame(conn, send_lock, body: bytes):
        with send_lock:
            conn.sendall(struct.pack(">i", len(body)) + body)

    def _send_watch_event(self, conn, send_lock, ev_type, path):
        body = (
            struct.pack(">iqi", XID_WATCH, 0, 0)
            + struct.pack(">ii", ev_type, 3)  # state SyncConnected
            + _pack_str(path)
        )
        try:
            self._send_frame(conn, send_lock, body)
        except OSError:
            pass

    @staticmethod
    def _recv_exact(conn, n):
        chunks = []
        while n > 0:
            b = conn.recv(n)
            if not b:
                raise ConnectionError("closed")
            chunks.append(b)
            n -= len(b)
        return b"".join(chunks)

    def _recv_frame(self, conn):
        (n,) = struct.unpack(">i", self._recv_exact(conn, 4))
        return self._recv_exact(conn, n)

    @staticmethod
    def _stat() -> bytes:
        return struct.pack(">qqqqiiiqiiq", 1, 2, 0, 0, 1, 0, 0, 0, 0, 0, 2)

    # -- server loops --
    def _accept(self):
        while not self.stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            with self.lock:
                self.clients.append(conn)
            threading.Thread(target=self._serve, args=(conn,), daemon=True).start()

    def _serve(self, conn):
        send_lock = threading.Lock()
        try:
            # Handshake.
            r = _Reader(self._recv_frame(conn))
            r.i32(); r.i64()
            timeout = r.i32()
            self._send_frame(
                conn, send_lock,
                struct.pack(">iiq", 0, timeout, 0x1234) + _pack_buf(b"\0" * 16),
            )
            while not self.stop.is_set():
                r = _Reader(self._recv_frame(conn))
                xid, op = r.i32(), r.i32()
                if self.inject_garbage_next_frame:
                    self.inject_garbage_next_frame = False
                    with send_lock:
                        conn.sendall(struct.pack(">i", 12) + b"\xff" * 2)  # truncated
                    conn.close()
                    return
                if op == OP_PING:
                    self.pings += 1
                    self._send_frame(conn, send_lock, struct.pack(">iqi", XID_PING, 0, 0))
                elif op == OP_AUTH:
                    r.i32()
                    self.auths.append((r.string(), r.buf()))
                elif op == OP_GETDATA:
                    path = r.string()
                    watch = r._take(1) == b"\x01"
                    self._handle_get(conn, send_lock, xid, path, watch)
                elif op == OP_EXISTS:
                    path = r.string()
                    watch = r._take(1) == b"\x01"
                    with self.lock:
                        present = path in self.nodes
                        if watch and not present:
                            self.watches.setdefault(path, []).append((conn, send_lock))
                    hdr = struct.pack(">iqi", xid, 0, ERR_OK if present else ERR_NONODE)
                    body = hdr + (self._stat() if present else b"")
                    self._send_frame(conn, send_lock, body)
                elif op == OP_SETDATA:
                    path = r.string()
                    data = r.buf() or b""
                    r.i32()  # version
                    with self.lock:
                        present = path in self.nodes
                    if not present:
                        self._send_frame(
                            conn, send_lock, struct.pack(">iqi", xid, 0, ERR_NONODE)
                        )
                    else:
                        self.set_data(path, data)
                        self._send_frame(
                            conn, send_lock,
                            struct.pack(">iqi", xid, 0, ERR_OK) + self._stat(),
                        )
                elif op == OP_CREATE:
                    path = r.string()
                    data = r.buf() or b""
                    with self.lock:
                        exists = path in self.nodes
                    if exists:
                        self._send_frame(
                            conn, send_lock, struct.pack(">iqi", xid, 0, ERR_NODEEXISTS)
                        )
                    else:
                        self.set_data(path, data)
                        self._send_frame(
                            conn, send_lock,
                            struct.pack(">iqi", xid, 0, ERR_OK) + _pack_str(path),
                        )
                elif op == OP_CLOSE:
                    self._send_frame(conn, send_lock, struct.pack(">iqi", xid, 0, 0))
                    conn.close()
                    return
                else:
                    self._send_frame(conn, send_lock, struct.pack(">iqi", xid, 0, -6))
        except (ConnectionError, OSError, struct.error):
            pass

    def _handle_get(self, conn, send_lock, xid, path, watch):
        with self.lock:
            data = self.nodes.get(path)
            if watch:
                self.watches.setdefault(path, []).append((conn, send_lock))
        if data is None:
            self._send_frame(conn, send_lock, struct.pack(">iqi", xid, 0, ERR_NONODE))
        else:
            self._send_frame(
                conn, send_lock,
                struct.pack(">iqi", xid, 0, ERR_OK) + _pack_buf(data) + self._stat(),
            )


def _rules_json(count):
    return json.dumps([{"resource": "zkres", "count": count}])


@pytest.fixture()
def fake_zk():
    srv = FakeZk()
    yield srv
    srv.close()


def _wait(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.02)
    return False


def _src(fake_zk, **kw):
    return ZookeeperDataSource(
        json_converter(FlowRule),
        path="/sentinel/flow",
        server_addr=f"127.0.0.1:{fake_zk.port}",
        reconnect_interval_sec=0.1,
        **kw,
    )


class TestZookeeperDataSource:
    def test_initial_load_and_watch_push(self, fake_zk):
        fake_zk.set_data("/sentinel/flow", _rules_json(7).encode())
        src = _src(fake_zk).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 7)
            # Server-side change → watch pushes within one round-trip.
            fake_zk.set_data("/sentinel/flow", _rules_json(9).encode())
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 9)
        finally:
            src.close()

    def test_absent_node_then_created(self, fake_zk):
        src = _src(fake_zk).start()
        try:
            # Creation watch armed via exists; create → value arrives.
            time.sleep(0.3)
            fake_zk.set_data("/sentinel/flow", _rules_json(3).encode())
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 3)
        finally:
            src.close()

    def test_write_round_trips(self, fake_zk):
        src = _src(fake_zk)
        src.write(_rules_json(5))
        assert fake_zk.nodes["/sentinel/flow"] == _rules_json(5).encode()
        # read_source without a running watcher (transient connection).
        assert json.loads(src.read_source())[0]["count"] == 5
        # Overwrite through setData now that the node exists.
        src.write(_rules_json(6))
        assert fake_zk.nodes["/sentinel/flow"] == _rules_json(6).encode()

    def test_outage_catch_up(self, fake_zk):
        fake_zk.set_data("/sentinel/flow", _rules_json(1).encode())
        src = _src(fake_zk).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 1)
            # Outage: kill every connection, change the data while the
            # client is down, let it reconnect — the post-reconnect
            # catch-up read must deliver the missed update.
            fake_zk.kill_clients()
            fake_zk.set_data("/sentinel/flow", _rules_json(2).encode())
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 2)
        finally:
            src.close()

    def test_corrupted_frame_recovers(self, fake_zk):
        fake_zk.set_data("/sentinel/flow", _rules_json(1).encode())
        src = _src(fake_zk).start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 1)
            # Next frame the server sends is garbage (length says 12,
            # body truncated, then hard close) — the client must treat
            # it as a dead connection and recover via reconnect.
            fake_zk.inject_garbage_next_frame = True
            fake_zk.set_data("/sentinel/flow", _rules_json(4).encode())
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 4, timeout=8.0)
        finally:
            src.close()

    def test_nacos_style_path_and_auth(self, fake_zk):
        src = ZookeeperDataSource(
            json_converter(FlowRule),
            group_id="sentinel",
            data_id="flow",
            server_addr=f"127.0.0.1:{fake_zk.port}",
            reconnect_interval_sec=0.1,
            auth=[("digest", b"u:p")],
        )
        assert src.path == "/sentinel/flow"
        fake_zk.set_data("/sentinel/flow", _rules_json(8).encode())
        src.start()
        try:
            assert _wait(lambda: (src.get_property().value or [None])[0] and src.get_property().value[0].count == 8)
            assert _wait(lambda: ("digest", b"u:p") in fake_zk.auths)
        finally:
            src.close()

    def test_rules_flow_into_manager(self, fake_zk, manual_clock, engine):
        """End to end: znode → datasource → flow rule manager → engine
        verdict (the reference's register_property wiring)."""
        import sentinel_tpu as st

        fake_zk.set_data("/sentinel/flow", json.dumps(
            [{"resource": "zkflow", "count": 0}]).encode())
        src = _src(fake_zk).start()
        try:
            st.flow_rule_manager.register_property(src.get_property())
            assert _wait(
                lambda: any(r.resource == "zkflow"
                            for r in st.flow_rule_manager.get_rules())
            )
            with pytest.raises(st.FlowBlockError):
                with st.entry("zkflow"):
                    pass
        finally:
            src.close()

def test_garbage_rule_payload_keeps_rules(fake_zk):
    """Converter-level garbage (valid frame, invalid JSON in the znode)
    must not clobber the last good rules — PushDataSource.on_update
    swallows convert errors (base.py), matching the reference listener
    stance. Distinct from the corrupted-FRAME test above (transport)."""
    fake_zk.set_data("/sentinel/flow", _rules_json(5).encode())
    src = _src(fake_zk).start()
    try:
        assert _wait(lambda: (src.get_property().value or [None])[0]
                     and src.get_property().value[0].count == 5)
        fake_zk.set_data("/sentinel/flow", b"{definitely not json")
        # The watch fires and the bad payload is converted (and
        # rejected); rules stay. Then a good payload recovers.
        fake_zk.set_data("/sentinel/flow", _rules_json(8).encode())
        assert _wait(lambda: src.get_property().value[0].count == 8)
        assert all(v is not None for v in [src.get_property().value])
    finally:
        src.close()


class TestConnectString:
    def test_parse_variants(self):
        from sentinel_tpu.datasource.zookeeper_source import _parse_connect_string

        assert _parse_connect_string("h1:2181,h2:2182") == [("h1", 2181), ("h2", 2182)]
        assert _parse_connect_string("h1") == [("h1", 2181)]
        assert _parse_connect_string("[::1]:2183") == [("::1", 2183)]
        assert _parse_connect_string("fe80::2") == [("fe80::2", 2181)]
        assert _parse_connect_string(" a:1 , b:2 ") == [("a", 1), ("b", 2)]
        with pytest.raises(ValueError):
            _parse_connect_string("")

    def test_ensemble_failover(self, fake_zk):
        """First server in the connect string is dead; the session loop
        rotates to the live one (Curator HostProvider round-robin)."""
        fake_zk.set_data("/sentinel/flow", _rules_json(9).encode())
        src = ZookeeperDataSource(
            json_converter(FlowRule),
            path="/sentinel/flow",
            server_addr=f"127.0.0.1:1,127.0.0.1:{fake_zk.port}",
            reconnect_interval_sec=0.05,
        )
        src.start()
        try:
            assert _wait(
                lambda: (src.get_property().value or [None])[0]
                and src.get_property().value[0].count == 9,
                timeout=8.0,
            )
            assert src.port == fake_zk.port  # settled on the live server
        finally:
            src.close()
