"""Circuit breaking — exception-ratio breaker trips OPEN, rejects while
open, probes HALF_OPEN after the time window, recovers on a good probe
(sentinel-demo-basic degrade demos).
"""

import _bootstrap  # noqa: F401

import sentinel_tpu as st
from sentinel_tpu.core import api
from sentinel_tpu.utils.clock import ManualClock, set_default_clock

# A manual clock makes the state machine visible step by step.
clock = ManualClock(0)
set_default_clock(clock)
api.reset(clock=clock)

st.flow_rule_manager.load_rules([st.FlowRule("backend", count=1000)])
st.degrade_rule_manager.load_rules([
    st.DegradeRule(resource="backend", grade=1, count=0.5,  # >50% errors
                   time_window=5, min_request_amount=5)
])


def call(ts, fail):
    clock.set_ms(ts)
    try:
        e = st.entry("backend")
    except st.DegradeBlockError:
        return "BLOCKED (breaker open)"
    if fail:
        e.set_error(RuntimeError("downstream 500"))
    e.exit()
    return "error" if fail else "ok"


print("-- 6 failing calls (ratio 100% > 50%, minRequest=5): breaker trips")
for i in range(6):
    print(f"  t={i * 10}ms: {call(i * 10, fail=True)}")
print(f"-- while OPEN: {call(1000, fail=False)}")
print(f"-- still OPEN: {call(3000, fail=False)}")
print("-- after the 5s time window, one probe goes through HALF_OPEN:")
print(f"  t=5200ms: {call(5200, fail=False)}")
print(f"-- good probe closed the breaker: {call(5300, fail=False)}")
