"""Cluster management through the authenticated console: a machine
with an embedded token server registers with the dashboard, the
operator logs in, assigns it as the app's token server over HTTP, and
reads back per-flowId state — the sentinel-dashboard cluster screen
flow (auth/SimpleWebAuthServiceImpl + ClusterAssignServiceImpl) end to
end.

Login: sentinel / sentinel  (http://127.0.0.1:18722/).
"""

import _bootstrap  # noqa: F401

import json
import os
import time
import urllib.request

import sentinel_tpu as st
from sentinel_tpu.cluster.flow_rules import (
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.cluster.state import EmbeddedClusterTokenServerProvider
from sentinel_tpu.cluster.token_service import DefaultTokenService
from sentinel_tpu.dashboard import DashboardServer
from sentinel_tpu.models.rules import ClusterFlowConfig
from sentinel_tpu.transport.command_center import CommandCenter

_port = int(os.environ.get("SENTINEL_DEMO_PORT", "18721"))
duration = float(os.environ.get("SENTINEL_DEMO_DURATION", "60"))

# The machine: command API + an embeddable token server + one
# cluster-mode flow rule.
EmbeddedClusterTokenServerProvider.register(
    SentinelTokenServer(port=0, service=DefaultTokenService())
)
cluster_server_config_manager.load_global_flow_config(
    exceed_count=1.0, max_allowed_qps=30000.0
)
cluster_flow_rule_manager.load_rules(
    "default",
    [st.FlowRule("pay", count=100, cluster_mode=True,
                 cluster_config=ClusterFlowConfig(flow_id=42))],
)
center = CommandCenter(port=_port).start()

# The console, with session auth on.
dashboard = DashboardServer(
    port=_port + 1 if _port else 0,
    fetch_interval_sec=0.5,
    auth_username="sentinel",
    auth_password="sentinel",
).start()

# Register the machine, then do what the console's buttons do: log in,
# assign this machine as the token server, read the cluster state.
base = f"http://127.0.0.1:{dashboard.port}"
urllib.request.urlopen(
    f"{base}/registry/machine?app=demo&ip=127.0.0.1&port={center.port}",
    timeout=5,
)
import http.cookiejar

jar = http.cookiejar.CookieJar()
opener = urllib.request.build_opener(urllib.request.HTTPCookieProcessor(jar))
opener.open(
    urllib.request.Request(
        f"{base}/auth/login", data=b"username=sentinel&password=sentinel",
        method="POST",
    ),
    timeout=5,
)
assign = json.loads(
    opener.open(
        f"{base}/cluster/assign?app=demo&server=127.0.0.1:{center.port}",
        timeout=10,
    ).read()
)
print(f"assign       : {assign}")

# Token traffic so the server has per-flow state to show.
svc = EmbeddedClusterTokenServerProvider.get_server().service
for _ in range(7):
    svc.request_token(42)

state = json.loads(
    opener.open(f"{base}/cluster/state?app=demo", timeout=10).read()
)
print(f"cluster state: {json.dumps(state, indent=2)[:400]}")
print(f"web console  : {base}/  (login sentinel/sentinel)")

end = time.time() + duration
while time.time() < end:
    time.sleep(0.25)
