"""Hot-parameter flow control — per-argument-value rate limits with an
exception item (sentinel-demo-parameter-flow-control).
"""

import _bootstrap  # noqa: F401

import sentinel_tpu as st
from sentinel_tpu.core import api
from sentinel_tpu.models.rules import ParamFlowItem
from sentinel_tpu.utils.clock import ManualClock, set_default_clock

clock = ManualClock(0)
set_default_clock(clock)
api.reset(clock=clock)

# 2 QPS per product id, but the flash-sale item gets 5.
st.param_flow_rule_manager.load_rules([
    st.ParamFlowRule(
        resource="buy", param_idx=0, count=2,
        param_flow_item_list=[ParamFlowItem(object="flash-sale", count=5)],
    )
])


def attempt(ts, product):
    clock.set_ms(ts)
    e = st.try_entry("buy", args=(product,))
    if e:
        e.exit()
        return "pass"
    return "BLOCK"


for product in ("normal-item", "flash-sale"):
    results = [attempt(100 + i, product) for i in range(7)]
    print(f"{product:12s}: {' '.join(results)}")
print("normal-item passes 2, flash-sale passes 5 — per-value budgets")
