"""Live second-window retune (SampleCountProperty / IntervalProperty).

The reference can rebuild every node's rolling second counter at
runtime; here the same knobs retune the shared window tensors — the
kernels re-trace on the new geometry, statistics reset cleanly, and
QPS rules reinterpret over the new interval.
"""

import _bootstrap  # noqa: F401

import sentinel_tpu as st
from sentinel_tpu.core import api
from sentinel_tpu.metrics import nodes
from sentinel_tpu.utils.clock import ManualClock, set_default_clock

clock = ManualClock(0)
set_default_clock(clock)
api.reset(clock=clock)

st.flow_rule_manager.load_rules([st.FlowRule("svc", count=5)])


def grants(n):
    return sum(st.try_entry("svc") is not None for _ in range(n))


print(f"geometry: {nodes.SECOND_CFG.sample_count} x "
      f"{nodes.SECOND_CFG.window_len_ms} ms")
print(f"  5-QPS rule over 1 s window: {grants(10)} of 10 admitted")

# Retune live: 4 buckets over a 2 s interval.
st.sample_count_property.update_value(4)
st.interval_property.update_value(2000)
print(f"retuned: {nodes.SECOND_CFG.sample_count} x "
      f"{nodes.SECOND_CFG.window_len_ms} ms (stats reset, kernels re-trace)")
print(f"  same rule over the 2 s window: {grants(20)} of 20 admitted "
      "(5 QPS x 2 s = 10)")

clock.advance(2001)
print(f"  next window: {grants(20)} of 20 admitted")
