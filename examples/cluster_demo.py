"""Cluster flow control — a standalone token server over TCP, clients
requesting QPS tokens and held concurrency tokens
(sentinel-demo-cluster).
"""

import _bootstrap  # noqa: F401

from sentinel_tpu.cluster import (
    DefaultTokenService,
    cluster_flow_rule_manager,
)
from sentinel_tpu.cluster.client import ClusterTokenClient
from sentinel_tpu.cluster.server import SentinelTokenServer
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
from sentinel_tpu.utils.clock import ManualClock

qps_rule = FlowRule("api", count=3, cluster_mode=True,
                    cluster_config=ClusterFlowConfig(
                        flow_id=100, threshold_type=C.FLOW_THRESHOLD_GLOBAL))
conc_rule = FlowRule("job", count=2, grade=C.FLOW_GRADE_THREAD, cluster_mode=True,
                     cluster_config=ClusterFlowConfig(flow_id=200))
cluster_flow_rule_manager.load_rules("default", [qps_rule, conc_rule])

server = SentinelTokenServer(port=0, service=DefaultTokenService(ManualClock(0)))
server.start()
print(f"token server on 127.0.0.1:{server.port}")

client = ClusterTokenClient(port=server.port).start()

print("-- global QPS tokens (count=3):")
for i in range(5):
    r = client.request_token(100)
    print(f"  request {i + 1}: {r.status.name}")

print("-- held concurrency tokens (count=2): acquire/release lifecycle")
t1 = client.request_concurrent_token(200)
t2 = client.request_concurrent_token(200)
t3 = client.request_concurrent_token(200)
print(f"  acquire x3: {t1.status.name}, {t2.status.name}, {t3.status.name}")
print(f"  release first: {client.release_concurrent_token(t1.token_id).status.name}")
print(f"  acquire again: {client.request_concurrent_token(200).status.name}")

client.stop()
server.stop()
