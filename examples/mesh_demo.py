"""Multi-chip engine — the deployable cluster unit: one engine sharded
over an 8-device mesh, budgets conserved across chips with ICI
collectives instead of a token-server RPC (the TPU-native replacement
for sentinel-demo-cluster's server deployment).

Runs on a virtual 8-device CPU mesh out of the box; on an 8-chip TPU
slice set SENTINEL_DEMO_REAL_DEVICES=1.
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import _bootstrap  # noqa: F401

import sentinel_tpu as st

eng = st.get_engine()
eng.enable_mesh(8)
st.flow_rule_manager.load_rules([st.FlowRule("global-api", count=20)])

now = eng.clock.now_ms()
ops = eng.submit_many([{"resource": "global-api", "ts": now} for _ in range(128)])
eng.flush()
admitted = sum(op.verdict.admitted for op in ops)
print(f"128 entries sharded over 8 devices against count=20:")
print(f"  admitted {admitted} (exactly the global budget, not 8 x 20)")
stats = eng.cluster_node_stats("global-api")
print(f"  minute totals: pass={stats['total_pass_minute']}  "
      f"block={stats['total_block_minute']}")
