"""FlowQpsDemo — the reference's flagship demo
(sentinel-demo-basic/.../flow/FlowQpsDemo.java): a QPS=20 rule pins
passes at 20/s while the rest of the offered load is rejected.
"""

import _bootstrap  # noqa: F401

import threading
import time

import sentinel_tpu as st

RESOURCE = "methodA"
st.flow_rule_manager.load_rules([st.FlowRule(RESOURCE, count=20)])

passed = blocked = 0
counter_lock = threading.Lock()
stop = threading.Event()


def worker():
    global passed, blocked
    while not stop.is_set():
        try:
            with st.entry(RESOURCE):
                with counter_lock:
                    passed += 1
        except st.FlowBlockError:
            with counter_lock:
                blocked += 1
        time.sleep(0.001)


threads = [threading.Thread(target=worker, daemon=True) for _ in range(8)]
print(f"offering load from {len(threads)} threads against a QPS=20 rule...")
for t in threads:
    t.start()

prev_p = prev_b = 0
for second in range(10):
    time.sleep(1)
    with counter_lock:
        p, b = passed, blocked
    print(f"t={second + 1:2d}s  pass/s={p - prev_p:4d}  block/s={b - prev_b:5d}")
    prev_p, prev_b = p, b
stop.set()
for t in threads:
    t.join(timeout=5)  # let in-flight flushes finish before teardown
print("done — passes should be pinned near 20/s once the kernel is warm")
