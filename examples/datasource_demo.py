"""Dynamic rule reloads through the datasource plane — the reference's
sentinel-demo-dynamic-file-rule shape: rules live in a JSON file, a
FileRefreshableDataSource feeds the flow rule manager, edits to the
file change live verdicts without touching the app, and rule pushes
persist back through a FileWritableDataSource.

The same `register_property` wiring works for every network source
(Redis/etcd/Consul/Nacos/ZooKeeper/Apollo/Eureka/Config Server) — the
file source is just the one that needs no external server.
"""

import _bootstrap  # noqa: F401

import json
import os
import tempfile
import time

import sentinel_tpu as st
from sentinel_tpu.datasource import (
    FileRefreshableDataSource,
    FileWritableDataSource,
    WritableDataSourceRegistry,
    json_converter,
)

DURATION = float(os.environ.get("SENTINEL_DEMO_DURATION", 6))
RESOURCE = "dynamicRes"

with tempfile.TemporaryDirectory() as tmp:
    path = os.path.join(tmp, "flow-rules.json")
    with open(path, "w") as f:
        json.dump([{"resource": RESOURCE, "count": 2}], f)

    src = FileRefreshableDataSource(
        path, json_converter(st.FlowRule), refresh_interval_sec=0.2
    ).start()
    st.flow_rule_manager.register_property(src.get_property())
    # The registry hands writers RULE OBJECTS (the command plane's
    # setRules push) — the encoder serializes them back to the file's
    # JSON shape so the refreshable side can re-read them.
    WritableDataSourceRegistry.register(
        "flow",
        FileWritableDataSource(
            path, encoder=lambda rules: json.dumps([r.to_dict() for r in rules])
        ),
    )

    def offered(n: int) -> int:
        admitted = 0
        for _ in range(n):
            e = st.try_entry(RESOURCE)
            if e is not None:
                admitted += 1
                e.exit()  # release the thread slot + context stack
        return admitted

    print(f"rules file: {path}")
    time.sleep(0.5)  # initial load
    warm = st.try_entry(RESOURCE)  # warm the kernel (first flush compiles)
    if warm is not None:
        warm.exit()
    st.get_engine().flush()  # also compile the entry+exit batch shape
    time.sleep(1.1)  # fresh QPS window after the warm-up entry
    print(f"count=2 → admitted {offered(6)}/6 this second")

    # "Operator edits the file" — the poll picks it up.
    with open(path, "w") as f:
        json.dump([{"resource": RESOURCE, "count": 5}], f)
    deadline = time.monotonic() + min(DURATION, 5)
    while time.monotonic() < deadline:
        rules = st.flow_rule_manager.get_rules() or []
        if any(r.count == 5 for r in rules):
            break
        time.sleep(0.05)
    else:
        print("WARNING: file edit never reached the manager — "
              "the next line measures the OLD rule")
    time.sleep(1.0)  # fresh QPS window
    print(f"count=5 → admitted {offered(8)}/8 this second")

    # Rule push persisting back to the file (the command plane's hop:
    # the registry hands the writer rule objects).
    WritableDataSourceRegistry.try_write(
        "flow", [st.FlowRule(RESOURCE, count=3)]
    )
    print("persisted via WritableDataSourceRegistry:", open(path).read())
    src.close()
print("done — live reload + persistence, no app restart")
