"""Shared demo setup: import path + a fast backend.

The demos default to CPU so they run anywhere instantly; delete the
``jax_platforms`` line to run on real TPU hardware (first compile takes
tens of seconds there, then flushes are sub-millisecond).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

if not os.environ.get("SENTINEL_DEMO_REAL_DEVICES"):
    jax.config.update("jax_platforms", "cpu")
