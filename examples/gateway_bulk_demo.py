"""Columnar gateway admission — adapter traffic on the bulk path.

Reference analog: sentinel-spring-cloud-gateway-adapter guarding routes
with GatewayFlowRule param matching; here a whole batching window of
requests is admitted in ONE columnar engine flush
(`gateway_submit_bulk` → `submit_bulk(args_column=...)`), with
per-client-IP budgets and array verdicts — the heavy-hitter mix rides
the closed-form rank path, no per-request Python objects.
"""

import _bootstrap  # noqa: F401

import numpy as np

import sentinel_tpu as st
from sentinel_tpu.adapters.gateway import (
    GatewayFlowRule,
    GatewayParamFlowItem,
    GatewayRequestBatch,
    GatewayRequestInfo,
    PARAM_PARSE_STRATEGY_CLIENT_IP,
    gateway_rule_manager,
    gateway_submit_bulk,
)
from sentinel_tpu.core import api
from sentinel_tpu.utils.clock import ManualClock, set_default_clock

clock = ManualClock(1000)
set_default_clock(clock)
api.reset(clock=clock)

eng = st.get_engine()
st.flow_rule_manager.load_rules([st.FlowRule("orders_route", count=10_000)])
gateway_rule_manager.load_rules([
    GatewayFlowRule(
        "orders_route", count=3,
        param_item=GatewayParamFlowItem(
            parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP),
    ),
])

# One batching window: 600 requests, two chatty clients + a long tail.
infos = (
    [GatewayRequestInfo(path="/orders", client_ip="10.0.0.1")] * 250
    + [GatewayRequestInfo(path="/orders", client_ip="10.0.0.2")] * 250
    + [GatewayRequestInfo(path="/orders", client_ip=f"10.9.9.{i}") for i in range(100)]
)
group = gateway_submit_bulk("orders_route", infos)
eng.flush()

adm = np.asarray(group.admitted)
print(f"window of {len(infos)} requests -> {int(adm.sum())} admitted")
print(f"  10.0.0.1 (250 reqs): {int(adm[:250].sum())} admitted (count=3)")
print(f"  10.0.0.2 (250 reqs): {int(adm[250:500].sum())} admitted (count=3)")
print(f"  long tail (100 one-shot IPs): {int(adm[500:].sum())} admitted")
assert int(adm[:250].sum()) == 3 and int(adm[250:500].sum()) == 3
assert int(adm[500:].sum()) == 100
eng.submit_exit_bulk(group.rows, int(adm.sum()), rt=4, resource="orders_route")
eng.flush()
print("per-IP budgets enforced in one columnar flush — OK")

# Second window, columnar ingest: a gateway that buffers its batching
# window as COLUMNS hands them straight in (GatewayRequestBatch) —
# zero per-request Python objects, and the chatty clients' values are
# already interned from the first window (the persistent value cache).
clock.advance(2000)
batch = GatewayRequestBatch(
    n=600,
    client_ip=["10.0.0.1"] * 250 + ["10.0.0.2"] * 250
    + [f"10.9.9.{i}" for i in range(100)],
)
group2 = gateway_submit_bulk("orders_route", batch)
eng.flush()
adm2 = np.asarray(group2.admitted)
print(f"columnar window -> {int(adm2.sum())} admitted "
      f"(encode {eng.last_flush_host_ms['encode_ms']:.2f} ms, "
      f"kernel {eng.last_flush_host_ms['kernel_ms']:.2f} ms)")
assert int(adm2[:250].sum()) == 3 and int(adm2[250:500].sum()) == 3
assert int(adm2[500:].sum()) == 100
print("columnar GatewayRequestBatch ingest — OK")
