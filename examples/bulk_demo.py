"""Bulk (columnar) mode — the TPU-idiomatic throughput path.

The reference's API is one CAS-racing call per request; on a TPU the
idiomatic shape is one columnar group per flush: a single slot
resolution, numpy-slice encoding, one kernel launch, dense verdict
arrays back. This demo rate-limits a burst of 100k requests against a
QPS rule and a breaker, then releases the admitted ones with one bulk
exit group.
"""

import _bootstrap  # noqa: F401

import time

import numpy as np

import sentinel_tpu as st

RESOURCE = "checkout"
st.flow_rule_manager.load_rules([st.FlowRule(RESOURCE, count=1000)])
st.degrade_rule_manager.load_rules(
    [st.DegradeRule(resource=RESOURCE, grade=1, count=0.5, time_window=5)]
)

eng = st.get_engine()

# Warm-up flush: pays the one-time XLA compile for this batch shape.
w = eng.submit_bulk(RESOURCE, 100_000)
eng.flush()
if w.admitted_count:
    eng.submit_exit_bulk(w.rows, w.admitted_count, rt=7, resource=RESOURCE)
    eng.flush()

# One columnar group: 100k entries, one resolve, one kernel launch.
n = 100_000
t0 = time.perf_counter()
g = eng.submit_bulk(RESOURCE, n)
eng.flush()
dt = time.perf_counter() - t0
print(
    f"bulk flush: {n:,} entries in {dt * 1e3:.1f} ms "
    f"({n / dt:,.0f} ops/s end-to-end) — admitted {g.admitted_count:,}, "
    f"blocked {int((~g.admitted).sum()):,}"
)

# Verdicts are dense arrays — slice, count, route without Python loops.
blocked_reasons = np.unique(g.reason[~g.admitted])
print("block reasons present:", blocked_reasons.tolist())

stats = eng.cluster_node_stats(RESOURCE)
print(
    f"node stats: pass_qps={stats['pass_qps']:.0f} "
    f"block_qps={stats['block_qps']:.0f} threads={stats['cur_thread_num']}"
)

# Release the admitted entries in one bulk exit group (success + RT +
# thread release + breaker completions).
if g.admitted_count:
    eng.submit_exit_bulk(g.rows, g.admitted_count, rt=7, resource=RESOURCE)
    eng.flush()
print(f"after exits: threads={eng.cluster_node_stats(RESOURCE)['cur_thread_num']}")
