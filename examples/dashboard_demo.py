"""The full observability plane: engine + command center + heartbeat +
metric log timer + dashboard with the embedded web console
(sentinel-dashboard + sentinel-transport wired together).

Open http://127.0.0.1:18720/ while it runs.
"""

import _bootstrap  # noqa: F401

import os
import time

import sentinel_tpu as st
from sentinel_tpu.dashboard import DashboardServer
from sentinel_tpu.metrics.metric_log import MetricTimer
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender

st.flow_rule_manager.load_rules([
    st.FlowRule("checkout", count=3),
    st.FlowRule("search", count=50),
])

# SENTINEL_DEMO_PORT=0 (the test default) binds ephemeral ports so
# parallel runs never collide; SENTINEL_DEMO_DURATION shortens the
# traffic loop.
_port = int(os.environ.get("SENTINEL_DEMO_PORT", "18719"))
duration = float(os.environ.get("SENTINEL_DEMO_DURATION", "60"))
center = CommandCenter(port=_port).start()
dashboard = DashboardServer(
    port=_port + 1 if _port else 0, fetch_interval_sec=0.5
).start()
heartbeat = HeartbeatSender(
    f"127.0.0.1:{dashboard.port}", command_port=center.port, interval_sec=1.0
).start()
timer = MetricTimer(st.get_engine(), interval_sec=0.5).start()

print(f"command API  : http://127.0.0.1:{center.port}/api")
print(f"Prometheus   : http://127.0.0.1:{center.port}/metrics")
print(f"web console  : http://127.0.0.1:{dashboard.port}/")
print(f"offering traffic for {duration:.0f}s (checkout pinned at 3/s) — ctrl-c to stop")

deadline = time.time() + duration
try:
    while time.time() < deadline:
        for _ in range(5):
            for resource in ("checkout", "search"):
                e = st.try_entry(resource)
                if e:
                    e.exit()
        time.sleep(0.25)
except KeyboardInterrupt:
    pass
finally:
    # Stop every background thread BEFORE interpreter teardown: a
    # daemon still inside a JAX/XLA call when the process exits can
    # abort in native code (observed flakily under machine load).
    timer.stop()
    heartbeat.stop()
    dashboard.stop()
    center.stop()
