"""The full observability plane: engine + command center + heartbeat +
metric log timer + dashboard with the embedded web console
(sentinel-dashboard + sentinel-transport wired together).

Open http://127.0.0.1:18720/ while it runs.
"""

import _bootstrap  # noqa: F401

import time

import sentinel_tpu as st
from sentinel_tpu.dashboard import DashboardServer
from sentinel_tpu.metrics.metric_log import MetricTimer
from sentinel_tpu.transport.command_center import CommandCenter
from sentinel_tpu.transport.heartbeat import HeartbeatSender

st.flow_rule_manager.load_rules([
    st.FlowRule("checkout", count=3),
    st.FlowRule("search", count=50),
])

center = CommandCenter(port=18719).start()
dashboard = DashboardServer(port=18720, fetch_interval_sec=0.5).start()
HeartbeatSender("127.0.0.1:18720", command_port=18719, interval_sec=1.0).start()
MetricTimer(st.get_engine(), interval_sec=0.5).start()

print("command API  : http://127.0.0.1:18719/api")
print("Prometheus   : http://127.0.0.1:18719/metrics")
print("web console  : http://127.0.0.1:18720/")
print("offering traffic for 60s (checkout pinned at 3/s) — ctrl-c to stop")

deadline = time.time() + 60
try:
    while time.time() < deadline:
        for _ in range(5):
            for resource in ("checkout", "search"):
                e = st.try_entry(resource)
                if e:
                    e.exit()
        time.sleep(0.25)
except KeyboardInterrupt:
    pass
finally:
    dashboard.stop()
    center.stop()
