"""Bench regression gate: compare a fresh bench.py JSON against the
latest committed ``BENCH_*.json`` trajectory point.

ROADMAP's "hardware truth" item: the committed ``BENCH_*`` files are a
perf trajectory, and a trajectory without a gate is a scrapbook — a
regression lands silently and the next session inherits it as the new
normal. This tool turns the trajectory into a gate:

* the **baseline** is the newest committed ``BENCH_*.json`` whose
  ``device_kind`` AND ``jax_version`` match the fresh run's (PR-7's
  hardware-truth header). No comparable baseline — different silicon,
  different jax, or a pre-header record — is a **SKIP with a reason**,
  never a fake pass/fail: comparing a TPU run against CPU liveness
  numbers is exactly the mistake the header exists to prevent;
* each stage metric is compared only when its stage **context**
  (ladder rung sizes: n_rules/n_entries etc.) matches — a budget-
  truncated ladder must not read as a slowdown;
* every metric carries its own **tolerance band** (throughput is a lot
  steadier than a p99 on a busy 1-core box), scaled globally by
  ``--tolerance-scale``. A metric worse than baseline by more than its
  band is a regression → exit 1 with a per-metric report.

Committed baselines may be the raw bench JSON or the driver wrapper
``{"parsed": {...}}`` shape — both load.

Usage::

    python bench.py --gate                    # bench + gate in one go
    python bench.py > fresh.json
    python tools/benchgate.py --fresh fresh.json [--repo-root .]
                              [--baseline BENCH_r05.json]
                              [--tolerance-scale 1.0]

Exit status: 0 pass or skip-with-reason, 1 regression (or a fresh
record that is itself an error), 2 usage error. The programmatic
surface (``load_record`` / ``find_baseline`` / ``compare`` / ``gate``)
is what tests/test_benchgate.py asserts on.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

# Direction per metric: "higher" = higher is better (throughput),
# "lower" = lower is better (latency). The band is the tolerated
# RELATIVE regression (0.60 = 60% worse than baseline still passes).
#
# Band sizing is empirical, from back-to-back CPU runs on the
# timeshared 1-core dev box (PR 8): throughputs swung up to 1.8x,
# mean sync latency 2.7x, percentile latencies 5x — pure tenancy
# noise, zero code change between the runs. The bands therefore catch
# ORDER-OF-MAGNITUDE regressions, which is the only gating a CPU
# liveness box honestly supports; on steady hardware (a real TPU run)
# tighten with ``--tolerance-scale 0.2``-ish. Too-loose-but-honest
# beats tight-but-flaky: a gate that cries wolf gets deleted.
STAGE_METRICS: Dict[str, Tuple[str, float]] = {
    "value": ("higher", 0.60),
    "flush_ms": ("lower", 2.00),
    "mixed_checks_per_sec": ("higher", 0.60),
    "mixed_flush_ms": ("lower", 2.00),
    "engine_ops_per_sec": ("higher", 0.60),
    "engine_bulk_ops_per_sec": ("higher", 0.60),
    "engine_adapter_ops_per_sec": ("higher", 0.60),
    "engine_pipelined_ops_per_sec": ("higher", 0.60),
    "engine_sync_latency_ms": ("lower", 2.00),
    # Flight-recorder arming cost (PR 19): same-run on/off median
    # ratios on the bulk loop — box noise cancels, so they get the
    # tight ratio band next to ipc_span_overhead.
    "engine_capture_overhead_d0": ("lower", 0.30),
    "engine_capture_overhead_d2": ("lower", 0.30),
    "spec_ops_per_sec": ("higher", 0.60),
    "spec_entry_p50_us": ("lower", 2.00),
    "spec_entry_p99_us": ("lower", 5.00),
    "spec_entry_sys_p50_us": ("lower", 2.00),
    "spec_entry_sys_p99_us": ("lower", 5.00),
    "shed_entry_p50_us": ("lower", 2.00),
    "shed_entry_p99_us": ("lower", 5.00),
    "sketch_ops_per_sec_on": ("higher", 0.60),
    "sketch_ops_per_sec_off": ("higher", 0.60),
    # Storm latency includes real decay-window waits, so box noise is
    # a smaller share — but keep the same latency-class band.
    "sketch_promote_storm_ms": ("lower", 2.00),
    # Adapter batch-window matrix (bench `adapters` stage). The spine
    # ratio is a RATIO of two same-run numbers, so box noise largely
    # cancels — it gets a tighter band than raw throughputs.
    "adapters_gateway_bulk_ops_per_sec": ("higher", 0.60),
    "adapters_spine_on_ops_per_sec": ("higher", 0.60),
    "adapters_spine_vs_bulk": ("higher", 0.30),
    "adapters_wsgi_on_ops_per_sec": ("higher", 0.60),
    "adapters_wsgi_off_ops_per_sec": ("higher", 0.60),
    "adapters_wsgi_on_p50_us": ("lower", 2.00),
    "adapters_wsgi_on_p99_us": ("lower", 5.00),
    "adapters_asgi_on_ops_per_sec": ("higher", 0.60),
    "adapters_asgi_off_ops_per_sec": ("higher", 0.60),
    "adapters_asgi_on_p50_us": ("lower", 2.00),
    "adapters_asgi_on_p99_us": ("lower", 5.00),
    "adapters_aiohttp_on_ops_per_sec": ("higher", 0.60),
    "adapters_aiohttp_off_ops_per_sec": ("higher", 0.60),
    "adapters_aiohttp_on_p50_us": ("lower", 2.00),
    "adapters_aiohttp_on_p99_us": ("lower", 5.00),
    "adapters_grpc_on_ops_per_sec": ("higher", 0.60),
    "adapters_grpc_off_ops_per_sec": ("higher", 0.60),
    "adapters_grpc_on_p50_us": ("lower", 2.00),
    "adapters_grpc_on_p99_us": ("lower", 5.00),
    "adapters_flask_on_ops_per_sec": ("higher", 0.60),
    "adapters_flask_off_ops_per_sec": ("higher", 0.60),
    "adapters_flask_on_p50_us": ("lower", 2.00),
    "adapters_flask_on_p99_us": ("lower", 5.00),
    "adapters_fastapi_on_ops_per_sec": ("higher", 0.60),
    "adapters_fastapi_off_ops_per_sec": ("higher", 0.60),
    "adapters_fastapi_on_p50_us": ("lower", 2.00),
    "adapters_fastapi_on_p99_us": ("lower", 5.00),
    # Self-tuning stage (bench `autotune`). The vs-static ratio is a
    # RATIO of two same-run numbers (box noise largely cancels), so it
    # gets the tighter ratio-class band like adapters_spine_vs_bulk.
    "autotune_static_best_ops_per_sec": ("higher", 0.60),
    "autotune_steady_ops_per_sec": ("higher", 0.60),
    "autotune_vs_static_best": ("higher", 0.30),
    # Multi-process ingest plane (bench `ipc` stage). The vs-inproc
    # ratio is a RATIO of two same-run numbers (box noise largely
    # cancels) — tighter band like the other ratio metrics; on the
    # 1-core box it measures transport overhead (3 processes share one
    # CPU), on real hardware it is the scale-out headline.
    "ipc_workers_ops_per_sec": ("higher", 0.60),
    "ipc_inproc_ops_per_sec": ("higher", 0.60),
    "ipc_vs_inproc": ("higher", 0.30),
    "ipc_entry_p50_us": ("lower", 2.00),
    "ipc_entry_p99_us": ("lower", 5.00),
    # IPC fast path (PR 14): the adaptive-wakeup A/B and the worker
    # concurrency sweep. Speedup/amortization are same-run RATIOS
    # (box noise cancels) — tighter bands; frames-per-entry is a pure
    # protocol count, the steadiest metric in the file.
    "ipc_entry_adaptive_p50_us": ("lower", 2.00),
    "ipc_entry_adaptive_p99_us": ("lower", 5.00),
    "ipc_wakeup_speedup": ("higher", 0.30),
    # Engine hot-restart outage (supervised kill -9 → device-served
    # again): dominated by process cold boot (JAX import + first
    # compile) + dead-ms detection + restart backoff, so it gets the
    # widest band the gate allows — its job is catching a recovery
    # that stops converging, not a ±second of import time.
    "ipc_restart_outage_ms": ("lower", 5.00),
    # Warm-standby takeover + planned handoff (PR 20). The standby
    # outage is detection + attach (cold boot is off the outage path)
    # but still rides process scheduling on a shared box; the handoff
    # gap includes the old world's drain + final durable spill; the
    # warm-boot column is a JAX import + first compile — all wall-clock
    # process-lifecycle numbers, so they keep the widest band. Their
    # job is catching a takeover that regresses to cold-boot-dominated,
    # not a ±second of import time.
    "ipc_standby_outage_ms": ("lower", 5.00),
    "ipc_handoff_outage_ms": ("lower", 5.00),
    "ipc_standby_warm_boot_ms": ("lower", 5.00),
    "ipc_percall_w1_ops_per_sec": ("higher", 0.60),
    "ipc_percall_w2_ops_per_sec": ("higher", 0.60),
    "ipc_percall_w4_ops_per_sec": ("higher", 0.60),
    "ipc_window_w1_ops_per_sec": ("higher", 0.60),
    "ipc_window_w2_ops_per_sec": ("higher", 0.60),
    "ipc_window_w4_ops_per_sec": ("higher", 0.60),
    "ipc_frames_per_entry_window": ("lower", 0.50),
    "ipc_window_amortization": ("higher", 0.30),
    # Batched cluster token plane (PR 16, bench `cluster` stage).
    # Frames-per-op and lease hit rate are protocol COUNTS (steadiest
    # class in the file); amortization is a same-run ratio. The lease
    # frames/op band is wide in relative terms because the absolute
    # number is tiny (~0.004) and one extra renewal frame doubles it.
    "cluster_percall_ops_per_sec": ("higher", 0.60),
    "cluster_window_ops_per_sec": ("higher", 0.60),
    "cluster_lease_ops_per_sec": ("higher", 0.60),
    "cluster_frames_per_op_window": ("lower", 0.50),
    "cluster_frames_per_op_lease": ("lower", 2.00),
    "cluster_lease_hit_rate": ("higher", 0.30),
    "cluster_window_amortization": ("higher", 0.30),
    # Sharded token plane (PR 17, bench `cluster` shard sweep).
    # Frames-per-op and lease hit rates are protocol COUNTS; the
    # capacity ratio and parallel-issue fraction are same-run ratios
    # (box noise cancels) — ratio-class bands. Per-shard capacity is
    # a busy-clock rate, steadier than wall throughput but still on a
    # shared box, so it keeps the throughput band.
    "cluster_shard1_window_ops_per_sec": ("higher", 0.60),
    "cluster_shard2_window_ops_per_sec": ("higher", 0.60),
    "cluster_shard4_window_ops_per_sec": ("higher", 0.60),
    "cluster_shard1_lease_ops_per_sec": ("higher", 0.60),
    "cluster_shard2_lease_ops_per_sec": ("higher", 0.60),
    "cluster_shard4_lease_ops_per_sec": ("higher", 0.60),
    "cluster_shard1_window_frames_per_op": ("lower", 0.50),
    "cluster_shard2_window_frames_per_op": ("lower", 0.50),
    "cluster_shard4_window_frames_per_op": ("lower", 0.50),
    "cluster_shard1_lease_hit_rate": ("higher", 0.30),
    "cluster_shard2_lease_hit_rate": ("higher", 0.30),
    "cluster_shard4_lease_hit_rate": ("higher", 0.30),
    "cluster_shard1_capacity_per_sec": ("higher", 0.60),
    "cluster_shard2_capacity_per_sec": ("higher", 0.60),
    "cluster_shard4_capacity_per_sec": ("higher", 0.60),
    "cluster_shard4_parallel_issue": ("higher", 0.30),
    "cluster_shard_capacity_ratio_4x": ("higher", 0.30),
    # Gossip merge cost: one merge_remote + fleet-view query, pure
    # numpy in-process — latency-class band.
    "cluster_gossip_merge_ms": ("lower", 2.00),
    # Fleet span decomposition (PR 18). The span-derived percentiles
    # get the latency-class bands; the armed/unarmed p50 ratio and the
    # wire share are same-run RATIOS (box noise cancels) — overhead
    # must stay near 1.0, so it gets the tight ratio band.
    "ipc_span_e2e_p50_us": ("lower", 2.00),
    "ipc_span_e2e_p99_us": ("lower", 5.00),
    "ipc_span_drain_p50_us": ("lower", 2.00),
    "ipc_span_overhead": ("lower", 0.30),
    "cluster_rpc_p50_ms": ("lower", 2.00),
    "cluster_rpc_p99_ms": ("lower", 5.00),
    "cluster_serve_p50_ms": ("lower", 2.00),
}

# Host-identity token (PR 14): device_kind + jax_version cannot tell
# two different-speed VMs apart (the r09→r10 re-anchor hole). When
# BOTH records carry the measured host token (bench._host_identity),
# the cpu count must match and the spin calibration must agree within
# this ratio band for the baseline to be comparable; records predating
# the token keep matching on the hardware header alone.
HOST_SPIN_BAND = 2.5

# Stage-context keys: a group's metrics are comparable only when every
# context key present in EITHER record matches (a missing stage on one
# side skips the group, a different rung size skips it too).
STAGE_CONTEXT: List[Tuple[Tuple[str, ...], Tuple[str, ...]]] = [
    (("n_rules", "n_entries"), ("value", "flush_ms")),
    (("mixed_n_rules", "mixed_n_entries"),
     ("mixed_checks_per_sec", "mixed_flush_ms")),
    (("engine_n_rules", "engine_n_ops"),
     ("engine_ops_per_sec", "engine_bulk_ops_per_sec",
      "engine_adapter_ops_per_sec", "engine_pipelined_ops_per_sec",
      "engine_sync_latency_ms",
      "engine_capture_overhead_d0", "engine_capture_overhead_d2")),
    ((), ("spec_ops_per_sec", "spec_entry_p50_us", "spec_entry_p99_us",
          "spec_entry_sys_p50_us", "spec_entry_sys_p99_us",
          "shed_entry_p50_us", "shed_entry_p99_us")),
    (("sketch_n_ops",),
     ("sketch_ops_per_sec_on", "sketch_ops_per_sec_off",
      "sketch_promote_storm_ms")),
    (("adapters_n_ops",),
     tuple(
         m for m in STAGE_METRICS if m.startswith("adapters_")
     )),
    (("autotune_n_ops",),
     ("autotune_static_best_ops_per_sec", "autotune_steady_ops_per_sec",
      "autotune_vs_static_best")),
    (("ipc_n_ops", "ipc_n_workers"),
     ("ipc_workers_ops_per_sec", "ipc_inproc_ops_per_sec",
      "ipc_vs_inproc", "ipc_entry_p50_us", "ipc_entry_p99_us",
      "ipc_entry_adaptive_p50_us", "ipc_entry_adaptive_p99_us",
      "ipc_wakeup_speedup", "ipc_restart_outage_ms",
      "ipc_standby_outage_ms", "ipc_handoff_outage_ms",
      "ipc_standby_warm_boot_ms",
      "ipc_span_e2e_p50_us", "ipc_span_e2e_p99_us",
      "ipc_span_drain_p50_us", "ipc_span_overhead")),
    # The sweep carries its own rung key so a truncated/smoke run
    # never reads as a slowdown (and pre-PR-14 baselines, which lack
    # both the key and the metrics, simply don't compare here).
    (("ipc_sweep_quota",),
     ("ipc_percall_w1_ops_per_sec", "ipc_percall_w2_ops_per_sec",
      "ipc_percall_w4_ops_per_sec", "ipc_window_w1_ops_per_sec",
      "ipc_window_w2_ops_per_sec", "ipc_window_w4_ops_per_sec",
      "ipc_frames_per_entry_window", "ipc_window_amortization")),
    # Batched cluster token plane (PR 16): keyed on its own op count
    # so smoke runs and pre-PR-16 baselines never compare here.
    (("cluster_n_ops",),
     ("cluster_percall_ops_per_sec", "cluster_window_ops_per_sec",
      "cluster_lease_ops_per_sec", "cluster_frames_per_op_window",
      "cluster_frames_per_op_lease", "cluster_lease_hit_rate",
      "cluster_window_amortization",
      "cluster_rpc_p50_ms", "cluster_rpc_p99_ms",
      "cluster_serve_p50_ms")),
    # Shard sweep (PR 17): keyed on its own rung size so truncated
    # runs and pre-PR-17 baselines never compare here.
    (("cluster_shard_ops",),
     tuple(
         m for m in STAGE_METRICS
         if m.startswith("cluster_shard") or m == "cluster_gossip_merge_ms"
     )),
]


def load_record(path_or_obj) -> Optional[dict]:
    """A bench record from a path (or an already-loaded object):
    unwraps the driver's ``{"parsed": {...}}`` wrapper shape; None when
    unreadable/not a dict."""
    obj = path_or_obj
    if isinstance(obj, str):
        try:
            with open(obj, "r", encoding="utf-8") as f:
                obj = json.load(f)
        except (OSError, ValueError):
            return None
    if not isinstance(obj, dict):
        return None
    parsed = obj.get("parsed")
    if isinstance(parsed, dict):
        obj = parsed
    return obj


def host_mismatch(fresh: dict, baseline: dict) -> Optional[str]:
    """A reason string when the two records' measured host-identity
    tokens (``host_cpu_count`` + ``host_spin_ms``) say DIFFERENT
    boxes, else None. Records missing the token (pre-PR-14) are never
    mismatched — the hardware header is then the only identity we
    have, which is exactly the r09→r10 hole this closes going
    forward."""
    f_cpu, b_cpu = fresh.get("host_cpu_count"), baseline.get("host_cpu_count")
    f_spin, b_spin = fresh.get("host_spin_ms"), baseline.get("host_spin_ms")
    if not isinstance(f_spin, (int, float)) or not isinstance(
        b_spin, (int, float)
    ) or f_spin <= 0 or b_spin <= 0:
        return None
    if (
        isinstance(f_cpu, int) and isinstance(b_cpu, int)
        and f_cpu > 0 and b_cpu > 0 and f_cpu != b_cpu
    ):
        return f"host cpu count differs ({b_cpu} vs {f_cpu})"
    ratio = f_spin / b_spin
    if ratio > HOST_SPIN_BAND or ratio < 1.0 / HOST_SPIN_BAND:
        return (
            f"host speed token differs ({b_spin:g} ms vs {f_spin:g} ms "
            f"spin calibration, {ratio:.2f}x, band {HOST_SPIN_BAND:g}x)"
        )
    return None


def find_baseline(
    repo_root: str, device_kind, jax_version, fresh: Optional[dict] = None
) -> Tuple[Optional[str], Optional[dict], str]:
    """Newest committed BENCH_*.json matching the fresh run's hardware
    header AND host-identity token: ``(path, record, reason)`` —
    path/record None when nothing comparable exists, with the reason
    spelled out."""
    if not device_kind or not jax_version:
        return None, None, "fresh record lacks device_kind/jax_version"
    paths = sorted(glob.glob(os.path.join(repo_root, "BENCH_*.json")))
    seen = 0
    host_skipped: List[str] = []
    for path in reversed(paths):
        rec = load_record(path)
        if rec is None or "error" in rec:
            continue
        seen += 1
        if (
            rec.get("device_kind") == device_kind
            and rec.get("jax_version") == jax_version
        ):
            why = host_mismatch(fresh or {}, rec)
            if why is not None:
                host_skipped.append(f"{os.path.basename(path)}: {why}")
                continue
            if host_skipped and not isinstance(
                rec.get("host_spin_ms"), (int, float)
            ):
                # A NEWER same-header baseline's token already said
                # "different box" — falling back to an older token-less
                # record would re-open exactly the cross-box comparison
                # the token refuses (the pre-token record carries no
                # evidence it came from this box either).
                host_skipped.append(
                    f"{os.path.basename(path)}: pre-token record behind "
                    "a token mismatch"
                )
                continue
            return path, rec, ""
    if not paths:
        return None, None, f"no BENCH_*.json baselines under {repo_root}"
    if host_skipped:
        return (
            None, None,
            "hardware header matches but the measured host-identity "
            "token does not — cross-box comparison refused ("
            + "; ".join(host_skipped) + ")",
        )
    return (
        None, None,
        f"no baseline among {seen} readable BENCH_*.json matches "
        f"device_kind={device_kind!r} jax_version={jax_version!r} "
        "(pre-header records never match)",
    )


def compare(
    fresh: dict, baseline: dict, tolerance_scale: float = 1.0
) -> Tuple[List[str], List[str], List[str]]:
    """``(regressions, compared, skipped)`` message lists. A metric is
    compared when both records carry it numerically and its stage
    context matches; regression means worse than baseline by more than
    ``band × tolerance_scale``."""
    regressions: List[str] = []
    compared: List[str] = []
    skipped: List[str] = []
    for ctx_keys, metrics in STAGE_CONTEXT:
        ctx_mismatch = None
        for k in ctx_keys:
            if k in fresh or k in baseline:
                if fresh.get(k) != baseline.get(k):
                    ctx_mismatch = (
                        f"{k}: fresh={fresh.get(k)} vs "
                        f"baseline={baseline.get(k)}"
                    )
                    break
        for m in metrics:
            f, b = fresh.get(m), baseline.get(m)
            if not isinstance(f, (int, float)) or not isinstance(b, (int, float)):
                continue  # stage absent on one side: silently not comparable
            if ctx_mismatch is not None:
                skipped.append(f"{m}: stage context differs ({ctx_mismatch})")
                continue
            if b <= 0:
                skipped.append(f"{m}: baseline value {b} not comparable")
                continue
            direction, band = STAGE_METRICS[m]
            band = band * tolerance_scale
            ratio = f / b
            if direction == "higher":
                bad = ratio < 1.0 - band
                word = "dropped"
            else:
                bad = ratio > 1.0 + band
                word = "rose"
            line = (
                f"{m}: {word if bad else 'ok'} {b:g} -> {f:g} "
                f"({ratio:.3f}x, band ±{band:.0%})"
            )
            (regressions if bad else compared).append(line)
    return regressions, compared, skipped


def gate(
    fresh: dict,
    repo_root: str,
    baseline_path: Optional[str] = None,
    tolerance_scale: float = 1.0,
) -> int:
    """Run the gate and print the report; returns the exit status."""
    if not isinstance(fresh, dict) or "error" in fresh:
        print(f"benchgate FAILED: fresh record is an error record: "
              f"{fresh.get('error') if isinstance(fresh, dict) else fresh!r}")
        return 1
    if baseline_path is not None:
        baseline = load_record(baseline_path)
        if baseline is None:
            print(f"benchgate usage error: cannot load {baseline_path}")
            return 2
        # An explicit baseline still honors the hardware-truth header
        # and the measured host-identity token.
        if (
            baseline.get("device_kind") != fresh.get("device_kind")
            or baseline.get("jax_version") != fresh.get("jax_version")
        ):
            print(
                "benchgate SKIP: explicit baseline "
                f"{os.path.basename(baseline_path)} has device_kind="
                f"{baseline.get('device_kind')!r}/jax="
                f"{baseline.get('jax_version')!r}, fresh has "
                f"{fresh.get('device_kind')!r}/{fresh.get('jax_version')!r}"
            )
            return 0
        host_why = host_mismatch(fresh, baseline)
        if host_why is not None:
            print(
                "benchgate SKIP: explicit baseline "
                f"{os.path.basename(baseline_path)} is a different box — "
                f"{host_why}"
            )
            return 0
    else:
        baseline_path, baseline, reason = find_baseline(
            repo_root, fresh.get("device_kind"), fresh.get("jax_version"),
            fresh=fresh,
        )
        if baseline is None:
            print(f"benchgate SKIP: {reason}")
            return 0
    regressions, compared, skipped = compare(fresh, baseline, tolerance_scale)
    base_name = os.path.basename(baseline_path)
    for line in skipped:
        print(f"  skip {line}")
    for line in compared:
        print(f"  {line}")
    if regressions:
        print(f"benchgate FAILED vs {base_name}:")
        for line in regressions:
            print(f"  REGRESSION {line}")
        return 1
    if not compared:
        print(f"benchgate SKIP: no comparable stage metrics vs {base_name}")
        return 0
    print(
        f"benchgate OK vs {base_name}: {len(compared)} metrics within "
        f"band ({len(skipped)} skipped)"
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--fresh", required=True,
                    help="fresh bench JSON path, or - for stdin")
    ap.add_argument("--repo-root",
                    default=os.path.dirname(os.path.dirname(
                        os.path.abspath(__file__))))
    ap.add_argument("--baseline", default=None,
                    help="explicit baseline path (default: newest "
                         "matching BENCH_*.json)")
    ap.add_argument("--tolerance-scale", type=float, default=1.0)
    args = ap.parse_args()
    if args.fresh == "-":
        try:
            fresh = load_record(json.load(sys.stdin))
        except ValueError:
            fresh = None
    else:
        fresh = load_record(args.fresh)
    if fresh is None:
        print(f"benchgate usage error: cannot load fresh record "
              f"{args.fresh}")
        return 2
    return gate(fresh, args.repo_root, args.baseline, args.tolerance_scale)


if __name__ == "__main__":
    sys.exit(main())
