"""Bisect the k=2 slot-width cliff on TPU (PERF_NOTES.md round 3).

Round-3 measurement: the flags-off kernel stage at n=131072 entries ran
0.14 ms with k=1 slots but 392 ms with k=2 (second slot all -1) — a
2800x jump for doubling the flat [n*k] width, while CPU shows +8%. This
probe times each suspect in isolation so the cliff can be attributed:

  sortP   lax.sort with P operands over the [n*k] flat slots
  admis   flow_admission alone, k=1 vs k=2
  flush   flush_step_jit (flags off), k=1 vs k=2
  stats   the metric-array batched window update alone
  seg     the segment cumsum/cummax rank math alone
  sketch  the sketch-tier count-min/candidate fold alone (2 widths)

Run: python tools/k2probe.py [--platform cpu] [--n 131072]
Each stage prints one line; a final JSON summary goes to stdout.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def _time(fn, *args, iters=5, warmup=2, **kw):
    """Compile + ``warmup`` extra executions before timing: one warm
    call is not enough through the remote tunnel (a cold connection's
    per-dispatch overhead lingers past the first execution and skewed
    the round-4 k=1-vs-k=2 comparison — PERF_NOTES 'probe-order
    warm-up')."""
    import jax

    for _ in range(1 + warmup):
        out = fn(*args, **kw)
        jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--n", type=int, default=131072)
    ap.add_argument("--rules", type=int, default=1 << 20)
    ap.add_argument("--iters", type=int, default=5)
    ap.add_argument(
        "--seed-out", default=None, metavar="FILE",
        help="measure per-shape closed-vs-scan param-path flush timings"
             " and write a sentinel.tpu.autotune.param.seed.file JSON"
             " (the ParamPathMemo then starts committed instead of"
             " exploring)",
    )
    args = ap.parse_args()
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import _example_batch
    from sentinel_tpu.metrics.nodes import make_stats
    from sentinel_tpu.rules.degrade_table import DegradeIndex
    from sentinel_tpu.rules.flow_table import FlowRuleDynState, FlowTableDevice
    from sentinel_tpu.rules.param_table import make_param_state
    from sentinel_tpu.runtime import flush as F
    from sentinel_tpu.runtime.flush import SystemDevice, flush_step_jit

    n, nr = args.n, args.rules
    results: dict[str, float] = {"platform": jax.default_backend(), "n": n}
    rng = np.random.default_rng(0)

    def report(name: str, dt: float) -> None:
        results[name] = round(dt * 1e3, 4)
        print(f"[k2probe] {name}: {dt * 1e3:.3f} ms", file=sys.stderr, flush=True)
        # Incremental partial line after every stage: tunnel liveness
        # windows can close mid-run (round 4: a wedge ate two full runs
        # that had produced zero output).
        print(json.dumps(results), file=sys.stderr, flush=True)

    # --- the headline cliff FIRST: full flags-off kernel + admission ---
    # (most valuable number if the tunnel wedges mid-run)
    dev = FlowTableDevice(
        grade=jnp.ones(nr, dtype=jnp.int32),
        count=jnp.full(nr, 20.0, dtype=jnp.float32),
        behavior=jnp.zeros(nr, dtype=jnp.int32),
        max_queueing_time_ms=jnp.zeros(nr, dtype=jnp.int32),
        cost1_ms=jnp.full(nr, 50, dtype=jnp.int32),
        warmup_warning_token=jnp.zeros(nr, dtype=jnp.int32),
        warmup_max_token=jnp.zeros(nr, dtype=jnp.int32),
        warmup_slope=jnp.zeros(nr, dtype=jnp.float32),
        warmup_refill_threshold=jnp.zeros(nr, dtype=jnp.int32),
    )
    dindex = DegradeIndex([])
    inf = float("inf")
    sysdev = SystemDevice(
        qps=jnp.float32(inf), max_thread=jnp.float32(inf), max_rt=jnp.float32(inf),
        load_threshold=jnp.float32(-1.0), cpu_threshold=jnp.float32(-1.0),
        cur_load=jnp.float32(-1.0), cur_cpu=jnp.float32(-1.0),
    )
    flags = dict(
        with_occupy=False, with_system=False, with_degrade=False, with_exits=False
    )
    stats = make_stats(nr)
    # Clean warmed A/B: compile + warm BOTH k's fully before timing
    # either, so neither absorbs cold-connection dispatch overhead (the
    # round-4 confound where the first-run k read slower).
    flush_states = {}
    for k in (1, 2):
        batch = _example_batch(n, nr, nr, k)
        s = {
            "batch": batch,
            "st": make_stats(nr),
            "dyn": FlowRuleDynState(
                latest_passed_time=jnp.full(nr, -(10**9), dtype=jnp.int32),
                stored_tokens=jnp.zeros(nr, dtype=jnp.float32),
                last_filled_time=jnp.full(nr, -(10**9), dtype=jnp.int32),
            ),
            "ddyn": dindex.make_dyn_state(),
            "pdyn": make_param_state(8),
        }
        flush_states[k] = s

    def _flush_once(s):
        out = flush_step_jit(
            s["st"], dev, s["dyn"], dindex.device, s["ddyn"], s["pdyn"],
            sysdev, s["batch"], **flags
        )
        s["st"], s["dyn"], s["ddyn"], s["pdyn"], _sk, res = out
        return res

    for k in (1, 2):
        t0 = time.perf_counter()
        jax.block_until_ready(_flush_once(flush_states[k]).admitted)
        dt = time.perf_counter() - t0
        # Into results, not just stderr: a wedge during the (long) k=2
        # compile must still leave a salvageable partial line.
        report(f"flush_k{k}_compile", dt)  # report() renders ms
        print(f"[k2probe] flush_k{k} compile+first {dt:.1f}s",
              file=sys.stderr, flush=True)
        for _ in range(2):  # extra warm executions per k
            jax.block_until_ready(_flush_once(flush_states[k]).admitted)
    print(json.dumps(results), flush=True)  # partial: warm phase done
    for k in (1, 2):
        t0 = time.perf_counter()
        for _ in range(args.iters):
            res = _flush_once(flush_states[k])
        jax.block_until_ready(res.admitted)
        report(f"flush_k{k}", (time.perf_counter() - t0) / args.iters)

    admis = jax.jit(
        lambda stats, dev, batch: F.flow_admission(
            stats, dev, batch, with_occupy=False
        )
    )
    admis_batches = {k: flush_states[k]["batch"] for k in (1, 2)}
    for k in (1, 2):  # warm both before timing either
        jax.block_until_ready(admis(stats, dev, admis_batches[k]))
        jax.block_until_ready(admis(stats, dev, admis_batches[k]))
    for k in (1, 2):
        report(
            f"admis_k{k}",
            _time(admis, stats, dev, admis_batches[k], iters=args.iters, warmup=0),
        )
    # Both k's device states are no longer needed; holding them through
    # the sort/seg/stats stages would pin ~2 extra StatsStates of HBM.
    del flush_states, admis_batches

    # --- sync vs pipelined engine flush (depth-K dispatch overlap) ----
    # The same bulk window through Engine.flush() at pipeline depth 0
    # (dispatch + fetch per flush) vs depth 2 (fetch deferred, one
    # coalesced device_get per drain): on a remote-tunnel backend the
    # gap is the per-flush fetch RTT the pipeline hides. Warm both
    # depths fully before timing either (probe-order warm-up).
    try:
        from sentinel_tpu.models.rules import FlowRule
        from sentinel_tpu.runtime.engine import Engine

        eng = Engine(initial_rows=4096)
        eng.set_flow_rules([FlowRule(resource=f"p{i}", count=1e9)
                            for i in range(64)])
        pipe_n = min(n, 1 << 14)

        def _window(depth):
            eng.pipeline_depth = depth
            for i in range(8):
                eng.submit_bulk(f"p{i}", pipe_n // 8)
            eng.flush()
            eng.drain()

        for depth in (0, 2):  # warm both before timing either
            _window(depth)
            _window(depth)
        for depth in (0, 2):
            eng.pipeline_depth = depth
            t0 = time.perf_counter()
            for _ in range(args.iters):
                for i in range(8):
                    eng.submit_bulk(f"p{i}", pipe_n // 8)
                eng.flush()
            eng.drain()
            report(
                f"engine_flush_depth{depth}",
                (time.perf_counter() - t0) / args.iters,
            )
        eng.pipeline_depth = 0
    except Exception as exc:  # engine drift — report, keep probing
        print(f"[k2probe] engine pipeline stage skipped: {exc}",
              file=sys.stderr)

    # --- speculative single-entry admission (host fast tier) ----------
    # entry_sync with the speculative tier on: the verdict comes from
    # the host mirror, no device round-trip on the timed path (the
    # settle flush runs between timed batches). p50/p99 wall per entry
    # — the sub-100 µs per-request story vs engine_flush_depth0's
    # multi-ms device round-trip.
    try:
        from sentinel_tpu.models.rules import FlowRule
        from sentinel_tpu.runtime.engine import Engine
        from sentinel_tpu.utils.config import config as _cfg

        _cfg.set(_cfg.SPECULATIVE_ENABLED, "true")
        _cfg.set(_cfg.SPECULATIVE_FLUSH_BATCH, "100000")
        try:
            seng = Engine(initial_rows=1024)
            seng.set_flow_rules(
                [FlowRule(resource=f"s{i}", count=1e9) for i in range(8)]
            )
            for i in range(64):
                seng.entry_sync(f"s{i % 8}")
            seng.flush()  # warm settle shape
            lats = []
            for r in range(args.iters):
                for i in range(512):
                    t0 = time.perf_counter()
                    seng.entry_sync(f"s{i % 8}")
                    lats.append(time.perf_counter() - t0)
                seng.flush()  # settle + reconcile between timed batches
            seng.drain()
            lats.sort()
            report("spec_entry_p50", lats[len(lats) // 2])
            report("spec_entry_p99", lats[int(len(lats) * 0.99)])

            # System gate overhead (PR 7): the same host fast path with
            # a wide-open system rule configured — the delta vs
            # spec_entry_* is the gate's per-entry cost.
            from sentinel_tpu.models import constants as _C
            from sentinel_tpu.rules.system_manager import SystemConfig

            seng.set_system_config(SystemConfig(qps=1e12))
            lats = []
            for r in range(args.iters):
                for i in range(512):
                    t0 = time.perf_counter()
                    seng.entry_sync(f"s{i % 8}", entry_type=_C.EntryType.IN)
                    lats.append(time.perf_counter() - t0)
                seng.flush()
            seng.drain()
            seng.set_system_config(None)
            lats.sort()
            report("spec_entry_sys_p50", lats[len(lats) // 2])
            report("spec_entry_sys_p99", lats[int(len(lats) * 0.99)])

            # Ingest shed fast path (PR 7): verdict latency when the
            # valve sheds — the under-saturation floor.
            from sentinel_tpu.runtime.ingest import IngestValve

            _cfg.set(_cfg.INGEST_DEADLINE_MS, "1")
            seng.ingest = IngestValve(seng)
            seng.ingest.force_latency_ms(1000.0)
            lats = []
            for i in range(2048):
                t0 = time.perf_counter()
                seng.entry_sync(f"s{i % 8}")
                lats.append(time.perf_counter() - t0)
            _cfg.set(_cfg.INGEST_DEADLINE_MS, "0")
            seng.ingest = IngestValve(seng)
            lats.sort()
            report("shed_entry_p50", lats[len(lats) // 2])
            report("shed_entry_p99", lats[int(len(lats) * 0.99)])
        finally:
            _cfg.set(_cfg.SPECULATIVE_ENABLED, "false")
            _cfg.set(_cfg.INGEST_DEADLINE_MS, "0")
    except Exception as exc:
        print(f"[k2probe] speculative stage skipped: {exc}", file=sys.stderr)

    # --- param path closed-vs-scan shape sweep (--seed-out) ------------
    # Times the SAME closed-form-eligible param batch through both
    # arms of the autotuner's cost memo (engine.param_force_path pins
    # the pick) at the memo's own bucket axes — (pow2 rows, ts
    # segments) — and emits the seed file ParamPathMemo.seed() loads at
    # engine start (sentinel.tpu.autotune.param.seed.file).
    try:
        from sentinel_tpu.models.rules import ParamFlowRule
        from sentinel_tpu.runtime.autotune import ParamPathMemo
        from sentinel_tpu.runtime.engine import Engine

        peng = Engine(initial_rows=1024)
        peng.set_param_rules(
            {"pp": [ParamFlowRule(resource="pp", param_idx=0, count=1e9)]}
        )
        seed_buckets = []
        shapes = [(256, 1), (256, 2), (2048, 1), (2048, 2), (2048, 4)]

        def _param_flush(n_items: int, nseg: int) -> None:
            base = peng.clock.now_ms()
            ts_col = np.asarray(
                [base + (i % nseg) for i in range(n_items)], dtype=np.int64
            )
            peng.submit_bulk(
                "pp", n_items, ts=ts_col - base,
                args_column=[(f"v{i % 64}",) for i in range(n_items)],
            )
            peng.flush()
            peng.drain()

        for n_items, nseg in shapes:
            timings = {}
            for path in ("closed", "scan"):
                peng.param_force_path = path
                _param_flush(n_items, nseg)  # warm/compile this arm
                t0 = time.perf_counter()
                for _ in range(args.iters):
                    _param_flush(n_items, nseg)
                timings[path] = (
                    (time.perf_counter() - t0) / args.iters * 1e3
                )
            peng.param_force_path = None
            bucket = ParamPathMemo.bucket_of(n_items, nseg)
            seed_buckets.append(
                {
                    "rows_bucket": bucket[0],
                    "segments": bucket[1],
                    "closed_ms": round(timings["closed"], 4),
                    "scan_ms": round(timings["scan"], 4),
                }
            )
            report(f"param_closed_n{n_items}_s{nseg}", timings["closed"] / 1e3)
            report(f"param_scan_n{n_items}_s{nseg}", timings["scan"] / 1e3)
        peng.close()
        if args.seed_out:
            seed = {
                "format": "sentinel-param-seed-v1",
                "platform": results["platform"],
                "jax_version": jax.__version__,
                "buckets": seed_buckets,
            }
            with open(args.seed_out, "w", encoding="utf-8") as f:
                json.dump(seed, f, indent=1)
            print(f"[k2probe] seed file written: {args.seed_out}",
                  file=sys.stderr, flush=True)
    except Exception as exc:
        print(f"[k2probe] param-path stage skipped: {exc}", file=sys.stderr)

    # --- ipc plane round trip (sentinel_tpu/ipc) -----------------------
    # One in-process worker client against a live plane: entry()
    # shared-memory round-trip latency (frame encode -> ring -> plane
    # decode -> columnar submit -> verdict frame back), the per-request
    # cost a GIL-bound front-end worker pays to ride the one engine.
    try:
        from sentinel_tpu.ipc.plane import IngestPlane
        from sentinel_tpu.ipc.worker import IngestClient
        from sentinel_tpu.models.rules import FlowRule
        from sentinel_tpu.runtime.engine import Engine
        from sentinel_tpu.utils.config import config as _cfg

        _cfg.set(_cfg.SPECULATIVE_ENABLED, "true")
        _cfg.set(_cfg.SPECULATIVE_FLUSH_BATCH, "100000")
        try:
            ieng = Engine(initial_rows=1024)
            ieng.set_flow_rules(
                [FlowRule(resource=f"i{i}", count=1e9) for i in range(8)]
            )
            plane = IngestPlane(ieng)
            cli = IngestClient(plane.channel(0), 0)
            for i in range(64):  # warm the settle shape + intern tables
                cli.entry(f"i{i % 8}")
            lats = []
            for _ in range(args.iters):
                for i in range(256):
                    t0 = time.perf_counter()
                    cli.entry(f"i{i % 8}")
                    lats.append(time.perf_counter() - t0)
                ieng.flush()
            lats.sort()
            report("ipc_entry_p50", lats[len(lats) // 2])
            report("ipc_entry_p99", lats[int(len(lats) * 0.99)])
            cli.close()
            plane.close()

            # Adaptive wakeups (spin-then-park ring waits) on the same
            # engine: the round-trip floor without the two sleep-poll
            # wake quanta. Fresh plane — doorbells exist only when it
            # is built under wakeup=adaptive.
            _cfg.set(_cfg.IPC_WAKEUP, "adaptive")
            try:
                plane = IngestPlane(ieng)
                cli = IngestClient(plane.channel(0), 0)
                for i in range(64):
                    cli.entry(f"i{i % 8}")
                lats = []
                for _ in range(args.iters):
                    for i in range(256):
                        t0 = time.perf_counter()
                        cli.entry(f"i{i % 8}")
                        lats.append(time.perf_counter() - t0)
                    ieng.flush()
                lats.sort()
                report("ipc_entry_adaptive_p50", lats[len(lats) // 2])
                report("ipc_entry_adaptive_p99",
                       lats[int(len(lats) * 0.99)])
                cli.close()
                plane.close()
            finally:
                _cfg.set(_cfg.IPC_WAKEUP, "sleep")
            ieng.close()
        finally:
            _cfg.set(_cfg.SPECULATIVE_ENABLED, "false")
    except Exception as exc:
        print(f"[k2probe] ipc stage skipped: {exc}", file=sys.stderr)

    # --- fleet span stamp cost (metrics/spans.py) ----------------------
    # One armed SpanJournal.record() as the admission call sites issue
    # it — two wall_ms reads bracketing the span plus the dict build +
    # locked ring append — and the disabled path's single bool read.
    # Reported in ns per stamp (not ms): these are the numbers the
    # ≤2% armed-overhead budget is built from, far below report()'s
    # ms resolution.
    try:
        from sentinel_tpu.metrics.spans import SpanJournal
        from sentinel_tpu.metrics.spans import wall_ms as _wms

        spj = SpanJournal(role="probe", enabled=True, ring=8192,
                          spill_every=0)
        n_st = 20000

        def _stamp(i: int) -> None:
            t0s = _wms()
            spj.record("probe", "worker", t0s, _wms() - t0s,
                       wid=0, seq=i, push_ms=0.01, v=t0s, win=1, adm=1)

        for i in range(2048):  # warm the deque + dict shapes
            _stamp(i)
        t0 = time.perf_counter()
        for i in range(n_st):
            _stamp(i)
        armed_ns = (time.perf_counter() - t0) / n_st * 1e9
        results["span_stamp_ns"] = round(armed_ns, 1)
        print(f"[k2probe] span_stamp_ns: {armed_ns:.0f} ns",
              file=sys.stderr, flush=True)

        spj.enabled = False
        t0 = time.perf_counter()
        for i in range(n_st):
            if spj.enabled:
                _stamp(i)
        off_ns = (time.perf_counter() - t0) / n_st * 1e9
        results["span_disabled_ns"] = round(off_ns, 2)
        print(f"[k2probe] span_disabled_ns: {off_ns:.1f} ns",
              file=sys.stderr, flush=True)
        print(json.dumps(results), file=sys.stderr, flush=True)
    except Exception as exc:
        print(f"[k2probe] span stage skipped: {exc}", file=sys.stderr)

    # --- flight-recorder hook cost (runtime/capture.py) ----------------
    # The two numbers the capture ≤2%-armed budget is built from: the
    # ARMED per-flush hook cost (note_chunk columnar spill + the
    # note_verdicts fill, measured by wrapping the real hooks inside a
    # real bulk flush loop — reported in ms/flush and ns/row) and the
    # DISABLED path, which is one attribute-is-None read per flush.
    try:
        import shutil
        import tempfile

        from sentinel_tpu.models.rules import FlowRule
        from sentinel_tpu.runtime.capture import CaptureJournal
        from sentinel_tpu.runtime.engine import Engine

        cap_tmp = tempfile.mkdtemp(prefix="k2probe-cap-")
        ceng = Engine()
        ceng.set_flow_rules(
            [FlowRule(f"cap{i}", count=1e9) for i in range(16)]
        )
        cap = CaptureJournal(ceng, directory=cap_tmp)
        cap.segment_bytes = 1 << 30
        ceng.capture = cap
        hook_s = [0.0]
        orig_chunk, orig_verd = cap.note_chunk, cap.note_verdicts

        def _timed_chunk(*a, **kw):
            t0 = time.perf_counter()
            try:
                return orig_chunk(*a, **kw)
            finally:
                hook_s[0] += time.perf_counter() - t0

        def _timed_verd(*a, **kw):
            t0 = time.perf_counter()
            try:
                return orig_verd(*a, **kw)
            finally:
                hook_s[0] += time.perf_counter() - t0

        cap.note_chunk = _timed_chunk
        cap.note_verdicts = _timed_verd
        cap_rows = 16 * 1024

        def _cap_win():
            for i in range(16):
                ceng.submit_bulk(f"cap{i}", 1024)
            ceng.flush()
            ceng.drain()

        _cap_win()  # warm: interning + kernel shape + first segment
        hook_s[0] = 0.0
        n_fl = 10
        for _ in range(n_fl):
            _cap_win()
        armed_ms = hook_s[0] / n_fl * 1e3
        results["capture_hook_ms_per_flush"] = round(armed_ms, 3)
        results["capture_hook_ns_per_row"] = round(
            hook_s[0] / (n_fl * cap_rows) * 1e9, 1
        )
        print(
            f"[k2probe] capture_hook_ms_per_flush: {armed_ms:.3f} ms"
            f" ({results['capture_hook_ns_per_row']:.0f} ns/row)",
            file=sys.stderr, flush=True,
        )
        cap.note_chunk, cap.note_verdicts = orig_chunk, orig_verd
        cap.close()
        ceng.capture = None
        n_ck = 200000
        t0 = time.perf_counter()
        for _ in range(n_ck):
            if ceng.capture is not None:
                _cap_win()  # never taken
        off_ns = (time.perf_counter() - t0) / n_ck * 1e9
        results["capture_disabled_ns"] = round(off_ns, 2)
        print(f"[k2probe] capture_disabled_ns: {off_ns:.1f} ns",
              file=sys.stderr, flush=True)
        print(json.dumps(results), file=sys.stderr, flush=True)
        ceng.close()
        shutil.rmtree(cap_tmp, ignore_errors=True)
    except Exception as exc:
        print(f"[k2probe] capture stage skipped: {exc}", file=sys.stderr)

    # --- cluster token plane round trips (sentinel_tpu/cluster) --------
    # One real TCP server on loopback: the three wire stances a token
    # decision can take — per-call frame, 8-row batch frame (cost shown
    # PER DECISION), and a local lease admit (zero frames). The spread
    # between the three is the whole argument for the batched plane.
    try:
        from sentinel_tpu.cluster import (
            cluster_flow_rule_manager as _cfrm,
            cluster_server_config_manager as _cscm,
        )
        from sentinel_tpu.cluster.client import ClusterTokenClient
        from sentinel_tpu.cluster.server import SentinelTokenServer
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.models import constants as CC
        from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule
        from sentinel_tpu.utils.config import config as _ccfg

        _cfrm.clear()
        _cscm.load_global_flow_config(exceed_count=1.0, max_allowed_qps=1e12)
        _cfrm.load_rules(
            "default",
            [FlowRule(
                "k2c", count=1e9, cluster_mode=True,
                cluster_config=ClusterFlowConfig(
                    flow_id=77,
                    threshold_type=CC.FLOW_THRESHOLD_GLOBAL,
                ),
            )],
        )
        csrv = SentinelTokenServer(port=0, service=DefaultTokenService())
        csrv.start()
        try:
            ccli = ClusterTokenClient("127.0.0.1", csrv.port).start()
            try:
                for _ in range(32):  # warm the connection + service row
                    ccli.request_token(77, 1)
                n_rt = 256
                lats = []
                for _ in range(args.iters):
                    for _ in range(n_rt):
                        t0 = time.perf_counter()
                        ccli.request_token(77, 1)
                        lats.append(time.perf_counter() - t0)
                lats.sort()
                report("cluster_percall_p50", lats[len(lats) // 2])
                report("cluster_percall_p99", lats[int(len(lats) * 0.99)])

                rows8 = [(77, 1, 0)] * 8
                lats = []
                for _ in range(args.iters):
                    for _ in range(n_rt // 8):
                        t0 = time.perf_counter()
                        ccli.request_tokens_batch(rows8)
                        lats.append((time.perf_counter() - t0) / 8)
                lats.sort()
                report("cluster_batch8_per_decision_p50",
                       lats[len(lats) // 2])

                # Lease admit: plant a lease by hand (the client-side
                # admit path is what's being timed, not the grant).
                _ccfg.set(_ccfg.CLUSTER_LEASE_ENABLED, "true")
                try:
                    ccli._store_leases([(77, n_rt * args.iters + 64, 60_000)])
                    lats = []
                    for _ in range(args.iters):
                        for _ in range(n_rt):
                            t0 = time.perf_counter()
                            ccli.request_token(77, 1)
                            lats.append(time.perf_counter() - t0)
                    lats.sort()
                    report("cluster_lease_admit_p50",
                           lats[len(lats) // 2])
                finally:
                    _ccfg.set(_ccfg.CLUSTER_LEASE_ENABLED, "false")
            finally:
                ccli.stop()
        finally:
            csrv.stop()
            _cfrm.clear()
    except Exception as exc:
        print(f"[k2probe] cluster stage skipped: {exc}", file=sys.stderr)

    # --- sharded token plane round trips (cluster/shards.py) -----------
    # The fan-out tax in isolation: a 32-row batch through 1/2/4 real
    # loopback shards, cost shown PER DECISION (routing split + M
    # concurrent frames + verdict reassembly vs one frame), plus the
    # pure hash-route cost per row. The single-shard row doubles as
    # the shards=1-is-PR-16 baseline.
    try:
        from sentinel_tpu.cluster import (
            cluster_flow_rule_manager as _cfrm,
            cluster_server_config_manager as _cscm,
        )
        from sentinel_tpu.cluster.server import SentinelTokenServer
        from sentinel_tpu.cluster.shards import (
            ShardMap, ShardedTokenClient, shard_of,
        )
        from sentinel_tpu.cluster.token_service import DefaultTokenService
        from sentinel_tpu.models import constants as CC
        from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule

        _cfrm.clear()
        _cscm.load_global_flow_config(exceed_count=1.0, max_allowed_qps=1e12)
        sh_flows = list(range(900, 932))
        _cfrm.load_rules(
            "default",
            [FlowRule(
                "k2s%d" % f, count=1e9, cluster_mode=True,
                cluster_config=ClusterFlowConfig(
                    flow_id=f, threshold_type=CC.FLOW_THRESHOLD_GLOBAL,
                ),
            ) for f in sh_flows],
        )
        rows32 = [(sh_flows[i % len(sh_flows)], 1, False) for i in range(32)]
        for n_sh in (1, 2, 4):
            srvs = [
                SentinelTokenServer(
                    port=0, service=DefaultTokenService()
                ).start()
                for _ in range(n_sh)
            ]
            scli = ShardedTokenClient(
                ShardMap(0, [("127.0.0.1", s.port) for s in srvs])
            ).start()
            try:
                for _ in range(8):  # warm every shard connection
                    scli.request_tokens_batch(rows32)
                lats = []
                for _ in range(args.iters):
                    for _ in range(32):
                        t0 = time.perf_counter()
                        scli.request_tokens_batch(rows32)
                        lats.append((time.perf_counter() - t0) / 32)
                lats.sort()
                report(
                    f"cluster_shard{n_sh}_batch_per_decision_p50",
                    lats[len(lats) // 2],
                )
            finally:
                scli.stop()
                for s in srvs:
                    s.stop()
        # Pure routing cost: the crc32 hash-partition per row.
        t0 = time.perf_counter()
        for _ in range(args.iters):
            for f in sh_flows * 8:
                shard_of(f, 4)
        report(
            "cluster_shard_route_per_row",
            (time.perf_counter() - t0) / (args.iters * len(sh_flows) * 8),
        )
        _cfrm.clear()
    except Exception as exc:
        print(f"[k2probe] cluster_shard stage skipped: {exc}",
              file=sys.stderr)

    # --- sketch-tier fold in isolation (runtime/sketch.py) -------------
    # The count-min + candidate merge over a pow2 key batch, jitted
    # standalone at two widths — the marginal device cost one armed
    # flush pays on top of the main kernel.
    try:
        from sentinel_tpu.runtime.sketch import (
            SketchBatch, make_sketch_state, sketch_fold,
        )

        sk_n = min(8192, n)
        ids = jnp.asarray(
            rng.integers(0, 2**31 - 1, sk_n).astype(np.int32)
        )
        w = jnp.ones((sk_n,), dtype=jnp.int32)
        for width in (2048, 16384):
            st = make_sketch_state(4, width, 64)
            fold = jax.jit(lambda s, i, ww: sketch_fold(
                s, SketchBatch(ids=i, w=ww)
            ))
            report(
                f"sketch_fold_w{width}",
                _time(fold, st, ids, w, iters=args.iters),
            )
    except Exception as exc:
        print(f"[k2probe] sketch stage skipped: {exc}", file=sys.stderr)

    # --- isolated sorts over the flat slot array -----------------------
    for k in (1, 2):
        size = n * k
        row_key = jnp.asarray(rng.integers(0, nr, size).astype(np.int32))
        ts = jnp.asarray(rng.integers(0, 400, size).astype(np.int32))
        eidx = jnp.arange(size, dtype=jnp.int32) // k
        pos = jnp.arange(size, dtype=jnp.int32)

        s4 = jax.jit(lambda a, b, c, d: jax.lax.sort((a, b, c, d), num_keys=3))
        s3 = jax.jit(lambda a, b, c: jax.lax.sort((a, b, c), num_keys=2))
        s2 = jax.jit(lambda a, b: jax.lax.sort((a, b), num_keys=1))
        s1 = jax.jit(lambda a: jax.lax.sort((a,), num_keys=1))
        report(f"sort4_k{k}", _time(s4, row_key, ts, eidx, pos, iters=args.iters))
        report(f"sort3_k{k}", _time(s3, row_key, ts, pos, iters=args.iters))
        report(f"sort2_k{k}", _time(s2, row_key, pos, iters=args.iters))
        report(f"sort1_k{k}", _time(s1, row_key, iters=args.iters))

    # --- segment rank math alone ---------------------------------------
    for k in (1, 2):
        size = n * k
        rk_s = jnp.sort(jnp.asarray(rng.integers(0, nr, size).astype(np.int32)))
        acq = jnp.ones(size, dtype=jnp.int32)

        @jax.jit
        def seg(rk_s, acq):
            ones = jnp.ones((1,), dtype=bool)
            new_grp = jnp.concatenate([ones, rk_s[1:] != rk_s[:-1]])
            return F.segment_excl_cumsum(new_grp, acq)

        report(f"seg_k{k}", _time(seg, rk_s, acq, iters=args.iters))

    # --- stats window update alone -------------------------------------
    from sentinel_tpu.metrics import metric_array as ma
    from sentinel_tpu.metrics.nodes import SECOND_CFG

    stats = make_stats(nr)
    for k in (1, 2):
        size = n * k
        rows = jnp.asarray(rng.integers(0, nr, size).astype(np.int32))
        ts = jnp.asarray(rng.integers(0, 400, size).astype(np.int32))
        deltas = jnp.ones((size, 1), dtype=jnp.int32) * jnp.ones(
            (1, F.NUM_EVENTS), dtype=jnp.int32
        )

        @jax.jit
        def upd(second, rows, ts, deltas):
            return ma.update(SECOND_CFG, second, rows, ts, deltas)

        try:
            report(
                f"stats_k{k}",
                _time(upd, stats.second, rows, ts, deltas, iters=args.iters),
            )
        except Exception as exc:  # signature drift — report, keep going
            print(f"[k2probe] stats_k{k} skipped: {exc}", file=sys.stderr)
            break

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
