#!/usr/bin/env python
"""One-line N-process worker deployment for the multi-process ingest
plane (sentinel_tpu/ipc): the CLI face of ``api.run_workers``.

The parent process owns the engine (and the plane); each worker process
runs in ipc worker mode (``sentinel.tpu.ipc.worker.mode``) — the whole
``api.entry`` surface, and therefore every adapter, rides its
IngestClient to the engine through the shared-memory rings. Serving a
WSGI app from N processes is one line::

    python tools/ipc_launch.py myservice:app --workers 4 --port 8080

Worker ``i`` binds ``port + i`` (put nginx/envoy in front, exactly like
gunicorn's ``--workers``). ``--client-window-ms`` arms the worker-side
micro-window, ``--wakeup adaptive`` the spin-then-park ring waits; both
replay into the children automatically.

``--supervise`` runs the ENGINE in a supervised child process on named
shared-memory rings (sentinel_tpu/ipc/supervise.py): a crashed engine
restarts on the shared Backoff and re-attaches to the EXISTING rings —
workers ride out the outage on the failover-policy snapshot, then
re-assert their live-admission ledgers and resume device-backed
verdicts. With ``sentinel.tpu.failover.checkpoint.path`` set the new
engine warm-starts from the durable checkpoint.

``--smoke`` runs the self-test used by tools/ci_check.sh: (1) two
spawned workers serve a built-in WSGI app in-process (no sockets), the
parent asserts the requests were admitted by the engine; (2) a
supervised engine is ``kill -9``'d mid-probing and must come back on
the same rings with the probing client reconnected — the whole
engine-restart path (epoch bump → re-intern → ledger re-assert →
device verdicts again) in one bounded cycle.
"""

from __future__ import annotations

import argparse
import importlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_app(spec: str):
    mod, _, attr = spec.partition(":")
    m = importlib.import_module(mod)
    return getattr(m, attr or "app")


def _demo_app(environ, start_response):
    start_response("200 OK", [("Content-Type", "text/plain")])
    return [b"ok\n"]


def serve_wsgi(worker_id: int, spec: str, port: int, wrap: bool) -> None:
    """Worker target: serve the WSGI app on ``port + worker_id``.
    Top-level so multiprocessing spawn children import it by name."""
    from wsgiref.simple_server import make_server

    from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

    app = _demo_app if spec == "-" else _load_app(spec)
    if wrap:
        app = SentinelWSGIMiddleware(app)
    srv = make_server("127.0.0.1", port + worker_id, app)
    print(f"[ipc_launch] worker {worker_id} serving on "
          f"http://127.0.0.1:{port + worker_id}", flush=True)
    srv.serve_forever()


def smoke_engine_setup(engine) -> None:
    """Supervised-engine setup (top-level so spawn children import it
    by name): the wide-open rule the smoke probes against."""
    from sentinel_tpu.models.rules import FlowRule

    engine.set_flow_rules([FlowRule(resource="web-total", count=1e9)])


def smoke_worker(worker_id: int, n_requests: int, q) -> None:
    """Smoke target: drive the built-in app through the WSGI adapter
    in-process (no sockets) and report the statuses."""
    from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

    app = SentinelWSGIMiddleware(_demo_app, total_resource="web-total")
    statuses = []

    def start_response(status, headers):
        statuses.append(status)

    for i in range(n_requests):
        environ = {"PATH_INFO": f"/smoke/{i % 4}", "REQUEST_METHOD": "GET"}
        body = b"".join(app(environ, start_response))
        assert body == b"ok\n", body
    q.put((worker_id, statuses))


def _smoke(n_workers: int = 2, n_requests: int = 8) -> int:
    from sentinel_tpu.core import api
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.rules.flow_manager import flow_rule_manager
    from sentinel_tpu.utils.config import config

    # The smoke pins the TRANSPORT path — generous liveness thresholds
    # so a loaded box (first compiles take seconds, heartbeat threads
    # starve) doesn't fake engine/worker death and pass the run through
    # the policy fallback instead. run_workers replays these into the
    # children.
    config.set(config.IPC_ENGINE_DEAD_MS, "60000")
    config.set(config.IPC_WORKER_DEAD_MS, "60000")
    config.set(config.IPC_TIMEOUT_MS, "120000")
    eng = api.get_engine()
    flow_rule_manager.load_rules(
        [FlowRule(resource="web-total", count=1e9)]
    )
    plane = None
    try:
        q = None
        ws = None
        # run_workers builds the plane; grab its spawn context for the
        # result queue AFTER so the queue comes from the same context.
        from sentinel_tpu.ipc.plane import IngestPlane

        plane = eng.ipc_plane or IngestPlane(eng)
        q = plane.spawn_context().Queue()
        ws = api.run_workers(
            smoke_worker, n=n_workers, args=(n_requests, q), engine=eng
        )
        seen = 0
        while seen < n_workers:
            wid, statuses = q.get(timeout=180)
            assert len(statuses) == n_requests, statuses
            assert all(s == "200 OK" for s in statuses), statuses
            seen += 1
        ws.join(timeout=30)
        # Poll, don't snapshot-and-assert: on a loaded box the drainer
        # can still be inside a first-compile flush with the whole run
        # queued in the ring (policy-served callers don't wait for it),
        # and the gauge drain for policy-served admissions rides the
        # dead-worker reap after the workers exit.
        import time

        want = n_workers * n_requests
        served = 0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            served = plane.snapshot()["counters"]["requests"]
            if served >= want:
                break
            time.sleep(0.25)
        assert served >= want, plane.snapshot()
        stats = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("web-total")
            if stats["cur_thread_num"] == 0:
                break
            time.sleep(0.25)
        assert stats is not None and stats["cur_thread_num"] == 0, stats
        print(f"[ipc_launch] smoke OK: {n_workers} workers x "
              f"{n_requests} requests, {served} plane requests, "
              f"gauges drained to 0")
        return 0
    finally:
        if plane is not None:
            plane.close()
        eng.close()


def _smoke_restart() -> int:
    """Smoke phase 2: the engine failure-recovery loop end-to-end —
    supervised engine up, probing client on the rings, ``kill -9`` the
    engine child, assert the supervisor brings a new engine up on the
    SAME rings, the client reconnects (ledger re-assert) and resumes
    device-backed verdicts within a bounded outage."""
    import os
    import tempfile

    from sentinel_tpu.ipc.supervise import measure_restart_outage
    from sentinel_tpu.utils.config import config

    # Snappy-but-safe liveness settings for a loaded CI box: the engine
    # child pays the full JAX import + first compile on boot.
    config.set(config.IPC_HEARTBEAT_MS, "50")
    config.set(config.IPC_ENGINE_DEAD_MS, "2000")
    config.set(config.IPC_WORKER_DEAD_MS, "60000")
    config.set(config.SUPERVISE_BACKOFF_MS, "200")
    config.set(config.FAILOVER_ENABLED, "true")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, "2")
    ckpt_dir = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    ckpt = os.path.join(ckpt_dir, f"stpu-smoke-ckpt-{os.getpid()}.bin")
    config.set(config.FAILOVER_CKPT_PATH, ckpt)
    try:
        out = measure_restart_outage(
            smoke_engine_setup, "web-total", timeout_s=240
        )
        assert out["restarts"] >= 1, out
        assert out["reconnects"] >= 1, out
        print(
            f"[ipc_launch] restart smoke OK: outage "
            f"{out['outage_ms']:.0f} ms, {out['policy_served']} "
            f"policy-served probes, {out['restarts']} restart(s), "
            f"{out['reconnects']} client reconnect(s)"
        )
        return 0
    finally:
        try:
            os.unlink(ckpt)
        except OSError:
            pass


def _smoke_standby() -> int:
    """Smoke phase 3: the zero-outage lifecycle — a warm standby
    takeover after ``kill -9`` (outage bounded by the detection window,
    not a cold boot) followed by a planned handoff cycle that completes
    with ZERO policy-served verdicts (callers held, never failed)."""
    import os
    import tempfile

    from sentinel_tpu.ipc.supervise import (
        measure_handoff_outage,
        measure_standby_outage,
    )
    from sentinel_tpu.utils.config import config

    config.set(config.IPC_HEARTBEAT_MS, "50")
    config.set(config.IPC_ENGINE_DEAD_MS, "2000")
    config.set(config.IPC_ENGINE_DEAD_CONFIRM_MS, "1000")
    config.set(config.IPC_WORKER_DEAD_MS, "60000")
    config.set(config.IPC_HANDOFF_WAIT_MS, "30000")
    config.set(config.SUPERVISE_BACKOFF_MS, "200")
    config.set(config.SUPERVISE_STANDBY, "true")
    config.set(config.SUPERVISE_STANDBY_WARM_MS, "500")
    config.set(config.FAILOVER_ENABLED, "true")
    config.set(config.FAILOVER_CHECKPOINT_EVERY, "2")
    ckpt_dir = "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()
    ckpt = os.path.join(ckpt_dir, f"stpu-smoke-sb-{os.getpid()}.bin")
    config.set(config.FAILOVER_CKPT_PATH, ckpt)
    try:
        out = measure_standby_outage(
            smoke_engine_setup, "web-total", timeout_s=240
        )
        assert out["standby_takeovers"] >= 1, out
        assert out["restarts"] == 0, out  # takeover, not cold respawn
        print(
            f"[ipc_launch] standby smoke OK: outage "
            f"{out['outage_ms']:.0f} ms (warm boot "
            f"{out['standby_warm_boot_ms']:.0f} ms off the outage "
            f"path), {out['policy_served']} policy-served probes, "
            f"{out['standby_takeovers']} takeover(s)"
        )
        out = measure_handoff_outage(
            smoke_engine_setup, "web-total", timeout_s=240
        )
        assert out["handoffs"] >= 1, out
        assert out["policy_served"] == 0, out
        assert out["not_admitted"] == 0, out
        print(
            f"[ipc_launch] handoff smoke OK: worst verdict gap "
            f"{out['handoff_outage_ms']:.0f} ms, 0 policy-served, "
            f"{out['handoffs']} handoff(s)"
        )
        return 0
    finally:
        try:
            os.unlink(ckpt)
        except OSError:
            pass


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("app", nargs="?", default="-",
                    help="WSGI app as module:attr ('-' = built-in demo app)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--no-wrap", action="store_true",
                    help="app is already Sentinel-wrapped")
    ap.add_argument("--client-window-ms", type=float, default=None,
                    help="arm the worker-side micro-window")
    ap.add_argument("--wakeup", choices=("sleep", "adaptive"), default=None)
    ap.add_argument("--supervise", action="store_true",
                    help="run the engine in a supervised child process "
                         "(auto-restart on crash; workers ride out the "
                         "outage on the policy snapshot and reconnect)")
    ap.add_argument("--setup", default=None,
                    help="module:fn loading rules in the supervised "
                         "engine child (called as fn(engine))")
    ap.add_argument("--smoke", action="store_true",
                    help="run the ci_check worker-mode + engine-restart "
                         "+ standby/handoff self-test and exit")
    args = ap.parse_args()

    from sentinel_tpu.utils.config import config

    if args.client_window_ms is not None:
        config.set(config.IPC_CLIENT_WINDOW_MS, str(args.client_window_ms))
    if args.wakeup is not None:
        config.set(config.IPC_WAKEUP, args.wakeup)
    if args.smoke:
        rc = _smoke(n_workers=min(2, max(1, args.workers)))
        if rc:
            return rc
        rc = _smoke_restart()
        if rc:
            return rc
        return _smoke_standby()

    from sentinel_tpu.core import api

    if args.supervise:
        import time

        setup = _load_app(args.setup) if args.setup else None
        sup = api.run_engine_supervised(setup=setup, n_workers=args.workers)
        procs = [
            sup.spawn_worker(
                serve_wsgi, wid, (args.app, args.port, not args.no_wrap)
            )
            for wid in range(args.workers)
        ]
        print(f"[ipc_launch] supervised engine up (pid {sup.engine_pid()}), "
              f"{len(procs)} workers (ports {args.port}.."
              f"{args.port + args.workers - 1}); Ctrl-C stops", flush=True)
        seen_restarts = 0
        try:
            while True:
                time.sleep(1.0)
                if sup.restarts != seen_restarts:
                    seen_restarts = sup.restarts
                    print(f"[ipc_launch] engine restarted "
                          f"(#{seen_restarts}, pid {sup.engine_pid()})",
                          flush=True)
                if sup.gave_up:
                    print("[ipc_launch] supervisor gave up (restart "
                          "budget spent)", flush=True)
                    return 1
        except KeyboardInterrupt:
            print("[ipc_launch] stopping", flush=True)
        finally:
            for p in procs:
                if p.is_alive():
                    p.terminate()
            for p in procs:
                p.join(5.0)
            sup.stop()
        return 0

    eng = api.get_engine()
    ws = api.run_workers(
        serve_wsgi, n=args.workers,
        args=(args.app, args.port, not args.no_wrap), engine=eng,
    )
    print(f"[ipc_launch] {len(ws)} workers up (ports {args.port}.."
          f"{args.port + args.workers - 1}); Ctrl-C stops", flush=True)
    try:
        ws.join()
    except KeyboardInterrupt:
        print("[ipc_launch] stopping workers", flush=True)
        ws.stop()
    finally:
        eng.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
