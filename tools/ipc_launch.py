#!/usr/bin/env python
"""One-line N-process worker deployment for the multi-process ingest
plane (sentinel_tpu/ipc): the CLI face of ``api.run_workers``.

The parent process owns the engine (and the plane); each worker process
runs in ipc worker mode (``sentinel.tpu.ipc.worker.mode``) — the whole
``api.entry`` surface, and therefore every adapter, rides its
IngestClient to the engine through the shared-memory rings. Serving a
WSGI app from N processes is one line::

    python tools/ipc_launch.py myservice:app --workers 4 --port 8080

Worker ``i`` binds ``port + i`` (put nginx/envoy in front, exactly like
gunicorn's ``--workers``). ``--client-window-ms`` arms the worker-side
micro-window, ``--wakeup adaptive`` the spin-then-park ring waits; both
replay into the children automatically.

``--smoke`` runs the self-test used by tools/ci_check.sh: two spawned
workers serve a built-in WSGI app in-process (no sockets), the parent
asserts the requests were admitted by the engine and exits 0 — the
whole worker-mode path (spawn → attach → adapter → rings → engine →
verdict → exit release) in a few seconds.
"""

from __future__ import annotations

import argparse
import importlib
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_app(spec: str):
    mod, _, attr = spec.partition(":")
    m = importlib.import_module(mod)
    return getattr(m, attr or "app")


def _demo_app(environ, start_response):
    start_response("200 OK", [("Content-Type", "text/plain")])
    return [b"ok\n"]


def serve_wsgi(worker_id: int, spec: str, port: int, wrap: bool) -> None:
    """Worker target: serve the WSGI app on ``port + worker_id``.
    Top-level so multiprocessing spawn children import it by name."""
    from wsgiref.simple_server import make_server

    from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

    app = _demo_app if spec == "-" else _load_app(spec)
    if wrap:
        app = SentinelWSGIMiddleware(app)
    srv = make_server("127.0.0.1", port + worker_id, app)
    print(f"[ipc_launch] worker {worker_id} serving on "
          f"http://127.0.0.1:{port + worker_id}", flush=True)
    srv.serve_forever()


def smoke_worker(worker_id: int, n_requests: int, q) -> None:
    """Smoke target: drive the built-in app through the WSGI adapter
    in-process (no sockets) and report the statuses."""
    from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware

    app = SentinelWSGIMiddleware(_demo_app, total_resource="web-total")
    statuses = []

    def start_response(status, headers):
        statuses.append(status)

    for i in range(n_requests):
        environ = {"PATH_INFO": f"/smoke/{i % 4}", "REQUEST_METHOD": "GET"}
        body = b"".join(app(environ, start_response))
        assert body == b"ok\n", body
    q.put((worker_id, statuses))


def _smoke(n_workers: int = 2, n_requests: int = 8) -> int:
    from sentinel_tpu.core import api
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.rules.flow_manager import flow_rule_manager
    from sentinel_tpu.utils.config import config

    # The smoke pins the TRANSPORT path — generous liveness thresholds
    # so a loaded box (first compiles take seconds, heartbeat threads
    # starve) doesn't fake engine/worker death and pass the run through
    # the policy fallback instead. run_workers replays these into the
    # children.
    config.set(config.IPC_ENGINE_DEAD_MS, "60000")
    config.set(config.IPC_WORKER_DEAD_MS, "60000")
    config.set(config.IPC_TIMEOUT_MS, "120000")
    eng = api.get_engine()
    flow_rule_manager.load_rules(
        [FlowRule(resource="web-total", count=1e9)]
    )
    plane = None
    try:
        q = None
        ws = None
        # run_workers builds the plane; grab its spawn context for the
        # result queue AFTER so the queue comes from the same context.
        from sentinel_tpu.ipc.plane import IngestPlane

        plane = eng.ipc_plane or IngestPlane(eng)
        q = plane.spawn_context().Queue()
        ws = api.run_workers(
            smoke_worker, n=n_workers, args=(n_requests, q), engine=eng
        )
        seen = 0
        while seen < n_workers:
            wid, statuses = q.get(timeout=180)
            assert len(statuses) == n_requests, statuses
            assert all(s == "200 OK" for s in statuses), statuses
            seen += 1
        ws.join(timeout=30)
        # Poll, don't snapshot-and-assert: on a loaded box the drainer
        # can still be inside a first-compile flush with the whole run
        # queued in the ring (policy-served callers don't wait for it),
        # and the gauge drain for policy-served admissions rides the
        # dead-worker reap after the workers exit.
        import time

        want = n_workers * n_requests
        served = 0
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            served = plane.snapshot()["counters"]["requests"]
            if served >= want:
                break
            time.sleep(0.25)
        assert served >= want, plane.snapshot()
        stats = None
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            eng.flush()
            eng.drain()
            stats = eng.cluster_node_stats("web-total")
            if stats["cur_thread_num"] == 0:
                break
            time.sleep(0.25)
        assert stats is not None and stats["cur_thread_num"] == 0, stats
        print(f"[ipc_launch] smoke OK: {n_workers} workers x "
              f"{n_requests} requests, {served} plane requests, "
              f"gauges drained to 0")
        return 0
    finally:
        if plane is not None:
            plane.close()
        eng.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("app", nargs="?", default="-",
                    help="WSGI app as module:attr ('-' = built-in demo app)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--no-wrap", action="store_true",
                    help="app is already Sentinel-wrapped")
    ap.add_argument("--client-window-ms", type=float, default=None,
                    help="arm the worker-side micro-window")
    ap.add_argument("--wakeup", choices=("sleep", "adaptive"), default=None)
    ap.add_argument("--smoke", action="store_true",
                    help="run the ci_check worker-mode self-test and exit")
    args = ap.parse_args()

    from sentinel_tpu.utils.config import config

    if args.client_window_ms is not None:
        config.set(config.IPC_CLIENT_WINDOW_MS, str(args.client_window_ms))
    if args.wakeup is not None:
        config.set(config.IPC_WAKEUP, args.wakeup)
    if args.smoke:
        return _smoke(n_workers=min(2, max(1, args.workers)))

    from sentinel_tpu.core import api

    eng = api.get_engine()
    ws = api.run_workers(
        serve_wsgi, n=args.workers,
        args=(args.app, args.port, not args.no_wrap), engine=eng,
    )
    print(f"[ipc_launch] {len(ws)} workers up (ports {args.port}.."
          f"{args.port + args.workers - 1}); Ctrl-C stops", flush=True)
    try:
        ws.join()
    except KeyboardInterrupt:
        print("[ipc_launch] stopping workers", flush=True)
        ws.stop()
    finally:
        eng.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
