"""Audit ``sentinel.tpu.*`` config keys against utils/config.py + docs.

Three checks:

* **declaration** — every ``sentinel.tpu.*`` key referenced anywhere
  under ``sentinel_tpu/`` (code, docstrings, comments — a key mentioned
  in prose is a key an operator will try to set) must be declared in
  ``SentinelConfig.DEFAULTS``. A key that is a strict PREFIX of
  declared keys (a family mention like ``sentinel.tpu.host.arena``
  standing for ``…arena.max.keys`` / ``…arena.per.key``, usually
  written with a trailing ``.*``) also passes.
* **documentation** — every DECLARED ``sentinel.tpu.*`` key must appear
  in ``docs/ARCHITECTURE.md``, either spelled out or covered by a
  family mention (``sentinel.tpu.ingest.*`` covers every
  ``sentinel.tpu.ingest.…`` key). A key an operator cannot find in the
  architecture doc is a key that drifts.
* **metrics** (``audit_metrics``) — every Prometheus metric FAMILY the
  exporter emits (read from a live ``render_metrics`` against a fresh
  default engine, PLUS the worker-process and cluster-server renders'
  zero-value shapes, so a family added anywhere in any render path is
  caught) and every ``TelemetryBus`` counter key must appear VERBATIM
  in ``docs/ARCHITECTURE.md``. The PR-7 config-key rule applied to the
  metric plane: an alert an operator cannot look up is an alert that
  gets ignored.
* **commands** (``audit_commands``) — every command the transport's
  ``@command_mapping`` registry exposes must appear backtick-quoted in
  ``docs/ARCHITECTURE.md``. A command an operator cannot find is a
  command that only its author ever calls.

This is the guard that lets a new key family (like
``sentinel.tpu.ingest.*`` / ``sentinel.tpu.speculative.shaping.*``)
land safely: referencing a key the config registry doesn't declare —
or declaring one the docs never mention — fails CI instead of rotting
silently.

Usage::

    python tools/config_audit.py [--root sentinel_tpu] [--doc docs/ARCHITECTURE.md]

Exit status 0 when clean; 1 with a per-key report otherwise. The
programmatic surface (``audit()`` / ``audit_docs()``) is what
tests/test_config_audit.py asserts on.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict, List, Optional, Set, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])

# A key: sentinel.tpu. followed by dot-separated lowercase segments.
# The trailing segment must be a word (so a family wildcard
# "sentinel.tpu.trace.*" matches up to "sentinel.tpu.trace").
_KEY_RE = re.compile(r"sentinel\.tpu\.[a-z0-9]+(?:\.[a-z0-9]+)*")


def declared_keys() -> Set[str]:
    """Keys registered in SentinelConfig.DEFAULTS (the layered-config
    single source of truth)."""
    from sentinel_tpu.utils.config import SentinelConfig

    return set(SentinelConfig.DEFAULTS)


def referenced_keys(root: str) -> Dict[str, List[str]]:
    """Every sentinel.tpu.* key string appearing in ``root``'s .py
    files -> the ``path:line`` locations that mention it."""
    refs: Dict[str, List[str]] = {}
    for dirpath, _dirnames, filenames in os.walk(root):
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            try:
                with open(path, "r", encoding="utf-8") as f:
                    lines = f.readlines()
            except OSError:
                continue
            for ln, line in enumerate(lines, 1):
                for m in _KEY_RE.finditer(line):
                    refs.setdefault(m.group(0), []).append(f"{path}:{ln}")
    return refs


def audit(root: str = "sentinel_tpu") -> Tuple[List[str], Dict[str, List[str]]]:
    """Returns ``(missing_keys_sorted, refs)`` — a referenced key is
    missing unless it is declared, or is a strict prefix of a declared
    key (a family mention)."""
    declared = declared_keys()
    refs = referenced_keys(root)
    missing = [
        key
        for key in refs
        if key not in declared
        and not any(d.startswith(key + ".") for d in declared)
    ]
    return sorted(missing), refs


def audit_docs(doc_path: str = "docs/ARCHITECTURE.md") -> List[str]:
    """Declared ``sentinel.tpu.*`` keys NOT mentioned (directly or via
    a family prefix like ``sentinel.tpu.ingest.*``) in the architecture
    doc — sorted; empty when clean. A missing/unreadable doc reports
    every key (a deleted doc must not read as 'all documented')."""
    try:
        with open(doc_path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
    mentioned = set(_KEY_RE.findall(text))
    undocumented = []
    for key in declared_keys():
        if not key.startswith("sentinel.tpu."):
            continue
        if key in mentioned:
            continue
        # A family mention covers its members: "sentinel.tpu.ingest.*"
        # is captured as "sentinel.tpu.ingest" by the key regex.
        if any(key.startswith(m + ".") for m in mentioned):
            continue
        undocumented.append(key)
    return sorted(undocumented)


def prometheus_families() -> Set[str]:
    """Every metric family the Prometheus exporter emits, read off the
    ``# TYPE`` metadata of a live render against a fresh default
    engine — introspection, not source-grepping, so a family built in
    any helper (histogram buckets, the bounded resource export, a
    future module) cannot dodge the audit."""
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.transport.prometheus import (
        render_cluster_server_metrics,
        render_metrics,
        render_worker_metrics,
    )

    # The worker/server renders accept None and emit every family at
    # its zero value exactly so this audit (and first scrapes) see the
    # full shape without spinning up a worker plane or a token server.
    text = "\n".join([
        render_metrics(Engine()),
        render_worker_metrics(None),
        render_cluster_server_metrics(None),
    ])
    return {
        line.split()[2]
        for line in text.splitlines()
        if line.startswith("# TYPE ")
    }


def telemetry_counter_keys() -> Set[str]:
    """The TelemetryBus counter-key registry (metrics/telemetry.py)."""
    from sentinel_tpu.metrics.telemetry import TelemetryBus

    return set(TelemetryBus(enabled=False).counters)


def audit_metrics(
    doc_path: str = "docs/ARCHITECTURE.md",
    families: Optional[Set[str]] = None,
    counters: Optional[Set[str]] = None,
) -> Tuple[List[str], List[str]]:
    """``(undocumented_families, undocumented_counters)`` — Prometheus
    families / TelemetryBus counter keys missing VERBATIM from the
    doc; both sorted, both empty when clean. A missing/unreadable doc
    reports everything (a deleted doc must not read as 'all
    documented'). ``families``/``counters`` injection is the test
    seam; production callers omit them."""
    try:
        with open(doc_path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
    if families is None:
        families = prometheus_families()
    if counters is None:
        counters = telemetry_counter_keys()
    # Verbatim word-boundary matches: "spec_admits" must not be
    # satisfied by "spec_admits_total" prose about a different thing —
    # but suffix-extended mentions DO document the base family for
    # Prometheus names (…_total in the doc covers the sample name).
    words = set(re.findall(r"[A-Za-z0-9_]+", text))
    missing_fams = sorted(f for f in families if f not in words)
    missing_ctrs = sorted(c for c in counters if c not in words)
    return missing_fams, missing_ctrs


def transport_commands() -> Set[str]:
    """Every command name the transport's ``@command_mapping`` registry
    exposes (transport/handlers.py) — introspection off the live
    registry, so a handler added anywhere import-time-reachable cannot
    dodge the audit."""
    from sentinel_tpu.transport.handlers import all_commands

    return set(all_commands())


def audit_commands(
    doc_path: str = "docs/ARCHITECTURE.md",
    commands: Optional[Set[str]] = None,
) -> List[str]:
    """Registered command names NOT backtick-quoted in the doc —
    sorted; empty when clean. Backtick-quoting is required (not a bare
    word match): command names like ``basicInfo`` or ``metrics`` are
    ordinary prose words, and prose must not satisfy the audit. A
    missing/unreadable doc reports every command. ``commands``
    injection is the test seam; production callers omit it."""
    try:
        with open(doc_path, "r", encoding="utf-8") as f:
            text = f.read()
    except OSError:
        text = ""
    # `cmd`, `cmd?arg=...`, or `path/cmd` inside backticks all count.
    # Scanned per LINE: pairing backticks across the whole document
    # lets one ``` fence line flip the pairing parity for everything
    # after it; markdown inline code never spans lines anyway.
    quoted: Set[str] = set()
    for line in text.splitlines():
        for span in re.findall(r"`([^`]+)`", line):
            for tok in re.split(r"[\s,]+", span):
                quoted.add(tok)
                quoted.add(tok.split("?")[0])
    if commands is None:
        commands = transport_commands()
    return sorted(c for c in commands if c not in quoted)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default="sentinel_tpu")
    ap.add_argument("--doc", default="docs/ARCHITECTURE.md")
    ap.add_argument(
        "--no-metrics", action="store_true",
        help="skip the metric-plane audit (it builds an Engine, which "
             "needs a working jax backend)",
    )
    ap.add_argument(
        "--no-commands", action="store_true",
        help="skip the command-registry audit (it imports the "
             "transport handlers)",
    )
    args = ap.parse_args()
    missing, refs = audit(args.root)
    undocumented = audit_docs(args.doc)
    bad_fams: List[str] = []
    bad_ctrs: List[str] = []
    if not args.no_metrics:
        bad_fams, bad_ctrs = audit_metrics(args.doc)
    bad_cmds: List[str] = []
    if not args.no_commands:
        bad_cmds = audit_commands(args.doc)
    n_refs = sum(len(v) for v in refs.values())
    if (not missing and not undocumented and not bad_fams
            and not bad_ctrs and not bad_cmds):
        print(
            f"config audit OK: {len(refs)} distinct sentinel.tpu.* keys "
            f"({n_refs} mentions) all declared in utils/config.py and "
            f"documented in {args.doc}"
            + ("" if args.no_metrics
               else "; every Prometheus family and telemetry counter "
                    "documented")
            + ("" if args.no_commands
               else f"; all {len(transport_commands())} transport "
                    "commands documented")
        )
        return 0
    if missing:
        print("config audit FAILED — referenced but not declared in "
              "SentinelConfig.DEFAULTS:")
        for key in missing:
            locs = refs[key]
            shown = ", ".join(locs[:3]) + (" …" if len(locs) > 3 else "")
            print(f"  {key}  ({shown})")
    if undocumented:
        print(f"config audit FAILED — declared but not documented in "
              f"{args.doc}:")
        for key in undocumented:
            print(f"  {key}")
    if bad_fams:
        print(f"config audit FAILED — Prometheus families emitted but "
              f"not documented in {args.doc}:")
        for name in bad_fams:
            print(f"  {name}")
    if bad_ctrs:
        print(f"config audit FAILED — TelemetryBus counters not "
              f"documented in {args.doc}:")
        for name in bad_ctrs:
            print(f"  {name}")
    if bad_cmds:
        print(f"config audit FAILED — transport commands registered "
              f"but not backtick-documented in {args.doc}:")
        for name in bad_cmds:
            print(f"  {name}")
    return 1


if __name__ == "__main__":
    sys.exit(main())
