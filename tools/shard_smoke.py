"""Sharded token plane smoke: 2 real TCP shards + one kill/recover
cycle, the surface tier-1's in-process tests cannot fully cover wired
into ci_check.sh.

What must hold (exit nonzero otherwise, one line per check):

1. a batched window splits across both shards and every row admits;
2. leases grant per shard (both shard clients hold a lease table);
3. killing shard 0 degrades only ITS flows — shard 1 keeps admitting
   with its lease table untouched (the PR-16 disconnect cleared ALL
   leases; this is the regression surface);
4. restarting shard 0 on the same port reconnects and its flows admit
   from the server again;
5. after quiesce the concurrent-token gauge on both shards reads 0.

Usage::

    python tools/shard_smoke.py [--timeout 30]
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from sentinel_tpu.cluster import (  # noqa: E402
    cluster_flow_rule_manager,
    cluster_server_config_manager,
)
from sentinel_tpu.cluster.server import SentinelTokenServer  # noqa: E402
from sentinel_tpu.cluster.shards import (  # noqa: E402
    ShardMap,
    ShardedTokenClient,
    shard_of,
)
from sentinel_tpu.cluster.token_service import DefaultTokenService  # noqa: E402
from sentinel_tpu.models import constants as C  # noqa: E402
from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule  # noqa: E402
from sentinel_tpu.utils.config import config  # noqa: E402

OK = C.TokenResultStatus.OK
FAILURES = []


def check(name: str, cond: bool, detail: str = "") -> None:
    line = f"[shard_smoke] {'ok  ' if cond else 'FAIL'} {name}"
    if detail:
        line += f" ({detail})"
    print(line, flush=True)
    if not cond:
        FAILURES.append(name)


def flows_on_shard(shard: int, n_shards: int, count: int, start: int = 7000):
    out, fid = [], start
    while len(out) < count:
        if shard_of(fid, n_shards) == shard:
            out.append(fid)
        fid += 1
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=float, default=30.0,
                    help="overall deadline for the reconnect waits")
    args = ap.parse_args()
    deadline = time.monotonic() + args.timeout

    config.set(config.CLUSTER_CLIENT_WINDOW_MS, "0")
    config.set(config.CLUSTER_LEASE_ENABLED, "true")
    config.set(config.CLUSTER_LEASE_TTL_MS, "60000")

    cluster_flow_rule_manager.clear()
    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=1e12
    )
    flows_a = flows_on_shard(0, 2, 4)
    flows_b = flows_on_shard(1, 2, 4)
    cluster_flow_rule_manager.load_rules(
        "default",
        [FlowRule(
            "sm%d" % f, count=1e9, cluster_mode=True,
            cluster_config=ClusterFlowConfig(
                flow_id=f, threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            ),
        ) for f in flows_a + flows_b],
    )

    servers = [
        SentinelTokenServer(port=0, service=DefaultTokenService()).start()
        for _ in range(2)
    ]
    port_a = servers[0].port
    client = ShardedTokenClient(
        ShardMap(0, [("127.0.0.1", s.port) for s in servers]),
        request_timeout_sec=2.0,
        reconnect_interval_sec=0.2,
    ).start()
    rows = [(f, 1, False) for f in (flows_a + flows_b) * 4]

    try:
        # 1. split + admit: one window, every row OK, both shards framed.
        for _ in range(3):  # warm + grant leases on both shards
            results = client.request_tokens_batch(rows)
        check("batched window admits on both shards",
              all(r.status == OK for r in results),
              f"{sum(r.status == OK for r in results)}/{len(results)} OK")
        srows = client.shard_rows()
        check("both shards carried frames",
              all(sr["batch_frames"] > 0 for sr in srows),
              "frames=" + ",".join(str(sr["batch_frames"]) for sr in srows))

        # 2. per-shard lease tables.
        check("leases granted per shard",
              all(sr["leases"] > 0 for sr in srows),
              "leases=" + ",".join(str(sr["leases"]) for sr in srows))
        leases_b = dict(client.clients[1]._leases)

        # 3. kill shard 0: only ITS flows degrade; shard 1's lease
        #    table survives the other shard's bounce.
        servers[0].stop()
        degraded = False
        while time.monotonic() < deadline and not degraded:
            results = client.request_tokens_batch(rows)
            by_flow = dict(zip([r[0] for r in rows], results))
            degraded = any(
                by_flow[f].status != OK for f in flows_a
            ) and not client.clients[0].connected
            time.sleep(0.05)
        check("dead shard flows degrade", degraded)
        check("live shard flows keep admitting",
              all(by_flow[f].status == OK for f in flows_b))
        check("live shard lease table untouched by the bounce",
              dict(client.clients[1]._leases) == leases_b and bool(leases_b),
              f"{len(leases_b)} leases")
        check("dead shard leases cleared, live shard's kept",
              len(client.clients[0]._leases) == 0)

        # 4. recover: same port, reconnect, server-side admits again.
        servers[0] = SentinelTokenServer(
            port=port_a, service=DefaultTokenService()
        ).start()
        recovered = False
        while time.monotonic() < deadline and not recovered:
            results = client.request_tokens_batch(rows)
            by_flow = dict(zip([r[0] for r in rows], results))
            recovered = all(
                by_flow[f].status == OK for f in flows_a + flows_b
            )
            time.sleep(0.05)
        check("killed shard recovers on the same port", recovered)

        # 5. concurrent gauge drains to exactly 0 on the granting shard.
        cluster_flow_rule_manager.load_rules(
            "default",
            [FlowRule(
                "smc", count=64, grade=C.FLOW_GRADE_THREAD,
                cluster_mode=True,
                cluster_config=ClusterFlowConfig(
                    flow_id=flows_a[0],
                    threshold_type=C.FLOW_THRESHOLD_GLOBAL,
                ),
            )],
        )
        grant = client.request_concurrent_token(flows_a[0], 1)
        released = (
            grant.status == OK
            and client.release_concurrent_token(grant.token_id).status
            in (OK, C.TokenResultStatus.RELEASE_OK)
        )
        check("concurrent token grant/release round trip", released)
    finally:
        client.stop()
        for s in servers:
            s.stop()
        cluster_flow_rule_manager.clear()
        for key in (
            config.CLUSTER_CLIENT_WINDOW_MS,
            config.CLUSTER_LEASE_ENABLED,
            config.CLUSTER_LEASE_TTL_MS,
        ):
            config.set(key, config.DEFAULTS[key])

    if FAILURES:
        print(f"[shard_smoke] FAILED: {', '.join(FAILURES)}")
        return 1
    print("[shard_smoke] all green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
