#!/usr/bin/env bash
# One entry point for the repo's full check matrix — the guard against
# the three existing audits silently drifting apart (a session that
# runs tier-1 but forgets the metrics audit, or greens the config audit
# while the bench gate regresses).
#
# Runs, in order, failing fast:
#   1. tier-1 tests        (pytest -m 'not slow', the ROADMAP verify)
#   2. config audit        (tools/config_audit.py: key declaration +
#                           --doc documentation + the metrics audit —
#                           every Prometheus family / telemetry counter
#                           documented in docs/ARCHITECTURE.md)
#   3. bench gate          (bench.py --gate vs the newest committed
#                           BENCH_*.json for this hardware)
#
# Usage:
#   tools/ci_check.sh                 # everything
#   CI_CHECK_SKIP_BENCH=1 tools/ci_check.sh   # audits + tests only
#                                     (the bench takes minutes; the
#                                     gate still runs in CI / pre-PR)
#   SENTINEL_BENCH_BUDGET_S=300 tools/ci_check.sh   # shorter bench
#
# Exit status: first failing step's status; 0 when everything is green.

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ci_check 1/3: tier-1 tests =="
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider

echo "== ci_check 2/3: config + doc + metrics audit =="
JAX_PLATFORMS=cpu python tools/config_audit.py \
    --root sentinel_tpu --doc docs/ARCHITECTURE.md

# Worker-mode + engine-restart + standby/handoff smoke (always):
# spawned workers serve a real WSGI adapter entirely through the
# rings; a SUPERVISED engine is kill -9'd mid-probing and must come
# back on the same rings (epoch bump → client reconnect → device
# verdicts again); then phase 3 arms the WARM STANDBY — the same kill
# must be a takeover (not a cold respawn) and a planned handoff cycle
# must complete with zero policy-served verdicts — the surfaces
# tier-1's in-process tests cannot fully cover.
echo "== ci_check 2b: ipc worker-mode + engine-restart smoke =="
JAX_PLATFORMS=cpu python tools/ipc_launch.py --smoke >/dev/null

# Sharded token plane smoke (always): two real TCP token shards behind
# the hash-routing client, one kill/recover cycle — a dead shard must
# degrade only ITS flows and leave the live shard's leases untouched,
# the scoping tier-1 covers in-process but not over real sockets.
echo "== ci_check 2c: sharded token plane smoke =="
JAX_PLATFORMS=cpu python tools/shard_smoke.py >/dev/null

# Fleet timeline smoke (always): 2 spawned ingest workers + this
# engine + 2 spawned token shards with span journals armed; every
# journal spills and fleetdump must merge them into ONE Perfetto
# trace carrying all three process-type track families with flow
# arrows crossing both boundaries (worker->engine on wid+seq,
# client->shard on port+xid).
echo "== ci_check 2d: fleet timeline (fleetdump) smoke =="
JAX_PLATFORMS=cpu python tools/fleetdump.py --smoke \
    --out /tmp/ci-fleet-trace.json >/dev/null

# Flight-recorder replay smoke (always): the committed golden capture
# (tests/data/capture_corpus/ — mixed single/bulk traffic, a
# mid-stream reload, a rollover, breaker + manual freezes) must replay
# through a fresh engine BIT-EXACTLY — --verify exits non-zero on the
# first verdict diff. This is the postmortem contract end-to-end: the
# same decode + frozen-clock replay path an operator runs on a
# production capture.
echo "== ci_check 2e: flight-recorder replay smoke =="
JAX_PLATFORMS=cpu python tools/replay.py \
    --dir tests/data/capture_corpus --verify >/dev/null
JAX_PLATFORMS=cpu python tools/replay.py \
    --dir tests/data/capture_corpus --verify --depth 2 >/dev/null

if [ "${CI_CHECK_SKIP_BENCH:-0}" = "1" ]; then
    echo "== ci_check 3/3: bench gate SKIPPED (CI_CHECK_SKIP_BENCH=1) =="
    # The ipc stage still smokes even when the full bench is skipped:
    # it exercises real spawned worker processes + shared-memory rings
    # (incl. the micro-window/per-call sweep and the adaptive-wakeup
    # A/B at smoke quotas).
    echo "== ci_check 3b: ipc stage smoke =="
    JAX_PLATFORMS=cpu python bench.py --run-stage --kind ipc \
        --rules 4 --entries 1024 --iters 1 --child-platform cpu >/dev/null
    # The cluster stage smokes too: a real TCP token server against the
    # batched client in all three stances (per-call, micro-window,
    # window+leases) — the wire plane tier-1 only covers in-process.
    echo "== ci_check 3c: cluster stage smoke =="
    JAX_PLATFORMS=cpu python bench.py --run-stage --kind cluster \
        --rules 1 --entries 1024 --iters 1 --child-platform cpu >/dev/null
else
    echo "== ci_check 3/3: bench gate (incl. ipc + cluster stages) =="
    JAX_PLATFORMS=cpu python bench.py --gate >/dev/null
fi

echo "ci_check: all green"
