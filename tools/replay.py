"""Deterministic replay of a black-box capture (runtime/capture.py).

A capture directory holds the columnar admission stream an engine
actually dispatched — entries, bulk groups, exits, the settled verdicts,
and the rule-timeline events (reloads, sketch promotions, shard-map
bumps, health transitions) that shaped them — stamped with the engine's
virtual clock. This tool reconstructs the deciding world (config
snapshot + rule snapshot from the segment header, then the rule
timeline in stream order), feeds the captured traffic to a FRESH engine
on a ``ManualClock`` pinned to each chunk's recorded ``now_ms``, and
flushes exactly at the captured chunk boundaries. Verdicts are pure
functions of ``(rules, windows, now)``, so the replayed verdicts must
be bit-identical to the captured ones — any diff is a real divergence
(a codec bug, a nondeterministic slot, or un-replayable inputs like
dropped bulk args columns).

Rows the differ EXCLUDES by construction (counted, reported, never
silently): captured verdicts carrying ``F_DEGRADED`` (the host fallback
decided while the device was lost — replay has a healthy device),
``F_SPECULATIVE`` (the speculative host tier decided pre-settle; replay
runs single-threaded without it), and ``F_VERDICT_MISSING`` (the
capture ended before that chunk's fill landed). ``--strict`` diffs them
anyway.

Modes::

    python tools/replay.py --dir CAPDIR --verify [--strict] [--depth K]
    python tools/replay.py --dir CAPDIR --bench  [--depth K]
    python tools/replay.py --dir CAPDIR --explain SEQ
    python tools/replay.py --dir CAPDIR --trace out.json

``--verify`` prints the bit-exact differential report (exit 1 on any
diff); ``--bench`` reuses the capture as a load generator and reports
replay throughput; ``--explain SEQ`` replays through the chunk that
decided captured row ``SEQ`` and prints the deciding rule row, slot,
threshold vs. the observed window stat, and the pre/post admission
state; ``--trace`` exports the capture timeline (chunks, rule reloads,
freezes) as Chrome trace-event JSON via ``metrics/perfetto.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np


# Captured-verdict flag bits a non-strict diff masks out (see module doc).
def _skip_mask_bits():
    from sentinel_tpu.ipc import frames
    from sentinel_tpu.runtime import capture as cap

    return frames.F_DEGRADED | frames.F_SPECULATIVE | cap.F_VERDICT_MISSING


def load_capture(directory: str, frozen: bool = True) -> Dict[str, Any]:
    """Decode a capture directory into the replay stream, restricted to
    ONE boot (the newest, unless every segment already agrees): mixed
    boots cannot share a virtual clock or a cap_seq space."""
    from sentinel_tpu.runtime import capture as cap

    paths = cap.capture_paths(directory, frozen=frozen)
    if not paths:
        raise SystemExit(f"replay: no capture segments under {directory!r}")
    by_boot: Dict[str, List[str]] = {}
    boot_wall: Dict[str, float] = {}
    for p in paths:
        header, _recs = cap.read_segment(p)
        b = header.get("boot_id", "?")
        by_boot.setdefault(b, []).append(p)
        boot_wall[b] = max(boot_wall.get(b, 0), header.get("wall_ms", 0))
    boot = max(boot_wall, key=boot_wall.get)
    if len(by_boot) > 1:
        print(
            f"replay: {len(by_boot)} boots in {directory!r}; "
            f"replaying newest boot {boot} "
            f"({len(by_boot[boot])}/{len(paths)} segments)"
        )
    return cap.decode_capture(by_boot[boot])


# Config keys the replay engine force-overrides after applying the
# captured snapshot: the capture itself (no recursive recording), the
# multi-process / batching / async planes (the captured stream is
# already the post-plane chunk sequence), and the host-side tiers whose
# verdicts the differ masks anyway.
def _forced_overrides(depth: int) -> Dict[str, str]:
    from sentinel_tpu.utils.config import config as C

    return {
        C.CAPTURE_ENABLED: "false",
        C.IPC_ENABLED: "false",
        C.IPC_WORKER_MODE: "false",
        C.SPANS_ENABLED: "false",
        C.INGEST_MAX_PENDING: "0",
        C.INGEST_MAX_PENDING_BULK: "0",
        C.INGEST_DEADLINE_MS: "0",
        C.INGEST_BATCH_WINDOW_MS: "0",
        C.AUTOTUNE_ENABLED: "false",
        C.SPECULATIVE_ENABLED: "false",
        C.FAILOVER_ENABLED: "false",
        C.PIPELINE_DEPTH: str(depth),
    }


def build_engine(header: Dict[str, Any], depth: int = 0):
    """A fresh engine under the captured config + rule snapshot, on a
    ManualClock anchored at the segment header's engine-clock ms."""
    from sentinel_tpu.utils.clock import ManualClock
    from sentinel_tpu.utils.config import config

    for k, v in (header.get("config") or {}).items():
        config.set(k, v)
    for k, v in _forced_overrides(depth).items():
        config.set(k, v)

    from sentinel_tpu.runtime.engine import Engine

    clk = ManualClock(start_ms=int(header.get("clock_ms", 0)))
    eng = Engine(clock=clk)
    apply_rules(eng, header.get("rules") or {})
    return eng, clk


def apply_rules(eng, snap: Dict[str, Any]) -> None:
    """Apply one header rule snapshot (all five kinds)."""
    _apply_rules_event(eng, "flow", snap.get("flow") or [])
    _apply_rules_event(eng, "degrade", snap.get("degrade") or [])
    _apply_rules_event(eng, "param", snap.get("param") or [])
    _apply_rules_event(eng, "authority", snap.get("authority") or {})
    _apply_rules_event(eng, "system", snap.get("system"))


def _apply_rules_event(eng, kind: str, rules: Any) -> None:
    from sentinel_tpu.models.rules import (
        AuthorityRule,
        DegradeRule,
        FlowRule,
        ParamFlowRule,
        rules_from_json,
    )

    if kind == "flow":
        eng.set_flow_rules(rules_from_json(rules, FlowRule))
    elif kind == "degrade":
        eng.set_degrade_rules(rules_from_json(rules, DegradeRule))
    elif kind == "param":
        by_res: Dict[str, List[ParamFlowRule]] = {}
        for r in rules_from_json(rules, ParamFlowRule):
            by_res.setdefault(r.resource, []).append(r)
        eng.set_param_rules(by_res)
    elif kind == "authority":
        by_res_a = {}
        for res, rd in (rules or {}).items():
            by_res_a[res] = rules_from_json([rd], AuthorityRule)[0]
        eng.set_authority_rules(by_res_a)
    elif kind == "system":
        from sentinel_tpu.rules.system_manager import SystemConfig

        eng.set_system_config(SystemConfig(**rules) if rules else None)


def _replay_chunk(eng, clk, ck) -> Tuple[list, list]:
    """Re-submit one captured chunk and flush at its boundary. Returns
    (entry_ops, bulk_ops) aligned to the chunk's cap_seq row order."""
    from sentinel_tpu.models import constants as C

    clk.set_ms(int(ck.now_ms))
    entry_ops = []
    for e in ck.entries:
        entry_ops.append(eng.submit_entry(
            e["resource"],
            context_name=e["context"] or C.CONTEXT_DEFAULT_NAME,
            origin=e["origin"],
            acquire=e["acquire"],
            entry_type=C.EntryType.IN if e["in"] else C.EntryType.OUT,
            prio=e["prio"],
            ts=e["ts"],
            args=e["args"],
        ))
    bulk_ops = []
    for group in ck.bulk:
        first = group[0]
        args_col = None
        if any(e["args"] for e in group):
            args_col = [tuple(e["args"]) for e in group]
        bulk_ops.append(eng.submit_bulk(
            first["resource"],
            len(group),
            ts=np.array([e["ts"] for e in group], dtype=np.int64),
            acquire=np.array([e["acquire"] for e in group], dtype=np.int32),
            context_name=first["context"] or C.CONTEXT_DEFAULT_NAME,
            origin=first["origin"],
            entry_type=C.EntryType.IN if first["in"] else C.EntryType.OUT,
            args_column=args_col,
        ))
    for x in ck.exits:
        thr = x["thr"]
        if thr == -1:
            eng.submit_exit(
                x["rows"], x["rt"], count=x["count"], err=x["err"],
                ts=x["ts"], resource=x["resource"],
                param_rows=x["p_rows"], speculative=False,
            )
        elif thr == 0:
            # Tracer exit: captured as count=0/err=N (engine.submit_trace).
            eng.submit_trace(x["rows"], count=x["err"], ts=x["ts"])
        else:
            # Speculative-reconciler gauge compensation (±thr, no stats).
            eng._submit_gauge_comp(x["rows"], thr)
    for group in ck.bulk_exits:
        first = group[0]
        n = len(group)
        eng.submit_exit_bulk(
            first["rows"], n,
            rt=np.array([x["rt"] for x in group], dtype=np.int64),
            count=np.array([x["count"] for x in group], dtype=np.int64),
            err=np.array([x["err"] for x in group], dtype=np.int64),
            ts=np.array([x["ts"] for x in group], dtype=np.int64),
            resource=first["resource"], speculative=False,
        )
    eng.flush()
    return entry_ops, bulk_ops


def replay(
    decoded: Dict[str, Any],
    depth: int = 0,
    stop_after_seq: Optional[int] = None,
) -> Dict[str, Any]:
    """Drive the full stream; returns ``{"engine", "clock", "chunks":
    [(CapturedChunk, entry_ops, bulk_ops)], "skipped_rules"}``. Rule
    events the sketch tier synthesized are skipped — the replay
    engine's OWN sketch tier re-derives promotions from the same
    traffic (they are host-tier state, not inputs)."""
    eng, clk = build_engine(decoded["header"], depth=depth)
    out: List[Tuple[Any, list, list]] = []
    skipped_rules = 0
    try:
        for kind, item in decoded["stream"]:
            if kind == "rules":
                if item.get("from_sketch"):
                    skipped_rules += 1
                    continue
                _apply_rules_event(eng, item["kind"], item["rules"])
            elif kind == "chunk":
                entry_ops, bulk_ops = _replay_chunk(eng, clk, item)
                out.append((item, entry_ops, bulk_ops))
                if (
                    stop_after_seq is not None
                    and item.cap_seq + item.rows > stop_after_seq
                ):
                    break
            # health / sketch / shard / freeze records are annotations:
            # replay re-derives engine state from traffic alone.
        eng.drain()
    except BaseException:
        eng.close()
        raise
    return {
        "engine": eng, "clock": clk, "chunks": out,
        "skipped_rules": skipped_rules,
    }


def _replayed_arrays(ck, entry_ops, bulk_ops):
    """(admitted u8, reason i16, wait i32, have u8) for one replayed
    chunk, aligned to cap_seq row order."""
    n = ck.rows
    admitted = np.zeros(n, np.uint8)
    reason = np.zeros(n, np.int16)
    wait = np.zeros(n, np.int32)
    have = np.zeros(n, np.uint8)
    i = 0
    for op in entry_ops:
        if op is not None:
            v = op.verdict
            if v is not None:
                admitted[i] = 1 if v.admitted else 0
                reason[i] = v.reason
                wait[i] = v.wait_ms
                have[i] = 1
        i += 1
    for gi, g in enumerate(bulk_ops):
        gn = len(ck.bulk[gi])
        if g is not None and g.admitted is not None:
            sl = slice(i, i + gn)
            admitted[sl] = g.admitted.astype(np.uint8)
            reason[sl] = g.reason.astype(np.int16)
            wait[sl] = g.wait_ms.astype(np.int32)
            have[sl] = 1
        i += gn
    return admitted, reason, wait, have


def verify(decoded: Dict[str, Any], depth: int = 0, strict: bool = False) -> Dict[str, Any]:
    """The differential report: replay and diff against the captured
    RK_VERDICT rows. Returns counts + at most 20 sample diffs."""
    res = replay(decoded, depth=depth)
    skip_bits = 0 if strict else _skip_mask_bits()
    report = {
        "chunks": len(res["chunks"]),
        "rows": 0,
        "compared": 0,
        "diffs": 0,
        "skipped_flagged": 0,   # degraded / speculative / missing rows
        "no_captured_verdict": 0,
        "not_replayed": 0,      # submit returned None (pass-through)
        "skipped_sketch_rules": res["skipped_rules"],
        "samples": [],
    }
    try:
        for ck, entry_ops, bulk_ops in res["chunks"]:
            report["rows"] += ck.rows
            if ck.verdicts is None:
                report["no_captured_verdict"] += ck.rows
                continue
            c_adm, c_rea, c_wait, c_flags = ck.verdicts
            r_adm, r_rea, r_wait, r_have = _replayed_arrays(
                ck, entry_ops, bulk_ops
            )
            for i in range(ck.rows):
                if skip_bits and (int(c_flags[i]) & skip_bits):
                    report["skipped_flagged"] += 1
                    continue
                if not r_have[i]:
                    report["not_replayed"] += 1
                    continue
                report["compared"] += 1
                if (
                    c_adm[i] != r_adm[i]
                    or c_rea[i] != r_rea[i]
                    or c_wait[i] != r_wait[i]
                ):
                    report["diffs"] += 1
                    if len(report["samples"]) < 20:
                        report["samples"].append({
                            "seq": ck.cap_seq + i,
                            "flush_seq": ck.flush_seq,
                            "captured": {
                                "admitted": int(c_adm[i]),
                                "reason": int(c_rea[i]),
                                "wait_ms": int(c_wait[i]),
                            },
                            "replayed": {
                                "admitted": int(r_adm[i]),
                                "reason": int(r_rea[i]),
                                "wait_ms": int(r_wait[i]),
                            },
                        })
    finally:
        res["engine"].close()
    return report


# ---------------------------------------------------------------------------
# --explain
# ---------------------------------------------------------------------------
def explain(decoded: Dict[str, Any], seq: int, depth: int = 0) -> Dict[str, Any]:
    """Replay through the chunk that decided captured row ``seq`` and
    attribute the verdict: the deciding rule row (the blocked rule
    bean), the slot family, the threshold vs. the observed one-second
    window stat, and the pre/post admission state of the resource."""
    from sentinel_tpu.core import errors as E

    target_ck = None
    for ck in decoded["chunks"].values():
        if ck.cap_seq <= seq < ck.cap_seq + ck.rows:
            target_ck = ck
            break
    if target_ck is None:
        raise SystemExit(f"replay: seq {seq} is not in this capture")

    # One-second observed window, reconstructed from the captured
    # stream itself (what the deciding kernel saw: every admitted
    # acquire on the row's resource inside the trailing 1000 ms).
    row = _row_of(target_ck, seq - target_ck.cap_seq)
    resource = row["resource"]
    now = int(target_ck.now_ms)
    observed_qps = 0.0
    for ck in decoded["chunks"].values():
        if ck.verdicts is None or ck.now_ms > now:
            continue
        c_adm = ck.verdicts[0]
        i = 0
        for e in ck.entries:
            if (
                e["resource"] == resource
                and now - 1000 < e["ts"] <= now
                and i < len(c_adm) and c_adm[i]
            ):
                observed_qps += e["acquire"]
            i += 1
        for group in ck.bulk:
            for e in group:
                if (
                    e["resource"] == resource
                    and now - 1000 < e["ts"] <= now
                    and i < len(c_adm) and c_adm[i]
                ):
                    observed_qps += e["acquire"]
                i += 1

    res = replay(decoded, depth=depth, stop_after_seq=seq)
    try:
        ck, entry_ops, bulk_ops = res["chunks"][-1]
        idx = seq - ck.cap_seq
        v = None
        if idx < len(entry_ops):
            op = entry_ops[idx]
            v = op.verdict if op is not None else None
        else:
            j = idx - len(entry_ops)
            for gi, group in enumerate(ck.bulk):
                if j < len(group):
                    g = bulk_ops[gi]
                    if g is not None and g.admitted is not None:
                        from sentinel_tpu.runtime.engine import Verdict

                        blocked = None
                        if not g.admitted[j]:
                            # Bulk verdict arrays carry no rule bean;
                            # attribute from the replay engine's live
                            # rule tables by (resource, reason code).
                            blocked = _attribute_rule(
                                res["engine"], resource, int(g.reason[j])
                            )
                        v = Verdict(
                            admitted=bool(g.admitted[j]),
                            reason=int(g.reason[j]),
                            wait_ms=int(g.wait_ms[j]),
                            blocked_rule=blocked,
                        )
                    break
                j -= len(group)

        pre = post = None
        if ck.verdicts is not None:
            c_adm = ck.verdicts[0]
            rows_res = [
                i for i in range(ck.rows)
                if _row_of(ck, i)["resource"] == resource
            ]
            before = [i for i in rows_res if i < idx]
            pre = {
                "resource_rows_in_chunk": len(rows_res),
                "admitted_before_row": int(sum(c_adm[i] for i in before)),
            }
            post = {
                "admitted_total": int(sum(c_adm[i] for i in rows_res)),
                "blocked_total": int(
                    len(rows_res) - sum(c_adm[i] for i in rows_res)
                ),
            }

        out: Dict[str, Any] = {
            "seq": seq,
            "flush_seq": ck.flush_seq,
            "now_ms": now,
            "row": row,
            "observed_window_qps": observed_qps,
        }
        if ck.verdicts is not None:
            out["captured"] = {
                "admitted": int(ck.verdicts[0][idx]),
                "reason": int(ck.verdicts[1][idx]),
                "reason_name": E.exc_name_for_code(int(ck.verdicts[1][idx]))
                if ck.verdicts[1][idx] else "PASS",
                "wait_ms": int(ck.verdicts[2][idx]),
                "flags": int(ck.verdicts[3][idx]),
            }
        if v is not None:
            rule = getattr(v, "blocked_rule", None)
            out["replayed"] = {
                "admitted": bool(v.admitted),
                "reason": int(v.reason),
                "reason_name": E.exc_name_for_code(v.reason)
                if v.reason else "PASS",
                "wait_ms": int(v.wait_ms),
                "limit_type": v.limit_type,
                "slot_name": v.slot_name,
                "deciding_rule": rule.to_dict() if rule is not None else None,
                "threshold": getattr(rule, "count", None),
            }
        if pre is not None:
            out["pre"] = pre
            out["post"] = post
        return out
    finally:
        res["engine"].close()


def _attribute_rule(eng, resource: str, reason: int):
    """Best-effort rule attribution for bulk rows (whose verdict
    arrays carry only the reason code): the live rule of that kind on
    that resource, from the replay engine's current tables."""
    from sentinel_tpu.core import errors as E

    if reason == E.BLOCK_FLOW:
        for r in eng.flow_index.user_rules():
            if r.resource == resource:
                return r
    elif reason == E.BLOCK_DEGRADE:
        for r in eng.degrade_index.rules:
            if r.resource == resource:
                return r
    elif reason == E.BLOCK_PARAM:
        for pairs in getattr(eng.param_index, "by_resource", {}).values():
            for _gid, r in pairs:
                if r.resource == resource:
                    return r
    elif reason == E.BLOCK_AUTHORITY:
        return eng.authority_rules.get(resource)
    return None


def _row_of(ck, idx: int) -> Dict[str, Any]:
    if idx < len(ck.entries):
        return ck.entries[idx]
    j = idx - len(ck.entries)
    for group in ck.bulk:
        if j < len(group):
            return group[j]
        j -= len(group)
    raise IndexError(f"row {idx} outside chunk of {ck.rows} rows")


# ---------------------------------------------------------------------------
# --bench / --trace
# ---------------------------------------------------------------------------
def bench(decoded: Dict[str, Any], depth: int = 0) -> Dict[str, Any]:
    """The capture as a load generator: time a full replay (submit +
    flush + drain) and report throughput, bench.py-style."""
    rows = sum(ck.rows for ck in decoded["chunks"].values())
    t0 = time.perf_counter()
    res = replay(decoded, depth=depth)
    elapsed = time.perf_counter() - t0
    res["engine"].close()
    return {
        "chunks": len(res["chunks"]),
        "rows": rows,
        "elapsed_s": round(elapsed, 4),
        "rows_per_s": round(rows / elapsed, 1) if elapsed > 0 else 0.0,
        "depth": depth,
    }


def trace_dict(decoded: Dict[str, Any]) -> Dict[str, Any]:
    """Capture timeline as Chrome trace-event JSON: one slice per chunk
    on a ``capture`` track, instants for rule reloads / health /
    freezes (metrics/perfetto.py emission)."""
    from sentinel_tpu.metrics.perfetto import TraceBuilder

    tb = TraceBuilder()
    pid = tb.process(decoded["header"].get("app", "capture"))
    tid = tb.thread(pid, "chunks")
    ev_tid = tb.thread(pid, "timeline")
    last_ms: Optional[int] = None
    for kind, item in decoded["stream"]:
        if kind == "chunk":
            start = item.now_ms if last_ms is None else min(item.now_ms, last_ms)
            tb.slice(
                pid, tid, "chunk", item.now_ms * 1000.0, 1000.0,
                args={
                    "flush_seq": item.flush_seq, "cap_seq": item.cap_seq,
                    "rows": item.rows,
                },
            )
            last_ms = item.now_ms
        else:
            ts = (last_ms or 0) * 1000.0
            tb.instant(pid, ev_tid, kind, ts, args=item)
    return tb.build()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", required=True, help="capture directory")
    ap.add_argument("--depth", type=int, default=0,
                    help="replay pipeline depth (default 0 = sync)")
    ap.add_argument("--no-frozen", action="store_true",
                    help="ignore frozen-* postmortem segments")
    ap.add_argument("--verify", action="store_true",
                    help="diff replayed verdicts against captured ones")
    ap.add_argument("--strict", action="store_true",
                    help="with --verify: diff degraded/speculative/"
                         "missing rows too")
    ap.add_argument("--bench", action="store_true",
                    help="time a full replay as a load generator")
    ap.add_argument("--explain", type=int, default=None, metavar="SEQ",
                    help="attribute the verdict of captured row SEQ")
    ap.add_argument("--trace", default=None, metavar="OUT",
                    help="export the capture timeline as trace-event JSON")
    ap.add_argument("--platform", default=None,
                    help="JAX platform override (e.g. cpu)")
    args = ap.parse_args()
    if args.platform:
        os.environ.setdefault("JAX_PLATFORMS", args.platform)

    decoded = load_capture(args.dir, frozen=not args.no_frozen)
    did = False
    if args.trace:
        trace = trace_dict(decoded)
        with open(args.trace, "w", encoding="utf-8") as f:
            json.dump(trace, f)
        print(f"wrote {args.trace}: {len(trace['traceEvents'])} events")
        did = True
    if args.explain is not None:
        print(json.dumps(explain(decoded, args.explain, depth=args.depth),
                         indent=2, default=str))
        did = True
    if args.bench:
        print(json.dumps(bench(decoded, depth=args.depth), indent=2))
        did = True
    if args.verify or not did:
        report = verify(decoded, depth=args.depth, strict=args.strict)
        print(json.dumps(report, indent=2))
        if report["diffs"]:
            raise SystemExit(1)
        print(
            f"replay verified: {report['compared']} verdicts bit-exact "
            f"({report['skipped_flagged']} flagged rows skipped, "
            f"depth {args.depth})"
        )


if __name__ == "__main__":
    main()
