#!/bin/bash
# TPU evidence capture watcher (round 4).
#
# The axon tunnel wedges for hours at a time (rounds 3-4) with short
# liveness windows in between; measurements must fire the moment a
# window opens, not when a human notices. Loop: cheap liveness probe
# every ~4 min; on success immediately run the full pipeline:
#
#   1. bench.py --platform tpu  (headline + mixed + engine stages,
#      incremental BENCH_partial.jsonl)
#   2. tools/k2probe.py         (k=2 cliff bisect, incremental stderr)
#
# Artifacts land in $OUT (default /tmp/tpucap); the session commits
# them into the repo after review. Exits once a bench run reports
# platform=tpu AND the k2probe completed, else keeps watching.
set -u
cd /root/repo
OUT=${OUT:-/tmp/tpucap}
mkdir -p "$OUT"
LOG="$OUT/watch.log"
say() { echo "$(date +%F' '%T) $*" >> "$LOG"; }

probe() {
  # Success requires the TPU backend specifically: a silent CPU-fallback
  # init would otherwise report ALIVE every cycle and burn the capture
  # timeouts on CPU-only work forever.
  timeout 90 python - <<'EOF' >> "$LOG" 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((256, 256), jnp.bfloat16)
(x @ x).block_until_ready()
print("probe ok:", jax.default_backend())
raise SystemExit(0 if jax.default_backend() == "tpu" else 1)
EOF
}

bench_done=0
k2_done=0
say "watcher started (pid $$)"
while true; do
  if probe; then
    say "tunnel ALIVE — starting capture pipeline"
    if [ "$bench_done" = 0 ]; then
      say "bench.py starting"
      SENTINEL_BENCH_BUDGET_S=900 timeout 1100 python bench.py --platform tpu \
        > "$OUT/bench.json" 2>> "$LOG"
      if grep -q '"platform": *"tpu"' "$OUT/bench.json" 2>/dev/null; then
        bench_done=1
        cp BENCH_partial.jsonl "$OUT/bench_partial.jsonl" 2>/dev/null
        say "bench CAPTURED on tpu: $(cat "$OUT/bench.json")"
      else
        say "bench did not land on tpu: $(cat "$OUT/bench.json" 2>/dev/null | head -c 400)"
      fi
    fi
    if [ "$k2_done" = 0 ]; then
      say "k2probe starting"
      timeout 1500 python tools/k2probe.py --iters 3 \
        > "$OUT/k2probe.json" 2>> "$LOG"
      if grep -q '"platform": *"tpu"' "$OUT/k2probe.json" 2>/dev/null; then
        k2_done=1
        say "k2probe CAPTURED on tpu: $(cat "$OUT/k2probe.json")"
      else
        say "k2probe did not land on tpu (partials are in this log): $(head -c 200 "$OUT/k2probe.json" 2>/dev/null)"
      fi
    fi
    if [ "$bench_done" = 1 ] && [ "$k2_done" = 1 ]; then
      say "all captures done — exiting"
      exit 0
    fi
  else
    say "probe failed/timed out (wedged)"
  fi
  sleep 240
done
