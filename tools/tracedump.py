"""Dump the engine flight recorder as Chrome trace-event JSON.

The depth-K flush pipeline's whole point is that host encode of flush
N+1 overlaps device execution of flush N — which was only ever
*inferrable* from ``dispatch_ms < kernel_ms`` in bench output. This
tool makes it *visible*: it converts the flight recorder's per-flush
spans (metrics/telemetry.py) into the Chrome trace-event object format,
loadable in Perfetto (https://ui.perfetto.dev) or chrome://tracing.

Track layout (see ``spans_to_trace``): ``host`` carries every flush's
``encode`` and ``dispatch`` slice (serialized under the flush lock, so
they never overlap); each deferred flush's dispatch→settle window is an
``inflight`` slice on its own ``inflight-N`` track — at depth K you see
up to K parallel inflight tracks whose slices straddle the next
flushes' encode slices on the host track. A ``requests`` track carries
one slice per sampled admission (metrics/admission_trace.py) spanning
enqueue→verdict, with a Perfetto flow arrow into the flush span that
DECIDED it — hover a 429'd request, read its W3C trace id, follow the
arrow into the deciding flush.

Usage::

    # Dump a live engine's recorder (from your own code):
    from tools.tracedump import dump
    dump(engine, "trace.json")

    # Self-contained demo: run a synthetic depth-2 workload and dump:
    python tools/tracedump.py --out trace.json [--depth 2] [--flushes 24]
        [--rows 512] [--platform cpu]
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def trace_dict(engine) -> dict:
    """The engine's current flight-recorder contents (flush spans +
    sampled admission records) as a Chrome trace-event JSON object."""
    from sentinel_tpu.metrics.telemetry import spans_to_trace

    return spans_to_trace(
        engine.telemetry.spans(), records=engine.admission_trace.records()
    )


def dump(engine, path: str) -> dict:
    """Write the engine's flight recorder to ``path``; returns the
    trace object."""
    trace = trace_dict(engine)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    return trace


def run_demo(depth: int = 2, flushes: int = 24, rows: int = 512) -> "object":
    """Synthetic pipelined workload on a fresh engine: one bulk group
    per flush at the requested pipeline depth, drained at the end, so
    the dump shows a saturated depth-K pipeline. The flow rule is
    tight enough to block part of every window and the tracer samples
    at 100%, so the ``requests`` track carries blocked AND admitted
    admissions with flow arrows. Returns the engine."""
    from sentinel_tpu.metrics.admission_trace import AdmissionTracer
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.runtime.engine import Engine

    eng = Engine(initial_rows=1024)
    eng.admission_trace = AdmissionTracer(sample_rate=1.0)
    eng.set_flow_rules([FlowRule(resource="demo", count=rows * 4)])
    # Warm-up: interning + kernel compile outside the recorded window.
    eng.submit_bulk("demo", rows)
    eng.flush()
    eng.pipeline_depth = depth
    for _ in range(flushes):
        eng.submit_bulk("demo", rows)
        eng.flush()
    eng.drain()
    eng.pipeline_depth = 0
    return eng


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="trace.json")
    ap.add_argument("--depth", type=int, default=2)
    ap.add_argument("--flushes", type=int, default=24)
    ap.add_argument("--rows", type=int, default=512)
    ap.add_argument("--platform", default=None,
                    help="JAX platform override (e.g. cpu)")
    args = ap.parse_args()
    if args.platform:
        import os

        os.environ.setdefault("JAX_PLATFORMS", args.platform)
    eng = run_demo(depth=args.depth, flushes=args.flushes, rows=args.rows)
    trace = dump(eng, args.out)
    n_inflight = sum(
        1 for e in trace["traceEvents"] if e.get("name") == "inflight"
    )
    n_flows = sum(1 for e in trace["traceEvents"] if e.get("ph") == "s")
    print(
        f"wrote {args.out}: {len(trace['traceEvents'])} events "
        f"({n_inflight} inflight spans, {n_flows} request flow arrows, "
        f"depth {args.depth}) — load it at https://ui.perfetto.dev"
    )


if __name__ == "__main__":
    main()
