"""Merge per-process fleet span journals into ONE Perfetto timeline.

tools/tracedump.py shows one engine's flush pipeline; this tool shows
the FLEET: every process's span journal (metrics/spans.py) — ingest
workers, the engine, cluster token shards — merged into a single
Chrome trace-event JSON where one admission is a chain of flow arrows
across process boundaries:

    worker admit ──s──▶ engine frame          (matched on wid + seq)
    client rpc   ──s──▶ shard serve           (matched on port + xid)

Track layout: one Perfetto process per journal (named
``sentinel-<role>``, pid = the real OS pid), one thread per span
category inside it (worker / engine / client / shard) — so an engine
process that also hosts the cluster client shows both tracks. Each
journal's spans are shifted by its recorded ``ruler_off_ms`` (local
clock minus the ipc control header's wall-ms ruler at the last beat
observed), landing every process on the shared ruler timeline.

Usage::

    # Merge journals spilled by a real run (workers/engine/shards
    # spill on close; or hit the `spans` command with &spill=1):
    python tools/fleetdump.py --out fleet.json /path/*-spans-*.jsonl

    # Self-contained demo: spawn 2 ingest workers + 2 token shards
    # around this process's engine, spill all journals, merge:
    python tools/fleetdump.py --demo --out fleet.json [--platform cpu]

    # Demo + hard checks (ci_check.sh stage): all three process-type
    # track families present, flow arrows cross both boundaries:
    python tools/fleetdump.py --smoke --out fleet.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Stable thread ordering inside each process: request flow reads
# top-to-bottom (worker joins -> engine drains -> client RPCs out ->
# shard serves) even when categories share a journal.
_CAT_ORDER = ("worker", "engine", "client", "shard")


def _cat_tid(cat: str) -> int:
    try:
        return _CAT_ORDER.index(cat) + 1
    except ValueError:
        return len(_CAT_ORDER) + 1


def merge_journals(journals) -> dict:
    """[{"meta": ..., "spans": [...]}] -> Chrome trace-event object.

    Spans become ``X`` slices (ts/dur in µs); cross-process admissions
    and RPCs become ``s``/``f`` flow-arrow pairs. Event mechanics
    (emit-once metadata, the backwards-arrow clamp) come from
    metrics/perfetto.py's :class:`TraceBuilder`; this function owns
    only the fleet-specific span matching."""
    from sentinel_tpu.metrics.perfetto import TraceBuilder

    tb = TraceBuilder()
    admits = []   # (ts_us, pid, tid, wid, seq, trace_id)
    frames = []   # (ts_us, pid, tid, wid, seq_lo, seq_hi)
    rpcs = []     # (ts_us, pid, tid, port, xid)
    serves = {}   # (port, xid) -> (ts_us, pid, tid)

    for i, j in enumerate(journals):
        meta = j.get("meta") or {}
        spans = j.get("spans") or []
        role = str(meta.get("role", "proc"))
        pid = int(meta.get("pid", 0) or (100 + i))
        off_ms = float(meta.get("ruler_off_ms", 0.0) or 0.0)
        tb.process(f"sentinel-{role}", pid=pid)
        for sp in spans:
            cat = str(sp.get("cat", role))
            tid = tb.thread(pid, cat, tid=_cat_tid(cat))
            ts = int(round((float(sp["t0"]) - off_ms) * 1000.0))
            dur = max(1, int(round(float(sp.get("dur", 0.0)) * 1000.0)))
            args = {
                k: v for k, v in sp.items()
                if k not in ("name", "cat", "t0", "dur")
            }
            tb.slice(pid, tid, sp["name"], ts, dur, cat=cat, args=args)
            name = sp["name"]
            if cat == "worker" and name in ("admit", "admit.bulk"):
                if "wid" in sp and "seq" in sp:
                    admits.append((ts, pid, tid, int(sp["wid"]),
                                   int(sp["seq"]), sp.get("trace")))
            elif cat == "engine" and name == "frame":
                frames.append((ts, pid, tid, int(sp.get("wid", -1)),
                               int(sp.get("seq_lo", 0)),
                               int(sp.get("seq_hi", -1))))
            elif cat == "client" and name == "rpc":
                rpcs.append((ts, pid, tid,
                             int(sp.get("port", 0)), int(sp.get("xid", 0))))
            elif cat == "shard" and name == "serve":
                key = (int(sp.get("port", 0)), int(sp.get("xid", 0)))
                serves[key] = (ts, pid, tid)

    # Admission arrows: the worker's admit span into the engine frame
    # that carried its seq. seq is monotone per worker, so at most one
    # frame matches.
    for ts, pid, tid, wid, seq, trace_id in admits:
        for f_ts, f_pid, f_tid, f_wid, lo, hi in frames:
            if f_wid == wid and lo <= seq <= hi:
                fid = str(trace_id) if trace_id else f"adm-{wid}-{seq}"
                tb.flow(fid, "admission", (ts, pid, tid),
                        (f_ts, f_pid, f_tid), cat="fleet")
                break
    # RPC arrows: the client frame into the shard that served its xid
    # (xids count per client connection; the port disambiguates).
    for ts, pid, tid, port, xid in rpcs:
        hit = serves.get((port, xid))
        if hit is not None:
            tb.flow(f"rpc-{port}-{xid}", "rpc", (ts, pid, tid), hit,
                    cat="fleet")

    return tb.build()


def merge_files(paths) -> dict:
    from sentinel_tpu.metrics.spans import load_journal

    return merge_journals([load_journal(p) for p in sorted(paths)])


# ---- demo: a real spawned fleet -----------------------------------------
#
# multiprocessing spawn children import these by module name, so they
# must stay top-level (same contract as tests/ipc_procs.py).

DEMO_FLOWS = (9101, 9102, 9103, 9104)


def _demo_cfg(spans_dir: str) -> dict:
    from sentinel_tpu.utils.config import SentinelConfig

    return {
        SentinelConfig.SPANS_ENABLED: "true",
        SentinelConfig.SPANS_DIR: spans_dir,
    }


def _worker_child(channel, wid, cfg, n, q):
    """Spawned ingest worker: n entries + one bulk against the shared
    rings; the journal spills on close."""
    from sentinel_tpu.utils.config import config

    for k, v in cfg.items():
        config.set(k, v)
    from sentinel_tpu.ipc.worker import IngestClient

    cli = IngestClient(channel, wid)
    try:
        admitted = 0
        for _ in range(n):
            v = cli.entry("fleet-res", timeout_ms=60000)
            admitted += int(v.admitted)
        a, _r, _w, _f = cli.bulk("fleet-res", 8)
        q.put(("done", wid, admitted + int(a.sum())))
    finally:
        cli.close()


def _shard_child(cfg, flow_ids, q, stop_evt):
    """Spawned token shard: a real TCP SentinelTokenServer with the
    demo's cluster flow rules loaded; journal spills on stop."""
    from sentinel_tpu.utils.config import config

    for k, v in cfg.items():
        config.set(k, v)
    from sentinel_tpu.cluster import (
        cluster_flow_rule_manager,
        cluster_server_config_manager,
    )
    from sentinel_tpu.cluster.server import SentinelTokenServer
    from sentinel_tpu.cluster.token_service import DefaultTokenService
    from sentinel_tpu.models import constants as C
    from sentinel_tpu.models.rules import ClusterFlowConfig, FlowRule

    cluster_server_config_manager.load_global_flow_config(
        exceed_count=1.0, max_allowed_qps=1e12
    )
    cluster_flow_rule_manager.load_rules(
        "default",
        [FlowRule(
            "fleet%d" % f, count=1e9, cluster_mode=True,
            cluster_config=ClusterFlowConfig(
                flow_id=f, threshold_type=C.FLOW_THRESHOLD_GLOBAL,
            ),
        ) for f in flow_ids],
    )
    srv = SentinelTokenServer(port=0, service=DefaultTokenService()).start()
    q.put(srv.port)
    stop_evt.wait(timeout=120)
    srv.stop()


def run_demo(out_path: str, spans_dir=None, entries: int = 12) -> dict:
    """2 spawned workers + this process's engine + 2 spawned token
    shards, spans armed everywhere; every journal spilled and merged
    to ``out_path``. Returns the trace object."""
    from sentinel_tpu.cluster.client import ClusterTokenClient
    from sentinel_tpu.ipc.plane import IngestPlane
    from sentinel_tpu.metrics import spans as spans_mod
    from sentinel_tpu.models.rules import FlowRule
    from sentinel_tpu.runtime.engine import Engine
    from sentinel_tpu.utils.config import config

    own_dir = spans_dir is None
    if own_dir:
        spans_dir = tempfile.mkdtemp(prefix="fleetdump-")
    cfg = _demo_cfg(spans_dir)
    saved = {k: config.get(k) for k in cfg}
    for k, v in cfg.items():
        config.set(k, v)
    spans_mod.reset_journal()  # re-arm this process's journal

    eng = Engine(initial_rows=1024)
    eng.set_flow_rules([FlowRule(resource="fleet-res", count=1e9)])
    plane = IngestPlane(eng)
    ctx = plane.spawn_context()
    procs, shard_stops, shard_ports = [], [], []
    try:
        for _ in range(2):
            q, stop = ctx.Queue(), ctx.Event()
            p = ctx.Process(
                target=_shard_child,
                args=(cfg, list(DEMO_FLOWS), q, stop), daemon=True,
            )
            p.start()
            procs.append(p)
            shard_stops.append(stop)
            shard_ports.append(q.get(timeout=60))

        worker_qs = []
        for wid in range(2):
            q = ctx.Queue()
            p = ctx.Process(
                target=_worker_child,
                args=(plane.channel(wid), wid, cfg, entries, q),
                daemon=True,
            )
            p.start()
            procs.append(p)
            worker_qs.append(q)
        for q in worker_qs:
            tag, _wid, _n = q.get(timeout=120)
            assert tag == "done"

        # The cluster-client leg lives in THIS (engine) process — its
        # rpc spans land on the engine journal's "client" track.
        for port in shard_ports:
            cli = ClusterTokenClient(
                port=port, request_timeout_sec=5.0,
                reconnect_interval_sec=0.2,
            ).start()
            try:
                for _ in range(3):
                    cli.request_tokens_batch(
                        [(f, 1, False) for f in DEMO_FLOWS]
                    )
            finally:
                cli.stop()
    finally:
        for stop in shard_stops:
            stop.set()
        deadline = time.monotonic() + 15
        for p in procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
            if p.is_alive():
                p.terminate()
        plane.close()  # spills the engine/client journal
        eng.close()
        for k, v in saved.items():
            config.set(k, v if v is not None else config.DEFAULTS.get(k, ""))
        spans_mod.reset_journal()

    paths = glob.glob(os.path.join(spans_dir, "*-spans-*.jsonl"))
    trace = merge_files(paths)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    return trace


def smoke_checks(trace: dict) -> list:
    """The ci_check.sh assertions; returns failure strings (empty =
    green)."""
    evs = trace.get("traceEvents", [])
    cats = {e.get("cat") for e in evs if e.get("ph") == "X"}
    fails = []
    for want in ("worker", "engine", "shard"):
        if want not in cats:
            fails.append(f"no '{want}' track family in merged trace")
    procs = {e["pid"] for e in evs if e.get("ph") == "M"
             and e.get("name") == "process_name"}
    if len(procs) < 5:  # 2 workers + engine + 2 shards
        fails.append(f"expected >=5 processes, merged {len(procs)}")
    adm = sum(1 for e in evs if e.get("ph") == "s"
              and e.get("name") == "admission")
    rpc = sum(1 for e in evs if e.get("ph") == "s" and e.get("name") == "rpc")
    if adm == 0:
        fails.append("no worker->engine admission flow arrows")
    if rpc == 0:
        fails.append("no client->shard rpc flow arrows")
    n_s = sum(1 for e in evs if e.get("ph") == "s")
    n_f = sum(1 for e in evs if e.get("ph") == "f")
    if n_s != n_f:
        fails.append(f"unbalanced flow arrows: {n_s} starts, {n_f} finishes")
    return fails


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("journals", nargs="*",
                    help="spilled *-spans-*.jsonl files to merge")
    ap.add_argument("--out", default="fleet.json")
    ap.add_argument("--demo", action="store_true",
                    help="spawn a 2-worker/1-engine/2-shard fleet")
    ap.add_argument("--smoke", action="store_true",
                    help="demo + hard checks (nonzero exit on failure)")
    ap.add_argument("--entries", type=int, default=12)
    ap.add_argument("--platform", default=None,
                    help="JAX platform override (e.g. cpu)")
    args = ap.parse_args()
    if args.platform:
        os.environ.setdefault("JAX_PLATFORMS", args.platform)
    if args.journals:
        trace = merge_files(args.journals)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(trace, f)
    elif args.demo or args.smoke:
        trace = run_demo(args.out, entries=args.entries)
    else:
        ap.error("give journal files or --demo/--smoke")
        return 2
    evs = trace["traceEvents"]
    n_x = sum(1 for e in evs if e.get("ph") == "X")
    n_s = sum(1 for e in evs if e.get("ph") == "s")
    procs = {e["pid"] for e in evs if e.get("name") == "process_name"}
    print(f"[fleetdump] wrote {args.out}: {len(procs)} processes, "
          f"{n_x} spans, {n_s} flow arrows — load at "
          "https://ui.perfetto.dev")
    if args.smoke:
        fails = smoke_checks(trace)
        for f in fails:
            print(f"[fleetdump] FAIL {f}")
        if fails:
            return 1
        print("[fleetdump] smoke all green")
    return 0


if __name__ == "__main__":
    sys.exit(main())
