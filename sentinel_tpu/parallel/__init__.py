"""Multi-chip execution: device meshes and ICI collectives.

The reference scales cluster flow control through a Netty token server
(SURVEY.md §2.3); the TPU-native design replaces that RPC hop with XLA
collectives over ICI: every chip runs the same jitted flush on its shard
of the traffic against replicated counters, and window deltas +
cluster-global limits are combined with ``psum``/``pmax`` inside the
step (see :mod:`sentinel_tpu.parallel.ici`).
"""

from typing import Optional

from sentinel_tpu.parallel.mesh import make_mesh
from sentinel_tpu.parallel.ici import (
    merge_window_across,
    merge_stats_across,
    cluster_allocate,
    make_sharded_flush,
    batch_partition_specs,
)


def mesh_unavailable_reason(n_devices: int = 2) -> Optional[str]:
    """Why the sharded flush path cannot run in this environment, or
    None when it can. The sharded kernels are written against the
    stable ``jax.shard_map`` / ``jax.lax.axis_size`` API surface; on an
    older jax (or with too few devices) the capability is absent and
    callers — tests above all — should skip with this reason instead
    of failing on an ImportError deep inside a kernel trace."""
    import jax

    if not hasattr(jax, "shard_map"):
        return (
            f"jax {jax.__version__} has no stable jax.shard_map "
            "(the sharded kernels require it)"
        )
    if not hasattr(jax.lax, "axis_size"):
        return f"jax {jax.__version__} lacks jax.lax.axis_size"
    if len(jax.devices()) < n_devices:
        return (
            f"needs a {n_devices}-device mesh, environment has "
            f"{len(jax.devices())}"
        )
    return None


__all__ = [
    "make_mesh",
    "merge_window_across",
    "merge_stats_across",
    "cluster_allocate",
    "make_sharded_flush",
    "batch_partition_specs",
    "mesh_unavailable_reason",
]
