"""Multi-chip execution: device meshes and ICI collectives.

The reference scales cluster flow control through a Netty token server
(SURVEY.md §2.3); the TPU-native design replaces that RPC hop with XLA
collectives over ICI: every chip runs the same jitted flush on its shard
of the traffic against replicated counters, and window deltas +
cluster-global limits are combined with ``psum``/``pmax`` inside the
step (see :mod:`sentinel_tpu.parallel.ici`).
"""

from sentinel_tpu.parallel.mesh import make_mesh
from sentinel_tpu.parallel.ici import (
    merge_window_across,
    merge_stats_across,
    cluster_allocate,
    make_sharded_flush,
    batch_partition_specs,
)

__all__ = [
    "make_mesh",
    "merge_window_across",
    "merge_stats_across",
    "cluster_allocate",
    "make_sharded_flush",
    "batch_partition_specs",
]
