"""ICI-collective building blocks for multi-chip flow control.

This module is the TPU-native replacement for the reference's
token-server RPC (SURVEY.md §5 "Distributed communication backend"):
instead of every app instance RPCing a single Netty server that owns the
global ClusterMetric (reference: sentinel-cluster-server-default/.../
flow/ClusterFlowChecker.java:36-118), every chip holds replicated
counter tensors, processes its shard of the entry batch, and the merged
global state is reconstructed with ``psum``/``pmax``/``pmin`` inside the
jitted step — one ICI all-reduce instead of a network round-trip.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.metrics.metric_array import MetricArrayState
from sentinel_tpu.metrics.nodes import StatsState


def merge_window_across(old: MetricArrayState, new: MetricArrayState, axis: str) -> MetricArrayState:
    """Rollover-aware all-reduce of one window array.

    A device that rolled a bucket to a newer window zeroed the old
    counts, so a naive delta-psum would subtract the old counts once per
    rolling device. Instead: the merged window start is the max across
    devices; only devices whose final window matches it contribute
    (their counts minus the shared base, which is the old counts iff the
    old window already was the merged one).
    """
    g_ws = jax.lax.pmax(new.window_start, axis)
    old_cur = (old.window_start == g_ws)[:, :, None]
    new_cur = (new.window_start == g_ws)[:, :, None]
    base = jnp.where(old_cur, old.counts, 0)
    contrib = jnp.where(new_cur, new.counts - base, 0)
    counts = base + jax.lax.psum(contrib, axis)
    big = jnp.int32(2**31 - 1)
    min_rt = jnp.minimum(
        jnp.where(old.window_start == g_ws, old.min_rt, big),
        jax.lax.pmin(jnp.where(new.window_start == g_ws, new.min_rt, big), axis),
    )
    return MetricArrayState(counts=counts, min_rt=min_rt, window_start=g_ws)


def merge_stats_across(old: StatsState, new: StatsState, axis: str) -> StatsState:
    """All-reduce the full stats family (second + minute + thread gauge
    + occupy future slab)."""
    # Future slab: same rollover-aware merge as the window arrays (max
    # window start wins; only chips whose final ws matches contribute).
    g_ws = jax.lax.pmax(new.future_ws, axis)
    old_cur = old.future_ws == g_ws
    new_cur = new.future_ws == g_ws
    base = jnp.where(old_cur, old.future_pass, 0)
    contrib = jnp.where(new_cur, new.future_pass - base, 0)
    fut_pass = base + jax.lax.psum(contrib, axis)
    return StatsState(
        second=merge_window_across(old.second, new.second, axis),
        minute=merge_window_across(old.minute, new.minute, axis),
        threads=old.threads + jax.lax.psum(new.threads - old.threads, axis),
        future_pass=fut_pass,
        future_ws=g_ws,
    )


def cluster_allocate(
    axis: str, demand: jax.Array, capacity: jax.Array, *, with_before: bool = False
):
    """Greedy chip-indexed allocation of global capacity.

    Each chip has ``demand`` admission candidates for a cluster rule;
    the global remaining capacity is split by exclusive prefix over the
    mesh axis: chip i may admit ``min(demand_i, capacity -
    sum_{j<i} demand_j)``. Deterministic and conserving — the analog of
    the token server serializing client requests in arrival order
    (arrival order there is nondeterministic; chip index here is).
    Shapes: demand/capacity broadcastable; returns the per-chip grant,
    or ``(grant, before)`` when ``with_before`` (``before`` = the
    exclusive demand prefix, i.e. this chip's starting offset into the
    global budget).
    """
    idx = jax.lax.axis_index(axis)
    n = jax.lax.axis_size(axis)
    # Exclusive prefix sum over the axis via one-hot matmul-free trick:
    # gather all demands, mask those with lower index.
    all_d = jax.lax.all_gather(demand, axis)  # [n, ...]
    ranks = jnp.arange(n)
    shape = (n,) + (1,) * (all_d.ndim - 1)
    before = jnp.sum(jnp.where(ranks.reshape(shape) < idx, all_d, 0), axis=0)
    left = jnp.maximum(capacity - before, 0)
    grant = jnp.minimum(demand, left)
    return (grant, before) if with_before else grant


def batch_partition_specs(axis: str = "data"):
    """PartitionSpec pytree for a FlushBatch: entries/exits sharded over
    the mesh, scalars replicated."""
    from jax.sharding import PartitionSpec as P

    from sentinel_tpu.runtime.flush import FlushBatch

    return FlushBatch(
        now=P(),
        e_valid=P(axis),
        e_ts=P(axis),
        e_acquire=P(axis),
        e_rows=P(axis, None),
        e_rule_gid=P(axis, None),
        e_check_row=P(axis, None),
        e_prio=P(axis),
        e_auth_ok=P(axis),
        e_cluster_ok=P(axis),
        e_dgid=P(axis, None),
        x_valid=P(axis),
        x_ts=P(axis),
        x_count=P(axis),
        x_rows=P(axis, None),
        x_rt=P(axis),
        x_err=P(axis),
        x_thr=P(axis),
        x_dgid=P(axis, None),
    )


def _split_and_spend(
    axis: str, batch, r_rows: int, mask: jax.Array, unit_f: jax.Array,
    cap_slot: jax.Array
) -> jax.Array:
    """The shared mesh-budget recipe behind both demotion passes, keyed
    per CHECK ROW — the same key the single-chip rank math segments on
    (flow_admission sorts slots by ``(row, ts, arrival)`` and charges
    per row), so the sharded budget is exact wherever single-chip
    batching is:

    * per-chip demand = sum of participating slots' ``unit_f`` per row;
    * the cross-chip exclusive demand prefix (``before``) offsets each
      chip into the global per-row charge stream, the deterministic
      analog of the token server serializing grants (reference:
      ClusterFlowChecker.java:55-112);
    * within the chip the row's stream is spent in (ts, arrival) order
      with the per-slot admission check ``before + prefix + acquire ≤
      cap_slot`` — ``cap_slot`` stays per-SLOT, so two rules sharing a
      row each enforce their own count against the shared row charge,
      exactly like the single-chip ``(cur + acquire) <= count_s``.

    Earlier rounds keyed this per rule with a MIN cap over the rule's
    rows, which over-blocked origin-split topologies (a rule checked
    against several origin rows was capped at its most-loaded row);
    row keying removes that deviation. Returns the per-entry keep mask
    (an entry is kept iff every participating slot fits)."""
    from sentinel_tpu.runtime.flush import segment_excl_cumsum

    n, k = batch.e_rule_gid.shape
    row_f = batch.e_check_row.reshape(-1)
    eidx_f = jnp.arange(n * k, dtype=jnp.int32) // k
    acq_f = batch.e_acquire[eidx_f]
    row_c = jnp.clip(row_f, 0, r_rows - 1)

    demand = (
        jnp.zeros((r_rows,), dtype=jnp.int32)
        .at[jnp.where(mask, row_c, r_rows)]
        .add(jnp.where(mask, unit_f, 0), mode="drop")
    )
    # Exclusive cross-chip prefix of per-row demand: chip i's offset
    # into each row's global charge stream.
    idx = jax.lax.axis_index(axis)
    nax = jax.lax.axis_size(axis)
    all_d = jax.lax.all_gather(demand, axis)  # [nax, r_rows]
    before = jnp.sum(
        jnp.where(jnp.arange(nax).reshape(nax, 1) < idx, all_d, 0), axis=0
    )

    # Spend in (ts, arrival) order within each row segment. Per-slot
    # admission = the reference's sequential check run at this chip's
    # offset into the global stream. Since unit ≤ acquire, kept spend
    # per chip stays ≤ its grant, so the total across the mesh never
    # exceeds any slot's cap.
    pos = jnp.arange(n * k, dtype=jnp.int32)
    row_key = jnp.where(mask, row_c, jnp.int32(r_rows))
    ts_f = batch.e_ts[eidx_f]
    key_s, ts_s, pos_s = jax.lax.sort((row_key, ts_f, pos), num_keys=3)
    acq_s = acq_f[pos_s]
    m_s = mask[pos_s]
    ones = jnp.ones((1,), dtype=bool)
    new_grp = jnp.concatenate([ones, key_s[1:] != key_s[:-1]])
    prefix = segment_excl_cumsum(new_grp, jnp.where(m_s, unit_f[pos_s], 0))
    key_c = jnp.clip(key_s, 0, r_rows - 1)
    keep_s = ~m_s | ((before[key_c] + prefix + acq_s) <= cap_slot[pos_s])
    keep_slot = jnp.ones((n * k,), dtype=bool).at[pos_s].set(keep_s)
    return keep_slot.reshape(n, k).all(axis=1)


def _demote_over_grant(
    axis: str, stats_x, flow_dev, batch, flow_live: jax.Array
) -> jax.Array:
    """Cap each DEFAULT-behavior flow rule's admissions at the globally
    allocated grant; returns the per-entry keep mask.

    Budgeting happens at the FLOW level (``flow_live`` = passed every
    stage up to the breaker): the reference's FlowSlot (order −2000)
    grants tokens before DegradeSlot (−1000) runs, and budgeting on the
    post-breaker set would let a demoted HALF_OPEN probe shift to a
    different, un-budgeted entry in pass 2.

    Per rule: each chip's demand is the budget-unit sum of its
    flow-passing entries; ``cluster_allocate`` splits the global
    remaining capacity by chip-indexed exclusive prefix (the
    deterministic analog of the token server serializing grants,
    reference: ClusterFlowChecker.java:55-112); within a chip the grant
    is spent in (ts, arrival) order and the remainder demoted.

    Budget units follow DefaultController.canPass (reference:
    controller/DefaultController.java:49-78): QPS grade spends
    ``acquire`` per entry against ``count − floor(passQps)``; THREAD
    grade spends 1 per entry (the gauge rises by 1 regardless of
    acquire) against ``count − curThreadNum``, with the per-entry
    admission check ``prefix + acquire ≤ grant`` in both grades.

    ``stats_x`` is the GLOBAL post-exit view (the sharded step merges
    exit deltas across the mesh before any admission), so thread
    capacity reads directly from its gauge. Rows are per-slot in
    general (limitApp×strategy); budgets are conserved per CHECK ROW
    with per-slot caps (see _split_and_spend), matching the single-chip
    row-keyed rank math — exact for origin-split topologies too.
    """
    from sentinel_tpu.metrics import metric_array as ma
    from sentinel_tpu.metrics.events import MetricEvent
    from sentinel_tpu.metrics.nodes import SECOND_CFG
    from sentinel_tpu.models import constants as C

    n, k = batch.e_rule_gid.shape
    nr = flow_dev.n_rules
    r_rows = stats_x.n_rows
    interval_sec = SECOND_CFG.interval_ms / 1000.0

    gid_f = batch.e_rule_gid.reshape(-1)
    row_f = batch.e_check_row.reshape(-1)
    eidx_f = jnp.arange(n * k, dtype=jnp.int32) // k
    gid_c = jnp.clip(gid_f, 0, nr - 1)
    is_qps = flow_dev.grade[gid_c] == C.FLOW_GRADE_QPS
    # Only DEFAULT-behavior slots consume budget here; shaping slots are
    # governed by their pacer scan, not the windowed count.
    constrained = (
        (gid_f >= 0)
        & (row_f >= 0)
        & batch.e_valid[eidx_f]
        & flow_live[eidx_f]
        & (flow_dev.behavior[gid_c] == C.CONTROL_BEHAVIOR_DEFAULT)
    )
    acq_f = batch.e_acquire[eidx_f]
    unit_f = jnp.where(is_qps, acq_f, 1)

    # --- global remaining capacity per rule: the MIN over every row the
    # rule is checked against in this batch (pass counts are replicated;
    # thread gauges are reconstructed globally). A per-(rule,row) budget
    # would be exact; per-rule min is conservative for origin-split
    # topologies and exact for the dominant single-row case. ---
    pass_sums = ma.window_sums(SECOND_CFG, stats_x.second, batch.now)[:, MetricEvent.PASS]
    threads_global = stats_x.threads
    row_fc = jnp.clip(row_f, 0, r_rows - 1)
    base_qps_slot = jnp.floor(pass_sums[row_fc].astype(jnp.float32) / interval_sec)
    base_thr_slot = threads_global[row_fc].astype(jnp.float32)
    base_slot = jnp.where(is_qps, base_qps_slot, base_thr_slot)
    cap_slot = jnp.maximum(
        jnp.floor(flow_dev.count[gid_c]) - base_slot, 0.0
    ).astype(jnp.int32)
    return _split_and_spend(axis, batch, r_rows, constrained, unit_f, cap_slot)


def _demote_over_borrow(
    axis, stats_pre, flow_dev, batch, occ_slot: jax.Array
) -> jax.Array:
    """Cap occupy borrows at the global borrow budget; returns the
    per-entry keep mask over pass-1-borrowing entries.

    A chip-local occupy grant honors ``waiting + borrow ≤ maxCount``
    only against its own slab writes (StatisticNode.tryOccupyNext's
    ``currentBorrow`` bound, reference: node/StatisticNode.java:305-307)
    — n chips could each borrow up to the full budget. Same recipe as
    ``_demote_over_grant``: per rule, demand = the borrowing slots'
    acquire units (``occ_slot`` from pass 1 — only slots that actually
    borrowed charge the budget, not the entry's other slots whose plain
    check passed), capacity = maxCount − already-waiting tokens
    (replicated pre-flush state, so identical on every chip), split by
    chip-indexed exclusive prefix, spent in (ts, arrival) order within
    the chip.
    """
    from sentinel_tpu.metrics.nodes import SECOND_CFG, waiting_tokens

    n, k = batch.e_rule_gid.shape
    nr = flow_dev.n_rules
    r_rows = stats_pre.n_rows
    interval_sec = SECOND_CFG.interval_ms / 1000.0

    gid_f = batch.e_rule_gid.reshape(-1)
    row_f = batch.e_check_row.reshape(-1)
    eidx_f = jnp.arange(n * k, dtype=jnp.int32) // k
    gid_c = jnp.clip(gid_f, 0, nr - 1)
    borrower = occ_slot.reshape(-1)
    acq_f = batch.e_acquire[eidx_f]

    waiting = waiting_tokens(stats_pre, batch.now)
    row_fc = jnp.clip(row_f, 0, r_rows - 1)
    max_count = jnp.floor(flow_dev.count[gid_c] * interval_sec)
    cap_slot = jnp.maximum(
        max_count - waiting[row_fc].astype(jnp.float32), 0.0
    ).astype(jnp.int32)
    return _split_and_spend(axis, batch, r_rows, borrower, acq_f, cap_slot)


def _global_param_scan(axis, pdyn, param_g, live_up, n_local, rounds=0):
    """Run the hot-param scan once per chip on the GLOBALLY-replicated
    item batch — every chip computes the identical new param state (no
    merge needed), and the scan sees the global (value-row, ts)-ordered
    stream, so token-bucket/throttle/thread semantics are exactly the
    single-chip ones.

    Item liveness (auth/system verdicts of the item's entry) lives on
    the entry's owner chip only; one psum ORs the owner bits so every
    chip gates the scan identically. Returns (new_pdyn, per-local-entry
    (param_ok, wait_param), owner mask, local entry idx).
    """
    from sentinel_tpu.rules.param_table import run_param

    c = jax.lax.axis_index(axis)
    owner = (param_g.eidx // n_local) == c
    lidx = jnp.clip(param_g.eidx % n_local, 0, n_local - 1)
    # Exits release per-value thread slots first (replicated op —
    # identical on every chip).
    pr0 = pdyn.threads.shape[0]
    dec_rows = jnp.where(param_g.exit_rows >= 0, param_g.exit_rows, jnp.int32(pr0))
    pdyn = pdyn._replace(threads=pdyn.threads.at[dec_rows].add(-1, mode="drop"))
    live_bit = owner & live_up[lidx]
    item_live = jax.lax.psum(live_bit.astype(jnp.int32), axis) > 0
    pg_live = param_g._replace(valid=param_g.valid & item_live)
    new_pdyn, p_ok, p_wait = run_param(pdyn, pg_live, rounds=rounds)
    drop = jnp.int32(n_local)
    sc = jnp.where(pg_live.valid & owner, lidx, drop)
    param_ok_local = jnp.ones((n_local,), dtype=bool).at[sc].min(p_ok, mode="drop")
    wait_local = jnp.zeros((n_local,), dtype=jnp.int32).at[sc].max(p_wait, mode="drop")
    return new_pdyn, (param_ok_local, wait_local), owner, lidx


def _global_shaping_scan(
    axis, stats_x, flow_dev, flow_dyn, shaping_g, batch, live_up, n_local, k, rounds=0
):
    """Run the shaping pacer scan once per chip on the GLOBALLY-
    replicated item batch: replicated ``flow_dyn`` in, identical new
    ``flow_dyn`` out on every chip, and the ``lax.scan`` sees the global
    (rule, ts)-ordered request stream — exactly the single-chip pacer
    semantics (a chip-local scan would let every chip pace its own
    sub-stream and admit n× the configured rate).

    ``passQps`` for the warm-up math is rebuilt deterministically from
    the replicated post-exit windows plus the intra-batch charge among
    the global shaping items themselves — charged over ALL valid items
    regardless of upstream liveness, like flow_admission's
    liveness-unmasked ``consumed_acq`` on the single-chip path. (The
    single-chip charge is own-row-gated for RELATE slots; a RELATE +
    warm-up combination on the mesh keeps the ungated charge here —
    one-sided conservative in that corner.) Charges from
    co-row DEFAULT slots within this same flush are not visible to it
    (they land in the windows by the next flush) — a within-one-flush
    optimism that only matters when a warm-up rule shares its check row
    with a DEFAULT rule matching a *different* entry set.
    """
    from sentinel_tpu.metrics import metric_array as ma
    from sentinel_tpu.metrics.events import MetricEvent
    from sentinel_tpu.metrics.nodes import SECOND_CFG
    from sentinel_tpu.runtime.flush import (
        _prev_second_pass,
        _segment_consumed,
    )
    from sentinel_tpu.rules.shaping import run_shaping

    c = jax.lax.axis_index(axis)
    owner = (shaping_g.eidx // n_local) == c
    lidx = jnp.clip(shaping_g.eidx % n_local, 0, n_local - 1)
    live_bit = owner & live_up[lidx]
    item_live = jax.lax.psum(live_bit.astype(jnp.int32), axis) > 0
    sg_live = shaping_g._replace(valid=shaping_g.valid & item_live)

    s = sg_live.valid.shape[0]
    r_rows = stats_x.n_rows
    pass_sums = ma.window_sums(SECOND_CFG, stats_x.second, batch.now)[:, MetricEvent.PASS]
    # Charge population = every valid item, NOT gated by liveness: the
    # single-chip pass_plus_consumed charges upstream-blocked entries
    # too (flow_admission's consumed_acq is unmasked), and parity with
    # it is the contract. Only the scan's state advance is live-gated.
    rkey = jnp.where(shaping_g.valid, shaping_g.row, jnp.int32(r_rows))
    pos = jnp.arange(s, dtype=jnp.int32)
    # Global items concatenate per chip in eidx order, so pos as the
    # last key reproduces (row, ts, eidx) with one less sort operand.
    rk_s, _, p_s = jax.lax.sort((rkey, shaping_g.ts, pos), num_keys=3)
    ei_s = shaping_g.eidx[p_s]
    ones = jnp.ones((1,), dtype=bool)
    new_grp = jnp.concatenate([ones, rk_s[1:] != rk_s[:-1]])
    last_of_ent = jnp.concatenate([rk_s[1:] != rk_s[:-1], ones]) | jnp.concatenate(
        [ei_s[1:] != ei_s[:-1], ones]
    )
    valid_sorted = shaping_g.valid[p_s]
    consumed = _segment_consumed(
        new_grp, last_of_ent, jnp.where(valid_sorted, shaping_g.acquire[p_s], 0)
    )
    base = pass_sums[jnp.clip(rk_s, 0, r_rows - 1)]
    ppc = (
        jnp.zeros((s,), dtype=jnp.int32)
        .at[p_s]
        .set((base + consumed).astype(jnp.int32))
    )
    prev = _prev_second_pass(stats_x, shaping_g.row, shaping_g.ts)
    interval_sec = SECOND_CFG.interval_ms / 1000.0
    new_fdyn, ok_s, wait_s = run_shaping(
        flow_dev, flow_dyn, sg_live, ppc, prev, interval_sec, rounds=rounds
    )
    lflat = lidx * k + shaping_g.flat_pos % k
    shaping_pre = (sg_live.valid & owner, lflat, lidx, ok_s, wait_s)
    return new_fdyn, shaping_pre


def make_sharded_flush(
    mesh,
    axis: str = "data",
    occupy_timeout_ms: int = 500,
    with_shaping: bool = False,
    with_param: bool = False,
    shaping_rounds: int = 0,
    param_rounds: int = 0,
):
    """The full batched step over an n-device mesh.

    Entries and exits are data-parallel across chips; counter tensors
    and rule tables are replicated; after each local flush the window
    deltas and breaker state are all-reduced so every chip ends the step
    with the identical global state.

    Flow budgets are conserved across the mesh in two passes: pass 1
    computes each chip's locally-admitted demand, ``cluster_allocate``
    splits the global remaining capacity deterministically, over-grant
    admissions are demoted to BLOCK via the batch's ``e_cluster_ok``
    channel, and pass 2 re-runs the step so accounting, breaker probes
    and verdicts all see the demotions coherently. This replaces the
    reference's token-server RPC (one all-gather over ICI instead of a
    Netty round-trip per request).

    ``with_shaping`` / ``with_param`` extend the signature with a
    ShapingBatch / ParamBatch holding the GLOBAL item set (replicated on
    every chip, ``eidx``/``flat_pos`` in global coordinates): the
    serializing per-rule scans run once per chip on replicated state —
    identical results everywhere, global-stream ordering — and each chip
    scatters its own entries' verdicts (see the helpers above). The
    returned callable's signature then matches ``flush_step`` with the
    same optional batches appended.
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from sentinel_tpu.runtime.flush import apply_exit_phase, flush_entries, system_check

    def sharded_step(
        stats, flow_dev, flow_dyn, ddev, ddyn, pdyn, sysdev, batch,
        shaping_g=None, param_g=None,
    ):
        from sentinel_tpu.metrics.nodes import materialize_matured
        from sentinel_tpu.rules.degrade_table import CLOSED as _CLOSED, OPEN as _OPEN

        from sentinel_tpu.rules.degrade_table import trip_condition

        def merge_breaker(base_ddyn, new_ddyn):
            """Merge per-chip breaker windows/state against a replicated
            base. State: transitions happen on the one chip whose shard
            carried the triggering op, so "any chip that changed wins" —
            a plain pmax would discard HALF_OPEN→CLOSED (0 < 2) and
            HALF_OPEN→OPEN (1 < 2), wedging the breaker forever; if
            several chips transitioned differently in one flush, the max
            changed state wins (OPEN over CLOSED — pessimistic, like the
            reference resolving concurrent probe outcomes through its
            CAS, AbstractCircuitBreaker.java:40-150). Windows merge
            rollover-aware like merge_window_across. Finally, a breaker
            whose MERGED window crosses the threshold may have tripped
            on no single chip (errors spread 1-per-chip): re-evaluate
            CLOSED→OPEN on the merged counts, retry deadline anchored
            at flush time (later than the crossing completion's ts by at
            most one flush interval)."""
            changed = new_ddyn.state != base_ddyn.state
            cand = jnp.where(changed, new_ddyn.state, jnp.int32(-1))
            best = jax.lax.pmax(cand, axis)
            merged_state = jnp.where(best >= 0, best, base_ddyn.state)
            g_dws = jax.lax.pmax(new_ddyn.ws, axis)
            d_old_cur = base_ddyn.ws == g_dws
            d_new_cur = new_ddyn.ws == g_dws
            base_bad = jnp.where(d_old_cur, base_ddyn.bad, 0)
            base_total = jnp.where(d_old_cur, base_ddyn.total, 0)
            out = type(base_ddyn)(
                state=merged_state,
                next_retry=jax.lax.pmax(new_ddyn.next_retry, axis),
                bad=base_bad
                + jax.lax.psum(
                    jnp.where(d_new_cur, new_ddyn.bad - base_bad, 0), axis
                ),
                total=base_total
                + jax.lax.psum(
                    jnp.where(d_new_cur, new_ddyn.total - base_total, 0), axis
                ),
                ws=g_dws,
            )
            trip = trip_condition(
                ddev.grade, ddev.threshold, ddev.slow_ratio,
                out.bad.astype(jnp.float32),
                out.total.astype(jnp.float32),
            )
            cross = (
                (out.state == _CLOSED)
                & (out.total >= ddev.min_request)
                & trip
            )
            return out._replace(
                state=jnp.where(cross, _OPEN, out.state),
                next_retry=jnp.where(
                    cross, batch.now + ddev.retry_ms, out.next_retry
                ),
            )

        # Matured borrows fold into the window FIRST — deterministic on
        # replicated state, so it must happen before per-shard writes
        # diverge and must be the merge base (otherwise every chip's
        # identical materialisation would be summed once per chip).
        stats = materialize_matured(stats, batch.now)
        # Exits once, then the post-exit view is made GLOBAL before any
        # admission: within one flush exits apply before entry checks
        # on the WHOLE mesh (flush.py "Intra-batch sequencing"), so a
        # thread release / breaker completion carried by one chip's
        # shard is visible to every chip's checks — without this an
        # entry landing on a different chip than its same-flush exit
        # was blocked against a stale gauge (caught by the batched mesh
        # differential, round 4). Window tensors are additive: local
        # apply + rollover-aware merge is exact.
        stats_x, _ = apply_exit_phase(stats, ddev, ddyn, batch)
        stats_x = merge_stats_across(stats, stats_x, axis)
        # Breaker completions are a serializing state machine (the trip
        # latches at the FIRST prefix crossing the threshold), so a
        # per-chip run + endpoint merge loses trips whose crossing
        # prefix spans chips (e.g. errors front-loaded in ts order but
        # sharded apart: the merged endpoint ratio can sit back under
        # the threshold). Same treatment as the shaping/param scans:
        # every chip runs the completion machine once on the GLOBALLY
        # gathered completion set — identical replicated result, exact
        # global (ts, chip, arrival) order, nothing to merge.
        from sentinel_tpu.rules.degrade_table import breaker_on_exits

        def gather_flat(x):
            g = jax.lax.all_gather(x, axis)  # [nch, M, ...]
            return g.reshape((-1,) + x.shape[1:])

        ddyn_x = breaker_on_exits(
            ddev,
            ddyn,
            gather_flat(batch.x_dgid),
            gather_flat(batch.x_ts),
            gather_flat(batch.x_rt),
            gather_flat(batch.x_err),
            gather_flat(batch.x_valid),
        )

        # ---- global serializing scans (shaping pacers, hot params) ----
        # Upstream liveness (auth + system) for this chip's entries —
        # the owner-chip bits gate the replicated global scans.
        n_local = batch.e_valid.shape[0]
        k = batch.e_rule_gid.shape[1]
        param_pre = None
        shaping_pre = None
        new_pdyn_scan = None
        new_fdyn_scan = None
        p_owner = p_lidx = None
        if shaping_g is not None or param_g is not None:
            live0 = batch.e_valid & batch.e_auth_ok
            sys_ok, _ = system_check(stats_x, sysdev, batch, live0)
            live_up = live0 & sys_ok
            if param_g is not None:
                new_pdyn_scan, param_pre, p_owner, p_lidx = _global_param_scan(
                    axis, pdyn, param_g, live_up, n_local, rounds=param_rounds
                )
                live_up = live_up & param_pre[0]
            if shaping_g is not None:
                new_fdyn_scan, shaping_pre = _global_shaping_scan(
                    axis, stats_x, flow_dev, flow_dyn, shaping_g, batch,
                    live_up, n_local, k, rounds=shaping_rounds,
                )

        # Pass 1 (no state writes): local flow-level admission demand.
        _, _, _, _, r1 = flush_entries(
            stats_x, flow_dev, flow_dyn, ddev, ddyn_x, pdyn, sysdev, batch,
            commit=False, occupy_timeout_ms=occupy_timeout_ms,
            param_pre=param_pre, shaping_pre=shaping_pre,
        )
        # Occupied entries borrow from future windows, not the current
        # budget — exclude them from the grant math (their slab commits
        # merge like window counters) and budget them separately against
        # the global borrow allowance.
        budgeted = r1.flow_live & ~r1.occupied
        keep = _demote_over_grant(axis, stats_x, flow_dev, batch, budgeted)
        keep_occ = _demote_over_borrow(axis, stats, flow_dev, batch, r1.occ_slot)
        # Pass 2 borrows only what pass 1 granted within the global
        # budget: demoted borrowers lose prio (they fall to plain BLOCK
        # — their plain check already failed, that's why they borrowed);
        # entries pass 1 never occupied must not start borrowing now
        # that demotions shrank the intra-chip charge.
        batch2 = batch._replace(
            e_cluster_ok=batch.e_cluster_ok & (keep | ~budgeted),
            e_prio=batch.e_prio & r1.occupied & keep_occ,
        )
        # Probe election: exactly ONE entry across the mesh may probe an
        # OPEN breaker (fromOpenToHalfOpen is a single CAS,
        # AbstractCircuitBreaker.java:91-110); without this every chip
        # admits its own local rank-0 candidate. Each chip offers its
        # best candidate ts; the global (ts, chip) minimum wins.
        nd = ddev.n_rules
        n, kd = batch.e_dgid.shape
        gid_f = batch.e_dgid.reshape(-1)
        eidx_d = jnp.arange(n * kd, dtype=jnp.int32) // kd
        gid_dc = jnp.clip(gid_f, 0, nd - 1)
        big = jnp.int32(2**31 - 1)
        cand = (
            (gid_f >= 0)
            & r1.flow_live[eidx_d]
            & (ddyn.state[gid_dc] == _OPEN)
            & (batch.e_ts[eidx_d] >= ddyn.next_retry[gid_dc])
        )
        best_ts = (
            jnp.full((nd,), big, dtype=jnp.int32)
            .at[jnp.where(cand, gid_f, nd)]
            .min(batch.e_ts[eidx_d], mode="drop")
        )
        g_ts = jax.lax.pmin(best_ts, axis)
        idx = jax.lax.axis_index(axis)
        nch = jax.lax.axis_size(axis)
        chip_rank = jnp.where(best_ts == g_ts, idx, jnp.int32(nch))
        g_chip = jax.lax.pmin(chip_rank, axis)
        probe_allowed = (g_ts < big) & (idx == g_chip)
        # Pass 2: the real step with over-grants demoted.
        new_stats, new_fdyn, new_ddyn, new_pdyn, result = flush_entries(
            stats_x, flow_dev, flow_dyn, ddev, ddyn_x, pdyn, sysdev, batch2,
            occupy_timeout_ms=occupy_timeout_ms, probe_allowed=probe_allowed,
            param_pre=param_pre, shaping_pre=shaping_pre,
        )
        # The serializing scans own their state families: the global
        # shaping scan's pacer columns and the global param scan's
        # buckets (plus thread-gauge increments for finally-admitted
        # entries, ORed across owner chips) replace the untouched
        # pass-through values.
        if new_fdyn_scan is not None:
            new_fdyn = new_fdyn_scan
        if new_pdyn_scan is not None:
            from sentinel_tpu.models import constants as _C

            adm_bit = p_owner & param_g.valid & result.admitted[p_lidx]
            adm_item = jax.lax.psum(adm_bit.astype(jnp.int32), axis) > 0
            inc = param_g.valid & (param_g.grade == _C.FLOW_GRADE_THREAD) & adm_item
            pr = new_pdyn_scan.threads.shape[0]
            inc_rows = jnp.where(inc, param_g.prow, jnp.int32(pr))
            new_pdyn = new_pdyn_scan._replace(
                threads=new_pdyn_scan.threads.at[inc_rows].add(1, mode="drop")
            )
        # Bases are the GLOBAL post-exit views (replicated identical on
        # every chip): the merges then sum exactly the per-chip entry
        # deltas, with the exit deltas counted once inside the base.
        merged = merge_stats_across(stats_x, new_stats, axis)
        merged_ddyn = merge_breaker(ddyn_x, new_ddyn)
        return merged, new_fdyn, merged_ddyn, new_pdyn, result

    # Shaping/param item batches are replicated (P() pytree prefix):
    # every chip holds the full global item set for the scans.
    in_specs = [P(), P(), P(), P(), P(), P(), P(), batch_partition_specs(axis)]
    if with_shaping:
        in_specs.append(P())
    if with_param:
        in_specs.append(P())
    names = [
        kw for kw, on in (("shaping_g", with_shaping), ("param_g", with_param)) if on
    ]
    body = lambda *a: sharded_step(*a[:8], **dict(zip(names, a[8:])))  # noqa: E731
    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P(), P(), P(), P(axis)),
        check_vma=False,
    )
    return jax.jit(fn)
