"""ICI-collective building blocks for multi-chip flow control.

This module is the TPU-native replacement for the reference's
token-server RPC (SURVEY.md §5 "Distributed communication backend"):
instead of every app instance RPCing a single Netty server that owns the
global ClusterMetric (reference: sentinel-cluster-server-default/.../
flow/ClusterFlowChecker.java:36-118), every chip holds replicated
counter tensors, processes its shard of the entry batch, and the merged
global state is reconstructed with ``psum``/``pmax``/``pmin`` inside the
jitted step — one ICI all-reduce instead of a network round-trip.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.metrics.metric_array import MetricArrayState
from sentinel_tpu.metrics.nodes import StatsState


def merge_window_across(old: MetricArrayState, new: MetricArrayState, axis: str) -> MetricArrayState:
    """Rollover-aware all-reduce of one window array.

    A device that rolled a bucket to a newer window zeroed the old
    counts, so a naive delta-psum would subtract the old counts once per
    rolling device. Instead: the merged window start is the max across
    devices; only devices whose final window matches it contribute
    (their counts minus the shared base, which is the old counts iff the
    old window already was the merged one).
    """
    g_ws = jax.lax.pmax(new.window_start, axis)
    old_cur = (old.window_start == g_ws)[:, :, None]
    new_cur = (new.window_start == g_ws)[:, :, None]
    base = jnp.where(old_cur, old.counts, 0)
    contrib = jnp.where(new_cur, new.counts - base, 0)
    counts = base + jax.lax.psum(contrib, axis)
    big = jnp.int32(2**31 - 1)
    min_rt = jnp.minimum(
        jnp.where(old.window_start == g_ws, old.min_rt, big),
        jax.lax.pmin(jnp.where(new.window_start == g_ws, new.min_rt, big), axis),
    )
    return MetricArrayState(counts=counts, min_rt=min_rt, window_start=g_ws)


def merge_stats_across(old: StatsState, new: StatsState, axis: str) -> StatsState:
    """All-reduce the full stats family (second + minute + thread gauge)."""
    return StatsState(
        second=merge_window_across(old.second, new.second, axis),
        minute=merge_window_across(old.minute, new.minute, axis),
        threads=old.threads + jax.lax.psum(new.threads - old.threads, axis),
    )


def cluster_allocate(
    axis: str, demand: jax.Array, capacity: jax.Array
) -> jax.Array:
    """Greedy chip-indexed allocation of global capacity.

    Each chip has ``demand`` admission candidates for a cluster rule;
    the global remaining capacity is split by exclusive prefix over the
    mesh axis: chip i may admit ``min(demand_i, capacity -
    sum_{j<i} demand_j)``. Deterministic and conserving — the analog of
    the token server serializing client requests in arrival order
    (arrival order there is nondeterministic; chip index here is).
    Shapes: demand/capacity broadcastable; returns per-chip grant.
    """
    idx = jax.lax.axis_index(axis)
    n = jax.lax.axis_size(axis)
    # Exclusive prefix sum over the axis via one-hot matmul-free trick:
    # gather all demands, mask those with lower index.
    all_d = jax.lax.all_gather(demand, axis)  # [n, ...]
    ranks = jnp.arange(n)
    shape = (n,) + (1,) * (all_d.ndim - 1)
    before = jnp.sum(jnp.where(ranks.reshape(shape) < idx, all_d, 0), axis=0)
    left = jnp.maximum(capacity - before, 0)
    return jnp.minimum(demand, left)


def batch_partition_specs(axis: str = "data"):
    """PartitionSpec pytree for a FlushBatch: entries/exits sharded over
    the mesh, scalars replicated."""
    from jax.sharding import PartitionSpec as P

    from sentinel_tpu.runtime.flush import FlushBatch

    return FlushBatch(
        now=P(),
        e_valid=P(axis),
        e_ts=P(axis),
        e_acquire=P(axis),
        e_rows=P(axis, None),
        e_rule_gid=P(axis, None),
        e_check_row=P(axis, None),
        e_prio=P(axis),
        e_auth_ok=P(axis),
        e_cluster_ok=P(axis),
        e_dgid=P(axis, None),
        x_valid=P(axis),
        x_ts=P(axis),
        x_count=P(axis),
        x_rows=P(axis, None),
        x_rt=P(axis),
        x_err=P(axis),
        x_thr=P(axis),
        x_dgid=P(axis, None),
    )


def make_sharded_flush(mesh, axis: str = "data"):
    """The full batched step over an n-device mesh.

    Entries and exits are data-parallel across chips; counter tensors
    and rule tables are replicated; after each local flush the window
    deltas and breaker state are all-reduced so every chip ends the step
    with the identical global state. Returns a jitted callable with the
    same signature as ``flush_step`` (without shaping/param batches —
    their per-rule scans are inherently serializing and stay
    single-chip for now).
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from sentinel_tpu.runtime.flush import flush_step

    def sharded_step(stats, flow_dev, flow_dyn, ddev, ddyn, pdyn, sysdev, batch):
        new_stats, new_fdyn, new_ddyn, new_pdyn, result = flush_step(
            stats, flow_dev, flow_dyn, ddev, ddyn, pdyn, sysdev, batch
        )
        merged = merge_stats_across(stats, new_stats, axis)
        merged_ddyn = type(ddyn)(
            state=jax.lax.pmax(new_ddyn.state, axis),
            next_retry=jax.lax.pmax(new_ddyn.next_retry, axis),
            bad=ddyn.bad + jax.lax.psum(new_ddyn.bad - ddyn.bad, axis),
            total=ddyn.total + jax.lax.psum(new_ddyn.total - ddyn.total, axis),
            ws=jax.lax.pmax(new_ddyn.ws, axis),
        )
        return merged, new_fdyn, merged_ddyn, new_pdyn, result

    fn = shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(), P(), P(), batch_partition_specs(axis)),
        out_specs=(P(), P(), P(), P(), P(axis)),
        check_vma=False,
    )
    return jax.jit(fn)
