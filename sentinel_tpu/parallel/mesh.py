"""Mesh construction helpers."""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np


def make_mesh(n_devices: Optional[int] = None, axis_name: str = "data"):
    """1-D mesh over the first ``n_devices`` devices (data-parallel over
    entries — the natural layout for flow-control traffic; counter rows
    are replicated and merged with collectives)."""
    import jax
    from jax.sharding import Mesh

    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(f"need {n_devices} devices, have {len(devices)}")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=(axis_name,))
