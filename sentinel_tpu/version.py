"""Framework version.

Mirrors the reference's version constant
(reference: sentinel-core/.../Constants.java:34, SENTINEL_VERSION = "1.8.4");
this framework tracks its own versioning.
"""

__version__ = "0.4.0"
