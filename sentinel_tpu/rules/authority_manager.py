"""Authority rule manager (reference: AuthorityRuleManager.java +
AuthorityRuleChecker.java:31-60). Origin white/black lists per resource;
the check itself is origin-id set membership, wired into the flush
kernel in the authority milestone."""

from __future__ import annotations

from typing import Dict, List

from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import AuthorityRule
from sentinel_tpu.rules.manager_base import RuleManager


class AuthorityRuleManager(RuleManager[AuthorityRule]):
    rule_kind = "authority"

    def __init__(self) -> None:
        super().__init__()
        # resource -> rule (reference keeps one rule per resource).
        self.by_resource: Dict[str, AuthorityRule] = {}

    def _apply(self, rules: List[AuthorityRule], engine) -> None:
        self.by_resource = {r.resource: r for r in rules if r.is_valid()}
        if engine is not None:
            engine.set_authority_rules(self.by_resource)

    @staticmethod
    def passes(rule: AuthorityRule, origin: str) -> bool:
        """AuthorityRuleChecker.passCheck: contains-check on the comma
        list, then white→must-contain / black→must-not-contain."""
        if not origin or not rule.limit_app:
            return True
        apps = {a.strip() for a in rule.limit_app.split(",")}
        contains = origin in apps
        if rule.strategy == C.AUTHORITY_BLACK:
            return not contains
        return contains


authority_rule_manager = AuthorityRuleManager()
