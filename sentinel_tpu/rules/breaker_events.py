"""Circuit-breaker state-change observers.

Reference: EventObserverRegistry (sentinel-core/.../slots/block/degrade/
circuitbreaker/EventObserverRegistry.java) and
CircuitBreakerStateChangeObserver.onStateChange(prevState, newState,
rule, snapshotValue) — callbacks fired exactly once per transition
(the CAS-once contract, AbstractCircuitBreaker.java:40-150), used for
alerting on CLOSED→OPEN etc.

TPU-first shape: transitions happen INSIDE the flush kernel on
device-resident state (rules/degrade_table.py), so observers are
detected host-side by an opt-in post-flush state diff that piggybacks
on the verdict fetch — zero extra device round-trips, and the
zero-observer path is completely unchanged. Because a whole flush's
transitions surface at once, a rule that trips AND recovers within one
flush reports the net edge (state_before → state_after), not the
intermediate hop — the batched analog of the reference's point-in-time
callbacks. Two more consequences of the opt-in design (enforced by the
engine's epoch/seq mirror discipline, Engine._apply_breaker_snapshot):
transitions during flushes that ran with NO observers registered are
not replayed later (the first observed flush resyncs silently), and a
rule reload starts a fresh epoch so in-flight async fetches from the
old rule world can never fire against the new one.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from sentinel_tpu.utils.record_log import record_log

# State codes (rules/degrade_table.py:39-41).
STATE_NAMES = {0: "CLOSED", 1: "OPEN", 2: "HALF_OPEN"}

# observer(prev_state, new_state, rule, resource) — prev/new are the
# int codes above; ``rule`` is the DegradeRule that transitioned.
StateChangeObserver = Callable[[int, int, object, str], None]

_lock = threading.Lock()
_observers: Dict[str, StateChangeObserver] = {}


def add_state_change_observer(name: str, observer: StateChangeObserver) -> None:
    """EventObserverRegistry.addStateChangeObserver."""
    if not name or observer is None:
        raise ValueError("observer name and callable are required")
    with _lock:
        _observers[name] = observer


def remove_state_change_observer(name: str) -> bool:
    """EventObserverRegistry.removeStateChangeObserver."""
    with _lock:
        return _observers.pop(name, None) is not None


def get_state_change_observer(name: str) -> Optional[StateChangeObserver]:
    with _lock:
        return _observers.get(name)


def has_observers() -> bool:
    return bool(_observers)


def clear() -> None:
    with _lock:
        _observers.clear()


def fire_transitions(prev_states, new_states, dindex) -> None:
    """Diff two host state vectors and notify every observer of each
    changed rule. Observer exceptions are logged, never propagated —
    a broken alert hook must not fail the flush's verdict fill."""
    with _lock:
        observers = list(_observers.items())
    if not observers:
        return
    for gid in range(min(len(prev_states), len(new_states))):
        prev, new = int(prev_states[gid]), int(new_states[gid])
        if prev == new:
            continue
        rule = dindex.rules[gid] if gid < len(dindex.rules) else None
        resource = getattr(rule, "resource", "")
        for name, obs in observers:
            try:
                obs(prev, new, rule, resource)
            except Exception:
                record_log.error(
                    f"[BreakerEvents] observer {name!r} failed", exc_info=True
                )
