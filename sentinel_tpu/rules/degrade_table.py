"""Circuit breakers — the degrade subsystem, batched.

The reference implements two breaker families on a CLOSED/OPEN/HALF_OPEN
CAS state machine with a per-rule 1-bucket sliding window (reference:
slots/block/degrade/circuitbreaker/AbstractCircuitBreaker.java:40-150,
ExceptionCircuitBreaker.java:35-134, ResponseTimeCircuitBreaker.java:34-120,
DegradeSlot.java:37-90). Here every breaker is one row of SoA columns:

static (DegradeTableDevice):  grade / threshold / slow-ratio /
    min-request / stat-interval / retry-timeout / max-allowed-RT
dynamic (DegradeDynState):    state / next-retry / bad / total / window-start

Exit-driven transitions are computed *per prefix*, not per batch total:
the reference evaluates the threshold after every completed request, and
an error ratio is not monotone within a bucket (later successes dilute
it), so the batched kernel computes cumulative (bad, total) at every
exit in (rule, ts) order and opens the breaker at the FIRST prefix that
crosses — exactly the sequential outcome — all with cumsum/segment math,
no scan. Entry-side probing admits exactly one candidate per OPEN
breaker whose retry timeout arrived (rank 0 in ts order), mirroring
fromOpenToHalfOpen; the HALF_OPEN transition is applied only if that
entry is admitted end-to-end, which reproduces the reference's
``whenTerminate`` revert workaround for probes blocked by later rules.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import DegradeRule
from sentinel_tpu.utils.numeric import pad_pow2
from sentinel_tpu.utils.record_log import record_log

_NO_GIDS: list = []  # shared empty default for gids_for (never mutated)

# Breaker states (CircuitBreaker.State ordinals).
CLOSED = 0
OPEN = 1
HALF_OPEN = 2

_I32_MAX = 2**31 - 1


class DegradeTableDevice(NamedTuple):
    grade: jax.Array  # int32 [ND]
    threshold: jax.Array  # float32 [ND] rule count (ratio / count / RT)
    slow_ratio: jax.Array  # float32 [ND]
    min_request: jax.Array  # int32 [ND]
    interval_ms: jax.Array  # int32 [ND] statIntervalMs (per-rule window)
    retry_ms: jax.Array  # int32 [ND] timeWindow * 1000
    max_rt: jax.Array  # int32 [ND] Math.round(count) for RT breakers

    @property
    def n_rules(self) -> int:
        return self.grade.shape[0]


class DegradeDynState(NamedTuple):
    state: jax.Array  # int32 [ND]
    next_retry: jax.Array  # int32 [ND]
    bad: jax.Array  # int32 [ND] slow/error count in current window
    total: jax.Array  # int32 [ND]
    ws: jax.Array  # int32 [ND] current window start


class DegradeIndex:
    """Host-side compiled degrade rules (DegradeRuleManager equivalent)."""

    def __init__(self, rules: Sequence[DegradeRule]) -> None:
        valid = []
        for r in rules:
            if r.is_valid():
                valid.append(r)
            else:
                record_log.warn("[DegradeIndex] Ignoring invalid degrade rule: %s", r)
        self.rules: List[DegradeRule] = valid
        self.by_resource: Dict[str, List[int]] = {}
        for gid, r in enumerate(valid):
            self.by_resource.setdefault(r.resource, []).append(gid)
        self.max_rules_per_resource = max(
            (len(v) for v in self.by_resource.values()), default=0
        )
        self.device = self._build_device()

    def _build_device(self) -> DegradeTableDevice:
        n = pad_pow2(len(self.rules), 8)
        grade = [C.DEGRADE_GRADE_RT] * n
        thr = [float("inf")] * n
        slow_ratio = [1.0] * n
        min_req = [_I32_MAX] * n  # padding never trips
        interval = [1000] * n
        retry = [0] * n
        max_rt = [_I32_MAX] * n
        for gid, r in enumerate(self.rules):
            grade[gid] = r.grade
            thr[gid] = float(r.count)
            slow_ratio[gid] = float(r.slow_ratio_threshold)
            min_req[gid] = int(r.min_request_amount)
            interval[gid] = int(r.stat_interval_ms)
            retry[gid] = int(r.time_window) * 1000
            # Java: maxAllowedRt = Math.round(rule.getCount()).
            max_rt[gid] = int(r.count + 0.5)
        return DegradeTableDevice(
            grade=jnp.array(grade, dtype=jnp.int32),
            threshold=jnp.array(thr, dtype=jnp.float32),
            slow_ratio=jnp.array(slow_ratio, dtype=jnp.float32),
            min_request=jnp.array(min_req, dtype=jnp.int32),
            interval_ms=jnp.array(interval, dtype=jnp.int32),
            retry_ms=jnp.array(retry, dtype=jnp.int32),
            max_rt=jnp.array(max_rt, dtype=jnp.int32),
        )

    def make_dyn_state(self) -> DegradeDynState:
        n = self.device.n_rules
        return DegradeDynState(
            state=jnp.full((n,), CLOSED, dtype=jnp.int32),
            next_retry=jnp.zeros((n,), dtype=jnp.int32),
            bad=jnp.zeros((n,), dtype=jnp.int32),
            total=jnp.zeros((n,), dtype=jnp.int32),
            ws=jnp.full((n,), -(10**9), dtype=jnp.int32),
        )

    def gids_for(self, resource: str) -> List[int]:
        # Shared immutable default: this runs once per submitted entry,
        # so a per-call empty-list allocation is measurable host cost.
        return self.by_resource.get(resource, _NO_GIDS)

    def rule_of_gid(self, gid: int):
        if 0 <= gid < len(self.rules):
            return self.rules[gid]
        return None


def mirror_any_open(mirror, gids) -> bool:
    """Host-mirror hook: True when any of ``gids`` is OPEN in the
    engine's host breaker mirror array — the one read shared by the
    degraded fallback and the speculative tier (the mirror itself is
    kept by the engine's breaker-event machinery, which the speculative
    tier rides on every flush)."""
    n = mirror.shape[0]
    for dg in gids:
        if 0 <= dg < n and mirror[dg] == OPEN:
            return True
    return False


def trip_condition(
    grade: jax.Array,  # int32 — per-element grade (gathered or full table)
    threshold: jax.Array,  # float32
    slow_ratio: jax.Array,  # float32
    bad: jax.Array,  # float32
    total: jax.Array,  # float32
) -> jax.Array:
    """The CLOSED→OPEN threshold predicate, shared by the per-exit
    prefix evaluation and the sharded path's merged-count re-check.

    RT breakers open when slowRatio exceeds the configured ratio, with
    the ratio==1.0 boundary opening when the threshold is >= 1
    (ResponseTimeCircuitBreaker.java:120-130); exception-ratio compares
    the ratio, exception-count the absolute count
    (ExceptionCircuitBreaker.java:110-134). min_request gating is the
    caller's job (it differs between prefix and merged evaluation).
    """
    ratio = bad / jnp.maximum(total, 1.0)
    is_rt = grade == C.DEGRADE_GRADE_RT
    is_exc_ratio = grade == C.DEGRADE_GRADE_EXCEPTION_RATIO
    rt_trip = (ratio > slow_ratio) | ((slow_ratio >= 1.0) & (ratio >= 1.0))
    return jnp.where(
        is_rt, rt_trip, jnp.where(is_exc_ratio, ratio > threshold, bad > threshold)
    )


def _segment_cum(new_grp: jax.Array, x: jax.Array) -> jax.Array:
    """Inclusive per-segment cumulative sum (segments flagged at starts)."""
    total = jnp.cumsum(x)
    excl = total - x
    base = jax.lax.cummax(jnp.where(new_grp, excl, 0))
    return total - base


def breaker_on_exits(
    ddev: DegradeTableDevice,
    dyn: DegradeDynState,
    x_dgid: jax.Array,  # int32 [M, KD] (-1 empty)
    x_ts: jax.Array,  # int32 [M]
    x_rt: jax.Array,  # int32 [M]
    x_err: jax.Array,  # int32 [M] (>0 = business error recorded)
    x_valid: jax.Array,  # bool [M]
) -> DegradeDynState:
    """onRequestComplete for a batch of completions (exit ops)."""
    m, kd = x_dgid.shape
    nd = ddev.n_rules
    gid_f = x_dgid.reshape(-1)
    eidx = jnp.arange(m * kd, dtype=jnp.int32) // kd
    valid = (gid_f >= 0) & x_valid[eidx]
    ts_f = x_ts[eidx]
    rt_f = x_rt[eidx]
    err_f = x_err[eidx]

    gid_key = jnp.where(valid, gid_f, jnp.int32(nd))
    pos = jnp.arange(m * kd, dtype=jnp.int32)
    gid_s, ts_s, p_s = jax.lax.sort((gid_key, ts_f, pos), num_keys=2)
    gid_c = jnp.clip(gid_s, 0, nd - 1)
    valid_s = valid[p_s]
    rt_s = rt_f[p_s]
    err_s = err_f[p_s]

    grade = ddev.grade[gid_c]
    is_rt = grade == C.DEGRADE_GRADE_RT
    bad_s = jnp.where(is_rt, rt_s > ddev.max_rt[gid_c], err_s > 0) & valid_s

    ones = jnp.ones((1,), dtype=bool)
    new_grp = jnp.concatenate([ones, gid_s[1:] != gid_s[:-1]])

    # ---- window rollover (per-rule interval, 1 bucket) ----
    iv = ddev.interval_ms[gid_c]
    aligned = ts_s - ts_s % jnp.maximum(iv, 1)
    ws_new = dyn.ws.at[jnp.where(valid_s, gid_c, jnp.int32(nd))].max(aligned, mode="drop")
    rolled = ws_new > dyn.ws
    base_bad = jnp.where(rolled, 0, dyn.bad)
    base_total = jnp.where(rolled, 0, dyn.total)
    # Exits from a superseded window do not contribute (sequentially the
    # newer request reset the bucket after them).
    in_win = valid_s & (aligned == ws_new[gid_c])

    inc = in_win.astype(jnp.int32)
    bad_inc = (bad_s & in_win).astype(jnp.int32)
    cum_total = _segment_cum(new_grp, inc)
    cum_bad = _segment_cum(new_grp, bad_inc)

    g_base_bad = base_bad[gid_c]
    g_base_total = base_total[gid_c]
    run_bad = (g_base_bad + cum_bad).astype(jnp.float32)
    run_total = (g_base_total + cum_total).astype(jnp.float32)

    # ---- CLOSED -> OPEN: first prefix crossing the threshold ----
    trip = trip_condition(
        grade, ddev.threshold[gid_c], ddev.slow_ratio[gid_c], run_bad, run_total
    )
    crossing = in_win & (run_total >= ddev.min_request[gid_c]) & trip

    was_closed = dyn.state == CLOSED
    crossing_eff = crossing & was_closed[gid_c]
    gid_cross = jnp.where(crossing_eff, gid_c, jnp.int32(nd))
    first_cross_ts = (
        jnp.full((nd,), _I32_MAX, dtype=jnp.int32).at[gid_cross].min(ts_s, mode="drop")
    )
    opened = first_cross_ts < _I32_MAX

    # ---- HALF_OPEN probe outcome: decided by the FIRST completion ----
    was_half = dyn.state == HALF_OPEN
    seg_start = new_grp & valid_s & was_half[gid_c]
    gid_first = jnp.where(seg_start, gid_c, jnp.int32(nd))
    probe_bad = jnp.zeros((nd,), dtype=jnp.int32).at[gid_first].max(
        bad_s.astype(jnp.int32), mode="drop"
    )
    probe_seen = jnp.zeros((nd,), dtype=jnp.int32).at[gid_first].max(1, mode="drop") > 0
    probe_ts = jnp.full((nd,), 0, dtype=jnp.int32).at[gid_first].max(ts_s, mode="drop")

    # ---- final per-rule accumulation + state resolution ----
    gid_scatter = jnp.where(in_win, gid_c, jnp.int32(nd))
    total_new = base_total.at[gid_scatter].add(inc, mode="drop")
    bad_new = base_bad.at[gid_scatter].add(bad_inc, mode="drop")

    state = dyn.state
    next_retry = dyn.next_retry
    # CLOSED -> OPEN
    state = jnp.where(was_closed & opened, OPEN, state)
    next_retry = jnp.where(
        was_closed & opened, first_cross_ts + ddev.retry_ms, next_retry
    )
    # HALF_OPEN -> OPEN / CLOSED (probe outcome; CLOSED resets the bucket,
    # ExceptionCircuitBreaker.resetStat / fromHalfOpenToClose)
    half_to_open = was_half & probe_seen & (probe_bad > 0)
    half_to_close = was_half & probe_seen & (probe_bad == 0)
    state = jnp.where(half_to_open, OPEN, state)
    next_retry = jnp.where(half_to_open, probe_ts + ddev.retry_ms, next_retry)
    state = jnp.where(half_to_close, CLOSED, state)
    total_new = jnp.where(half_to_close, 0, total_new)
    bad_new = jnp.where(half_to_close, 0, bad_new)

    return DegradeDynState(
        state=state, next_retry=next_retry, bad=bad_new, total=total_new, ws=ws_new
    )


def breaker_try_pass(
    ddev: DegradeTableDevice,
    dyn: DegradeDynState,
    e_dgid: jax.Array,  # int32 [N, KD]
    e_ts: jax.Array,  # int32 [N]
    e_live: jax.Array,  # bool [N] — entries not blocked by earlier slots
    probe_allowed: Optional[jax.Array] = None,  # bool [ND]
) -> Tuple[jax.Array, jax.Array]:
    """tryPass for a batch of entries.

    Returns (slot_ok [N,KD], probe_slot [N,KD]) — probe_slot marks the
    single admitted OPEN->HALF_OPEN probe candidate per breaker; the
    caller applies the HALF_OPEN transition only for entries admitted
    end-to-end. ``probe_allowed`` restricts which breakers this batch
    may probe at all — the sharded path's cross-chip election passes
    the per-chip winner mask so only ONE chip (hence one entry) probes
    each OPEN breaker (fromOpenToHalfOpen is a single CAS in the
    reference, AbstractCircuitBreaker.java:91-110).
    """
    n, kd = e_dgid.shape
    nd = ddev.n_rules
    gid_f = e_dgid.reshape(-1)
    eidx = jnp.arange(n * kd, dtype=jnp.int32) // kd
    valid = (gid_f >= 0) & e_live[eidx]
    ts_f = e_ts[eidx]

    gid_c = jnp.clip(gid_f, 0, nd - 1)
    st = dyn.state[gid_c]
    closed = st == CLOSED
    open_ = st == OPEN
    retry_ok = ts_f >= dyn.next_retry[gid_c]
    candidate = valid & open_ & retry_ok
    if probe_allowed is not None:
        candidate = candidate & probe_allowed[gid_c]

    # rank-0 candidate per breaker gets the probe.
    gid_key = jnp.where(candidate, gid_f, jnp.int32(nd))
    pos = jnp.arange(n * kd, dtype=jnp.int32)
    # pos subsumes eidx as tie-break (eidx == pos // kd is
    # nondecreasing in pos): one less sort operand, deterministic.
    gid_s, ts_s, p_s = jax.lax.sort((gid_key, ts_f, pos), num_keys=3)
    ones = jnp.ones((1,), dtype=bool)
    new_grp = jnp.concatenate([ones, gid_s[1:] != gid_s[:-1]])
    first_s = new_grp & (gid_s < nd)
    probe_flat = jnp.zeros((n * kd,), dtype=bool).at[p_s].set(first_s)

    ok = closed | probe_flat
    ok = ok | ~valid
    return ok.reshape(n, kd), (probe_flat & valid).reshape(n, kd)


def apply_probe_transitions(
    dyn: DegradeDynState,
    e_dgid: jax.Array,  # int32 [N, KD]
    probe_slot: jax.Array,  # bool [N, KD]
    admitted: jax.Array,  # bool [N]
) -> DegradeDynState:
    """OPEN -> HALF_OPEN for probes whose entry was admitted end-to-end."""
    n, kd = e_dgid.shape
    nd = dyn.state.shape[0]
    go = probe_slot & admitted[:, None]
    gid = jnp.where(go, e_dgid, jnp.int32(nd)).reshape(-1)
    state = dyn.state.at[gid].set(HALF_OPEN, mode="drop")
    return dyn._replace(state=state)
