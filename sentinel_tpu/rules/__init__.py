"""Rule engine: compilation of rule beans into device SoA tensors plus
the host-side indexes the op encoder uses.

Equivalent of the reference's rule managers + checkers
(reference: sentinel-core/.../slots/block/flow/FlowRuleManager.java,
FlowRuleUtil.java:84-161, FlowRuleChecker.java:44-230 and the sibling
Degrade/System/Authority/ParamFlow managers). Where the reference builds
one ``TrafficShapingController`` object per rule, this build compiles
all rules of a kind into parallel arrays (grade/count/behavior/...) that
one vectorized kernel evaluates for the whole batch at once; a rule
update rebuilds the arrays and swaps them in (the analog of the
volatile map swap in FlowRuleManager.java:159).
"""

from typing import List


def all_managers() -> List[object]:
    from sentinel_tpu.rules.authority_manager import authority_rule_manager
    from sentinel_tpu.rules.degrade_manager import degrade_rule_manager
    from sentinel_tpu.rules.flow_manager import flow_rule_manager
    from sentinel_tpu.rules.param_manager import param_flow_rule_manager
    from sentinel_tpu.rules.system_manager import system_rule_manager

    return [
        flow_rule_manager,
        degrade_rule_manager,
        system_rule_manager,
        authority_rule_manager,
        param_flow_rule_manager,
    ]
