"""Common rule-manager machinery.

Every reference rule manager follows one shape (reference:
FlowRuleManager.java:56-170): a static rule map, a SentinelProperty it
listens on, ``loadRules`` = ``property.updateValue``, and
``register2Property`` to re-bind to a datasource's property. This base
class reproduces that shape; subclasses implement ``_apply`` to compile
and push the new rule set into the engine.
"""

from __future__ import annotations

import threading
from typing import Generic, List, Optional, Sequence, TypeVar

from sentinel_tpu.core.property import (
    DynamicSentinelProperty,
    PropertyListener,
    SentinelProperty,
)
from sentinel_tpu.utils.record_log import record_log

R = TypeVar("R")


class RuleManager(Generic[R]):
    rule_kind = "rule"

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rules: List[R] = []
        self._listener = _ManagerListener(self)
        self._property: SentinelProperty = DynamicSentinelProperty()
        self._property.add_listener(self._listener)

    def load_rules(self, rules: Optional[Sequence[R]]) -> None:
        """FlowRuleManager.loadRules: push through the property so
        datasource-driven and manual updates share one path."""
        self._property.update_value(list(rules) if rules else [])

    def register_property(self, prop: SentinelProperty) -> None:
        """FlowRuleManager.register2Property."""
        with self._lock:
            self._property.remove_listener(self._listener)
            self._property = prop
            prop.add_listener(self._listener)

    def get_rules(self) -> List[R]:
        with self._lock:
            return list(self._rules)

    def has_rules(self) -> bool:
        with self._lock:
            return bool(self._rules)

    def clear(self) -> None:
        self.load_rules([])

    # -- internal --
    def _on_update(self, rules: Optional[Sequence[R]]) -> None:
        rules = list(rules) if rules else []
        with self._lock:
            self._rules = rules
            try:
                self._apply(rules)
            except Exception:
                record_log.error(
                    "[%s] Failed to apply rules", type(self).__name__, exc_info=True
                )
        record_log.info("[%s] Rules loaded: %d", type(self).__name__, len(rules))

    def _apply(self, rules: List[R]) -> None:
        raise NotImplementedError


class _ManagerListener(PropertyListener):
    def __init__(self, mgr: RuleManager) -> None:
        self._mgr = mgr

    def config_update(self, value) -> None:
        self._mgr._on_update(value)
