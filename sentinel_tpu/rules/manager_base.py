"""Common rule-manager machinery.

Every reference rule manager follows one shape (reference:
FlowRuleManager.java:56-170): a static rule map, a SentinelProperty it
listens on, ``loadRules`` = ``property.updateValue``, and
``register2Property`` to re-bind to a datasource's property. This base
class reproduces that shape; subclasses implement ``_apply`` to compile
and push the new rule set into the engine.
"""

from __future__ import annotations

import threading
from typing import Generic, List, Optional, Sequence, TypeVar

from sentinel_tpu.core.property import (
    DynamicSentinelProperty,
    PropertyListener,
    SentinelProperty,
)
from sentinel_tpu.utils.record_log import record_log

R = TypeVar("R")


class RuleManager(Generic[R]):
    rule_kind = "rule"

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._rules: List[R] = []
        # Monotonic store counter vs. the last version pushed into an
        # engine: lets the boot path's second re-apply pass skip
        # managers whose rules were already applied (no double compile).
        self._version = 0
        self._applied_version = 0
        self._listener = _ManagerListener(self)
        self._property: SentinelProperty = DynamicSentinelProperty()
        self._property.add_listener(self._listener)

    def load_rules(self, rules: Optional[Sequence[R]]) -> None:
        """FlowRuleManager.loadRules: push through the property so
        datasource-driven and manual updates share one path."""
        self._property.update_value(list(rules) if rules else [])

    def register_property(self, prop: SentinelProperty) -> None:
        """FlowRuleManager.register2Property."""
        with self._lock:
            self._property.remove_listener(self._listener)
            self._property = prop
            prop.add_listener(self._listener)

    def get_rules(self) -> List[R]:
        with self._lock:
            return list(self._rules)

    def has_rules(self) -> bool:
        with self._lock:
            return bool(self._rules)

    def clear(self) -> None:
        """Imperative reset (api.reset / tests) — deliberately NOT a
        ``load_rules([])``: the property dedups equal values, so a
        clear while the stored list is already empty would never fire
        ``_apply`` — yet _apply must still run, because it also pushes
        manager-held derived state (e.g. the gateway-converted param
        rules) into the CURRENT engine, which api.reset has just
        replaced with a fresh one. The property's cached value resets
        too, so a later datasource re-push of the same config is not
        silently deduped either."""
        reset = getattr(self._property, "reset_value", None)
        if reset is not None:
            reset()
            self._on_update([])
        elif not self._property.update_value(None):
            # Custom property without reset_value: update_value(None)
            # clears the cache AND fires _on_update through the
            # listener; when the cache was already None (deduped), the
            # apply still must run — it re-pushes manager-held derived
            # state into the current engine.
            self._on_update([])

    def re_apply(self, engine) -> None:
        """Push the stored rules into the given engine if they haven't
        been pushed yet. Called by ``api.get_engine()`` on first engine
        construction, so rules loaded before any entry call (the
        reference allows loadRules before InitExecutor.doInit runs) are
        not lost."""
        with self._lock:
            if self._version == self._applied_version:
                return
            self._applied_version = self._version
            if self._has_pending_state():
                self._apply(self._rules, engine)

    def _has_pending_state(self) -> bool:
        return bool(self._rules)

    # -- internal --
    def _on_update(self, rules: Optional[Sequence[R]]) -> None:
        from sentinel_tpu.core.api import peek_engine

        rules = list(rules) if rules else []
        with self._lock:
            self._rules = rules
            self._version += 1
            # Do not force engine construction from a rule load: module
            # import instantiates the managers with an empty load, and
            # creating the Engine allocates device tensors — importing
            # this library must never commit a JAX backend. When no
            # engine exists, _apply still runs (manager-local derived
            # state like SystemRuleManager.effective must track the
            # stored rules) with engine=None, and the engine push
            # happens when the engine first comes up (re_apply).
            # NOTE: the peek must happen AFTER storing self._rules (the
            # boot thread's post-publication re_apply pass then cannot
            # miss them), and _apply receives the peeked engine rather
            # than calling get_engine() — taking api._engine_lock while
            # holding self._lock would invert the boot path's lock order
            # (ABBA deadlock with _reapply_all_managers).
            engine = peek_engine()
            applied = engine is not None
            if applied:
                self._applied_version = self._version
            try:
                self._apply(rules, engine)
            except Exception:
                record_log.error(
                    "[%s] Failed to apply rules", type(self).__name__, exc_info=True
                )
        record_log.info(
            "[%s] Rules loaded: %d%s",
            type(self).__name__,
            len(rules),
            "" if applied else " (stored; engine not yet up)",
        )

    def _apply(self, rules: List[R], engine) -> None:
        raise NotImplementedError


class _ManagerListener(PropertyListener):
    def __init__(self, mgr: RuleManager) -> None:
        self._mgr = mgr

    def config_update(self, value) -> None:
        self._mgr._on_update(value)
