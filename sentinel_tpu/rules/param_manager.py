"""Hot-parameter flow rule manager (reference:
sentinel-extension/sentinel-parameter-flow-control/.../ParamFlowRuleManager.java).
Rule storage now; hashed-row token buckets in the param-flow milestone
(SURVEY.md §7 stage 5)."""

from __future__ import annotations

from typing import Dict, List

from sentinel_tpu.models.rules import ParamFlowRule
from sentinel_tpu.rules.manager_base import RuleManager


class ParamFlowRuleManager(RuleManager[ParamFlowRule]):
    rule_kind = "param-flow"

    def __init__(self) -> None:
        # Fields _apply READS must exist before super().__init__():
        # the base class attaches the property listener there, and
        # DynamicSentinelProperty.add_listener fires config_load
        # synchronously — which runs _apply on this half-built
        # instance.
        self.by_resource: Dict[str, List[ParamFlowRule]] = {}
        # Converted gateway rules contribute alongside user rules
        # (GatewayRuleManager feeds GatewayFlowSlot via param checking
        # in the reference; here both share the engine's param index).
        self._gateway_rules: List[ParamFlowRule] = []
        super().__init__()

    def set_gateway_rules(self, rules: List[ParamFlowRule]) -> None:
        from sentinel_tpu.core.api import peek_engine

        with self._lock:
            self._gateway_rules = list(rules)
            self._version += 1
            engine = peek_engine()
            if engine is not None:
                self._applied_version = self._version
            self._apply(self._rules, engine)
        # engine None: stored; the boot re_apply pass folds them in.

    def _has_pending_state(self) -> bool:
        # Gateway-converted rules count as stored rules too — without
        # this, a gateway-only config loaded pre-boot would never reach
        # the engine (base re_apply skips when nothing is pending).
        return bool(self._rules or self._gateway_rules)

    def _apply(self, rules: List[ParamFlowRule], engine) -> None:
        # engine.set_param_rules builds a FRESH ParamIndex: every
        # value→prow interning (and the host-ingest resolved-value
        # cache riding it) is invalidated here, exactly like the
        # reference clearing ParameterMetric on reload — a reload must
        # never serve stale prow mappings to in-flight traffic.
        by_res: Dict[str, List[ParamFlowRule]] = {}
        for r in list(rules) + self._gateway_rules:
            if r.is_valid():
                by_res.setdefault(r.resource, []).append(r)
        self.by_resource = by_res
        if engine is not None:
            engine.set_param_rules(by_res)


param_flow_rule_manager = ParamFlowRuleManager()
