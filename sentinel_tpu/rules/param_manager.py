"""Hot-parameter flow rule manager (reference:
sentinel-extension/sentinel-parameter-flow-control/.../ParamFlowRuleManager.java).
Rule storage now; hashed-row token buckets in the param-flow milestone
(SURVEY.md §7 stage 5)."""

from __future__ import annotations

from typing import Dict, List

from sentinel_tpu.models.rules import ParamFlowRule
from sentinel_tpu.rules.manager_base import RuleManager


class ParamFlowRuleManager(RuleManager[ParamFlowRule]):
    rule_kind = "param-flow"

    def __init__(self) -> None:
        super().__init__()
        self.by_resource: Dict[str, List[ParamFlowRule]] = {}
        # Converted gateway rules contribute alongside user rules
        # (GatewayRuleManager feeds GatewayFlowSlot via param checking
        # in the reference; here both share the engine's param index).
        self._gateway_rules: List[ParamFlowRule] = []

    def set_gateway_rules(self, rules: List[ParamFlowRule]) -> None:
        self._gateway_rules = list(rules)
        self._apply(self.get_rules())

    def _apply(self, rules: List[ParamFlowRule]) -> None:
        by_res: Dict[str, List[ParamFlowRule]] = {}
        for r in list(rules) + self._gateway_rules:
            if r.is_valid():
                by_res.setdefault(r.resource, []).append(r)
        self.by_resource = by_res
        from sentinel_tpu.core.api import get_engine

        engine = get_engine()
        if hasattr(engine, "set_param_rules"):
            engine.set_param_rules(by_res)


param_flow_rule_manager = ParamFlowRuleManager()
