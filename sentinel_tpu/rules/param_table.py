"""Hot-parameter flow control — ParamFlowChecker, batched.

The reference rate-limits per *parameter value*: a CacheMap per rule maps
each seen value to token/time counters (reference: sentinel-extension/
sentinel-parameter-flow-control/.../ParamFlowChecker.java:46-280,
ParameterMetric.java:37-108, caps 4000 values/rule base — scaled by
durationSec, total 200k). Here every (rule, value) pair is interned by
the host to a **param row** in SoA state columns:

    tokens / last_add   — the simplified token bucket (passDefaultLocalCheck)
    latest              — the throttle pacer (passThrottleLocalCheck)
    threads             — per-value concurrency (FLOW_GRADE_THREAD)

Like the shaping controllers, per-value checks are a recurrence over
that value's request sequence, resolved by one ``lax.scan`` over the
batch's param slots sorted by (row, ts, entry). LRU eviction happens on
the host; evicted rows are recycled and reset by the kernel on the next
flush (the CacheMap eviction equivalent).

Semantics preserved exactly (single-threaded collapse of the CAS loops):

* token bucket: first-seen value => tokens = maxCount - acquire, pass;
  within a window => decrement-if-enough; past the window => refill
  ``passTime*tokenCount/durationMs`` (integer division), clamp at
  maxCount, reject if the post-consume balance would go negative —
  without touching state on reject (the CAS-failure return path);
* throttle: cost = round(1000*acquire*durationSec/tokenCount) computed
  host-side in float64; first-seen passes free; queueing accepts waits
  STRICTLY below maxQueueingTimeMs and records ``latest = expected``;
* per-value thread grade: ++threadCount <= threshold, incremented only
  for entries admitted end-to-end (the StatisticSlot callback path),
  decremented at exit;
* hot items (paramFlowItemList) override the threshold per value,
  matched by string form of the value.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ParamFlowRule
from sentinel_tpu.utils.config import config
from sentinel_tpu.utils.record_log import record_log

PARAM_NEVER = -(2**30)  # "no state yet" sentinel for last_add/latest

# Closed-form rank path: max (row, ts) sub-segments per value row before
# the selector falls back to rounds/scan. Each sub-segment costs one
# vectorized pass in the unrolled segment loop, so this bounds compile
# size; realistic gateway batches straddle at most a window edge or two
# (2-3 distinct timestamps per hot value).
PARAM_CLOSED_MAX_SEGMENTS = 8

# Cache-miss marker for the resolved-value fast path (identity compare
# only — never equal to a real (prow, tc, cost) triple).
_MISS = object()
_NO_TRIP = (0, 0, 0)


class ArgsColumns:
    """Columnar args for ``Engine.submit_bulk``: one value column per
    ``param_idx``, equivalent to a length-``n`` column of args tuples
    ``t`` with ``t[idx] = by_idx[idx][j]`` — but with no per-request
    tuple allocation (the gateway fast-attr path hands its client-IP /
    host column straight through). A ``param_idx`` absent from
    ``by_idx`` means "no value for that rule" (the rule passes), like a
    too-short args tuple."""

    __slots__ = ("n", "by_idx")

    def __init__(self, n: int, by_idx: Dict[int, Sequence[object]]) -> None:
        self.n = int(n)
        for idx, col in by_idx.items():
            if len(col) != self.n:
                raise ValueError(
                    f"ArgsColumns: column for param_idx {idx} has length"
                    f" {len(col)} != n={self.n}"
                )
        self.by_idx = by_idx

    def __len__(self) -> int:
        return self.n


def _extract_arg(args: object, idx: int) -> object:
    """One entry's value for ``param_idx`` from its args (tuple/list) —
    a bare scalar arg behaves like a 1-tuple, matching the old
    normalization ``a if isinstance(a, (list, tuple)) else (a,)``."""
    if isinstance(args, (list, tuple)):
        return args[idx] if idx < len(args) else None
    return args if idx == 0 else None


class ParamDynState(NamedTuple):
    tokens: jax.Array  # int32 [PR]
    last_add: jax.Array  # int32 [PR]
    latest: jax.Array  # int32 [PR]
    threads: jax.Array  # int32 [PR]


def make_param_state(n_rows: int) -> ParamDynState:
    return ParamDynState(
        tokens=jnp.zeros((n_rows,), dtype=jnp.int32),
        last_add=jnp.full((n_rows,), PARAM_NEVER, dtype=jnp.int32),
        latest=jnp.full((n_rows,), PARAM_NEVER, dtype=jnp.int32),
        threads=jnp.zeros((n_rows,), dtype=jnp.int32),
    )


def grow_param_state(state: ParamDynState, n_rows: int) -> ParamDynState:
    if n_rows <= state.tokens.shape[0]:
        return state
    extra = make_param_state(n_rows - state.tokens.shape[0])
    return ParamDynState(*(jnp.concatenate([a, b]) for a, b in zip(state, extra)))


class ParamBatch(NamedTuple):
    """Per-slot arrays for this flush's param checks ([S] each)."""

    valid: jax.Array  # bool
    prow: jax.Array  # int32 param state row
    eidx: jax.Array  # int32 entry index
    ts: jax.Array  # int32
    acquire: jax.Array  # int32
    grade: jax.Array  # int32 FLOW_GRADE_*
    behavior: jax.Array  # int32 DEFAULT or RATE_LIMITER
    token_count: jax.Array  # int32 threshold (hot-item resolved)
    burst: jax.Array  # int32
    duration_ms: jax.Array  # int32
    maxq: jax.Array  # int32 maxQueueingTimeMs
    cost_ms: jax.Array  # int32 host-precomputed throttle cost (f64 exact)
    reset_rows: jax.Array  # int32 [Q] rows recycled by LRU eviction (-1 pad)
    exit_rows: jax.Array  # int32 [SX] thread-grade rows released by exits (-1 pad)


@dataclass
class ParamSlotInfo:
    """Host-side resolved slot (before encoding)."""

    prow: int
    grade: int
    behavior: int
    token_count: int
    burst: int
    duration_ms: int
    maxq: int
    cost_ms: int
    rule: Optional[ParamFlowRule] = None  # for block attribution
    value_key: str = ""  # interned value string (cluster RPC payload)

    def mirror_bucket(self) -> Tuple[float, float]:
        """Host-mirror compilation hook: ``(capacity, window_ms)`` of
        the token bucket approximating this value row's device budget
        (token_count + burst over duration_ms) — the ONE home of that
        mapping, shared by the degraded fallback and the speculative
        tier (runtime/failover.py, runtime/speculative.py)."""
        return (
            float(self.token_count + self.burst),
            max(float(self.duration_ms), 1.0),
        )


def _transition(tokens, last, latest, thr_used, x):
    """One param slot's check + state update, vector-friendly (used by
    both the rounds path and the scan). Invalid items are identity on
    state and ok=True. Returns (ok, wait, tokens', last', latest',
    thr_used')."""
    (valid, ts, acq, grade, beh, tc, burst, dur, maxq, cost, g_threads) = x

    max_count = tc + burst
    never = last == PARAM_NEVER

    # --- token bucket (passDefaultLocalCheck) ---
    first_tokens = max_count - acq
    pass_time = ts - last
    refill_win = pass_time > dur
    to_add = (pass_time * tc) // dur
    new_qps = jnp.where(
        to_add + tokens > max_count, max_count - acq, tokens + to_add - acq
    )
    tb_ok = jnp.where(
        never,
        True,
        jnp.where(refill_win, new_qps >= 0, tokens - acq >= 0),
    )
    tb_ok = tb_ok & (tc > 0) & (acq <= max_count)
    tokens2 = jnp.where(
        never,
        first_tokens,
        jnp.where(refill_win, jnp.where(new_qps >= 0, new_qps, tokens), tokens - acq),
    )
    tokens2 = jnp.where(tb_ok, tokens2, tokens)
    last2 = jnp.where(tb_ok & (never | refill_win), ts, last)

    # --- throttle (passThrottleLocalCheck) ---
    t_never = latest == PARAM_NEVER
    expected = latest + cost
    th_imm = expected <= ts
    th_wait = expected - ts
    th_q = (~th_imm) & (th_wait < maxq)  # STRICT < (ParamFlowChecker.java:258)
    th_ok = (t_never | th_imm | th_q) & (tc > 0)
    latest2 = jnp.where(
        t_never, ts, jnp.where(th_imm, ts, jnp.where(th_q, expected, latest))
    )
    latest2 = jnp.where(th_ok, latest2, latest)
    th_wait_out = jnp.where(th_q & th_ok & ~t_never, jnp.maximum(th_wait, 0), 0)

    # --- per-value thread grade ---
    thr_cnt = g_threads + thr_used
    thr_ok = thr_cnt + 1 <= tc
    thr_used2 = thr_used + jnp.where(thr_ok, 1, 0)

    is_qps = grade == C.FLOW_GRADE_QPS
    is_throttle = is_qps & (beh == C.CONTROL_BEHAVIOR_RATE_LIMITER)
    ok = jnp.where(is_throttle, th_ok, jnp.where(is_qps, tb_ok, thr_ok))
    ok = ok | ~valid
    wait = jnp.where(is_throttle & valid, th_wait_out, 0)

    # Only the behavior in effect mutates its state column.
    tokens3 = jnp.where(valid & is_qps & ~is_throttle, tokens2, tokens)
    last3 = jnp.where(valid & is_qps & ~is_throttle, last2, last)
    latest3 = jnp.where(valid & is_throttle, latest2, latest)
    thr_used3 = jnp.where(valid & ~is_qps, thr_used2, thr_used)
    return ok, wait, tokens3, last3, latest3, thr_used3


def _seg_end_rows(row_s, row_c, valid_s, pr):
    """Scatter targets for the per-segment final state: each segment's
    LAST valid item writes its row; everything else drops (row = pr)."""
    seg_end = jnp.concatenate(
        [row_s[1:] != row_s[:-1], jnp.ones((1,), dtype=bool)]
    ) & valid_s
    return jnp.where(seg_end, row_c, jnp.int32(pr))


def run_param(
    dyn: ParamDynState,
    pb: ParamBatch,
    rounds: int = 0,
) -> Tuple[ParamDynState, jax.Array, jax.Array]:
    """Evaluate param slots; returns (new_dyn, ok [S] in caller order,
    wait_ms [S] in caller order).

    ``rounds`` (static): host-known upper bound on items-per-value-row
    in this batch — picks the vectorized rounds path (round *r*
    resolves every row's *r*-th item in parallel, each item chaining
    from its predecessor in the sorted order); 0 falls back to the
    sequential ``lax.scan``; ``rounds <= -1`` selects the closed-form
    rank path with ``-rounds`` timestamp sub-segments per row (−1 =
    single-ts batches, −S = mixed-ts batches with at most S distinct
    timestamps per value row), ONLY valid when the host verified every
    item is QPS-grade DEFAULT with one acquire ≥ 1
    (Engine._param_rounds_for owns that predicate — run_param does not
    re-validate).
    """
    s = pb.valid.shape[0]
    pr = dyn.tokens.shape[0]

    # Recycle evicted rows first.
    rr = jnp.where(pb.reset_rows >= 0, pb.reset_rows, jnp.int32(pr))
    dyn = ParamDynState(
        tokens=dyn.tokens.at[rr].set(0, mode="drop"),
        last_add=dyn.last_add.at[rr].set(PARAM_NEVER, mode="drop"),
        latest=dyn.latest.at[rr].set(PARAM_NEVER, mode="drop"),
        threads=dyn.threads.at[rr].set(0, mode="drop"),
    )

    key = jnp.where(pb.valid, pb.prow, jnp.int32(pr))
    pos = jnp.arange(s, dtype=jnp.int32)
    # Compacted batches are built in entry order (eidx nondecreasing in
    # item position), so pos as the last key reproduces the
    # (row, ts, eidx) order with one less sort operand.
    row_s, ts_s, p_s = jax.lax.sort((key, pb.ts, pos), num_keys=3)
    row_c = jnp.clip(row_s, 0, pr - 1)
    valid_s = pb.valid[p_s]

    # Segment-start state is pre-gathered OUTSIDE the recurrence (one
    # vectorized gather instead of per-step dynamic gathers).
    seg_tokens = dyn.tokens[row_c]
    seg_last = dyn.last_add[row_c]
    seg_latest = dyn.latest[row_c]
    seg_threads = dyn.threads[row_c]

    items = (
        valid_s, ts_s, pb.acquire[p_s], pb.grade[p_s], pb.behavior[p_s],
        pb.token_count[p_s], pb.burst[p_s], jnp.maximum(pb.duration_ms[p_s], 1),
        pb.maxq[p_s], pb.cost_ms[p_s], seg_threads,
    )
    ones = jnp.ones((1,), dtype=bool)
    new_grp = jnp.concatenate([ones, row_s[1:] != row_s[:-1]])

    if rounds <= -1:
        # Closed-form heavy-hitter path (host-selected when EVERY item
        # in the batch is QPS-grade DEFAULT behavior with ONE acquire
        # value — the columnar-adapter shape): under those conditions
        # the per-item greedy recurrence equals rank math. Within one
        # (row, ts) sub-segment the refill window can open at most once
        # (the first grant pins last_add to ts), so the sub-segment's
        # budget is
        #     avail = never   ? max_count
        #           : refill  ? min(tokens + to_add, max_count)
        #           : tokens
        # and with uniform acquire the greedy admit set is exactly the
        # first floor(avail/acq) items — any per-value multiplicity in
        # O(sort), no 16-round unroll, no sequential scan.
        #
        # Mixed-timestamp batches (``nseg = -rounds > 1``): segment
        # boundaries fall at ts changes within a row (the sort is
        # (row, ts, arrival)); the unrolled loop below resolves every
        # row's *i*-th sub-segment in parallel and applies each
        # sub-segment's refill + spend to the row state BETWEEN
        # iterations — rank math per sub-segment, recurrence only
        # across the (host-bounded, ≤ PARAM_CLOSED_MAX_SEGMENTS)
        # sub-segments. A rejected sub-segment (avail < acquire)
        # commits nothing, exactly like the per-item CAS-failure path.
        nseg = -rounds
        (valid_x, ts_x, acq_x, _g, _b, tc_x, burst_x, dur_x, _mq, _c,
         _thr) = items
        idx = jnp.arange(s, dtype=jnp.int32)
        new_sub = new_grp | jnp.concatenate([ones, ts_s[1:] != ts_s[:-1]])
        sub_start = jax.lax.cummax(jnp.where(new_sub, idx, 0))
        sub_rank = idx - sub_start
        # Sub-segment index within the row: running count of sub-starts
        # (inclusive, restarting per row) minus one — the segment
        # exclusive-cumsum recovered via a running max over row-start
        # snapshots (same construction as flush.segment_excl_cumsum,
        # not imported: rules must not depend on runtime).
        sub_flag = new_sub.astype(jnp.int32)
        excl = jnp.cumsum(sub_flag) - sub_flag
        sub_idx = (
            excl - jax.lax.cummax(jnp.where(new_grp, excl, 0)) + sub_flag - 1
        )
        last_of_sub = jnp.concatenate([new_sub[1:], ones])

        max_count = tc_x + burst_x
        gate = (tc_x > 0) & (acq_x <= max_count)

        # Row state lives in full [PR] columns across the unroll: each
        # iteration gathers the current state, decides one sub-segment
        # per row, and scatters the sub-segment-end state back (rows
        # with fewer sub-segments are untouched). After the last
        # iteration these columns ARE the new dyn state — no separate
        # seg-end write-back.
        row_tokens = dyn.tokens
        row_last = dyn.last_add
        ok_s = ~valid_s
        for seg_i in range(nseg):
            in_seg = valid_s & (sub_idx == seg_i)
            cur_tokens = row_tokens[row_c]
            cur_last = row_last[row_c]
            never = cur_last == PARAM_NEVER
            pass_time = ts_x - cur_last
            refill = pass_time > dur_x
            to_add = (pass_time * tc_x) // dur_x
            avail = jnp.where(
                never,
                max_count,
                jnp.where(refill, jnp.minimum(cur_tokens + to_add, max_count),
                          cur_tokens),
            )
            cap = jnp.where(gate, avail // jnp.maximum(acq_x, 1), 0)
            ok_s = jnp.where(in_seg, gate & (sub_rank < cap), ok_s)
            granted_here = jnp.minimum(sub_rank + 1, cap)
            tok_here = jnp.where(
                granted_here > 0, avail - granted_here * acq_x, cur_tokens
            )
            last_here = jnp.where(
                (granted_here > 0) & (never | refill), ts_x, cur_last
            )
            sc = jnp.where(in_seg & last_of_sub, row_c, jnp.int32(pr))
            row_tokens = row_tokens.at[sc].set(tok_here, mode="drop")
            row_last = row_last.at[sc].set(last_here, mode="drop")
        new_dyn = ParamDynState(
            tokens=row_tokens,
            last_add=row_last,
            latest=dyn.latest,
            threads=dyn.threads,
        )
        ok_out = jnp.ones((s,), dtype=bool).at[p_s].set(ok_s)
        # All grants are immediate on this path: wait is identically 0.
        return new_dyn, ok_out, jnp.zeros((s,), dtype=jnp.int32)

    def transition(states, item_vals):
        tokens, last, latest, thr_used = states
        ok, wait, t2, l2, lt2, thr2 = _transition(
            tokens, last, latest, thr_used, item_vals
        )
        return (ok, wait), (t2, l2, lt2, thr2)

    from sentinel_tpu.rules.recurrence import run_segmented

    # thr_used (intra-batch thread charge) restarts at 0 per segment.
    seg_thr_used = jnp.zeros((s,), dtype=jnp.int32)
    ok_s, wait_s, (tok_s, last_s, lat_s, _) = run_segmented(
        new_grp, (seg_tokens, seg_last, seg_latest, seg_thr_used),
        items, transition, rounds,
    )

    sc = _seg_end_rows(row_s, row_c, valid_s, pr)
    new_dyn = ParamDynState(
        tokens=dyn.tokens.at[sc].set(tok_s, mode="drop"),
        last_add=dyn.last_add.at[sc].set(last_s, mode="drop"),
        latest=dyn.latest.at[sc].set(lat_s, mode="drop"),
        threads=dyn.threads,
    )

    ok_out = jnp.ones((s,), dtype=bool).at[p_s].set(ok_s)
    wait_out = jnp.zeros((s,), dtype=jnp.int32).at[p_s].set(wait_s)
    return new_dyn, ok_out, wait_out


class ParamIndex:
    """Host-side compiled hot-param rules + per-rule value interning.

    ``sketch_tier`` (runtime/sketch.SketchTier, optional) activates
    sketch-native resolution for rules with ``sketch_mode=True``: cold
    values get NO dense row (they pass; the fixed-size device sketch
    tracks them), and only values in the tier's promoted set intern
    into exact rows — the promotion target the sketch controller
    drives through this index's existing LRU row-recycle machinery.
    Without a tier (or with it disarmed) sketch-mode rules dense-track
    every value exactly like before."""

    def __init__(
        self,
        by_resource: Dict[str, List[ParamFlowRule]],
        sketch_tier=None,
    ) -> None:
        self.by_resource: Dict[str, List[Tuple[int, ParamFlowRule]]] = {}
        self.rules: List[ParamFlowRule] = []
        for res, rs in by_resource.items():
            lst = []
            for r in rs:
                gid = len(self.rules)
                self.rules.append(r)
                lst.append((gid, r))
            self.by_resource[res] = lst
        self._sketch_tier = sketch_tier
        self.sketch_gids = {
            gid
            for gid, r in (
                (g, r) for lst in self.by_resource.values() for g, r in lst
            )
            if getattr(r, "sketch_mode", False)
        }
        # resource -> sorted distinct param_idx of its sketch-mode
        # rules: the key-extraction map the tier's encode walks.
        self.sketch_idx_by_resource: Dict[str, Tuple[int, ...]] = {}
        if sketch_tier is not None and getattr(sketch_tier, "armed", False):
            for res, lst in self.by_resource.items():
                idxs = sorted(
                    {
                        r.param_idx
                        for _g, r in lst
                        if getattr(r, "sketch_mode", False)
                        and r.param_idx is not None
                    }
                )
                if idxs:
                    self.sketch_idx_by_resource[res] = tuple(idxs)
        self._sketch_filtering = bool(self.sketch_idx_by_resource)
        # (gid) -> {value_key -> prow}; LRU by insertion-move.
        self._values: List[Dict[str, int]] = [dict() for _ in self.rules]
        # Persistent per-rule resolved-value cache: value_key ->
        # (prow, token_count, cost_ms). Heavy-hitter values resolve to
        # one dict get per request instead of paying np.unique +
        # interning on every flush (the host-ingest fast path). Lives
        # and dies with this ParamIndex, so a param-rule reload (which
        # rebuilds the index) invalidates it wholesale; an LRU eviction
        # drops the evicted key (see _intern). Gated by the
        # sentinel.tpu.host.fastpath config switch.
        self._resolved: List[Dict[str, Tuple[int, int, int]]] = [
            dict() for _ in self.rules
        ]
        self._use_value_cache = config.get_bool(config.HOST_FASTPATH, True)
        # Telemetry counters for the resolved-value cache (hits/misses
        # on the bulk fast path) and value-row LRU evictions (any
        # path). Plain ints — GIL-atomic increments on the submit hot
        # path; they live and die with this index, so a param-rule
        # reload (index rebuild) resets them to zero, which the
        # invalidation test asserts.
        self.cache_hits = 0
        self.cache_misses = 0
        self.cache_evictions = 0
        self._hot: List[Dict[str, int]] = [
            {it.object: int(it.count) for it in r.param_flow_item_list} for r in self.rules
        ]
        self._caps: List[int] = [
            min(C.PARAM_FLOW_DEFAULT_CACHE_SIZE * max(1, int(r.duration_in_sec)), 200_000)
            for r in self.rules
        ]
        self._free_rows: List[int] = []
        self._next_row = 0
        self.pending_resets: List[int] = []

    @property
    def n_rows(self) -> int:
        return self._next_row

    def has_rules(self) -> bool:
        return bool(self.rules)

    def values_snapshot(self) -> dict:
        """JSON-able capture of the value→row interning state for the
        durable checkpoint (runtime/durable.py): per-gid value maps in
        LRU (insertion) order, the free-row pool and the high-water row
        counter — everything a fresh process needs to make restored
        ``param_dyn`` rows mean the same (rule, value) pairs again."""
        return {
            "values": [list(v.items()) for v in self._values],
            "free_rows": list(self._free_rows),
            "next_row": self._next_row,
        }

    def adopt_values(self, snap) -> bool:
        """Install a :meth:`values_snapshot` into THIS index. Refuses —
        returning False, never raising — when the index already
        interned values (live rows would collide with adopted ones),
        the snapshot's shape doesn't match the compiled rule count, or
        any row assignment is inconsistent. Insertion order is
        preserved, so LRU recycling resumes exactly where the dead
        process left off."""
        try:
            vals = snap["values"]
            free = [int(r) for r in snap["free_rows"]]
            nxt = int(snap["next_row"])
        except (KeyError, TypeError, ValueError):
            return False
        if not isinstance(vals, list) or len(vals) != len(self.rules):
            return False
        if nxt < 0 or any(not (0 <= r < nxt) for r in free):
            return False
        if any(self._values) or self._next_row or self._free_rows:
            return False
        seen: set = set(free)
        if len(seen) != len(free):
            return False
        adopted: List[Dict[str, int]] = []
        for per_gid in vals:
            d: Dict[str, int] = {}
            try:
                for key, row in per_gid:
                    row = int(row)
                    if not (0 <= row < nxt) or row in seen:
                        return False
                    seen.add(row)
                    d[str(key)] = row
            except (TypeError, ValueError):
                return False
            adopted.append(d)
        self._values = adopted
        self._free_rows = free
        self._next_row = nxt
        return True

    def _intern(self, gid: int, key: str) -> int:
        vals = self._values[gid]
        row = vals.get(key)
        if row is not None:
            # LRU touch.
            del vals[key]
            vals[key] = row
            return row
        if len(vals) >= self._caps[gid]:
            old_key = next(iter(vals))
            old_row = vals.pop(old_key)
            # The recycled row now means a different value: the
            # resolved-value cache must never serve the old mapping.
            self._resolved[gid].pop(old_key, None)
            self.pending_resets.append(old_row)
            self.cache_evictions += 1
            row = old_row
        elif self._free_rows:
            row = self._free_rows.pop()
        else:
            row = self._next_row
            self._next_row += 1
        vals[key] = row
        return row

    @staticmethod
    def _value_key(value: object) -> Optional[str]:
        if value is None:
            return None
        if hasattr(value, "param_flow_key"):
            value = value.param_flow_key()  # ParamFlowArgument equivalent
            if value is None:
                return None
        return str(value)

    def slots_for(
        self, resource: str, args: Sequence[object], max_slots: int = 64
    ) -> List[ParamSlotInfo]:
        """Resolve the entry's param slots (ParamFlowChecker.passCheck:
        one check per rule per value, collections/arrays expand)."""
        out: List[ParamSlotInfo] = []
        for gid, r in self.by_resource.get(resource, ()):
            if r.param_idx is None or r.param_idx >= len(args):
                continue
            promoted = None
            if self._sketch_filtering and gid in self.sketch_gids:
                # Sketch-native rule: only promoted heavy hitters get a
                # dense slot; cold values pass here and are tracked by
                # the device sketch instead (runtime/sketch.py).
                promoted = self._sketch_tier.promoted_values.get(resource)
                if not promoted:
                    continue
            value = args[r.param_idx]
            values = (
                list(value) if isinstance(value, (list, tuple, set, frozenset)) else [value]
            )
            for v in values:
                key = self._value_key(v)
                if key is None:
                    continue
                if promoted is not None and key not in promoted:
                    continue
                # acquire==1 cost (the API default); recomputed
                # host-side per acquire at submit if needed.
                tc, cost = self._threshold_and_cost(gid, r, key)
                out.append(
                    ParamSlotInfo(
                        prow=self._intern(gid, key),
                        grade=r.grade,
                        behavior=r.control_behavior,
                        token_count=tc,
                        burst=int(r.burst_count),
                        duration_ms=int(r.duration_in_sec) * 1000,
                        maxq=int(r.max_queueing_time_ms),
                        cost_ms=cost,
                        rule=r,
                        value_key=key,
                    )
                )
                if len(out) >= max_slots:
                    record_log.warn(
                        "[ParamIndex] truncating param slots for %s at %d", resource, max_slots
                    )
                    return out
        return out

    def _threshold_and_cost(self, gid: int, r: ParamFlowRule, key: str) -> Tuple[int, int]:
        """Hot-item-resolved threshold + rate-limiter cost for one
        value key — the ONE home of the cost formula
        (Math.round(1.0*1000*durationSec/count) for acquire=1); every
        resolution path (slots_for, cached, exact) must go through it
        or the fast path desynchronizes from its differential
        reference."""
        tc = self._hot[gid].get(key, int(r.count))
        cost = (
            int(1000.0 * r.duration_in_sec / tc + 0.5)
            if r.control_behavior == C.CONTROL_BEHAVIOR_RATE_LIMITER and tc > 0
            else 0
        )
        return tc, cost

    def _resolve_value(self, gid: int, r: ParamFlowRule, key: str) -> Tuple[int, int, int]:
        """Intern + threshold/cost resolution for one value key, cached
        persistently (the per-rule resolved-value cache). tc and cost
        are static per (rule, key), so a cached triple stays valid
        until the key's row is LRU-evicted or the index is rebuilt."""
        tc, cost = self._threshold_and_cost(gid, r, key)
        trip = (self._intern(gid, key), tc, cost)
        self._resolved[gid][key] = trip
        return trip

    def _resolve_value_col(
        self, gid: int, r: ParamFlowRule, values: Optional[Sequence[object]], n: int
    ) -> Optional[Tuple["np.ndarray", "np.ndarray", "np.ndarray", "np.ndarray"]]:
        """Resolve one rule's per-entry value column to
        ``(valid[n], prow[n], token_count[n], cost_ms[n])``. Returns
        None when a value is a collection (per-entry expansion doesn't
        fit fixed columns) — callers fall back to the per-entry path.

        Fast path (config ``sentinel.tpu.host.fastpath``, default on):
        one dict get per request against the persistent resolved-value
        cache; misses (and non-string values) resolve once and stay
        cached. LRU recency is maintained EXACTLY like the exact path:
        at column end, this column's distinct keys are re-touched in
        sorted order — the same per-flush per-sorted-unique ordering
        the np.unique path produces — so the two paths' intern tables
        evolve identically and eviction picks the same victims
        (verdict bit-identity holds through eviction pressure, not
        just below the cap). A column whose misses would cross the cap
        (the next intern would evict, possibly a key already resolved
        from the cache in pass 1 — its prow would alias a reset row)
        restarts wholesale on the exact path — so at the cap, all-hit
        heavy-hitter columns keep the one-dict-get win and only
        columns introducing NEW values pay the exact rerun.

        Exact path (fast path off, or a column whose first evicting
        intern restarts it): np.unique interning per flush — also the
        differential reference for the smoke test."""
        if values is None:
            z = np.zeros(n, dtype=np.int32)
            return np.zeros(n, dtype=bool), z, z.copy(), z.copy()
        if self._sketch_filtering and gid in self.sketch_gids:
            return self._resolve_value_col_sketch(gid, r, values, n)
        if self._use_value_cache:
            rget = self._resolved[gid].get
            miss = _MISS
            # Pass 1: interned string values (the hot shape) resolve in
            # one C-level comprehension of dict gets.
            trips = [rget(v, miss) if type(v) is str else miss for v in values]
            # trips.count runs at C speed. Hits/misses accumulate in
            # locals and commit only when the column COMPLETES on this
            # path — a bail to the exact path (eviction at the cap) or
            # the per-entry path (collection value) redoes the work, so
            # committing early would over-report exactly the
            # eviction-pressure workloads the counters diagnose.
            hits = n - trips.count(miss)
            misses = 0
            # Pass 2: fix misses in place — list.index scans at C speed,
            # so all-hit columns pay one scan and zero Python-level
            # iterations here.
            vals = self._values[gid]
            cap = self._caps[gid]
            extra_keys: List[str] = []  # pass-2 keys (non-str forms too)
            j = 0
            while True:
                try:
                    j = trips.index(miss, j)
                except ValueError:
                    break
                v = values[j]
                if isinstance(v, (list, tuple, set, frozenset)):
                    return None  # collection expansion → per-entry path
                key = self._value_key(v)
                if key is None:
                    trips[j] = None
                else:
                    trip = rget(key)
                    if trip is None:
                        # A key already interned (e.g. via a past exact
                        # rerun or the per-entry slots_for path) only
                        # lacks its cache triple — resolving it touches,
                        # never evicts, so it is safe at the cap too.
                        if key not in vals and len(vals) >= cap:
                            # A genuinely NEW key whose intern would
                            # evict: restart on the exact path BEFORE
                            # any eviction can happen (misses so far
                            # only inserted below the cap).
                            return self._resolve_value_col_exact(
                                gid, r, values, n
                            )
                        misses += 1
                        trip = self._resolve_value(gid, r, key)
                    else:
                        hits += 1
                    extra_keys.append(key)
                    trips[j] = trip
                j += 1
            # Recency parity with the exact path: touch this column's
            # distinct keys in SORTED order — the same per-flush
            # per-sorted-unique sequence np.unique/_intern produces —
            # so both paths' intern tables evolve identically and
            # eviction later picks identical victims. Cache-hit string
            # values ARE their keys; pass-2 resolutions contribute
            # their computed keys. (Comprehension, not set(values):
            # the type filter must run before hashing — an unhashable
            # non-collection value, e.g. a dict, is a legal arg.)
            self.cache_hits += hits
            self.cache_misses += misses
            touch = {v for v in values if type(v) is str}
            touch.update(extra_keys)
            vals_pop = vals.pop
            for key in sorted(touch):
                row = vals_pop(key, None)
                if row is not None:
                    vals[key] = row
            valid = np.fromiter((t is not None for t in trips), dtype=bool, count=n)
            if valid.all():
                arr = np.array(trips, dtype=np.int32).reshape(n, 3)
            else:
                arr = np.array(
                    [t if t is not None else _NO_TRIP for t in trips],
                    dtype=np.int32,
                ).reshape(n, 3)
            return valid, arr[:, 0], arr[:, 1], arr[:, 2]
        return self._resolve_value_col_exact(gid, r, values, n)

    def _resolve_value_col_sketch(
        self, gid: int, r: ParamFlowRule, values: Sequence[object], n: int
    ) -> Optional[Tuple["np.ndarray", "np.ndarray", "np.ndarray", "np.ndarray"]]:
        """Sketch-native column resolve: only values in the tier's
        promoted set intern into dense rows; every other value is
        invalid (the rule passes it — the sketch tracks it instead).
        The 100k-distinct-cold-keys case is a single dict read: with
        nothing promoted, NO per-value work happens at all — that is
        the O(1) contract this tier exists for."""
        promoted = self._sketch_tier.promoted_values.get(r.resource)
        valid = np.zeros(n, dtype=bool)
        z = np.zeros(n, dtype=np.int32)
        if values is None or not promoted:
            return valid, z, z.copy(), z.copy()
        prow = np.zeros(n, dtype=np.int32)
        tc = np.zeros(n, dtype=np.int32)
        cost = np.zeros(n, dtype=np.int32)
        rget = self._resolved[gid].get
        for j, v in enumerate(values):
            if v is None:
                continue
            if isinstance(v, (list, tuple, set, frozenset)):
                return None  # collection expansion → per-entry path
            key = v if type(v) is str else self._value_key(v)
            if key is None or key not in promoted:
                continue
            trip = rget(key)
            if trip is None:
                trip = self._resolve_value(gid, r, key)
            valid[j] = True
            prow[j], tc[j], cost[j] = trip
        return valid, prow, tc, cost

    def release_value(self, resource: str, key: str) -> None:
        """Sketch-tier demotion: drop a promoted value's dense row and
        queue its device-state reset — the inverse of the promotion
        intern, reusing the same recycle plumbing as LRU eviction. A
        later re-promotion re-interns fresh (first-seen bucket state),
        so promote → demote → promote never resurrects stale tokens."""
        for gid, _r in self.by_resource.get(resource, ()):
            if gid not in self.sketch_gids:
                continue
            row = self._values[gid].pop(key, None)
            if row is None:
                continue
            self._resolved[gid].pop(key, None)
            self.pending_resets.append(row)
            self._free_rows.append(row)

    def _resolve_value_col_exact(
        self, gid: int, r: ParamFlowRule, values: Sequence[object], n: int
    ) -> Optional[Tuple["np.ndarray", "np.ndarray", "np.ndarray", "np.ndarray"]]:
        """The exact path: per-flush np.unique interning with an LRU
        touch per distinct value (heavy hitters stay resident under
        eviction pressure). Also the fastpath-off differential
        reference."""
        col: List[Optional[str]] = [None] * n
        for j, v in enumerate(values):
            if v is None:
                continue
            if isinstance(v, (list, tuple, set, frozenset)):
                return None
            col[j] = self._value_key(v)
        arr_o = np.asarray(col, dtype=object)
        valid = np.asarray([c is not None for c in col], dtype=bool)
        prow = np.zeros(n, dtype=np.int32)
        tc = np.zeros(n, dtype=np.int32)
        cost = np.zeros(n, dtype=np.int32)
        if valid.any():
            uniq, inverse = np.unique(arr_o[valid].astype(str), return_inverse=True)
            u_prow = np.empty(len(uniq), dtype=np.int32)
            u_tc = np.empty(len(uniq), dtype=np.int32)
            u_cost = np.empty(len(uniq), dtype=np.int32)
            for u, key in enumerate(uniq):
                u_prow[u] = self._intern(gid, key)
                u_tc[u], u_cost[u] = self._threshold_and_cost(gid, r, key)
            prow[valid] = u_prow[inverse]
            tc[valid] = u_tc[inverse]
            cost[valid] = u_cost[inverse]
        return valid, prow, tc, cost

    def bulk_cols(
        self, resource: str, args_column: Sequence
    ) -> Optional[List[Tuple[ParamFlowRule, "np.ndarray", "np.ndarray", "np.ndarray", "np.ndarray"]]]:
        """Columnar ``slots_for`` over a whole bulk group: one
        ``(rule, valid[n], prow[n], token_count[n], cost_ms[n])`` tuple
        per param rule on the resource. ``args_column`` is either a
        sequence of per-entry args tuples, or an :class:`ArgsColumns`
        (pre-split value columns — no per-request tuple walk at all).
        Returns None when a value is a collection (per-entry expansion
        doesn't fit fixed columns) — callers fall back to the per-entry
        path."""
        rules = self.by_resource.get(resource, ())
        if not rules:
            return []
        n = len(args_column)
        flat = isinstance(args_column, ArgsColumns)
        out = []
        for gid, r in rules:
            idx = r.param_idx
            if idx is None:
                values: Optional[Sequence[object]] = None
            elif flat:
                values = args_column.by_idx.get(idx)
            else:
                values = [_extract_arg(a, idx) for a in args_column]
            cols = self._resolve_value_col(gid, r, values, n)
            if cols is None:
                return None
            out.append((r,) + cols)
        return out

    def cache_stats(self) -> Dict[str, int]:
        """Intern/resolved-value cache counters for the telemetry bus.
        ``interned`` is the live value-row population across rules."""
        return {
            "hits": self.cache_hits,
            "misses": self.cache_misses,
            "evictions": self.cache_evictions,
            "interned": sum(len(v) for v in self._values),
        }

    def take_resets(self) -> List[int]:
        out, self.pending_resets = self.pending_resets, []
        return out
