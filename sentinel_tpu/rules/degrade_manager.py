"""Degrade (circuit breaker) rule manager (reference:
DegradeRuleManager.java). Rule storage + validation land here now;
breaker state-machine enforcement is wired into the flush kernel in the
degrade milestone (SURVEY.md §7 stage 5)."""

from __future__ import annotations

from typing import List

from sentinel_tpu.models.rules import DegradeRule
from sentinel_tpu.rules.manager_base import RuleManager


class DegradeRuleManager(RuleManager[DegradeRule]):
    rule_kind = "degrade"

    def _apply(self, rules: List[DegradeRule], engine) -> None:
        if engine is not None:
            engine.set_degrade_rules([r for r in rules if r.is_valid()])


degrade_rule_manager = DegradeRuleManager()
