"""System rule manager (reference: SystemRuleManager.java:298-353).

Stores adaptive-protection rules; the effective config is the minimum
across rules per dimension, matching loadSystemConf. Kernel enforcement
(global QPS / thread / RT / BBR load+CPU on the ENTRY_NODE row) is wired
in the system-protection milestone."""

from __future__ import annotations

from typing import List, NamedTuple

from sentinel_tpu.models.rules import SystemRule
from sentinel_tpu.rules.manager_base import RuleManager


class SystemConfig(NamedTuple):
    highest_system_load: float = -1.0
    highest_cpu_usage: float = -1.0
    qps: float = -1.0
    max_rt: int = -1
    max_thread: int = -1

    @property
    def any_enabled(self) -> bool:
        return (
            self.highest_system_load >= 0
            or self.highest_cpu_usage >= 0
            or self.qps >= 0
            or self.max_rt >= 0
            or self.max_thread >= 0
        )


def _min_enabled(cur: float, new: float) -> float:
    if new < 0:
        return cur
    return new if cur < 0 else min(cur, new)


class SystemRuleManager(RuleManager[SystemRule]):
    rule_kind = "system"

    def __init__(self) -> None:
        super().__init__()
        self.effective = SystemConfig()

    def _apply(self, rules: List[SystemRule], engine) -> None:
        cfg = SystemConfig()
        for r in rules:
            cfg = SystemConfig(
                highest_system_load=_min_enabled(cfg.highest_system_load, r.highest_system_load),
                highest_cpu_usage=_min_enabled(cfg.highest_cpu_usage, r.highest_cpu_usage),
                qps=_min_enabled(cfg.qps, r.qps),
                max_rt=int(_min_enabled(cfg.max_rt, r.avg_rt)),
                max_thread=int(_min_enabled(cfg.max_thread, r.max_thread)),
            )
        self.effective = cfg
        if engine is not None:
            engine.set_system_config(cfg)


system_rule_manager = SystemRuleManager()
