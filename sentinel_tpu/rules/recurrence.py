"""Shared driver for per-segment recurrences over sorted item arrays.

Both serializing rule families — shaping pacers (rules/shaping.py) and
hot-param buckets (rules/param_table.py) — reduce to the same shape:
items sorted by (key, ts, arrival), per-key state threaded through the
key's items in order, a per-item ``transition`` producing (ok, wait)
and the successor state. This module owns the two exact execution
schedules so they cannot drift apart:

* ``rounds > 0`` — vectorized: within a segment each item's input
  state is its immediate predecessor's output (adjacent in the sorted
  order), so round *r* resolves every segment's *r*-th item in
  parallel. ``rounds`` is the host-known max items-per-key (static).
* ``rounds == 0`` — one ``lax.scan``: the carry is the running state;
  a segment start reloads from the pre-gathered segment-start state.

Invalid items must sort to the tail (callers key them past every real
key) and their transition must be identity on state with ok=True.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

# Rounds counts at or below this unroll at trace time (XLA fuses the
# whole chain); above it a fori_loop compiles the round body once.
# tests/test_scan_rounds.py derives its cross-path parity case from
# this constant.
UNROLL_MAX_ROUNDS = 4


def run_segmented(
    new_grp: jax.Array,  # bool [S] — segment starts in sorted order
    seg_states: Tuple[jax.Array, ...],  # per-item segment-START state
    items: Tuple[jax.Array, ...],  # per-item transition inputs [S]
    transition: Callable,  # (states, items) -> ((ok, wait), new_states)
    rounds: int,
) -> Tuple[jax.Array, jax.Array, Tuple[jax.Array, ...]]:
    """Returns (ok [S] bool, wait [S] int32, post-item states) in the
    sorted order of the inputs; a segment's final state sits at its
    last item's position (the caller's seg-end write-back)."""
    s = new_grp.shape[0]
    if rounds > 0:
        idx = jnp.arange(s, dtype=jnp.int32)
        seg_start = jax.lax.cummax(jnp.where(new_grp, idx, 0))
        seg_pos = idx - seg_start
        ok = jnp.ones((s,), dtype=bool)
        wait = jnp.zeros((s,), dtype=jnp.int32)

        def one_round(r, ok, wait, out_states):
            # Round r resolves every segment's r-th item: its input
            # state is seg-start state (r==0) or the adjacent
            # predecessor's output from the previous round.
            shifted = tuple(jnp.concatenate([o[:1], o[:-1]]) for o in out_states)
            ins = tuple(
                jnp.where(jnp.equal(r, 0), ss, sh)
                for ss, sh in zip(seg_states, shifted)
            )
            (ok_r, wait_r), new_states = transition(ins, items)
            sel = seg_pos == r
            ok = jnp.where(sel, ok_r, ok)
            wait = jnp.where(sel, wait_r, wait)
            out_states = tuple(
                jnp.where(sel, ns, os) for ns, os in zip(new_states, out_states)
            )
            return ok, wait, out_states

        if rounds <= UNROLL_MAX_ROUNDS:
            # Small counts: unroll at trace time so XLA fuses freely.
            out_states = seg_states
            for r in range(rounds):
                ok, wait, out_states = one_round(
                    jnp.int32(r), ok, wait, out_states
                )
            return ok, wait, out_states

        # Large counts: a fori_loop compiles the round body ONCE.
        # Unrolling 16+ copies of the transition into the HLO multiplied
        # remote-compile time past the bench's stage timeout (round-4
        # hardware session) for runtime that is identical.
        n_st = len(seg_states)

        def body(r, carry):
            ok, wait = carry[0], carry[1]
            out_states = tuple(carry[2 : 2 + n_st])
            ok, wait, out_states = one_round(r, ok, wait, out_states)
            return (ok, wait, *out_states)

        out = jax.lax.fori_loop(0, rounds, body, (ok, wait, *seg_states))
        return out[0], out[1], tuple(out[2 : 2 + n_st])

    n_st = len(seg_states)

    def step(carry, x):
        ng = x[0]
        item_vals = x[1 : 1 + len(items)]
        seg_vals = x[1 + len(items) :]
        states = tuple(
            jnp.where(ng, sv, cv) for sv, cv in zip(seg_vals, carry)
        )
        (ok_i, wait_i), new_states = transition(states, item_vals)
        return new_states, (ok_i, wait_i) + new_states

    init = tuple(a[0] for a in seg_states)
    xs = (new_grp,) + items + seg_states
    _, ys = jax.lax.scan(step, init, xs)
    return ys[0], ys[1], tuple(ys[2 : 2 + n_st])
