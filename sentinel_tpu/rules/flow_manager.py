"""Flow rule manager (reference: FlowRuleManager.java:56-170).

``load_rules`` validates + compiles the rule set to the device SoA table
(FlowIndex) and swaps it into the engine — the analog of
FlowRuleUtil.buildFlowRuleMap + the volatile map swap
(FlowRuleUtil.java:84-161, FlowRuleManager.java:159).
"""

from __future__ import annotations

from typing import List

from sentinel_tpu.models.rules import FlowRule
from sentinel_tpu.rules.manager_base import RuleManager


class FlowRuleManager(RuleManager[FlowRule]):
    rule_kind = "flow"

    def _apply(self, rules: List[FlowRule], engine) -> None:
        if engine is not None:
            engine.set_flow_rules(rules)

    def is_other_origin(self, origin: str, resource: str) -> bool:
        from sentinel_tpu.core.api import get_engine

        return get_engine().flow_index.is_other_origin(origin, resource)


flow_rule_manager = FlowRuleManager()
