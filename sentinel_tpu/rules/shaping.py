"""Traffic-shaping controllers: RateLimiter, WarmUp, WarmUpRateLimiter.

These three behaviors carry *per-rule mutable state* across requests —
``latestPassedTime`` for the leaky-bucket pacer (reference: controller/
RateLimiterController.java:28-90), ``storedTokens``/``lastFilledTime``
for the Guava-style warm-up ramp (reference: controller/
WarmUpController.java:84-175, WarmUpRateLimiterController.java:25-90) —
which makes them a *recurrence* over each rule's request sequence, not a
stateless threshold like DefaultController.

Batched execution: shaping slots (a tiny minority of traffic in
practice) are gathered into their own compact array, sorted by
``(rule, ts, entry)``, and resolved per rule-segment. Two exact
implementations share one transition function:

* ``rounds > 0`` — the vectorized path: within a segment each item's
  state comes from its immediate predecessor in the sorted order, so
  round *r* resolves every segment's *r*-th item in parallel; ``rounds``
  is the host-known max items-per-rule in the batch (a static arg —
  each bucket compiles once). M full-vector passes instead of an
  s-step sequential scan: on TPU this is the difference between ~µs
  and ~ms, because a ``lax.scan`` iteration costs per-step loop
  overhead regardless of how little work the body does.
* ``rounds == 0`` — one ``lax.scan`` whose carry is the current rule's
  shaping state; the fallback when one rule dominates the batch
  (max-per-rule too large for unrolled rounds).

Both reproduce the reference's per-request logic step for step —
including the per-second token re-fill (syncToken) — so they are exact
even when a batch spans multiple seconds. The vectorized DEFAULT path
never pays for any of this: when no shaping rules are loaded the module
is never entered.

Numerics: Java computes in float64; the math uses float32 for the
warm-up slope (divergence only possible exactly at a threshold
boundary for extreme rule counts) and host-precomputed exact int
``cost1_ms`` for the ubiquitous acquire==1 rate-limiter case. Java's
``latestPassedTime``/``lastFilledTime`` start effectively "infinitely
past" because wall-clock ms are huge; with the engine's relative clock
the same effect comes from the -1e9 initialisation in
FlowIndex.make_dyn_state.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from sentinel_tpu.models import constants as C
from sentinel_tpu.rules.flow_table import FlowRuleDynState, FlowTableDevice


class ShapingBatch(NamedTuple):
    """Compact per-slot arrays for shaping-controlled rule slots.

    ``flat_pos`` indexes back into the [N*K] flattened slot matrix so
    verdicts/waits can be scattered into the main result.
    """

    valid: jax.Array  # bool [S]
    gid: jax.Array  # int32 [S] rule id
    row: jax.Array  # int32 [S] check-node row
    eidx: jax.Array  # int32 [S] entry index
    flat_pos: jax.Array  # int32 [S] position in the [N*K] slot matrix
    ts: jax.Array  # int32 [S]
    acquire: jax.Array  # int32 [S]


def _pacer_cost(acq_f, acq_i, cnt, c1):
    """RateLimiter pacing cost in ms: the host-precomputed exact cost1
    for the acquire==1 fast path, else round(acquire/count*1000).
    Shared by the recurrence and the closed-form rank path — their
    bit-exact parity depends on one cost formula."""
    cost_generic = jnp.floor(acq_f / jnp.maximum(cnt, 1e-9) * 1000.0 + 0.5)
    return jnp.where(acq_i == 1, c1.astype(jnp.float32), cost_generic).astype(
        jnp.int32
    )


def _transition(latest, stored, lastfill, x):
    """One item's controller decision + state update, vector-friendly
    (works elementwise on arrays of items as well as on scan scalars).
    Invalid items are identity on state and ok=True.
    Returns (ok, wait_out, latest', stored', lastfill')."""
    (valid, ts, acq_f, acq, passq, prevq, b, cnt, mq, c1, wn, mx, sl, rt) = x

    is_wu = (b == C.CONTROL_BEHAVIOR_WARM_UP) | (
        b == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER
    )

    # --- syncToken (WarmUpController.syncToken/coolDownTokens) ---
    sec = ts - ts % 1000
    do_sync = is_wu & (sec > lastfill) & valid
    elapsed = (sec - lastfill).astype(jnp.float32)
    refill_ok = (stored < wn) | ((stored > wn) & (prevq < rt))
    refilled = jnp.minimum(jnp.floor(stored + elapsed * cnt / 1000.0), mx)
    stored1 = jnp.where(do_sync & refill_ok, refilled, stored)
    stored2 = jnp.where(do_sync, jnp.maximum(stored1 - prevq, 0.0), stored1)
    lastfill2 = jnp.where(do_sync, sec, lastfill)

    # --- warm-up admitted-QPS (above the warning line) ---
    above = jnp.maximum(stored2 - wn, 0.0)
    inv = above * sl + 1.0 / jnp.maximum(cnt, 1e-9)
    # Math.nextUp on the Java double; nextafter on f32 here.
    warning_qps = jnp.nextafter(1.0 / inv, jnp.float32(jnp.inf))
    cold = stored2 >= wn

    wu_ok = jnp.where(cold, passq + acq_f <= warning_qps, passq + acq_f <= cnt)

    # --- pacer cost (RateLimiter / WarmUpRateLimiter) ---
    cost_rl = _pacer_cost(acq_f, acq, cnt, c1).astype(jnp.float32)
    cost_wurl_cold = jnp.floor(acq_f / warning_qps * 1000.0 + 0.5)
    cost_wurl = jnp.where(cold, cost_wurl_cold, cost_rl)
    cost = jnp.where(
        b == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER, cost_wurl, cost_rl
    ).astype(jnp.int32)

    expected = latest + cost
    imm = expected <= ts
    wait = expected - ts
    queued = (~imm) & (wait <= mq)
    pacer_ok = (imm | queued) & (cnt > 0)
    pacer_ok = pacer_ok | (acq <= 0)  # acquire<=0 always passes
    latest2 = jnp.where(
        valid & pacer_ok & (acq > 0), jnp.where(imm, ts, latest + cost), latest
    )
    wait_out = jnp.where(queued & pacer_ok, wait, 0)

    is_pacer = (b == C.CONTROL_BEHAVIOR_RATE_LIMITER) | (
        b == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER
    )
    ok = jnp.where(
        b == C.CONTROL_BEHAVIOR_WARM_UP,
        wu_ok,
        jnp.where(is_pacer, pacer_ok, True),
    )
    ok = ok | ~valid
    wait_out = jnp.where(valid & is_pacer, wait_out, 0)

    # Pacer state only advances for pacer behaviors; warm-up state
    # only via sync. Invalid items must not touch state.
    latest3 = jnp.where(valid & is_pacer, latest2, latest)
    stored3 = jnp.where(valid, stored2, stored)
    lastfill3 = jnp.where(valid, lastfill2, lastfill)
    return ok, wait_out, latest3, stored3, lastfill3


def run_shaping(
    flow_dev: FlowTableDevice,
    flow_dyn: FlowRuleDynState,
    shaping: ShapingBatch,
    pass_consumed: jax.Array,  # int32 [S] — windowed pass sum + intra-batch charge
    prev_pass: jax.Array,  # int32 [S] — previous 1s-bucket pass count (minute array)
    interval_sec: float,
    rounds: int = 0,
) -> Tuple[FlowRuleDynState, jax.Array, jax.Array]:
    """Evaluate shaping slots; returns (new_dyn, ok [S], wait_ms [S])
    in the caller's slot order.

    ``rounds`` (static): host-known upper bound on items-per-rule in
    this batch — picks the vectorized rounds path; 0 falls back to the
    sequential ``lax.scan`` (see module docstring); −1 selects the
    closed-form pacer rank path, which is ONLY valid when the host
    verified every item is a plain RATE_LIMITER at one ts with one
    acquire ≥ 1 (Engine._shaping_rounds_for owns that predicate —
    run_shaping does not re-validate).

    The three behaviors (reference files in module docstring):

    * RATE_LIMITER — pace requests ``cost = round(acquire/count*1000)``
      ms apart; queue up to ``max_queueing_time_ms``, else block.
    * WARM_UP — token bucket from cold: above the warning line the
      admitted QPS is ``1/(aboveToken*slope + 1/count)``; refill happens
      once per second, consuming the previous second's pass count.
    * WARM_UP_RATE_LIMITER — the pacer with the warm-up-adjusted cost.
    """
    s = shaping.valid.shape[0]
    nr = flow_dev.n_rules

    # Sort by (gid, ts, arrival); invalid slots sort last (gid = nr).
    # Compacted batches are built in entry order (eidx nondecreasing in
    # item position), so pos as the last key reproduces the
    # (gid, ts, eidx) order with one less sort operand.
    gid_key = jnp.where(shaping.valid, shaping.gid, jnp.int32(nr))
    pos = jnp.arange(s, dtype=jnp.int32)
    gid_s, ts_s, p_s = jax.lax.sort((gid_key, shaping.ts, pos), num_keys=3)
    gid_c = jnp.clip(gid_s, 0, nr - 1)
    valid_s = shaping.valid[p_s]
    acq_s = shaping.acquire[p_s].astype(jnp.float32)
    acq_i = shaping.acquire[p_s]
    passq_s = jnp.floor(pass_consumed[p_s].astype(jnp.float32) / interval_sec)
    prevq_s = prev_pass[p_s].astype(jnp.float32)

    beh = flow_dev.behavior[gid_c]
    count = flow_dev.count[gid_c]
    maxq = flow_dev.max_queueing_time_ms[gid_c]
    cost1 = flow_dev.cost1_ms[gid_c]
    warn = flow_dev.warmup_warning_token[gid_c].astype(jnp.float32)
    maxtok = flow_dev.warmup_max_token[gid_c].astype(jnp.float32)
    slope = flow_dev.warmup_slope[gid_c]
    refill_thr = flow_dev.warmup_refill_threshold[gid_c].astype(jnp.float32)

    # Segment-start state is pre-gathered OUTSIDE the recurrence (one
    # vectorized gather instead of per-step dynamic gathers).
    seg_latest = flow_dyn.latest_passed_time[gid_c]
    seg_stored = flow_dyn.stored_tokens[gid_c]
    seg_lastfill = flow_dyn.last_filled_time[gid_c]

    items = (
        valid_s, ts_s, acq_s, acq_i, passq_s, prevq_s,
        beh, count, maxq, cost1, warn, maxtok, slope, refill_thr,
    )
    ones = jnp.ones((1,), dtype=bool)
    new_grp = jnp.concatenate([ones, gid_s[1:] != gid_s[:-1]])

    if rounds == -1:
        # Closed-form pacer path (host-selected when EVERY item is a
        # plain RATE_LIMITER at ONE timestamp with ONE acquire ≥ 1 —
        # the columnar-bulk shape): with a single ts per rule, at most
        # the FIRST grant can be immediate (it pins latest to ts), and
        # each further grant queues exactly one more ``cost`` out, so
        # the r-th grant's wait is a closed form of the segment-start
        # state and admission is prefix-monotone rank math — any
        # per-rule multiplicity in O(sort), no unroll, no scan.
        idx = jnp.arange(s, dtype=jnp.int32)
        seg_start = jax.lax.cummax(jnp.where(new_grp, idx, 0))
        r1 = idx - seg_start + 1  # rank within segment, 1-indexed

        cost = _pacer_cost(acq_s, acq_i, count, cost1)
        latest0 = seg_latest
        imm0 = latest0 + cost <= ts_s
        gate = count > 0

        # Segment grant cap G, division math only — ``r1 <= cap`` is
        # the admission test precisely BECAUSE rank×cost products can
        # overflow int32 for large segments × large costs (a wait-based
        # test wraps negative and wrongly admits); the cap form never
        # multiplies. (cost==0 → unbounded: every grant is immediate /
        # same constant wait, latest never advances.)
        big = jnp.int32(1 << 30)
        safe_cost = jnp.maximum(cost, 1)
        g_imm = jnp.where(cost > 0, 1 + maxq // safe_cost, big)
        g_queue = jnp.where(
            cost > 0,
            jnp.maximum((ts_s + maxq - latest0) // safe_cost, 0),
            jnp.where(latest0 - ts_s <= maxq, big, 0),
        )
        cap = jnp.where(gate, jnp.where(imm0, g_imm, g_queue), 0)
        ok_s = (valid_s & (r1 <= cap)) | ~valid_s
        # Waits only for admitted items, whose rank×cost is bounded by
        # maxq (+ts−latest0) and cannot overflow; blocked lanes may
        # wrap but are masked to 0.
        wait_r = jnp.where(imm0, (r1 - 1) * cost, latest0 + r1 * cost - ts_s)
        wait_out_s = jnp.where(valid_s & ok_s & (wait_r > 0), wait_r, 0)
        granted_here = jnp.minimum(r1, cap)
        latest_here = jnp.where(
            granted_here > 0,
            jnp.where(
                imm0, ts_s + (granted_here - 1) * cost,
                latest0 + granted_here * cost,
            ),
            latest0,
        )
        seg_end = jnp.concatenate(
            [gid_s[1:] != gid_s[:-1], jnp.ones((1,), dtype=bool)]
        ) & valid_s
        scatter_gid = jnp.where(seg_end, gid_c, jnp.int32(nr))
        new_dyn = FlowRuleDynState(
            latest_passed_time=flow_dyn.latest_passed_time.at[scatter_gid].set(
                latest_here, mode="drop"
            ),
            # Warm-up columns untouched: no WARM_UP items are eligible.
            stored_tokens=flow_dyn.stored_tokens,
            last_filled_time=flow_dyn.last_filled_time,
        )
        ok_out = jnp.ones((s,), dtype=bool).at[p_s].set(ok_s)
        wait_out = jnp.zeros((s,), dtype=jnp.int32).at[p_s].set(wait_out_s)
        return new_dyn, ok_out, wait_out

    def transition(states, item_vals):
        latest, stored, lastfill = states
        ok, wait_out, l2, s2, f2 = _transition(latest, stored, lastfill, item_vals)
        return (ok, wait_out), (l2, s2, f2)

    from sentinel_tpu.rules.recurrence import run_segmented

    ok_s, wait_s, (latest_s, stored_s, lastfill_s) = run_segmented(
        new_grp, (seg_latest, seg_stored, seg_lastfill), items, transition, rounds
    )

    # Write final per-rule state back at segment ends (last write wins).
    seg_end = jnp.concatenate(
        [gid_s[1:] != gid_s[:-1], jnp.ones((1,), dtype=bool)]
    ) & valid_s
    scatter_gid = jnp.where(seg_end, gid_c, jnp.int32(nr))  # nr -> dropped
    new_dyn = FlowRuleDynState(
        latest_passed_time=flow_dyn.latest_passed_time.at[scatter_gid].set(
            latest_s, mode="drop"
        ),
        stored_tokens=flow_dyn.stored_tokens.at[scatter_gid].set(stored_s, mode="drop"),
        last_filled_time=flow_dyn.last_filled_time.at[scatter_gid].set(
            lastfill_s, mode="drop"
        ),
    )

    # Un-sort results back to the caller's slot order.
    ok_out = jnp.ones((s,), dtype=bool).at[p_s].set(ok_s)
    wait_out = jnp.zeros((s,), dtype=jnp.int32).at[p_s].set(wait_s)
    return new_dyn, ok_out, wait_out


# ----------------------------------------------------------------------
# Host mirror of the shaping controllers (speculative fast tier)
# ----------------------------------------------------------------------
# The speculative tier (runtime/speculative.py) serves shaped resources
# from a persistent host mirror instead of declining them to the sync
# device path; the mirror's per-op decision lives HERE, next to the
# kernel recurrence it mirrors, so the two transition functions can
# only drift in one reviewed place. State (one mutable record per rule)
# lives on failover.HostFallbackAdmitter; these are pure-ish functions
# over that record. The device settles the very same ops and the mirror
# re-anchors to the settled ``latestPassedTime`` at every drain.


def mirror_pacer_cost(acquire: int, count: float, cost1_ms: int) -> int:
    """Host twin of :func:`_pacer_cost` — ONE cost formula. The
    ubiquitous acquire==1 case returns the host-precomputed exact int
    ``cost1_ms`` (bit-exact with the kernel, which reads the same
    column); generic acquire replicates the kernel's float32 math so a
    boundary-rounding divergence cannot admit on one plane and block on
    the other."""
    if acquire == 1:
        return int(cost1_ms)
    acq = np.float32(acquire)
    cnt = np.float32(max(float(count), 1e-9))
    return int(np.floor(np.float32(np.float32(acq / cnt) * np.float32(1000.0))
                        + np.float32(0.5)))


def mirror_shaping_decide(st, info, ts: int, acquire: int) -> Tuple[bool, int]:
    """One host decision + state update for a shaping-governed slot,
    mirroring :func:`_transition` step for step (syncToken refill,
    warm-up warning line, pacer cost/queueing). ``st`` is the mutable
    per-rule mirror record (failover._HostShaping: ``latest`` /
    ``stored`` / ``lastfill`` plus its pass counters); ``info`` is
    FlowIndex.mirror_shaping_info's static tuple. Returns
    ``(ok, wait_ms)``; state advances exactly when the kernel's would
    (a pacer grant advances ``latest`` even if a sibling slot later
    vetoes the entry — the caller sequences the stages to match)."""
    (_rule, behavior, count, maxq_ms, cost1_ms,
     warn, maxtok, slope, refill_thr) = info

    is_wu = behavior in (
        C.CONTROL_BEHAVIOR_WARM_UP, C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER
    )
    if is_wu:
        # --- syncToken (once per second; consumes prev-second pass),
        # in float32 elementwise like the kernel — a float64 floor can
        # land one token lower than the f32 one at product boundaries,
        # flipping the cold/warm classification between planes ---
        sec = ts - ts % 1000
        if sec > st.lastfill:
            prevq = float(st.pass_prev)
            refill_ok = st.stored < warn or (
                st.stored > warn and prevq < refill_thr
            )
            if refill_ok:
                elapsed = np.float32(sec - st.lastfill)
                refilled = np.floor(np.float32(
                    np.float32(st.stored)
                    + np.float32(np.float32(elapsed * np.float32(count))
                                 / np.float32(1000.0))
                ))
                st.stored = float(min(refilled, np.float32(maxtok)))
            st.stored = float(np.maximum(
                np.float32(st.stored) - np.float32(prevq), np.float32(0.0)
            ))
            st.lastfill = sec

    # --- warm-up admitted-QPS above the warning line (float32, like
    # the kernel — a float64 warning line could round the boundary
    # differently) ---
    above = np.float32(max(st.stored - warn, 0.0))
    inv = np.float32(
        above * np.float32(slope)
        + np.float32(1.0) / np.float32(max(float(count), 1e-9))
    )
    warning_qps = float(np.nextafter(np.float32(np.float32(1.0) / inv),
                                     np.float32(np.inf)))
    cold = st.stored >= warn

    if behavior == C.CONTROL_BEHAVIOR_WARM_UP:
        # passQps = floor(windowed pass / interval_sec), same rolling
        # LeapArray validity as the kernel's window_sums input.
        from sentinel_tpu.metrics import nodes as _ncfg

        interval_sec = _ncfg.SECOND_CFG.interval_ms / 1000.0
        passq = float(math.floor(st.passq(ts) / interval_sec))
        limit = warning_qps if cold else float(count)
        return passq + acquire <= limit, 0

    # --- pacer behaviors (RATE_LIMITER / WARM_UP_RATE_LIMITER) ---
    if acquire <= 0:
        return True, 0  # acquire<=0 always passes, no state change
    if count <= 0:
        return False, 0  # pacer_ok requires count > 0
    cost = mirror_pacer_cost(acquire, count, cost1_ms)
    if behavior == C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER and cold:
        cost = int(np.floor(
            np.float32(np.float32(np.float32(acquire)
                                  / np.float32(warning_qps))
                       * np.float32(1000.0))
            + np.float32(0.5)
        ))
    expected = st.latest + cost
    if expected <= ts:
        st.latest = ts  # immediate grant pins latest to NOW, not +=cost
        return True, 0
    wait = expected - ts
    if wait <= maxq_ms:
        st.latest += cost
        return True, int(wait)
    return False, 0


def mirror_pacer_bulk(
    latest0: int, count: float, maxq_ms: int, cost: int, ts: int,
    ranks: "np.ndarray",
) -> Tuple["np.ndarray", "np.ndarray", int]:
    """Closed-form host pacer for one bulk group's RATE_LIMITER slot —
    the host twin of the kernel's ``rounds == -1`` rank path (same
    preconditions: ONE timestamp, ONE acquire >= 1 per row, plain
    RATE_LIMITER; the speculative tier's predicate owns that check).
    ``ranks`` is the 1-indexed grant rank of each still-live row.
    Returns ``(ok, wait_ms, latest')``."""
    n = ranks.shape[0]
    if count <= 0:
        return (np.zeros(n, dtype=bool), np.zeros(n, dtype=np.int64),
                latest0)
    big = 1 << 30
    imm0 = latest0 + cost <= ts
    if cost > 0:
        g_imm = 1 + maxq_ms // cost
        g_queue = max((ts + maxq_ms - latest0) // cost, 0)
    else:
        g_imm = big
        g_queue = big if latest0 - ts <= maxq_ms else 0
    cap = g_imm if imm0 else g_queue
    ok = ranks <= cap
    if imm0:
        wait = (ranks.astype(np.int64) - 1) * cost
    else:
        wait = latest0 + ranks.astype(np.int64) * cost - ts
    wait = np.where(ok & (wait > 0), wait, 0)
    granted = int(min(int(ranks.max(initial=0)), cap))
    if granted > 0:
        latest = ts + (granted - 1) * cost if imm0 else latest0 + granted * cost
    else:
        latest = latest0
    return ok, wait, int(latest)
