"""Flow rule compilation and per-entry rule-slot resolution.

Compiles ``FlowRule`` beans into:

* **device SoA tensors** (one slot per rule, padded to a power of two):
  grade / count / control-behavior / shaping parameters — read by the
  vectorized admission kernel (equivalent to FlowRuleUtil.buildFlowRuleMap
  + generateRater, reference: FlowRuleUtil.java:84-161);
* a **host index**: rules grouped per resource in FlowRuleComparator
  order (origin-specific first, ``default`` last — reference:
  FlowRuleComparator.java), plus the limit-app set per resource needed
  for ``other`` matching (FlowRuleManager.isOtherOrigin).

Per-entry node selection (FlowRuleChecker.selectNodeByRequesterAndStrategy,
reference: FlowRuleChecker.java:96-165) runs on the host when an op is
encoded, yielding for each entry up to K ``(rule_gid, check_row)`` slots;
a rule that does not apply to the entry (null node in the reference)
contributes no slot and therefore passes trivially.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Set, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.metrics.nodes import NodeRegistry
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import FlowRule
from sentinel_tpu.utils.numeric import pad_pow2 as _pad_pow2
from sentinel_tpu.utils.record_log import record_log


class FlowTableDevice(NamedTuple):
    """Per-rule static parameters on device (padded; padding = always-pass)."""

    grade: jax.Array  # int32 [NR] FLOW_GRADE_THREAD / FLOW_GRADE_QPS
    count: jax.Array  # float32 [NR] threshold
    behavior: jax.Array  # int32 [NR] CONTROL_BEHAVIOR_*
    max_queueing_time_ms: jax.Array  # int32 [NR] (rate limiter)
    cost1_ms: jax.Array  # int32 [NR] host-precomputed round(1000/count) — the
    # acquire==1 rate-limiter cost in exact float64 (Java Math.round of a
    # double; device floats are f32, so the common case is computed on host)
    warmup_warning_token: jax.Array  # int32 [NR] (warm up)
    warmup_max_token: jax.Array  # int32 [NR]
    warmup_slope: jax.Array  # float32 [NR]
    warmup_refill_threshold: jax.Array  # int32 [NR] (int)count / coldFactor
    # (integer division, the refill gate in WarmUpController.coolDownTokens)

    @property
    def n_rules(self) -> int:
        return self.grade.shape[0]


class FlowRuleDynState(NamedTuple):
    """Per-rule *mutable* shaping state, carried across flushes.

    latest_passed_time ≙ RateLimiterController.latestPassedTime
    (reference: controller/RateLimiterController.java:28-90);
    stored_tokens / last_filled_time ≙ WarmUpController.storedTokens /
    lastFilledTime (reference: controller/WarmUpController.java:64-130).
    """

    latest_passed_time: jax.Array  # int32 [NR], ms rel epoch (-large init)
    stored_tokens: jax.Array  # float32 [NR]
    last_filled_time: jax.Array  # int32 [NR]


def _cost1_ms(count: float) -> int:
    """The acquire==1 rate-limiter cost: Java Math.round(1.0/count*1000)
    in float64 (Math.round is floor(x+0.5), not round-half-even; int()
    truncates = floor for positives). ONE home shared by the device
    table build and the host shaping mirror — the acquire==1 pacer's
    bit-exact cross-plane parity depends on the two reading the same
    integer."""
    return int(1.0 / count * 1000 + 0.5)


def _warmup_constants(r: FlowRule, cold_factor: int) -> Tuple[int, int, float, int]:
    """Guava SmoothWarmingUp-derived constants, computed exactly as the
    reference does (WarmUpController.construct, reference: controller/
    WarmUpController.java:84-107):

    *   warningToken = (int)(warmupSec * count) / (coldFactor-1)
        [int cast of the product, then INTEGER division]
    *   maxToken = warningToken + (int)(2*warmupSec*count/(1+coldFactor))
    *   slope = (coldFactor - 1) / count / (maxToken - warningToken)
    *   refill gate: passQps < (int)count / coldFactor
        ((int) binds to count; then integer division).

    The ONE home for both the device table build and the host shaping
    mirror (mirror_shaping_info) — the same constants on both planes is
    what makes the mirror's warm-up ramp faithful."""
    cf = cold_factor
    warning = int(r.warm_up_period_sec * r.count) // (cf - 1)
    max_tok = warning + int(2 * r.warm_up_period_sec * r.count / (1.0 + cf))
    slope = (
        (cf - 1.0) / r.count / (max_tok - warning)
        if r.count > 0 and max_tok > warning
        else 0.0
    )
    return warning, max_tok, slope, int(r.count) // cf


@dataclass
class CompiledFlowRule:
    gid: int
    rule: FlowRule


class FlowIndex:
    """Host-side compiled view of the active flow rules."""

    def __init__(self, rules: Sequence[FlowRule], cold_factor: int = 3) -> None:
        # (resource, context, origin) -> resolved slots; see resolve_slots.
        self._slot_cache: Dict[Tuple[str, str, str], List[Tuple[int, int]]] = {}
        valid: List[FlowRule] = []
        for r in rules:
            if isinstance(r, dict):
                from sentinel_tpu.models.rules import rules_from_json

                r = rules_from_json([r], FlowRule)[0]
            if r.is_valid():
                valid.append(r)
            else:
                record_log.warn("[FlowIndex] Ignoring invalid flow rule: %s", r)

        # FlowRuleComparator: origin-specific first, LIMIT_APP_OTHER next,
        # LIMIT_APP_DEFAULT last (stable within class).
        def order_key(r: FlowRule) -> int:
            if r.limit_app == C.LIMIT_APP_DEFAULT:
                return 2
            if r.limit_app == C.LIMIT_APP_OTHER:
                return 1
            return 0

        self.rules: List[CompiledFlowRule] = []
        self.by_resource: Dict[str, List[CompiledFlowRule]] = {}
        self.limit_apps: Dict[str, Set[str]] = {}
        by_res: Dict[str, List[FlowRule]] = {}
        for r in valid:
            by_res.setdefault(r.resource, []).append(r)
        for res, rs in by_res.items():
            rs_sorted = sorted(rs, key=order_key)
            compiled = []
            for r in rs_sorted:
                cr = CompiledFlowRule(gid=len(self.rules), rule=r)
                self.rules.append(cr)
                compiled.append(cr)
            self.by_resource[res] = compiled
            self.limit_apps[res] = {r.limit_app for r in rs}

        self.max_rules_per_resource = max((len(v) for v in self.by_resource.values()), default=0)
        self.cold_factor = cold_factor
        self.device = self._build_device()
        self.shaping_gids = {
            cr.gid
            for cr in self.rules
            if cr.rule.control_behavior != C.CONTROL_BEHAVIOR_DEFAULT
        }
        # Cluster-mode rules route through the token service
        # (FlowRuleChecker.passClusterCheck) instead of the local check.
        self.cluster_gids = {
            cr.gid: cr.rule for cr in self.rules if cr.rule.cluster_mode
        }

    def _build_device(self) -> FlowTableDevice:
        n = _pad_pow2(len(self.rules))
        grade = [C.FLOW_GRADE_QPS] * n
        count = [float("inf")] * n  # padding threshold: always pass
        behavior = [C.CONTROL_BEHAVIOR_DEFAULT] * n
        maxq = [0] * n
        cost1 = [0] * n
        w_warn = [0] * n
        w_max = [0] * n
        w_slope = [0.0] * n
        w_refill = [0] * n
        self.has_shaping = False
        for cr in self.rules:
            r = cr.rule
            grade[cr.gid] = r.grade
            count[cr.gid] = float(r.count)
            behavior[cr.gid] = r.control_behavior
            maxq[cr.gid] = int(r.max_queueing_time_ms)
            if r.control_behavior != C.CONTROL_BEHAVIOR_DEFAULT:
                self.has_shaping = True
            if r.count > 0:
                cost1[cr.gid] = _cost1_ms(r.count)
            if r.control_behavior in (
                C.CONTROL_BEHAVIOR_WARM_UP,
                C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER,
            ):
                warning, max_tok, slope, refill = _warmup_constants(
                    r, self.cold_factor
                )
                w_warn[cr.gid] = warning
                w_max[cr.gid] = max_tok
                w_slope[cr.gid] = slope
                w_refill[cr.gid] = refill
        return FlowTableDevice(
            grade=jnp.array(grade, dtype=jnp.int32),
            count=jnp.array(count, dtype=jnp.float32),
            behavior=jnp.array(behavior, dtype=jnp.int32),
            max_queueing_time_ms=jnp.array(maxq, dtype=jnp.int32),
            cost1_ms=jnp.array(cost1, dtype=jnp.int32),
            warmup_warning_token=jnp.array(w_warn, dtype=jnp.int32),
            warmup_max_token=jnp.array(w_max, dtype=jnp.int32),
            warmup_slope=jnp.array(w_slope, dtype=jnp.float32),
            warmup_refill_threshold=jnp.array(w_refill, dtype=jnp.int32),
        )

    def make_dyn_state(self, prev: Optional[FlowRuleDynState] = None) -> FlowRuleDynState:
        """Fresh mutable columns; carried values are NOT preserved across
        rule reloads, matching the reference where loadRules builds new
        controller objects with fresh state (FlowRuleUtil.java:141-161)."""
        n = self.device.n_rules
        return FlowRuleDynState(
            latest_passed_time=jnp.full((n,), -(10**9), dtype=jnp.int32),
            stored_tokens=jnp.zeros((n,), dtype=jnp.float32),
            last_filled_time=jnp.full((n,), -(10**9), dtype=jnp.int32),
        )

    def is_other_origin(self, origin: str, resource: str) -> bool:
        """Reference: FlowRuleManager.isOtherOrigin — origin counts as
        "other" iff no rule of this resource names it as limitApp."""
        if not origin:
            return False
        return origin not in self.limit_apps.get(resource, set())

    def resolve_slots(
        self,
        resource: str,
        context_name: str,
        origin: str,
        nodes: NodeRegistry,
    ) -> List[Tuple[int, int]]:
        """(rule_gid, check_row) for every rule that applies to this entry.

        Mirrors selectNodeByRequesterAndStrategy
        (FlowRuleChecker.java:96-165). A rule returning "no node" there is
        simply omitted (it passes trivially).

        Memoized per (resource, context, origin): node rows are stable
        once interned and the rule set is frozen per index, so repeat
        submissions skip the per-rule row selection (the submit hot
        path — the analog of the reference caching one slot chain per
        resource, CtSph.lookProcessChain). The cache assumes one
        NodeRegistry per index, which the engine guarantees (a reload
        builds a fresh index; reset builds both fresh). Callers must
        not mutate the returned list.
        """
        key = (resource, context_name, origin)
        hit = self._slot_cache.get(key)
        if hit is not None:
            return hit
        out: List[Tuple[int, int]] = []
        cacheable = True
        for cr in self.by_resource.get(resource, ()):
            r = cr.rule
            row = self._select_row(r, resource, context_name, origin, nodes)
            if row is not None:
                out.append((cr.gid, row))
            elif (
                r.strategy == C.STRATEGY_RELATE
                and r.ref_resource
                and nodes.lookup_cluster_row(r.ref_resource) is None
            ):
                # RELATE omission is TRANSIENT: the referenced
                # resource's node appears when it first sees traffic
                # (lookup is non-creating, matching selectReferenceNode
                # returning null until then) — pinning the omission
                # would disable the cross-resource limit forever.
                cacheable = False
        if cacheable:
            self._slot_cache[key] = out
        return out

    def _select_row(
        self,
        r: FlowRule,
        resource: str,
        context_name: str,
        origin: str,
        nodes: NodeRegistry,
    ) -> Optional[int]:
        la = r.limit_app
        if la == origin and origin not in (C.LIMIT_APP_DEFAULT, C.LIMIT_APP_OTHER):
            if r.strategy == C.STRATEGY_DIRECT:
                return nodes.origin_row(resource, origin)
            return self._reference_row(r, resource, context_name, nodes)
        if la == C.LIMIT_APP_DEFAULT:
            if r.strategy == C.STRATEGY_DIRECT:
                return nodes.cluster_row(resource)
            return self._reference_row(r, resource, context_name, nodes)
        if la == C.LIMIT_APP_OTHER and self.is_other_origin(origin, resource):
            if r.strategy == C.STRATEGY_DIRECT:
                return nodes.origin_row(resource, origin)
            return self._reference_row(r, resource, context_name, nodes)
        return None

    def _reference_row(
        self, r: FlowRule, resource: str, context_name: str, nodes: NodeRegistry
    ) -> Optional[int]:
        # Reference: FlowRuleChecker.selectReferenceNode.
        if not r.ref_resource:
            return None
        if r.strategy == C.STRATEGY_RELATE:
            return nodes.lookup_cluster_row(r.ref_resource)
        if r.strategy == C.STRATEGY_CHAIN:
            if r.ref_resource != context_name:
                return None
            return nodes.default_row(resource, context_name)
        return None

    def get_rules(self) -> List[FlowRule]:
        return [cr.rule for cr in self.rules]

    def user_rules(self) -> List[FlowRule]:
        """Rules excluding sketch-tier synthetics (``from_sketch``) —
        the base a promotion/demotion rebuild layers its synthetic
        dense guards on top of (runtime/sketch.py). A user reload
        through the rule manager never carries synthetics, so the tier
        re-asserts live promotions on its next controller pass."""
        return [
            cr.rule
            for cr in self.rules
            if not getattr(cr.rule, "from_sketch", False)
        ]

    def rule_of_gid(self, gid: int) -> Optional[FlowRule]:
        if 0 <= gid < len(self.rules):
            return self.rules[gid].rule
        return None

    def mirror_info(self, gid: int):
        """Host-mirror compilation hook (runtime/speculative.py /
        runtime/failover.py): ``(rule, grade, capacity, window_ms)``
        for one gid, or None. Compiled lazily once per index — the
        speculative tier consults this per admitted op, so the grade
        test and threshold float() must not be re-derived from the rule
        bean every time. QPS thresholds are per 1 s, the reference's
        windowed count."""
        cache = getattr(self, "_mirror_cache", None)
        if cache is None:
            cache = self._mirror_cache = {}
        hit = cache.get(gid)
        if hit is None:
            rule = self.rule_of_gid(gid)
            if rule is None:
                return None
            hit = cache[gid] = (
                rule, rule.grade, float(rule.count), 1000.0,
            )
        return hit

    def mirror_shaping_info(self, gid: int):
        """Host-mirror compilation hook for shaping-governed rules
        (runtime/speculative.py via failover.HostFallbackAdmitter):
        ``(rule, behavior, count, max_queueing_time_ms, cost1_ms,
        warning_token, max_token, slope, refill_threshold)`` for one
        gid, or None for non-shaping/unknown gids. ``cost1_ms`` is the
        same host-precomputed exact int the device table carries — the
        acquire==1 pacer cost is therefore bit-identical on both
        planes. Cached once per index, like :meth:`mirror_info`."""
        cache = getattr(self, "_shaping_mirror_cache", None)
        if cache is None:
            cache = self._shaping_mirror_cache = {}
        hit = cache.get(gid)
        if hit is None:
            if gid not in self.shaping_gids:
                return None
            rule = self.rule_of_gid(gid)
            if rule is None:
                return None
            cost1 = _cost1_ms(rule.count) if rule.count > 0 else 0
            warning = max_tok = refill = 0
            slope = 0.0
            if rule.control_behavior in (
                C.CONTROL_BEHAVIOR_WARM_UP,
                C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER,
            ):
                warning, max_tok, slope, refill = _warmup_constants(
                    rule, self.cold_factor
                )
            hit = cache[gid] = (
                rule, rule.control_behavior, float(rule.count),
                int(rule.max_queueing_time_ms), cost1,
                float(warning), float(max_tok), float(slope), float(refill),
            )
        return hit
