"""Plugin registry — the SPI equivalent.

The reference discovers implementations through a custom ``SpiLoader``
reading ``META-INF/services`` files, with ``@Spi(order, isSingleton,
isDefault)`` metadata (reference: sentinel-core/.../spi/SpiLoader.java:73,
168,179 and spi/Spi.java). The Python-native equivalent is a registry
keyed by interface with decorator registration plus optional
``importlib.metadata`` entry-point discovery (group
``sentinel_tpu.<iface-name>``), preserving order / singleton / default
semantics.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Type


@dataclass(order=True)
class _Provider:
    order: int
    name: str = field(compare=False)
    factory: Callable[[], Any] = field(compare=False)
    singleton: bool = field(compare=False, default=True)
    is_default: bool = field(compare=False, default=False)
    _instance: Any = field(compare=False, default=None, repr=False)

    def get(self) -> Any:
        if not self.singleton:
            return self.factory()
        if self._instance is None:
            self._instance = self.factory()
        return self._instance


class Registry:
    """Per-interface provider table with sorted loading.

    API mirrors SpiLoader: ``load_instance_list_sorted()``
    (SpiLoader.java:168), ``load_highest_priority_instance()``
    (SpiLoader.java:179), ``load_default()`` and name lookup.
    """

    _registries: Dict[str, "Registry"] = {}
    _global_lock = threading.Lock()

    def __init__(self, iface: str) -> None:
        self.iface = iface
        self._providers: Dict[str, _Provider] = {}
        self._lock = threading.Lock()
        self._entry_points_loaded = False

    @classmethod
    def of(cls, iface: Any) -> "Registry":
        key = iface if isinstance(iface, str) else f"{iface.__module__}.{iface.__qualname__}"
        with cls._global_lock:
            reg = cls._registries.get(key)
            if reg is None:
                reg = cls(key)
                cls._registries[key] = reg
            return reg

    @classmethod
    def reset_all(cls) -> None:
        with cls._global_lock:
            cls._registries.clear()

    def register(
        self,
        factory: Callable[[], Any],
        *,
        name: Optional[str] = None,
        order: int = 0,
        singleton: bool = True,
        default: bool = False,
    ) -> None:
        pname = name or getattr(factory, "__name__", repr(factory))
        with self._lock:
            self._providers[pname] = _Provider(
                order=order, name=pname, factory=factory, singleton=singleton, is_default=default
            )

    def _discover_entry_points(self) -> None:
        if self._entry_points_loaded:
            return
        self._entry_points_loaded = True
        try:
            from importlib.metadata import entry_points

            group = "sentinel_tpu." + self.iface.rsplit(".", 1)[-1].lower()
            for ep in entry_points(group=group):
                self.register(ep.load(), name=ep.name)
        except Exception:  # discovery is best-effort, like SpiLoader's classpath scan
            pass

    def _sorted(self) -> List[_Provider]:
        self._discover_entry_points()
        with self._lock:
            return sorted(self._providers.values())

    def load_instance_list_sorted(self) -> List[Any]:
        return [p.get() for p in self._sorted()]

    def load_highest_priority_instance(self) -> Optional[Any]:
        ps = self._sorted()
        return ps[0].get() if ps else None

    def load_default(self) -> Optional[Any]:
        for p in self._sorted():
            if p.is_default:
                return p.get()
        return self.load_highest_priority_instance()

    def load_by_name(self, name: str) -> Optional[Any]:
        self._discover_entry_points()
        with self._lock:
            p = self._providers.get(name)
        return p.get() if p else None

    def names(self) -> List[str]:
        return [p.name for p in self._sorted()]


def provider(
    iface: Any,
    *,
    name: Optional[str] = None,
    order: int = 0,
    singleton: bool = True,
    default: bool = False,
) -> Callable[[Type], Type]:
    """Class decorator: ``@provider(ProcessorSlot, order=-7000)``.

    Equivalent of the reference's ``@Spi`` annotation (spi/Spi.java).
    """

    def deco(cls: Type) -> Type:
        Registry.of(iface).register(
            cls, name=name or cls.__name__, order=order, singleton=singleton, default=default
        )
        return cls

    return deco
