"""Layered configuration.

Mirrors the reference's config stack (reference: sentinel-core/.../config/
SentinelConfigLoader.java:38-59 — JVM ``-Dcsp.sentinel.*`` > properties
file > defaults; SentinelConfig.java:54-65 for the key set). Here the
layers are: runtime ``set()`` > environment ``SENTINEL_TPU_*`` (or the
reference-compatible ``CSP_SENTINEL_*``) > properties file > defaults.

The properties file path comes from ``SENTINEL_TPU_CONFIG_FILE`` /
``CSP_SENTINEL_CONFIG_FILE`` (reference: SentinelConfigLoader.java:41) or
defaults to ``./sentinel.properties`` if present.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional


def _parse_properties(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#") or line.startswith("!"):
            continue
        for sep in ("=", ":"):
            if sep in line:
                k, _, v = line.partition(sep)
                out[k.strip()] = v.strip()
                break
    return out


class SentinelConfig:
    """Key registry + typed accessors.

    Key names keep the reference's ``csp.sentinel.*`` spelling
    (reference: SentinelConfig.java:54-65) so existing property files
    carry over.
    """

    APP_NAME = "project.name"
    APP_TYPE = "csp.sentinel.app.type"
    CHARSET = "csp.sentinel.charset"
    SINGLE_METRIC_FILE_SIZE = "csp.sentinel.metric.file.single.size"
    TOTAL_METRIC_FILE_COUNT = "csp.sentinel.metric.file.total.count"
    COLD_FACTOR = "csp.sentinel.flow.cold.factor"
    STATISTIC_MAX_RT = "csp.sentinel.statistic.max.rt"
    SPI_CLASSLOADER = "csp.sentinel.spi.classloader"
    METRIC_FLUSH_INTERVAL = "csp.sentinel.metric.flush.interval"
    # TPU-native keys (no reference equivalent).
    FLUSH_INTERVAL_MS = "sentinel.tpu.flush.interval.ms"
    FLUSH_MAX_BATCH = "sentinel.tpu.flush.max.batch"
    # Max flush_async dispatches in flight before the oldest fetch is
    # forced (bounds device memory held by unfetched results).
    FLUSH_MAX_INFLIGHT = "sentinel.tpu.flush.max.inflight"
    # OccupyTimeoutProperty (reference: CORE/node/OccupyTimeoutProperty.java):
    # max borrowable wait for prioritized entries, < interval.
    OCCUPY_TIMEOUT_MS = "csp.sentinel.statistic.occupy.timeout"
    INITIAL_ROWS = "sentinel.tpu.rows.initial"
    # Host-ingest fast path: persistent param-value intern cache +
    # reusable encode-buffer arena. On by default; the off position
    # exists for differential testing (the with/without smoke test) and
    # as an escape hatch — both paths must produce bit-identical
    # verdicts.
    HOST_FASTPATH = "sentinel.tpu.host.fastpath"
    # Depth-K flush pipeline: Engine.flush() keeps up to this many
    # dispatched-but-unfetched flushes in flight (encode/dispatch of
    # flush N+1 overlaps device execution of flush N). 0 = the fully
    # synchronous flush — the differential oracle for the pipelined
    # path and the default.
    PIPELINE_DEPTH = "sentinel.tpu.host.pipeline.depth"
    # Encode-buffer arena bounds: how many recent padded-shape keys are
    # pooled, and how many buffer sets per key. The per-key bound is
    # raised automatically to pipeline_depth + 1 (every in-flight flush
    # pins one staging set per shape key; an undersized pool would
    # silently fall back to fresh allocations at depth).
    ARENA_MAX_KEYS = "sentinel.tpu.host.arena.max.keys"
    ARENA_PER_KEY = "sentinel.tpu.host.arena.per.key"
    # Engine flight recorder (metrics/telemetry.py): per-flush spans,
    # latency histograms and the blocked-resource sketch. Enabled by
    # default — the off position compiles the kernel sketch fold away
    # and skips every span record (the ≤2% overhead contract is
    # enforced by the telemetry bench test).
    TELEMETRY_ENABLED = "sentinel.tpu.telemetry.enabled"
    TELEMETRY_RING = "sentinel.tpu.telemetry.ring"
    # Device-side top-K blocked-resource candidates folded into each
    # flush's kernel outputs (0 disables the fold entirely). The
    # ``blocked.topk`` spelling is preferred since the statistics
    # sketch tier (sentinel.tpu.sketch.*) landed; the historical
    # ``telemetry.sketch.*`` keys stay as accepted fallbacks (read when
    # the new key is unset) so existing property files keep working.
    TELEMETRY_BLOCKED_TOPK_K = "sentinel.tpu.telemetry.blocked.topk.k"
    TELEMETRY_SKETCH_K = "sentinel.tpu.telemetry.sketch.k"
    # Host-side space-saving summary capacity the per-flush top-Ks
    # merge into (same preferred/fallback pairing as above).
    TELEMETRY_BLOCKED_TOPK_CAP = "sentinel.tpu.telemetry.blocked.topk.capacity"
    TELEMETRY_SKETCH_CAP = "sentinel.tpu.telemetry.sketch.capacity"
    # How many blocked-top-K rows the exports list (Prometheus
    # sentinel_engine_blocked_weight, the `telemetry` command, the
    # sketch tier's candidate listing) when the device fold is off —
    # the ONE home of the former hand-rolled `sketch_k or 10`.
    TELEMETRY_TOPK_EXPORT = "sentinel.tpu.telemetry.topk.export"
    # Statistics sketch tier (runtime/sketch.py): fixed-size on-device
    # count-min + candidate table tracking EVERY key the engine sees
    # (unconfigured/cold resources, high-cardinality param values) with
    # heavy-hitter promotion into exact dense rows. Opt-in — disabled
    # costs one attribute read per submit/flush and the kernel fold is
    # never compiled.
    SKETCH_ENABLED = "sentinel.tpu.sketch.enabled"
    # Count-min geometry: depth hash rows x width counters (width is
    # rounded up to a power of two). Device memory is depth*width*4
    # bytes — O(1) in the key cardinality.
    SKETCH_DEPTH = "sentinel.tpu.sketch.depth"
    SKETCH_WIDTH = "sentinel.tpu.sketch.width"
    # Device candidate-table slots (the space-saving-style heavy-hitter
    # set that rides the coalesced drain fetch).
    SKETCH_CANDIDATES = "sentinel.tpu.sketch.candidates"
    # Decay window: counts halve once per window (engine clock), so a
    # key's steady-state count converges to ~2x its per-window volume.
    SKETCH_WINDOW_MS = "sentinel.tpu.sketch.window.ms"
    # Promotion threshold for sketch-mode param VALUES (estimated
    # acquire/sec; 0 disarms value promotion).
    SKETCH_PROMOTE_QPS = "sentinel.tpu.sketch.promote.qps"
    # Default dense-rule QPS for promoted unconfigured RESOURCES (the
    # synthetic flow rule's count; 0 disarms resource promotion).
    SKETCH_RESOURCE_QPS = "sentinel.tpu.sketch.resource.qps"
    # Max promoted keys (values + resources) held at once.
    SKETCH_PROMOTE_MAX = "sentinel.tpu.sketch.promote.max"
    # Consecutive decay windows a promoted key must stay below the
    # demotion threshold before it falls back to sketch-only.
    SKETCH_DEMOTE_WINDOWS = "sentinel.tpu.sketch.demote.windows"
    # Bound on the host id->name map resolving drained candidate ids
    # back to key names (LRU; ids are stable hashes so eviction never
    # corrupts device state).
    SKETCH_NAMES_CAP = "sentinel.tpu.sketch.names.capacity"
    # Admission tracing (metrics/admission_trace.py): bounded sampled
    # ring of per-admission verdict-provenance records with W3C
    # trace-context propagation. Enabled by default — disabled costs
    # one bool read per submit.
    TRACE_ENABLED = "sentinel.tpu.trace.enabled"
    TRACE_RING = "sentinel.tpu.trace.ring"
    # Head-based probabilistic sample rate (0..1) for admissions with
    # no inbound trace decision; an inbound traceparent's sampled flag
    # is honored as-is.
    TRACE_SAMPLE_RATE = "sentinel.tpu.trace.sample.rate"
    # Always record blocked admissions regardless of the head decision
    # (the "why was THIS call 429'd" mode).
    TRACE_SAMPLE_BLOCKED = "sentinel.tpu.trace.sample.blocked"
    # Per bulk group, at most this many rows recorded per class
    # (blocked / head-sampled) — keeps tracing bounded at bulk sizes.
    TRACE_BULK_CAP = "sentinel.tpu.trace.bulk.cap"
    # Device-failure domain (runtime/failover.py): health state
    # machine + flush watchdog + host-fallback admission + checkpoint/
    # restore. Opt-in — disabled costs one attribute read per flush and
    # device errors re-raise to callers exactly as before.
    FAILOVER_ENABLED = "sentinel.tpu.failover.enabled"
    # Watchdog bound on kernel dispatch and the device->host fetch: a
    # wedged jax.device_get times out (on a waiter thread) and trips
    # the engine DEGRADED instead of stranding submitters forever.
    FAILOVER_FETCH_TIMEOUT_MS = "sentinel.tpu.failover.fetch.timeout.ms"
    # Per-resource fail-open/fail-closed while DEGRADED: "open" |
    # "closed" | "open,resA=closed,..." (first '='-less segment is the
    # default). Default open, like the reference's pass-on-fallback.
    FAILOVER_POLICY = "sentinel.tpu.failover.policy"
    # Host checkpoint cadence in flushes (rides the coalesced result
    # fetch; 0 disables checkpoints — recovery then restores fresh
    # states).
    FAILOVER_CHECKPOINT_EVERY = "sentinel.tpu.failover.checkpoint.every"
    # Consecutive successful probe no-op flushes required before a
    # RECOVERING engine goes HEALTHY.
    FAILOVER_PROBE_FLUSHES = "sentinel.tpu.failover.probe.flushes"
    # Min gap (engine clock) between automatic recovery attempts from
    # the flush path; explicit try_recover() ignores it.
    FAILOVER_RETRY_MS = "sentinel.tpu.failover.retry.ms"
    # Durable checkpoint spill (runtime/failover.py): when set, every
    # stored in-memory checkpoint also spills to this file (atomic
    # rename, versioned header, crc) so a RESTARTED engine process can
    # warm-start via restore_durable(). "" (the default) = off, the
    # pre-PR-15 in-memory-only behavior bit for bit.
    FAILOVER_CKPT_PATH = "sentinel.tpu.failover.checkpoint.path"
    # Min gap between durable spills (wall ms) — bounds the write cost
    # at high flush rates without touching the in-memory cadence.
    FAILOVER_CKPT_INTERVAL_MS = "sentinel.tpu.failover.checkpoint.interval.ms"
    # Max age (wall ms) a durable checkpoint may have at load; older
    # files degrade to a cold start (counted, never an exception).
    # 0 = no age limit (shape/window-geometry validation still applies).
    FAILOVER_CKPT_STALE_MS = "sentinel.tpu.failover.checkpoint.stale.ms"
    # Speculative admission tier (runtime/speculative.py): host mirrors
    # serve the immediate verdict for single entries and bulk groups,
    # the device flush settles authoritatively, and reconciliation at
    # each drain bounds the drift. Opt-in — disabled costs one bool
    # read per entry_sync/submit_bulk.
    SPECULATIVE_ENABLED = "sentinel.tpu.speculative.enabled"
    # Pending-op count at which a speculative entry_sync/submit triggers
    # an async settle dispatch (bounds reconciliation lag without a
    # blocking flush on the admission path).
    SPECULATIVE_FLUSH_BATCH = "sentinel.tpu.speculative.flush.batch"
    # Per-window observed over-admits (speculative admit, device block)
    # after which the tier stops speculating until the window rolls —
    # the divergence safety valve the differential test pins (0 = no
    # enforcement, drift is still measured).
    SPECULATIVE_OVERADMIT_MAX = "sentinel.tpu.speculative.overadmit.max"
    # Drift accounting window (engine clock) for the per-window
    # over/under-admit counters and the drift histogram.
    SPECULATIVE_WINDOW_MS = "sentinel.tpu.speculative.drift.window.ms"
    # Host mirror of the shaping controllers (RateLimiter pacer /
    # WarmUp token ramp): shaped resources get immediate speculative
    # verdicts with exact pacing waits instead of declining to the
    # sync device path. On by default when the tier is on; the off
    # position restores the PR-6 decline-to-device stance.
    SPECULATIVE_SHAPING = "sentinel.tpu.speculative.shaping.enabled"
    # Engine ingest self-protection (runtime/ingest.py): bounded
    # pending-op/bulk queues with a deadline-aware shedding valve.
    # Under saturation callers get a fast BLOCK_SHED verdict instead of
    # unbounded queue growth or indefinite blocking. All three keys
    # default 0 = disarmed (one attribute read per submit).
    INGEST_MAX_PENDING = "sentinel.tpu.ingest.max.pending"
    INGEST_MAX_PENDING_BULK = "sentinel.tpu.ingest.max.pending.bulk"
    # Adapter-edge batch window (runtime/window.py): concurrent
    # in-flight requests from the per-request adapters (WSGI/ASGI/
    # Flask/FastAPI/aiohttp/gRPC/gateway_entry) coalesce for up to
    # window.ms into ONE columnar submit_bulk ride per resource group,
    # with per-request verdict fan-out. 0 (the default) = off: every
    # adapter keeps today's per-request submit+flush behavior.
    INGEST_BATCH_WINDOW_MS = "sentinel.tpu.ingest.batch.window.ms"
    # Max requests one window coalesces before it flushes early.
    INGEST_BATCH_MAX = "sentinel.tpu.ingest.batch.max"
    # Shed when the estimated verdict latency (settle-latency EWMA x
    # (in-flight flushes + 1), the PR-3 flight-recorder signals)
    # exceeds this deadline.
    INGEST_DEADLINE_MS = "sentinel.tpu.ingest.deadline.ms"
    # Self-tuning control plane (runtime/autotune.py): an engine-scoped
    # controller driven once per drain tick that AIMD-adjusts the flush
    # pipeline depth, retunes the adapter batch window, and picks the
    # closed-form vs scan param path from a shape-bucketed cost memo.
    # Default off = bit-identical static-config behavior (one attribute
    # read per drain).
    AUTOTUNE_ENABLED = "sentinel.tpu.autotune.enabled"
    # Decision cadence (engine clock) and per-knob cooldown after a
    # change (hysteresis against oscillation).
    AUTOTUNE_INTERVAL_MS = "sentinel.tpu.autotune.interval.ms"
    AUTOTUNE_COOLDOWN_MS = "sentinel.tpu.autotune.cooldown.ms"
    # Upper bound the depth controller may raise
    # sentinel.tpu.host.pipeline.depth to (never exceeded).
    AUTOTUNE_DEPTH_MAX = "sentinel.tpu.autotune.depth.max"
    # Min settled flush spans per tick before any decision is taken —
    # a thin sample must hold, not steer.
    AUTOTUNE_MIN_FLUSHES = "sentinel.tpu.autotune.min.flushes"
    # Occupancy dead band: raise depth only at >= high, lower only at
    # <= low for idle.ticks consecutive ticks. The gap between the two
    # is the hysteresis band that prevents K <-> K+1 flapping.
    AUTOTUNE_OCC_HIGH = "sentinel.tpu.autotune.occupancy.high"
    AUTOTUNE_OCC_LOW = "sentinel.tpu.autotune.occupancy.low"
    AUTOTUNE_IDLE_TICKS = "sentinel.tpu.autotune.idle.ticks"
    # Device-wait fractions (relative to host encode+dispatch work per
    # tick): raise depth only when unhidden device wait exceeds
    # raise.frac (there is something to hide); treat device wait beyond
    # stall.frac as a drain stall and step depth back down.
    AUTOTUNE_RAISE_FRAC = "sentinel.tpu.autotune.raise.frac"
    AUTOTUNE_STALL_FRAC = "sentinel.tpu.autotune.stall.frac"
    # Batch-window bounds the window controller may grow
    # sentinel.tpu.ingest.batch.{window.ms,max} to.
    AUTOTUNE_WINDOW_MS_MAX = "sentinel.tpu.autotune.window.ms.max"
    AUTOTUNE_WINDOW_BATCH_MAX = "sentinel.tpu.autotune.window.batch.max"
    # Closed-form vs scan param-path cost memo: enabled, exploration
    # samples per (shape bucket, path) before committing, and the
    # relative margin a path must win by before the pick switches.
    AUTOTUNE_PARAM_PATH = "sentinel.tpu.autotune.param.path"
    AUTOTUNE_PARAM_EXPLORE = "sentinel.tpu.autotune.param.explore"
    AUTOTUNE_PARAM_MARGIN = "sentinel.tpu.autotune.param.margin"
    # Bounded decision-log ring (the trajectory the bench stage and the
    # `autotune` command report).
    AUTOTUNE_LOG = "sentinel.tpu.autotune.log"
    # Pre-measured closed-vs-scan param-path timings for the cost memo
    # (tools/k2probe.py --seed-out emits the file): when set, the memo
    # starts COMMITTED to the measured winner per shape bucket instead
    # of exploring each path live. Empty (the default) = explore.
    AUTOTUNE_PARAM_SEED_FILE = "sentinel.tpu.autotune.param.seed.file"
    # Sketch-tier cold-key admission ceiling (runtime/sketch.py):
    # estimated QPS above which an UNPROMOTED sketch-tracked resource
    # (unconfigured or over-cap — today's zero-protection classes) is
    # blocked from the host count-min twin's estimate, closing the gap
    # HashPipe-style heavy-hitter promotion leaves open (a key can burn
    # the full promotion budget's worth of traffic while staying just
    # under every promotion threshold). 0 (the default) = today's
    # cold-pass behavior. The twin is host-side, so the ceiling stays
    # enforced while DEGRADED.
    SKETCH_COLD_QPS = "sentinel.tpu.sketch.cold.qps"
    # Multi-process ingest plane (sentinel_tpu/ipc/): N worker
    # processes encode admissions into a shared-memory MPSC request
    # ring and one engine process drains it onto the columnar
    # submit_bulk spine, fanning verdict frames back through per-worker
    # SPSC response rings. Disabled (the default) = the plane is never
    # constructed, no shared memory exists, and the engine pays at most
    # one attribute read on any hot path.
    IPC_ENABLED = "sentinel.tpu.ipc.enabled"
    # Request-ring geometry: slot count (rounded up to a power of two)
    # and fixed payload bytes per slot (one frame per slot; a frame
    # that cannot fit splits at encode time).
    IPC_RING_SLOTS = "sentinel.tpu.ipc.ring.slots"
    IPC_SLOT_BYTES = "sentinel.tpu.ipc.slot.bytes"
    # Per-worker response-ring slot count (same slot.bytes).
    IPC_RESP_SLOTS = "sentinel.tpu.ipc.response.slots"
    # Worker-slot table size in the control header (max workers that
    # can attach to one plane).
    IPC_WORKERS_MAX = "sentinel.tpu.ipc.workers.max"
    # Worker heartbeat bump cadence, and how stale a worker's heartbeat
    # epoch may go before the plane declares it dead and auto-exits its
    # live THREAD admissions (gauges return to exactly 0).
    IPC_HEARTBEAT_MS = "sentinel.tpu.ipc.heartbeat.ms"
    IPC_WORKER_DEAD_MS = "sentinel.tpu.ipc.worker.dead.ms"
    # How stale the ENGINE heartbeat may go before a worker stops
    # waiting and serves verdicts from the fail-open/closed failover
    # policy snapshot published in the control header.
    IPC_ENGINE_DEAD_MS = "sentinel.tpu.ipc.engine.dead.ms"
    # Death-confirmation grace (ipc/worker.py): with dead.confirm.ms
    # > 0, a stale engine wall clock alone does not flip a worker to
    # the policy path — the worker first re-reads the heartbeat epoch,
    # probes the published engine pid (signal 0) and rings the request
    # doorbell; while the process is provably alive the declaration is
    # deferred up to dead.ms + dead.confirm.ms, so sub-second dead.ms
    # on a pegged-but-alive box does not produce false positives.
    # 0 (the default) keeps the PR-15 wall-staleness predicate exactly.
    IPC_ENGINE_DEAD_CONFIRM_MS = "sentinel.tpu.ipc.engine.dead.confirm.ms"
    # Max time a worker blocks on one verdict before consulting the
    # engine-death path above (bounds a wedged-but-heartbeating engine).
    IPC_TIMEOUT_MS = "sentinel.tpu.ipc.timeout.ms"
    # Drainer idle poll floor, microseconds (the plane backs off toward
    # this when the request ring runs empty; "sleep" wakeup mode only).
    IPC_POLL_US = "sentinel.tpu.ipc.poll.us"
    # Worker-side micro-window (ipc/worker.py): concurrent
    # entry/bulk/exit calls on one IngestClient coalesce into ONE
    # columnar frame per bounded window — the client-side twin of the
    # adapter batch window (runtime/window.py) — amortizing ring
    # claims, intern lookups, publishes and wakeups under concurrency.
    # window.ms 0 (the default) keeps per-call framing exactly;
    # window.max caps rows per window (flush-on-size).
    IPC_CLIENT_WINDOW_MS = "sentinel.tpu.ipc.client.window.ms"
    IPC_CLIENT_WINDOW_MAX = "sentinel.tpu.ipc.client.window.max"
    # Ring wakeup strategy for the plane drainer and the worker reader
    # threads: "sleep" (the default — fixed sleep-poll backoff) or
    # "adaptive" (bounded spin for spin.us, then park on a
    # shared-memory doorbell semaphore with an exponentially growing
    # timeout capped at park.ms — cuts the round-trip floor without
    # burning a core when idle; the producer rings the doorbell only
    # when the consumer is parked). spin.us -1 (the default) auto-picks
    # 0 on <=2-core hosts (spinning steals the core the OTHER side of
    # the pipe needs — measured 2x WORSE than pure park on the 1-core
    # box) and 50 on larger hosts where a published frame usually lands
    # within the spin.
    IPC_WAKEUP = "sentinel.tpu.ipc.wakeup"
    IPC_WAKEUP_SPIN_US = "sentinel.tpu.ipc.wakeup.spin.us"
    IPC_WAKEUP_PARK_MS = "sentinel.tpu.ipc.wakeup.park.ms"
    # Worker mode (ipc/worker_mode.py): route this process's api.entry
    # surface — entry/try_entry/entry_async/entry_windowed(_async), and
    # therefore every adapter — through its attached IngestClient
    # instead of a local engine, making a gunicorn-style N-process
    # deployment one line (api.run_workers / tools/ipc_launch.py).
    IPC_WORKER_MODE = "sentinel.tpu.ipc.worker.mode"
    # Engine hot-restart (ipc/supervise.py, PR 15). shm.prefix names
    # the plane's shared-memory segments deterministically
    # ("<prefix>-ctl" / "-req" / "-resp<N>") so a RESTARTED engine
    # process re-attaches to the EXISTING rings instead of creating
    # fresh anonymous ones; "" (the default) keeps the anonymous
    # PR-13/14 segments exactly.
    IPC_SHM_PREFIX = "sentinel.tpu.ipc.shm.prefix"
    # Worker reconnect: when the control header's engine-boot epoch
    # bumps (a new engine attached to the rings), workers re-intern,
    # re-assert their live-admission ledgers and replay completions
    # buffered during the dead window (up to reconnect.exits.max;
    # overflow drops oldest, counted in exits_dropped). Off restores
    # the PR-14 stance: engine death permanently drops undeliverable
    # completions and a returning engine starts with empty ledgers.
    IPC_RECONNECT = "sentinel.tpu.ipc.reconnect.enabled"
    IPC_RECONNECT_EXITS_MAX = "sentinel.tpu.ipc.reconnect.exits.max"
    # Planned live handoff (ipc/plane.py handoff() + supervise.py):
    # how long a worker HOLDS a new admission when the control header
    # publishes HANDOFF (old engine draining) before giving up and
    # serving the failover policy snapshot. The hold also covers the
    # detach->successor-attach gap, so an orderly config-push handoff
    # serves ZERO policy verdicts.
    IPC_HANDOFF_WAIT_MS = "sentinel.tpu.ipc.handoff.wait.ms"
    # Engine supervision (ipc/supervise.py run_engine_supervised /
    # tools/ipc_launch.py --supervise): restart backoff (shared
    # datasource Backoff shape: capped exponential) and a restart
    # budget (0 = unlimited).
    SUPERVISE_BACKOFF_MS = "sentinel.tpu.supervise.backoff.ms"
    SUPERVISE_BACKOFF_MAX_MS = "sentinel.tpu.supervise.backoff.max.ms"
    SUPERVISE_RESTARTS_MAX = "sentinel.tpu.supervise.restarts.max"
    # Warm standby (ipc/supervise.py): pre-fork a SECOND engine child
    # that imports JAX, loads rules, warm-compiles the flush kernels
    # via probe batches and re-warms from the durable checkpoint every
    # warm.interval.ms — parked WITHOUT attaching to the rings. On
    # primary death (or planned handoff) it attaches immediately,
    # cutting the outage from cold-boot seconds to the detection
    # window; the supervisor pre-forks the next standby right after.
    # Off (the default) keeps PR-15 cold-respawn supervision exactly.
    SUPERVISE_STANDBY = "sentinel.tpu.supervise.standby.enabled"
    SUPERVISE_STANDBY_WARM_MS = "sentinel.tpu.supervise.standby.warm.interval.ms"
    # Per-resource provenance metric plane (metrics/provenance.py):
    # (second, resource) speculative/degraded/shed/drift ledger drained
    # into MetricNodeLine v2 columns and the bounded
    # sentinel_resource_* Prometheus export. Enabled by default —
    # disabled costs one bool read per call site.
    RESOURCE_METRICS_ENABLED = "sentinel.tpu.metrics.resource.enabled"
    # Cardinality bound of the ledger: resources past this fold into
    # the __other__ row (the export is additionally bounded by the
    # blocked top-K sketch + configured resources).
    RESOURCE_METRICS_CAP = "sentinel.tpu.metrics.resource.capacity"
    # Batched cluster token plane (cluster/{protocol,client,server}.py).
    # window.ms > 0 turns on the client-side micro-window: concurrent
    # per-op token requests coalesce under the client lock into one
    # FLOW_REQUEST_BATCH frame (flushed after window.ms or at
    # window.max rows, whichever first), xid-multiplexed on the reader
    # so windows pipeline without waiting for earlier responses.
    # window.ms 0 (the default) keeps per-call framing exactly.
    CLUSTER_CLIENT_WINDOW_MS = "sentinel.tpu.cluster.client.window.ms"
    CLUSTER_CLIENT_WINDOW_MAX = "sentinel.tpu.cluster.client.window.max"
    # Local quota leases: with lease.enabled the server may attach a
    # lease (N tokens, valid lease.ttl.ms from receipt) to a batch
    # response for a flow that was hot in that frame (≥ lease.min.batch
    # admitted rows); the grant is lease.frac of the flow's remaining
    # headroom capped at lease.max tokens, debited from the server
    # window UP FRONT (never over-admits globally; unused remainder is
    # forfeited, not credited back). The client then admits that flow
    # locally with zero RPCs until the lease drains or expires, and
    # reports consumption on its next batch frame. Off (the default)
    # grants nothing and the client stance is bit-identical to per-call.
    CLUSTER_LEASE_ENABLED = "sentinel.tpu.cluster.lease.enabled"
    CLUSTER_LEASE_MIN_BATCH = "sentinel.tpu.cluster.lease.min.batch"
    CLUSTER_LEASE_FRAC = "sentinel.tpu.cluster.lease.frac"
    CLUSTER_LEASE_MAX = "sentinel.tpu.cluster.lease.max"
    CLUSTER_LEASE_TTL_MS = "sentinel.tpu.cluster.lease.ttl.ms"
    # Cap on the TOTAL milliseconds one op batch may sleep honoring
    # SHOULD_WAIT verdicts (prioritized occupy-style pacing); overflow
    # is forfeited and the op proceeds. The pre-cap behavior slept
    # per-op back-to-back, unbounded.
    CLUSTER_WAIT_CAP_MS = "sentinel.tpu.cluster.wait.cap.ms"
    # Sharded token plane (cluster/shards.py): shards > 1 partitions
    # token state across N token servers by flow-id hash
    # (shard = crc32(flow_id) % shards). shards.map is the endpoint
    # list, CSV "host:port,host:port,..." with at least `shards`
    # entries; shards.map.version is bumped by the operator on every
    # map edit — clients compare it per batch and rebuild their
    # connections when it moves. shards=1 (the default) keeps the
    # single-server PR-16 client byte-identical.
    CLUSTER_SHARDS = "sentinel.tpu.cluster.shards"
    CLUSTER_SHARDS_MAP = "sentinel.tpu.cluster.shards.map"
    CLUSTER_SHARDS_MAP_VERSION = "sentinel.tpu.cluster.shards.map.version"
    # Sketch gossip (cluster/gossip.py): engines exchange their host
    # count-min twin + candidate tables (SKETCH_PUSH/SKETCH_MERGED) so
    # heavy hitters are detected fleet-wide. enabled arms the host twin
    # and the fleet-view evaluation; port is this engine's gossip
    # listener (0 = ephemeral); peers is CSV "host:port,..." of other
    # engines' listeners; interval.ms > 0 starts a pusher thread (0 =
    # manual rounds only); stale.windows bounds how many decay windows
    # a remote snapshot outlives its last push before it is dropped.
    GOSSIP_ENABLED = "sentinel.tpu.gossip.enabled"
    GOSSIP_PORT = "sentinel.tpu.gossip.port"
    GOSSIP_PEERS = "sentinel.tpu.gossip.peers"
    GOSSIP_INTERVAL_MS = "sentinel.tpu.gossip.interval.ms"
    GOSSIP_STALE_WINDOWS = "sentinel.tpu.gossip.stale.windows"
    # Fleet span journal (metrics/spans.py): per-process bounded ring
    # of wall-clock admission spans (worker join->verdict, engine
    # frame drain, cluster RPC, shard serve) with rolling jsonl spill
    # for tools/fleetdump.py to merge into one Perfetto timeline.
    # Off by default — disabled costs one bool read per call site and
    # verdicts are bit-identical either way.
    SPANS_ENABLED = "sentinel.tpu.spans.enabled"
    # Bounded in-memory ring per process (oldest spans drop first).
    SPANS_RING = "sentinel.tpu.spans.ring"
    # Journal spill directory ("" = the metric log dir). Files are
    # named {app}-spans-{role}-{pid}.jsonl, size-rolled to one .1
    # backup like the metric log.
    SPANS_DIR = "sentinel.tpu.spans.dir"
    # Spill to the journal file automatically once this many spans
    # accumulate since the last spill (0 = only explicit/close spills).
    SPANS_SPILL_EVERY = "sentinel.tpu.spans.spill.every"
    # Black-box flight recorder (runtime/capture.py): bounded rolling
    # on-disk capture of the columnar admission stream in the
    # ipc/frames.py codec, replayable bit-exactly by tools/replay.py.
    # Off by default — the disabled footprint is one attribute read per
    # flush and verdicts are bit-identical either way.
    CAPTURE_ENABLED = "sentinel.tpu.capture.enabled"
    # Segment directory ("" = ./sentinel-capture).
    CAPTURE_DIR = "sentinel.tpu.capture.dir"
    # Rollover size per segment file and the live (rollover-eligible)
    # segment count bound; oldest live segments are deleted past it.
    CAPTURE_SEGMENT_BYTES = "sentinel.tpu.capture.segment.bytes"
    CAPTURE_SEGMENTS_MAX = "sentinel.tpu.capture.segments.max"
    # Postmortem freeze: segments whose last record is younger than
    # freeze.seconds are renamed frozen-* (pinned against rollover) on
    # a breaker opening, a DEGRADED transition, a shed streak of
    # freeze.shed.streak consecutive valve sheds, the `capture`
    # transport command, or (next boot) engine death. frozen.max bounds
    # the pinned set, oldest deleted first.
    CAPTURE_FREEZE_SECONDS = "sentinel.tpu.capture.freeze.seconds"
    CAPTURE_FROZEN_MAX = "sentinel.tpu.capture.frozen.max"
    CAPTURE_SHED_STREAK = "sentinel.tpu.capture.freeze.shed.streak"
    LOG_DIR = "csp.sentinel.log.dir"

    DEFAULTS: Dict[str, str] = {
        APP_TYPE: "0",
        CHARSET: "utf-8",
        SINGLE_METRIC_FILE_SIZE: str(1024 * 1024 * 50),
        TOTAL_METRIC_FILE_COUNT: "6",
        COLD_FACTOR: "3",
        STATISTIC_MAX_RT: "4900",  # reference: SentinelConfig.java DEFAULT_STATISTIC_MAX_RT
        METRIC_FLUSH_INTERVAL: "1",
        FLUSH_INTERVAL_MS: "2",
        FLUSH_MAX_BATCH: "131072",
        FLUSH_MAX_INFLIGHT: "2",
        INITIAL_ROWS: "1024",
        OCCUPY_TIMEOUT_MS: "500",
        HOST_FASTPATH: "true",
        PIPELINE_DEPTH: "0",
        ARENA_MAX_KEYS: "8",
        ARENA_PER_KEY: "4",
        TELEMETRY_ENABLED: "true",
        TELEMETRY_RING: "4096",
        TELEMETRY_SKETCH_K: "8",
        TELEMETRY_SKETCH_CAP: "64",
        # -1 = unset: fall back to the historical telemetry.sketch.*
        # spelling above.
        TELEMETRY_BLOCKED_TOPK_K: "-1",
        TELEMETRY_BLOCKED_TOPK_CAP: "-1",
        TELEMETRY_TOPK_EXPORT: "10",
        SKETCH_ENABLED: "false",
        SKETCH_DEPTH: "4",
        SKETCH_WIDTH: "2048",
        SKETCH_CANDIDATES: "64",
        SKETCH_WINDOW_MS: "1000",
        SKETCH_PROMOTE_QPS: "0",
        SKETCH_RESOURCE_QPS: "0",
        SKETCH_PROMOTE_MAX: "64",
        SKETCH_DEMOTE_WINDOWS: "3",
        SKETCH_NAMES_CAP: "65536",
        SKETCH_COLD_QPS: "0",
        TRACE_ENABLED: "true",
        TRACE_RING: "2048",
        TRACE_SAMPLE_RATE: "0.01",
        TRACE_SAMPLE_BLOCKED: "true",
        TRACE_BULK_CAP: "4",
        FAILOVER_ENABLED: "false",
        FAILOVER_FETCH_TIMEOUT_MS: "5000",
        FAILOVER_POLICY: "open",
        FAILOVER_CHECKPOINT_EVERY: "8",
        FAILOVER_PROBE_FLUSHES: "3",
        FAILOVER_RETRY_MS: "1000",
        FAILOVER_CKPT_PATH: "",
        FAILOVER_CKPT_INTERVAL_MS: "1000",
        FAILOVER_CKPT_STALE_MS: "0",
        SPECULATIVE_ENABLED: "false",
        SPECULATIVE_FLUSH_BATCH: "64",
        SPECULATIVE_OVERADMIT_MAX: "64",
        SPECULATIVE_WINDOW_MS: "1000",
        SPECULATIVE_SHAPING: "true",
        INGEST_MAX_PENDING: "0",
        INGEST_MAX_PENDING_BULK: "0",
        INGEST_DEADLINE_MS: "0",
        INGEST_BATCH_WINDOW_MS: "0",
        INGEST_BATCH_MAX: "256",
        RESOURCE_METRICS_ENABLED: "true",
        RESOURCE_METRICS_CAP: "256",
        AUTOTUNE_ENABLED: "false",
        AUTOTUNE_INTERVAL_MS: "250",
        AUTOTUNE_COOLDOWN_MS: "1000",
        AUTOTUNE_DEPTH_MAX: "4",
        AUTOTUNE_MIN_FLUSHES: "8",
        AUTOTUNE_OCC_HIGH: "0.85",
        AUTOTUNE_OCC_LOW: "0.2",
        AUTOTUNE_IDLE_TICKS: "3",
        AUTOTUNE_RAISE_FRAC: "0.1",
        AUTOTUNE_STALL_FRAC: "2.0",
        AUTOTUNE_WINDOW_MS_MAX: "20",
        AUTOTUNE_WINDOW_BATCH_MAX: "4096",
        AUTOTUNE_PARAM_PATH: "true",
        AUTOTUNE_PARAM_EXPLORE: "3",
        AUTOTUNE_PARAM_MARGIN: "0.15",
        AUTOTUNE_PARAM_SEED_FILE: "",
        AUTOTUNE_LOG: "256",
        IPC_ENABLED: "false",
        IPC_RING_SLOTS: "1024",
        IPC_SLOT_BYTES: "16384",
        IPC_RESP_SLOTS: "1024",
        IPC_WORKERS_MAX: "8",
        IPC_HEARTBEAT_MS: "100",
        IPC_WORKER_DEAD_MS: "1000",
        IPC_ENGINE_DEAD_MS: "1000",
        IPC_ENGINE_DEAD_CONFIRM_MS: "0",
        IPC_TIMEOUT_MS: "5000",
        IPC_POLL_US: "200",
        IPC_CLIENT_WINDOW_MS: "0",
        IPC_CLIENT_WINDOW_MAX: "256",
        IPC_WAKEUP: "sleep",
        IPC_WAKEUP_SPIN_US: "-1",
        IPC_WAKEUP_PARK_MS: "5",
        IPC_WORKER_MODE: "false",
        IPC_SHM_PREFIX: "",
        IPC_RECONNECT: "true",
        IPC_RECONNECT_EXITS_MAX: "4096",
        IPC_HANDOFF_WAIT_MS: "3000",
        SUPERVISE_BACKOFF_MS: "500",
        SUPERVISE_BACKOFF_MAX_MS: "10000",
        SUPERVISE_RESTARTS_MAX: "0",
        SUPERVISE_STANDBY: "false",
        SUPERVISE_STANDBY_WARM_MS: "2000",
        CLUSTER_CLIENT_WINDOW_MS: "0",
        CLUSTER_CLIENT_WINDOW_MAX: "128",
        CLUSTER_LEASE_ENABLED: "false",
        CLUSTER_LEASE_MIN_BATCH: "4",
        CLUSTER_LEASE_FRAC: "0.5",
        CLUSTER_LEASE_MAX: "256",
        CLUSTER_LEASE_TTL_MS: "100",
        CLUSTER_WAIT_CAP_MS: "1000",
        CLUSTER_SHARDS: "1",
        CLUSTER_SHARDS_MAP: "",
        CLUSTER_SHARDS_MAP_VERSION: "0",
        GOSSIP_ENABLED: "false",
        GOSSIP_PORT: "0",
        GOSSIP_PEERS: "",
        GOSSIP_INTERVAL_MS: "0",
        GOSSIP_STALE_WINDOWS: "4",
        SPANS_ENABLED: "false",
        SPANS_RING: "8192",
        SPANS_DIR: "",
        SPANS_SPILL_EVERY: "0",
        CAPTURE_ENABLED: "false",
        CAPTURE_DIR: "",
        CAPTURE_SEGMENT_BYTES: "4194304",
        CAPTURE_SEGMENTS_MAX: "8",
        CAPTURE_FREEZE_SECONDS: "30",
        CAPTURE_FROZEN_MAX: "16",
        CAPTURE_SHED_STREAK: "64",
    }

    def __init__(self, load_env: bool = True, config_file: Optional[str] = None) -> None:
        self._lock = threading.RLock()
        self._runtime: Dict[str, str] = {}
        self._file: Dict[str, str] = {}
        self._env: Dict[str, str] = {}
        if load_env:
            self._load_file(config_file)
            self._load_env()

    def _load_file(self, config_file: Optional[str]) -> None:
        path = (
            config_file
            or os.environ.get("SENTINEL_TPU_CONFIG_FILE")
            or os.environ.get("CSP_SENTINEL_CONFIG_FILE")
            or "sentinel.properties"
        )
        try:
            with open(path, "r", encoding="utf-8") as f:
                self._file = _parse_properties(f.read())
        except OSError:
            self._file = {}

    def _load_env(self) -> None:
        # Accept each key upper-cased with dots as underscores — both the
        # exact form (CSP_SENTINEL_FLOW_COLD_FACTOR, PROJECT_NAME) and a
        # SENTINEL_TPU_-prefixed form for keys not already namespaced.
        for key in list(self.DEFAULTS) + [self.APP_NAME, self.LOG_DIR]:
            env_key = key.replace(".", "_").upper()
            candidates = [env_key]
            if not env_key.startswith(("CSP_", "SENTINEL_TPU_")):
                candidates.append("SENTINEL_TPU_" + env_key)
            for cand in candidates:
                v = os.environ.get(cand)
                if v is not None:
                    self._env[key] = v
                    break

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        with self._lock:
            for layer in (self._runtime, self._env, self._file):
                if key in layer:
                    return layer[key]
        if key in self.DEFAULTS:
            return self.DEFAULTS[key]
        return default

    def set(self, key: str, value: str) -> None:
        with self._lock:
            self._runtime[key] = str(value)

    def set_if_absent(self, key: str, value: str) -> None:
        with self._lock:
            if self.get(key) is None:
                self._runtime[key] = str(value)

    def runtime_snapshot(self, prefix: str = "") -> Dict[str, str]:
        """Copy of the runtime-set keys (``config.set``) under a prefix
        — what a spawned worker process replays so it sees this
        process's runtime config (spawn children start from defaults +
        env, not from the parent's runtime layer)."""
        with self._lock:
            return {
                k: v for k, v in self._runtime.items()
                if k.startswith(prefix)
            }

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        try:
            return int(v) if v is not None else default
        except ValueError:
            return default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        try:
            return float(v) if v is not None else default
        except ValueError:
            return default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        return v.strip().lower() in ("1", "true", "yes", "on")

    # --- commonly used typed views (reference: SentinelConfig.java) ---
    @property
    def app_name(self) -> str:
        return self.get(self.APP_NAME) or "sentinel-tpu-app"

    @property
    def cold_factor(self) -> int:
        # Reference clamps coldFactor <= 1 back to 3 (SentinelConfig#coldFactor).
        v = self.get_int(self.COLD_FACTOR, 3)
        return 3 if v <= 1 else v

    @property
    def statistic_max_rt(self) -> int:
        return self.get_int(self.STATISTIC_MAX_RT, 4900)

    @property
    def occupy_timeout_ms(self) -> int:
        # Clamped to the statistic interval like OccupyTimeoutProperty
        # (a wait beyond one interval can never be satisfied).
        from sentinel_tpu.models import constants as C

        v = self.get_int(self.OCCUPY_TIMEOUT_MS, 500)
        return max(0, min(v, C.DEFAULT_WINDOW_INTERVAL_MS))

    def reset(self) -> None:
        with self._lock:
            self._runtime.clear()


config = SentinelConfig()
