"""String→row interning.

The reference keys everything by resource-name strings and caches wrapper
objects per string (reference: sentinel-core/.../CtSph.java:206-233 chain
map capped at MAX_SLOT_CHAIN_SIZE=6000; context/ContextUtil.java:129-190
capped at MAX_CONTEXT_NAME_SIZE=2000; Constants.java:36-37). On TPU every
named thing must become a **stable integer row id** into the counter
tensors. The interner assigns dense ids, enforces the same capacity-cap
semantics (returning ``None`` above cap → callers degrade to pass-through,
exactly like CtSph returning a no-op chain), and keeps the reverse map
for the command/metric plane.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional, Tuple


class Interner:
    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._to_id: Dict[str, int] = {}
        self._to_name: List[str] = []
        self.capacity = capacity

    def intern(self, name: str) -> Optional[int]:
        """Return the id for ``name``, assigning one if new.

        Returns ``None`` if at capacity — the caller must treat the
        resource as unprotected (pass-through), mirroring
        CtSph.lookProcessChain's null return above the 6000-chain cap.
        """
        with self._lock:
            i = self._to_id.get(name)
            if i is not None:
                return i
            if self.capacity is not None and len(self._to_name) >= self.capacity:
                return None
            i = len(self._to_name)
            self._to_id[name] = i
            self._to_name.append(name)
            return i

    def lookup(self, name: str) -> Optional[int]:
        with self._lock:
            return self._to_id.get(name)

    def name_of(self, i: int) -> str:
        with self._lock:
            return self._to_name[i]

    def __len__(self) -> int:
        with self._lock:
            return len(self._to_name)

    def __contains__(self, name: str) -> bool:
        with self._lock:
            return name in self._to_id

    def items(self) -> Iterator[Tuple[str, int]]:
        with self._lock:
            snapshot = list(self._to_id.items())
        return iter(snapshot)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._to_name)

    def clear(self) -> None:
        with self._lock:
            self._to_id.clear()
            self._to_name.clear()


class PairInterner:
    """Interns (a_id, b_id) pairs — e.g. (resource, context) for
    per-context DefaultNode rows or (resource, origin) for origin nodes
    (reference: NodeSelectorSlot.java:127-186, ClusterBuilderSlot.java:49).
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._lock = threading.Lock()
        self._to_id: Dict[Tuple[int, int], int] = {}
        self._pairs: List[Tuple[int, int]] = []
        self.capacity = capacity

    def intern(self, a: int, b: int) -> Optional[int]:
        key = (a, b)
        with self._lock:
            i = self._to_id.get(key)
            if i is not None:
                return i
            if self.capacity is not None and len(self._pairs) >= self.capacity:
                return None
            i = len(self._pairs)
            self._to_id[key] = i
            self._pairs.append(key)
            return i

    def lookup(self, a: int, b: int) -> Optional[int]:
        with self._lock:
            return self._to_id.get((a, b))

    def pair_of(self, i: int) -> Tuple[int, int]:
        with self._lock:
            return self._pairs[i]

    def items(self) -> Iterator[Tuple[Tuple[int, int], int]]:
        with self._lock:
            snapshot = list(self._to_id.items())
        return iter(snapshot)

    def __len__(self) -> int:
        with self._lock:
            return len(self._pairs)

    def clear(self) -> None:
        with self._lock:
            self._to_id.clear()
            self._pairs.clear()
