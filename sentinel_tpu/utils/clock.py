"""Explicit clock abstraction.

The reference caches ``System.currentTimeMillis()`` on a daemon thread to
avoid syscall storms under high request concurrency (reference:
sentinel-core/.../util/TimeUtil.java:42-113). The TPU build is
batch-driven, so there is no syscall storm to dodge — but the clock still
has to be an *explicit input* to every kernel, because all sliding-window
semantics are functions of ``(counters, rule, now)``. Making time a value
rather than ambient state is also what made the reference's fake-clock
test fixture necessary (reference: sentinel-core/src/test/.../test/
AbstractTimeBasedTest.java:36-60, which PowerMock-mocks the static
clock); here the equivalent fixture is just ``ManualClock``.

Device timestamps are **int32 milliseconds relative to the clock's
epoch** (int64 arithmetic is disabled by default under JAX and slow on
TPU). int32 ms covers ~24.8 days from the epoch; long-running processes
re-base the epoch during idle flushes (see
:meth:`SystemClock.rebase_headroom_ms`).
"""

from __future__ import annotations

import threading
import time


class Clock:
    """Millisecond clock with an explicit epoch.

    ``now_ms()`` is the device-facing time: int milliseconds since
    ``epoch_wall_ms``. ``wall_ms()`` is wall time (Unix ms) for logs and
    the transport plane.
    """

    def now_ms(self) -> int:
        raise NotImplementedError

    def wall_ms(self) -> int:
        raise NotImplementedError

    def sleep_ms(self, ms: int) -> None:
        raise NotImplementedError

    @property
    def epoch_wall_ms(self) -> int:
        raise NotImplementedError

    def to_wall(self, rel_ms: int) -> int:
        return self.epoch_wall_ms + rel_ms

    def from_wall(self, wall_ms: int) -> int:
        return wall_ms - self.epoch_wall_ms


class SystemClock(Clock):
    """Real clock; epoch anchored at construction time."""

    INT32_MAX = 2**31 - 1

    def __init__(self) -> None:
        self._epoch_wall_ms = int(time.time() * 1000)
        self._mono_base_ns = time.monotonic_ns()

    @property
    def epoch_wall_ms(self) -> int:
        return self._epoch_wall_ms

    def now_ms(self) -> int:
        return (time.monotonic_ns() - self._mono_base_ns) // 1_000_000

    def wall_ms(self) -> int:
        return self._epoch_wall_ms + self.now_ms()

    def sleep_ms(self, ms: int) -> None:
        if ms > 0:
            time.sleep(ms / 1000.0)

    def rebase_headroom_ms(self) -> int:
        """How far from int32 overflow the relative clock is."""
        return self.INT32_MAX - self.now_ms()

    # Rebase offsets must preserve every window grid: bucket index is
    # (ts // window_len) % n, so a shift must be ≡ 0 modulo
    # lcm(second-window 500ms grid over 2 buckets, minute-window 1000ms
    # grid over 60 buckets, breaker 1000ms window) = 60_000 ms.
    # An unaligned shift silently remaps/resets every live bucket.
    # (Per-rule breaker windows may use any statIntervalMs; those are
    # floor-realigned to their own grid in Engine._apply_rebase.)
    REBASE_GRANULARITY_MS = 60_000

    def rebase(self) -> int:
        """Re-anchor the epoch (aligned down to REBASE_GRANULARITY_MS);
        returns the shift applied.

        Callers (the engine, during an idle flush) must shift any stored
        relative timestamps by the returned offset.
        """
        offset = self.now_ms()
        offset -= offset % self.REBASE_GRANULARITY_MS
        if offset <= 0:
            return 0
        self._epoch_wall_ms += offset
        self._mono_base_ns += offset * 1_000_000
        return offset


class ManualClock(Clock):
    """Deterministic clock for tests.

    Replaces the reference's PowerMock fixture
    (AbstractTimeBasedTest.setCurrentMillis / sleep): tests advance time
    explicitly and every windowed/QPS/breaker assertion becomes
    deterministic.
    """

    def __init__(self, start_ms: int = 0, epoch_wall_ms: int = 1_700_000_000_000) -> None:
        self._now = start_ms
        self._epoch = epoch_wall_ms
        self._lock = threading.Lock()

    @property
    def epoch_wall_ms(self) -> int:
        return self._epoch

    def now_ms(self) -> int:
        return self._now

    def wall_ms(self) -> int:
        return self._epoch + self._now

    def set_ms(self, ms: int) -> None:
        with self._lock:
            self._now = ms

    def advance(self, ms: int) -> None:
        with self._lock:
            self._now += ms

    # In tests "sleeping" is advancing the virtual clock.
    def sleep_ms(self, ms: int) -> None:
        self.advance(ms)


_default_clock: Clock = SystemClock()
_default_lock = threading.Lock()


def default_clock() -> Clock:
    return _default_clock


def set_default_clock(clock: Clock) -> Clock:
    """Swap the process-default clock (tests); returns the previous one."""
    global _default_clock
    with _default_lock:
        prev = _default_clock
        _default_clock = clock
        return prev
