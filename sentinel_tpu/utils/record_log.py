"""Internal record log.

Equivalent of the reference's RecordLog (reference: sentinel-core/.../log/
RecordLog.java) with the SLF4J bridge role played by the stdlib
``logging`` module (reference: sentinel-logging/sentinel-logging-slf4j —
the Logger SPI there maps to handlers here). Files land under
``$SENTINEL_TPU_LOG_DIR`` or ``~/logs/csp/`` like the reference's
``${user.home}/logs/csp``.
"""

from __future__ import annotations

import logging
import os
import threading
from logging.handlers import RotatingFileHandler

_lock = threading.Lock()
_configured = False


def _log_dir() -> str:
    from sentinel_tpu.utils.config import config

    d = config.get(config.LOG_DIR) or os.environ.get("SENTINEL_TPU_LOG_DIR")
    if not d:
        d = os.path.join(os.path.expanduser("~"), "logs", "csp")
    return d


def _configure() -> logging.Logger:
    global _configured
    logger = logging.getLogger("sentinel_tpu.record")
    with _lock:
        if _configured:
            return logger
        logger.setLevel(logging.INFO)
        logger.propagate = False
        try:
            d = _log_dir()
            os.makedirs(d, exist_ok=True)
            handler: logging.Handler = RotatingFileHandler(
                os.path.join(d, "sentinel-tpu-record.log"),
                maxBytes=50 * 1024 * 1024,
                backupCount=3,
                encoding="utf-8",
            )
        except OSError:
            handler = logging.NullHandler()
        handler.setFormatter(logging.Formatter("%(asctime)s %(levelname)s %(message)s"))
        logger.addHandler(handler)
        _configured = True
    return logger


class _RecordLog:
    """API shape of RecordLog.info/warn/error(fmt, *args)."""

    @property
    def _logger(self) -> logging.Logger:
        return _configure()

    def info(self, msg: str, *args: object) -> None:
        self._logger.info(msg, *args)

    def warn(self, msg: str, *args: object) -> None:
        self._logger.warning(msg, *args)

    def error(self, msg: str, *args: object, exc_info: bool = False) -> None:
        self._logger.error(msg, *args, exc_info=exc_info)

    def debug(self, msg: str, *args: object) -> None:
        self._logger.debug(msg, *args)


record_log = _RecordLog()
