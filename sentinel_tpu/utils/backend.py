"""Backend/platform selection helpers.

The environment's site hook may pre-register an accelerator plugin and
pin ``jax_platforms`` before env vars are read, and ``jax.devices()``
(or any compile) commits the backend irrevocably — after that,
``jax.config.update("jax_platforms", ...)`` is a no-op. Every caller
that wants the virtual-CPU mesh must therefore (1) set the env vars,
(2) import jax, (3) set the config explicitly, all BEFORE the first
backend touch. This helper is the single copy of that dance (used by
tests/conftest.py, __graft_entry__.dryrun_multichip and bench.py).
"""

from __future__ import annotations

import os
import re

_COUNT_FLAG = "--xla_force_host_platform_device_count"


def force_cpu(n_devices: int | None = None) -> None:
    """Pin JAX to the host-CPU platform, optionally as ``n_devices``
    virtual devices. Must run before the first backend use; safe to call
    whether or not jax is already imported."""
    flags = os.environ.get("XLA_FLAGS", "")
    if n_devices is not None:
        m = re.search(rf"{_COUNT_FLAG}=(\d+)", flags)
        if m is None:
            flags = (flags + f" {_COUNT_FLAG}={n_devices}").strip()
        elif int(m.group(1)) < n_devices:
            # Only widen — an externally-requested larger mesh stands.
            flags = re.sub(rf"{_COUNT_FLAG}=\d+", f"{_COUNT_FLAG}={n_devices}", flags)
        os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already committed; caller checks device count
