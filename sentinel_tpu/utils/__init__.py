"""Runtime substrate: clock, config, plugin registry, interning, record log.

Equivalent layer to the reference's CORE/util, CORE/config, CORE/spi,
CORE/log packages (reference: sentinel-core/.../util/TimeUtil.java:42,
config/SentinelConfig.java:54, spi/SpiLoader.java:73, log/RecordLog.java).
"""

from sentinel_tpu.utils.clock import Clock, SystemClock, ManualClock, default_clock
from sentinel_tpu.utils.config import SentinelConfig, config
from sentinel_tpu.utils.registry import Registry, provider
from sentinel_tpu.utils.interner import Interner
from sentinel_tpu.utils.record_log import record_log

__all__ = [
    "Clock",
    "SystemClock",
    "ManualClock",
    "default_clock",
    "SentinelConfig",
    "config",
    "Registry",
    "provider",
    "Interner",
    "record_log",
]
