"""System load / CPU sampler.

Equivalent of the reference's SystemStatusListener (reference:
slots/system/SystemStatusListener.java:31-60), which polls
OperatingSystemMXBean once a second for the 1-minute load average and
CPU usage (max of system and process CPU). Here: ``os.getloadavg`` and
/proc/stat deltas (plus process CPU via ``os.times``), sampled by a
daemon thread started lazily when system rules first need it.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional, Tuple


def _read_proc_stat() -> Optional[Tuple[int, int]]:
    """(busy, total) jiffies from /proc/stat, or None off-Linux."""
    try:
        with open("/proc/stat", "r") as f:
            line = f.readline()
        parts = [int(x) for x in line.split()[1:]]
        idle = parts[3] + (parts[4] if len(parts) > 4 else 0)
        total = sum(parts)
        return total - idle, total
    except (OSError, ValueError, IndexError):
        return None


class SystemStatusSampler:
    def __init__(self, interval_sec: float = 1.0) -> None:
        self.interval = interval_sec
        self._load = -1.0
        self._cpu = -1.0
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._prev_stat: Optional[Tuple[int, int]] = None
        self._prev_proc: Optional[Tuple[float, float]] = None
        self._stop = threading.Event()
        self._forced = False

    def start(self) -> None:
        with self._lock:
            if self._thread is not None:
                return
            self._thread = threading.Thread(
                target=self._run, name="sentinel-system-status", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            self._sample()
            self._stop.wait(self.interval)

    def _sample(self) -> None:
        try:
            load = os.getloadavg()[0]
        except (OSError, AttributeError):
            load = -1.0
        sys_cpu = -1.0
        cur = _read_proc_stat()
        if cur is not None and self._prev_stat is not None:
            db = cur[0] - self._prev_stat[0]
            dt = cur[1] - self._prev_stat[1]
            if dt > 0:
                sys_cpu = db / dt
        self._prev_stat = cur
        # Process CPU (the reference takes max(process, system)).
        t = os.times()
        now = time.monotonic()
        proc_cpu = -1.0
        if self._prev_proc is not None:
            dcpu = (t.user + t.system) - self._prev_proc[0]
            dwall = now - self._prev_proc[1]
            ncpu = os.cpu_count() or 1
            if dwall > 0:
                proc_cpu = dcpu / dwall / ncpu
        self._prev_proc = (t.user + t.system, now)
        with self._lock:
            if self._forced:
                return
            self._load = load
            self._cpu = max(sys_cpu, proc_cpu)

    @property
    def load(self) -> float:
        with self._lock:
            return self._load

    @property
    def cpu(self) -> float:
        with self._lock:
            return self._cpu

    def read(self) -> Tuple[float, float]:
        """Atomic ``(load, cpu)`` pair under ONE lock acquisition —
        the kernel's SystemDevice build and the host system gate
        (runtime/failover.py) both consume the pair; two separate
        property reads could tear across a sample and gate the two
        planes on different instants."""
        with self._lock:
            return self._load, self._cpu

    # Test hook: force values (the reference's tests mock the MXBean).
    def force(self, load: float, cpu: float) -> None:
        with self._lock:
            self._forced = True
            self._load = load
            self._cpu = cpu
        self._stop.set()

    def unforce(self) -> None:
        with self._lock:
            self._forced = False


sampler = SystemStatusSampler()
