"""Small numeric helpers shared across the runtime."""

from __future__ import annotations


def pad_pow2(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= max(n, minimum).

    Batch and table sizes are padded to powers of two so the jitted
    flush kernel sees a bounded set of shapes (each new shape is a
    compile).
    """
    p = max(1, minimum)
    while p < n:
        p <<= 1
    return p
