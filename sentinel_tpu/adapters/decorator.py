"""The @sentinel_resource decorator.

Reference: sentinel-annotation-aspectj's @SentinelResource +
SentinelResourceAspect (SentinelResourceAspect.java:36-83,
AbstractSentinelAspectSupport.java:83): wrap the function in
entry/exit; on BlockError dispatch to ``block_handler``; on business
exceptions dispatch to ``fallback`` (or ``default_fallback``) and trace
the exception; otherwise re-raise. Handlers receive the original
arguments plus the exception as a trailing argument, like the
reference's handler signature convention.
"""

from __future__ import annotations

import functools
import inspect
from typing import Callable, Optional, Sequence, Tuple

from sentinel_tpu.core import api
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.models import constants as C


def sentinel_resource(
    resource: Optional[str] = None,
    *,
    entry_type: C.EntryType = C.EntryType.OUT,
    resource_type: int = 0,
    block_handler: Optional[Callable] = None,
    fallback: Optional[Callable] = None,
    default_fallback: Optional[Callable] = None,
    exceptions_to_ignore: Tuple[type, ...] = (),
    param_args: bool = False,
    traceparent_extractor: Optional[Callable] = None,
):
    """Decorate a callable as a protected resource.

    ``param_args=True`` forwards the call's positional arguments to
    hot-parameter rules (SphU.entry(..., args)).

    ``traceparent_extractor(*args, **kwargs)`` — when given, called
    with the invocation arguments and expected to return the inbound
    W3C ``traceparent`` header string (or None): the decorator's
    inbound parse for message-consumer / task-queue shapes where the
    carrier is an argument (a message envelope, a job payload) rather
    than an HTTP request. The parsed context is ambient for the whole
    call, so the admission record and any guarded outbound hop carry
    the producer's trace id.
    """

    def deco(fn: Callable) -> Callable:
        name = resource or f"{fn.__module__}:{fn.__qualname__}"

        def trace_token(args, kwargs):
            """set_trace token for this call, or None when no
            extractor is configured (zero ambient writes then)."""
            if traceparent_extractor is None:
                return None
            from sentinel_tpu.core.context import ContextUtil
            from sentinel_tpu.metrics.admission_trace import parse_traceparent

            try:
                header = traceparent_extractor(*args, **kwargs)
            except Exception:
                header = None  # a broken extractor must not fail the call
            return ContextUtil.set_trace(parse_traceparent(header))

        def trace_reset(token):
            if token is not None:
                from sentinel_tpu.core.context import ContextUtil

                ContextUtil.reset_trace(token)

        def handle_block(e: BlockError, args, kwargs):
            if block_handler is not None:
                return block_handler(*args, **kwargs, error=e) if _wants_kw(
                    block_handler, "error"
                ) else block_handler(*args, e, **kwargs)
            raise e

        def handle_fallback(e: BaseException, args, kwargs):
            handler = fallback or default_fallback
            if handler is not None and not isinstance(e, exceptions_to_ignore):
                return handler(*args, **kwargs, error=e) if _wants_kw(
                    handler, "error"
                ) else handler(*args, e, **kwargs)
            raise e

        if inspect.iscoroutinefunction(fn):

            @functools.wraps(fn)
            async def async_wrapper(*args, **kwargs):
                token = trace_token(args, kwargs)
                try:
                    try:
                        entry = api.entry(
                            name,
                            entry_type=entry_type,
                            args=args if param_args else (),
                        )
                    except BlockError as e:
                        return handle_block(e, args, kwargs)
                    try:
                        result = await fn(*args, **kwargs)
                    except BlockError:
                        # A nested guarded call blocked: pass it through
                        # untraced, but the OUTER entry still completes
                        # (a leaked entry pins its thread slot forever).
                        entry.exit()
                        raise
                    except BaseException as e:
                        # Per-decorator ignores gate here (the annotation
                        # check, AbstractSentinelAspectSupport.java:44-53);
                        # the global Tracer filters apply inside set_error.
                        if not isinstance(e, exceptions_to_ignore):
                            entry.set_error(e)
                        entry.exit()
                        return handle_fallback(e, args, kwargs)
                    entry.exit()
                    return result
                finally:
                    trace_reset(token)

            return async_wrapper

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            token = trace_token(args, kwargs)
            try:
                try:
                    entry = api.entry(
                        name,
                        entry_type=entry_type,
                        args=args if param_args else (),
                    )
                except BlockError as e:
                    return handle_block(e, args, kwargs)
                try:
                    result = fn(*args, **kwargs)
                except BlockError:
                    # See async_wrapper: the outer entry must exit even
                    # when a nested guarded call blocked.
                    entry.exit()
                    raise
                except BaseException as e:
                    if not isinstance(e, exceptions_to_ignore):
                        entry.set_error(e)
                    entry.exit()
                    return handle_fallback(e, args, kwargs)
                entry.exit()
                return result
            finally:
                trace_reset(token)

        return wrapper

    return deco


def _wants_kw(fn: Callable, kw: str) -> bool:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    p = sig.parameters.get(kw)
    return p is not None and p.kind in (p.KEYWORD_ONLY, p.POSITIONAL_OR_KEYWORD)
