"""First-class `requests` integration.

Reference analog: sentinel-okhttp-adapter's SentinelOkHttpInterceptor
(okhttp/SentinelOkHttpInterceptor.java:35-60) — an interceptor mounted
on the client so EVERY outbound call is guarded transparently, with a
configurable resource extractor and fallback. The Python-native mount
point is a ``requests`` transport adapter::

    import requests
    from sentinel_tpu.adapters.requests_adapter import SentinelHTTPAdapter

    s = requests.Session()
    s.mount("http://", SentinelHTTPAdapter())
    s.mount("https://", SentinelHTTPAdapter())
    s.get("http://api.internal/users")   # guarded: OUT entry per call

Blocked calls raise :class:`~sentinel_tpu.core.errors.BlockError` by
default, or return ``block_response_factory(request, error)`` when
given (the okhttp adapter's SentinelOkHttpConfig fallback).
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import inject_trace_headers
from sentinel_tpu.models import constants as C

try:  # gated: requests is an optional dependency
    from requests.adapters import HTTPAdapter as _HTTPAdapter
except ImportError:  # pragma: no cover - exercised only without requests
    _HTTPAdapter = object


def default_resource_extractor(request) -> str:
    """``METHOD:scheme://host/path`` — the okhttp adapter's default
    (method + URL, query string dropped so resources stay bounded)."""
    url = request.url or ""
    return f"{request.method}:{url.split('?', 1)[0]}"


class SentinelHTTPAdapter(_HTTPAdapter):
    """A ``requests`` transport adapter guarding every ``send``.

    Parameters mirror the reference interceptor config: a resource
    extractor (request → resource name), an optional origin, and an
    optional factory producing a synthetic ``Response`` for blocked
    calls instead of raising.
    """

    def __init__(
        self,
        resource_extractor: Callable = default_resource_extractor,
        origin: str = "",
        block_response_factory: Optional[Callable] = None,
        **kwargs,
    ) -> None:
        if _HTTPAdapter is object:  # pragma: no cover
            raise ImportError("requests is not installed")
        super().__init__(**kwargs)
        self._extract = resource_extractor
        self._origin = origin
        self._block_response_factory = block_response_factory

    def send(self, request, **kwargs):
        resource = self._extract(request)
        try:
            entry = api.entry(
                resource, entry_type=C.EntryType.OUT, origin=self._origin
            )
        except BlockError as e:
            if self._block_response_factory is not None:
                return self._block_response_factory(request, e)
            raise
        # Outbound W3C propagation: the ambient trace (set by whichever
        # inbound adapter admitted this request) crosses the hop as a
        # child span, so a downstream block stays attributable to the
        # original caller. No ambient trace -> headers untouched.
        inject_trace_headers(request.headers)
        try:
            resp = super().send(request, **kwargs)
        except BaseException as e:
            entry.set_error(e)
            raise
        finally:
            entry.exit()
        return resp
