"""ASGI middleware (async web frameworks: Starlette/FastAPI/Quart...).

Reference: sentinel-spring-webflux-adapter / sentinel-reactor-adapter —
the reactive pipeline wraps each exchange in an entry and maps blocks
to a 429 response.

Admissions ride the columnar ingest spine: with the adapter-edge batch
window armed (``sentinel.tpu.ingest.batch.window.ms`` > 0) concurrent
exchanges coalesce into one columnar ``submit_bulk`` flush — awaited,
so the event loop stays free while the window assembles — with
per-request verdict fan-out; window off is exactly the per-request
path. In ipc worker mode (``sentinel.tpu.ipc.worker.mode``) the same
awaits ride the process's IngestClient to the engine process (in the
loop's default executor), middleware unchanged.
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import parse_traceparent
from sentinel_tpu.models import constants as C

DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"
WEB_CONTEXT_NAME = "sentinel_web_context"


def _scope_trace(scope):
    """Inbound W3C trace context from the ASGI header list (keys are
    lower-cased bytes per the ASGI spec)."""
    tp, ts = None, ""
    for k, v in scope.get("headers") or ():
        if k == b"traceparent":
            tp = v.decode("latin-1")
        elif k == b"tracestate":
            ts = v.decode("latin-1")
    return parse_traceparent(tp, ts)


class SentinelASGIMiddleware:
    def __init__(
        self,
        app,
        *,
        resource_extractor: Optional[Callable[[dict], str]] = None,
        origin_parser: Optional[Callable[[dict], str]] = None,
        total_resource: Optional[str] = "web-total",
    ) -> None:
        self.app = app
        self.resource_extractor = resource_extractor or (
            lambda scope: f"{scope.get('method', 'GET')}:{scope.get('path', '/')}"
        )
        self.origin_parser = origin_parser or (lambda scope: "")
        self.total_resource = total_resource

    async def __call__(self, scope, receive, send):
        if scope.get("type") != "http":
            await self.app(scope, receive, send)
            return
        resource = self.resource_extractor(scope)
        origin = self.origin_parser(scope)
        # Inbound trace identity rides the context into every entry's
        # admission record and out through guarded downstream clients.
        trace_token = ContextUtil.set_trace(_scope_trace(scope))
        ctx = ContextUtil.enter(WEB_CONTEXT_NAME, origin)
        entries = []
        try:
            try:
                if self.total_resource:
                    entries.append(
                        await api.entry_windowed_async(
                            self.total_resource, entry_type=C.EntryType.IN,
                            detached=False,
                        )
                    )
                entries.append(
                    await api.entry_windowed_async(
                        resource, entry_type=C.EntryType.IN, detached=False
                    )
                )
            except BlockError:
                await send(
                    {
                        "type": "http.response.start",
                        "status": 429,
                        "headers": [(b"content-type", b"text/plain")],
                    }
                )
                await send({"type": "http.response.body", "body": DEFAULT_BLOCK_BODY})
                return
            try:
                await self.app(scope, receive, send)
            except BaseException as e:
                for en in entries:
                    en.set_error(e)
                raise
        finally:
            for en in reversed(entries):
                en.exit()
            ContextUtil.exit()
            ContextUtil.reset_trace(trace_token)
