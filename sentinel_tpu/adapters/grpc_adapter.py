"""gRPC interceptors.

Reference: sentinel-grpc-adapter's SentinelGrpcServerInterceptor /
SentinelGrpcClientInterceptor. Gated on grpcio being installed (it is
not a framework dependency).

W3C trace context rides gRPC metadata under the standard lowercase
``traceparent`` / ``tracestate`` keys (the gRPC transport for W3C
trace-context): the server interceptor parses them so the admission —
and any guarded outbound call the handler makes — carries the caller's
trace identity; the client interceptor injects a child span outbound.
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import (
    TRACEPARENT_HEADER,
    TRACESTATE_HEADER,
    inject_trace_headers,
    parse_traceparent,
)
from sentinel_tpu.models import constants as C

try:  # pragma: no cover - exercised only when grpcio is present
    import grpc
except ImportError:  # pragma: no cover
    grpc = None


def _require_grpc():
    if grpc is None:
        raise ImportError("grpcio is not installed; gRPC adapters unavailable")


def trace_from_metadata(metadata) -> Optional[object]:
    """Inbound W3C trace context from a gRPC metadata sequence of
    (key, value) pairs (keys are lowercase on the wire). Shared by the
    server interceptor and directly testable without grpcio."""
    tp, ts = None, ""
    for k, v in metadata or ():
        if k == TRACEPARENT_HEADER:
            tp = v if isinstance(v, str) else v.decode("latin-1")
        elif k == TRACESTATE_HEADER:
            ts = v if isinstance(v, str) else v.decode("latin-1")
    return parse_traceparent(tp, ts)


def metadata_with_trace(metadata) -> list:
    """Outbound injection: the given metadata (or ()) plus a child
    ``traceparent``/``tracestate`` of the ambient trace; unchanged
    when no trace is ambient. Shared by the client interceptor and
    directly testable without grpcio."""
    md = list(metadata or ())
    hdrs: dict = {}
    if inject_trace_headers(hdrs) is not None:
        md.extend(hdrs.items())
    return md


if grpc is not None:

    class SentinelServerInterceptor(grpc.ServerInterceptor):  # pragma: no cover
        """Every inbound RPC enters an IN resource named by the method."""

        def intercept_service(self, continuation, handler_call_details):
            resource = handler_call_details.method
            tc = trace_from_metadata(
                getattr(handler_call_details, "invocation_metadata", ())
            )
            token = ContextUtil.set_trace(tc)
            try:
                # Windowed columnar admission (runtime/window.py) when
                # armed (gRPC worker threads coalesce); per-request
                # entry otherwise.
                entry = api.entry_windowed(
                    resource, entry_type=C.EntryType.IN
                )
            except BlockError:
                def abort(request, context):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, "Blocked by Sentinel"
                    )

                return grpc.unary_unary_rpc_method_handler(abort)
            finally:
                ContextUtil.reset_trace(token)
            handler = continuation(handler_call_details)
            if handler is None or not handler.unary_unary:
                entry.exit()
                return handler

            inner = handler.unary_unary

            def wrapped(request, context):
                # The handler may run on another thread: re-establish
                # the caller's trace identity around it so guarded
                # outbound calls propagate it.
                tok = ContextUtil.set_trace(tc)
                try:
                    return inner(request, context)
                except BaseException as e:
                    entry.set_error(e)
                    raise
                finally:
                    entry.exit()
                    ContextUtil.reset_trace(tok)

            return grpc.unary_unary_rpc_method_handler(
                wrapped,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )

    class _TracedClientCallDetails(
        grpc.ClientCallDetails
    ):  # pragma: no cover
        """ClientCallDetails copy with replaced metadata (the grpc API
        gives no mutation surface)."""

        def __init__(self, base, metadata) -> None:
            self.method = base.method
            self.timeout = getattr(base, "timeout", None)
            self.metadata = metadata
            self.credentials = getattr(base, "credentials", None)
            self.wait_for_ready = getattr(base, "wait_for_ready", None)
            self.compression = getattr(base, "compression", None)

    class SentinelClientInterceptor(
        grpc.UnaryUnaryClientInterceptor
    ):  # pragma: no cover
        """Outbound RPCs enter an OUT resource; blocks raise before the
        wire; the ambient trace is injected as a child span."""

        def intercept_unary_unary(self, continuation, client_call_details, request):
            resource = client_call_details.method
            entry = api.entry(resource, entry_type=C.EntryType.OUT)
            details = _TracedClientCallDetails(
                client_call_details,
                metadata_with_trace(
                    getattr(client_call_details, "metadata", None)
                ),
            )
            try:
                result = continuation(details, request)
                return result
            except BaseException as e:
                entry.set_error(e)
                raise
            finally:
                entry.exit()

else:  # keep the names importable for documentation/tests

    class SentinelServerInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            _require_grpc()

    class SentinelClientInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            _require_grpc()
