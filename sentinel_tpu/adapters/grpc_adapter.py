"""gRPC interceptors.

Reference: sentinel-grpc-adapter's SentinelGrpcServerInterceptor /
SentinelGrpcClientInterceptor. Gated on grpcio being installed (it is
not a framework dependency).
"""

from __future__ import annotations

from typing import Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.models import constants as C

try:  # pragma: no cover - exercised only when grpcio is present
    import grpc
except ImportError:  # pragma: no cover
    grpc = None


def _require_grpc():
    if grpc is None:
        raise ImportError("grpcio is not installed; gRPC adapters unavailable")


if grpc is not None:

    class SentinelServerInterceptor(grpc.ServerInterceptor):  # pragma: no cover
        """Every inbound RPC enters an IN resource named by the method."""

        def intercept_service(self, continuation, handler_call_details):
            resource = handler_call_details.method
            try:
                entry = api.entry(resource, entry_type=C.EntryType.IN)
            except BlockError:
                def abort(request, context):
                    context.abort(
                        grpc.StatusCode.RESOURCE_EXHAUSTED, "Blocked by Sentinel"
                    )

                return grpc.unary_unary_rpc_method_handler(abort)
            handler = continuation(handler_call_details)
            if handler is None or not handler.unary_unary:
                entry.exit()
                return handler

            inner = handler.unary_unary

            def wrapped(request, context):
                try:
                    return inner(request, context)
                except BaseException as e:
                    entry.set_error(e)
                    raise
                finally:
                    entry.exit()

            return grpc.unary_unary_rpc_method_handler(
                wrapped,
                request_deserializer=handler.request_deserializer,
                response_serializer=handler.response_serializer,
            )

    class SentinelClientInterceptor(
        grpc.UnaryUnaryClientInterceptor
    ):  # pragma: no cover
        """Outbound RPCs enter an OUT resource; blocks raise before the wire."""

        def intercept_unary_unary(self, continuation, client_call_details, request):
            resource = client_call_details.method
            entry = api.entry(resource, entry_type=C.EntryType.OUT)
            try:
                result = continuation(client_call_details, request)
                return result
            except BaseException as e:
                entry.set_error(e)
                raise
            finally:
                entry.exit()

else:  # keep the names importable for documentation/tests

    class SentinelServerInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            _require_grpc()

    class SentinelClientInterceptor:  # type: ignore[no-redef]
        def __init__(self, *a, **k):
            _require_grpc()
