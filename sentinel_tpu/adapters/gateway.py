"""API-gateway flow control.

Reference: sentinel-api-gateway-adapter-common — GatewayFlowRule
(per-route / per-custom-API rules with parameter matching on client IP /
host / header / URL param / cookie, exact-prefix-regex matchers),
converted to hot-param rules by GatewayRuleConverter, params extracted
by GatewayParamParser, checked by GatewayFlowSlot, plus ApiDefinition
route groups (reference: .../gateway/common/rule/GatewayRuleManager.java:39,
slot/GatewayFlowSlot.java:37, param/GatewayParamParser.java,
api/ApiDefinition.java).

Usage::

    gateway_rule_manager.load_rules([
        GatewayFlowRule("my_route", count=10,
                        param_item=GatewayParamFlowItem(parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP)),
    ])
    info = GatewayRequestInfo(path="/api/x", client_ip="1.2.3.4", ...)
    with gateway_entry("my_route", info):
        ...
"""

from __future__ import annotations

import re
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.core import api
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ParamFlowRule

# Resource modes (SentinelGatewayConstants).
RESOURCE_MODE_ROUTE_ID = 0
RESOURCE_MODE_CUSTOM_API_NAME = 1

# Param parse strategies.
PARAM_PARSE_STRATEGY_CLIENT_IP = 0
PARAM_PARSE_STRATEGY_HOST = 1
PARAM_PARSE_STRATEGY_HEADER = 2
PARAM_PARSE_STRATEGY_URL_PARAM = 3
PARAM_PARSE_STRATEGY_COOKIE = 4

# String match strategies.
PARAM_MATCH_STRATEGY_EXACT = 0
PARAM_MATCH_STRATEGY_PREFIX = 1
PARAM_MATCH_STRATEGY_REGEX = 2

# URL match strategies for ApiDefinition predicates.
URL_MATCH_STRATEGY_EXACT = 0
URL_MATCH_STRATEGY_PREFIX = 1
URL_MATCH_STRATEGY_REGEX = 2

# The constant param value used when a rule has no param item
# (SentinelGatewayConstants.GATEWAY_DEFAULT_PARAM).
GATEWAY_DEFAULT_PARAM = "$D"

# Parse strategy → the request attribute/column it reads. The ONE home
# of that mapping, shared by the per-request parser, the columnar
# parser and the needed-columns transpose — a strategy added to only
# one of them would silently diverge the fast and slow paths.
_STRATEGY_FIELD = {
    PARAM_PARSE_STRATEGY_CLIENT_IP: "client_ip",
    PARAM_PARSE_STRATEGY_HOST: "host",
    PARAM_PARSE_STRATEGY_HEADER: "headers",
    PARAM_PARSE_STRATEGY_URL_PARAM: "url_params",
    PARAM_PARSE_STRATEGY_COOKIE: "cookies",
}
# Strategies whose field holds per-request dicts read via field_name.
_DICT_STRATEGIES = frozenset(
    (PARAM_PARSE_STRATEGY_HEADER, PARAM_PARSE_STRATEGY_URL_PARAM,
     PARAM_PARSE_STRATEGY_COOKIE)
)


@dataclass(frozen=True)
class GatewayParamFlowItem:
    parse_strategy: int = PARAM_PARSE_STRATEGY_CLIENT_IP
    field_name: Optional[str] = None  # header/url-param/cookie name
    pattern: Optional[str] = None
    match_strategy: int = PARAM_MATCH_STRATEGY_EXACT


@dataclass(frozen=True)
class GatewayFlowRule:
    resource: str = ""
    resource_mode: int = RESOURCE_MODE_ROUTE_ID
    grade: int = C.FLOW_GRADE_QPS
    count: float = 0.0
    interval_sec: int = 1
    control_behavior: int = C.CONTROL_BEHAVIOR_DEFAULT
    burst: int = 0
    max_queueing_timeout_ms: int = 500
    param_item: Optional[GatewayParamFlowItem] = None


@dataclass(frozen=True)
class ApiPredicateItem:
    pattern: str = ""
    match_strategy: int = URL_MATCH_STRATEGY_EXACT

    def matches(self, path: str) -> bool:
        if self.match_strategy == URL_MATCH_STRATEGY_PREFIX:
            return path.startswith(self.pattern)
        if self.match_strategy == URL_MATCH_STRATEGY_REGEX:
            try:
                return re.fullmatch(self.pattern, path) is not None
            except re.error:
                return False
        return path == self.pattern


@dataclass(frozen=True)
class ApiDefinition:
    api_name: str
    predicate_items: Tuple[ApiPredicateItem, ...] = ()

    def matches(self, path: str) -> bool:
        return any(p.matches(path) for p in self.predicate_items)


@dataclass
class GatewayRequestInfo:
    """The request attributes GatewayParamParser reads."""

    path: str = "/"
    client_ip: str = ""
    host: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    url_params: Dict[str, str] = field(default_factory=dict)
    cookies: Dict[str, str] = field(default_factory=dict)


@dataclass
class GatewayRequestBatch:
    """Columnar request attributes for :func:`gateway_submit_bulk` —
    the host-ingest fast path. Each column is a length-``n`` sequence
    (list or numpy object array); columns the loaded rules never read
    may stay ``None`` (their rules then pass, like an empty attribute).
    A gateway that buffers a batching window can fill these columns
    directly from its own row storage and skip per-request
    ``GatewayRequestInfo`` objects entirely; parsing then touches each
    column once instead of walking attribute-by-attribute per request.
    """

    n: int
    client_ip: Optional[Sequence[str]] = None
    host: Optional[Sequence[str]] = None
    path: Optional[Sequence[str]] = None
    headers: Optional[Sequence[Dict[str, str]]] = None
    url_params: Optional[Sequence[Dict[str, str]]] = None
    cookies: Optional[Sequence[Dict[str, str]]] = None

    # The ONE home of the column-name set: validation and from_infos
    # both iterate it, so a new column added here is covered by both.
    _FIELDS = ("client_ip", "host", "path", "headers", "url_params", "cookies")

    def __post_init__(self) -> None:
        for name in self._FIELDS:
            col = getattr(self, name)
            if col is not None and len(col) != self.n:
                raise ValueError(
                    f"GatewayRequestBatch: column {name!r} has length"
                    f" {len(col)} != n={self.n}"
                )

    @classmethod
    def from_infos(
        cls,
        infos: Sequence[GatewayRequestInfo],
        fields: Optional[Sequence[str]] = None,
    ) -> "GatewayRequestBatch":
        """Transpose per-request infos into columns (one pass per
        column). ``fields`` limits the transpose to the columns a
        caller will actually read — the rules' parse strategies, on
        the ingest hot path. Callers that already hold columns should
        construct the batch directly and skip the info objects."""
        want = cls._FIELDS if fields is None else fields
        cols = {
            f: [getattr(i, f) for i in infos] for f in cls._FIELDS if f in want
        }
        return cls(n=len(infos), **cols)


class GatewayApiDefinitionManager:
    """Custom API groups (GatewayApiDefinitionManager)."""

    def __init__(self) -> None:
        self._apis: Dict[str, ApiDefinition] = {}

    def load_api_definitions(self, defs: Sequence[ApiDefinition]) -> None:
        self._apis = {d.api_name: d for d in defs}

    def get_api_definitions(self) -> List[ApiDefinition]:
        return list(self._apis.values())

    def matching_apis(self, path: str) -> List[str]:
        return [name for name, d in self._apis.items() if d.matches(path)]


class GatewayRuleManager:
    """Holds gateway rules, converts them to hot-param rules
    (GatewayRuleConverter.applyToParamRule) and contributes them to the
    param-flow manager; extracts each entry's param tuple."""

    def __init__(self) -> None:
        self._rules: List[GatewayFlowRule] = []
        self._by_resource: Dict[str, List[GatewayFlowRule]] = {}

    def load_rules(self, rules: Sequence[GatewayFlowRule]) -> None:
        self._rules = [r for r in rules if r.resource and r.count >= 0]
        self._by_resource = {}
        for r in self._rules:
            self._by_resource.setdefault(r.resource, []).append(r)
        converted: List[ParamFlowRule] = []
        for res, rs in self._by_resource.items():
            for idx, r in enumerate(rs):
                converted.append(
                    ParamFlowRule(
                        resource=res,
                        grade=r.grade,
                        param_idx=idx,
                        count=r.count,
                        control_behavior=r.control_behavior,
                        max_queueing_time_ms=r.max_queueing_timeout_ms,
                        burst_count=r.burst,
                        duration_in_sec=max(1, r.interval_sec),
                    )
                )
        from sentinel_tpu.rules.param_manager import param_flow_rule_manager

        param_flow_rule_manager.set_gateway_rules(converted)

    def get_rules(self) -> List[GatewayFlowRule]:
        return list(self._rules)

    def rules_for(self, resource: str) -> List[GatewayFlowRule]:
        return self._by_resource.get(resource, [])

    # --- GatewayParamParser ---
    def parse_params(self, resource: str, info: GatewayRequestInfo) -> Tuple:
        out = []
        for r in self.rules_for(resource):
            out.append(self._parse_one(r, info))
        return tuple(out)

    @staticmethod
    def _value_matcher(item: GatewayParamFlowItem):
        """The ONE home of param-item match semantics, shared by the
        per-request parser and the columnar parser: None when every
        non-empty value is limited (no pattern), else a predicate.
        A bad regex never matches, like the reference swallowing the
        PatternSyntaxException."""
        if not item.pattern:
            return None
        pat = item.pattern
        if item.match_strategy == PARAM_MATCH_STRATEGY_PREFIX:
            return lambda v: v.startswith(pat)
        if item.match_strategy == PARAM_MATCH_STRATEGY_REGEX:
            try:
                rx = re.compile(pat)
            except re.error:
                return lambda v: False
            return lambda v: rx.fullmatch(v) is not None
        return lambda v: v == pat

    @classmethod
    def _parse_one(cls, rule: GatewayFlowRule, info: GatewayRequestInfo) -> Optional[str]:
        item = rule.param_item
        if item is None:
            # No param matching: the whole route shares one bucket.
            return GATEWAY_DEFAULT_PARAM
        ps = item.parse_strategy
        field_name = _STRATEGY_FIELD.get(ps)
        if field_name is None:
            value = ""
        elif ps in _DICT_STRATEGIES:
            value = getattr(info, field_name).get(item.field_name or "", "")
        else:
            value = getattr(info, field_name)
        if not value:
            return None  # nothing to limit on -> rule passes
        keep = cls._value_matcher(item)
        if keep is not None and not keep(value):
            return None  # unmatched values are not limited
        return value

    # --- columnar GatewayParamParser (host-ingest fast path) ---
    def parse_params_batch(self, resource: str, batch: GatewayRequestBatch):
        """:meth:`parse_params` over a whole batch, one value column
        per rule — the strategy dispatch and pattern compile run once
        per rule instead of once per request. Returns an
        :class:`~sentinel_tpu.rules.param_table.ArgsColumns` suitable
        for ``Engine.submit_bulk``'s ``args_column``."""
        from sentinel_tpu.rules.param_table import ArgsColumns

        return ArgsColumns(
            batch.n,
            {
                idx: self._parse_col(r, batch)
                for idx, r in enumerate(self.rules_for(resource))
            },
        )

    @classmethod
    def _parse_col(cls, rule: GatewayFlowRule, batch: GatewayRequestBatch) -> List[Optional[str]]:
        """One rule's per-request value column — semantics identical to
        ``_parse_one`` per request (empty/unmatched values become None:
        nothing to limit on, the rule passes), with the strategy
        dispatch and matcher compile hoisted out of the request loop."""
        n = batch.n
        item = rule.param_item
        if item is None:
            # No param matching: the whole route shares one bucket.
            return [GATEWAY_DEFAULT_PARAM] * n
        ps = item.parse_strategy
        field_name = _STRATEGY_FIELD.get(ps)
        if field_name is None:
            return [None] * n
        col = getattr(batch, field_name)
        if col is None:
            return [None] * n
        if ps in _DICT_STRATEGIES:
            name = item.field_name or ""
            # A None element means "this request had no headers/params/
            # cookies" — treat like the info default {} (rule passes).
            raw = [d.get(name, "") if d else "" for d in col]
        else:
            raw = col
        keep = cls._value_matcher(item)
        if keep is None:
            return [v or None for v in raw]
        return [v if v and keep(v) else None for v in raw]


gateway_rule_manager = GatewayRuleManager()
gateway_api_definition_manager = GatewayApiDefinitionManager()


@contextmanager
def gateway_entry(route_id: str, info: GatewayRequestInfo):
    """Enter the route resource (+ any matching custom-API resources)
    with the extracted gateway params; the GatewayFlowSlot equivalent.
    Raises ParamFlowBlockError/BlockError when limited. An inbound
    W3C ``traceparent`` in ``info.headers`` becomes the ambient trace
    identity for the admissions and the proxied call."""
    from sentinel_tpu.core.context import ContextUtil
    from sentinel_tpu.metrics.admission_trace import parse_traceparent

    resources = [route_id] + gateway_api_definition_manager.matching_apis(info.path)
    trace_token = ContextUtil.set_trace(
        parse_traceparent(
            info.headers.get("traceparent"),
            info.headers.get("tracestate", ""),
        )
    )
    entries = []
    try:
        for res in resources:
            args = gateway_rule_manager.parse_params(res, info)
            # Windowed columnar admission (runtime/window.py) when the
            # adapter-edge batch window is armed: the extracted param
            # tuple rides the window's ArgsColumns; per-request
            # api.entry otherwise.
            entries.append(
                api.entry_windowed(res, entry_type=C.EntryType.IN, args=args)
            )
        yield entries
    except BaseException as e:
        from sentinel_tpu.core.errors import BlockError

        if not isinstance(e, BlockError):
            for en in entries:
                en.set_error(e)
        raise
    finally:
        for en in reversed(entries):
            en.exit()
        ContextUtil.reset_trace(trace_token)


def gateway_submit_bulk(
    route_id: str,
    infos,
    *,
    engine=None,
    ts=None,
    acquire=1,
    flush: bool = False,
):
    """Columnar gateway admission — the adapter fast path onto
    :meth:`Engine.submit_bulk`.

    Parses the batch's gateway params (GatewayParamParser, host side)
    into per-rule value columns and submits the whole batch as a single
    bulk group: one slot resolution for the route, per-value interning
    once per distinct value (persistently cached across flushes), array
    verdicts after ``flush()``. Three orders of magnitude less
    per-request Python than ``gateway_entry`` (no Entry objects, no
    context, no per-request engine lock).

    ``infos`` is either a ``Sequence[GatewayRequestInfo]`` (the
    original signature) or a :class:`GatewayRequestBatch` of columns —
    the columnar form skips every per-request attribute walk: the
    fast-attr case (single rule on client IP / host, no pattern)
    becomes one vectorized column view with no tuple allocation at all.

    Scope (the high-throughput subset): route-level rules only — custom
    ApiDefinition resources, THREAD-grade and cluster-mode rules stay
    on the per-request ``gateway_entry`` path. Returns the
    :class:`~sentinel_tpu.runtime.engine.BulkOp` (or None for
    pass-through); ``op.admitted`` is the per-request verdict array
    after ``flush()``. Callers account completions with
    ``submit_exit_bulk`` like any bulk group.

    ``flush=True`` flushes the engine before returning — with the
    engine's flush pipeline enabled (``sentinel.tpu.host.pipeline.
    depth`` > 0) that dispatch is pipelined: the adapter's next window
    parses and encodes while this window's kernel runs, and the
    returned group's verdicts materialize lazily on first access
    (``op.admitted``), exactly like any pipelined flush.
    """
    from sentinel_tpu.rules.param_table import ArgsColumns

    eng = engine if engine is not None else api.get_engine()
    is_batch = isinstance(infos, GatewayRequestBatch)
    n = infos.n if is_batch else len(infos)
    # Single-rule direct-attribute strategies (client IP / host, no
    # pattern) skip the per-request parser walk — the common gateway
    # config, and the host-side hot loop at bulk sizes.
    rules = gateway_rule_manager.rules_for(route_id)
    fast_attr = None
    if len(rules) == 1 and rules[0].param_item is not None and not rules[0].param_item.pattern:
        ps = rules[0].param_item.parse_strategy
        if ps == PARAM_PARSE_STRATEGY_CLIENT_IP:
            fast_attr = "client_ip"
        elif ps == PARAM_PARSE_STRATEGY_HOST:
            fast_attr = "host"
    if is_batch:
        if fast_attr is not None:
            raw = getattr(infos, fast_attr)
            col = [None] * n if raw is None else [v or None for v in raw]
            args_column = ArgsColumns(n, {0: col})
        else:
            args_column = gateway_rule_manager.parse_params_batch(route_id, infos)
    elif fast_attr is not None:
        # Tuple-free fast-attr column straight off the info objects.
        args_column = ArgsColumns(
            n, {0: [getattr(info, fast_attr) or None for info in infos]}
        )
    else:
        # Generic rules: transpose the infos (only the columns the
        # route's strategies read) and run the columnar parser — same
        # ArgsColumns path and parse semantics as the batch form.
        need = {
            f
            for r in rules
            if r.param_item is not None
            and (f := _STRATEGY_FIELD.get(r.param_item.parse_strategy))
        }
        args_column = gateway_rule_manager.parse_params_batch(
            route_id, GatewayRequestBatch.from_infos(infos, fields=need)
        )
    op = eng.submit_bulk(
        route_id,
        n,
        ts=ts,
        acquire=acquire,
        entry_type=C.EntryType.IN,
        args_column=args_column,
    )
    # Skip the flush when nothing is pending (flush-on-size inside
    # submit_bulk already dispatched this window): at pipeline depth >
    # 0 an EMPTY flush settles the whole in-flight queue, which would
    # silently de-pipeline exactly the max_batch-sized windows.
    if flush and eng.has_pending():
        eng.flush()
    return op
