"""API-gateway flow control.

Reference: sentinel-api-gateway-adapter-common — GatewayFlowRule
(per-route / per-custom-API rules with parameter matching on client IP /
host / header / URL param / cookie, exact-prefix-regex matchers),
converted to hot-param rules by GatewayRuleConverter, params extracted
by GatewayParamParser, checked by GatewayFlowSlot, plus ApiDefinition
route groups (reference: .../gateway/common/rule/GatewayRuleManager.java:39,
slot/GatewayFlowSlot.java:37, param/GatewayParamParser.java,
api/ApiDefinition.java).

Usage::

    gateway_rule_manager.load_rules([
        GatewayFlowRule("my_route", count=10,
                        param_item=GatewayParamFlowItem(parse_strategy=PARAM_PARSE_STRATEGY_CLIENT_IP)),
    ])
    info = GatewayRequestInfo(path="/api/x", client_ip="1.2.3.4", ...)
    with gateway_entry("my_route", info):
        ...
"""

from __future__ import annotations

import re
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.core import api
from sentinel_tpu.models import constants as C
from sentinel_tpu.models.rules import ParamFlowRule

# Resource modes (SentinelGatewayConstants).
RESOURCE_MODE_ROUTE_ID = 0
RESOURCE_MODE_CUSTOM_API_NAME = 1

# Param parse strategies.
PARAM_PARSE_STRATEGY_CLIENT_IP = 0
PARAM_PARSE_STRATEGY_HOST = 1
PARAM_PARSE_STRATEGY_HEADER = 2
PARAM_PARSE_STRATEGY_URL_PARAM = 3
PARAM_PARSE_STRATEGY_COOKIE = 4

# String match strategies.
PARAM_MATCH_STRATEGY_EXACT = 0
PARAM_MATCH_STRATEGY_PREFIX = 1
PARAM_MATCH_STRATEGY_REGEX = 2

# URL match strategies for ApiDefinition predicates.
URL_MATCH_STRATEGY_EXACT = 0
URL_MATCH_STRATEGY_PREFIX = 1
URL_MATCH_STRATEGY_REGEX = 2

# The constant param value used when a rule has no param item
# (SentinelGatewayConstants.GATEWAY_DEFAULT_PARAM).
GATEWAY_DEFAULT_PARAM = "$D"


@dataclass(frozen=True)
class GatewayParamFlowItem:
    parse_strategy: int = PARAM_PARSE_STRATEGY_CLIENT_IP
    field_name: Optional[str] = None  # header/url-param/cookie name
    pattern: Optional[str] = None
    match_strategy: int = PARAM_MATCH_STRATEGY_EXACT


@dataclass(frozen=True)
class GatewayFlowRule:
    resource: str = ""
    resource_mode: int = RESOURCE_MODE_ROUTE_ID
    grade: int = C.FLOW_GRADE_QPS
    count: float = 0.0
    interval_sec: int = 1
    control_behavior: int = C.CONTROL_BEHAVIOR_DEFAULT
    burst: int = 0
    max_queueing_timeout_ms: int = 500
    param_item: Optional[GatewayParamFlowItem] = None


@dataclass(frozen=True)
class ApiPredicateItem:
    pattern: str = ""
    match_strategy: int = URL_MATCH_STRATEGY_EXACT

    def matches(self, path: str) -> bool:
        if self.match_strategy == URL_MATCH_STRATEGY_PREFIX:
            return path.startswith(self.pattern)
        if self.match_strategy == URL_MATCH_STRATEGY_REGEX:
            try:
                return re.fullmatch(self.pattern, path) is not None
            except re.error:
                return False
        return path == self.pattern


@dataclass(frozen=True)
class ApiDefinition:
    api_name: str
    predicate_items: Tuple[ApiPredicateItem, ...] = ()

    def matches(self, path: str) -> bool:
        return any(p.matches(path) for p in self.predicate_items)


@dataclass
class GatewayRequestInfo:
    """The request attributes GatewayParamParser reads."""

    path: str = "/"
    client_ip: str = ""
    host: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    url_params: Dict[str, str] = field(default_factory=dict)
    cookies: Dict[str, str] = field(default_factory=dict)


class GatewayApiDefinitionManager:
    """Custom API groups (GatewayApiDefinitionManager)."""

    def __init__(self) -> None:
        self._apis: Dict[str, ApiDefinition] = {}

    def load_api_definitions(self, defs: Sequence[ApiDefinition]) -> None:
        self._apis = {d.api_name: d for d in defs}

    def get_api_definitions(self) -> List[ApiDefinition]:
        return list(self._apis.values())

    def matching_apis(self, path: str) -> List[str]:
        return [name for name, d in self._apis.items() if d.matches(path)]


class GatewayRuleManager:
    """Holds gateway rules, converts them to hot-param rules
    (GatewayRuleConverter.applyToParamRule) and contributes them to the
    param-flow manager; extracts each entry's param tuple."""

    def __init__(self) -> None:
        self._rules: List[GatewayFlowRule] = []
        self._by_resource: Dict[str, List[GatewayFlowRule]] = {}

    def load_rules(self, rules: Sequence[GatewayFlowRule]) -> None:
        self._rules = [r for r in rules if r.resource and r.count >= 0]
        self._by_resource = {}
        for r in self._rules:
            self._by_resource.setdefault(r.resource, []).append(r)
        converted: List[ParamFlowRule] = []
        for res, rs in self._by_resource.items():
            for idx, r in enumerate(rs):
                converted.append(
                    ParamFlowRule(
                        resource=res,
                        grade=r.grade,
                        param_idx=idx,
                        count=r.count,
                        control_behavior=r.control_behavior,
                        max_queueing_time_ms=r.max_queueing_timeout_ms,
                        burst_count=r.burst,
                        duration_in_sec=max(1, r.interval_sec),
                    )
                )
        from sentinel_tpu.rules.param_manager import param_flow_rule_manager

        param_flow_rule_manager.set_gateway_rules(converted)

    def get_rules(self) -> List[GatewayFlowRule]:
        return list(self._rules)

    def rules_for(self, resource: str) -> List[GatewayFlowRule]:
        return self._by_resource.get(resource, [])

    # --- GatewayParamParser ---
    def parse_params(self, resource: str, info: GatewayRequestInfo) -> Tuple:
        out = []
        for r in self.rules_for(resource):
            out.append(self._parse_one(r, info))
        return tuple(out)

    @staticmethod
    def _parse_one(rule: GatewayFlowRule, info: GatewayRequestInfo) -> Optional[str]:
        item = rule.param_item
        if item is None:
            # No param matching: the whole route shares one bucket.
            return GATEWAY_DEFAULT_PARAM
        ps = item.parse_strategy
        if ps == PARAM_PARSE_STRATEGY_CLIENT_IP:
            value = info.client_ip
        elif ps == PARAM_PARSE_STRATEGY_HOST:
            value = info.host
        elif ps == PARAM_PARSE_STRATEGY_HEADER:
            value = info.headers.get(item.field_name or "", "")
        elif ps == PARAM_PARSE_STRATEGY_URL_PARAM:
            value = info.url_params.get(item.field_name or "", "")
        elif ps == PARAM_PARSE_STRATEGY_COOKIE:
            value = info.cookies.get(item.field_name or "", "")
        else:
            value = ""
        if not value:
            return None  # nothing to limit on -> rule passes
        if item.pattern:
            if item.match_strategy == PARAM_MATCH_STRATEGY_PREFIX:
                matched = value.startswith(item.pattern)
            elif item.match_strategy == PARAM_MATCH_STRATEGY_REGEX:
                try:
                    matched = re.fullmatch(item.pattern, value) is not None
                except re.error:
                    matched = False
            else:
                matched = value == item.pattern
            if not matched:
                return None  # unmatched values are not limited
        return value


gateway_rule_manager = GatewayRuleManager()
gateway_api_definition_manager = GatewayApiDefinitionManager()


@contextmanager
def gateway_entry(route_id: str, info: GatewayRequestInfo):
    """Enter the route resource (+ any matching custom-API resources)
    with the extracted gateway params; the GatewayFlowSlot equivalent.
    Raises ParamFlowBlockError/BlockError when limited."""
    resources = [route_id] + gateway_api_definition_manager.matching_apis(info.path)
    entries = []
    try:
        for res in resources:
            args = gateway_rule_manager.parse_params(res, info)
            entries.append(api.entry(res, entry_type=C.EntryType.IN, args=args))
        yield entries
    except BaseException as e:
        from sentinel_tpu.core.errors import BlockError

        if not isinstance(e, BlockError):
            for en in entries:
                en.set_error(e)
        raise
    finally:
        for en in reversed(entries):
            en.exit()


def gateway_submit_bulk(
    route_id: str,
    infos: Sequence[GatewayRequestInfo],
    *,
    engine=None,
    ts=None,
):
    """Columnar gateway admission — the adapter fast path onto
    :meth:`Engine.submit_bulk`.

    Parses each request's gateway params (GatewayParamParser, host
    side) into one args column and submits the whole batch as a single
    bulk group: one slot resolution for the route, per-value interning
    once per distinct value, array verdicts after ``flush()``. Three
    orders of magnitude less per-request Python than ``gateway_entry``
    (no Entry objects, no context, no per-request engine lock).

    Scope (the high-throughput subset): route-level rules only — custom
    ApiDefinition resources, THREAD-grade and cluster-mode rules stay
    on the per-request ``gateway_entry`` path. Returns the
    :class:`~sentinel_tpu.runtime.engine.BulkOp` (or None for
    pass-through); ``op.admitted`` is the per-request verdict array
    after ``flush()``. Callers account completions with
    ``submit_exit_bulk`` like any bulk group.
    """
    eng = engine if engine is not None else api.get_engine()
    # Single-rule direct-attribute strategies (client IP / host, no
    # pattern) skip the per-request parser walk — the common gateway
    # config, and the host-side hot loop at bulk sizes.
    rules = gateway_rule_manager.rules_for(route_id)
    fast_attr = None
    if len(rules) == 1 and rules[0].param_item is not None and not rules[0].param_item.pattern:
        ps = rules[0].param_item.parse_strategy
        if ps == PARAM_PARSE_STRATEGY_CLIENT_IP:
            fast_attr = "client_ip"
        elif ps == PARAM_PARSE_STRATEGY_HOST:
            fast_attr = "host"
    if fast_attr is not None:
        args_column = [(getattr(info, fast_attr) or None,) for info in infos]
    else:
        args_column = [
            gateway_rule_manager.parse_params(route_id, info) for info in infos
        ]
    return eng.submit_bulk(
        route_id,
        len(infos),
        ts=ts,
        entry_type=C.EntryType.IN,
        args_column=args_column,
    )
