"""Flask extension sugar over the WSGI integration.

Reference analog: sentinel-spring-webmvc-adapter's SentinelWebInterceptor
(AbstractSentinelInterceptor.java:60-110) registered through framework
hooks rather than a raw filter. The generic
:class:`~sentinel_tpu.adapters.SentinelWSGIMiddleware` already works on
any Flask app (``app.wsgi_app = SentinelWSGIMiddleware(app.wsgi_app)``);
this extension is the idiomatic mount with per-view resources and a
configurable block handler::

    from flask import Flask
    from sentinel_tpu.adapters.flask_adapter import SentinelFlask

    app = Flask(__name__)
    SentinelFlask(app, total_resource="flask-total")

All imports of flask happen at ``init_app`` time — importing this
module never requires flask.
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import parse_traceparent
from sentinel_tpu.models import constants as C

BLOCK_BODY = "Blocked by Sentinel (flow limiting)"
_ENTRIES_KEY = "_sentinel_entries"
_TRACE_TOKEN_KEY = "_sentinel_trace_token"


class SentinelFlask:
    """Per-request IN entries via Flask request hooks.

    Resource = ``METHOD:url_rule`` (the route pattern, so path params
    don't explode the resource space — the spring-webmvc adapter's
    pattern-based resource), plus an optional app-total resource.
    Blocked requests return ``(block_body, block_status)``; handler
    exceptions are traced to the circuit breakers.
    """

    def __init__(
        self,
        app=None,
        total_resource: Optional[str] = None,
        origin_parser: Optional[Callable] = None,
        block_status: int = 429,
        block_body: str = BLOCK_BODY,
    ) -> None:
        self.total_resource = total_resource
        self.origin_parser = origin_parser or (lambda request: "")
        self.block_status = block_status
        self.block_body = block_body
        if app is not None:
            self.init_app(app)

    def _resource(self, request) -> str:
        rule = request.url_rule.rule if request.url_rule is not None else request.path
        return f"{request.method}:{rule}"

    def init_app(self, app) -> None:
        from flask import g, request

        ext = self

        @app.before_request
        def _sentinel_enter():
            resources = []
            if ext.total_resource:
                resources.append(ext.total_resource)
            resources.append(ext._resource(request))
            origin = ext.origin_parser(request)
            # Inbound W3C trace context: ambient for the whole request
            # (handler + guarded outbound calls); the token is reset at
            # teardown so a reused worker thread never leaks identity.
            token = ContextUtil.set_trace(
                parse_traceparent(
                    request.headers.get("traceparent"),
                    request.headers.get("tracestate", ""),
                )
            )
            setattr(g, _TRACE_TOKEN_KEY, token)
            entries = []
            try:
                for res in resources:
                    # Windowed columnar admission (runtime/window.py)
                    # when armed; per-request entry_async otherwise.
                    entries.append(
                        api.entry_windowed(
                            res, entry_type=C.EntryType.IN, origin=origin,
                            detached=True,
                        )
                    )
            except BlockError:
                for en in reversed(entries):
                    en.exit()
                return ext.block_body, ext.block_status
            setattr(g, _ENTRIES_KEY, entries)
            return None

        @app.teardown_request
        def _sentinel_exit(exc):
            token = getattr(g, _TRACE_TOKEN_KEY, None)
            if token is not None:
                setattr(g, _TRACE_TOKEN_KEY, None)
                ContextUtil.reset_trace(token)
            entries = getattr(g, _ENTRIES_KEY, None)
            if not entries:
                return
            setattr(g, _ENTRIES_KEY, None)
            for en in entries:
                if exc is not None:
                    en.set_error(exc)
            for en in reversed(entries):
                en.exit()
