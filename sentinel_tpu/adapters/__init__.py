"""Framework adapters.

Equivalent of sentinel-adapter's 17 modules + the annotation extension
(reference: sentinel-adapter/* and sentinel-extension/
sentinel-annotation-aspectj/.../SentinelResourceAspect.java:36-83). All
reference adapters follow one pattern — map an invocation to
``ContextUtil.enter(context, origin) + SphU.entry(resource, type) +
Tracer.trace + exit`` with configurable origin parser / resource-name
customizer / fallback — and so do these:

* :func:`sentinel_resource` — the ``@SentinelResource`` decorator
  (blockHandler / fallback / defaultFallback dispatch, sync + async).
* :class:`SentinelWSGIMiddleware` — sentinel-web-servlet /
  spring-webmvc (total + per-URL resources, origin parser, block page).
* :class:`SentinelASGIMiddleware` — spring-webflux / reactor.
* gRPC server/client interceptors — sentinel-grpc-adapter.
* :func:`guard_call` / :class:`GuardedClient` (+ async twins) — the
  outbound-client adapters (okhttp / apache-httpclient), fitting
  requests.Session / httpx.Client / httpx.AsyncClient.
* :class:`SentinelHTTPAdapter` — transparent ``requests`` transport
  adapter (mount once, every call guarded).
* :mod:`sentinel_tpu.adapters.aiohttp_adapter` — aiohttp server
  middleware + guarded ClientSession.
* :class:`SentinelFlask` / :func:`sentinel_guard` — Flask extension and
  FastAPI dependency sugar (gated on those packages).
* :mod:`sentinel_tpu.adapters.gateway` — api-gateway-adapter-common:
  GatewayFlowRule with param matching, ApiDefinition groups, conversion
  to hot-param rules.
"""

from sentinel_tpu.adapters.decorator import sentinel_resource
from sentinel_tpu.adapters.wsgi import SentinelWSGIMiddleware
from sentinel_tpu.adapters.asgi import SentinelASGIMiddleware
from sentinel_tpu.adapters.client import (
    GuardedAsyncClient,
    GuardedClient,
    guard_call,
    guard_call_async,
)
from sentinel_tpu.adapters.requests_adapter import SentinelHTTPAdapter
from sentinel_tpu.adapters.flask_adapter import SentinelFlask
from sentinel_tpu.adapters.fastapi_adapter import sentinel_guard

__all__ = [
    "sentinel_resource",
    "SentinelWSGIMiddleware",
    "SentinelASGIMiddleware",
    "GuardedClient",
    "GuardedAsyncClient",
    "guard_call",
    "guard_call_async",
    "SentinelHTTPAdapter",
    "SentinelFlask",
    "sentinel_guard",
]
