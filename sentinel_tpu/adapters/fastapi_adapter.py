"""FastAPI sugar over the ASGI integration.

FastAPI apps are ASGI apps, so the generic
:class:`~sentinel_tpu.adapters.SentinelASGIMiddleware` is the app-wide
mount (``app.add_middleware(SentinelASGIMiddleware)`` works as-is).
This module adds the idiomatic per-route dependency::

    from fastapi import Depends, FastAPI
    from sentinel_tpu.adapters.fastapi_adapter import sentinel_guard

    app = FastAPI()

    @app.get("/users", dependencies=[Depends(sentinel_guard())])
    async def users(): ...

Blocked requests raise fastapi's HTTPException(429). All fastapi
imports happen inside the dependency — importing this module never
requires fastapi.
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import parse_traceparent
from sentinel_tpu.models import constants as C

BLOCK_DETAIL = "Blocked by Sentinel (flow limiting)"


def sentinel_guard(
    resource: Optional[str] = None,
    origin_parser: Optional[Callable] = None,
    block_status: int = 429,
):
    """A FastAPI dependency entering an IN-typed resource for the route
    (default resource = ``METHOD:route-path-template``); the yield
    teardown exits the entry and traces handler exceptions."""

    async def _dep(request):
        from fastapi import HTTPException

        route = request.scope.get("route")
        path = getattr(route, "path", None) or request.url.path
        res = resource or f"{request.method}:{path}"
        origin = origin_parser(request) if origin_parser else ""
        # Inbound W3C trace context, ambient through the handler (the
        # dependency's contextvars scope spans the endpoint call).
        token = ContextUtil.set_trace(
            parse_traceparent(
                request.headers.get("traceparent"),
                request.headers.get("tracestate", ""),
            )
        )
        try:
            try:
                # Windowed columnar admission (runtime/window.py) when
                # armed — awaited so the loop stays free while the
                # window assembles; per-request entry_async otherwise.
                entry = await api.entry_windowed_async(
                    res, entry_type=C.EntryType.IN, origin=origin
                )
            except BlockError:
                raise HTTPException(
                    status_code=block_status, detail=BLOCK_DETAIL
                )
            try:
                yield entry
            except BaseException as e:
                entry.set_error(e)
                raise
            finally:
                entry.exit()
        finally:
            ContextUtil.reset_trace(token)

    # FastAPI resolves the Request parameter by annotation; attach it
    # lazily so importing this module works without fastapi installed.
    try:
        from fastapi import Request

        _dep.__annotations__["request"] = Request
    except ImportError:  # pragma: no cover - no fastapi in this env
        pass
    return _dep
