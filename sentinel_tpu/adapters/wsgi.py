"""WSGI middleware.

Reference: sentinel-web-servlet's CommonFilter + the spring-webmvc
interceptor: each request enters the web context with a parsed origin,
then a total-inbound resource plus the per-URL resource; blocks render a
429 page (configurable); business errors are traced on exit.

Admissions ride the columnar ingest spine: with the adapter-edge batch
window armed (``sentinel.tpu.ingest.batch.window.ms`` > 0) concurrent
requests coalesce into one columnar ``submit_bulk`` flush with
per-request verdict fan-out (``api.entry_windowed``); window off is
exactly the per-request path. In ipc worker mode
(``sentinel.tpu.ipc.worker.mode``) the same calls ride the process's
IngestClient to the engine process instead — this middleware is
unchanged either way (see sentinel_tpu/ipc/worker_mode.py).
"""

from __future__ import annotations

from typing import Callable, Iterable, Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import parse_traceparent
from sentinel_tpu.models import constants as C

DEFAULT_BLOCK_BODY = b"Blocked by Sentinel (flow limiting)"
WEB_CONTEXT_NAME = "sentinel_web_context"


class SentinelWSGIMiddleware:
    def __init__(
        self,
        app,
        *,
        resource_extractor: Optional[Callable[[dict], str]] = None,
        origin_parser: Optional[Callable[[dict], str]] = None,
        block_handler: Optional[Callable[[dict, BlockError], tuple]] = None,
        total_resource: Optional[str] = "web-total",
        http_method_specify: bool = True,
    ) -> None:
        self.app = app
        self.resource_extractor = resource_extractor or self._default_resource
        self.origin_parser = origin_parser or (lambda env: "")
        self.block_handler = block_handler
        self.total_resource = total_resource
        self.http_method_specify = http_method_specify

    def _default_resource(self, environ: dict) -> str:
        path = environ.get("PATH_INFO", "/")
        if self.http_method_specify:
            return f"{environ.get('REQUEST_METHOD', 'GET')}:{path}"
        return path

    def __call__(self, environ: dict, start_response):
        resource = self.resource_extractor(environ)
        origin = self.origin_parser(environ)
        # CGI spelling of the W3C headers: traceparent -> HTTP_TRACEPARENT.
        trace_token = ContextUtil.set_trace(
            parse_traceparent(
                environ.get("HTTP_TRACEPARENT"),
                environ.get("HTTP_TRACESTATE", ""),
            )
        )
        ctx = ContextUtil.enter(WEB_CONTEXT_NAME, origin)
        entries = []
        try:
            try:
                if self.total_resource:
                    entries.append(
                        api.entry_windowed(
                            self.total_resource, entry_type=C.EntryType.IN
                        )
                    )
                entries.append(
                    api.entry_windowed(resource, entry_type=C.EntryType.IN)
                )
            except BlockError as e:
                return self._blocked(environ, start_response, e)
            try:
                result = self.app(environ, start_response)
                return result
            except BaseException as e:
                for en in entries:
                    en.set_error(e)
                raise
        finally:
            for en in reversed(entries):
                en.exit()
            ContextUtil.exit()
            ContextUtil.reset_trace(trace_token)

    def _blocked(self, environ, start_response, e: BlockError) -> Iterable[bytes]:
        if self.block_handler is not None:
            status, headers, body = self.block_handler(environ, e)
        else:
            status = "429 Too Many Requests"
            body = DEFAULT_BLOCK_BODY
            headers = [("Content-Type", "text/plain"), ("Content-Length", str(len(body)))]
        start_response(status, headers)
        return [body]
