"""aiohttp integration: server middleware + guarded client session.

Reference analogs: the servlet/spring-webmvc adapters' filter
(AbstractSentinelInterceptor.java:60-110 — IN entry per request, block
page on limit) for the server side, and the okhttp interceptor for the
client side. Both are gated on aiohttp being importable.

Server::

    from aiohttp import web
    from sentinel_tpu.adapters.aiohttp_adapter import sentinel_middleware

    app = web.Application(middlewares=[sentinel_middleware()])

Client::

    from sentinel_tpu.adapters.aiohttp_adapter import SentinelClientSession

    async with SentinelClientSession() as s:
        await s.get("http://api.internal/users")
"""

from __future__ import annotations

from typing import Callable, Optional

from sentinel_tpu.core import api
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import parse_traceparent
from sentinel_tpu.models import constants as C

BLOCK_BODY = "Blocked by Sentinel (flow limiting)"


def sentinel_middleware(
    resource_extractor: Optional[Callable] = None,
    origin_parser: Optional[Callable] = None,
    block_status: int = 429,
    block_body: str = BLOCK_BODY,
    total_resource: Optional[str] = None,
):
    """aiohttp.web middleware: one IN entry per request (resource =
    ``METHOD:path`` by default, plus an optional app-total resource
    like the servlet filter's WebServletConfig total target), 429 +
    body on block, exceptions traced to the breaker."""
    from aiohttp import web

    extract = resource_extractor or (lambda req: f"{req.method}:{req.path}")
    parse_origin = origin_parser or (lambda req: "")

    @web.middleware
    async def _middleware(request, handler):
        resources = []
        if total_resource:
            resources.append(total_resource)
        resources.append(extract(request))
        origin = parse_origin(request)
        # Inbound W3C trace context, ambient through the handler and
        # any guarded outbound calls it makes.
        token = ContextUtil.set_trace(
            parse_traceparent(
                request.headers.get("traceparent"),
                request.headers.get("tracestate", ""),
            )
        )
        entries = []
        try:
            try:
                for res in resources:
                    # Windowed columnar admission (runtime/window.py)
                    # when armed — awaited so the loop stays free while
                    # the window assembles; entry_async otherwise.
                    entries.append(
                        await api.entry_windowed_async(
                            res, entry_type=C.EntryType.IN, origin=origin
                        )
                    )
            except BlockError:
                for en in reversed(entries):
                    en.exit()
                return web.Response(status=block_status, text=block_body)
            try:
                return await handler(request)
            except web.HTTPException:
                raise  # normal aiohttp control flow, not a fault
            except BaseException as e:
                for en in entries:
                    en.set_error(e)
                raise
            finally:
                for en in reversed(entries):
                    en.exit()
        finally:
            ContextUtil.reset_trace(token)

    return _middleware


def _default_client_resource(method: str, url) -> str:
    u = str(url).split("?", 1)[0]
    return f"{method}:{u}"


class _GuardedRequestCtx:
    """Awaitable + async-context-manager over a guarded request, so
    both aiohttp idioms work::

        resp = await s.get(url)
        async with s.get(url) as resp: ...   # releases on exit
    """

    __slots__ = ("_coro", "_resp")

    def __init__(self, coro) -> None:
        self._coro = coro
        self._resp = None

    def __await__(self):
        return self._coro.__await__()

    async def __aenter__(self):
        self._resp = await self._coro
        return self._resp

    async def __aexit__(self, *exc) -> None:
        resp = self._resp
        if resp is not None and hasattr(resp, "release"):
            resp.release()


class SentinelClientSession:
    """An ``aiohttp.ClientSession`` wrapper guarding every request with
    an OUT entry (the okhttp-interceptor shape). Constructed lazily so
    importing this module never requires aiohttp; unknown attributes
    (``patch``-less verbs aside, e.g. ``ws_connect``, ``closed``,
    ``headers``) delegate to the underlying session UNGUARDED."""

    def __init__(
        self,
        *args,
        resource_extractor: Callable = _default_client_resource,
        fallback: Optional[Callable] = None,
        **kwargs,
    ) -> None:
        import aiohttp

        self._session = aiohttp.ClientSession(*args, **kwargs)
        self._extract = resource_extractor
        self._fallback = fallback

    async def __aenter__(self) -> "SentinelClientSession":
        await self._session.__aenter__()
        return self

    async def __aexit__(self, *exc) -> None:
        await self._session.__aexit__(*exc)

    async def close(self) -> None:
        await self._session.close()

    async def _request(self, method: str, url, **kwargs):
        from sentinel_tpu.adapters.client import (
            _with_trace_headers,
            guard_call_async,
        )

        resource = self._extract(method, url)
        return await guard_call_async(
            resource,
            self._session.request,
            method,
            url,
            fallback=self._fallback,
            **_with_trace_headers(kwargs),
        )

    def request(self, method, url, **kwargs) -> _GuardedRequestCtx:
        return _GuardedRequestCtx(self._request(method, url, **kwargs))

    def get(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("GET", url, **kwargs)

    def post(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("POST", url, **kwargs)

    def put(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("PUT", url, **kwargs)

    def delete(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("DELETE", url, **kwargs)

    def patch(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("PATCH", url, **kwargs)

    def head(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("HEAD", url, **kwargs)

    def options(self, url, **kwargs) -> _GuardedRequestCtx:
        return self.request("OPTIONS", url, **kwargs)

    def __getattr__(self, name):
        return getattr(self._session, name)
