"""Outbound-client guards.

Reference: sentinel-okhttp-adapter / sentinel-apache-httpclient-adapter:
wrap outbound calls in an OUT-typed entry named after the request
(cleaner: ``METHOD:host/path``) so downstream dependencies get their own
flow rules and circuit breakers.
"""

from __future__ import annotations

from typing import Callable, Optional, TypeVar

from sentinel_tpu.core import api
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.models import constants as C

T = TypeVar("T")


def guard_call(resource: str, fn: Callable[..., T], *args, fallback=None, **kwargs) -> T:
    """Run ``fn`` under an OUT entry; trace errors; on block call
    ``fallback(error)`` or raise."""
    try:
        entry = api.entry(resource, entry_type=C.EntryType.OUT)
    except BlockError as e:
        if fallback is not None:
            return fallback(e)
        raise
    try:
        result = fn(*args, **kwargs)
    except BaseException as e:
        entry.set_error(e)
        raise
    finally:
        entry.exit()
    return result


class GuardedClient:
    """Wrap any HTTP-client-like object whose request method is
    ``request(method, url, ...)`` (requests.Session, httpx.Client...)."""

    def __init__(
        self,
        client,
        resource_extractor: Optional[Callable[[str, str], str]] = None,
        fallback: Optional[Callable] = None,
    ) -> None:
        self._client = client
        self._extract = resource_extractor or (lambda method, url: f"{method.upper()}:{url}")
        self._fallback = fallback

    def request(self, method: str, url: str, *args, **kwargs):
        resource = self._extract(method, url)
        return guard_call(
            resource, self._client.request, method, url, *args,
            fallback=self._fallback, **kwargs,
        )

    def get(self, url: str, **kwargs):
        return self.request("GET", url, **kwargs)

    def post(self, url: str, **kwargs):
        return self.request("POST", url, **kwargs)

    def __getattr__(self, name):
        return getattr(self._client, name)
