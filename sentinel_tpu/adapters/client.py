"""Outbound-client guards.

Reference: sentinel-okhttp-adapter / sentinel-apache-httpclient-adapter:
wrap outbound calls in an OUT-typed entry named after the request
(cleaner: ``METHOD:host/path``) so downstream dependencies get their own
flow rules and circuit breakers.
"""

from __future__ import annotations

from typing import Callable, Optional, TypeVar

from sentinel_tpu.core import api
from sentinel_tpu.core.errors import BlockError
from sentinel_tpu.metrics.admission_trace import inject_trace_headers
from sentinel_tpu.models import constants as C

T = TypeVar("T")


def _with_trace_headers(kwargs: dict) -> dict:
    """Outbound W3C propagation for kwargs-style clients: when a trace
    is ambient, return kwargs with a COPY of ``headers`` carrying a
    child ``traceparent`` (the caller's mapping is never mutated);
    otherwise return kwargs unchanged."""
    hdrs: dict = {}
    if inject_trace_headers(hdrs) is None:
        return kwargs
    merged = dict(kwargs.get("headers") or {})
    merged.update(hdrs)
    out = dict(kwargs)
    out["headers"] = merged
    return out


def guard_call(resource: str, fn: Callable[..., T], *args, fallback=None, **kwargs) -> T:
    """Run ``fn`` under an OUT entry; trace errors; on block call
    ``fallback(error)`` or raise."""
    try:
        entry = api.entry(resource, entry_type=C.EntryType.OUT)
    except BlockError as e:
        if fallback is not None:
            return fallback(e)
        raise
    try:
        result = fn(*args, **kwargs)
    except BaseException as e:
        entry.set_error(e)
        raise
    finally:
        entry.exit()
    return result


class GuardedClient:
    """Wrap any HTTP-client-like object whose request method is
    ``request(method, url, ...)`` (requests.Session, httpx.Client...)."""

    def __init__(
        self,
        client,
        resource_extractor: Optional[Callable[[str, str], str]] = None,
        fallback: Optional[Callable] = None,
    ) -> None:
        self._client = client
        self._extract = resource_extractor or (lambda method, url: f"{method.upper()}:{url}")
        self._fallback = fallback

    def request(self, method: str, url: str, *args, **kwargs):
        resource = self._extract(method, url)
        return guard_call(
            resource, self._client.request, method, url, *args,
            fallback=self._fallback, **_with_trace_headers(kwargs),
        )

    def get(self, url: str, **kwargs):
        return self.request("GET", url, **kwargs)

    def post(self, url: str, **kwargs):
        return self.request("POST", url, **kwargs)

    def put(self, url: str, **kwargs):
        return self.request("PUT", url, **kwargs)

    def delete(self, url: str, **kwargs):
        return self.request("DELETE", url, **kwargs)

    def patch(self, url: str, **kwargs):
        return self.request("PATCH", url, **kwargs)

    def head(self, url: str, **kwargs):
        return self.request("HEAD", url, **kwargs)

    def options(self, url: str, **kwargs):
        return self.request("OPTIONS", url, **kwargs)

    def __getattr__(self, name):
        return getattr(self._client, name)


async def guard_call_async(
    resource: str, fn: Callable, *args, fallback=None, **kwargs
):
    """Async ``guard_call``: await ``fn`` under an OUT entry; trace
    errors; on block call ``fallback(error)`` (sync or async) or
    raise."""
    import inspect

    try:
        entry = api.entry_async(resource, entry_type=C.EntryType.OUT)
    except BlockError as e:
        if fallback is not None:
            result = fallback(e)
            if inspect.isawaitable(result):
                result = await result
            return result
        raise
    try:
        result = await fn(*args, **kwargs)
    except BaseException as e:
        entry.set_error(e)
        raise
    finally:
        entry.exit()
    return result


def _default_extractor(method: str, url: str) -> str:
    # Query string dropped so resources stay bounded (one node per
    # endpoint, not per query).
    return f"{method.upper()}:{str(url).split('?', 1)[0]}"


class GuardedAsyncClient:
    """Async twin of :class:`GuardedClient` for clients whose request
    method is an ``async request(method, url, ...)``
    (httpx.AsyncClient, aiohttp.ClientSession...)."""

    def __init__(
        self,
        client,
        resource_extractor: Optional[Callable[[str, str], str]] = None,
        fallback: Optional[Callable] = None,
    ) -> None:
        self._client = client
        self._extract = resource_extractor or _default_extractor
        self._fallback = fallback

    async def request(self, method: str, url: str, *args, **kwargs):
        resource = self._extract(method, str(url))
        return await guard_call_async(
            resource, self._client.request, method, url, *args,
            fallback=self._fallback, **_with_trace_headers(kwargs),
        )

    async def get(self, url: str, **kwargs):
        return await self.request("GET", url, **kwargs)

    async def post(self, url: str, **kwargs):
        return await self.request("POST", url, **kwargs)

    async def put(self, url: str, **kwargs):
        return await self.request("PUT", url, **kwargs)

    async def delete(self, url: str, **kwargs):
        return await self.request("DELETE", url, **kwargs)

    async def patch(self, url: str, **kwargs):
        return await self.request("PATCH", url, **kwargs)

    async def head(self, url: str, **kwargs):
        return await self.request("HEAD", url, **kwargs)

    async def options(self, url: str, **kwargs):
        return await self.request("OPTIONS", url, **kwargs)

    def __getattr__(self, name):
        return getattr(self._client, name)
