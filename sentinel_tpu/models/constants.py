"""Rule and framework constants.

Values mirror the reference exactly so serialized rules interoperate
(reference: sentinel-core/.../slots/block/RuleConstant.java:26-66,
Constants.java:36-66, EntryType.java).
"""

from __future__ import annotations

import enum

# --- flow rule grades (RuleConstant.java:27-28) ---
FLOW_GRADE_THREAD = 0
FLOW_GRADE_QPS = 1

# --- degrade grades (RuleConstant.java:30-37) ---
DEGRADE_GRADE_RT = 0
DEGRADE_GRADE_EXCEPTION_RATIO = 1
DEGRADE_GRADE_EXCEPTION_COUNT = 2

DEGRADE_DEFAULT_SLOW_REQUEST_AMOUNT = 5
DEGRADE_DEFAULT_MIN_REQUEST_AMOUNT = 5

# --- authority (RuleConstant.java:42-43) ---
AUTHORITY_WHITE = 0
AUTHORITY_BLACK = 1

# --- flow relation strategy (RuleConstant.java:45-47) ---
STRATEGY_DIRECT = 0
STRATEGY_RELATE = 1
STRATEGY_CHAIN = 2

# --- traffic shaping behavior (RuleConstant.java:49-52) ---
CONTROL_BEHAVIOR_DEFAULT = 0
CONTROL_BEHAVIOR_WARM_UP = 1
CONTROL_BEHAVIOR_RATE_LIMITER = 2
CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER = 3

# --- cluster acquire-refuse / resource-timeout strategies (RuleConstant.java:54-61) ---
DEFAULT_BLOCK_STRATEGY = 0
TRY_AGAIN_BLOCK_STRATEGY = 1
TRY_UNTIL_SUCCESS_BLOCK_STRATEGY = 2
DEFAULT_RESOURCE_TIMEOUT_STRATEGY = 0
RELEASE_RESOURCE_TIMEOUT_STRATEGY = 1
KEEP_RESOURCE_TIMEOUT_STRATEGY = 2

LIMIT_APP_DEFAULT = "default"
LIMIT_APP_OTHER = "other"

# --- statistic defaults (RuleConstant.java:65-66, StatisticNode.java:90-112) ---
DEFAULT_SAMPLE_COUNT = 2
DEFAULT_WINDOW_INTERVAL_MS = 1000
MINUTE_SAMPLE_COUNT = 60
MINUTE_INTERVAL_MS = 60_000

# --- scale caps (Constants.java:36-37) ---
MAX_CONTEXT_NAME_SIZE = 2000
MAX_SLOT_CHAIN_SIZE = 6000

# --- well-known names (Constants.java:41-66) ---
ROOT_ID = "machine-root"
CONTEXT_DEFAULT_NAME = "sentinel_default_context"
TOTAL_IN_RESOURCE_NAME = "__total_inbound_traffic__"
SYSTEM_LOAD_RESOURCE_NAME = "__system_load__"
CPU_USAGE_RESOURCE_NAME = "__cpu_usage__"

# Reference: Constants.java TIME_DROP_VALVE = 4900 (max recorded RT).
DEFAULT_STATISTIC_MAX_RT = 4900

# --- hot-param defaults (ParamFlowRule.java / ParameterMetric.java:37-38) ---
PARAM_FLOW_DEFAULT_CACHE_SIZE = 4000


class EntryType(enum.IntEnum):
    """Resource invocation direction (reference: EntryType.java).

    Only ``IN`` traffic is guarded by system rules
    (SystemSlot/SystemRuleManager.checkSystem).
    """

    IN = 0
    OUT = 1


class ResourceType(enum.IntEnum):
    """Classification of resources (reference: ResourceTypeConstants.java)."""

    COMMON = 0
    WEB = 1
    RPC = 2
    GATEWAY = 3
    DB_SQL = 4


# --- cluster constants (sentinel-cluster-common-default/.../ClusterConstants.java:24-41) ---
MSG_TYPE_PING = 0
MSG_TYPE_FLOW = 1
MSG_TYPE_PARAM_FLOW = 2
MSG_TYPE_CONCURRENT_FLOW_ACQUIRE = 3
MSG_TYPE_CONCURRENT_FLOW_RELEASE = 4
# This framework's batched extension (not in the reference codec): one
# frame carries a whole admission window's worth of token requests.
MSG_TYPE_FLOW_BATCH = 16
MSG_TYPE_PARAM_FLOW_BATCH = 17
# Sketch gossip (this framework's own): engines exchange count-min
# arrays + candidate tables so heavy hitters are detected fleet-wide.
MSG_TYPE_SKETCH_PUSH = 18
MSG_TYPE_SKETCH_MERGED = 19
# Shard introspection (this framework's own): one round trip returns
# the server's work clocks / stat-log counters as a JSON snapshot so
# per-shard state is readable outside the bench harness.
MSG_TYPE_STATS = 20

FLOW_THRESHOLD_AVG_LOCAL = 0
FLOW_THRESHOLD_GLOBAL = 1

CLUSTER_MODE_CLIENT = 0
CLUSTER_MODE_SERVER = 1
CLUSTER_MODE_NOT_STARTED = -1


class TokenResultStatus(enum.IntEnum):
    """Cluster token request outcome (reference: sentinel-core/.../cluster/
    TokenResultStatus.java)."""

    BAD_REQUEST = -4
    TOO_MANY_REQUEST = -2
    FAIL = -1
    OK = 0
    BLOCKED = 1
    SHOULD_WAIT = 2
    NO_RULE_EXISTS = 3
    NO_REF_RULE_EXISTS = 4
    NOT_AVAILABLE = 5
    RELEASE_OK = 6
    ALREADY_RELEASE = 7
