"""Rule dataclasses.

Field names/defaults mirror the reference rule beans so JSON rule files
interoperate (reference: FlowRule.java:52-90, DegradeRule.java:59-84,
SystemRule.java:43-50, AuthorityRule.java, ParamFlowRule.java:45-83,
ClusterFlowConfig.java:34-51). Rules are *immutable values*; compilation
into device tensors happens in rule managers (double-buffered swap, the
analog of the reference's volatile map swap in FlowRuleManager.java:159).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.models import constants as C


def _freeze(obj: Any) -> Any:
    if isinstance(obj, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in obj.items()))
    if isinstance(obj, (list, set)):
        return tuple(_freeze(v) for v in obj)
    return obj


@dataclass(frozen=True)
class AbstractRule:
    """Common rule base (reference: slots/block/AbstractRule.java)."""

    resource: str = ""
    limit_app: str = C.LIMIT_APP_DEFAULT

    def is_valid(self) -> bool:
        return bool(self.resource and self.resource.strip())

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclass(frozen=True)
class ClusterFlowConfig:
    """Cluster-mode per-rule config (reference: ClusterFlowConfig.java:34-51)."""

    flow_id: Optional[int] = None
    threshold_type: int = C.FLOW_THRESHOLD_AVG_LOCAL
    fallback_to_local_when_fail: bool = True
    sample_count: int = 10  # ClusterRuleConstant.DEFAULT_CLUSTER_SAMPLE_COUNT
    window_interval_ms: int = C.DEFAULT_WINDOW_INTERVAL_MS
    acquire_refuse_strategy: int = C.DEFAULT_BLOCK_STRATEGY
    # Concurrent (held-token) mode timeouts, ms (ClusterFlowConfig.java:
    # resourceTimeout / clientOfflineTime defaults).
    resource_timeout: int = 2000
    client_offline_time: int = 2000


@dataclass(frozen=True)
class FlowRule(AbstractRule):
    """Flow-control rule (reference: FlowRule.java:52-90).

    grade: FLOW_GRADE_QPS (default) or FLOW_GRADE_THREAD.
    strategy: DIRECT / RELATE(ref_resource) / CHAIN(entrance context).
    control_behavior: DEFAULT / WARM_UP / RATE_LIMITER / WARM_UP_RATE_LIMITER.
    """

    grade: int = C.FLOW_GRADE_QPS
    count: float = 0.0
    strategy: int = C.STRATEGY_DIRECT
    ref_resource: Optional[str] = None
    control_behavior: int = C.CONTROL_BEHAVIOR_DEFAULT
    warm_up_period_sec: int = 10
    max_queueing_time_ms: int = 500
    cluster_mode: bool = False
    cluster_config: Optional[ClusterFlowConfig] = None
    # True only on rules the sketch tier synthesized for a promoted
    # unconfigured resource (runtime/sketch.py). A user rule reload
    # never carries it, so the tier can tell its own synthetics apart
    # when rebuilding the rule set on promotion/demotion.
    from_sketch: bool = False

    def is_valid(self) -> bool:
        # Reference: FlowRuleUtil.isValidRule — non-null resource, count >= 0,
        # valid strategy/behavior; RELATE/CHAIN need refResource.
        if not super().is_valid() or self.count < 0:
            return False
        if self.grade not in (C.FLOW_GRADE_THREAD, C.FLOW_GRADE_QPS):
            return False
        if self.strategy not in (C.STRATEGY_DIRECT, C.STRATEGY_RELATE, C.STRATEGY_CHAIN):
            return False
        if self.strategy != C.STRATEGY_DIRECT and not self.ref_resource:
            return False
        if self.control_behavior not in (
            C.CONTROL_BEHAVIOR_DEFAULT,
            C.CONTROL_BEHAVIOR_WARM_UP,
            C.CONTROL_BEHAVIOR_RATE_LIMITER,
            C.CONTROL_BEHAVIOR_WARM_UP_RATE_LIMITER,
        ):
            return False
        if self.cluster_mode and (self.cluster_config is None or self.cluster_config.flow_id is None):
            return False
        return True


@dataclass(frozen=True)
class DegradeRule(AbstractRule):
    """Circuit-breaking rule (reference: DegradeRule.java:59-84).

    grade RT → slow-call-ratio breaker with ``count`` = max RT (ms) and
    ``slow_ratio_threshold``; grade EXCEPTION_RATIO / EXCEPTION_COUNT →
    exception breaker. ``time_window`` is the recovery (OPEN) timeout in
    seconds; ``stat_interval_ms`` the breaker's own sliding window.
    """

    grade: int = C.DEGRADE_GRADE_RT
    count: float = 0.0
    time_window: int = 0
    min_request_amount: int = C.DEGRADE_DEFAULT_MIN_REQUEST_AMOUNT
    slow_ratio_threshold: float = 1.0
    stat_interval_ms: int = 1000

    def is_valid(self) -> bool:
        # Reference: DegradeRuleManager.isValidRule.
        if not super().is_valid() or self.count < 0 or self.time_window <= 0:
            return False
        if self.min_request_amount <= 0 or self.stat_interval_ms <= 0:
            return False
        if self.grade == C.DEGRADE_GRADE_RT:
            return self.slow_ratio_threshold >= 0
        if self.grade == C.DEGRADE_GRADE_EXCEPTION_RATIO:
            return 0 <= self.count <= 1
        return self.grade == C.DEGRADE_GRADE_EXCEPTION_COUNT


@dataclass(frozen=True)
class SystemRule(AbstractRule):
    """Global inbound protection thresholds (reference: SystemRule.java:43-50).

    -1 disables a dimension; the effective system config is the min over
    all loaded rules per dimension (SystemRuleManager.loadSystemConf).
    """

    highest_system_load: float = -1.0
    highest_cpu_usage: float = -1.0
    qps: float = -1.0
    avg_rt: int = -1
    max_thread: int = -1


@dataclass(frozen=True)
class AuthorityRule(AbstractRule):
    """Origin white/black list (reference: authority/AuthorityRule.java).

    ``limit_app`` holds the comma-separated origin list, like the
    reference (AuthorityRuleChecker.java:31-60).
    """

    strategy: int = C.AUTHORITY_WHITE

    def is_valid(self) -> bool:
        return super().is_valid() and bool(self.limit_app and self.limit_app.strip())


@dataclass(frozen=True)
class ParamFlowItem:
    """Per-value threshold exception (reference: ParamFlowItem.java)."""

    object: str = ""
    count: int = 0
    class_type: str = "java.lang.String"


@dataclass(frozen=True)
class ParamFlowRule(AbstractRule):
    """Hot-parameter rule (reference: ParamFlowRule.java:45-83)."""

    grade: int = C.FLOW_GRADE_QPS
    param_idx: Optional[int] = None
    count: float = 0.0
    control_behavior: int = C.CONTROL_BEHAVIOR_DEFAULT
    max_queueing_time_ms: int = 0
    burst_count: int = 0
    duration_in_sec: int = 1
    param_flow_item_list: Tuple[ParamFlowItem, ...] = field(default_factory=tuple)
    # Sketch-native mode (runtime/sketch.py): cold values are tracked
    # only by the fixed-size device sketch and PASS without a dense
    # row; sketch-detected heavy hitters are promoted into exact dense
    # rows (threshold = this rule's count, hot items still override)
    # and demoted back on decay. With the sketch tier disabled the
    # flag is ignored and the rule dense-tracks every value as before.
    sketch_mode: bool = False
    cluster_mode: bool = False
    # ParamFlowClusterConfig (reference: ParamFlowClusterConfig.java:30-49)
    # shares ClusterFlowConfig's shape: flowId, thresholdType,
    # fallbackToLocalWhenFail, sampleCount, windowIntervalMs.
    cluster_config: Optional[ClusterFlowConfig] = None

    def __post_init__(self) -> None:
        if isinstance(self.param_flow_item_list, list):
            object.__setattr__(self, "param_flow_item_list", tuple(self.param_flow_item_list))

    def is_valid(self) -> bool:
        # Reference: ParamFlowRuleUtil.isValidRule.
        if self.cluster_mode and (
            self.cluster_config is None or self.cluster_config.flow_id is None
        ):
            return False
        return (
            super().is_valid()
            and self.count >= 0
            and self.grade in (C.FLOW_GRADE_THREAD, C.FLOW_GRADE_QPS)
            and self.param_idx is not None
            and self.duration_in_sec > 0
        )


def rules_from_json(
    data: Sequence[Dict[str, Any]], rule_cls: type, aliases: Optional[Dict[str, str]] = None
) -> List[Any]:
    """Build rules from JSON-ish dicts, accepting both this framework's
    snake_case and the reference's camelCase field names (so rule files
    written for the Java dashboard load unchanged)."""

    def snake(name: str) -> str:
        out = []
        for ch in name:
            if ch.isupper():
                out.append("_")
                out.append(ch.lower())
            else:
                out.append(ch)
        return "".join(out)

    field_names = {f.name for f in dataclasses.fields(rule_cls)}
    result = []
    for item in data:
        kwargs: Dict[str, Any] = {}
        for k, v in item.items():
            key = snake(k)
            if aliases and key in aliases:
                key = aliases[key]
            if key in field_names:
                if key == "cluster_config" and isinstance(v, dict):
                    v = ClusterFlowConfig(
                        **{
                            snake(ck): cv
                            for ck, cv in v.items()
                            if snake(ck) in {f.name for f in dataclasses.fields(ClusterFlowConfig)}
                        }
                    )
                if key == "param_flow_item_list" and isinstance(v, list):
                    v = tuple(
                        ParamFlowItem(
                            object=str(it.get("object", "")),
                            count=int(it.get("count", 0)),
                            class_type=str(it.get("classType", it.get("class_type", "java.lang.String"))),
                        )
                        for it in v
                    )
                kwargs[key] = v
        result.append(rule_cls(**kwargs))
    return result
