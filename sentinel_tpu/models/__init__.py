"""Rule models and constants (the framework's data model layer).

Equivalent of the reference's rule classes (reference: sentinel-core/.../
slots/block/flow/FlowRule.java:52-90, degrade/DegradeRule.java:59-84,
system/SystemRule.java:43-50, authority/AuthorityRule.java and
sentinel-extension/sentinel-parameter-flow-control/.../ParamFlowRule.java)
expressed as frozen dataclasses. Rule *compilation* to SoA device tensors
lives in :mod:`sentinel_tpu.rules`.
"""

from sentinel_tpu.models import constants
from sentinel_tpu.models.rules import (
    AbstractRule,
    FlowRule,
    ClusterFlowConfig,
    DegradeRule,
    SystemRule,
    AuthorityRule,
    ParamFlowRule,
    ParamFlowItem,
)

__all__ = [
    "constants",
    "AbstractRule",
    "FlowRule",
    "ClusterFlowConfig",
    "DegradeRule",
    "SystemRule",
    "AuthorityRule",
    "ParamFlowRule",
    "ParamFlowItem",
]
