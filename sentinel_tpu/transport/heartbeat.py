"""Heartbeat to the dashboard.

Reference: SimpleHttpHeartbeatSender posts to the dashboard's
``/registry/machine`` every ~10 s with app / ip / port / version
(sentinel-transport-simple-http/.../heartbeat/
SimpleHttpHeartbeatSender.java:36-65); the dashboard feeds these into
its machine discovery (SimpleMachineDiscovery).
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.parse
import urllib.request
from typing import Optional

from sentinel_tpu.utils.config import config
from sentinel_tpu.utils.record_log import record_log
from sentinel_tpu.version import __version__


def local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class HeartbeatSender:
    def __init__(
        self,
        dashboard_addr: str,  # "host:port"
        command_port: int,
        app_name: Optional[str] = None,
        interval_sec: float = 10.0,
    ) -> None:
        self.dashboard_addr = dashboard_addr
        self.command_port = command_port
        self.app_name = app_name or config.app_name
        self.interval = interval_sec
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def heartbeat_once(self) -> bool:
        params = urllib.parse.urlencode(
            {
                "app": self.app_name,
                "app_type": config.get_int(config.APP_TYPE, 0),
                "version": __version__,
                "v": __version__,
                "hostname": socket.gethostname(),
                "ip": local_ip(),
                "port": self.command_port,
                "pid": 0,
            }
        )
        url = f"http://{self.dashboard_addr}/registry/machine?{params}"
        try:
            with urllib.request.urlopen(url, timeout=3) as resp:
                return 200 <= resp.status < 300
        except OSError:
            return False

    def start(self) -> "HeartbeatSender":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sentinel-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            ok = self.heartbeat_once()
            if not ok:
                record_log.warn("[HeartbeatSender] heartbeat to %s failed", self.dashboard_addr)
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
