"""Heartbeat to the dashboard.

Reference: SimpleHttpHeartbeatSender posts to the dashboard's
``/registry/machine`` every ~10 s with app / ip / port / version
(sentinel-transport-simple-http/.../heartbeat/
SimpleHttpHeartbeatSender.java:36-65); the dashboard feeds these into
its machine discovery (SimpleMachineDiscovery).

Beyond the reference's app/ip/port/version tuple, the heartbeat
carries the machine's admission-plane health so the dashboard's
machine table shows fleet state at a glance without a command-API
round-trip per machine:

* ``health``     — the failover state machine (HEALTHY / DEGRADED /
  RECOVERING; runtime/failover.py);
* ``spec_enabled`` / ``spec_suspended`` — speculative fast tier armed,
  and whether the drift valve currently has it suspended
  (runtime/speculative.py);
* ``ingest_armed`` / ``shed_total`` / ``shedding`` — ingest valve
  state, cumulative shed count, and whether sheds happened since the
  previous heartbeat (runtime/ingest.py).

The fields ride the same GET query; a dashboard that ignores them
(the seed dashboard did) keeps working unchanged.
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.parse
import urllib.request
from typing import Optional

from sentinel_tpu.utils.config import config
from sentinel_tpu.utils.record_log import record_log
from sentinel_tpu.version import __version__


def local_ip() -> str:
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"


class HeartbeatSender:
    def __init__(
        self,
        dashboard_addr: str,  # "host:port"
        command_port: int,
        app_name: Optional[str] = None,
        interval_sec: float = 10.0,
        engine=None,
    ) -> None:
        self.dashboard_addr = dashboard_addr
        self.command_port = command_port
        self.app_name = app_name or config.app_name
        self.interval = interval_sec
        # The engine whose health this heartbeat reports. None (the
        # seed signature) falls back to the process-global engine IF
        # one already exists — a heartbeat must never be the thing
        # that constructs the engine.
        self._engine = engine
        # Cumulative shed count as of the last DELIVERED heartbeat:
        # "shedding" means sheds happened since the dashboard last
        # heard from us. The baseline advances only on a successful
        # send (heartbeat_once), so a failed POST can't swallow a
        # shedding episode's edge.
        self._last_shed_total = 0
        self._pending_shed_total = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _health_params(self) -> dict:
        """The admission-plane health fields (empty dict when no
        engine exists yet — the heartbeat never constructs one)."""
        engine = self._engine
        if engine is None:
            from sentinel_tpu.core.api import peek_engine

            engine = peek_engine()
        if engine is None:
            return {}
        spec = engine.speculative
        valve = engine.ingest
        shed_total = (
            valve.counters["shed_entries"] + valve.counters["shed_rows"]
        )
        if shed_total < self._last_shed_total:
            # The counters went backwards — Engine.reset() zeroed the
            # valve. Re-anchor, or the edge detector stays blind until
            # cumulative sheds re-exceed the pre-reset baseline.
            self._last_shed_total = 0
        shedding = shed_total > self._last_shed_total
        self._pending_shed_total = shed_total
        # Engine lifecycle provenance (PR 15 exposed these in
        # Prometheus; riding the heartbeat lets the dashboard's
        # Machines table flag a recently hot-restarted engine without
        # a scrape round-trip per machine). epoch 1 = first boot of
        # the shared rings; restarts = epoch - 1, matching the
        # sentinel_engine_restarts_total definition.
        plane = getattr(engine, "ipc_plane", None)
        epoch = plane.engine_epoch if plane is not None else 1
        workers = plane.live_workers() if plane is not None else 0
        return {
            "health": engine.failover.state,
            "spec_enabled": int(spec.enabled),
            "spec_suspended": int(spec.enabled and spec.suspended),
            "ingest_armed": int(valve.armed),
            "shed_total": shed_total,
            "shedding": int(shedding),
            "engine_epoch": epoch,
            "restarts_total": max(0, epoch - 1),
            "workers": workers,
        }

    def heartbeat_once(self) -> bool:
        fields = {
            "app": self.app_name,
            "app_type": config.get_int(config.APP_TYPE, 0),
            "version": __version__,
            "v": __version__,
            "hostname": socket.gethostname(),
            "ip": local_ip(),
            "port": self.command_port,
            "pid": 0,
        }
        fields.update(self._health_params())
        params = urllib.parse.urlencode(fields)
        url = f"http://{self.dashboard_addr}/registry/machine?{params}"
        try:
            with urllib.request.urlopen(url, timeout=3) as resp:
                ok = 200 <= resp.status < 300
        except OSError:
            return False
        if ok:
            # The dashboard has seen this interval's shedding flag:
            # only now does the edge detector's baseline advance.
            self._last_shed_total = self._pending_shed_total
        return ok

    def start(self) -> "HeartbeatSender":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sentinel-heartbeat", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            ok = self.heartbeat_once()
            if not ok:
                record_log.warn("[HeartbeatSender] heartbeat to %s failed", self.dashboard_addr)
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
