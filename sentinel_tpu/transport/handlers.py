"""Built-in command handlers.

Reference: the ~18 handlers in sentinel-transport-common/.../command/
handler/ — ModifyRulesCommandHandler (setRules),
FetchActiveRuleCommandHandler (getRules), SendMetricCommandHandler
(metric by time range), fetch tree / clusterNode / systemStatus,
on/off switch, cluster-mode handlers — plus the param-flow handlers
from sentinel-parameter-flow-control.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import List

from sentinel_tpu.metrics.metric_log import MetricSearcher
from sentinel_tpu.models.rules import (
    AuthorityRule,
    DegradeRule,
    FlowRule,
    ParamFlowRule,
    SystemRule,
    rules_from_json,
)
from sentinel_tpu.transport.command_center import (
    CommandRequest,
    CommandResponse,
    all_commands,
    command_mapping,
)
from sentinel_tpu.utils.config import config
from sentinel_tpu.version import __version__


def _engine():
    from sentinel_tpu.core.api import get_engine

    return get_engine()


def _managers():
    from sentinel_tpu.rules.authority_manager import authority_rule_manager
    from sentinel_tpu.rules.degrade_manager import degrade_rule_manager
    from sentinel_tpu.rules.flow_manager import flow_rule_manager
    from sentinel_tpu.rules.param_manager import param_flow_rule_manager
    from sentinel_tpu.rules.system_manager import system_rule_manager

    return {
        "flow": (flow_rule_manager, FlowRule),
        "degrade": (degrade_rule_manager, DegradeRule),
        "system": (system_rule_manager, SystemRule),
        "authority": (authority_rule_manager, AuthorityRule),
        "paramFlow": (param_flow_rule_manager, ParamFlowRule),
    }


# Upper bound on ?spans= / ?n= style list params: a command response
# is one JSON blob — an absurd count must clamp, not OOM the center.
_MAX_LIST_PARAM = 65536


def _count_param(req: CommandRequest, name: str, default: int = 0):
    """Validated non-negative bounded int query param, shared by the
    ``telemetry`` (?spans=) and ``traces`` (?n=) commands: returns
    ``(value, None)`` or ``(None, failure_response)``. Negative values
    are rejected — ``int("-5")`` parses fine but would silently slice
    a ring from the wrong end."""
    raw = req.params.get(name)
    if raw is None:
        return default, None
    try:
        v = int(raw)
    except ValueError:
        return None, CommandResponse.of_failure(f"invalid {name} count")
    if v < 0:
        return None, CommandResponse.of_failure(f"invalid {name} count")
    return min(v, _MAX_LIST_PARAM), None


def _camel(obj: dict) -> dict:
    def cc(k: str) -> str:
        parts = k.split("_")
        return parts[0] + "".join(p.title() for p in parts[1:])

    return {cc(k): v for k, v in obj.items() if v is not None}


def _rules_json(rules: List) -> str:
    return json.dumps([_camel(dataclasses.asdict(r)) for r in rules])


@command_mapping("version", "get sentinel version")
def version_handler(req: CommandRequest) -> CommandResponse:
    return CommandResponse.of_success(__version__)


@command_mapping("api", "list available commands")
def api_handler(req: CommandRequest) -> CommandResponse:
    return CommandResponse.of_json(all_commands())


@command_mapping("basicInfo", "basic machine/app info")
def basic_info_handler(req: CommandRequest) -> CommandResponse:
    return CommandResponse.of_json(
        {
            "appName": config.app_name,
            "appType": config.get_int(config.APP_TYPE, 0),
            "version": __version__,
            "pid": os.getpid(),
            "currentTime": int(time.time() * 1000),
        }
    )


@command_mapping("getRules", "get rules by type: flow|degrade|system|authority|paramFlow")
def get_rules_handler(req: CommandRequest) -> CommandResponse:
    kind = req.params.get("type", "flow")
    entry = _managers().get(kind)
    if entry is None:
        return CommandResponse.of_failure(f"invalid type: {kind}")
    mgr, _cls = entry
    return CommandResponse.of_success(_rules_json(mgr.get_rules()), json_body=True)


@command_mapping("setRules", "set rules: type=...&data=<json list>")
def set_rules_handler(req: CommandRequest) -> CommandResponse:
    kind = req.params.get("type", "flow")
    data = req.params.get("data", "[]")
    entry = _managers().get(kind)
    if entry is None:
        return CommandResponse.of_failure(f"invalid type: {kind}")
    mgr, cls = entry
    try:
        rules = rules_from_json(json.loads(data), cls)
    except (ValueError, TypeError) as e:
        return CommandResponse.of_failure(f"bad rule payload: {e}")
    mgr.load_rules(rules)
    # Push-persistence when a writable datasource is registered
    # (WritableDataSourceRegistry / ModifyRulesCommandHandler).
    from sentinel_tpu.datasource import WritableDataSourceRegistry

    WritableDataSourceRegistry.try_write(kind, rules)
    return CommandResponse.of_success("success")


@command_mapping("metric", "metric log by time range: startTime&endTime[&identity]")
def metric_handler(req: CommandRequest) -> CommandResponse:
    try:
        begin = int(req.params.get("startTime", 0))
        end = int(req.params.get("endTime", 2**62))
    except ValueError:
        return CommandResponse.of_failure("invalid time range")
    resource = req.params.get("identity")
    searcher = MetricSearcher()
    lines = searcher.find(begin, end, resource)
    return CommandResponse.of_success("\n".join(n.to_line() for n in lines))


@command_mapping("tree", "node tree with per-node statistics")
def tree_handler(req: CommandRequest) -> CommandResponse:
    engine = _engine()
    engine.flush()
    out = []
    pairs = [("machine-root", engine.nodes.entry_node_row)] + engine.nodes.resources()
    by_row = engine.rows_stats([row for _, row in pairs])
    for name, row in pairs:
        s = by_row[row]
        out.append(
            f"{name}: thread={s['cur_thread_num']} pass={s['pass_qps']:.0f} "
            f"block={s['block_qps']:.0f} success={s['success_qps']:.0f} "
            f"exception={s['exception_qps']:.0f} rt={s['avg_rt']:.1f}"
        )
    return CommandResponse.of_success("\n".join(out))


@command_mapping("clusterNode", "cluster node statistics as JSON")
def cluster_node_handler(req: CommandRequest) -> CommandResponse:
    engine = _engine()
    engine.flush()
    out = []
    pairs = engine.nodes.resources()
    by_row = engine.rows_stats([row for _, row in pairs])
    for name, row in pairs:
        s = by_row[row]
        out.append({"resourceName": name, **{k: float(v) for k, v in s.items()}})
    return CommandResponse.of_json(out)


@command_mapping("origin", "per-origin statistics for a resource: id=<resource>")
def origin_handler(req: CommandRequest) -> CommandResponse:
    engine = _engine()
    resource = req.params.get("id", "")
    crow = engine.nodes.lookup_cluster_row(resource)
    if crow is None:
        return CommandResponse.of_failure(f"unknown resource: {resource}")
    engine.flush()
    out = []
    origin_pairs = list(engine.nodes.origin_rows.get(crow, {}).items())
    by_row = engine.rows_stats([row for _, row in origin_pairs])
    for origin, row in origin_pairs:
        s = by_row[row]
        out.append({"origin": origin, **{k: float(v) for k, v in s.items()}})
    return CommandResponse.of_json(out)


@command_mapping("systemStatus", "system protection status")
def system_status_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.utils.system_status import sampler

    engine = _engine()
    g = engine.entry_node_stats()
    return CommandResponse.of_json(
        {
            "qps": g["pass_qps"],
            "thread": g["cur_thread_num"],
            "rt": g["avg_rt"],
            "load": sampler.load,
            "cpu": sampler.cpu,
        }
    )


@command_mapping("getSwitch", "get the global protection switch")
def get_switch_handler(req: CommandRequest) -> CommandResponse:
    return CommandResponse.of_success(str(_engine().enabled).lower())


@command_mapping("setSwitch", "set the global protection switch: value=true|false")
def set_switch_handler(req: CommandRequest) -> CommandResponse:
    value = req.params.get("value", "").lower()
    if value not in ("true", "false"):
        return CommandResponse.of_failure("invalid value")
    _engine().enabled = value == "true"
    return CommandResponse.of_success("success")


@command_mapping("getClusterMode", "cluster mode state")
def get_cluster_mode_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.state import ClusterStateManager

    return CommandResponse.of_json({"mode": ClusterStateManager.get_mode()})


@command_mapping("setClusterMode", "set cluster mode: mode=0(client)|1(server)|-1(off)")
def set_cluster_mode_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.state import ClusterStateManager

    try:
        mode = int(req.params.get("mode", "-1"))
    except ValueError:
        return CommandResponse.of_failure("invalid mode")
    ClusterStateManager.apply_state(mode)
    return CommandResponse.of_success("success")


@command_mapping("cluster/server/flowRules", "cluster server flow rules: namespace=")
def cluster_server_flow_rules_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.flow_rules import cluster_flow_rule_manager

    ns = req.params.get("namespace", "default")
    with cluster_flow_rule_manager._lock:
        rules = list(cluster_flow_rule_manager._rules.get(ns, {}).values())
    return CommandResponse.of_success(_rules_json(rules), json_body=True)


@command_mapping("cluster/server/modifyFlowRules", "set cluster flow rules: namespace=&data=")
def cluster_server_modify_flow_rules_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.flow_rules import cluster_flow_rule_manager

    ns = req.params.get("namespace", "default")
    try:
        rules = rules_from_json(json.loads(req.params.get("data", "[]")), FlowRule)
    except (ValueError, TypeError) as e:
        return CommandResponse.of_failure(f"bad payload: {e}")
    cluster_flow_rule_manager.load_rules(ns, rules)
    return CommandResponse.of_success("success")


@command_mapping("cluster/server/config", "cluster server config")
def cluster_server_config_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.flow_rules import cluster_server_config_manager

    cfg = cluster_server_config_manager.config
    return CommandResponse.of_json(
        {
            "port": cfg.port,
            "exceedCount": cfg.exceed_count,
            "maxAllowedQps": cfg.max_allowed_qps,
            "namespaces": sorted(cfg.namespaces),
        }
    )


@command_mapping("cluster/server/stats", "token-server per-flowId qps/concurrency")
def cluster_server_stats_handler(req: CommandRequest) -> CommandResponse:
    """The dashboard cluster screen's data: per-flowId granted QPS +
    held concurrency from the embedded token server (reference analog:
    ClusterServerStatLogUtil counters surfaced to the console)."""
    from sentinel_tpu.cluster.state import (
        ClusterStateManager,
        EmbeddedClusterTokenServerProvider,
    )

    server = EmbeddedClusterTokenServerProvider.get_server()
    service = getattr(server, "service", None)
    flows = service.flow_stats() if hasattr(service, "flow_stats") else []
    held = 0
    concurrent = getattr(service, "concurrent", None)
    if concurrent is not None:
        held = concurrent.held_tokens()
    connections = getattr(service, "connections", None)
    by_namespace = connections.snapshot() if connections is not None else {}
    connected = (
        connections.total()
        if connections is not None
        else getattr(service, "connected_count", 0)
    )
    return CommandResponse.of_json(
        {
            "mode": ClusterStateManager.get_mode(),
            "port": getattr(server, "port", None) if server is not None else None,
            "connectedCount": connected,
            "connectionGroups": by_namespace,
            "heldTokens": held,
            "flows": flows,
        }
    )


@command_mapping("cluster/client/config", "cluster client config (server address)")
def cluster_client_config_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.state import ClusterClientConfigManager

    return CommandResponse.of_json(ClusterClientConfigManager.snapshot())


@command_mapping(
    "cluster/client/modifyConfig",
    "point this client at a token server: "
    "serverHost=&serverPort=[&requestTimeout=][&namespace=]",
)
def cluster_client_modify_config_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.cluster.state import (
        ClusterClientConfigManager,
        ClusterStateManager,
        TokenClientProvider,
    )

    host = req.params.get("serverHost", "")
    try:
        port = int(req.params.get("serverPort", "0"))
        timeout = req.params.get("requestTimeout")
        timeout_ms = int(timeout) if timeout is not None else None
    except ValueError:
        return CommandResponse.of_failure("invalid port/timeout")
    if not host or port <= 0:
        return CommandResponse.of_failure("serverHost and serverPort required")
    ClusterClientConfigManager.apply(
        host, port, timeout_ms, namespace=req.params.get("namespace")
    )
    # Re-point a live client: stop the old one so the next mode apply
    # (or the current client mode) reconnects at the new address.
    client = TokenClientProvider.get_client()
    if client is not None and (
        getattr(client, "host", None) != host
        or getattr(client, "port", None) != port
        or getattr(client, "namespace", None) != ClusterClientConfigManager.namespace
    ):
        try:
            if hasattr(client, "stop"):
                client.stop()
        finally:
            TokenClientProvider.clear()
        if ClusterStateManager.is_client():
            new_client = ClusterClientConfigManager.build_client()
            if new_client is not None:
                TokenClientProvider.register(new_client)
                new_client.start()
    return CommandResponse.of_success("success")


@command_mapping(
    "metrics",
    "Prometheus text-format metrics (JMX exporter analog);"
    " ?format=openmetrics adds admission-trace exemplars",
)
def prometheus_handler(req: CommandRequest) -> CommandResponse:
    from sentinel_tpu.transport.prometheus import (
        OPENMETRICS_CONTENT_TYPE,
        render_metrics,
    )

    # Exemplars are only legal in the OpenMetrics dialect — the classic
    # 0.0.4 text parser rejects a mid-line '#', failing the whole
    # scrape — so the format (and content type) switch together.
    om = req.params.get("format", "").lower() == "openmetrics"
    content_type = (
        OPENMETRICS_CONTENT_TYPE
        if om
        else "text/plain; version=0.0.4; charset=utf-8"
    )
    # Metrics federation: a worker-mode process has NO engine — its
    # scrape is the sentinel_worker_* families (constructing an engine
    # here would defeat worker mode's whole point), while an engine
    # process renders the engine families plus, when a token shard is
    # embedded in-process, the shard's sentinel_cluster_server_* rows.
    from sentinel_tpu.ipc import worker_mode

    wcli = worker_mode.current()
    if wcli is not None:
        from sentinel_tpu.transport.prometheus import render_worker_metrics

        return CommandResponse(
            True, render_worker_metrics(wcli, openmetrics=om), content_type
        )
    text = render_metrics(_engine(), openmetrics=om)
    from sentinel_tpu.cluster.state import EmbeddedClusterTokenServerProvider

    srv = EmbeddedClusterTokenServerProvider.get_server()
    if srv is not None:
        from sentinel_tpu.transport.prometheus import (
            cluster_server_metric_lines,
        )

        extra = "\n".join(
            cluster_server_metric_lines(srv, openmetrics=om)
        ) + "\n"
        if om and text.endswith("# EOF\n"):
            # The OM terminator must stay last.
            text = text[: -len("# EOF\n")] + extra + "# EOF\n"
        else:
            text += extra
    return CommandResponse(True, text, content_type)


@command_mapping(
    "telemetry",
    "engine flight recorder snapshot: spans, histograms, blocked sketch"
    " [?spans=N for the last N ring spans]",
)
def telemetry_handler(req: CommandRequest) -> CommandResponse:
    """The engine-internals view the per-resource commands cannot give:
    flush/drain/e2e latency histograms, pipeline occupancy, arena and
    intern-cache hit rates, coalesced-fetch fallbacks, and the
    blocked-resource heavy-hitter sketch (metrics/telemetry.py)."""
    engine = _engine()
    tele = engine.telemetry
    n_spans, err = _count_param(req, "spans")
    if err is not None:
        return err
    out = tele.snapshot(engine)
    if n_spans > 0:
        out["spans"] = [s.as_dict() for s in tele.spans()[-n_spans:]]
    return CommandResponse.of_json(out)


@command_mapping(
    "health",
    "engine failure-domain state: health machine, degraded counters,"
    " checkpoint age, fallback policy",
)
def health_handler(req: CommandRequest) -> CommandResponse:
    """The failover view (runtime/failover.py): current health state
    (HEALTHY/DEGRADED/RECOVERING), the last fault, transition events,
    degraded-admission counters, checkpoint seq/age and the effective
    per-resource fail-open/fail-closed policy."""
    engine = _engine()
    out = engine.failover.snapshot()
    out["flush_seq"] = engine.flush_seq
    # Hot-restart provenance: which boot of the shared rings this
    # engine is (1 = first boot; see ipc/supervise.py).
    plane = getattr(engine, "ipc_plane", None)
    out["engine_epoch"] = plane.engine_epoch if plane is not None else 1
    return CommandResponse.of_json(out)


@command_mapping(
    "speculative",
    "speculative admission tier: fast-path counters, drift windows,"
    " valve state, mirror snapshot",
)
def speculative_handler(req: CommandRequest) -> CommandResponse:
    """The two-tier admission view (runtime/speculative.py): how many
    verdicts the host fast tier served, how far it drifted from device
    settlement per window (over/under-admits, bucket clamps, gauge
    compensations), whether the drift valve is currently suspending
    speculation, and the live mirror state."""
    engine = _engine()
    out = engine.speculative.snapshot()
    out["health"] = engine.failover.state
    out["flush_seq"] = engine.flush_seq
    return CommandResponse.of_json(out)


@command_mapping(
    "sketch",
    "statistics sketch tier: candidate heavy hitters, promoted keys,"
    " occupancy, estimate-error gauge",
)
def sketch_handler(req: CommandRequest) -> CommandResponse:
    """The unbounded-cardinality view (runtime/sketch.py): what the
    fixed-size on-device count-min/candidate tier currently believes
    the heavy hitters are, which keys hold promoted exact dense rows,
    how full the candidate table runs, and how far the estimates sit
    above the exact host counters — the long-tail complement of the
    per-resource commands, which can only describe keys that HAVE
    dense rows."""
    engine = _engine()
    out = engine.sketch.snapshot()
    out["flush_seq"] = engine.flush_seq
    return CommandResponse.of_json(out)


@command_mapping(
    "capture",
    "black-box flight recorder: segment/counter snapshot;"
    " freeze=<reason> pins the recent segments on demand",
)
def capture_handler(req: CommandRequest) -> CommandResponse:
    """The admission black box (runtime/capture.py): live/frozen
    segment inventory, spill counters and the capture row cursor. With
    ``?freeze=<reason>`` the recent segments are pinned against
    rollover first (an on-demand postmortem — same mechanics as the
    breaker/shed/DEGRADED triggers) and the frozen paths are
    returned."""
    engine = _engine()
    cap = getattr(engine, "capture", None)
    if cap is None:
        return CommandResponse.of_json(
            {"enabled": False, "flush_seq": engine.flush_seq}
        )
    reason = req.params.get("freeze")
    out = {"enabled": True}
    if reason:
        safe = "".join(
            ch for ch in reason[:32] if ch.isalnum() or ch in "-_"
        ) or "manual"
        out["frozen_now"] = [os.path.basename(p) for p in cap.freeze(safe)]
    out.update(cap.snapshot())
    out["flush_seq"] = engine.flush_seq
    return CommandResponse.of_json(out)


@command_mapping(
    "autotune",
    "self-tuning control plane: chosen depth/window, decision log,"
    " param-path cost memo",
)
def autotune_handler(req: CommandRequest) -> CommandResponse:
    """The closed-loop tuning view (runtime/autotune.py): what the
    controller currently holds the pipeline depth and batch window at,
    the bounded decision log (knob, from->to, reason — the convergence
    trajectory), and the shape-bucketed closed-form-vs-scan cost memo
    with per-path sample counts and cost EWMAs."""
    engine = _engine()
    out = engine.autotune.snapshot()
    out["flush_seq"] = engine.flush_seq
    return CommandResponse.of_json(out)


@command_mapping(
    "ipc",
    "multi-process ingest plane: ring occupancy, live workers, frame"
    " counters, intern generation",
)
def ipc_handler(req: CommandRequest) -> CommandResponse:
    """The scale-out front-end view (sentinel_tpu/ipc): whether the
    shared-memory plane is serving, how full the request ring runs,
    which worker slots are attached (with their live-admission ledger
    sizes), and the frame/shed/death counters — the one place that
    tells 'the engine is slow' from 'a worker died and its gauges were
    auto-exited'."""
    engine = _engine()
    plane = getattr(engine, "ipc_plane", None)
    if plane is None:
        return CommandResponse.of_json(
            {"enabled": False, "flush_seq": engine.flush_seq}
        )
    out = plane.snapshot()
    out["flush_seq"] = engine.flush_seq
    return CommandResponse.of_json(out)


@command_mapping(
    "handoff",
    "request a planned engine handoff: drain, final durable spill,"
    " standby takeover (supervised engines only)",
)
def handoff_handler(req: CommandRequest) -> CommandResponse:
    """Operator trigger for the planned live handoff
    (ipc/supervise.py): sets the engine's ``handoff_requested`` event;
    the supervised serve loop drains in-flight flushes, spills a final
    durable checkpoint, publishes the HANDOFF control word and exits
    ``EXIT_HANDOFF`` so the warm standby attaches. On an unsupervised
    engine the event is set but nothing consumes it — the response
    says so instead of pretending a drain happened."""
    engine = _engine()
    evt = getattr(engine, "handoff_requested", None)
    if evt is None:
        return CommandResponse.of_failure("engine has no handoff support")
    supervised = getattr(engine, "ipc_plane", None) is not None
    evt.set()
    return CommandResponse.of_json(
        {"ok": True, "handoff": "requested", "ipc_plane": supervised}
    )


@command_mapping(
    "cluster",
    "batched cluster token plane: client counters, RPC latency,"
    " live leases, per-shard rows, gossip state, window config",
)
def cluster_handler(req: CommandRequest) -> CommandResponse:
    """The cluster token path view (cluster/client.py + shards.py):
    how many token decisions the client served and by which stance
    (batched frame, local lease, FAIL fallback), the RPC round-trip
    summary, and — when a client is live — its connection, intern
    table, lease table and micro-window configuration. A sharded
    client's ``plane_snapshot`` carries per-shard rows (connection,
    leases, honest fallback counters per shard). The ``gossip`` block
    is this engine's sketch-gossip endpoint: origin, peers, wire
    counters and how many remote views the tier holds. Counters are
    process-wide (the ``client_stats``/``gossip_stats`` singletons) so
    the command answers even before a cluster rule ever attached a
    client."""
    from sentinel_tpu.cluster.client import client_stats
    from sentinel_tpu.cluster.gossip import gossip_stats
    from sentinel_tpu.cluster.state import (
        ClusterStateManager,
        TokenClientProvider,
    )

    engine = _engine()
    out = {"mode": ClusterStateManager.get_mode(), "stats": client_stats.snapshot()}
    client = TokenClientProvider.get_client()
    if client is not None and hasattr(client, "plane_snapshot"):
        out["client"] = client.plane_snapshot()
    agent = getattr(engine, "gossip", None)
    if agent is not None:
        out["gossip"] = agent.snapshot()
    else:
        out["gossip"] = {
            "running": False,
            "tier": engine.sketch.gossip_info(),
            "stats": gossip_stats.snapshot(),
        }
    out["flush_seq"] = engine.flush_seq
    return CommandResponse.of_json(out)


@command_mapping(
    "traces",
    "sampled admission trace records: [?n=N][&resource=][&reason=code|name]",
)
def traces_handler(req: CommandRequest) -> CommandResponse:
    """Per-request verdict provenance (metrics/admission_trace.py):
    who was blocked, by which rule family, decided in which flush span,
    carrying which W3C trace id — the request-level complement of the
    ``telemetry`` command's engine view. ``reason`` accepts the numeric
    code or the shared exception-name spelling
    (core/errors.BLOCK_EXC_NAMES, e.g. ``FlowException``)."""
    from sentinel_tpu.core.errors import BLOCK_EXC_NAMES

    engine = _engine()
    tracer = engine.admission_trace
    n, err = _count_param(req, "n")
    if err is not None:
        return err
    resource = req.params.get("resource")
    reason_raw = req.params.get("reason")
    reason = None
    if reason_raw is not None:
        by_name = {v: k for k, v in BLOCK_EXC_NAMES.items()}
        if reason_raw in by_name:
            reason = by_name[reason_raw]
        else:
            try:
                reason = int(reason_raw)
            except ValueError:
                return CommandResponse.of_failure(
                    f"invalid reason: {reason_raw}"
                )
    out = tracer.snapshot()
    out["records"] = [
        r.as_dict()
        for r in tracer.records(n=n or None, resource=resource, reason=reason)
    ]
    return CommandResponse.of_json(out)


@command_mapping(
    "spans",
    "fleet span journal: per-process admission spans"
    " [?n=N last spans][&cat=worker|engine|client|shard][&spill=1]",
)
def spans_handler(req: CommandRequest) -> CommandResponse:
    """The per-process half of the fleet timeline (metrics/spans.py):
    journal state plus the last N buffered spans. ``spill=1`` forces a
    journal-file spill so ``tools/fleetdump.py`` can merge a LIVE
    process without waiting for its close — the command answers with
    the spill path."""
    from sentinel_tpu.metrics.spans import get_journal

    j = get_journal()
    n, err = _count_param(req, "n")
    if err is not None:
        return err
    out = j.snapshot()
    cat = req.params.get("cat") or None
    if n > 0:
        out["spans"] = j.spans(cat=cat)[-n:]
    if req.params.get("spill") in ("1", "true"):
        try:
            out["spilled_to"] = j.spill()
        except OSError as e:
            return CommandResponse.of_failure(f"spill failed: {e}")
    return CommandResponse.of_json(out)
