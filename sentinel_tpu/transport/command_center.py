"""HTTP command center.

Reference: the CommandCenter SPI + CommandHandler/@CommandMapping
discovery (sentinel-transport-common/.../command/CommandHandler.java,
annotation/CommandMapping.java, CommandHandlerProvider) served over a
minimal HTTP endpoint (sentinel-transport-simple-http/.../
SimpleHttpCommandCenter.java:48, http/HttpEventTask.java). Handlers are
registered with :func:`command_mapping` and dispatched by URL path;
both GET query params and POST form bodies populate the request.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, NamedTuple, Optional
from urllib.parse import parse_qsl, urlparse

from sentinel_tpu.utils.record_log import record_log


class CommandRequest(NamedTuple):
    path: str
    params: Dict[str, str]
    body: str


class CommandResponse(NamedTuple):
    success: bool
    result: str
    content_type: str = "text/plain; charset=utf-8"

    @classmethod
    def of_success(cls, result: str, json_body: bool = False) -> "CommandResponse":
        return cls(True, result, "application/json" if json_body else "text/plain; charset=utf-8")

    @classmethod
    def of_json(cls, obj) -> "CommandResponse":
        return cls(True, json.dumps(obj), "application/json")

    @classmethod
    def of_failure(cls, msg: str) -> "CommandResponse":
        return cls(False, msg)


_handlers: Dict[str, Callable[[CommandRequest], CommandResponse]] = {}
_descriptions: Dict[str, str] = {}


def command_mapping(name: str, desc: str = ""):
    """@CommandMapping equivalent — registers a handler under /name."""

    def deco(fn):
        _handlers[name] = fn
        _descriptions[name] = desc
        return fn

    return deco


def get_handler(name: str):
    return _handlers.get(name)


def all_commands() -> Dict[str, str]:
    return dict(_descriptions)


class _HttpHandler(BaseHTTPRequestHandler):
    server_version = "sentinel-tpu-command-center"

    def log_message(self, fmt, *args):  # route to record log, not stderr
        record_log.debug("[CommandCenter] " + fmt, *args)

    def _dispatch(self, body: str) -> None:
        parsed = urlparse(self.path)
        name = parsed.path.strip("/")
        params = dict(parse_qsl(parsed.query))
        if body:
            params.update(dict(parse_qsl(body)))
        handler = _handlers.get(name)
        if handler is None:
            self._respond(400, f"Unknown command `{name}`; known: {sorted(_handlers)}")
            return
        try:
            resp = handler(CommandRequest(name, params, body))
        except Exception as e:  # handler crash must not kill the server
            record_log.error("[CommandCenter] handler %s failed", name, exc_info=True)
            self._respond(500, f"command error: {e}")
            return
        self._respond(200 if resp.success else 400, resp.result, resp.content_type)

    def _respond(self, code: int, body: str, content_type: str = "text/plain; charset=utf-8"):
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:
        self._dispatch("")

    def do_POST(self) -> None:
        try:
            length = int(self.headers.get("Content-Length") or 0)
        except ValueError:
            length = -1
        if length < 0:
            self._respond(400, "bad Content-Length")
            return
        if length > 10 * 1024 * 1024:
            self._respond(413, "body too large")
            return
        raw = self.rfile.read(length) if length else b""
        try:
            body = raw.decode("utf-8")
        except UnicodeDecodeError:
            self._respond(400, "body is not valid UTF-8")
            return
        self._dispatch(body)


class CommandCenter:
    """The simple-http command center (start on the transport port)."""

    def __init__(self, port: int = 0) -> None:
        self._requested_port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # Ensure built-in handlers are registered.
        from sentinel_tpu.transport import handlers as _  # noqa: F401

    @property
    def port(self) -> int:
        if self._server is None:
            return self._requested_port
        return self._server.server_address[1]

    def start(self) -> "CommandCenter":
        if self._server is not None:
            return self
        self._server = ThreadingHTTPServer(("0.0.0.0", self._requested_port), _HttpHandler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sentinel-command-center", daemon=True
        )
        self._thread.start()
        record_log.info("[CommandCenter] listening on %d", self.port)
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
