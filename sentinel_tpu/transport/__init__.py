"""Transport plane: command center + heartbeat.

Equivalent of sentinel-transport (reference: sentinel-transport-common
CommandHandler/@CommandMapping/CommandCenter SPI + ~18 built-in
handlers; sentinel-transport-simple-http's raw-socket HTTP server;
heartbeat/SimpleHttpHeartbeatSender.java:36-65). The command center
exposes rule CRUD, metric pull, node introspection and cluster-mode
switches over plain HTTP for the dashboard.
"""

from sentinel_tpu.transport.command_center import (
    CommandCenter,
    command_mapping,
    CommandRequest,
    CommandResponse,
)
from sentinel_tpu.transport.heartbeat import HeartbeatSender

__all__ = [
    "CommandCenter",
    "command_mapping",
    "CommandRequest",
    "CommandResponse",
    "HeartbeatSender",
]
