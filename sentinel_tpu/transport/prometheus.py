"""Prometheus text-format metric exporter.

Reference: sentinel-metric-exporter/.../jmx/JMXMetricExporter.java:31 —
the reference exports per-resource metric beans over JMX; the Python-
native analog is a ``/metrics`` endpoint on the command center in the
Prometheus exposition format (text/plain; version=0.0.4), scraping the
same per-resource statistics the dashboard pulls.

Beyond the reference's per-resource view, the scrape also exposes the
engine internals the flight recorder collects (metrics/telemetry.py):
``sentinel_engine_*`` counters, ``_bucket`` histogram series for
flush/drain/end-to-end admission latency, the flush-pipeline occupancy
gauge, the per-stage host breakdown of the most recent flush
(``Engine.last_flush_host_ms`` — previously reachable only from
bench.py), and the blocked-resource heavy-hitter sketch.
"""

from __future__ import annotations

import time as _time
from typing import Dict, List, Tuple

_GAUGES: List[Tuple[str, str, str]] = [
    # (prometheus metric suffix, engine stat key, help text)
    ("pass_qps", "pass_qps", "Passed requests per second (1s window)"),
    ("block_qps", "block_qps", "Blocked requests per second (1s window)"),
    ("success_qps", "success_qps", "Completed requests per second (1s window)"),
    ("exception_qps", "exception_qps", "Business exceptions per second (1s window)"),
    ("avg_rt_ms", "avg_rt", "Average response time, ms"),
    ("min_rt_ms", "min_rt", "Minimum response time in window, ms"),
    ("cur_thread_num", "cur_thread_num", "In-flight (concurrent) requests"),
    ("waiting_requests", "waiting", "Tokens borrowed for future windows (occupy)"),
    ("pass_total_minute", "total_pass_minute", "Passed requests, last 60s"),
    ("block_total_minute", "total_block_minute", "Blocked requests, last 60s"),
    ("success_total_minute", "total_success_minute", "Completed requests, last 60s"),
    ("exception_total_minute", "total_exception_minute", "Exceptions, last 60s"),
]

_PREFIX = "sentinel"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


OPENMETRICS_CONTENT_TYPE = (
    "application/openmetrics-text; version=1.0.0; charset=utf-8"
)


def render_metrics(engine, openmetrics: bool = False) -> str:
    """All resources' stats in the Prometheus exposition format.

    ``openmetrics=True`` renders the OpenMetrics dialect: admission
    exemplars (``# {trace_id="…"} value``) on the e2e latency buckets
    and a trailing ``# EOF``. Exemplars are ONLY legal there — the
    classic ``text/plain; version=0.0.4`` parser rejects a mid-line
    ``#``, which would fail the entire scrape — so the default
    (classic) rendering omits them and the handler switches the
    content type along with the format."""
    engine.flush()
    resources = engine.nodes.resources()
    all_rows = [row for _, row in resources] + [engine.nodes.entry_node_row]
    by_row = engine.rows_stats(all_rows)  # one batched device read
    rows: Dict[str, Dict[str, float]] = {
        resource: by_row[row] for resource, row in resources
    }
    entry_stats = by_row[engine.nodes.entry_node_row]

    out: List[str] = []
    for suffix, key, help_text in _GAUGES:
        name = f"{_PREFIX}_{suffix}"
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} gauge")
        for resource, stats in sorted(rows.items()):
            v = stats.get(key, 0)
            out.append(f'{name}{{resource="{_escape_label(resource)}"}} {v}')
        out.append(f'{name}{{resource="__total_inbound_traffic__"}} {entry_stats.get(key, 0)}')
    # Engine gauges.
    out.append(f"# HELP {_PREFIX}_engine_enabled Global protection switch (1 on)")
    out.append(f"# TYPE {_PREFIX}_engine_enabled gauge")
    out.append(f"{_PREFIX}_engine_enabled {1 if engine.enabled else 0}")
    out.append(f"# HELP {_PREFIX}_resources Known protected resources")
    out.append(f"# TYPE {_PREFIX}_resources gauge")
    out.append(f"{_PREFIX}_resources {len(rows)}")
    out.extend(engine_telemetry_lines(engine, openmetrics=openmetrics))
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def _counter(name: str, help_text: str, value, openmetrics: bool = False) -> List[str]:
    # OpenMetrics 1.0 names a counter FAMILY without the _total suffix
    # (the sample keeps it); the classic format metadata uses the full
    # sample name. Emitting the classic shape under the OM content
    # type makes strict OM parsers reject the whole scrape.
    family = name[:-len("_total")] if openmetrics and name.endswith("_total") else name
    return [
        f"# HELP {family} {help_text}",
        f"# TYPE {family} counter",
        f"{name} {value}",
    ]


def _gauge(name: str, help_text: str, value) -> List[str]:
    return [
        f"# HELP {name} {help_text}",
        f"# TYPE {name} gauge",
        f"{name} {value}",
    ]


def engine_telemetry_lines(engine, openmetrics: bool = False) -> List[str]:
    """The ``sentinel_engine_*`` family: flight-recorder counters,
    latency histogram series, pipeline occupancy, last-flush host
    breakdown, intern-cache counters and the blocked-resource sketch.
    Rendered even when telemetry is disabled (zeros) so dashboards keep
    their series. ``openmetrics`` gates the admission exemplars (legal
    only in that dialect — see :func:`render_metrics`)."""
    p = f"{_PREFIX}_engine"
    tele = engine.telemetry
    c = tele.counters_snapshot()
    out: List[str] = []

    def ctr(name: str, help_text: str, value) -> List[str]:
        return _counter(name, help_text, value, openmetrics)
    out += ctr(f"{p}_flushes_total", "Dispatched flush chunks", c["flushes"])
    out += ctr(f"{p}_ops_total", "Ops (entries+exits, incl. bulk rows) flushed", c["ops"])
    out += ctr(
        f"{p}_deferred_flushes_total",
        "Flush chunks dispatched without an inline fetch (pipelined/async)",
        c["deferred_flushes"],
    )
    out += ctr(
        f"{p}_coalesced_fallback_total",
        "Coalesced drain fetches that fell back to per-record fetches",
        c["coalesced_fallbacks"],
    )
    out += ctr(f"{p}_arena_hits_total", "Encode-arena staging pool hits", c["arena_hits"])
    out += ctr(f"{p}_arena_misses_total", "Encode-arena staging pool misses (fresh builds)", c["arena_misses"])

    # Histograms: host-blocking flush time, coalesced drain fetches,
    # end-to-end admission (dispatch start -> verdicts materialized).
    out += tele.hist_flush.prometheus_lines(
        f"{p}_flush_duration_ms", "Host-blocking flush duration, ms"
    )
    out += tele.hist_drain.prometheus_lines(
        f"{p}_drain_duration_ms", "Coalesced device->host drain fetch duration, ms"
    )
    out += tele.hist_e2e.prometheus_lines(
        f"{p}_e2e_duration_ms",
        "End-to-end admission: encode start to verdicts materialized, ms",
    )
    # Sampled per-ADMISSION latency (enqueue -> verdict), the tracer's
    # histogram: its buckets carry the OpenMetrics exemplars — counts
    # and exemplars measure the SAME quantity, so an exemplar never
    # lands on an empty bucket (per-flush e2e above is a different
    # quantity under deferred submission and stays exemplar-free).
    tracer = getattr(engine, "admission_trace", None)
    if tracer is not None:
        out += tracer.hist_latency.prometheus_lines(
            f"{p}_admission_latency_ms",
            "Sampled admission enqueue->verdict latency, ms",
            exemplars=tracer.exemplars() if openmetrics else None,
        )

    # Admission-tracer counters (metrics/admission_trace.py).
    if tracer is not None:
        tc = tracer.counters_snapshot()
        out += ctr(
            f"{p}_trace_records_total",
            "Admission trace records written to the ring",
            tc["recorded"],
        )
        out += ctr(
            f"{p}_trace_head_sampled_total",
            "Records selected by the head sampling decision",
            tc["head_sampled"],
        )
        out += ctr(
            f"{p}_trace_blocked_sampled_total",
            "Records selected by the always-sample-blocked mode only",
            tc["blocked_sampled"],
        )

    # Flush pipeline occupancy (Engine.pipeline_stats — previously a
    # bench.py dead end): mean in-flight depth per dispatching flush,
    # and the 0..1 occupancy against the configured depth.
    ps = engine.pipeline_stats()
    depth = engine.pipeline_depth
    occupancy = (ps["mean_inflight"] / depth) if depth > 0 else 0.0
    out += _gauge(f"{p}_pipeline_depth", "Configured flush pipeline depth", depth)
    out += ctr(
        f"{p}_pipeline_dispatches_total",
        "Dispatching deferred flushes since the last stats reset",
        int(ps["dispatches"]),
    )
    out += _gauge(
        f"{p}_pipeline_mean_inflight",
        "Mean in-flight queue depth sampled per dispatching flush",
        round(ps["mean_inflight"], 6),
    )
    out += _gauge(
        f"{p}_pipeline_occupancy",
        "Pipeline occupancy: mean in-flight depth / configured depth (0..1)",
        round(occupancy, 6),
    )

    # Per-stage host breakdown of the most recent flush
    # (Engine.last_flush_host_ms, wired off the bench-only path).
    lf = engine.last_flush_host_ms
    for stage in ("encode_ms", "dispatch_ms", "kernel_ms", "drain_ms"):
        out += _gauge(
            f"{p}_last_flush_{stage}",
            f"Most recent flush host breakdown: {stage}",
            round(lf.get(stage, 0.0), 6),
        )

    # ParamIndex intern-cache counters (host-ingest fast path).
    pindex = getattr(engine, "param_index", None)
    if pindex is not None and hasattr(pindex, "cache_stats"):
        cs = pindex.cache_stats()
        out += ctr(f"{p}_param_cache_hits_total", "Param resolved-value cache hits", cs["hits"])
        out += ctr(f"{p}_param_cache_misses_total", "Param resolved-value cache misses", cs["misses"])
        out += ctr(f"{p}_param_cache_evictions_total", "Param value-row LRU evictions", cs["evictions"])

    # Failure domain (runtime/failover.py): health state gauge plus
    # degraded-admission counters — the scrape-side view that tells
    # degraded admits from device admits.
    fo = getattr(engine, "failover", None)
    if fo is not None:
        from sentinel_tpu.runtime.failover import HEALTH_GAUGE

        out += _gauge(
            f"{p}_health",
            "Engine health state (0 HEALTHY, 1 DEGRADED, 2 RECOVERING)",
            HEALTH_GAUGE.get(fo.state, 0),
        )
        out += _gauge(
            f"{p}_failover_enabled",
            "Device-failure domain armed (sentinel.tpu.failover.enabled)",
            1 if fo.armed else 0,
        )
        fc = dict(fo.counters)
        out += ctr(
            f"{p}_degraded_admits_total",
            "Admissions decided by the host fallback while DEGRADED",
            fc.get("degraded_admits", 0),
        )
        out += ctr(
            f"{p}_degraded_blocks_total",
            "Blocks decided by the host fallback while DEGRADED (incl. fail-closed sheds)",
            fc.get("degraded_blocks", 0),
        )
        out += ctr(
            f"{p}_quarantined_flushes_total",
            "In-flight flushes quarantined on a device fault",
            fc.get("quarantined_records", 0),
        )
        out += ctr(
            f"{p}_failover_trips_total",
            "HEALTHY->DEGRADED transitions (device faults/timeouts)",
            fc.get("trips", 0),
        )
        out += ctr(
            f"{p}_failover_checkpoints_total",
            "Host checkpoints captured (riding the coalesced fetch)",
            fc.get("checkpoints", 0),
        )
        out += ctr(
            f"{p}_failover_probe_flushes_total",
            "Recovery probe no-op flushes executed",
            fc.get("probe_flushes", 0),
        )

    # Speculative tier (runtime/speculative.py): fast-path verdict
    # counters, reconciliation drift by direction, the per-window drift
    # histogram the differential bound is stated over, and the valve
    # state.
    spec = getattr(engine, "speculative", None)
    if spec is not None:
        sc = dict(spec.counters)
        out += _gauge(
            f"{p}_speculative_enabled",
            "Speculative admission tier armed (sentinel.tpu.speculative.enabled)",
            1 if spec.enabled else 0,
        )
        out += ctr(
            f"{p}_speculative_admits_total",
            "Admissions served by the speculative host tier",
            sc.get("spec_admits", 0),
        )
        out += ctr(
            f"{p}_speculative_blocks_total",
            "Blocks served by the speculative host tier",
            sc.get("spec_blocks", 0),
        )
        out += ctr(
            f"{p}_speculative_declined_total",
            "Ops the speculative tier declined to the device path",
            sc.get("spec_declined", 0),
        )
        out += ctr(
            f"{p}_speculative_over_admits_total",
            "Speculative admits the device settlement blocked",
            sc.get("over_admits", 0),
        )
        out += ctr(
            f"{p}_speculative_under_admits_total",
            "Speculative blocks the device settlement admitted",
            sc.get("under_admits", 0),
        )
        out += ctr(
            f"{p}_speculative_suspensions_total",
            "Drift-valve suspensions (overadmit.max reached in a window)",
            sc.get("suspensions", 0),
        )
        out += _gauge(
            f"{p}_speculative_suspended",
            "Speculation currently suspended by the drift valve (0/1)",
            1 if spec.suspended else 0,
        )
        out += _gauge(
            f"{p}_speculative_max_over_admit_window",
            "Max over-admits observed in any single drift window",
            spec.max_over_admit_window,
        )
        out += tele.hist_spec_drift.prometheus_lines(
            f"{p}_speculative_drift_per_window",
            "Over-admits per closed drift window (speculative vs settled)",
        )
        out += ctr(
            f"{p}_speculative_shaped_total",
            "Shaped (pacer/warm-up) ops served by the host mirror",
            sc.get("spec_shaped", 0),
        )
        out += ctr(
            f"{p}_speculative_system_blocks_total",
            "Host system-gate blocks served by the speculative tier",
            sc.get("spec_system_blocks", 0),
        )

    # Ingest self-protection valve (runtime/ingest.py).
    valve = getattr(engine, "ingest", None)
    if valve is not None:
        ic = dict(valve.counters)
        out += _gauge(
            f"{p}_ingest_armed",
            "Ingest shed valve armed (any sentinel.tpu.ingest.* bound set)",
            1 if valve.armed else 0,
        )
        out += ctr(
            f"{p}_ingest_shed_total",
            "Ops shed at submit by the ingest valve (entries + bulk rows)",
            ic.get("shed_entries", 0) + ic.get("shed_rows", 0),
        )
        out += ctr(
            f"{p}_ingest_shed_queue_total",
            "Sheds caused by a pending-queue bound",
            ic.get("shed_queue", 0),
        )
        out += ctr(
            f"{p}_ingest_shed_deadline_total",
            "Sheds caused by the verdict-deadline estimate",
            ic.get("shed_deadline", 0),
        )
        if valve.armed:
            out += _gauge(
                f"{p}_ingest_estimate_ms",
                "Estimated verdict latency for an op queued now",
                round(valve.estimate_ms(), 3),
            )

    # Blocked-resource heavy-hitter summary (space-saving over the
    # kernel's per-flush top-K): weight = blocked acquire sum. Export
    # K comes from the ONE config-backed home (TelemetryBus.
    # export_topk_k) shared with the `telemetry` command and the
    # sketch tier's candidate listing.
    name = f"{p}_blocked_weight"
    out.append(f"# HELP {name} Blocked acquire weight per resource (space-saving summary)")
    out.append(f"# TYPE {name} gauge")
    for key, cnt, _err in tele.blocked_sketch.topk(tele.export_topk_k):
        out.append(f'{name}{{resource="{_escape_label(key)}"}} {cnt}')

    # Statistics sketch tier (runtime/sketch.py): occupancy, promotion
    # flow, and the estimated-vs-exact error gauge. Rendered even when
    # disarmed (zeros) so dashboards keep their series.
    tier = getattr(engine, "sketch", None)
    if tier is not None:
        out += _gauge(
            f"{p}_sketch_enabled",
            "Statistics sketch tier armed (sentinel.tpu.sketch.enabled)",
            1 if tier.armed else 0,
        )
        out += ctr(
            f"{p}_sketch_keys_total",
            "Distinct keys folded into the device sketch (per-chunk sum)",
            c.get("sketch_keys", 0),
        )
        out += ctr(
            f"{p}_sketch_promotions_total",
            "Heavy-hitter keys promoted to exact dense rows",
            c.get("sketch_promotions", 0),
        )
        out += ctr(
            f"{p}_sketch_demotions_total",
            "Promoted keys demoted back to sketch-only on decay",
            c.get("sketch_demotions", 0),
        )
        out += ctr(
            f"{p}_sketch_host_folds_total",
            "DEGRADED chunks folded into the host space-saving mirror",
            c.get("sketch_host_folds", 0),
        )
        out += _gauge(
            f"{p}_sketch_promoted",
            "Keys currently promoted (values + resources)",
            tier.promoted_count,
        )
        out += _gauge(
            f"{p}_sketch_occupancy",
            "Candidate-table slots in use / capacity (0..1)",
            round(tier.occupancy, 4),
        )
        out += _gauge(
            f"{p}_sketch_est_error_ratio",
            "Mean relative overestimate of candidate counts vs exact host counters",
            round(tier.est_error_ratio, 6),
        )
        out += ctr(
            f"{p}_sketch_cold_blocks_total",
            "Submits blocked by the cold-key admission ceiling "
            "(sentinel.tpu.sketch.cold.qps, count-min estimate)",
            c.get("sketch_cold_blocks", 0),
        )

    # Black-box flight recorder (runtime/capture.py). Rendered even
    # when capture is off (zeros) so dashboards keep their series.
    cap = getattr(engine, "capture", None)
    out += _gauge(
        f"{p}_capture_enabled",
        "Admission capture journal armed (sentinel.tpu.capture.enabled)",
        1 if cap is not None else 0,
    )
    out += ctr(
        f"{p}_capture_chunks_total",
        "Dispatched chunks spilled to the capture journal",
        c.get("capture_chunks", 0),
    )
    out += ctr(
        f"{p}_capture_records_total",
        "Frame/timeline records written to capture segments",
        c.get("capture_records", 0),
    )
    out += ctr(
        f"{p}_capture_bytes_total",
        "Bytes written to capture segments (headers + payloads)",
        c.get("capture_bytes", 0),
    )
    out += ctr(
        f"{p}_capture_rollovers_total",
        "Capture segment rollovers (oldest live segment deleted past the bound)",
        c.get("capture_rollovers", 0),
    )
    out += ctr(
        f"{p}_capture_freezes_total",
        "Postmortem freezes (breaker trip / shed streak / DEGRADED / on-demand)",
        c.get("capture_freezes", 0),
    )
    out += ctr(
        f"{p}_capture_args_dropped_total",
        "Bulk rows captured without their args column (non-serializable column)",
        c.get("capture_args_dropped", 0),
    )

    # Multi-process ingest plane (sentinel_tpu/ipc): ring/worker/frame
    # counters plus the live ring-occupancy and worker gauges. Rendered
    # even when the plane is down (zeros) so dashboards keep their
    # series across restarts.
    plane = getattr(engine, "ipc_plane", None)
    out += _gauge(
        f"{p}_ipc_enabled",
        "Multi-process ingest plane running (sentinel.tpu.ipc.enabled)",
        1 if (plane is not None and not plane.closed) else 0,
    )
    out += _gauge(
        f"{p}_ipc_workers",
        "Worker processes currently attached to the ingest plane",
        plane.live_workers() if plane is not None else 0,
    )
    out += _gauge(
        f"{p}_ipc_ring_occupancy",
        "Request-ring slots in use / capacity (0..1)",
        round(plane.request.occupancy(), 4) if plane is not None else 0.0,
    )
    out += ctr(
        f"{p}_ipc_frames_total",
        "Request frames drained from the shared-memory ring",
        c.get("ipc_frames", 0),
    )
    out += ctr(
        f"{p}_ipc_requests_total",
        "Admission rows carried by drained request frames",
        c.get("ipc_requests", 0),
    )
    out += ctr(
        f"{p}_ipc_sheds_total",
        "Worker-side ring-full sheds folded into the valve accounting",
        c.get("ipc_sheds", 0),
    )
    out += ctr(
        f"{p}_ipc_worker_deaths_total",
        "Workers declared dead on a stale heartbeat (live admissions auto-exited)",
        c.get("ipc_worker_deaths", 0),
    )
    out += ctr(
        f"{p}_ipc_auto_exits_total",
        "Live admissions auto-exited for dead workers (gauges returned to 0)",
        c.get("ipc_auto_exits", 0),
    )
    # Engine supervision & warm hot-restart (ipc/supervise.py): the
    # boot-epoch word doubles as a restart count — epoch 1 is the first
    # engine on these rings, every re-attach bumps it.
    epoch = plane.engine_epoch if plane is not None else 1
    out += _gauge(
        f"{p}_epoch",
        "Engine boot epoch on the current ingest-plane rings "
        "(bumped once per plane attach; 1 = first boot)",
        epoch,
    )
    out += ctr(
        f"{p}_restarts_total",
        "Engine hot-restarts observed on these rings (boot epoch - 1)",
        max(0, epoch - 1),
    )
    out += ctr(
        f"{p}_ipc_worker_reconnects_total",
        "Workers that re-asserted their live-admission ledgers after an "
        "engine hot-restart",
        c.get("ipc_worker_reconnects", 0),
    )
    # Durable checkpoint spill (sentinel.tpu.failover.checkpoint.path):
    # write flow + freshness of the warm-restart file.
    fo = engine.failover
    out += ctr(
        f"{p}_checkpoint_durable_writes_total",
        "Durable checkpoint files written (atomic replace)",
        fo.counters.get("durable_writes", 0),
    )
    out += ctr(
        f"{p}_checkpoint_durable_errors_total",
        "Durable checkpoint spill failures (in-memory checkpoint unaffected)",
        fo.counters.get("durable_write_errors", 0),
    )
    out += ctr(
        f"{p}_checkpoint_durable_cold_loads_total",
        "Durable checkpoint loads that degraded to a cold start "
        "(missing components, corrupt, or stale file)",
        fo.counters.get("durable_load_cold", 0),
    )
    last = fo.last_durable
    out += _gauge(
        f"{p}_checkpoint_durable_age_ms",
        "Age of the last durable checkpoint write (-1 = never written)",
        (max(0, int(_time.time() * 1000) - last[0]) if last else -1),
    )
    out += _gauge(
        f"{p}_checkpoint_durable_write_ms",
        "Serialization + write cost of the last durable spill "
        "(-1 = never written)",
        (round(last[2], 3) if last else -1),
    )
    # Batched cluster token plane (cluster/client.py): the process-wide
    # client stats singleton — deliberately NOT per-engine, because an
    # engine has no cluster client attached until a cluster rule
    # arrives but the families must exist from the first scrape.
    from sentinel_tpu.cluster.client import client_stats

    ccs = client_stats.snapshot()
    out += ctr(
        f"{_PREFIX}_cluster_requests_total",
        "Token decisions asked of the cluster client (all paths)",
        ccs["requests"],
    )
    out += ctr(
        f"{_PREFIX}_cluster_batch_frames_total",
        "Batched token frames sent (FLOW/PARAM_FLOW_REQUEST_BATCH)",
        ccs["batch_frames"],
    )
    out += ctr(
        f"{_PREFIX}_cluster_leases_granted_total",
        "Local quota leases received from the token server",
        ccs["leases_granted"],
    )
    out += ctr(
        f"{_PREFIX}_cluster_lease_admits_total",
        "Admissions served from a local lease (zero RPCs)",
        ccs["lease_admits"],
    )
    out += ctr(
        f"{_PREFIX}_cluster_fallbacks_total",
        "FAIL-family serves (send/timeout/short frame) — caller falls "
        "back to the local decision",
        ccs["fallbacks"],
    )
    out += client_stats.rpc_ms.prometheus_lines(
        f"{_PREFIX}_cluster_rpc_ms",
        "Cluster token RPC round-trip (frame send to verdict), ms",
    )
    # Bounded SHOULD_WAIT pacing actually slept by the engine
    # (sentinel.tpu.cluster.wait.cap.ms caps each op batch).
    out += ctr(
        f"{p}_cluster_wait_ms_total",
        "Milliseconds slept honoring cluster SHOULD_WAIT verdicts "
        "(capped per op batch by sentinel.tpu.cluster.wait.cap.ms)",
        c.get("cluster_wait_ms", 0),
    )

    # Sharded token plane (cluster/shards.py): per-shard labeled rows
    # off the live provider client, so a dead shard's fallbacks and a
    # bounced shard's cleared leases are attributable to THAT shard.
    # Family headers render even when the plane is unsharded (or no
    # client is attached) so dashboards keep their series.
    from sentinel_tpu.cluster.state import TokenClientProvider

    sc = TokenClientProvider.get_client()
    s_rows = sc.shard_rows() if hasattr(sc, "shard_rows") else []
    out += _gauge(
        f"{_PREFIX}_cluster_shard_count",
        "Token shards behind the sharded client (0 = unsharded plane)",
        len(s_rows),
    )
    out += _gauge(
        f"{_PREFIX}_cluster_shard_map_version",
        "Version of the shard map the client currently routes by "
        "(sentinel.tpu.cluster.shards.map.version; -1 = unsharded)",
        sc.shard_map.version if hasattr(sc, "shard_map") else -1,
    )
    for fam, kind, help_text, col in (
        ("connected", "gauge",
         "Shard connection state (1 = TCP connected)", "connected"),
        ("leases", "gauge",
         "Live local-quota leases held against this shard", "leases"),
        ("requests_total", "counter",
         "Token decisions routed to this shard (all stances)",
         "requests"),
        ("batch_frames_total", "counter",
         "Batched token frames sent to this shard", "batch_frames"),
        ("lease_admits_total", "counter",
         "Admissions served from this shard's local leases (zero RPCs)",
         "lease_admits"),
        ("fallbacks_total", "counter",
         "FAIL-family serves on this shard — its flows fell back to "
         "the local decision", "fallbacks"),
    ):
        name = f"{_PREFIX}_cluster_shard_{fam}"
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} {kind}")
        for r in s_rows:
            v = int(r[col]) if col != "connected" else int(bool(r[col]))
            out.append(
                f'{name}{{shard="{r["shard"]}",'
                f'server="{_escape_label(r["server"])}"}} {v}'
            )

    # Sketch gossip plane (cluster/gossip.py + runtime/sketch.py): the
    # process-wide wire counters plus this engine's fold state — how
    # many peer views the tier currently holds and how many merges it
    # folded, the pair that says fleet-wide promotion is actually fed.
    from sentinel_tpu.cluster.gossip import gossip_stats

    gs = gossip_stats.snapshot()
    gi = engine.sketch.gossip_info()
    out += _gauge(
        f"{p}_gossip_enabled",
        "Sketch gossip armed on this engine (sentinel.tpu.gossip.enabled "
        "with the sketch tier on)",
        1 if gi.get("armed") else 0,
    )
    out += _gauge(
        f"{p}_gossip_remote_origins",
        "Peer engines whose sketch views this tier currently holds",
        # gossip_info carries the origin NAMES (the cluster command
        # shows them); the gauge is their count.
        len(gi.get("remote_origins") or ()),
    )
    out += ctr(
        f"{p}_gossip_merges_total",
        "Remote sketch views folded into this tier (snapshot-replace "
        "per origin)",
        gi.get("merges", 0),
    )
    out += ctr(
        f"{p}_gossip_rounds_total",
        "Gossip push rounds driven by this process",
        gs["rounds"],
    )
    out += ctr(
        f"{p}_gossip_frames_sent_total",
        "SKETCH_PUSH/SKETCH_MERGED frames sent",
        gs["frames_sent"],
    )
    out += ctr(
        f"{p}_gossip_frames_received_total",
        "SKETCH_PUSH/SKETCH_MERGED frames received",
        gs["frames_received"],
    )
    out += ctr(
        f"{p}_gossip_version_rejects_total",
        "Foreign-GOSSIP_VERSION frames answered with an empty merged "
        "frame (mixed-version fleet degrades to per-engine promotion)",
        gs["version_rejects"],
    )
    out += ctr(
        f"{p}_gossip_errors_total",
        "Gossip round/peer failures (dead peer, timeout, bad frame)",
        gs["errors"],
    )

    # Param admission path selection (Engine._encode_param): batches
    # routed to the closed-form rank path vs the rounds/scan family —
    # the pick the self-tuning cost memo arbitrates when enabled.
    out += ctr(
        f"{p}_param_closed_form_total",
        "Param batches routed to the closed-form rank path",
        c.get("param_closed_form", 0),
    )
    out += ctr(
        f"{p}_param_scan_total",
        "Param batches routed to the rounds/scan family",
        c.get("param_scan", 0),
    )

    # Self-tuning control plane (runtime/autotune.py): whether the
    # loop is closed, what it currently holds the knobs at, and how
    # often it moves them. Rendered even when disabled (zeros/current
    # static values) so dashboards keep their series.
    at = getattr(engine, "autotune", None)
    if at is not None:
        out += _gauge(
            f"{p}_autotune_enabled",
            "Self-tuning control plane armed (sentinel.tpu.autotune.enabled)",
            1 if at.enabled else 0,
        )
        out += ctr(
            f"{p}_autotune_decisions_total",
            "Applied autotune knob changes (depth / window retunes)",
            c.get("autotune_decisions", 0),
        )
        out += _gauge(
            f"{p}_autotune_depth",
            "Pipeline depth currently in effect (autotune-chosen when armed)",
            engine.pipeline_depth,
        )
        w = getattr(engine, "ingest_window", None)
        if w is not None:
            out += _gauge(
                f"{p}_autotune_window_ms",
                "Batch-window length currently in effect, ms",
                round(w.window_ms, 3),
            )
            out += _gauge(
                f"{p}_autotune_window_batch_max",
                "Batch-window early-flush bound currently in effect",
                w.batch_max,
            )
    out += resource_provenance_lines(engine, openmetrics=openmetrics)
    return out


def _configured_resources(engine) -> set:
    """Resources an operator explicitly configured a rule for — these
    always deserve their own label row (the operator asked about them
    by name)."""
    out = set()
    for idx_attr in ("flow_index", "degrade_index", "param_index"):
        idx = getattr(engine, idx_attr, None)
        out.update(getattr(idx, "by_resource", {}) or {})
    out.update(getattr(engine, "authority_rules", {}) or {})
    return out


def resource_provenance_lines(engine, openmetrics: bool = False) -> List[str]:
    """The ``sentinel_resource_*`` family: per-resource two-tier
    admission provenance (metrics/provenance.py totals) with BOUNDED
    label cardinality — label rows are granted only to configured
    resources (rule-bearing) and the blocked-weight top-K sketch's
    current heavy hitters; every other resource folds into one
    ``resource="__other__"`` row — the same collision-proof fold label
    the metric-log plane uses, so the row has ONE identity across both
    exports and no user resource name can shadow it (PAPERS.md
    1902.06993: bound the export with the sketch, not one series per
    key). Empty when the ledger is disabled
    (``sentinel.tpu.metrics.resource.enabled=false``)."""
    rm = getattr(engine, "resource_metrics", None)
    if rm is None or not rm.enabled:
        return []
    from sentinel_tpu.metrics.provenance import OTHER_RESOURCE

    tele = engine.telemetry
    allowed = _configured_resources(engine)
    allowed.update(
        k for k, _c, _e in tele.blocked_sketch.topk(tele.export_topk_k)
    )
    totals = rm.totals()
    folded: Dict[str, List[int]] = {}
    for res, cells in totals.items():
        key = (
            res if (res in allowed and res != OTHER_RESOURCE)
            else OTHER_RESOURCE
        )
        agg = folded.setdefault(key, [0, 0, 0, 0])
        for i, v in enumerate(cells):
            agg[i] += v
    out: List[str] = []
    fams = [
        ("speculative_total", 0, "counter",
         "Acquire-weighted verdicts served by the speculative host tier"),
        ("degraded_total", 1, "counter",
         "Acquire-weighted verdicts served by the host fallback while DEGRADED"),
        ("shed_total", 2, "counter",
         "Acquire-weighted ops shed at submit by the ingest valve"),
        ("drift", 3, "gauge",
         "Net speculative over-admit (over minus under reconciliation mismatches)"),
    ]
    for suffix, col, kind, help_text in fams:
        name = f"{_PREFIX}_resource_{suffix}"
        family = (
            name[: -len("_total")]
            if openmetrics and kind == "counter" and name.endswith("_total")
            else name
        )
        out.append(f"# HELP {family} {help_text}")
        out.append(f"# TYPE {family} {kind}")
        for res in sorted(folded):
            out.append(
                f'{name}{{resource="{_escape_label(res)}"}} {folded[res][col]}'
            )
    return out


def worker_metric_lines(client=None, openmetrics: bool = False) -> List[str]:
    """The ``sentinel_worker_*`` family: one worker process's
    IngestClient counters — admissions, frames-per-entry amortization,
    shed causes (ring sheds vs policy serves vs dropped completions)
    and reconnect state. ``client=None`` renders zero-valued families
    (the metrics-federation twin of the cluster singletons: the
    families must exist from the first scrape, and the config audit
    introspects this render without a live plane)."""
    p = f"{_PREFIX}_worker"
    snap = client.snapshot() if client is not None else {}
    c = snap.get("counters", {})
    out: List[str] = []

    def ctr(name: str, help_text: str, value) -> List[str]:
        return _counter(name, help_text, value, openmetrics)

    out += ctr(f"{p}_entries_total",
               "Per-call admissions pushed through the plane", c.get("entries", 0))
    out += ctr(f"{p}_bulk_rows_total",
               "Bulk admission rows pushed through the plane", c.get("bulk_rows", 0))
    out += ctr(f"{p}_exits_total",
               "Completions delivered to the engine", c.get("exits", 0))
    out += ctr(f"{p}_exits_dropped_total",
               "Completions dropped (engine provably gone)", c.get("exits_dropped", 0))
    out += ctr(f"{p}_sheds_total",
               "Local BLOCK_SHED verdicts (request ring full)", c.get("sheds", 0))
    out += ctr(f"{p}_policy_served_total",
               "Verdicts served from the failover policy snapshot "
               "(engine dead or verdict timeout)", c.get("policy_served", 0))
    out += ctr(f"{p}_frames_total",
               "Request frames pushed onto the shared-memory ring", c.get("frames", 0))
    out += ctr(f"{p}_window_flushes_total",
               "Client micro-window flushes", c.get("window_flushes", 0))
    out += ctr(f"{p}_reconnects_total",
               "Engine hot-restart reconnects (boot epoch bumps seen)",
               c.get("reconnects", 0))
    out += ctr(f"{p}_dead_suspicions_total",
               "Death-confirmation episodes opened (heartbeat stale past "
               "ipc.engine.dead.ms)", c.get("dead_suspicions", 0))
    out += ctr(f"{p}_dead_false_alarms_total",
               "Suspicion episodes cleared by a fresh heartbeat or live "
               "pid probe (engine was busy, not dead)",
               c.get("dead_false_alarms", 0))
    out += ctr(f"{p}_dead_declared_total",
               "Suspicion episodes that ended in a confirmed death "
               "declaration", c.get("dead_declared", 0))
    out += ctr(f"{p}_handoff_holds_total",
               "Admissions held through a planned-handoff window instead "
               "of failing to the policy path", c.get("handoff_holds", 0))
    ops = c.get("entries", 0) + c.get("bulk_rows", 0)
    out += _gauge(
        f"{p}_frames_per_entry",
        "Request frames per admission row (micro-window amortization; "
        "1.0 = per-call framing)",
        round(c.get("frames", 0) / ops, 4) if ops else 0.0,
    )
    out += _gauge(f"{p}_engine_alive",
                  "Engine heartbeat fresh from this worker's view (1 = alive)",
                  int(bool(snap.get("engine_alive", 0))))
    out += _gauge(f"{p}_live_admissions",
                  "Admissions this worker holds open (reconnect ledger)",
                  snap.get("live_admissions", 0))
    out += _gauge(f"{p}_pending_waits",
                  "Callers parked waiting for a verdict",
                  snap.get("pending_waits", 0))
    out += _gauge(f"{p}_buffered_exits",
                  "Completions buffered for post-restart replay",
                  snap.get("buffered_exits", 0))
    out += _gauge(f"{p}_id", "This process's worker slot id",
                  snap.get("worker_id", -1))
    return out


def render_worker_metrics(client=None, openmetrics: bool = False) -> str:
    """Full exposition for a worker-mode process (no engine to
    render — the worker families ARE its scrape)."""
    out = worker_metric_lines(client, openmetrics=openmetrics)
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"


def cluster_server_metric_lines(server=None, openmetrics: bool = False) -> List[str]:
    """The ``sentinel_cluster_server_*`` family: a token shard's work
    clocks (decisions, frames, busy seconds), lease grants, connection
    count per namespace, and the per-(category,outcome) stat-log rows.
    ``server=None`` renders zero-valued families for the same
    first-scrape/audit reasons as the worker render."""
    p = f"{_PREFIX}_cluster_server"
    work = server.work_stats() if server is not None else {}
    out: List[str] = []

    def ctr(name: str, help_text: str, value) -> List[str]:
        return _counter(name, help_text, value, openmetrics)

    out += ctr(f"{p}_decisions_total",
               "Token decisions made by this shard", work.get("decisions", 0))
    out += ctr(f"{p}_frames_total",
               "Request frames handled (decode->decide->pack)",
               work.get("frames", 0))
    out += ctr(f"{p}_busy_seconds_total",
               "Handler seconds spent deciding (excluding socket waits)",
               round(work.get("busy_s", 0.0), 6))
    out += ctr(f"{p}_lease_grants_total",
               "Local-quota leases granted to clients", work.get("lease_grants", 0))
    name = f"{p}_connections"
    out.append(f"# HELP {name} Connected token clients per namespace")
    out.append(f"# TYPE {name} gauge")
    groups = server.connections.snapshot() if server is not None else {}
    for ns, n in sorted(groups.items()):
        out.append(f'{name}{{namespace="{_escape_label(ns)}"}} {n}')
    if not groups:
        out.append(f'{name}{{namespace="default"}} 0')
    from sentinel_tpu.cluster import stat_log

    name = f"{p}_stat_total"
    fam = name[:-len("_total")] if openmetrics else name
    out.append(f"# HELP {fam} Stat-log lines per (category, outcome) "
               "— the wire twin of sentinel-cluster.log")
    out.append(f"# TYPE {fam} counter")
    counts = stat_log.counters_snapshot() if server is not None else {}
    for key, n in sorted(counts.items()):
        cat, _, outcome = key.partition(".")
        out.append(
            f'{name}{{category="{_escape_label(cat)}",'
            f'outcome="{_escape_label(outcome)}"}} {n}'
        )
    if not counts:
        out.append(f'{name}{{category="flow",outcome="pass"}} 0')
    return out


def render_cluster_server_metrics(server=None, openmetrics: bool = False) -> str:
    """Full exposition for a token shard process."""
    out = cluster_server_metric_lines(server, openmetrics=openmetrics)
    if openmetrics:
        out.append("# EOF")
    return "\n".join(out) + "\n"
