"""Prometheus text-format metric exporter.

Reference: sentinel-metric-exporter/.../jmx/JMXMetricExporter.java:31 —
the reference exports per-resource metric beans over JMX; the Python-
native analog is a ``/metrics`` endpoint on the command center in the
Prometheus exposition format (text/plain; version=0.0.4), scraping the
same per-resource statistics the dashboard pulls.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

_GAUGES: List[Tuple[str, str, str]] = [
    # (prometheus metric suffix, engine stat key, help text)
    ("pass_qps", "pass_qps", "Passed requests per second (1s window)"),
    ("block_qps", "block_qps", "Blocked requests per second (1s window)"),
    ("success_qps", "success_qps", "Completed requests per second (1s window)"),
    ("exception_qps", "exception_qps", "Business exceptions per second (1s window)"),
    ("avg_rt_ms", "avg_rt", "Average response time, ms"),
    ("min_rt_ms", "min_rt", "Minimum response time in window, ms"),
    ("cur_thread_num", "cur_thread_num", "In-flight (concurrent) requests"),
    ("waiting_requests", "waiting", "Tokens borrowed for future windows (occupy)"),
    ("pass_total_minute", "total_pass_minute", "Passed requests, last 60s"),
    ("block_total_minute", "total_block_minute", "Blocked requests, last 60s"),
    ("success_total_minute", "total_success_minute", "Completed requests, last 60s"),
    ("exception_total_minute", "total_exception_minute", "Exceptions, last 60s"),
]

_PREFIX = "sentinel"


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def render_metrics(engine) -> str:
    """All resources' stats in the Prometheus exposition format."""
    engine.flush()
    resources = engine.nodes.resources()
    all_rows = [row for _, row in resources] + [engine.nodes.entry_node_row]
    by_row = engine.rows_stats(all_rows)  # one batched device read
    rows: Dict[str, Dict[str, float]] = {
        resource: by_row[row] for resource, row in resources
    }
    entry_stats = by_row[engine.nodes.entry_node_row]

    out: List[str] = []
    for suffix, key, help_text in _GAUGES:
        name = f"{_PREFIX}_{suffix}"
        out.append(f"# HELP {name} {help_text}")
        out.append(f"# TYPE {name} gauge")
        for resource, stats in sorted(rows.items()):
            v = stats.get(key, 0)
            out.append(f'{name}{{resource="{_escape_label(resource)}"}} {v}')
        out.append(f'{name}{{resource="__total_inbound_traffic__"}} {entry_stats.get(key, 0)}')
    # Engine gauges.
    out.append(f"# HELP {_PREFIX}_engine_enabled Global protection switch (1 on)")
    out.append(f"# TYPE {_PREFIX}_engine_enabled gauge")
    out.append(f"{_PREFIX}_engine_enabled {1 if engine.enabled else 0}")
    out.append(f"# HELP {_PREFIX}_resources Known protected resources")
    out.append(f"# TYPE {_PREFIX}_resources gauge")
    out.append(f"{_PREFIX}_resources {len(rows)}")
    return "\n".join(out) + "\n"
