"""Dashboard rule storage providers — persist rules in a config
center instead of pushing to machines.

Reference: sentinel-dashboard/src/main/java/com/alibaba/csp/sentinel/
dashboard/rule/DynamicRuleProvider.java:26 + DynamicRulePublisher.java
— the console's pluggable pull/push pair. With a provider configured,
the console reads/writes the config center and every machine picks the
change up through its own datasource watch (the production topology);
without one it falls back to pushing straight at machine command APIs
(the default in-memory mode, like the reference's
FlowRuleApiProvider/Publisher).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from sentinel_tpu.utils.record_log import record_log


class DynamicRuleProvider:
    """Pull one (app, kind)'s rules from durable storage."""

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        raise NotImplementedError


class DynamicRulePublisher:
    """Push one (app, kind)'s rules to durable storage."""

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        raise NotImplementedError


class RuleStore(DynamicRuleProvider, DynamicRulePublisher):
    """Both halves on one backend (how every reference impl ships)."""


class InMemoryRuleStore(RuleStore):
    def __init__(self) -> None:
        self._data: Dict[tuple, List[dict]] = {}
        self._lock = threading.Lock()

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        with self._lock:
            return self._data.get((app, kind))

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        with self._lock:
            self._data[(app, kind)] = list(rules)


class EtcdRuleStore(RuleStore):
    """Rules in etcd under ``{prefix}/{app}/{kind}`` — machines watch
    the same keys with :class:`~sentinel_tpu.datasource.EtcdDataSource`
    (reference: the etcd DynamicRuleProvider/Publisher pair the
    dashboard docs describe for production rule persistence)."""

    def __init__(
        self,
        endpoint: str = "http://127.0.0.1:2379",
        prefix: str = "sentinel/rules",
        timeout_sec: float = 5.0,
    ) -> None:
        from sentinel_tpu.datasource.etcd_source import EtcdDataSource

        self._mk = lambda key: EtcdDataSource(
            lambda raw: raw, key, endpoint=endpoint, timeout_sec=timeout_sec
        )
        self.prefix = prefix.strip("/")

    def key_for(self, app: str, kind: str) -> str:
        return f"{self.prefix}/{app}/{kind}"

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        src = self._mk(self.key_for(app, kind))
        try:
            raw = src.read_source()
            if raw is None:
                return None
            out = json.loads(raw)
            return out if isinstance(out, list) else None
        except (OSError, ValueError) as e:
            record_log.warn("[EtcdRuleStore] read %s/%s failed: %s", app, kind, e)
            return None

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        src = self._mk(self.key_for(app, kind))
        src.write(json.dumps(rules))
