"""Dashboard rule storage providers — persist rules in a config
center instead of pushing to machines.

Reference: sentinel-dashboard/src/main/java/com/alibaba/csp/sentinel/
dashboard/rule/DynamicRuleProvider.java:26 + DynamicRulePublisher.java
— the console's pluggable pull/push pair. With a provider configured,
the console reads/writes the config center and every machine picks the
change up through its own datasource watch (the production topology);
without one it falls back to pushing straight at machine command APIs
(the default in-memory mode, like the reference's
FlowRuleApiProvider/Publisher).
"""

from __future__ import annotations

import json
import threading
from typing import Dict, List, Optional

from sentinel_tpu.utils.record_log import record_log


class DynamicRuleProvider:
    """Pull one (app, kind)'s rules from durable storage."""

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        raise NotImplementedError


class DynamicRulePublisher:
    """Push one (app, kind)'s rules to durable storage."""

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        raise NotImplementedError


class RuleStore(DynamicRuleProvider, DynamicRulePublisher):
    """Both halves on one backend (how every reference impl ships)."""

    def _read_rules(self, tag: str, app: str, kind: str, fetch) -> Optional[List[dict]]:
        """Shared pull body: fetch raw JSON, parse, validate list shape.
        ANY failure (transport errors of whatever exception type the
        backing client raises — ZkError is a plain Exception — or bad
        JSON) logs and returns None, which the dashboard treats as
        "fall back to direct machine fetch"."""
        try:
            raw = fetch()
            if raw is None:
                return None
            out = json.loads(raw)
            return out if isinstance(out, list) else None
        except Exception as e:
            record_log.warn("[%s] read %s/%s failed: %s", tag, app, kind, e)
            return None


class InMemoryRuleStore(RuleStore):
    def __init__(self) -> None:
        self._data: Dict[tuple, List[dict]] = {}
        self._lock = threading.Lock()

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        with self._lock:
            return self._data.get((app, kind))

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        with self._lock:
            self._data[(app, kind)] = list(rules)


class EtcdRuleStore(RuleStore):
    """Rules in etcd under ``{prefix}/{app}/{kind}`` — machines watch
    the same keys with :class:`~sentinel_tpu.datasource.EtcdDataSource`
    (reference: the etcd DynamicRuleProvider/Publisher pair the
    dashboard docs describe for production rule persistence)."""

    def __init__(
        self,
        endpoint: str = "http://127.0.0.1:2379",
        prefix: str = "sentinel/rules",
        timeout_sec: float = 5.0,
    ) -> None:
        from sentinel_tpu.datasource.etcd_source import EtcdDataSource

        self._mk = lambda key: EtcdDataSource(
            lambda raw: raw, key, endpoint=endpoint, timeout_sec=timeout_sec
        )
        self.prefix = prefix.strip("/")

    def key_for(self, app: str, kind: str) -> str:
        return f"{self.prefix}/{app}/{kind}"

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        src = self._mk(self.key_for(app, kind))
        return self._read_rules("EtcdRuleStore", app, kind, src.read_source)

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        src = self._mk(self.key_for(app, kind))
        src.write(json.dumps(rules))


class NacosRuleStore(RuleStore):
    """Rules in Nacos config under dataId ``{app}-{kind}-rules`` /
    group ``SENTINEL_GROUP`` — the reference dashboard's Nacos
    provider/publisher conventions (sentinel-dashboard/.../rule/nacos/
    NacosConfigUtil.java: RULE_*_DATA_ID_POSTFIX + GROUP_ID). Machines
    watch the same (dataId, group) with
    :class:`~sentinel_tpu.datasource.NacosDataSource`."""

    def __init__(
        self,
        endpoint: str = "http://127.0.0.1:8848",
        group: str = "SENTINEL_GROUP",
        tenant: str = "",
        context_path: str = "/nacos",
        timeout_sec: float = 5.0,
    ) -> None:
        from sentinel_tpu.datasource.nacos_source import NacosDataSource

        self.group = group
        self._mk = lambda data_id: NacosDataSource(
            lambda raw: raw,
            data_id,
            group=group,
            endpoint=endpoint,
            tenant=tenant,
            context_path=context_path,
            timeout_sec=timeout_sec,
        )

    def data_id_for(self, app: str, kind: str) -> str:
        return f"{app}-{kind}-rules"

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        src = self._mk(self.data_id_for(app, kind))
        return self._read_rules("NacosRuleStore", app, kind, src.read_source)

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        self._mk(self.data_id_for(app, kind)).write(json.dumps(rules))


class ZookeeperRuleStore(RuleStore):
    """Rules in znodes ``{root}/{app}/{kind}`` (reference:
    sentinel-dashboard/.../rule/zookeeper/ZookeeperConfigUtil.getPath —
    ``/sentinel_rule_config/{appName}...``). Machines watch the same
    path with :class:`~sentinel_tpu.datasource.ZookeeperDataSource`;
    the store reads/writes over transient sessions."""

    def __init__(
        self,
        server_addr: str = "127.0.0.1:2181",
        root: str = "/sentinel_rule_config",
        timeout_sec: float = 5.0,
    ) -> None:
        from sentinel_tpu.datasource.zookeeper_source import ZookeeperDataSource

        self._mk = lambda path: ZookeeperDataSource(
            lambda raw: raw,
            path=path,
            server_addr=server_addr,
            request_timeout_sec=timeout_sec,
        )
        self.root = "/" + root.strip("/")

    def path_for(self, app: str, kind: str) -> str:
        return f"{self.root}/{app}/{kind}"

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        src = self._mk(self.path_for(app, kind))
        try:
            return self._read_rules("ZookeeperRuleStore", app, kind, src.read_source)
        finally:
            src.close()

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        src = self._mk(self.path_for(app, kind))
        try:
            src.write(json.dumps(rules))
        finally:
            src.close()


class ApolloRuleStore(RuleStore):
    """Rules as one Apollo property ``{app}-{kind}-rules`` in a
    namespace. Reads go through the config service (what machines
    watch via :class:`~sentinel_tpu.datasource.ApolloDataSource`);
    publishes go through the Portal OpenAPI — upsert the item, then
    release the namespace (reference: sentinel-dashboard/.../rule/
    apollo/FlowRuleApolloPublisher.java using ApolloOpenApiClient's
    createOrUpdateItem + publishNamespace)."""

    def __init__(
        self,
        config_endpoint: str = "http://127.0.0.1:8080",
        portal_endpoint: str = "http://127.0.0.1:8070",
        token: str = "",
        app_id: str = "sentinel",
        env: str = "DEV",
        cluster: str = "default",
        namespace: str = "application",
        operator: str = "sentinel-dashboard",
        timeout_sec: float = 5.0,
    ) -> None:
        self.config_endpoint = config_endpoint.rstrip("/")
        self.portal_endpoint = portal_endpoint.rstrip("/")
        self.token = token
        self.app_id = app_id
        self.env = env
        self.cluster = cluster
        self.namespace = namespace
        self.operator = operator
        self.timeout = timeout_sec

    def key_for(self, app: str, kind: str) -> str:
        return f"{app}-{kind}-rules"

    def get_rules(self, app: str, kind: str) -> Optional[List[dict]]:
        from sentinel_tpu.datasource.apollo_source import ApolloDataSource

        src = ApolloDataSource(
            lambda raw: raw,
            self.namespace,
            self.key_for(app, kind),
            endpoint=self.config_endpoint,
            app_id=self.app_id,
            cluster=self.cluster,
            timeout_sec=self.timeout,
        )
        return self._read_rules("ApolloRuleStore", app, kind, src.read_source)

    def _portal(self, method: str, path: str, payload: dict) -> None:
        import urllib.parse
        import urllib.request

        q = lambda seg: urllib.parse.quote(str(seg), safe="")
        req = urllib.request.Request(
            f"{self.portal_endpoint}/openapi/v1/envs/{q(self.env)}"
            f"/apps/{q(self.app_id)}/clusters/{q(self.cluster)}"
            f"/namespaces/{q(self.namespace)}{path}",
            data=json.dumps(payload).encode("utf-8"),
            headers={
                "Content-Type": "application/json;charset=UTF-8",
                "Authorization": self.token,
            },
            method=method,
        )
        with urllib.request.urlopen(req, timeout=self.timeout):
            pass

    def publish(self, app: str, kind: str, rules: List[dict]) -> None:
        import urllib.parse

        key = self.key_for(app, kind)
        self._portal(
            "PUT",
            f"/items/{urllib.parse.quote(key, safe='')}?createIfNotExists=true",
            {
                "key": key,
                "value": json.dumps(rules),
                "dataChangeLastModifiedBy": self.operator,
                "dataChangeCreatedBy": self.operator,
            },
        )
        self._portal(
            "POST",
            "/releases",
            {
                "releaseTitle": f"sentinel-{app}-{kind}",
                "releasedBy": self.operator,
            },
        )
