"""Embedded dashboard console — a single-file web UI over the JSON API.

Reference: sentinel-dashboard ships an AngularJS webapp
(sentinel-dashboard/src/main/webapp/) with app list, real-time metrics
and rule management screens. A full SPA port is out of scope; this is a
dependency-free vanilla HTML/JS console served straight from the
dashboard process covering the same core screens: application list,
per-resource live QPS table with pass/block sparklines, and a rule
viewer/editor that pushes through the machine command API.
"""

CONSOLE_HTML = """<!DOCTYPE html>
<html lang="en">
<head>
<meta charset="utf-8">
<title>Sentinel TPU Console</title>
<style>
  :root { --bg:#0f1419; --panel:#1a2129; --fg:#d8dee6; --dim:#7d8a99;
          --accent:#4aa8ff; --ok:#3fb68b; --bad:#e05d5d; --line:#2a333e; }
  * { box-sizing:border-box; }
  body { margin:0; font:14px/1.45 system-ui,sans-serif; background:var(--bg); color:var(--fg); }
  header { padding:14px 22px; border-bottom:1px solid var(--line); display:flex; gap:14px; align-items:baseline; }
  header h1 { font-size:17px; margin:0; letter-spacing:.4px; }
  header .sub { color:var(--dim); font-size:12px; }
  main { display:grid; grid-template-columns:220px 1fr; min-height:calc(100vh - 53px); }
  nav { border-right:1px solid var(--line); padding:14px; }
  nav h2, section h2 { font-size:12px; text-transform:uppercase; color:var(--dim); margin:0 0 8px; }
  nav button { display:block; width:100%; text-align:left; margin:2px 0; padding:7px 10px;
    background:none; border:1px solid transparent; border-radius:6px; color:var(--fg); cursor:pointer; }
  nav button.active, nav button:hover { background:var(--panel); border-color:var(--line); }
  nav .dot { display:inline-block; width:7px; height:7px; border-radius:50%; margin-right:7px; }
  section { padding:16px 22px; overflow:auto; }
  table { border-collapse:collapse; width:100%; margin-bottom:22px; }
  th, td { text-align:right; padding:6px 10px; border-bottom:1px solid var(--line); font-variant-numeric:tabular-nums; }
  th { color:var(--dim); font-weight:500; font-size:12px; }
  th:first-child, td:first-child { text-align:left; }
  td.res { color:var(--accent); }
  .pass { color:var(--ok); } .block { color:var(--bad); }
  svg.spark { vertical-align:middle; }
  textarea { width:100%; height:180px; background:var(--panel); color:var(--fg);
    border:1px solid var(--line); border-radius:6px; padding:10px; font:12px/1.5 ui-monospace,monospace; }
  .rulebar { display:flex; gap:8px; margin:8px 0 16px; align-items:center; }
  select, .rulebar button { background:var(--panel); color:var(--fg); border:1px solid var(--line);
    border-radius:6px; padding:6px 12px; cursor:pointer; }
  .rulebar button:hover { border-color:var(--accent); }
  #status { color:var(--dim); font-size:12px; margin-left:auto; }
  .empty { color:var(--dim); padding:30px 0; }
  #login { position:fixed; inset:0; background:rgba(10,14,18,.93); display:none;
    align-items:center; justify-content:center; z-index:10; }
  #login form { background:var(--panel); border:1px solid var(--line); border-radius:10px;
    padding:26px 30px; display:flex; flex-direction:column; gap:10px; min-width:260px; }
  #login input { background:var(--bg); color:var(--fg); border:1px solid var(--line);
    border-radius:6px; padding:8px 10px; }
  #login button { background:var(--accent); color:#07121d; border:none; border-radius:6px;
    padding:8px; font-weight:600; cursor:pointer; }
  #loginerr { color:var(--bad); font-size:12px; min-height:14px; }
  td.mode-server { color:var(--accent); } td.mode-client { color:var(--ok); }
  tr.stale td { opacity:.45; }
  td.warn { color:#e0a95d; }
  #cluster button { background:var(--panel); color:var(--fg); border:1px solid var(--line);
    border-radius:6px; padding:4px 10px; cursor:pointer; }
  #cluster button:hover { border-color:var(--accent); }
</style>
</head>
<body>
<header><h1>Sentinel&nbsp;TPU</h1><span class="sub">flow control console</span>
  <span id="status"></span></header>
<div id="login"><form onsubmit="return doLogin(event)">
  <strong>Sign in</strong>
  <input id="lu" placeholder="username" autocomplete="username">
  <input id="lp" type="password" placeholder="password" autocomplete="current-password">
  <div id="loginerr"></div>
  <button type="submit">Log in</button>
</form></div>
<main>
  <nav><h2>Applications</h2><div id="apps" class="empty">loading…</div></nav>
  <section>
    <h2>Machines</h2>
    <table id="machines"><thead><tr>
      <th>machine</th><th>version</th><th>health</th><th>speculative</th>
      <th>shed</th><th>engine</th><th>heartbeat</th>
    </tr></thead><tbody></tbody></table>
    <h2>Real-time metrics <span id="appname"></span></h2>
    <table id="metrics"><thead><tr>
      <th>resource</th><th>pass/s</th><th>block/s</th><th>spec/s</th>
      <th>shed/s</th><th>drift</th><th>rt ms</th>
      <th>threads</th><th>trend (60s)</th>
    </tr></thead><tbody></tbody></table>
    <h2>Cluster</h2>
    <table id="cluster"><thead><tr>
      <th>machine</th><th>mode</th><th>server</th><th>flows (qps / conc / thr)</th><th></th>
    </tr></thead><tbody></tbody></table>
    <h2>Rules</h2>
    <div class="rulebar">
      <select id="ruletype">
        <option value="flow">flow</option><option value="degrade">degrade</option>
        <option value="system">system</option><option value="authority">authority</option>
        <option value="paramFlow">paramFlow</option>
      </select>
      <button onclick="loadRules()">Load</button>
      <button onclick="pushRules()">Push to machines</button>
    </div>
    <textarea id="rules" spellcheck="false"></textarea>
  </section>
</main>
<script>
let app = null;
let rulesLoaded = false;  // first successful app discovery loads rules
const hist = {};           // resource -> [{t, pass, block}]
const $ = (id) => document.getElementById(id);
const fetchJson = (url) => fetch(url).then(r => {
  if (r.status === 401) { $('login').style.display = 'flex'; throw new Error('login required'); }
  return r.json();
});
async function doLogin(ev) {
  ev.preventDefault();
  // Credentials go in the POST body, never the query string (query
  // lines end up in access/record logs).
  const body = `username=${encodeURIComponent($('lu').value)}&password=${encodeURIComponent($('lp').value)}`;
  const r = await fetch('/auth/login', { method: 'POST', body,
    headers: { 'Content-Type': 'application/x-www-form-urlencoded' } });
  if (r.status === 200) { $('login').style.display = 'none'; $('loginerr').textContent = '';
    refreshApps(); refreshMetrics(); refreshCluster(); }
  else $('loginerr').textContent = 'bad credentials';
  return false;
}
// Names arrive from the unauthenticated registry endpoint: escape
// EVERYTHING interpolated into markup (stored-XSS surface otherwise).
const esc = (s) => String(s).replace(/[&<>"']/g,
  c => ({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;',"'":'&#39;'}[c]));

// Enriched-heartbeat machine table (health / speculative tier / shed
// valve, stale machines dimmed + flagged). Numbers coerced, strings
// escaped — heartbeat fields arrive from the auth-exempt registry.
function renderMachines(ms) {
  const body = $('machines').tBodies[0];
  const num = (v) => (Number.isFinite(+v) ? +v : 0);
  // The highest engine_epoch any machine of this app reported: a
  // machine still heartbeating a LOWER epoch predates a hot-restart
  // (its worker fleet reattached to newer rings) — highlight it.
  const maxEpoch = Math.max(0, ...(ms || []).map(m => +m.engine_epoch || 0));
  body.innerHTML = (ms || []).map(m => {
    const stale = !m.healthy;
    const health = m.health || '';
    // An empty health string means the machine never reported the
    // enrichment fields (seed-era sender / engine not constructed):
    // render UNKNOWN ("—"), never a confident-looking default.
    const reported = !!health;
    const spec = !reported ? '—'
      : m.spec_enabled ? (m.spec_suspended ? 'suspended' : 'on') : 'off';
    const shed = !reported ? '—'
      : `${num(m.shed_total)}${m.shedding ? ' (shedding)' : ''}` +
        `${m.ingest_armed ? '' : ' (disarmed)'}`;
    const hcls = stale || health === 'DEGRADED' ? 'block'
      : health === 'RECOVERING' ? 'warn' : reported ? 'pass' : '';
    const epoch = num(m.engine_epoch);
    const staleEpoch = epoch > 0 && epoch < maxEpoch;
    const eng = !epoch ? '—'
      : `epoch ${epoch}` +
        `${num(m.restarts_total) ? ` (${num(m.restarts_total)} restarts)` : ''}` +
        ` · ${num(m.workers)}w${staleEpoch ? ' (stale epoch)' : ''}`;
    const ecls = staleEpoch ? 'block' : num(m.restarts_total) ? 'warn' : '';
    const hb = m.heartbeat_age_ms != null
      ? Math.round(num(m.heartbeat_age_ms) / 1000) + 's ago'  // server-computed: immune to browser clock skew
      : '—';
    return `<tr class="${stale ? 'stale' : ''}">` +
      `<td>${esc(m.ip)}:${num(m.port)}</td><td>${esc(m.version || '')}</td>` +
      `<td class="${hcls}">${esc(health || '—')}${stale ? ' (stale)' : ''}</td>` +
      `<td class="${m.spec_suspended ? 'warn' : ''}">${spec}</td>` +
      `<td class="${m.shedding ? 'block' : ''}">${shed}</td>` +
      `<td class="${ecls}">${eng}</td>` +
      `<td>${hb}</td></tr>`;
  }).join('') || '<tr><td colspan="7" class="empty">no machines</td></tr>';
}

async function refreshApps() {
  try {
    const apps = await fetchJson('/apps');
    const names = Object.keys(apps);
    const el = $('apps');
    if (!names.length) { el.className = 'empty'; el.textContent = 'no apps registered';
      renderMachines([]); return; }
    el.className = '';
    if (!app || !names.includes(app)) app = names[0];
    el.innerHTML = names.map((n, i) => {
      const healthy = apps[n].some(m => m.healthy);
      return `<button class="${n === app ? 'active' : ''}" data-i="${i}">` +
        `<span class="dot" style="background:${healthy ? 'var(--ok)' : 'var(--bad)'}"></span>${esc(n)}</button>`;
    }).join('');
    el.querySelectorAll('button').forEach(b =>
      b.addEventListener('click', () => selectApp(names[+b.dataset.i])));
    $('appname').textContent = '— ' + app;
    renderMachines(apps[app]);
    if (!rulesLoaded) { rulesLoaded = true; loadRules(); }
  } catch (e) { $('status').textContent = 'apps: ' + e; }
}
function selectApp(n) { app = n; refreshApps(); refreshMetrics(); refreshCluster(); loadRules(); }

async function refreshCluster() {
  if (!app) return;
  try {
    const ms = await fetchJson(`/cluster/state?app=${encodeURIComponent(app)}`);
    const body = $('cluster').tBodies[0];
    // Every machine-supplied field is attacker-reachable through the
    // auth-exempt registry + proxied command responses: numbers are
    // coerced (NaN -> 0), strings escaped — nothing lands in markup raw.
    const num = (v) => (Number.isFinite(+v) ? +v : 0);
    body.innerHTML = ms.map(m => {
      const addr = `${esc(m.ip)}:${num(m.port)}`;
      const mode = m.mode === 1 ? 'server' : m.mode === 0 ? 'client' : 'off';
      let detail = '—', flows = '—';
      if (m.server) {
        const cfg = m.server.config || {};
        detail = `:${num(cfg.port)} ns=${esc((cfg.namespaces || []).join(','))}`;
        const st = (m.server.stats || {}).flows || [];
        flows = st.map(f =>
          `#${num(f.flowId)}: ${num(f.currentQps).toFixed(1)} / ${num(f.concurrency)}` +
          ` / ${f.threshold == null ? '∞' : num(f.threshold)}`
        ).join('<br>') || 'no flows';
      } else if (m.client) {
        detail = `→ ${esc(m.client.serverHost ?? '')}:${num(m.client.serverPort)}`;
      }
      // No inline-JS interpolation: HTML-entity escaping does not
      // survive into the onclick JS-string context (entities decode
      // back before the JS runs). The address rides a data- attribute
      // and a delegated listener below reads it via the DOM API.
      return `<tr><td>${addr}</td><td class="mode-${mode}">${mode}</td>` +
        `<td>${detail}</td><td style="text-align:left">${flows}</td>` +
        `<td><button class="assign" data-ip="${esc(m.ip)}" data-port="${num(m.port)}">` +
        `make server</button></td></tr>`;
    }).join('') || '<tr><td colspan="5" class="empty">no machines</td></tr>';
    body.querySelectorAll('button.assign').forEach(b =>
      b.addEventListener('click', () =>
        assignServer(`${b.dataset.ip}:${b.dataset.port}`)));
  } catch (e) { $('status').textContent = 'cluster: ' + e; }
}
async function assignServer(addr) {
  try {
    const r = await fetchJson(
      `/cluster/assign?app=${encodeURIComponent(app)}&server=${encodeURIComponent(addr)}`);
    $('status').textContent = r.code === 0 ? `cluster assigned: ${addr} serves`
      : `assign failed: ${(r.failed || []).join(', ')}`;
    refreshCluster();
  } catch (e) { $('status').textContent = 'assign: ' + e; }
}

function spark(points, key, color) {
  if (points.length < 2) return '';
  const max = Math.max(1, ...points.map(p => p[key]));
  const xs = points.map((p, i) => (i / (points.length - 1)) * 118 + 1);
  const ys = points.map(p => 19 - (p[key] / max) * 17);
  const d = xs.map((x, i) => `${i ? 'L' : 'M'}${x.toFixed(1)},${ys[i].toFixed(1)}`).join('');
  return `<path d="${d}" fill="none" stroke="${color}" stroke-width="1.4"/>`;
}

async function refreshMetrics() {
  if (!app) return;
  try {
    const now = Date.now();
    const nodes = await fetchJson(`/metric?app=${encodeURIComponent(app)}&startTime=${now - 65000}&endTime=${now}`);
    const latest = {};
    for (const n of nodes) {
      (hist[n.resource] = hist[n.resource] || []).push({ t: n.timestamp, pass: n.pass_qps, block: n.block_qps });
      if (!latest[n.resource] || n.timestamp > latest[n.resource].timestamp) latest[n.resource] = n;
    }
    for (const r in hist) {
      const seen = new Set(); // dedupe by ts, keep last 60
      hist[r] = hist[r].filter(p => !seen.has(p.t) && seen.add(p.t)).slice(-60);
    }
    const body = $('metrics').tBodies[0];
    const rows = Object.keys(latest).sort().map(r => {
      const n = latest[r];
      const drift = n.drift ?? 0;
      return `<tr><td class="res">${esc(r)}</td><td class="pass">${n.pass_qps}</td>` +
        `<td class="block">${n.block_qps}</td>` +
        `<td>${n.speculative_qps ?? 0}</td>` +
        `<td class="${(n.shed_qps ?? 0) > 0 ? 'block' : ''}">${n.shed_qps ?? 0}</td>` +
        `<td class="${drift !== 0 ? 'warn' : ''}">${drift}</td>` +
        `<td>${(n.rt ?? 0).toFixed(1)}</td>` +
        `<td>${n.concurrency ?? 0}</td>` +
        `<td><svg class="spark" width="120" height="20">` +
        spark(hist[r], 'pass', 'var(--ok)') + spark(hist[r], 'block', 'var(--bad)') +
        `</svg></td></tr>`;
    });
    body.innerHTML = rows.join('') || '<tr><td colspan="9" class="empty">no traffic yet</td></tr>';
    $('status').textContent = 'updated ' + new Date().toLocaleTimeString();
  } catch (e) { $('status').textContent = 'metrics: ' + e; }
}

async function loadRules() {
  if (!app) return;
  const kind = $('ruletype').value;
  try {
    const rules = await fetchJson(`/rules?app=${encodeURIComponent(app)}&type=${kind}`);
    $('rules').value = JSON.stringify(rules, null, 2);
  } catch (e) { $('status').textContent = 'rules: ' + e; }
}
async function pushRules() {
  if (!app) return;
  const kind = $('ruletype').value;
  let data;
  try { data = JSON.stringify(JSON.parse($('rules').value)); }
  catch (e) { $('status').textContent = 'rules are not valid JSON'; return; }
  try {
    const resp = await fetchJson(`/rules?app=${encodeURIComponent(app)}&type=${kind}&data=${encodeURIComponent(data)}`);
    $('status').textContent = resp.code === 0 ? 'rules pushed' : 'push failed';
  } catch (e) { $('status').textContent = 'push failed: ' + e; }
}

fetch('/auth/check').then(r => r.json()).then(s => {
  if (s.enabled && !s.loggedIn) $('login').style.display = 'flex';
});
refreshApps(); setInterval(refreshApps, 5000);
refreshMetrics(); setInterval(refreshMetrics, 2000);
refreshCluster(); setInterval(refreshCluster, 5000);
</script>
</body>
</html>
"""
