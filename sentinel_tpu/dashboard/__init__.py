"""Dashboard: machine discovery, metric aggregation, rule management.

Equivalent of sentinel-dashboard (reference: .../dashboard/metric/
MetricFetcher.java:70-282 polling every machine's /metric each second
into an InMemoryMetricsRepository with 5-minute retention;
discovery/SimpleMachineDiscovery fed by /registry/machine heartbeats;
client/SentinelApiClient.java:93 pushing/pulling rules through the
command API; REST controllers per rule type). The AngularJS console is
out of scope — the JSON REST surface it sits on is here.
"""

from sentinel_tpu.dashboard.app import (
    DashboardServer,
    AppManagement,
    InMemoryMetricsRepository,
    MachineInfo,
    MetricFetcher,
    SentinelApiClient,
)

__all__ = [
    "DashboardServer",
    "AppManagement",
    "InMemoryMetricsRepository",
    "MachineInfo",
    "MetricFetcher",
    "SentinelApiClient",
]
