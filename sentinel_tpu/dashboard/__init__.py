"""Dashboard: machine discovery, metric aggregation, rule management.

Equivalent of sentinel-dashboard (reference: .../dashboard/metric/
MetricFetcher.java:70-282 polling every machine's /metric each second
into an InMemoryMetricsRepository with 5-minute retention;
discovery/SimpleMachineDiscovery fed by /registry/machine heartbeats;
client/SentinelApiClient.java:93 pushing/pulling rules through the
command API; REST controllers per rule type; auth/
SimpleWebAuthServiceImpl session login; service/cluster assign plane;
rule/DynamicRuleProvider + Publisher config-center persistence). A
dependency-free single-file console (webui.py) replaces the AngularJS
SPA: app list, live QPS sparklines, rule editor, login, cluster
management.
"""

from sentinel_tpu.dashboard.app import (
    AuthService,
    DashboardServer,
    AppManagement,
    InMemoryMetricsRepository,
    MachineInfo,
    MetricFetcher,
    SentinelApiClient,
)
from sentinel_tpu.dashboard.rules import (
    DynamicRuleProvider,
    DynamicRulePublisher,
    ApolloRuleStore,
    EtcdRuleStore,
    InMemoryRuleStore,
    NacosRuleStore,
    RuleStore,
    ZookeeperRuleStore,
)

__all__ = [
    "AuthService",
    "DashboardServer",
    "AppManagement",
    "InMemoryMetricsRepository",
    "MachineInfo",
    "MetricFetcher",
    "SentinelApiClient",
    "DynamicRuleProvider",
    "DynamicRulePublisher",
    "ApolloRuleStore",
    "EtcdRuleStore",
    "InMemoryRuleStore",
    "NacosRuleStore",
    "RuleStore",
    "ZookeeperRuleStore",
]
