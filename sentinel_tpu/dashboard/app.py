"""Dashboard server: discovery + metrics + rule REST.

HTTP surface (JSON unless noted):

    GET  /registry/machine?app=&ip=&port=...   heartbeat registration
    GET  /apps                                 known apps + machines
    GET  /metric?app=&identity=&startTime=&endTime=   aggregated metrics
    GET  /rules?app=&type=flow|degrade|...     pull rules from machines
    POST /rules?app=&type=&data=<json>         push rules to machines
    GET  /clusterNode?app=                     live cluster-node stats
    GET  /cluster/state?app=                   per-machine cluster mode/stats
    POST /cluster/assign?app=&server=ip:port   server+clients assignment
    POST /auth/login?username=&password=       session cookie (when enabled)
    GET  /auth/check                           {"loggedIn": bool}
    POST /auth/logout
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
import urllib.request
from collections import defaultdict
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qsl, urlparse

from sentinel_tpu.metrics.metric_log import MetricNodeLine
from sentinel_tpu.utils.record_log import record_log


@dataclass
class MachineInfo:
    app: str
    ip: str
    port: int
    hostname: str = ""
    version: str = ""
    # Admission-plane health fields from the enriched heartbeat
    # (transport/heartbeat.py); empty/zero for seed-era senders.
    health: str = ""
    spec_enabled: int = 0
    spec_suspended: int = 0
    ingest_armed: int = 0
    shed_total: int = 0
    shedding: int = 0
    # Engine lifecycle provenance (PR 18 heartbeat enrichment):
    # epoch 1 = first boot of the shared rings; restarts = epoch - 1;
    # workers = currently-attached ingest workers on the mp plane.
    engine_epoch: int = 0
    restarts_total: int = 0
    workers: int = 0
    last_heartbeat_ms: float = field(default_factory=lambda: time.time() * 1000)

    @property
    def key(self) -> Tuple[str, str, int]:
        return (self.app, self.ip, self.port)

    def is_healthy(self, timeout_ms: float = 60_000) -> bool:
        return time.time() * 1000 - self.last_heartbeat_ms < timeout_ms


class AppManagement:
    """SimpleMachineDiscovery + AppManagement."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._machines: Dict[Tuple[str, str, int], MachineInfo] = {}

    def register(self, info: MachineInfo) -> None:
        with self._lock:
            existing = self._machines.get(info.key)
            if existing is not None:
                existing.last_heartbeat_ms = time.time() * 1000
                existing.version = info.version or existing.version
                for f in ("health", "spec_enabled", "spec_suspended",
                          "ingest_armed", "shed_total", "shedding",
                          "engine_epoch", "restarts_total", "workers"):
                    setattr(existing, f, getattr(info, f))
            else:
                self._machines[info.key] = info

    def apps(self) -> Dict[str, List[MachineInfo]]:
        with self._lock:
            out: Dict[str, List[MachineInfo]] = defaultdict(list)
            for m in self._machines.values():
                out[m.app].append(m)
            return dict(out)

    def machines_of(self, app: str) -> List[MachineInfo]:
        with self._lock:
            return [m for m in self._machines.values() if m.app == app]


class InMemoryMetricsRepository:
    """5-minute in-memory metric store keyed by (app, resource)
    (repository/metric/InMemoryMetricsRepository.java:40)."""

    RETENTION_MS = 5 * 60 * 1000

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: Dict[Tuple[str, str], List[MetricNodeLine]] = defaultdict(list)

    def save_all(self, app: str, nodes: List[MetricNodeLine]) -> None:
        now = time.time() * 1000
        with self._lock:
            for n in nodes:
                lst = self._data[(app, n.resource)]
                lst.append(n)
                cutoff = now - self.RETENTION_MS
                while lst and lst[0].timestamp < cutoff:
                    lst.pop(0)

    def query(self, app: str, resource: str, begin_ms: int, end_ms: int) -> List[MetricNodeLine]:
        with self._lock:
            return [
                n
                for n in self._data.get((app, resource), ())
                if begin_ms <= n.timestamp <= end_ms
            ]

    def resources_of(self, app: str) -> List[str]:
        with self._lock:
            return sorted({r for (a, r) in self._data if a == app})


class SentinelApiClient:
    """Pull/push from/to app machines via their command API
    (client/SentinelApiClient.java:93)."""

    def __init__(self, timeout_sec: float = 3.0) -> None:
        self.timeout = timeout_sec

    def _get(self, ip: str, port: int, path: str, params: Dict[str, str]) -> Optional[str]:
        qs = urllib.parse.urlencode(params)
        url = f"http://{ip}:{port}/{path}?{qs}"
        try:
            with urllib.request.urlopen(url, timeout=self.timeout) as resp:
                return resp.read().decode("utf-8")
        except OSError:
            record_log.warn("[SentinelApiClient] GET %s failed", url)
            return None

    def fetch_metrics(self, m: MachineInfo, begin_ms: int, end_ms: int) -> List[MetricNodeLine]:
        raw = self._get(m.ip, m.port, "metric", {"startTime": begin_ms, "endTime": end_ms})
        if not raw:
            return []
        out = []
        for line in raw.splitlines():
            node = MetricNodeLine.from_line(line)
            if node is not None:
                out.append(node)
        return out

    def fetch_rules(self, m: MachineInfo, kind: str) -> Optional[List[dict]]:
        raw = self._get(m.ip, m.port, "getRules", {"type": kind})
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def set_rules(self, m: MachineInfo, kind: str, rules_json: str) -> bool:
        raw = self._get(m.ip, m.port, "setRules", {"type": kind, "data": rules_json})
        return raw == "success"

    def fetch_cluster_nodes(self, m: MachineInfo) -> Optional[List[dict]]:
        raw = self._get(m.ip, m.port, "clusterNode", {})
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def api_json(self, m: MachineInfo, path: str, params: Optional[Dict[str, str]] = None):
        """Generic command call decoded as JSON (None on failure)."""
        raw = self._get(m.ip, m.port, path, params or {})
        if raw is None:
            return None
        try:
            return json.loads(raw)
        except ValueError:
            return None

    def api_call(self, m: MachineInfo, path: str, params: Optional[Dict[str, str]] = None) -> bool:
        return self._get(m.ip, m.port, path, params or {}) == "success"


class MetricFetcher:
    """Polls every healthy machine's /metric window into the repository
    (metric/MetricFetcher.java:70-282)."""

    def __init__(
        self,
        apps: AppManagement,
        repo: InMemoryMetricsRepository,
        client: Optional[SentinelApiClient] = None,
        interval_sec: float = 1.0,
    ) -> None:
        self.apps = apps
        self.repo = repo
        self.client = client or SentinelApiClient()
        self.interval = interval_sec
        self._last_fetch: Dict[Tuple[str, str, int], int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def fetch_once(self) -> int:
        total = 0
        now = int(time.time() * 1000)
        for app, machines in self.apps.apps().items():
            for m in machines:
                if not m.is_healthy():
                    continue
                begin = self._last_fetch.get(m.key, now - 6000)
                nodes = self.client.fetch_metrics(m, begin + 1, now)
                if nodes:
                    self.repo.save_all(app, nodes)
                    self._last_fetch[m.key] = max(n.timestamp for n in nodes)
                    total += len(nodes)
        return total

    def start(self) -> "MetricFetcher":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="sentinel-metric-fetcher", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.fetch_once()
            except Exception:
                record_log.error("[MetricFetcher] fetch failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()


class AuthService:
    """Session login for the console (reference: dashboard auth/
    SimpleWebAuthServiceImpl.java:30 + LoginAuthenticationFilter —
    username/password from config, a session cookie afterwards). Auth
    is DISABLED when no credentials are configured, matching the
    reference's ``auth.username=`` empty-string behavior."""

    COOKIE = "sentinel_dashboard_session"

    def __init__(
        self,
        username: Optional[str] = None,
        password: Optional[str] = None,
        session_ttl_sec: float = 3600.0,
    ) -> None:
        self.username = username
        self.password = password
        self.ttl = session_ttl_sec
        self._sessions: Dict[str, float] = {}  # token -> expiry
        self._lock = threading.Lock()

    @property
    def enabled(self) -> bool:
        # BOTH must be set: a username without a password would raise
        # an auth wall that accepts a blank password (the reference
        # disables auth only when the credentials are absent).
        return bool(self.username) and bool(self.password)

    def login(self, username: str, password: str) -> Optional[str]:
        import hmac
        import secrets

        if not self.enabled:
            return None
        # Compare as utf-8 bytes: compare_digest raises TypeError on
        # non-ASCII str inputs, which would crash the login handler on
        # a unicode password instead of returning 401.
        if not (
            hmac.compare_digest(
                username.encode("utf-8"), (self.username or "").encode("utf-8")
            )
            and hmac.compare_digest(
                password.encode("utf-8"), (self.password or "").encode("utf-8")
            )
        ):
            return None
        token = secrets.token_hex(16)
        now = time.time()
        with self._lock:
            self._sessions = {
                t: exp for t, exp in self._sessions.items() if exp > now
            }
            self._sessions[token] = now + self.ttl
        return token

    def check(self, token: Optional[str]) -> bool:
        if not self.enabled:
            return True
        if not token:
            return False
        with self._lock:
            exp = self._sessions.get(token)
            if exp is None or exp <= time.time():
                self._sessions.pop(token, None)
                return False
            return True

    def logout(self, token: Optional[str]) -> None:
        if token:
            with self._lock:
                self._sessions.pop(token, None)


# Paths reachable without a session (the reference's auth filter
# excludes login + the machine registry; the SPA itself is static).
_AUTH_EXEMPT = {"/", "/index.html", "/auth/login", "/auth/check", "/version",
                "/registry/machine"}


class DashboardServer:
    """The REST facade over discovery + repo + api client."""

    def __init__(
        self,
        port: int = 0,
        fetch_interval_sec: float = 1.0,
        auth_username: Optional[str] = None,
        auth_password: Optional[str] = None,
        rule_store=None,
    ) -> None:
        self.apps = AppManagement()
        self.repo = InMemoryMetricsRepository()
        self.client = SentinelApiClient()
        self.fetcher = MetricFetcher(self.apps, self.repo, self.client, fetch_interval_sec)
        self.auth = AuthService(auth_username, auth_password)
        # Optional DynamicRuleProvider/Publisher pair (dashboard/rules
        # .py): when set, rule reads/writes go to durable storage and
        # machines pick changes up through their own datasource watch
        # instead of a direct command-API push.
        self.rule_store = rule_store
        self._requested_port = port
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        if self._server is None:
            return self._requested_port
        return self._server.server_address[1]

    # ---- request handling ----
    def _handle(self, path: str, params: Dict[str, str]) -> Tuple[int, str]:
        if path == "/registry/machine":
            def _i(key: str) -> int:
                try:
                    return int(params.get(key, 0) or 0)
                except ValueError:
                    return 0  # enrichment fields degrade, never 400

            try:
                info = MachineInfo(
                    app=params.get("app", "unknown"),
                    ip=params.get("ip", "127.0.0.1"),
                    port=int(params.get("port", 0)),
                    hostname=params.get("hostname", ""),
                    version=params.get("version", params.get("v", "")),
                    health=params.get("health", ""),
                    spec_enabled=_i("spec_enabled"),
                    spec_suspended=_i("spec_suspended"),
                    ingest_armed=_i("ingest_armed"),
                    shed_total=_i("shed_total"),
                    shedding=_i("shedding"),
                    engine_epoch=_i("engine_epoch"),
                    restarts_total=_i("restarts_total"),
                    workers=_i("workers"),
                )
            except ValueError:
                return 400, json.dumps({"code": -1, "msg": "bad port"})
            self.apps.register(info)
            return 200, json.dumps({"code": 0, "msg": "success"})
        if path == "/apps":
            return 200, json.dumps(
                {
                    app: [
                        {
                            "ip": m.ip,
                            "port": m.port,
                            "hostname": m.hostname,
                            "version": m.version,
                            "healthy": m.is_healthy(),
                            "stale": not m.is_healthy(),
                            "health": m.health,
                            "spec_enabled": m.spec_enabled,
                            "spec_suspended": m.spec_suspended,
                            "ingest_armed": m.ingest_armed,
                            "shed_total": m.shed_total,
                            "shedding": m.shedding,
                            "engine_epoch": m.engine_epoch,
                            "restarts_total": m.restarts_total,
                            "workers": m.workers,
                            "last_heartbeat_ms": int(m.last_heartbeat_ms),
                            # Server-computed age: the console must not
                            # mix its own clock with the dashboard's
                            # (skew would corrupt the "Ns ago" column).
                            "heartbeat_age_ms": max(
                                0,
                                int(time.time() * 1000
                                    - m.last_heartbeat_ms),
                            ),
                        }
                        for m in machines
                    ]
                    for app, machines in self.apps.apps().items()
                }
            )
        if path == "/fleet":
            # Fleet rollup: one JSON object per app summarising its
            # machines — the console's /fleet card and any external
            # poller get the whole fleet's posture in one round-trip
            # instead of a per-machine scrape. Divergent engine_epoch
            # across one app's machines means some heartbeats predate
            # a hot-restart: flagged as stale_epochs.
            out = {}
            for app, machines in self.apps.apps().items():
                max_epoch = max((m.engine_epoch for m in machines), default=0)
                out[app] = {
                    "machines": len(machines),
                    "healthy": sum(1 for m in machines if m.is_healthy()),
                    "workers": sum(m.workers for m in machines),
                    "restarts_total": sum(m.restarts_total for m in machines),
                    "shed_total": sum(m.shed_total for m in machines),
                    "shedding": sum(1 for m in machines if m.shedding),
                    "max_epoch": max_epoch,
                    "stale_epochs": sum(
                        1 for m in machines
                        if m.engine_epoch and m.engine_epoch < max_epoch
                    ),
                }
            return 200, json.dumps(out)
        if path == "/metric":
            app = params.get("app", "")
            resource = params.get("identity", "")
            begin = int(params.get("startTime", 0))
            end = int(params.get("endTime", 2**62))
            if resource:
                nodes = self.repo.query(app, resource, begin, end)
            else:
                nodes = []
                for r in self.repo.resources_of(app):
                    nodes.extend(self.repo.query(app, r, begin, end))
            return 200, json.dumps([n.__dict__ for n in nodes])
        if path == "/resources":
            return 200, json.dumps(self.repo.resources_of(params.get("app", "")))
        if path == "/rules":
            app = params.get("app", "")
            kind = params.get("type", "flow")
            data = params.get("data")
            if self.rule_store is not None:
                # Config-center mode (DynamicRuleProvider/Publisher):
                # the store is authoritative; machines follow it via
                # their own datasource watch.
                if data is not None:
                    try:
                        rules = json.loads(data)
                        if not isinstance(rules, list):
                            raise ValueError("rules must be a JSON list")
                    except ValueError as e:
                        return 400, json.dumps({"code": -1, "msg": str(e)})
                    try:
                        self.rule_store.publish(app, kind, rules)
                    except Exception as e:
                        return 502, json.dumps({"code": -1, "msg": f"publish: {e}"})
                    return 200, json.dumps({"code": 0})
                rules = self.rule_store.get_rules(app, kind)
                if rules is not None:
                    return 200, json.dumps(rules)
                # fall through to machines when the store has nothing
            machines = [m for m in self.apps.machines_of(app) if m.is_healthy()]
            if not machines:
                return 404, json.dumps({"code": -1, "msg": f"no machines for {app}"})
            if data is not None:
                ok = all(self.client.set_rules(m, kind, data) for m in machines)
                return 200, json.dumps({"code": 0 if ok else -1})
            rules = self.client.fetch_rules(machines[0], kind)
            return 200, json.dumps(rules if rules is not None else [])
        if path == "/clusterNode":
            app = params.get("app", "")
            machines = [m for m in self.apps.machines_of(app) if m.is_healthy()]
            if not machines:
                return 200, json.dumps([])
            return 200, json.dumps(self.client.fetch_cluster_nodes(machines[0]) or [])
        if path == "/cluster/state":
            app = params.get("app", "")
            out = []
            for m in self.apps.machines_of(app):
                if not m.is_healthy():
                    continue
                mode = self.client.api_json(m, "getClusterMode") or {}
                entry = {
                    "ip": m.ip,
                    "port": m.port,
                    "mode": mode.get("mode", -1),
                }
                if entry["mode"] == 1:  # server: config + per-flow stats
                    entry["server"] = {
                        "config": self.client.api_json(m, "cluster/server/config"),
                        "stats": self.client.api_json(m, "cluster/server/stats"),
                    }
                elif entry["mode"] == 0:  # client: its server address
                    entry["client"] = self.client.api_json(m, "cluster/client/config")
                out.append(entry)
            return 200, json.dumps(out)
        if path == "/cluster/assign":
            # ClusterAssignServiceImpl.java:36 — one machine becomes the
            # token server, the rest its clients.
            app = params.get("app", "")
            target = params.get("server", "")
            if ":" not in target:
                return 400, json.dumps({"code": -1, "msg": "server=ip:port required"})
            s_ip, s_port = target.rsplit(":", 1)
            machines = [m for m in self.apps.machines_of(app) if m.is_healthy()]
            server_m = next(
                (m for m in machines if m.ip == s_ip and str(m.port) == s_port), None
            )
            if server_m is None:
                return 404, json.dumps({"code": -1, "msg": f"unknown machine {target}"})
            ok = self.client.api_call(server_m, "setClusterMode", {"mode": "1"})
            # The ACTUALLY bound token port (cluster/server/stats reads
            # it off the live server object) — the static config port
            # diverges whenever the server bound an ephemeral port.
            token_port = (
                self.client.api_json(server_m, "cluster/server/stats") or {}
            ).get("port") or (
                self.client.api_json(server_m, "cluster/server/config") or {}
            ).get("port")
            if not ok or not token_port:
                # Do NOT demote the other machines to clients of a
                # server that never started — that would degrade every
                # machine's flow checks in one call.
                return 200, json.dumps(
                    {"code": -1, "server": target, "failed": [target]}
                )
            failed = []
            for m in machines:
                if m is server_m:
                    continue
                good = self.client.api_call(
                    m,
                    "cluster/client/modifyConfig",
                    {"serverHost": server_m.ip, "serverPort": str(token_port)},
                ) and self.client.api_call(m, "setClusterMode", {"mode": "0"})
                if not good:
                    failed.append(f"{m.ip}:{m.port}")
            code = 0 if not failed else -1
            return 200, json.dumps(
                {"code": code, "server": target, "failed": failed}
            )
        if path == "/version":
            from sentinel_tpu.version import __version__

            return 200, __version__
        return 404, json.dumps({"code": -1, "msg": f"unknown path {path}"})

    def start(self) -> "DashboardServer":
        if self._server is not None:
            return self
        dashboard = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                # Never persist query strings of auth requests (they
                # could carry credentials a client wrongly put there).
                args = tuple(
                    a.split("?")[0] + "?<redacted>"
                    if isinstance(a, str) and a.startswith(("GET /auth", "POST /auth"))
                    and "?" in a
                    else a
                    for a in args
                )
                record_log.debug("[Dashboard] " + fmt, *args)

            def _body_params(self) -> Dict[str, str]:
                try:
                    n = int(self.headers.get("Content-Length", 0) or 0)
                except ValueError:
                    return {}
                if n <= 0 or n > 1 << 20:
                    return {}
                try:
                    return dict(parse_qsl(self.rfile.read(n).decode("utf-8")))
                except (UnicodeDecodeError, OSError):
                    return {}

            def _cookie_token(self) -> Optional[str]:
                raw = self.headers.get("Cookie", "")
                for part in raw.split(";"):
                    k, _, v = part.strip().partition("=")
                    if k == AuthService.COOKIE:
                        return v
                return None

            def _reply(self, code, body, ctype="application/json", cookie=None):
                data = body.encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if cookie is not None:
                    self.send_header("Set-Cookie", cookie)
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                parsed = urlparse(self.path)
                params = dict(parse_qsl(parsed.query))
                if self.command == "POST":
                    params.update(self._body_params())
                auth = dashboard.auth
                token = self._cookie_token()
                if parsed.path == "/auth/login":
                    got = auth.login(
                        params.get("username", ""), params.get("password", "")
                    )
                    if got is None and auth.enabled:
                        return self._reply(
                            401, json.dumps({"code": -1, "msg": "bad credentials"})
                        )
                    cookie = (
                        f"{AuthService.COOKIE}={got}; HttpOnly; SameSite=Strict; Path=/"
                        if got
                        else None
                    )
                    return self._reply(200, json.dumps({"code": 0}), cookie=cookie)
                if parsed.path == "/auth/check":
                    return self._reply(
                        200,
                        json.dumps(
                            {"enabled": auth.enabled, "loggedIn": auth.check(token)}
                        ),
                    )
                if parsed.path == "/auth/logout":
                    auth.logout(token)
                    return self._reply(200, json.dumps({"code": 0}))
                if parsed.path not in _AUTH_EXEMPT and not auth.check(token):
                    return self._reply(
                        401, json.dumps({"code": -1, "msg": "login required"})
                    )
                if parsed.path in ("/", "/index.html"):
                    from sentinel_tpu.dashboard.webui import CONSOLE_HTML

                    return self._reply(200, CONSOLE_HTML, "text/html; charset=utf-8")
                code, body = dashboard._handle(parsed.path, params)
                self._reply(code, body)

            do_POST = do_GET

        self._server = ThreadingHTTPServer(("0.0.0.0", self._requested_port), _Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="sentinel-dashboard", daemon=True
        )
        self._thread.start()
        self.fetcher.start()
        record_log.info("[Dashboard] listening on %d", self.port)
        return self

    def stop(self) -> None:
        self.fetcher.stop()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
