"""Block log — the LogSlot → EagleEye StatLogger pipeline, batched.

Reference: LogSlot catches the BlockException and stat-logs
(resource, exceptionName, ruleLimitApp, origin) with the blocked count
(slots/logger/LogSlot.java:31-40, EagleEyeLogUtil.java:20-40); the
EagleEye StatLogger aggregates per 1 s interval keyed by the tuple and
writes one line per key per interval to a size-rolled
``sentinel-block.log`` (eagleeye/StatLogController.java:134-153 — line
layout ``time|statType|key,key,...|value``).

The batched engine produces blocked verdicts a flush at a time, so the
aggregation is a dict update per flush instead of per-request counters;
completed seconds are written when a later second rolls in (or on
:meth:`flush`). Rolling keeps ``max_backup_index`` shifted backups like
EagleEyeRollingFileAppender.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterable, List, Optional, Tuple

from sentinel_tpu.utils.clock import Clock, default_clock
from sentinel_tpu.utils.record_log import record_log

FILE_NAME = "sentinel-block.log"

# (resource, exception_name, rule_limit_app, origin)
BlockKey = Tuple[str, str, str, str]

_live_loggers: "weakref.WeakSet[BlockLogger]" = None  # type: ignore[assignment]


def _init_atexit() -> None:
    global _live_loggers
    import atexit
    import weakref

    _live_loggers = weakref.WeakSet()

    def _flush_all() -> None:
        for logger in list(_live_loggers):
            try:
                logger.flush()
            except Exception:
                pass

    atexit.register(_flush_all)


_init_atexit()


class BlockLogger:
    """Per-second aggregated block log with size-rolled output."""

    STAT_TYPE = "count"

    def __init__(
        self,
        base_dir: Optional[str] = None,
        file_name: str = FILE_NAME,
        interval_ms: int = 1000,
        max_entry_count: int = 6000,
        max_file_size: int = 300 * 1024 * 1024,
        max_backup_index: int = 3,
        clock: Optional[Clock] = None,
    ) -> None:
        from sentinel_tpu.utils.record_log import _log_dir

        self.base_dir = base_dir or _log_dir()
        self.path = os.path.join(self.base_dir, file_name)
        self.interval_ms = interval_ms
        self.max_entry_count = max_entry_count
        self.max_file_size = max_file_size
        self.max_backup_index = max_backup_index
        self.clock = clock or default_clock()
        self._lock = threading.Lock()
        self._cur_sec: Optional[int] = None  # wall-ms aligned interval start
        self._entries: Dict[BlockKey, int] = {}
        # The last partial interval must survive process exit — an
        # operator investigating an incident reads this file. One
        # process-level hook over a weak set: discarded loggers are
        # collectable, not pinned by the atexit registry.
        _live_loggers.add(self)

    # ------------------------------------------------------------------
    def log(
        self,
        resource: str,
        exception_name: str,
        rule_limit_app: str = "default",
        origin: str = "",
        count: int = 1,
        now_wall_ms: Optional[int] = None,
    ) -> None:
        self.log_batch(
            [(resource, exception_name, rule_limit_app, origin, count)], now_wall_ms
        )

    def log_blocked(
        self,
        resource: str,
        reason_code: int,
        rule_limit_app: str = "default",
        origin: str = "",
        count: int = 1,
        now_wall_ms: Optional[int] = None,
    ) -> None:
        """Log a blocked verdict by its REASON CODE — the name comes
        from the one shared mapping (core/errors.BLOCK_EXC_NAMES), so a
        caller holding a verdict tensor's reason never spells the
        exception name by hand (and a new BLOCK_* code can't silently
        log under a divergent name)."""
        from sentinel_tpu.core.errors import exc_name_for_code

        self.log(
            resource, exc_name_for_code(reason_code), rule_limit_app,
            origin, count, now_wall_ms,
        )

    def log_batch(
        self,
        items: Iterable[Tuple],
        now_wall_ms: Optional[int] = None,
    ) -> None:
        """One lock acquisition for a whole flush's items. Each item is
        ``(*key_parts, count)`` — key arity is free (the block log uses
        4 parts; the cluster stat log uses whatever the tag needs,
        StatLogger.stat(...) style)."""
        now = self.clock.wall_ms() if now_wall_ms is None else now_wall_ms
        aligned = now - now % self.interval_ms
        with self._lock:
            if self._cur_sec is not None and aligned > self._cur_sec:
                self._write_locked()
            if self._cur_sec is None or aligned > self._cur_sec:
                self._cur_sec = aligned
            for item in items:
                key, count = tuple(str(p) for p in item[:-1]), int(item[-1])
                if key not in self._entries and len(self._entries) >= self.max_entry_count:
                    continue  # maxEntryCount cap: drop new keys, keep hot ones
                self._entries[key] = self._entries.get(key, 0) + count

    def stat(self, *key_parts: str, count: int = 1,
             now_wall_ms: Optional[int] = None) -> None:
        """StatLogger.stat(keys...).count(n) shorthand."""
        self.log_batch([(*key_parts, count)], now_wall_ms)

    def flush(self) -> None:
        """Force-write the current interval (tests / shutdown)."""
        with self._lock:
            self._write_locked()

    def maybe_flush(self, now_wall_ms: Optional[int] = None) -> None:
        """Write the pending interval if it has completed — called by
        the engine after each flush so a burst followed by quiet still
        reaches disk without waiting for the next blocked request."""
        now = self.clock.wall_ms() if now_wall_ms is None else now_wall_ms
        with self._lock:
            if (
                self._entries
                and self._cur_sec is not None
                and now - now % self.interval_ms > self._cur_sec
            ):
                self._write_locked()

    # ------------------------------------------------------------------
    def _write_locked(self) -> None:
        if not self._entries or self._cur_sec is None:
            self._entries = {}
            return
        lines: List[str] = []
        for key_parts, count in self._entries.items():
            key = ",".join(key_parts)
            lines.append(f"{self._cur_sec}|{self.STAT_TYPE}|{key}|{count}\n")
        self._entries = {}
        try:
            self._roll_if_needed()
            os.makedirs(self.base_dir, exist_ok=True)
            with open(self.path, "a", encoding="utf-8") as f:
                f.writelines(lines)
        except OSError:
            record_log.error("[BlockLogger] write failed", exc_info=True)

    def _roll_if_needed(self) -> None:
        try:
            if os.path.getsize(self.path) < self.max_file_size:
                return
        except OSError:
            return
        # Shift backups: .2 -> .3, .1 -> .2, base -> .1 (rolling appender).
        for i in range(self.max_backup_index - 1, 0, -1):
            src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
            if os.path.exists(src):
                os.replace(src, dst)
        os.replace(self.path, f"{self.path}.1")

    # ------------------------------------------------------------------
    def read_entries(self) -> List[Tuple[int, BlockKey, int]]:
        """Parse the log back: [(interval_start_ms, key, count)] —
        test/introspection helper."""
        out: List[Tuple[int, BlockKey, int]] = []
        try:
            with open(self.path, encoding="utf-8") as f:
                for line in f:
                    parts = line.rstrip("\n").split("|")
                    if len(parts) != 4:
                        continue
                    ts, _stat, key, count = parts
                    fields = key.split(",")
                    out.append((int(ts), tuple(fields), int(count)))  # type: ignore[arg-type]
        except OSError:
            pass
        return out
