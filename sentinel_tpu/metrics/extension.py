"""Metric extension SPI — pluggable per-event metric callbacks.

Reference: MetricExtension / AdvancedMetricExtension
(sentinel-core/.../metric/extension/MetricExtension.java) wired into the
StatisticSlot through MetricEntryCallback / MetricExitCallback
(metric/extension/callback/MetricEntryCallback.java:33-56,
MetricExitCallback.java:34-60) and registered via the InitFunc SPI
(MetricCallbackInit). The engine invokes registered extensions with each
flush's verdicts — same callback surface, batched delivery.

Extensions run under the engine's flush lock on the flushing thread
(the reference runs them inline on the request thread): keep them fast
and non-blocking; exceptions are swallowed and logged.
"""

from __future__ import annotations

import threading
from typing import List, Sequence

from sentinel_tpu.utils.record_log import record_log


class MetricExtension:
    """Callback surface (MetricExtension.java method-for-method; Python
    names snake_cased). Subclass and override what you need."""

    def add_pass(self, resource: str, n: int, *args: object) -> None:
        """Invocation passed all checks (n = acquire count)."""

    def add_block(
        self, resource: str, n: int, origin: str, block_error: object, *args: object
    ) -> None:
        """Invocation blocked; ``block_error`` is the BlockError."""

    def add_success(self, resource: str, n: int, *args: object) -> None:
        """Invocation completed successfully."""

    def add_exception(self, resource: str, n: int, throwable: object) -> None:
        """Business exception recorded (Tracer)."""

    def add_rt(self, resource: str, rt_ms: int, *args: object) -> None:
        """Response time recorded at completion."""

    def increase_thread_num(self, resource: str, *args: object) -> None:
        pass

    def decrease_thread_num(self, resource: str, *args: object) -> None:
        pass


class MetricExtensionProvider:
    """Registry (MetricExtensionProvider.java) — explicit registration
    plus entry-point SPI discovery on first use."""

    _lock = threading.Lock()
    _extensions: List[MetricExtension] = []
    _spi_loaded = False

    @classmethod
    def get_extensions(cls) -> Sequence[MetricExtension]:
        if not cls._spi_loaded:
            cls._load_spi()
        return cls._extensions

    @classmethod
    def _load_spi(cls) -> None:
        with cls._lock:
            if cls._spi_loaded:
                return
            cls._spi_loaded = True
            try:
                from sentinel_tpu.utils.registry import Registry

                for ext in Registry.of("MetricExtension").load_instance_list_sorted():
                    cls._extensions.append(ext)
            except Exception:
                record_log.error("[MetricExtension] SPI load failed", exc_info=True)

    @classmethod
    def register(cls, ext: MetricExtension) -> None:
        with cls._lock:
            cls._extensions.append(ext)

    @classmethod
    def clear(cls) -> None:
        with cls._lock:
            cls._extensions.clear()
            cls._spi_loaded = False

    # ------------------------------------------------------------------
    # Batched dispatch helpers (called by the engine; one guard per
    # extension so one misbehaving extension cannot starve the rest).
    @staticmethod
    def _call(fn, *args) -> None:
        # Each callback is guarded independently: one throwing method
        # must not suppress the extension's other deliveries (e.g. a
        # failing add_rt skipping decrease_thread_num would drift the
        # extension's concurrency gauge forever).
        try:
            fn(*args)
        except Exception:
            record_log.error(
                "[MetricExtension] %s failed", getattr(fn, "__name__", fn),
                exc_info=True,
            )

    @classmethod
    def on_pass(cls, resource: str, n: int, args: Sequence[object]) -> None:
        for ext in cls.get_extensions():
            cls._call(ext.add_pass, resource, n, *args)
            cls._call(ext.increase_thread_num, resource, *args)

    @classmethod
    def on_blocked(
        cls, resource: str, n: int, origin: str, block_error: object,
        args: Sequence[object],
    ) -> None:
        for ext in cls.get_extensions():
            cls._call(ext.add_block, resource, n, origin, block_error, *args)

    @classmethod
    def on_complete(cls, resource: str, rt_ms: int, n: int, err: int) -> None:
        for ext in cls.get_extensions():
            cls._call(ext.add_rt, resource, rt_ms)
            cls._call(ext.add_success, resource, n)
            if err:
                cls._call(ext.add_exception, resource, err, None)
            cls._call(ext.decrease_thread_num, resource)
