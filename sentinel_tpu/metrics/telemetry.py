"""Engine flight recorder: per-flush spans, histograms, blocked sketch.

The reference ships a metric-log/exporter stack that only ever sees
*per-resource* second aggregates (metric_log.py, block_log.py,
transport/prometheus.py); the engine internals that PR 2's depth-K
flush pipeline introduced — whether host encode actually overlaps
device execution, where a drain stalls, how full the in-flight queue
runs — were visible only through ``bench.py``'s one-off dicts. This
module is the first-class telemetry layer:

* a bounded ring-buffer **flight recorder** of structured per-flush
  spans (:class:`FlushSpan`) — flush id, pipeline depth and in-flight
  occupancy at dispatch, batch rows, encode/dispatch/settle wall-ms,
  arena and intern-cache hit/miss deltas, coalesced-fetch fallbacks —
  recorded by ``Engine._run_chunk`` with near-zero overhead and nothing
  at all when disabled (``sentinel.tpu.telemetry.enabled``);
* fixed-bucket **latency histograms** (metrics/histogram.py) for
  host-blocking flush time, coalesced drain fetches and end-to-end
  admission (dispatch start → verdicts materialized) — tails, not
  averages;
* a host-side **space-saving top-K sketch** of blocked weight per
  resource, fed by the *on-device* per-flush top-K that the flush
  kernel folds into its outputs (runtime/flush.py ``blk_topk``) — the
  data-plane heavy-hitter design (Sivaraman et al., arXiv:1611.04825;
  Basat et al., arXiv:1710.03155): compute the candidate set where the
  verdicts are, fetch only the summary on the existing coalesced
  ``device_get``;
* per-second engine aggregates drained by the metric-log timer into the
  rolled ``{app}-metrics.log`` files (resource ``__engine__``), and a
  Chrome trace-event export (:func:`spans_to_trace`) that
  ``tools/tracedump.py`` writes for Perfetto.

The bus is engine-scoped (one per :class:`Engine`); the process-global
engine's bus is therefore the process view. Config keys::

    sentinel.tpu.telemetry.enabled      default true
    sentinel.tpu.telemetry.ring         span ring capacity, default 4096
    sentinel.tpu.telemetry.blocked.topk.k
                                        device blocked top-K per flush,
                                        default 8 (0 disables the fold);
                                        falls back to the historical
                                        sentinel.tpu.telemetry.sketch.k
    sentinel.tpu.telemetry.blocked.topk.capacity
                                        host summary capacity, default
                                        64; falls back to
                                        sentinel.tpu.telemetry.sketch.capacity
    sentinel.tpu.telemetry.topk.export  rows the exports list when the
                                        fold is off, default 10

The ``blocked.*`` spelling landed with the statistics sketch tier
(runtime/sketch.py, ``sentinel.tpu.sketch.*``) so the PR-3
blocked-weight top-K and the count-min statistics tier stay
distinguishable in code, config, and docs; ``TelemetryBus.sketch`` /
``sketch_k`` remain as deprecated read aliases.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from sentinel_tpu.metrics.histogram import LatencyHistogram
from sentinel_tpu.utils.config import config


@dataclass(slots=True)
class FlushSpan:
    """One dispatched flush chunk's structured record. Mutable: the
    settle fields land later for a pipelined flush (the ring holds the
    reference, so readers see the update). Timestamps are
    ``time.perf_counter()`` seconds — monotonic, shared by every span
    in the process, which is all a trace needs."""

    flush_id: int
    t0: float  # perf_counter at encode start
    depth: int  # configured pipeline depth at dispatch
    inflight: int  # dispatched-but-unfetched flushes ahead of this one
    n_entries: int = 0  # single entry ops
    n_exits: int = 0  # single exit/trace ops
    n_bulk: int = 0  # bulk entry rows
    n_bulk_exits: int = 0  # bulk exit rows
    encode_ms: float = 0.0
    dispatch_ms: float = 0.0
    settle_t0: float = 0.0  # perf_counter when the result fetch began
    settle_end: float = 0.0  # perf_counter when verdicts materialized
    settle_ms: float = 0.0  # device→host fetch duration (own or coalesced share)
    deferred: bool = False  # dispatched without fetching (pipelined/async)
    settled: bool = False
    arena_hits: int = 0
    arena_misses: int = 0
    intern_hits: int = 0  # ParamIndex resolved-value cache delta since prev span
    intern_misses: int = 0
    fallbacks: int = 0  # coalesced-fetch failures this span rode through
    # Failover quarantined this flush: its device results were lost and
    # its verdicts came from the host fallback (runtime/failover.py).
    quarantined: bool = False
    # Autotune param-path cost attribution (runtime/autotune.py): the
    # shape bucket and path (PATH_CLOSED/PATH_SCAN) the cost memo
    # picked for this chunk's param batch — None/0 when autotune is off
    # or the chunk carried no eligible param batch. Internal to the
    # tuner; deliberately NOT part of as_dict().
    param_bucket: Optional[tuple] = None
    param_path: int = 0

    @property
    def rows(self) -> int:
        return self.n_entries + self.n_exits + self.n_bulk + self.n_bulk_exits

    @property
    def host_ms(self) -> float:
        """Host-blocking cost of this flush: encode + dispatch, plus
        the fetch when it was synchronous (a deferred settle overlaps
        the next flush's host work by design)."""
        ms = self.encode_ms + self.dispatch_ms
        if not self.deferred:
            ms += self.settle_ms
        return ms

    def as_dict(self) -> dict:
        return {
            "flush_id": self.flush_id,
            "t0": self.t0,
            "depth": self.depth,
            "inflight": self.inflight,
            "rows": self.rows,
            "n_entries": self.n_entries,
            "n_exits": self.n_exits,
            "n_bulk": self.n_bulk,
            "n_bulk_exits": self.n_bulk_exits,
            "encode_ms": round(self.encode_ms, 4),
            "dispatch_ms": round(self.dispatch_ms, 4),
            "settle_ms": round(self.settle_ms, 4),
            "deferred": self.deferred,
            "settled": self.settled,
            "arena_hits": self.arena_hits,
            "arena_misses": self.arena_misses,
            "intern_hits": self.intern_hits,
            "intern_misses": self.intern_misses,
            "fallbacks": self.fallbacks,
            "quarantined": self.quarantined,
        }


class SpaceSaving:
    """Bounded heavy-hitter summary (Metwally et al.'s space-saving, the
    merge target for the kernel's per-flush top-K). ``counts[key]`` is
    an overestimate by at most ``error[key]`` — the guarantee the
    differential test exercises: any key whose true weight exceeds the
    minimum counter is present."""

    __slots__ = ("capacity", "_counts", "_error", "_lock")

    def __init__(self, capacity: int = 64) -> None:
        self.capacity = max(1, int(capacity))
        self._counts: Dict[str, int] = {}
        self._error: Dict[str, int] = {}
        self._lock = threading.Lock()

    def offer(self, key: str, weight: int = 1) -> None:
        if weight <= 0:
            return
        with self._lock:
            c = self._counts.get(key)
            if c is not None:
                self._counts[key] = c + weight
                return
            if len(self._counts) < self.capacity:
                self._counts[key] = weight
                self._error[key] = 0
                return
            victim = min(self._counts, key=self._counts.__getitem__)
            floor = self._counts.pop(victim)
            self._error.pop(victim, None)
            self._counts[key] = floor + weight
            self._error[key] = floor

    def topk(self, k: int = 10) -> List[Tuple[str, int, int]]:
        """[(key, count, max_overestimate)] sorted by count desc."""
        with self._lock:
            items = sorted(
                self._counts.items(), key=lambda kv: kv[1], reverse=True
            )[: max(0, int(k))]
            return [(key, cnt, self._error.get(key, 0)) for key, cnt in items]

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            self._error.clear()


class TelemetryBus:
    """Engine-scoped telemetry: span ring + histograms + counters +
    blocked-resource sketch + per-second aggregates.

    Hot-path contract: when ``enabled`` is False the engine makes no
    calls here at all (one attribute read per flush); when True, a
    flush costs one dataclass build, one deque append and a few
    histogram records — microseconds against a multi-ms flush."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        ring: Optional[int] = None,
        sketch_k: Optional[int] = None,
        sketch_capacity: Optional[int] = None,
    ) -> None:
        self.enabled = (
            config.get_bool(config.TELEMETRY_ENABLED, True)
            if enabled is None
            else bool(enabled)
        )
        self.ring_size = max(
            1,
            ring
            if ring is not None
            else config.get_int(config.TELEMETRY_RING, 4096),
        )
        # Blocked-weight top-K fold size (PR 3) — NOT the statistics
        # sketch tier (sentinel.tpu.sketch.*, runtime/sketch.py). The
        # ``blocked.topk.k`` spelling is preferred; the historical
        # ``telemetry.sketch.k`` key is the fallback when unset.
        if sketch_k is not None:
            k = sketch_k
        else:
            k = config.get_int(config.TELEMETRY_BLOCKED_TOPK_K, -1)
            if k < 0:
                k = config.get_int(config.TELEMETRY_SKETCH_K, 8)
        self.blocked_topk_k = max(0, k)
        self._spans: "deque[FlushSpan]" = deque(maxlen=self.ring_size)
        self._lock = threading.Lock()
        self._next_id = 0
        self.hist_flush = LatencyHistogram()
        self.hist_drain = LatencyHistogram()
        self.hist_e2e = LatencyHistogram()
        # Per-drift-window over-admit counts of the speculative tier —
        # the distribution the differential bound is stated over (a
        # count histogram riding the same pow2-bucket machinery).
        self.hist_spec_drift = LatencyHistogram()
        self.counters: Dict[str, int] = {
            "flushes": 0,
            "ops": 0,
            "deferred_flushes": 0,
            "coalesced_fallbacks": 0,
            "arena_hits": 0,
            "arena_misses": 0,
            # Failure domain (runtime/failover.py): host-fallback
            # verdicts served while DEGRADED, health transitions, and
            # recovery probe flushes.
            "degraded_admits": 0,
            "degraded_blocks": 0,
            "health_transitions": 0,
            "probe_flushes": 0,
            # Speculative tier (runtime/speculative.py): fast-path
            # verdicts served, declines (device-only semantics or the
            # drift valve), reconciliation mismatches by direction, and
            # valve suspensions.
            "spec_admits": 0,
            "spec_blocks": 0,
            "spec_declined": 0,
            "spec_over_admits": 0,
            "spec_under_admits": 0,
            "spec_suspensions": 0,
            # Fast-tier coverage extensions (PR 7): shaped ops served
            # host-side and host system-gate blocks.
            "spec_shaped": 0,
            "spec_system_blocks": 0,
            # Ingest valve (runtime/ingest.py): ops shed at submit.
            "ingest_shed": 0,
            # Adapter-edge batch window (runtime/window.py): requests
            # coalesced into columnar windows, and windows flushed.
            "ingest_window_reqs": 0,
            "ingest_window_flushes": 0,
            # Statistics sketch tier (runtime/sketch.py): distinct keys
            # folded per chunk, heavy-hitter promotions/demotions, and
            # DEGRADED host-mirror folds.
            "sketch_keys": 0,
            "sketch_promotions": 0,
            "sketch_demotions": 0,
            "sketch_host_folds": 0,
            # Param admission path selection (Engine._encode_param):
            # batches routed to the closed-form rank path vs the
            # rounds/scan family — one count per encoded param batch.
            "param_closed_form": 0,
            "param_scan": 0,
            # Self-tuning control plane (runtime/autotune.py): applied
            # knob changes (depth / window retunes).
            "autotune_decisions": 0,
            # Sketch-tier cold-key admission ceiling
            # (sentinel.tpu.sketch.cold.qps): submits blocked from the
            # host count-min twin's estimate.
            "sketch_cold_blocks": 0,
            # Multi-process ingest plane (sentinel_tpu/ipc): request
            # frames drained / rows carried, worker-side ring-full
            # sheds folded in, dead-worker reaps with their auto-exited
            # live admissions.
            "ipc_frames": 0,
            "ipc_requests": 0,
            "ipc_sheds": 0,
            "ipc_worker_deaths": 0,
            "ipc_auto_exits": 0,
            # Engine hot-restart (PR 15): workers that detected the
            # boot-epoch bump and re-asserted their live ledgers into
            # this (new) engine world.
            "ipc_worker_reconnects": 0,
            # Cluster token plane (PR 16): milliseconds actually slept
            # honoring SHOULD_WAIT verdicts (bounded per op batch by
            # sentinel.tpu.cluster.wait.cap.ms — the pre-cap path slept
            # per op back-to-back, unbounded).
            "cluster_wait_ms": 0,
            # Black-box flight recorder (runtime/capture.py): chunks
            # and frame records spilled, bytes written, segment
            # rollovers, postmortem freezes, and bulk rows whose args
            # column could not be serialized (those rows replay
            # without args).
            "capture_chunks": 0,
            "capture_records": 0,
            "capture_bytes": 0,
            "capture_rollovers": 0,
            "capture_freezes": 0,
            "capture_args_dropped": 0,
        }
        # Bounded ring of health transitions (now_ms is engine-clock
        # relative ms): the flight-recorder view of the failover state
        # machine — the authoritative copy (with counters) lives on
        # FailoverManager; this one rides telemetry snapshots.
        self.health_events: "deque[Tuple[int, str, str, str]]" = deque(
            maxlen=64
        )
        if sketch_capacity is not None:
            cap = sketch_capacity
        else:
            cap = config.get_int(config.TELEMETRY_BLOCKED_TOPK_CAP, -1)
            if cap < 0:
                cap = config.get_int(config.TELEMETRY_SKETCH_CAP, 64)
        self.blocked_sketch = SpaceSaving(cap)
        # Most recent flush's device top-K, already name-resolved:
        # [(resource, blocked_weight)] — the "what is being throttled
        # right now" read, no extra host round-trip.
        self.last_blocked_topk: List[Tuple[str, int]] = []
        # Engine-clock-relative per-second aggregates for the metric
        # log: sec -> [flushes, ops, host_ms_sum]. Bounded: the timer
        # drains it every second; a stopped timer must not leak, so
        # inserts evict the oldest past _SEC_CAP.
        self._sec: Dict[int, List[float]] = {}
        self._SEC_CAP = 600

    # ------------------------------------------------------------------
    # naming-compat aliases (PR-3 callers): ``sketch``/``sketch_k``
    # predate the statistics sketch tier — the blocked-weight fold now
    # lives under its own name so the two planes stay distinguishable.
    # ------------------------------------------------------------------
    @property
    def sketch(self) -> SpaceSaving:
        """Deprecated alias of :attr:`blocked_sketch`."""
        return self.blocked_sketch

    @property
    def sketch_k(self) -> int:
        """Deprecated alias of :attr:`blocked_topk_k`."""
        return self.blocked_topk_k

    @property
    def export_topk_k(self) -> int:
        """How many blocked-top-K rows the exports list — the ONE home
        of the former hand-rolled ``sketch_k or 10`` (Prometheus, the
        ``telemetry`` command, and the sketch tier's candidate listing
        all read this)."""
        return self.blocked_topk_k or config.get_int(
            config.TELEMETRY_TOPK_EXPORT, 10
        )

    # ------------------------------------------------------------------
    # span lifecycle (engine hot path)
    # ------------------------------------------------------------------
    def begin_span(
        self,
        t0: float,
        depth: int,
        inflight: int,
        n_entries: int,
        n_exits: int,
        n_bulk: int,
        n_bulk_exits: int,
        deferred: bool,
        now_rel_ms: int,
    ) -> FlushSpan:
        with self._lock:
            fid = self._next_id
            self._next_id += 1
            span = FlushSpan(
                flush_id=fid, t0=t0, depth=depth, inflight=inflight,
                n_entries=n_entries, n_exits=n_exits, n_bulk=n_bulk,
                n_bulk_exits=n_bulk_exits, deferred=deferred,
            )
            self._spans.append(span)
            self.counters["flushes"] += 1
            if deferred:
                self.counters["deferred_flushes"] += 1
            self.counters["ops"] += span.rows
            sec = (now_rel_ms // 1000) * 1000
            agg = self._sec.get(sec)
            if agg is None:
                if len(self._sec) >= self._SEC_CAP:
                    self._sec.pop(min(self._sec), None)
                agg = self._sec[sec] = [0.0, 0.0, 0.0]
            agg[0] += 1
            agg[1] += span.rows
        return span

    def dispatch_done(self, span: FlushSpan) -> None:
        """Encode+dispatch times are on the span; record the deferred
        flush's host-blocking cost now (its settle overlaps later host
        work by design)."""
        if span.deferred:
            self.hist_flush.record(span.encode_ms + span.dispatch_ms)
            self._add_sec_host_ms(span.encode_ms + span.dispatch_ms)

    def settle(self, span: FlushSpan, settle_t0: float, end: float) -> None:
        """Verdicts materialized: close the span, record histograms."""
        span.settle_t0 = settle_t0
        span.settle_end = end
        span.settle_ms = max(0.0, (end - settle_t0) * 1e3)
        span.settled = True
        if not span.deferred:
            self.hist_flush.record(span.host_ms)
            self._add_sec_host_ms(span.host_ms)
        self.hist_e2e.record(max(0.0, (end - span.t0) * 1e3))

    def _add_sec_host_ms(self, ms: float) -> None:
        with self._lock:
            # Attribute to the newest live second — per-second host-ms
            # is a rate diagnostic, not an exact ledger.
            if self._sec:
                self._sec[max(self._sec)][2] += ms

    def note_drain(self, ms: float) -> None:
        self.hist_drain.record(ms)

    def note_fallback(self, n: int = 1) -> None:
        with self._lock:
            self.counters["coalesced_fallbacks"] += n

    def note_arena(self, hits: int, misses: int) -> None:
        with self._lock:
            self.counters["arena_hits"] += hits
            self.counters["arena_misses"] += misses

    def note_health(self, frm: str, to: str, reason: str,
                    now_ms: int = 0) -> None:
        """One failover state transition (span-mark analog: spans that
        settle as quarantined carry the per-flush view; this is the
        engine-level event stream)."""
        with self._lock:
            self.counters["health_transitions"] += 1
            self.health_events.append((now_ms, frm, to, reason))

    def note_degraded(self, admits: int, blocks: int) -> None:
        with self._lock:
            self.counters["degraded_admits"] += admits
            self.counters["degraded_blocks"] += blocks

    def note_probe(self) -> None:
        with self._lock:
            self.counters["probe_flushes"] += 1

    # ------------------------------------------------------------------
    # speculative tier (runtime/speculative.py)
    # ------------------------------------------------------------------
    def note_speculative(self, admits: int, blocks: int) -> None:
        with self._lock:
            self.counters["spec_admits"] += admits
            self.counters["spec_blocks"] += blocks

    def note_spec_declined(self, n: int = 1) -> None:
        with self._lock:
            self.counters["spec_declined"] += n

    def note_spec_drift(self, over: int, under: int) -> None:
        with self._lock:
            self.counters["spec_over_admits"] += over
            self.counters["spec_under_admits"] += under

    def note_spec_window(self, net_over: int) -> None:
        """One closed drift window: its NET excess-admit count joins
        the drift histogram (the bound is per window, so the histogram
        is per window too — raw per-direction mismatches ride the
        counters only)."""
        self.hist_spec_drift.record(float(net_over))

    def note_spec_suspended(self) -> None:
        with self._lock:
            self.counters["spec_suspensions"] += 1

    def note_spec_shaped(self, n: int = 1) -> None:
        with self._lock:
            self.counters["spec_shaped"] += n

    def note_spec_system_block(self, n: int = 1) -> None:
        with self._lock:
            self.counters["spec_system_blocks"] += n

    def note_ingest_shed(self, n: int = 1) -> None:
        with self._lock:
            self.counters["ingest_shed"] += n

    # ------------------------------------------------------------------
    # black-box flight recorder (runtime/capture.py)
    # ------------------------------------------------------------------
    def note_capture(
        self, chunks: int, records: int, nbytes: int,
        rollovers: int = 0, args_dropped: int = 0,
    ) -> None:
        """One journal flush interval's deltas — the capture journal
        batches its counter publishes so the hot path stays at one
        attribute read plus the spill itself."""
        with self._lock:
            self.counters["capture_chunks"] += chunks
            self.counters["capture_records"] += records
            self.counters["capture_bytes"] += nbytes
            self.counters["capture_rollovers"] += rollovers
            self.counters["capture_args_dropped"] += args_dropped

    def note_capture_freeze(self, n: int = 1) -> None:
        with self._lock:
            self.counters["capture_freezes"] += n

    def note_window(self, reqs: int) -> None:
        """One adapter-edge batch window flushed with ``reqs`` coalesced
        requests (runtime/window.py)."""
        with self._lock:
            self.counters["ingest_window_reqs"] += reqs
            self.counters["ingest_window_flushes"] += 1

    # ------------------------------------------------------------------
    # statistics sketch tier (runtime/sketch.py)
    # ------------------------------------------------------------------
    def note_sketch_keys(self, n: int) -> None:
        with self._lock:
            self.counters["sketch_keys"] += n

    def note_sketch_promotion(self, n: int = 1) -> None:
        with self._lock:
            self.counters["sketch_promotions"] += n

    def note_sketch_demotion(self, n: int = 1) -> None:
        with self._lock:
            self.counters["sketch_demotions"] += n

    def note_sketch_host_fold(self, n: int = 1) -> None:
        with self._lock:
            self.counters["sketch_host_folds"] += n

    def note_param_path(self, closed: bool) -> None:
        """One encoded param batch routed to the closed-form rank path
        (``closed``) or the rounds/scan family."""
        with self._lock:
            self.counters[
                "param_closed_form" if closed else "param_scan"
            ] += 1

    def note_autotune_decision(self, n: int = 1) -> None:
        with self._lock:
            self.counters["autotune_decisions"] += n

    def note_cluster_wait(self, ms: int) -> None:
        """Milliseconds actually slept honoring cluster SHOULD_WAIT
        verdicts (already bounded by the per-op-batch cap)."""
        with self._lock:
            self.counters["cluster_wait_ms"] += ms

    def note_sketch_cold_block(self, n: int = 1) -> None:
        with self._lock:
            self.counters["sketch_cold_blocks"] += n

    # ------------------------------------------------------------------
    # multi-process ingest plane (sentinel_tpu/ipc)
    # ------------------------------------------------------------------
    def note_ipc_frames(self, frames: int, rows: int) -> None:
        with self._lock:
            self.counters["ipc_frames"] += frames
            self.counters["ipc_requests"] += rows

    def note_ipc_shed(self, n: int = 1) -> None:
        with self._lock:
            self.counters["ipc_sheds"] += n

    def note_ipc_worker_death(self, released: int) -> None:
        with self._lock:
            self.counters["ipc_worker_deaths"] += 1
            self.counters["ipc_auto_exits"] += released

    def note_ipc_reconnect(self) -> None:
        with self._lock:
            self.counters["ipc_worker_reconnects"] += 1

    def fold_blocked_topk(self, pairs: Sequence[Tuple[str, int]]) -> None:
        """Fold one flush's device top-K (already name-resolved) into
        the running space-saving summary."""
        for key, w in pairs:
            self.blocked_sketch.offer(key, w)
        self.last_blocked_topk = list(pairs)

    # ------------------------------------------------------------------
    # readers
    # ------------------------------------------------------------------
    def spans(self) -> List[FlushSpan]:
        with self._lock:
            return list(self._spans)

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self.counters)

    def drain_second_aggregates(self, upto_rel_ms: int) -> List[Tuple[int, int, int, float]]:
        """Completed engine-clock seconds strictly before
        ``upto_rel_ms`` (second-aligned), removed from the bus:
        [(sec_rel_ms, flushes, ops, host_ms_sum)] ascending — the
        metric-log timer's pull."""
        out = []
        with self._lock:
            for sec in sorted(self._sec):
                if sec >= upto_rel_ms:
                    break
                f, o, ms = self._sec.pop(sec)
                out.append((sec, int(f), int(o), ms))
        return out

    def snapshot(self, engine=None) -> dict:
        """Everything the ``telemetry`` transport command serves."""
        out = {
            "enabled": self.enabled,
            "ring_size": self.ring_size,
            "spans_recorded": self._next_id,
            "counters": self.counters_snapshot(),
            "flush_ms": self.hist_flush.summary(),
            "drain_ms": self.hist_drain.summary(),
            "e2e_ms": self.hist_e2e.summary(),
            "spec_drift_per_window": self.hist_spec_drift.summary(),
            "blocked_topk": [
                {"resource": k, "weight": c, "max_error": e}
                for k, c, e in self.blocked_sketch.topk(self.export_topk_k)
            ],
            "last_flush_blocked_topk": [
                {"resource": k, "weight": w} for k, w in self.last_blocked_topk
            ],
            "recent_spans": [s.as_dict() for s in self.spans()[-16:]],
            "health_events": [
                {"now_ms": ms, "from": f, "to": t, "reason": r}
                for ms, f, t, r in list(self.health_events)
            ],
        }
        if engine is not None:
            out["pipeline"] = engine.pipeline_stats()
            out["pipeline_depth"] = engine.pipeline_depth
            out["last_flush_host_ms"] = engine.last_flush_host_ms
            spec = getattr(engine, "speculative", None)
            if spec is not None and spec.enabled:
                out["speculative"] = spec.snapshot()
            valve = getattr(engine, "ingest", None)
            if valve is not None and valve.armed:
                out["ingest"] = valve.snapshot()
            rm = getattr(engine, "resource_metrics", None)
            if rm is not None and rm.enabled:
                out["resource_metrics"] = rm.snapshot()
            pindex = getattr(engine, "param_index", None)
            if pindex is not None and hasattr(pindex, "cache_stats"):
                out["param_cache"] = pindex.cache_stats()
            tier = getattr(engine, "sketch", None)
            if tier is not None and tier.armed:
                out["sketch_tier"] = tier.snapshot()
            at = getattr(engine, "autotune", None)
            if at is not None and at.enabled:
                out["autotune"] = at.snapshot()
        return out

    def bench_summary(self) -> dict:
        """Compact summary for bench.py's JSON line."""
        c = self.counters_snapshot()
        denom = c["arena_hits"] + c["arena_misses"]
        return {
            "flushes": c["flushes"],
            "ops": c["ops"],
            "flush_ms_p50": self.hist_flush.percentile(0.5),
            "flush_ms_p99": self.hist_flush.percentile(0.99),
            "e2e_ms_p50": self.hist_e2e.percentile(0.5),
            "e2e_ms_p99": self.hist_e2e.percentile(0.99),
            "drain_ms_p99": self.hist_drain.percentile(0.99),
            "arena_hit_rate": round(c["arena_hits"] / denom, 4) if denom else 0.0,
            "coalesced_fallbacks": c["coalesced_fallbacks"],
            "blocked_topk": [
                [k, c_] for k, c_, _ in self.blocked_sketch.topk(5)
            ],
        }


# ----------------------------------------------------------------------
# Chrome trace-event export (Perfetto-loadable)
# ----------------------------------------------------------------------
def spans_to_trace(
    spans: Sequence[FlushSpan], pid: int = 1, records: Sequence = None
) -> dict:
    """Convert flight-recorder spans to the Chrome trace-event JSON
    object format (Perfetto loads it directly). The emission mechanics
    and layout live in :func:`metrics.perfetto.spans_to_trace` — the
    shared home of trace-event building for tracedump / fleetdump /
    replay; this name stays as the stable import surface."""
    from sentinel_tpu.metrics.perfetto import spans_to_trace as _impl

    return _impl(spans, pid=pid, records=records)
