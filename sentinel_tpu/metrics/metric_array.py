"""The sliding-window counter tensor — LeapArray, the TPU way.

The reference's hot data structure is ``LeapArray<MetricBucket>``: an
``AtomicReferenceArray`` of time-bucketed counter cells, where every
request CAS-creates / reuses / tryLock-resets its current bucket
(reference: sentinel-core/.../slots/statistic/base/LeapArray.java:41-222)
and bumps ``LongAdder`` cells (data/MetricBucket.java:28-120).

Here the whole fleet of LeapArrays for all nodes is ONE tensor per
geometry::

    counts       int32 [rows, buckets, NUM_EVENTS]
    min_rt       int32 [rows, buckets]
    window_start int32 [rows, buckets]     (ms relative to clock epoch)

and a batch of updates is applied by a single jitted, single-writer
kernel — the CAS loop becomes::

    new_ws = window_start.at[rows, idx].max(entry_ws)   # who rolls the bucket
    stale  = new_ws > window_start                      # buckets that rolled
    counts = where(stale, 0, counts).at[rows, idx].add(deltas_in_new_window)

Semantics intentionally preserved from the reference:

* bucket index ``(ts // window_len) % buckets`` and aligned window start
  ``ts - ts % window_len`` (LeapArray.java:109-119);
* a bucket is deprecated for reads iff ``now - window_start > interval``
  (LeapArray#isWindowDeprecated, strict inequality);
* updates whose window is older than the bucket's (post-batch) window are
  discarded — identical to the sequential outcome where the newer request
  resets the bucket after the older one wrote it;
* ``min_rt`` starts at the statistic max RT (4900 by default), matching
  MetricBucket's ``minRt`` initialisation.

Time is int32 ms relative to the engine epoch (see utils/clock.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from sentinel_tpu.metrics.events import NUM_EVENTS


class MetricArrayConfig(NamedTuple):
    """Geometry of one window array family.

    sample_count × window_len_ms = interval_ms, exactly like
    LeapArray's constructor invariant (LeapArray.java:58-76).
    """

    sample_count: int
    interval_ms: int
    max_rt: int = 4900  # reference: Constants.TIME_DROP_VALVE / statisticMaxRt

    @property
    def window_len_ms(self) -> int:
        return self.interval_ms // self.sample_count

    @property
    def empty_ws(self) -> int:
        # A window start so old that it is always deprecated for ts >= 0.
        return -self.interval_ms - 1


class MetricArrayState(NamedTuple):
    counts: jax.Array  # int32 [R, B, E]
    min_rt: jax.Array  # int32 [R, B]
    window_start: jax.Array  # int32 [R, B]

    @property
    def n_rows(self) -> int:
        return self.counts.shape[0]


def make_state(n_rows: int, cfg: MetricArrayConfig) -> MetricArrayState:
    b = cfg.sample_count
    return MetricArrayState(
        counts=jnp.zeros((n_rows, b, NUM_EVENTS), dtype=jnp.int32),
        min_rt=jnp.full((n_rows, b), cfg.max_rt, dtype=jnp.int32),
        window_start=jnp.full((n_rows, b), cfg.empty_ws, dtype=jnp.int32),
    )


def grow(state: MetricArrayState, new_rows: int, cfg: MetricArrayConfig) -> MetricArrayState:
    """Host-side row-capacity growth (new rows empty)."""
    extra = new_rows - state.n_rows
    if extra <= 0:
        return state
    tail = make_state(extra, cfg)
    return MetricArrayState(
        counts=jnp.concatenate([state.counts, tail.counts], axis=0),
        min_rt=jnp.concatenate([state.min_rt, tail.min_rt], axis=0),
        window_start=jnp.concatenate([state.window_start, tail.window_start], axis=0),
    )


def update(
    cfg: MetricArrayConfig,
    state: MetricArrayState,
    rows: jax.Array,  # int32 [N]
    ts: jax.Array,  # int32 [N], ms rel epoch, >= 0
    deltas: jax.Array,  # int32 [N, NUM_EVENTS]
    rt_sample: Optional[jax.Array] = None,  # int32 [N] per-entry RT for min tracking
    mask: Optional[jax.Array] = None,  # bool [N] entry validity
) -> MetricArrayState:
    """Apply a batch of bucket updates (the LeapArray.currentWindow + add path).

    Masked-out entries contribute nothing. Duplicate (row, bucket) keys in
    one batch accumulate; entries from a superseded window are dropped
    (see module docstring).
    """
    wlen = cfg.window_len_ms
    b = cfg.sample_count
    idx = (ts // wlen) % b
    ws = ts - ts % wlen

    if mask is None:
        mask = jnp.ones(rows.shape, dtype=bool)
    rows_eff = jnp.where(mask, rows, 0).astype(jnp.int32)
    ws_eff = jnp.where(mask, ws, jnp.int32(cfg.empty_ws))

    # 1. Advance window starts (scatter-max — newest write wins the bucket).
    new_ws = state.window_start.at[rows_eff, idx].max(ws_eff, mode="drop")

    # 2. Zero buckets that rolled to a newer window (the vectorized
    #    equivalent of LeapArray's tryLock+reset, LeapArray.java:180-221).
    stale = new_ws > state.window_start
    counts = jnp.where(stale[:, :, None], 0, state.counts)
    min_rt = jnp.where(stale, jnp.int32(cfg.max_rt), state.min_rt)

    # 3. Accumulate entries that belong to the bucket's (new) window.
    contrib = mask & (ws_eff == new_ws[rows_eff, idx])
    deltas_eff = jnp.where(contrib[:, None], deltas, 0).astype(jnp.int32)
    counts = counts.at[rows_eff, idx, :].add(deltas_eff, mode="drop")

    if rt_sample is not None:
        rt_eff = jnp.where(contrib, rt_sample, jnp.int32(2**31 - 1))
        min_rt = min_rt.at[rows_eff, idx].min(rt_eff, mode="drop")

    return MetricArrayState(counts=counts, min_rt=min_rt, window_start=new_ws)


def _valid_mask(cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array) -> jax.Array:
    # Reference: LeapArray#isWindowDeprecated — deprecated iff
    # time - windowStart > intervalInMs (strict).
    return (now - state.window_start) <= cfg.interval_ms


def window_sums(
    cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array
) -> jax.Array:
    """Windowed event sums per row: int32 [R, NUM_EVENTS].

    Equivalent of ArrayMetric.pass_()/block()/success()/rt()... which sum
    MetricBucket cells over non-deprecated windows (ArrayMetric.java:37+).
    QPS values are these sums divided by ``interval_ms/1000`` (float) —
    division left to callers to keep this integer-exact.
    """
    valid = _valid_mask(cfg, state, now)
    return jnp.sum(state.counts * valid[:, :, None].astype(jnp.int32), axis=1)


def window_min_rt(cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array) -> jax.Array:
    """Windowed min RT per row (int32 [R]); ``max_rt`` when empty.

    Reference: ArrayMetric#minRt over valid buckets, floored at 1 by
    StatisticNode.minRt readers (StatisticNode.java keeps the raw value;
    SystemRuleManager's BBR uses max(1, minRt) — flooring is done there).
    """
    valid = _valid_mask(cfg, state, now)
    masked = jnp.where(valid, state.min_rt, jnp.int32(cfg.max_rt))
    return jnp.min(masked, axis=1)


def bucket_windows(
    cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(window_start [R,B], counts [R,B,E], valid [R,B]) for the metric
    log pipeline (MetricTimerListener reads per-second buckets via
    node.metrics(); reference: node/metric/MetricTimerListener.java:34-70).
    """
    valid = _valid_mask(cfg, state, now)
    return state.window_start, state.counts, valid
