"""The sliding-window counter tensor — LeapArray, the TPU way.

The reference's hot data structure is ``LeapArray<MetricBucket>``: an
``AtomicReferenceArray`` of time-bucketed counter cells, where every
request CAS-creates / reuses / tryLock-resets its current bucket
(reference: sentinel-core/.../slots/statistic/base/LeapArray.java:41-222)
and bumps ``LongAdder`` cells (data/MetricBucket.java:28-120).

Here the whole fleet of LeapArrays for all nodes is ONE tensor per
geometry::

    counts       int32 [rows, buckets, NUM_EVENTS]
    min_rt       int32 [rows, buckets]
    window_start int32 [rows, buckets]     (ms relative to clock epoch)

and a batch of updates is applied by a single jitted, single-writer
kernel — the CAS loop becomes::

    new_ws = window_start.at[rows, idx].max(entry_ws)   # who rolls the bucket
    stale  = new_ws > window_start                      # buckets that rolled
    counts = where(stale, 0, counts).at[rows, idx].add(deltas_in_new_window)

Semantics intentionally preserved from the reference:

* bucket index ``(ts // window_len) % buckets`` and aligned window start
  ``ts - ts % window_len`` (LeapArray.java:109-119);
* a bucket is deprecated for reads iff ``now - window_start > interval``
  (LeapArray#isWindowDeprecated, strict inequality);
* updates whose window is older than the bucket's (post-batch) window are
  discarded — identical to the sequential outcome where the newer request
  resets the bucket after the older one wrote it;
* ``min_rt`` starts at the statistic max RT (4900 by default), matching
  MetricBucket's ``minRt`` initialisation.

Time is int32 ms relative to the engine epoch (see utils/clock.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from sentinel_tpu.metrics.events import NUM_EVENTS


class MetricArrayConfig(NamedTuple):
    """Geometry of one window array family.

    sample_count × window_len_ms = interval_ms, exactly like
    LeapArray's constructor invariant (LeapArray.java:58-76).
    """

    sample_count: int
    interval_ms: int
    max_rt: int = 4900  # reference: Constants.TIME_DROP_VALVE / statisticMaxRt

    @property
    def window_len_ms(self) -> int:
        return self.interval_ms // self.sample_count

    @property
    def empty_ws(self) -> int:
        # A window start so old that it is always deprecated for ts >= 0.
        return -self.interval_ms - 1


class MetricArrayState(NamedTuple):
    counts: jax.Array  # int32 [R, B, E]
    min_rt: jax.Array  # int32 [R, B]
    window_start: jax.Array  # int32 [R, B]

    @property
    def n_rows(self) -> int:
        return self.counts.shape[0]


def make_state(n_rows: int, cfg: MetricArrayConfig) -> MetricArrayState:
    b = cfg.sample_count
    return MetricArrayState(
        counts=jnp.zeros((n_rows, b, NUM_EVENTS), dtype=jnp.int32),
        min_rt=jnp.full((n_rows, b), cfg.max_rt, dtype=jnp.int32),
        window_start=jnp.full((n_rows, b), cfg.empty_ws, dtype=jnp.int32),
    )


def grow(state: MetricArrayState, new_rows: int, cfg: MetricArrayConfig) -> MetricArrayState:
    """Host-side row-capacity growth (new rows empty)."""
    extra = new_rows - state.n_rows
    if extra <= 0:
        return state
    tail = make_state(extra, cfg)
    return MetricArrayState(
        counts=jnp.concatenate([state.counts, tail.counts], axis=0),
        min_rt=jnp.concatenate([state.min_rt, tail.min_rt], axis=0),
        window_start=jnp.concatenate([state.window_start, tail.window_start], axis=0),
    )


def update(
    cfg: MetricArrayConfig,
    state: MetricArrayState,
    rows: jax.Array,  # int32 [N]
    ts: jax.Array,  # int32 [N], ms rel epoch, >= 0
    deltas: jax.Array,  # int32 [N, NUM_EVENTS]
    rt_sample: Optional[jax.Array] = None,  # int32 [N] per-entry RT for min tracking
    mask: Optional[jax.Array] = None,  # bool [N] entry validity
) -> MetricArrayState:
    """Apply a batch of bucket updates (the LeapArray.currentWindow + add path).

    Masked-out entries contribute nothing. Duplicate (row, bucket) keys in
    one batch accumulate; entries from a superseded window are dropped
    (see module docstring).

    Implementation: O(batch log batch), never O(rows). The batch is
    sorted by (row, bucket) and reduced to one aggregate per touched
    bucket with segment sums; each touched bucket is then updated with a
    UNIQUE-index scatter, choosing between

    * add — the aggregate belongs to the bucket's stored window;
    * set — the aggregate's window is newer (the bucket rolled: the
      reference's tryLock+reset, LeapArray.java:180-221);
    * drop — the aggregate's window is older than the stored one.

    Touched-only writes keep the flush cost independent of the number of
    rows (the minute tensor alone is GBs at 1M rows); untouched stale
    buckets are excluded lazily by the read-side deprecation mask.
    """
    wlen = cfg.window_len_ms
    b = cfg.sample_count
    n = rows.shape[0]
    r_rows = state.n_rows
    idx = ((ts // wlen) % b).astype(jnp.int32)
    ws = (ts - ts % wlen).astype(jnp.int32)

    if mask is None:
        mask = jnp.ones(rows.shape, dtype=bool)

    # Sort by flat bucket key; masked-out entries sort to the tail.
    key = jnp.where(mask, rows.astype(jnp.int32) * b + idx, jnp.int32(r_rows * b))
    pos = jnp.arange(n, dtype=jnp.int32)
    key_s, p_s = jax.lax.sort((key, pos), num_keys=1)
    ws_s = ws[p_s]
    mask_s = mask[p_s]

    new_seg = jnp.concatenate([jnp.ones((1,), bool), key_s[1:] != key_s[:-1]])
    seg_id = jnp.cumsum(new_seg.astype(jnp.int32)) - 1  # [n], ids dense by position

    # Newest window per touched bucket wins; older-window entries drop.
    seg_ws = jax.ops.segment_max(
        jnp.where(mask_s, ws_s, jnp.int32(cfg.empty_ws)), seg_id, num_segments=n
    )
    contrib = mask_s & (ws_s == seg_ws[seg_id])

    deltas_s = jnp.where(contrib[:, None], deltas[p_s], 0).astype(jnp.int32)
    seg_sums = jax.ops.segment_sum(deltas_s, seg_id, num_segments=n)  # [n, E]
    if rt_sample is not None:
        rt_s = jnp.where(contrib, rt_sample[p_s], jnp.int32(2**31 - 1))
        seg_rt = jax.ops.segment_min(rt_s, seg_id, num_segments=n)

    # One representative position per touched bucket (segment starts).
    valid_seg = new_seg & mask_s
    u_key = jnp.where(valid_seg, key_s, jnp.int32(r_rows * b))
    u_row = jnp.minimum(u_key // b, r_rows)  # r_rows -> dropped by mode="drop"
    u_idx = u_key % b
    u_sid = seg_id  # at segment-start positions, seg_id is the segment's id
    u_ws = seg_ws[u_sid]
    u_sums = seg_sums[u_sid]

    old_ws = state.window_start[jnp.clip(u_row, 0, r_rows - 1), u_idx]
    same_win = valid_seg & (u_ws == old_ws)
    newer_win = valid_seg & (u_ws > old_ws)

    drop_i = jnp.int32(r_rows)
    add_row = jnp.where(same_win, u_row, drop_i)
    set_row = jnp.where(newer_win, u_row, drop_i)

    counts = state.counts.at[add_row, u_idx, :].add(u_sums, mode="drop", unique_indices=True)
    counts = counts.at[set_row, u_idx, :].set(u_sums, mode="drop", unique_indices=True)

    new_ws_arr = state.window_start.at[set_row, u_idx].set(u_ws, mode="drop", unique_indices=True)

    min_rt = state.min_rt
    if rt_sample is not None:
        u_rt = seg_rt[u_sid]
        min_rt = min_rt.at[add_row, u_idx].min(u_rt, mode="drop", unique_indices=True)
        min_rt = min_rt.at[set_row, u_idx].set(
            jnp.minimum(u_rt, jnp.int32(cfg.max_rt)), mode="drop", unique_indices=True
        )
    else:
        min_rt = min_rt.at[set_row, u_idx].set(
            jnp.int32(cfg.max_rt), mode="drop", unique_indices=True
        )

    return MetricArrayState(counts=counts, min_rt=min_rt, window_start=new_ws_arr)


def _valid_mask(cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array) -> jax.Array:
    # Reference: LeapArray#isWindowDeprecated — deprecated iff
    # time - windowStart > intervalInMs (strict).
    return (now - state.window_start) <= cfg.interval_ms


def window_sums(
    cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array
) -> jax.Array:
    """Windowed event sums per row: int32 [R, NUM_EVENTS].

    Equivalent of ArrayMetric.pass_()/block()/success()/rt()... which sum
    MetricBucket cells over non-deprecated windows (ArrayMetric.java:37+).
    QPS values are these sums divided by ``interval_ms/1000`` (float) —
    division left to callers to keep this integer-exact.
    """
    valid = _valid_mask(cfg, state, now)
    return jnp.sum(state.counts * valid[:, :, None].astype(jnp.int32), axis=1)


def window_min_rt(cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array) -> jax.Array:
    """Windowed min RT per row (int32 [R]); ``max_rt`` when empty.

    Reference: ArrayMetric#minRt over valid buckets, floored at 1 by
    StatisticNode.minRt readers (StatisticNode.java keeps the raw value;
    SystemRuleManager's BBR uses max(1, minRt) — flooring is done there).
    """
    valid = _valid_mask(cfg, state, now)
    masked = jnp.where(valid, state.min_rt, jnp.int32(cfg.max_rt))
    return jnp.min(masked, axis=1)


def bucket_windows(
    cfg: MetricArrayConfig, state: MetricArrayState, now: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(window_start [R,B], counts [R,B,E], valid [R,B]) for the metric
    log pipeline (MetricTimerListener reads per-second buckets via
    node.metrics(); reference: node/metric/MetricTimerListener.java:34-70).
    """
    valid = _valid_mask(cfg, state, now)
    return state.window_start, state.counts, valid
