"""Fixed-bucket latency histograms with power-of-two bounds.

The engine's timing diagnostics were avg-only (``last_flush_host_ms``
keeps one number per stage); tails are what actually matter when tuning
the depth-K flush pipeline on a real accelerator window, so the
telemetry bus records every flush/drain/end-to-end duration into these
histograms instead.

Design constraints (why not a library):

* **Fixed pow2 buckets** — bucket ``i`` covers ``(base·2^(i-1),
  base·2^i]`` ms (bucket 0 is ``(0, base]``), so two histograms with the
  same geometry are mergeable by adding their count vectors — the
  property Prometheus ``_bucket`` series and cross-process aggregation
  both need. No dynamic rebucketing, ever.
* **O(1) record** — a ``bit_length`` on the scaled integer, no search.
* **numpy counts** — ``merge`` and the cumulative render are vector
  adds; the snapshot is a copy, safe to hold across later records.
"""

from __future__ import annotations

import math
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

# Default geometry: 1 µs .. ~33.5 s in 26 pow2 buckets (+1 overflow).
DEFAULT_BASE_MS = 0.001
DEFAULT_N_BUCKETS = 26


class LatencyHistogram:
    """Thread-safe fixed-bucket histogram of millisecond durations."""

    __slots__ = ("base_ms", "n_buckets", "bounds_ms", "_counts", "_sum_ms",
                 "_lock")

    def __init__(
        self,
        base_ms: float = DEFAULT_BASE_MS,
        n_buckets: int = DEFAULT_N_BUCKETS,
    ) -> None:
        if base_ms <= 0 or n_buckets < 1:
            raise ValueError("histogram geometry must be positive")
        self.base_ms = float(base_ms)
        self.n_buckets = int(n_buckets)
        # Upper bound of bucket i (inclusive): base * 2**i.
        self.bounds_ms = self.base_ms * np.exp2(
            np.arange(self.n_buckets, dtype=np.float64)
        )
        # counts[n_buckets] is the +Inf overflow bucket.
        self._counts = np.zeros(self.n_buckets + 1, dtype=np.int64)
        self._sum_ms = 0.0
        self._lock = threading.Lock()

    # ------------------------------------------------------------------
    def _bucket_of(self, ms: float) -> int:
        v = ms / self.base_ms
        if v <= 1.0:
            return 0
        # ceil to the next integer so 2.5×base lands in the 4×base
        # bucket; bit_length is the exact pow2 exponent.
        b = (math.ceil(v) - 1).bit_length()
        return b if b < self.n_buckets else self.n_buckets

    def bucket_of(self, ms: float) -> int:
        """Public bucket index for ``ms`` (the exemplar keying used by
        the admission tracer — exemplars must land on the same
        ``_bucket`` series their latency was counted in)."""
        return self._bucket_of(max(ms, 0.0))

    def record(self, ms: float) -> None:
        if ms < 0.0:
            ms = 0.0
        b = self._bucket_of(ms)
        with self._lock:
            self._counts[b] += 1
            self._sum_ms += ms

    def record_many(self, ms_values: Sequence[float]) -> None:
        a = np.asarray(ms_values, dtype=np.float64)
        if a.size == 0:
            return
        a = np.maximum(a, 0.0)
        # side="left": bounds are inclusive upper edges.
        idx = np.searchsorted(self.bounds_ms, a, side="left")
        add = np.bincount(idx, minlength=self.n_buckets + 1).astype(np.int64)
        with self._lock:
            self._counts += add
            self._sum_ms += float(a.sum())

    # ------------------------------------------------------------------
    def merge(self, other: "LatencyHistogram") -> None:
        """Fold another histogram's counts in (same geometry required —
        mergeability is the whole point of fixed buckets)."""
        if (
            other.base_ms != self.base_ms
            or other.n_buckets != self.n_buckets
        ):
            raise ValueError("cannot merge histograms with different geometry")
        counts, total = other.snapshot_counts()
        with self._lock:
            self._counts += counts
            self._sum_ms += total

    def snapshot_counts(self) -> Tuple[np.ndarray, float]:
        with self._lock:
            return self._counts.copy(), self._sum_ms

    @property
    def count(self) -> int:
        with self._lock:
            return int(self._counts.sum())

    @property
    def sum_ms(self) -> float:
        with self._lock:
            return self._sum_ms

    def reset(self) -> None:
        with self._lock:
            self._counts[:] = 0
            self._sum_ms = 0.0

    # ------------------------------------------------------------------
    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding the q-quantile observation
        (0 < q <= 1). Conservative by construction: the true value is
        <= the returned bound. 0.0 on an empty histogram; the overflow
        bucket reports the largest finite bound."""
        counts, _ = self.snapshot_counts()
        total = int(counts.sum())
        if total == 0:
            return 0.0
        target = max(1, math.ceil(q * total))
        cum = np.cumsum(counts)
        b = int(np.searchsorted(cum, target, side="left"))
        return float(self.bounds_ms[min(b, self.n_buckets - 1)])

    def summary(self) -> dict:
        counts, total_ms = self.snapshot_counts()
        n = int(counts.sum())
        return {
            "count": n,
            "sum_ms": round(total_ms, 3),
            "mean_ms": round(total_ms / n, 4) if n else 0.0,
            "p50_ms": self.percentile(0.50),
            "p99_ms": self.percentile(0.99),
        }

    # ------------------------------------------------------------------
    def prometheus_lines(
        self,
        name: str,
        help_text: str,
        labels: str = "",
        exemplars: Optional[dict] = None,
    ) -> List[str]:
        """Render as a Prometheus histogram family: cumulative
        ``_bucket`` series with ``le`` upper bounds, then ``_sum`` and
        ``_count``. ``labels`` is a pre-rendered ``k="v"`` list (no
        braces) merged with the ``le`` label. ``exemplars`` maps a
        bucket index (``bucket_of``; ``n_buckets`` = the +Inf bucket)
        to ``(trace_id, value_ms)`` — rendered as an OpenMetrics
        exemplar (``# {trace_id="…"} value``) on that bucket line, the
        metrics→trace pivot Grafana/Prometheus follow natively."""
        counts, total_ms = self.snapshot_counts()
        cum = np.cumsum(counts)
        out = [f"# HELP {name} {help_text}", f"# TYPE {name} histogram"]
        sep = "," if labels else ""

        def lbl(le: str) -> str:
            return "{" + labels + sep + f'le="{le}"' + "}"

        def exm(i: int) -> str:
            ex = exemplars.get(i) if exemplars else None
            if ex is None:
                return ""
            tid, value = ex
            return f' # {{trace_id="{tid}"}} {round(float(value), 6)}'

        for i in range(self.n_buckets):
            out.append(
                f"{name}_bucket{lbl(repr(float(self.bounds_ms[i])))}"
                f" {int(cum[i])}{exm(i)}"
            )
        out.append(f"{name}_bucket{lbl('+Inf')} {int(cum[-1])}{exm(self.n_buckets)}")
        brace = ("{" + labels + "}") if labels else ""
        out.append(f"{name}_sum{brace} {total_ms}")
        out.append(f"{name}_count{brace} {int(cum[-1])}")
        return out
