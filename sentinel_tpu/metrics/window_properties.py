"""Live second-window geometry properties.

Reference: node/SampleCountProperty.java:26-52 and
node/IntervalProperty.java:26-50 — two static SentinelProperty<Integer>
hooks; updating either rebuilds every StatisticNode's rolling second
counter to ``SampleCountProperty.SAMPLE_COUNT`` buckets over
``IntervalProperty.INTERVAL`` ms and resets its second-window
statistics ("All statistics will be reset" in the reference's own
words). Datasources can drive them like any other property.

Here both feed :meth:`Engine.retune_second_window`, which drains
pending ops against the old geometry, swaps ``nodes.SECOND_CFG`` and
rebuilds the shared second-window tensors; the jitted flush kernels key
their caches on the config so the next flush re-traces with the new
constants.
"""

from __future__ import annotations

import threading
from typing import Optional

from sentinel_tpu.core.property import DynamicSentinelProperty, FuncListener
from sentinel_tpu.models import constants as C
from sentinel_tpu.utils.record_log import record_log

# Initial value None: registration fires config_load with the current
# value, and a None no-ops in the listeners below — importing this
# module must not instantiate the engine.
sample_count_property: DynamicSentinelProperty = DynamicSentinelProperty(None)
interval_property: DynamicSentinelProperty = DynamicSentinelProperty(None)

_lock = threading.Lock()


def _apply(sample_count: Optional[int], interval_ms: Optional[int]) -> None:
    """Combine the updated dimension with the live geometry (the other
    dimension always reads whatever is currently in force, like the
    reference pairing SAMPLE_COUNT with IntervalProperty.INTERVAL)."""
    from sentinel_tpu.core import api
    from sentinel_tpu.metrics import nodes

    with _lock:
        sc = int(sample_count) if sample_count is not None else nodes.SECOND_CFG.sample_count
        iv = int(interval_ms) if interval_ms is not None else nodes.SECOND_CFG.interval_ms
        try:
            api.get_engine().retune_second_window(sc, iv)
            record_log.info(
                "[WindowProperties] second window retuned to %d x %d ms", sc, iv // sc
            )
        except ValueError as e:
            # SampleCountProperty ignores invalid updates (java:42-49).
            record_log.warn(
                "[WindowProperties] rejected geometry %dx%dms: %s", sc, iv, e
            )


sample_count_property.add_listener(
    FuncListener(lambda v: _apply(v, None) if v is not None else None)
)
interval_property.add_listener(
    FuncListener(lambda v: _apply(None, v) if v is not None else None)
)
