"""Shared Chrome trace-event (Perfetto) emission.

Three dump surfaces render Sentinel timelines for ui.perfetto.dev —
the single-engine flush pipeline (``tools/tracedump.py`` over
``spans_to_trace``), the merged fleet timeline (``tools/fleetdump.py``),
and the capture-journal timeline (``tools/replay.py --trace``). They
used to re-implement the same event mechanics independently; this
module is the one home of that mechanics:

* :class:`TraceBuilder` — event list + emit-once ``process_name`` /
  ``thread_name`` metadata, ``X`` complete slices, ``i`` instants, and
  ``s``/``f`` flow-arrow pairs with the finish-timestamp clamp
  (Perfetto silently drops an arrow whose finish is earlier than its
  start; one ruler beat of residual cross-process skew can produce
  exactly that).
* :class:`SlotTracks` — greedy interval→track assignment for
  overlapping windows (depth-K in-flight fetches, concurrent sampled
  admissions): the first track whose last end precedes the new start
  is reused, optionally capped so a dump with thousands of concurrent
  intervals overflows onto the last track instead of exploding the
  track count.
* :func:`spans_to_trace` — the flight-recorder conversion itself
  (flush encode/dispatch/inflight + sampled-admission request tracks
  with decide arrows), shared by ``tools/tracedump.py`` and
  ``metrics/telemetry.py``.

All timestamps are microseconds (trace-event convention); callers pick
their own time base.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

# (ts_us, pid, tid) — a flow-arrow endpoint.
Anchor = Tuple[float, int, int]


class TraceBuilder:
    """Accumulates trace events; ``build()`` wraps them in the JSON
    object format (``{"traceEvents": [...], "displayTimeUnit": "ms"}``)
    that Perfetto and chrome://tracing load directly."""

    def __init__(self) -> None:
        self.events: List[dict] = []
        self._pids: Dict[str, int] = {}
        self._tids: Dict[Tuple[int, str], int] = {}
        self._named_threads: set = set()
        self._next_pid = 1

    # -- metadata (emit-once) -------------------------------------------
    def process(self, name: str, pid: Optional[int] = None) -> int:
        """Register a Perfetto process; emits ``process_name`` metadata
        the first time a name is seen. Explicit ``pid`` (e.g. the real
        OS pid) wins; otherwise pids auto-increment."""
        if name in self._pids and pid is None:
            return self._pids[name]
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
        if self._pids.get(name) != pid:
            self._pids[name] = pid
            self.events.append({
                "name": "process_name", "ph": "M", "pid": pid,
                "args": {"name": name},
            })
        return pid

    def thread(self, pid: int, name: str, tid: Optional[int] = None) -> int:
        """Register a thread track inside ``pid``; emits
        ``thread_name`` metadata once per (pid, tid)."""
        key = (pid, name)
        if tid is None:
            tid = self._tids.get(key)
            if tid is None:
                tid = len([k for k in self._tids if k[0] == pid]) + 1
        self._tids.setdefault(key, tid)
        if (pid, tid) not in self._named_threads:
            self._named_threads.add((pid, tid))
            self.events.append({
                "ph": "M", "pid": pid, "tid": tid, "name": "thread_name",
                "args": {"name": name},
            })
        return tid

    # -- events ----------------------------------------------------------
    def slice(
        self, pid: int, tid: int, name: str, ts: float, dur: float,
        cat: Optional[str] = None, args: Optional[dict] = None,
    ) -> None:
        ev: dict = {"ph": "X", "pid": pid, "tid": tid, "name": name,
                    "ts": ts, "dur": dur}
        if cat is not None:
            ev["cat"] = cat
        if args is not None:
            ev["args"] = args
        self.events.append(ev)

    def instant(
        self, pid: int, tid: int, name: str, ts: float,
        cat: Optional[str] = None, args: Optional[dict] = None,
    ) -> None:
        ev: dict = {"ph": "i", "pid": pid, "tid": tid, "name": name,
                    "ts": ts, "s": "t"}
        if cat is not None:
            ev["cat"] = cat
        if args is not None:
            ev["args"] = args
        self.events.append(ev)

    def flow(
        self, flow_id, name: str, src: Anchor, dst: Anchor,
        cat: Optional[str] = None,
    ) -> None:
        """One ``s``/``f`` arrow pair. The finish timestamp is clamped
        to ``max(dst.ts, src.ts)`` — Perfetto drops backwards arrows,
        and residual cross-process clock skew can put the target stamp
        marginally before the source's."""
        s_ts, s_pid, s_tid = src
        f_ts, f_pid, f_tid = dst
        base: dict = {"name": name, "id": flow_id}
        if cat is not None:
            base["cat"] = cat
        self.events.append({**base, "ph": "s", "pid": s_pid,
                            "tid": s_tid, "ts": s_ts})
        self.events.append({**base, "ph": "f", "bp": "e", "pid": f_pid,
                            "tid": f_tid, "ts": max(f_ts, s_ts)})

    def build(self) -> dict:
        return {"traceEvents": self.events, "displayTimeUnit": "ms"}


class SlotTracks:
    """Greedy interval→slot assignment: ``assign(start, end)`` returns
    the first slot whose last end precedes ``start`` (epsilon for fp
    jitter), growing the slot set as needed — capped at ``max_tracks``
    when given (overflow shares the last slot)."""

    def __init__(self, max_tracks: Optional[int] = None, eps: float = 1e-3) -> None:
        self.ends: List[float] = []
        self.max_tracks = max_tracks
        self.eps = eps

    def assign(self, start: float, end: float) -> int:
        slot = None
        for i, e in enumerate(self.ends):
            if e <= start + self.eps:
                slot = i
                break
        if slot is None:
            if self.max_tracks is None or len(self.ends) < self.max_tracks:
                slot = len(self.ends)
                self.ends.append(0.0)
            else:
                slot = self.max_tracks - 1
        self.ends[slot] = max(self.ends[slot], end)
        return slot


# ----------------------------------------------------------------------
# flight-recorder conversion (tools/tracedump.py, telemetry delegate)
# ----------------------------------------------------------------------
def spans_to_trace(
    spans: Sequence[Any], pid: int = 1, records: Sequence = None
) -> dict:
    """Convert flight-recorder spans (telemetry.FlushSpan) to the
    Chrome trace-event object format.

    Layout: every span's ``encode`` and ``dispatch`` slices go on tid 1
    (``host``) — flush dispatches are serialized under the engine's
    flush lock, so they never overlap. The dispatch→settle window of a
    deferred flush (``inflight``: device execution + fetch latency)
    goes on the first free ``inflight-N`` tid (greedy interval
    assignment), so a depth-K pipeline shows K parallel tracks whose
    slices overlap the NEXT flush's encode on the host track — the
    visual proof that host encode overlaps device execution.

    ``records`` (admission_trace.AdmissionRecord) adds ``requests-N``
    tracks: one slice per sampled admission spanning enqueue→verdict,
    plus a Perfetto flow arrow from the admission to the flush span
    that DECIDED it (matched on ``flush_seq``) — you can see a 429'd
    call, hover its trace id, and follow the arrow into the flush that
    produced the verdict.

    All ``ts``/``dur`` are µs relative to the earliest span/record."""
    spans = list(spans)
    records = list(records) if records else []
    if not spans and not records:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min([s.t0 for s in spans] + [r.t0 for r in records])

    def us(t: float) -> float:
        return (t - base) * 1e6

    tb = TraceBuilder()
    tb.thread(pid, "host", tid=1)
    inflight = SlotTracks()
    # flush_id -> a ts inside that span's dispatch slice (flow-arrow
    # anchor: a flow endpoint must land within a slice on its tid).
    dispatch_anchor: Dict[int, float] = {}
    for s in sorted(spans, key=lambda s: s.t0):
        enc_start = us(s.t0)
        enc_dur = s.encode_ms * 1e3
        disp_start = enc_start + enc_dur
        disp_dur = s.dispatch_ms * 1e3
        args = {
            "flush_id": s.flush_id, "rows": s.rows, "depth": s.depth,
            "inflight": s.inflight, "deferred": s.deferred,
        }
        tb.slice(pid, 1, "encode", enc_start, enc_dur, cat="flush", args=args)
        tb.slice(pid, 1, "dispatch", disp_start, disp_dur, cat="flush",
                 args=args)
        dispatch_anchor[s.flush_id] = disp_start + disp_dur * 0.5
        if s.settled and s.settle_end > s.t0:
            fly_start = disp_start + disp_dur
            fly_end = us(s.settle_end)
            fly_dur = max(fly_end - fly_start, 0.0)
            slot = inflight.assign(fly_start, fly_start + fly_dur)
            tid = tb.thread(pid, f"inflight-{slot}", tid=10 + slot)
            tb.slice(pid, tid, "inflight", fly_start, fly_dur,
                     cat="device", args=args)
    if records:
        # Concurrent admissions overlap in time (a whole chunk settles
        # together), so request slices get the same greedy slot-track
        # assignment as the inflight windows: tids 100+N, capped — a
        # dump with thousands of concurrent sampled requests overflows
        # onto the last track rather than exploding the track count.
        REQ_TID0, REQ_TRACKS_MAX = 100, 16
        req_tracks = SlotTracks(max_tracks=REQ_TRACKS_MAX)
        for i, r in enumerate(sorted(records, key=lambda r: r.t0)):
            req_start = us(r.t0)
            req_dur = max(r.latency_ms * 1e3, 1.0)
            slot = req_tracks.assign(req_start, req_start + req_dur)
            tid = tb.thread(pid, f"requests-{slot}", tid=REQ_TID0 + slot)
            tb.slice(pid, tid, r.resource, req_start, req_dur,
                     cat="admission", args={
                         "trace_id": r.trace_id, "span_id": r.span_id,
                         "admitted": r.admitted, "reason": r.reason,
                         "reason_name": r.reason_name,
                         "flush_seq": r.flush_seq,
                         "origin": r.origin,
                     })
            anchor = dispatch_anchor.get(r.flush_seq)
            if anchor is None or anchor < req_start:
                # No linkable flush span in the dump (telemetry off,
                # span evicted from the ring, or clock skew) — the
                # request slice still renders, just without an arrow.
                continue
            # Arrow: admission enqueue (request track) → deciding
            # flush's dispatch slice (tid 1). Chrome flows require
            # s.ts <= f.ts; an op is always enqueued before its flush
            # dispatches, and the start is clamped below the anchor in
            # case the dispatch followed within the nudge.
            tb.flow(
                i + 1, "decide",
                (min(req_start + min(req_dur * 0.25, 1.0), anchor), pid, tid),
                (anchor, pid, 1),
                cat="admission",
            )
    return tb.build()
