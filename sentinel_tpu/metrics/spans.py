"""Per-process fleet span journal — the cross-process half of tracing.

The PR-3 flight recorder and PR-4 admission tracer are engine-scoped:
once the plane went multi-process (ipc workers, cluster token shards)
a single admission's life — worker window join, ring residency,
engine drain, shard RPC — spans three process types that none of the
existing machinery can see at once.

This module is the per-process leg: a bounded ring of wall-clock
spans with rolling jsonl spill. ``tools/fleetdump.py`` merges the
journals of every process in a run into ONE Perfetto trace, using the
correlation keys each span carries:

* ``wid``/``seq`` — the (worker_id, client seq) pair that crosses the
  shared-memory ring (ipc/frames.py puts seq columns on both request
  and verdict frames);
* ``trace`` — the W3C traceparent hex when the admission carried one;
* ``xid`` — the cluster wire's transaction id (client RPC span on one
  side, shard serve span on the other).

Clock model: every span stamps ``time.time()*1000`` — the SAME clock
the ipc ControlBlock's wall-ms ruler (header offset 32) publishes each
heartbeat, so worker and engine spans align without NTP: each spill
records the delta between the local clock and the last ruler beat the
process observed (``ruler_off_ms``), bounding skew to one heartbeat
cadence.

Disabled (the default) costs ONE bool read per call site: sites hold
the journal and check ``journal.enabled`` before stamping anything.
Verdicts are bit-identical either way — spans only observe.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from sentinel_tpu.utils.config import SentinelConfig, config

# Size-rolled like the metric log: one live file + one .1 backup.
_SPILL_ROLL_BYTES = 16 * 1024 * 1024


def wall_ms() -> float:
    """The shared ruler clock: epoch wall time in milliseconds."""
    return time.time() * 1000.0


class SpanJournal:
    """Bounded per-process span ring with rolling jsonl spill.

    One journal per process (``get_journal``); every span source in
    the process — IngestClient, IngestPlane, ClusterTokenClient,
    SentinelTokenServer — appends here, tagged with its own ``cat``
    (worker / engine / client / shard) so fleetdump can build one
    track per stage even when stages share a process.
    """

    def __init__(
        self,
        role: str = "engine",
        enabled: Optional[bool] = None,
        ring: Optional[int] = None,
        spill_every: Optional[int] = None,
        base_dir: Optional[str] = None,
    ) -> None:
        self.role = role
        self.pid = os.getpid()
        self.enabled = (
            config.get_bool(SentinelConfig.SPANS_ENABLED)
            if enabled is None
            else enabled
        )
        cap = ring if ring is not None else config.get_int(SentinelConfig.SPANS_RING, 8192)
        self._ring = max(16, cap)
        self._spill_every = (
            spill_every
            if spill_every is not None
            else config.get_int(SentinelConfig.SPANS_SPILL_EVERY, 0)
        )
        self._base_dir = base_dir or config.get(SentinelConfig.SPANS_DIR) or None
        self._lock = threading.Lock()
        self._spans: deque = deque(maxlen=self._ring)
        self._since_spill = 0
        self._spilled_total = 0
        self._recorded_total = 0
        # Last control-header ruler beat this process observed, as
        # (ruler_wall_ms, local_wall_ms_at_read). Zero until the first
        # heartbeat crosses the ring.
        self._ruler = (0.0, 0.0)

    # ---- recording ---------------------------------------------------

    def record(self, name: str, cat: str, t0_ms: float, dur_ms: float, **fields: Any) -> None:
        """Append one finished span. Callers gate on ``self.enabled``
        BEFORE computing t0/dur so the disabled path stays one bool."""
        sp: Dict[str, Any] = {
            "name": name,
            "cat": cat,
            "t0": round(t0_ms, 3),
            "dur": round(max(0.0, dur_ms), 3),
        }
        for k, v in fields.items():
            if v is not None:
                sp[k] = v
        spill = False
        with self._lock:
            self._spans.append(sp)
            self._recorded_total += 1
            self._since_spill += 1
            if self._spill_every > 0 and self._since_spill >= self._spill_every:
                spill = True
        if spill:
            try:
                self.spill()
            except OSError:
                pass

    def note_ruler(self, ruler_wall_ms: float) -> None:
        """Record the latest control-header wall-ms beat (ipc worker
        and engine call this when they touch the header)."""
        self._ruler = (float(ruler_wall_ms), wall_ms())

    # ---- reading -----------------------------------------------------

    def spans(self, cat: Optional[str] = None) -> List[Dict[str, Any]]:
        with self._lock:
            out = list(self._spans)
        if cat is not None:
            out = [s for s in out if s.get("cat") == cat]
        return out

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "role": self.role,
                "pid": self.pid,
                "enabled": self.enabled,
                "ring": self._ring,
                "buffered": len(self._spans),
                "recorded_total": self._recorded_total,
                "spilled_total": self._spilled_total,
            }

    # ---- spill -------------------------------------------------------

    def _meta(self) -> Dict[str, Any]:
        ruler, at = self._ruler
        meta: Dict[str, Any] = {
            "meta": 1,
            "role": self.role,
            "pid": self.pid,
            "app": config.app_name,
            "wall_ms": round(wall_ms(), 3),
        }
        if ruler:
            # Local-clock minus ruler-clock at the moment the beat was
            # read: fleetdump subtracts this to land every process on
            # the ruler timeline.
            meta["ruler_off_ms"] = round(at - ruler, 3)
        return meta

    def spill_path(self) -> str:
        base = self._base_dir
        if not base:
            from sentinel_tpu.utils.record_log import _log_dir

            base = _log_dir()
        os.makedirs(base, exist_ok=True)
        return os.path.join(
            base, f"{config.app_name}-spans-{self.role}-{self.pid}.jsonl"
        )

    def spill(self, path: Optional[str] = None) -> Optional[str]:
        """Drain the ring to the journal file (appending). Each spill
        batch starts with a meta line; fleetdump uses the LAST meta's
        ruler offset (freshest skew estimate). Returns the path, or
        None when there was nothing to write."""
        with self._lock:
            batch = list(self._spans)
            self._spans.clear()
            self._since_spill = 0
        if not batch:
            return None
        out = path or self.spill_path()
        self._roll_if_needed(out)
        with open(out, "a", encoding="utf-8") as fh:
            fh.write(json.dumps(self._meta(), separators=(",", ":")) + "\n")
            for sp in batch:
                fh.write(json.dumps(sp, separators=(",", ":")) + "\n")
        with self._lock:
            self._spilled_total += len(batch)
        return out

    def _roll_if_needed(self, path: str) -> None:
        try:
            if os.path.getsize(path) < _SPILL_ROLL_BYTES:
                return
        except OSError:
            return
        try:
            os.replace(path, path + ".1")
        except OSError:
            pass


# ---- process-wide journal ------------------------------------------------

_journal: Optional[SpanJournal] = None
_journal_lock = threading.Lock()


def get_journal(role: str = "engine") -> SpanJournal:
    """The process-wide journal. The FIRST caller's role names the
    process (engine constructs before workers attach in-process, so
    worker processes pass role="worker" from IngestClient, shard
    server processes "shard" from SentinelTokenServer.start)."""
    global _journal
    j = _journal
    if j is not None:
        return j
    with _journal_lock:
        if _journal is None:
            _journal = SpanJournal(role=role)
        return _journal


def reset_journal() -> None:
    """Test hook: drop the singleton so the next get_journal re-reads
    config (enabled/ring/dir)."""
    global _journal
    with _journal_lock:
        _journal = None


def load_journal(path: str) -> Dict[str, Any]:
    """Parse one spilled journal file -> {"meta": ..., "spans": [...]}.
    Malformed tail lines are skipped (a crash mid-spill must not sink
    the whole fleet merge); the last meta line wins."""
    meta: Dict[str, Any] = {}
    spans: List[Dict[str, Any]] = []
    with open(path, "r", encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            if obj.get("meta"):
                meta = obj
            elif "name" in obj and "t0" in obj:
                spans.append(obj)
    return {"meta": meta, "spans": spans}
