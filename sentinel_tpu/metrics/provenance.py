"""Per-resource admission-provenance accumulator for the metric plane.

PRs 5-7 gave the engine a two-tier admission plane — speculative host
verdicts, degraded host-fallback windows, reconciliation drift, ingest
shedding — but every one of those signals lands only in engine-scoped
telemetry counters (metrics/telemetry.py). The fleet artifact Sentinel
is named for, the per-second per-resource MetricNode line, stayed blind
to all of it: a dashboard could tell you *that* the tier over-admitted,
never *which resource* it over-admitted on.

This module is the host-side (second, resource) ledger those signals
fold into:

* ``speculative`` — ops whose caller-visible verdict came from the
  speculative host tier (admits AND blocks: serves, acquire-weighted to
  match the device PASS/BLOCK columns);
* ``degraded``    — ops served by the host fallback with degraded
  provenance (device lost). NOT disjoint from ``speculative``: a
  speculative serve while DEGRADED carries both marks, exactly like
  ``Verdict.speculative`` composing with ``Verdict.degraded``;
* ``shed``        — ops the ingest valve turned away at submit
  (BLOCK_SHED; these never reach the device, so without this column
  they would vanish from the per-resource view entirely);
* ``drift``       — NET over-admit (over − under reconciliation
  mismatches, signed) attributed per resource.

Every event is attributed to the op's **submit-ts second** (PR-7's
drift-window attribution rule, applied to the whole ledger): a depth-K
pipelined settle must not smear one arrival second's provenance across
the seconds its drains happen to land in. The metric-log timer drains
completed seconds into :class:`~sentinel_tpu.metrics.metric_log.
MetricNodeLine` v2 columns; cumulative per-resource totals feed the
bounded ``sentinel_resource_*`` Prometheus export
(transport/prometheus.py).

Cardinality is bounded twice: the ledger itself folds resources past
``sentinel.tpu.metrics.resource.capacity`` into the ``__other__`` row
(space never grows past capacity × seconds-retained), and the
Prometheus exporter additionally restricts labels to the PR-3 blocked
top-K sketch plus configured resources (PAPERS.md 1902.06993: bound the
export, not the traffic).

Write cadence: the admission fast path itself NEVER writes the ledger.
Single speculative serves are accumulated chunk-locally at settle time
(`Engine._fill_results` → :meth:`ResourceProvenance.note_serves_batch`,
one locked call per chunk) or, while the device is lost, noted in
``fill_degraded``'s kept-speculative branch; bulk groups note once per
group (:meth:`note_col`, already columnar); sheds/degraded fills note
on their own off-hot paths. Attribution is by submit ts regardless of
when the write happens, and the metric timer drains the flush pipeline
before each pull, so settle-time writing is invisible to the
per-second lines.

Disabled (``sentinel.tpu.metrics.resource.enabled=false``) the engine
pays exactly one bool read per call site — the same contract as the
TelemetryBus.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Tuple

import numpy as np

from sentinel_tpu.utils.config import config

# The fold row for resources past the cardinality cap. Double
# underscores like the reference's __total_inbound_traffic__ pseudo
# resource, so no user resource name can collide with it.
OTHER_RESOURCE = "__other__"

# Column order of the internal per-(second, resource) cells.
_SPEC, _DEGRADED, _SHED, _OVER, _UNDER = range(5)


class ResourceProvenance:
    """Engine-scoped (one per Engine) submit-ts-second × resource
    provenance ledger; see module doc. All methods are thread-safe and
    the lock is a leaf (call sites may hold engine or tier locks)."""

    # Seconds retained before the oldest is evicted — the metric timer
    # drains every second; a stopped timer must not leak (same bound as
    # TelemetryBus._SEC_CAP).
    SEC_CAP = 600

    def __init__(self, enabled=None, capacity=None) -> None:
        self.enabled = (
            config.get_bool(config.RESOURCE_METRICS_ENABLED, True)
            if enabled is None
            else bool(enabled)
        )
        self.capacity = max(
            8,
            capacity
            if capacity is not None
            else config.get_int(config.RESOURCE_METRICS_CAP, 256),
        )
        self._lock = threading.Lock()
        # sec(rel ms, second-aligned) -> resource -> [spec, degraded,
        # shed, over, under]
        self._sec: Dict[int, Dict[str, List[int]]] = {}
        # Cumulative per-resource totals (Prometheus export), same cell
        # layout, folded to OTHER_RESOURCE past capacity.
        self._totals: Dict[str, List[int]] = {}

    # ------------------------------------------------------------------
    # writers (engine / speculative tier / failover call sites — each
    # gated on ``self.enabled`` by the caller)
    # ------------------------------------------------------------------
    def _cell(self, table: Dict[str, List[int]], resource: str) -> List[int]:
        cell = table.get(resource)
        if cell is None:
            # One slot is reserved for the fold row, so a table never
            # exceeds `capacity` entries including __other__.
            if resource != OTHER_RESOURCE and len(table) >= self.capacity - 1:
                return self._cell(table, OTHER_RESOURCE)
            cell = table[resource] = [0, 0, 0, 0, 0]
        return cell

    def _cells_locked(self, ts_rel_ms: int, resource: str):
        """(per-second cell, totals cell) for one event's key — fetched
        once per note; this is the speculative fast path's ledger cost."""
        sec = int(ts_rel_ms) // 1000 * 1000
        table = self._sec.get(sec)
        if table is None:
            if len(self._sec) >= self.SEC_CAP:
                self._sec.pop(min(self._sec), None)
            table = self._sec[sec] = {}
        return self._cell(table, resource), self._cell(self._totals, resource)

    def note(
        self,
        ts_rel_ms: int,
        resource: str,
        spec: int = 0,
        degraded: int = 0,
        shed: int = 0,
        over: int = 0,
        under: int = 0,
    ) -> None:
        """One op's provenance events at its submit ts (engine-clock
        relative ms). Weights follow the device PASS/BLOCK convention:
        acquire-weighted serves/sheds, per-op mismatch weights."""
        with self._lock:
            cell, tot = self._cells_locked(ts_rel_ms, resource)
            for col, n in (
                (_SPEC, spec), (_DEGRADED, degraded), (_SHED, shed),
                (_OVER, over), (_UNDER, under),
            ):
                if n:
                    cell[col] += n
                    tot[col] += n

    def note_serves_batch(self, acc: Dict[Tuple[int, str], list]) -> None:
        """One settled chunk's speculative serve notes in one locked
        pass: ``{(submit-sec rel ms, resource): [spec_n, degraded_n]}``
        — the singles fast path pays ZERO ledger cost at admission
        time; `Engine._fill_results` accumulates into a plain local
        dict per chunk and hands it over here (one call per chunk, so
        the per-op share is dict-add cheap; the ≤2% metric-plane guard
        in tests/test_metric_plane.py is stated over exactly this)."""
        with self._lock:
            for (sec, resource), (n, d) in acc.items():
                cell, tot = self._cells_locked(sec, resource)
                cell[_SPEC] += n
                tot[_SPEC] += n
                if d:
                    cell[_DEGRADED] += d
                    tot[_DEGRADED] += d

    def note_col(
        self,
        resource: str,
        ts_col,
        weights=None,
        spec: bool = False,
        degraded: bool = False,
        shed: bool = False,
        over: bool = False,
        under: bool = False,
    ) -> None:
        """Columnar writer for bulk groups: ``ts_col`` (int ms, one per
        event row) is grouped by submit second host-side; ``weights``
        (same length; default all-1) is summed per second. The flag set
        selects which columns receive the per-second sums."""
        ts = np.asarray(ts_col)
        if ts.size == 0:
            return
        secs = (ts.astype(np.int64) // 1000) * 1000
        w = (
            np.ones(ts.shape[0], dtype=np.int64)
            if weights is None
            else np.asarray(weights, dtype=np.int64)
        )
        uniq, inv = np.unique(secs, return_inverse=True)
        sums = np.bincount(inv, weights=w.astype(np.float64)).astype(np.int64)
        cols = [
            c
            for c, on in (
                (_SPEC, spec), (_DEGRADED, degraded), (_SHED, shed),
                (_OVER, over), (_UNDER, under),
            )
            if on
        ]
        with self._lock:
            for s, n in zip(uniq.tolist(), sums.tolist()):
                if not n:
                    continue
                cell, tot = self._cells_locked(int(s), resource)
                for c in cols:
                    cell[c] += int(n)
                    tot[c] += int(n)

    # ------------------------------------------------------------------
    # readers
    # ------------------------------------------------------------------
    def drain_seconds(
        self, upto_rel_ms: int
    ) -> List[Tuple[int, str, int, int, int, int]]:
        """Completed engine-clock seconds strictly before
        ``upto_rel_ms`` (second-aligned), removed from the ledger:
        ``[(sec_rel_ms, resource, speculative, degraded, shed, drift)]``
        ascending by (second, resource) — the metric-log timer's pull.
        ``drift`` is signed net over-admit (over − under)."""
        out: List[Tuple[int, str, int, int, int, int]] = []
        with self._lock:
            for sec in sorted(self._sec):
                if sec >= upto_rel_ms:
                    break
                table = self._sec.pop(sec)
                for resource in sorted(table):
                    c = table[resource]
                    if not any(c):
                        continue
                    out.append(
                        (sec, resource, c[_SPEC], c[_DEGRADED], c[_SHED],
                         c[_OVER] - c[_UNDER])
                    )
        return out

    def totals(self) -> Dict[str, Tuple[int, int, int, int]]:
        """Cumulative ``resource -> (speculative, degraded, shed,
        drift)`` — the Prometheus exporter's read (drift signed)."""
        with self._lock:
            return {
                r: (c[_SPEC], c[_DEGRADED], c[_SHED], c[_OVER] - c[_UNDER])
                for r, c in self._totals.items()
            }

    def snapshot(self) -> dict:
        with self._lock:
            open_secs = len(self._sec)
            tracked = len(self._totals)
        return {
            "enabled": self.enabled,
            "capacity": self.capacity,
            "open_seconds": open_secs,
            "tracked_resources": tracked,
            "totals": self.totals(),
        }

    def reset(self) -> None:
        with self._lock:
            self._sec.clear()
            self._totals.clear()
