"""Statistics layer: sliding-window counter tensors and node views.

Equivalent of the reference's statistics core (reference:
sentinel-core/.../slots/statistic/base/LeapArray.java:41-222,
data/MetricBucket.java:28-120, metric/ArrayMetric.java:37-58 and
node/StatisticNode.java:90-112) — redesigned from per-request CAS loops
over ``AtomicReferenceArray`` buckets into batched, single-writer
vectorized updates over an HBM-resident tensor
``counts[rows, buckets, events]``.
"""

from sentinel_tpu.metrics.admission_trace import (
    AdmissionRecord,
    AdmissionTracer,
    TraceContext,
    inject_trace_headers,
    parse_traceparent,
)
from sentinel_tpu.metrics.block_log import BlockLogger
from sentinel_tpu.metrics.events import MetricEvent, NUM_EVENTS
from sentinel_tpu.metrics.extension import MetricExtension, MetricExtensionProvider
from sentinel_tpu.metrics.histogram import LatencyHistogram
from sentinel_tpu.metrics.provenance import OTHER_RESOURCE, ResourceProvenance
from sentinel_tpu.metrics.telemetry import (
    FlushSpan,
    SpaceSaving,
    TelemetryBus,
    spans_to_trace,
)
from sentinel_tpu.metrics.metric_array import (
    MetricArrayConfig,
    MetricArrayState,
    make_state,
    update,
    window_sums,
    window_min_rt,
    bucket_windows,
    grow,
)

__all__ = [
    "AdmissionRecord",
    "AdmissionTracer",
    "TraceContext",
    "inject_trace_headers",
    "parse_traceparent",
    "BlockLogger",
    "FlushSpan",
    "LatencyHistogram",
    "MetricExtension",
    "MetricExtensionProvider",
    "OTHER_RESOURCE",
    "ResourceProvenance",
    "SpaceSaving",
    "TelemetryBus",
    "spans_to_trace",
    "MetricEvent",
    "NUM_EVENTS",
    "MetricArrayConfig",
    "MetricArrayState",
    "make_state",
    "update",
    "window_sums",
    "window_min_rt",
    "bucket_windows",
    "grow",
]
