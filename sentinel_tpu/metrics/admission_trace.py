"""Admission tracing: per-request verdict provenance + W3C trace context.

PR 3's flight recorder made the *engine* observable (per-flush spans,
histograms, blocked-resource sketch) but left every blocked request
anonymous: an operator could see THAT a resource was being throttled,
not WHY a particular call was — which rule family, decided in which
flush, on behalf of which upstream. This module is the request-level
half of that story, the batched analog of the reference's LogSlot →
EagleEye pipeline with trace identity attached:

* :class:`TraceContext` + :func:`parse_traceparent` /
  ``TraceContext.to_traceparent`` — W3C trace-context
  (https://www.w3.org/TR/trace-context/) parse and render, used by
  every adapter for inbound extraction and by the outbound clients for
  injection, so a block is attributable ACROSS service hops;
* :class:`AdmissionTracer` — an engine-scoped, bounded ring of
  :class:`AdmissionRecord` per-admission provenance records
  (trace/span ids, resource, origin, context name, verdict reason code
  from the flush kernel's ``reason`` tensor, the deciding flush-span
  seq from the PR 3 TelemetryBus, and enqueue→verdict latency), fed by
  ``Engine._fill_results`` at verdict materialization — so records are
  exact for the pipelined (depth-K) flush path too;
* head-based probabilistic sampling plus an **always-sample-blocked**
  mode: the head decision (one ``random()`` per submit, or the inbound
  traceparent's sampled flag, honored as-is) bounds steady-state cost,
  while blocked verdicts are recorded regardless — the same
  bounded-state discipline as the data-plane heavy-hitter work
  (Sivaraman et al., arXiv:1611.04825): keep per-key state only for
  the traffic that matters, decide cheaply for the rest.

Hot-path contract: when ``sentinel.tpu.trace.enabled`` is false the
engine pays exactly one bool read per submit and one ``None`` check
per op at fill; when true, an UNSAMPLED admitted op pays one
``perf_counter`` + one ``random()`` at submit and nothing at fill.
Trace/span ids are minted lazily at RECORD time, never for unsampled
traffic.

Config keys (all ``sentinel.tpu.trace.*``)::

    sentinel.tpu.trace.enabled         default true
    sentinel.tpu.trace.ring            record ring capacity, default 2048
    sentinel.tpu.trace.sample.rate     head sample probability, default 0.01
    sentinel.tpu.trace.sample.blocked  always record blocked, default true
    sentinel.tpu.trace.bulk.cap        rows recorded per bulk group per
                                       class (blocked / sampled), default 4
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from sentinel_tpu.core import errors as E
from sentinel_tpu.core.context import ContextUtil
from sentinel_tpu.metrics.histogram import LatencyHistogram
from sentinel_tpu.utils.config import config

TRACEPARENT_HEADER = "traceparent"
TRACESTATE_HEADER = "tracestate"

_rand = random.Random()
_HEX = "0123456789abcdef"


def new_trace_id() -> str:
    """A random 32-hex-char (128-bit) nonzero W3C trace id."""
    return f"{_rand.getrandbits(128) or 1:032x}"


def new_span_id() -> str:
    """A random 16-hex-char (64-bit) nonzero W3C span id."""
    return f"{_rand.getrandbits(64) or 1:016x}"


def _is_hex(s: str) -> bool:
    return bool(s) and all(c in _HEX for c in s)


@dataclass(frozen=True, slots=True)
class TraceContext:
    """One hop's W3C trace identity: the trace id, the CURRENT span id
    (the parent of any span created under it), the sampled flag, and
    the opaque ``tracestate`` passed through unmodified (the spec's
    vendor list — this library neither reads nor edits it)."""

    trace_id: str
    span_id: str
    sampled: bool = True
    tracestate: str = ""

    def child(self) -> "TraceContext":
        """A child hop: same trace, fresh span id, decision inherited —
        what outbound injection writes on the wire."""
        return TraceContext(self.trace_id, new_span_id(), self.sampled,
                            self.tracestate)

    def to_traceparent(self) -> str:
        return (
            f"00-{self.trace_id}-{self.span_id}-"
            f"{'01' if self.sampled else '00'}"
        )


def parse_traceparent(
    value: Optional[str], tracestate: str = ""
) -> Optional[TraceContext]:
    """Parse a W3C ``traceparent`` header; None on anything invalid
    (the spec says a receiver that cannot parse MUST restart the trace
    — returning None lets the caller do exactly that). Future versions
    (``version != 00``) are accepted as long as the four base fields
    parse, per the spec's forward-compatibility rule; version ``ff``
    is explicitly invalid."""
    if not value:
        return None
    parts = value.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id, flags = parts[0], parts[1], parts[2], parts[3]
    if len(version) != 2 or not _is_hex(version) or version == "ff":
        return None
    if version == "00" and len(parts) != 4:
        return None
    if len(trace_id) != 32 or not _is_hex(trace_id) or trace_id == "0" * 32:
        return None
    if len(span_id) != 16 or not _is_hex(span_id) or span_id == "0" * 16:
        return None
    if len(flags) != 2 or not _is_hex(flags):
        return None
    return TraceContext(
        trace_id=trace_id,
        span_id=span_id,
        sampled=bool(int(flags, 16) & 0x01),
        tracestate=tracestate or "",
    )


def inject_trace_headers(headers, parent: Optional[TraceContext] = None):
    """Outbound W3C injection: write ``traceparent`` (and
    ``tracestate``) for a CHILD span of the ambient trace (or an
    explicit ``parent``) into a mutable header mapping. No ambient
    trace → no-op returning None: outbound guards never mint trace ids
    for untraced calls (the head decision belongs to the inbound edge).
    Returns the injected child context."""
    tc = parent if parent is not None else ContextUtil.get_trace()
    if tc is None:
        return None
    child = tc.child()
    headers[TRACEPARENT_HEADER] = child.to_traceparent()
    if child.tracestate:
        headers[TRACESTATE_HEADER] = child.tracestate
    return child


class TraceTag(NamedTuple):
    """The per-op submit-time stamp (``_EntryOp.trace`` /
    ``BulkOp.trace``): the inbound parent (if any), the head sampling
    decision, and the enqueue ``perf_counter``. Ids are minted at
    record time, so an unsampled tag allocates nothing but this tuple."""

    parent: Optional[TraceContext]
    sampled: bool
    t0: float


@dataclass(slots=True)
class AdmissionRecord:
    """One sampled admission's verdict provenance."""

    trace_id: str
    span_id: str
    parent_span_id: str  # inbound hop's span id ("" when trace-rooted)
    resource: str
    origin: str
    context_name: str
    admitted: bool
    reason: int  # errors.PASS / BLOCK_*
    reason_name: str  # shared errors.BLOCK_EXC_NAMES spelling; "" = pass
    flush_seq: int  # deciding FlushSpan.flush_id (-1: telemetry off)
    t0: float  # perf_counter at enqueue (tracedump timeline)
    latency_ms: float  # enqueue -> verdict materialized
    head_sampled: bool  # False = recorded by the always-blocked mode
    # Verdict provenance: decided by the host fallback admitter while
    # the engine was DEGRADED (reason BLOCK_FAILOVER for policy sheds;
    # degraded ADMITS keep reason PASS but carry this mark).
    degraded: bool = False
    # Tier provenance: "device" (settled on-device, the default),
    # "degraded" (host fallback, device lost), or "speculative" (host
    # fast tier — runtime/speculative.py; the speculative→settled
    # story: ``flush_seq`` names the settling flush and
    # ``settled_match`` whether the device agreed; None = never
    # settled, e.g. quarantined by a device fault).
    provenance: str = "device"
    settled_match: Optional[bool] = None

    def as_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_span_id,
            "resource": self.resource,
            "origin": self.origin,
            "context_name": self.context_name,
            "admitted": self.admitted,
            "reason": self.reason,
            "reason_name": self.reason_name,
            "flush_seq": self.flush_seq,
            "latency_ms": round(self.latency_ms, 4),
            "head_sampled": self.head_sampled,
            "degraded": self.degraded,
            "provenance": self.provenance,
            "settled_match": self.settled_match,
        }


class AdmissionTracer:
    """Engine-scoped sampled admission-trace ring (one per
    :class:`~sentinel_tpu.runtime.engine.Engine`, like the
    TelemetryBus)."""

    def __init__(
        self,
        enabled: Optional[bool] = None,
        ring: Optional[int] = None,
        sample_rate: Optional[float] = None,
        sample_blocked: Optional[bool] = None,
        bulk_cap: Optional[int] = None,
    ) -> None:
        self.enabled = (
            config.get_bool(config.TRACE_ENABLED, True)
            if enabled is None
            else bool(enabled)
        )
        self.ring_size = max(
            1,
            ring if ring is not None else config.get_int(config.TRACE_RING, 2048),
        )
        rate = (
            sample_rate
            if sample_rate is not None
            else config.get_float(config.TRACE_SAMPLE_RATE, 0.01)
        )
        self.sample_rate = min(1.0, max(0.0, float(rate)))
        self.sample_blocked = (
            config.get_bool(config.TRACE_SAMPLE_BLOCKED, True)
            if sample_blocked is None
            else bool(sample_blocked)
        )
        self.bulk_cap = max(
            0,
            bulk_cap
            if bulk_cap is not None
            else config.get_int(config.TRACE_BULK_CAP, 4),
        )
        self._records: "deque[AdmissionRecord]" = deque(maxlen=self.ring_size)
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "recorded": 0,
            "head_sampled": 0,
            "blocked_sampled": 0,
        }
        # Tagged but neither head- nor blocked-sampled. Kept OUTSIDE
        # the lock: at the default 1% rate this bumps for ~99% of ops
        # on the verdict-fill hot path, and a diagnostic counter does
        # not justify a mutex acquisition per op (int += under the GIL
        # is close enough; exactness is not load-bearing).
        self._skipped = 0
        # Sampled admission enqueue→verdict latencies — the histogram
        # whose `_bucket` series carries the exemplars below, so
        # exemplar values and bucket counts measure the SAME quantity.
        self.hist_latency = LatencyHistogram()
        # Latest exemplar per latency bucket: idx -> (trace_id, ms).
        self._exemplars: Dict[int, Tuple[str, float]] = {}

    # ------------------------------------------------------------------
    # submit hot path
    # ------------------------------------------------------------------
    def make_tag(self) -> TraceTag:
        """The per-submit stamp. An inbound traceparent's sampled flag
        is the head decision (propagated, per W3C); trace-rooted
        admissions sample probabilistically."""
        parent = ContextUtil.get_trace()
        if parent is not None:
            sampled = parent.sampled
        else:
            r = self.sample_rate
            sampled = r >= 1.0 or (r > 0.0 and _rand.random() < r)
        return TraceTag(parent, sampled, time.perf_counter())

    # ------------------------------------------------------------------
    # verdict materialization (engine fill path)
    # ------------------------------------------------------------------
    def record_admission(
        self,
        tag: TraceTag,
        resource: str,
        origin: str,
        context_name: str,
        admitted: bool,
        reason: int,
        flush_seq: int,
        end_pc: float,
        degraded: bool = False,
        provenance: str = "",
        settled_match: Optional[bool] = None,
    ) -> Optional[AdmissionRecord]:
        """Record one settled admission if the tag (or the blocked
        override) selects it; returns the record or None."""
        if not (tag.sampled or (not admitted and self.sample_blocked)):
            self._skipped += 1
            return None
        if not provenance:
            provenance = "degraded" if degraded else "device"
        parent = tag.parent
        rec = AdmissionRecord(
            trace_id=parent.trace_id if parent is not None else new_trace_id(),
            span_id=new_span_id(),
            parent_span_id=parent.span_id if parent is not None else "",
            resource=resource,
            origin=origin,
            context_name=context_name,
            admitted=bool(admitted),
            reason=int(reason),
            reason_name="" if admitted else E.exc_name_for_code(reason),
            flush_seq=int(flush_seq),
            t0=tag.t0,
            latency_ms=max(0.0, (end_pc - tag.t0) * 1e3),
            head_sampled=tag.sampled,
            degraded=degraded,
            provenance=provenance,
            settled_match=settled_match,
        )
        self.hist_latency.record(rec.latency_ms)
        bucket = self.hist_latency.bucket_of(rec.latency_ms)
        with self._lock:
            self._records.append(rec)
            self.counters["recorded"] += 1
            if tag.sampled:
                self.counters["head_sampled"] += 1
            else:
                self.counters["blocked_sampled"] += 1
            self._exemplars[bucket] = (rec.trace_id, rec.latency_ms)
        return rec

    def record_bulk(
        self,
        tag: TraceTag,
        resource: str,
        origin: str,
        context_name: str,
        admitted,
        reasons,
        flush_seq: int,
        end_pc: float,
        degraded: bool = False,
        provenance: str = "",
        settled_match: Optional[bool] = None,
    ) -> None:
        """Bounded per-row records for one bulk group: up to
        ``bulk_cap`` blocked rows (always-blocked mode) plus, when the
        group's head tag sampled, up to ``bulk_cap`` admitted rows —
        never a full walk of the group. Bulk rows have no per-request
        inbound identity, so each record is trace-rooted unless the
        SUBMITTING call carried one (then all rows share its trace)."""
        cap = self.bulk_cap
        if cap <= 0:
            return
        # Vectorized row selection — a Python walk of a 100k-row group
        # per flush would be exactly the per-row interpreter work the
        # columnar bulk path exists to avoid.
        adm = np.asarray(admitted)
        rows: List[int] = []
        if self.sample_blocked or tag.sampled:
            rows.extend(np.flatnonzero(~adm)[:cap].tolist())
        if tag.sampled:
            rows.extend(np.flatnonzero(adm)[:cap].tolist())
        # record_admission's own gate re-applies (a blocked row rides
        # the always-blocked mode; an admitted row needs tag.sampled),
        # so the per-row record keeps honest head_sampled attribution.
        for i in rows:
            self.record_admission(
                tag, resource, origin, context_name,
                bool(adm[i]), int(reasons[i]), flush_seq, end_pc,
                degraded=degraded, provenance=provenance,
                settled_match=settled_match,
            )

    # ------------------------------------------------------------------
    # readers
    # ------------------------------------------------------------------
    def records(
        self,
        n: Optional[int] = None,
        resource: Optional[str] = None,
        reason: Optional[int] = None,
    ) -> List[AdmissionRecord]:
        """Ring snapshot, oldest first, optionally filtered by resource
        and/or reason code; ``n`` keeps only the newest n AFTER the
        filters (the ``traces`` command's semantics)."""
        with self._lock:
            out = list(self._records)
        if resource is not None:
            out = [r for r in out if r.resource == resource]
        if reason is not None:
            out = [r for r in out if r.reason == reason]
        if n is not None and n > 0:
            out = out[-n:]
        return out

    def counters_snapshot(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.counters)
        out["skipped"] = self._skipped
        return out

    def exemplars(self) -> Dict[int, Tuple[str, float]]:
        """Latest (trace_id, latency_ms) exemplar per ``hist_latency``
        bucket — the OpenMetrics exemplar payload for
        ``transport/prometheus.py``."""
        with self._lock:
            return dict(self._exemplars)

    def snapshot(self) -> dict:
        """Config + counters view for the ``traces`` command."""
        return {
            "enabled": self.enabled,
            "ring_size": self.ring_size,
            "sample_rate": self.sample_rate,
            "sample_blocked": self.sample_blocked,
            "bulk_cap": self.bulk_cap,
            "counters": self.counters_snapshot(),
            "latency_ms": self.hist_latency.summary(),
        }

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._exemplars.clear()
            for k in self.counters:
                self.counters[k] = 0
        self._skipped = 0
        self.hist_latency.reset()
