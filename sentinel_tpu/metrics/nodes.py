"""Node registry and per-node statistics state.

The reference keeps a tree of stat-holding objects per dimension:

* ``DefaultNode`` per (resource, context) linked into a call tree
  (reference: slots/nodeselector/NodeSelectorSlot.java:127-186);
* one shared ``ClusterNode`` per resource plus per-origin sub-nodes
  (reference: slots/clusterbuilder/ClusterBuilderSlot.java:49);
* ``EntranceNode`` per context aggregating its children
  (reference: node/EntranceNode.java, context/ContextUtil.java:129-190);
* the global inbound ``Constants.ENTRY_NODE``
  (reference: Constants.java:66).

Every such node here is **one row** of the shared stats tensors
(second window, minute window, thread gauge) — the node "tree" is a
host-side id table plus parent/child lists used only by the
introspection plane; the hot path touches rows, never objects.

Each node kind gets a distinct key prefix in one interner so row ids are
dense across kinds. Capacity caps mirror the reference: 6000 resources
(MAX_SLOT_CHAIN_SIZE), 2000 contexts (MAX_CONTEXT_NAME_SIZE); above the
cap callers receive ``None`` and degrade to pass-through, like
CtSph.lookProcessChain / ContextUtil.trueEnter.
"""

from __future__ import annotations

import threading
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from sentinel_tpu.metrics.events import MetricEvent, NUM_EVENTS
from sentinel_tpu.metrics import metric_array as ma
from sentinel_tpu.models import constants as C


SECOND_CFG = ma.MetricArrayConfig(
    sample_count=C.DEFAULT_SAMPLE_COUNT, interval_ms=C.DEFAULT_WINDOW_INTERVAL_MS
)
MINUTE_CFG = ma.MetricArrayConfig(
    sample_count=C.MINUTE_SAMPLE_COUNT, interval_ms=C.MINUTE_INTERVAL_MS
)


def set_second_window(sample_count: int, interval_ms: int) -> ma.MetricArrayConfig:
    """Rebind the second-window geometry (reference:
    SampleCountProperty.java + IntervalProperty.java — updating either
    rebuilds every StatisticNode's rolling second counter and RESETS its
    statistics; the minute window and thread gauges are untouched).

    This only swaps the module-global config; callers that own stats
    tensors (Engine.retune_second_window) must rebuild them to the new
    geometry. All kernel readers reference ``nodes.SECOND_CFG``
    dynamically and key their jit caches on it, so the next trace bakes
    the new constants."""
    global SECOND_CFG
    sample_count = int(sample_count)
    interval_ms = int(interval_ms)
    if sample_count <= 0 or interval_ms <= 0 or interval_ms % sample_count != 0:
        # SampleCountProperty ignores invalid updates (java:42-49).
        raise ValueError(
            "invalid window geometry: sample_count must divide interval_ms"
        )
    SECOND_CFG = ma.MetricArrayConfig(
        sample_count=sample_count, interval_ms=interval_ms, max_rt=SECOND_CFG.max_rt
    )
    return SECOND_CFG


class StatsState(NamedTuple):
    """Device-resident statistics for all nodes.

    The reference's StatisticNode holds a 1 s rolling window (2×500 ms),
    a 60 s window (60×1 s) and a thread gauge
    (reference: node/StatisticNode.java:90-112), plus — for prioritized
    entries — a future-bucket slab tracking tokens borrowed from
    not-yet-current windows (reference: OccupiableBucketLeapArray +
    FutureBucketLeapArray, slots/statistic/metric/occupy/
    OccupiableBucketLeapArray.java:29-75). ``future_pass[r, b]`` holds
    tokens borrowed for the window starting at ``future_ws[r, b]``;
    while that start is still ahead of now they count as *waiting*.
    Once matured they are swept into the second window by
    :func:`materialize_matured` at the start of every flush (the
    batched form of the reference's bucket-reset copy); between flushes
    the read-side fold (:func:`occupied_in_window`) makes them visible
    to metric reads without mutating state.
    """

    second: ma.MetricArrayState
    minute: ma.MetricArrayState
    threads: jax.Array  # int32 [R]
    future_pass: jax.Array  # int32 [R, B] borrowed tokens per future bucket
    future_ws: jax.Array  # int32 [R, B] aligned start of the borrowed window

    @property
    def n_rows(self) -> int:
        return self.threads.shape[0]


def make_stats(n_rows: int) -> StatsState:
    b = SECOND_CFG.sample_count
    return StatsState(
        second=ma.make_state(n_rows, SECOND_CFG),
        minute=ma.make_state(n_rows, MINUTE_CFG),
        threads=jnp.zeros((n_rows,), dtype=jnp.int32),
        future_pass=jnp.zeros((n_rows, b), dtype=jnp.int32),
        future_ws=jnp.full((n_rows, b), SECOND_CFG.empty_ws, dtype=jnp.int32),
    )


def rebuild_second(state: StatsState) -> StatsState:
    """Rebuild the second window + occupy slab to the CURRENT
    ``SECOND_CFG`` geometry, dropping their contents (the reference's
    ``rollingCounterInSecond = new ArrayMetric(...)`` on a
    SampleCountProperty/IntervalProperty update — a clean statistics
    reset). Minute window and live thread gauges carry over."""
    n = state.n_rows
    b = SECOND_CFG.sample_count
    return state._replace(
        second=ma.make_state(n, SECOND_CFG),
        future_pass=jnp.zeros((n, b), dtype=jnp.int32),
        future_ws=jnp.full((n, b), SECOND_CFG.empty_ws, dtype=jnp.int32),
    )


def grow_stats(state: StatsState, new_rows: int) -> StatsState:
    if new_rows <= state.n_rows:
        return state
    extra = make_stats(new_rows - state.n_rows)
    return StatsState(
        second=ma.grow(state.second, new_rows, SECOND_CFG),
        minute=ma.grow(state.minute, new_rows, MINUTE_CFG),
        threads=jnp.concatenate([state.threads, extra.threads]),
        future_pass=jnp.concatenate([state.future_pass, extra.future_pass]),
        future_ws=jnp.concatenate([state.future_ws, extra.future_ws]),
    )


def occupied_in_window(state: StatsState, now: jax.Array) -> jax.Array:
    """Borrowed tokens whose window is now current (int32 [R]).

    The reference materialises these into the second window when the
    bucket resets (OccupiableBucketLeapArray.newEmptyBucket copies
    borrowArray's matured count); here they are folded in at read time:
    a slab entry counts iff its window has started and is not yet
    deprecated (same strict-age rule as the window arrays).
    """
    age = now - state.future_ws
    current = (age >= 0) & (age <= SECOND_CFG.interval_ms)
    return jnp.sum(jnp.where(current, state.future_pass, 0), axis=1)


def materialize_matured(state: StatsState, now: jax.Array) -> StatsState:
    """Fold matured borrows into the second window and clear their slab
    slots — the batched analog of OccupiableBucketLeapArray.resetWindowTo
    copying borrowArray's bucket into the rolled window (reference:
    OccupiableBucketLeapArray.java:41-55).

    Run once per flush, before admission. The read-side fold
    (:func:`occupied_in_window`) alone is not enough: the slab has only
    ``sample_count`` slots per row, so a *new* borrow whose target
    window reuses a slot would evict matured tokens that no bucket ever
    absorbed, silently refunding them. Materialising first makes slot
    reuse safe. Slab entries land at bucket index
    ``(ws // window_len) % n`` — the same index their window occupies in
    the main array, so the fold is a pure per-(row, bucket) operation.
    """
    ws = state.future_ws  # [R, B]
    age = now - ws
    matured = age >= 0
    live = matured & (age <= SECOND_CFG.interval_ms)
    bws = state.second.window_start
    newer = live & (ws > bws)  # the roll the reference does lazily
    same = live & (ws == bws)  # bucket already current: plain add
    counts = jnp.where(newer[:, :, None], 0, state.second.counts)
    add = jnp.where(same | newer, state.future_pass, 0)
    counts = counts.at[:, :, MetricEvent.PASS].add(add)
    second = state.second._replace(
        counts=counts,
        window_start=jnp.where(newer, ws, bws),
        min_rt=jnp.where(newer, jnp.int32(SECOND_CFG.max_rt), state.second.min_rt),
    )
    return state._replace(
        second=second,
        future_ws=jnp.where(matured, jnp.int32(SECOND_CFG.empty_ws), state.future_ws),
        future_pass=jnp.where(matured, 0, state.future_pass),
    )


def waiting_tokens(state: StatsState, now: jax.Array) -> jax.Array:
    """Tokens borrowed for still-future windows (int32 [R]) —
    ``StatisticNode.waiting()`` (reference: node/StatisticNode.java:337)."""
    future = state.future_ws > now
    return jnp.sum(jnp.where(future, state.future_pass, 0), axis=1)


def apply_updates(
    state: StatsState,
    rows: jax.Array,  # int32 [M]
    ts: jax.Array,  # int32 [M]
    deltas: jax.Array,  # int32 [M, NUM_EVENTS]
    rt_sample: Optional[jax.Array],  # int32 [M] or None
    thread_delta: jax.Array,  # int32 [M]
    mask: jax.Array,  # bool [M]
    minute_deltas: Optional[jax.Array] = None,
) -> StatsState:
    """One scatter pass over both windows + the thread gauge.

    ``minute_deltas`` overrides the event deltas for the minute window —
    occupied entries diverge between windows (addOccupiedPass writes
    PASS + OCCUPIED_PASS to the minute counter only, reference:
    node/StatisticNode.java:343-346, while the second window's pass
    materialises when the borrowed window becomes current)."""
    second = ma.update(SECOND_CFG, state.second, rows, ts, deltas, rt_sample, mask)
    minute = ma.update(
        MINUTE_CFG, state.minute, rows, ts,
        deltas if minute_deltas is None else minute_deltas, rt_sample, mask,
    )
    rows_eff = jnp.where(mask, rows, 0).astype(jnp.int32)
    thr = jnp.where(mask, thread_delta, 0).astype(jnp.int32)
    threads = state.threads.at[rows_eff].add(thr, mode="drop")
    return state._replace(second=second, minute=minute, threads=threads)


class NodeKind:
    CLUSTER = "C"  # per-resource ClusterNode
    DEFAULT = "D"  # per-(resource, context) DefaultNode
    ORIGIN = "O"  # per-(resource, origin) origin StatisticNode
    ENTRANCE = "E"  # per-context EntranceNode


class NodeRegistry:
    """Host-side name→row table plus the call-tree structure."""

    def __init__(
        self,
        max_resources: int = C.MAX_SLOT_CHAIN_SIZE,
        max_contexts: int = C.MAX_CONTEXT_NAME_SIZE,
    ) -> None:
        self._lock = threading.RLock()
        self._rows: Dict[str, int] = {}
        self._keys: List[str] = []
        self.max_resources = max_resources
        self.max_contexts = max_contexts
        self._n_resources = 0
        self._n_contexts = 0
        # Call tree: entrance row -> child default rows (EntranceNode children).
        self.children: Dict[int, List[int]] = {}
        # Origin rows per cluster row (ClusterNode#originCountMap analog).
        self.origin_rows: Dict[int, Dict[str, int]] = {}
        # The global inbound node is always row 0 (Constants.ENTRY_NODE).
        self.entry_node_row = self._alloc(NodeKind.CLUSTER + ":" + C.TOTAL_IN_RESOURCE_NAME)
        assert self.entry_node_row == 0

    def _alloc(self, key: str) -> int:
        row = self._rows.get(key)
        if row is None:
            row = len(self._keys)
            self._rows[key] = row
            self._keys.append(key)
        return row

    def __len__(self) -> int:
        with self._lock:
            return len(self._keys)

    def key_of(self, row: int) -> str:
        with self._lock:
            return self._keys[row]

    def cluster_row(self, resource: str) -> Optional[int]:
        """Row of the resource's ClusterNode; None above the resource cap."""
        key = NodeKind.CLUSTER + ":" + resource
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                return row
            if self._n_resources >= self.max_resources:
                return None
            self._n_resources += 1
            return self._alloc(key)

    def default_row(self, resource: str, context: str) -> Optional[int]:
        """Row of the per-context DefaultNode (NodeSelectorSlot.java:135-180)."""
        key = NodeKind.DEFAULT + ":" + resource + "|" + context
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                return row
            row = self._alloc(key)
            ent = self.entrance_row(context)
            if ent is not None:
                self.children.setdefault(ent, []).append(row)
            return row

    def origin_row(self, resource: str, origin: str) -> Optional[int]:
        """Row of the per-origin node under the resource's ClusterNode
        (ClusterBuilderSlot.java:49+, ClusterNode#getOrCreateOriginNode)."""
        if not origin:
            return None
        crow = self.cluster_row(resource)
        if crow is None:
            return None
        key = NodeKind.ORIGIN + ":" + resource + "|" + origin
        with self._lock:
            row = self._rows.get(key)
            if row is None:
                row = self._alloc(key)
                self.origin_rows.setdefault(crow, {})[origin] = row
            return row

    def entrance_row(self, context: str) -> Optional[int]:
        """Row of the context's EntranceNode; None above the 2000 cap."""
        key = NodeKind.ENTRANCE + ":" + context
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                return row
            if self._n_contexts >= self.max_contexts:
                return None
            self._n_contexts += 1
            return self._alloc(key)

    def promote_cluster_row(self, resource: str) -> int:
        """Cluster-row allocation that IGNORES the resource cap — the
        sketch tier's promotion grant (runtime/sketch.py): an over-cap
        resource that proved itself a heavy hitter deserves the dense
        row the first-come-first-served cap refused it. Rows are never
        released, so the TIER budgets cumulative grants (8x its
        ``promote.max`` — see ``SketchTier._cap_grants``); a churn of
        distinct over-cap heavy hitters cannot regrow unbounded
        per-key state through this door."""
        key = NodeKind.CLUSTER + ":" + resource
        with self._lock:
            row = self._rows.get(key)
            if row is not None:
                return row
            self._n_resources += 1
            return self._alloc(key)

    def lookup_cluster_row(self, resource: str) -> Optional[int]:
        with self._lock:
            return self._rows.get(NodeKind.CLUSTER + ":" + resource)

    def resources(self) -> List[Tuple[str, int]]:
        """All (resource, cluster_row) pairs (ClusterBuilderSlot map view)."""
        prefix = NodeKind.CLUSTER + ":"
        with self._lock:
            return [
                (k[len(prefix):], r)
                for k, r in self._rows.items()
                if k.startswith(prefix) and r != self.entry_node_row
            ]

    def keys_snapshot(self) -> List[str]:
        """Row-ordered key list (what a durable checkpoint carries so a
        restarted process can rebuild the name→row mapping)."""
        with self._lock:
            return list(self._keys)

    def adopt_keys(self, keys: List[str]) -> Dict[int, int]:
        """Replay another registry's row-ordered key list through the
        PUBLIC registration paths (caps + call-tree structure apply
        exactly as live registration would) and return the old-row →
        new-row mapping for every key that got a row — the durable
        restore's stats remap (runtime/failover.restore_durable). On a
        FRESH registry the mapping is the identity; on a registry that
        already served traffic, rows land wherever the live order put
        them. Keys refused by the caps are simply absent from the map
        (their window rows cold-start, same as any over-cap node)."""
        out: Dict[int, int] = {}
        for old_row, key in enumerate(keys):
            kind, _, rest = key.partition(":")
            row: Optional[int] = None
            if kind == NodeKind.CLUSTER:
                row = self.cluster_row(rest)
            elif kind == NodeKind.ENTRANCE:
                row = self.entrance_row(rest)
            elif kind == NodeKind.DEFAULT:
                res, _, ctx = rest.partition("|")
                row = self.default_row(res, ctx)
            elif kind == NodeKind.ORIGIN:
                res, _, org = rest.partition("|")
                row = self.origin_row(res, org)
            if row is not None:
                out[old_row] = row
        return out

    def entrance_children(self, context: str) -> List[int]:
        with self._lock:
            row = self._rows.get(NodeKind.ENTRANCE + ":" + context)
            if row is None:
                return []
            return list(self.children.get(row, ()))

    def clear(self) -> None:
        with self._lock:
            self._rows.clear()
            self._keys.clear()
            self.children.clear()
            self.origin_rows.clear()
            self._n_resources = 0
            self._n_contexts = 0
            self.entry_node_row = self._alloc(NodeKind.CLUSTER + ":" + C.TOTAL_IN_RESOURCE_NAME)
